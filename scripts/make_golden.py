"""Generate the golden history corpus (tests/data/*.json).

Reference parity: knossos ships `data/` dirs of known good/bad stored
histories checked for expected verdicts (SURVEY.md §4 "golden-file
style").  Each file freezes one history + the verdict established at
generation time; `tests/test_golden.py` replays every file through the
host oracle AND the device pipeline and demands the stored verdict —
pinning today's checker behavior against regressions.

Rerun only to EXTEND the corpus (files are stable given seeds):
    python scripts/make_golden.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from jepsen_tpu.utils.backend import force_cpu_backend  # noqa: E402

force_cpu_backend()

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "data")


def _ops_to_json(h):
    return [{"type": op.type, "process": op.process, "f": op.f,
             "value": op.value} for op in h]


def la_cases():
    from jepsen_tpu.checkers.elle import oracle
    from jepsen_tpu.workloads import synth

    cases = []
    for name, seed, inject in [
        ("la-valid-small", 3, None),
        ("la-valid-concurrent", 11, None),
        ("la-g1a", 5, "g1a"),
        ("la-g1b", 21, "g1b"),
        ("la-wr-cycle", 7, "wr"),
        ("la-rw-cycle", 9, "rw"),
        ("la-dense-cycles", 13, "many"),
    ]:
        h = synth.la_history(n_txns=80, n_keys=4, concurrency=5,
                             fail_prob=0.05, info_prob=0.05,
                             multi_append_prob=0.2, seed=seed)
        if inject == "g1a":
            assert synth.inject_g1a(h)
        elif inject == "g1b":
            assert synth.inject_g1b(h)
        elif inject == "wr":
            assert synth.inject_wr_cycle(h)
        elif inject == "rw":
            assert synth.inject_rw_cycle(h)
        elif inject == "many":
            for _ in range(3):
                synth.inject_wr_cycle(h)
                synth.inject_rw_cycle(h)
        models = ["strict-serializable"]
        r = oracle.check(h, models)
        cases.append((name, {
            "workload": "list-append", "models": models,
            "expected": {"valid?": r["valid?"],
                         "anomaly-types": sorted(r["anomaly-types"])},
            "history": _ops_to_json(h),
        }))
    return cases


def _concurrent_txns(*txns):
    from jepsen_tpu.history import history
    from jepsen_tpu.history.ops import Op

    inv = [Op(type="invoke", process=i, f="txn", value=mi)
           for i, (mi, _) in enumerate(txns)]
    comp = [Op(type="ok", process=i, f="txn", value=mo)
            for i, (_, mo) in enumerate(txns)]
    return history(inv + comp)


def rw_cases():
    from jepsen_tpu.checkers.elle import rw_register
    from jepsen_tpu.workloads import synth

    cases = []
    for name, seed in [("rw-valid", 2), ("rw-valid-concurrent", 17)]:
        h = synth.rw_history(n_txns=80, n_keys=4, concurrency=5,
                             fail_prob=0.05, info_prob=0.05, seed=seed)
        models = ["strict-serializable"]
        r = rw_register.check(h, models, use_device=False)
        cases.append((name, {
            "workload": "rw-register", "models": models,
            "expected": {"valid?": r["valid?"],
                         "anomaly-types": sorted(r["anomaly-types"])},
            "history": _ops_to_json(h),
        }))
    # anomaly families, hand-built (the corpus must pin failures too)
    anomalous = [
        ("rw-lost-update", ["snapshot-isolation"], _concurrent_txns(
            ([["r", "x", None], ["w", "x", 1]],
             [["r", "x", None], ["w", "x", 1]]),
            ([["r", "x", None], ["w", "x", 2]],
             [["r", "x", None], ["w", "x", 2]]))),
        ("rw-g1c-wr-cycle", ["read-committed"], _concurrent_txns(
            ([["w", "x", 1], ["r", "y", None]],
             [["w", "x", 1], ["r", "y", 9]]),
            ([["w", "y", 9], ["r", "x", None]],
             [["w", "y", 9], ["r", "x", 1]]))),
        ("rw-write-skew-g2", ["serializable"], _concurrent_txns(
            ([["r", "x", None], ["w", "y", 10]],
             [["r", "x", None], ["w", "y", 10]]),
            ([["r", "y", None], ["w", "x", 1]],
             [["r", "y", None], ["w", "x", 1]]))),
    ]
    for name, models, h in anomalous:
        r = rw_register.check(h, models, use_device=False)
        assert r["valid?"] is False, (name, r)
        cases.append((name, {
            "workload": "rw-register", "models": models,
            "expected": {"valid?": r["valid?"],
                         "anomaly-types": sorted(r["anomaly-types"])},
            "history": _ops_to_json(h),
        }))
    return cases


def lin_cases():
    from jepsen_tpu.checkers.knossos import wgl
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.workloads import synth

    cases = []
    for name, kw in [
        ("lin-valid", dict(n_ops=40, concurrency=3, seed=4)),
        ("lin-valid-cas", dict(n_ops=40, concurrency=3, cas_prob=0.5,
                               seed=6)),
        ("lin-stale-reads", dict(n_ops=40, concurrency=3,
                                 stale_read_prob=0.5, seed=8)),
    ]:
        h = synth.lin_register_history(**kw)
        r = wgl.check(h, cas_register())
        cases.append((name, {
            "workload": "linearizable-register", "models": ["cas-register"],
            "expected": {"valid?": r["valid?"]},
            "history": _ops_to_json(h),
        }))
    return cases


def main():
    os.makedirs(OUT, exist_ok=True)
    n = 0
    for name, payload in la_cases() + rw_cases() + lin_cases():
        path = os.path.join(OUT, f"{name}.json")
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
        print(f"{name}: valid?={payload['expected']['valid?']} "
              f"{payload['expected'].get('anomaly-types', '')}")
        n += 1
    print(f"wrote {n} golden files to {OUT}")


if __name__ == "__main__":
    main()
