"""Agreement fuzz: knossos wgl / device BFS / competition must agree on
every definitive linearizability verdict (unknown = budget cap, allowed).
Env: FUZZ_N (default 150), FUZZ_SEED.
"""
import signal, sys, random, time
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from jepsen_tpu.utils.backend import force_cpu_backend
force_cpu_backend()
import jax
from jepsen_tpu.checkers.knossos import competition
from jepsen_tpu.models import cas_register
from jepsen_tpu.workloads import synth


class CaseTimeout(Exception):
    pass


def _alarm(sig, frame):
    raise CaseTimeout()


signal.signal(signal.SIGALRM, _alarm)

rng = random.Random(int(os.environ.get("FUZZ_SEED", 5150)))
n_fail = n_to = 0
t_start = time.time()
N = int(os.environ.get("FUZZ_N", 150))
for case in range(N):
    params = dict(
        n_ops=rng.choice([12, 24, 40]),
        concurrency=rng.choice([2, 3]),
        stale_read_prob=rng.choice([0.0, 0.0, 0.2, 0.5]),
        info_prob=rng.choice([0.0, 0.05, 0.15]),
        cas_prob=rng.choice([0.0, 0.2, 0.5]),
        seed=rng.randrange(1 << 30),
    )
    h = synth.lin_register_history(**params)
    try:
        signal.alarm(120)
        rs = {}
        for algo in ("wgl", "device", "competition"):
            rs[algo] = competition.analysis(
                h, cas_register(), algorithm=algo,
                max_configs=200_000)["valid?"]
        signal.alarm(0)
        definitive = {k: v for k, v in rs.items() if v != "unknown"}
        if len(set(definitive.values())) > 1:
            n_fail += 1
            print(f"MISMATCH case={case} params={params}: {rs}", flush=True)
    except CaseTimeout:
        n_to += 1
        print(f"TIMEOUT case={case} params={params}", flush=True)
    except Exception as e:
        signal.alarm(0)
        n_fail += 1
        print(f"ERROR case={case} params={params}: "
              f"{type(e).__name__}: {e}", flush=True)
    if case % 25 == 24:
        jax.clear_caches()
        print(f"[{case+1}/{N}] {time.time()-t_start:.0f}s "
              f"mismatches={n_fail} timeouts={n_to}", flush=True)
print(f"DONE {N} cases, {n_fail} mismatches, {n_to} timeouts, "
      f"{time.time()-t_start:.0f}s", flush=True)
sys.exit(1 if n_fail else 0)
