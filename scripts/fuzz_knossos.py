"""Agreement fuzz: knossos wgl / linear / device BFS / competition must
agree on every definitive linearizability verdict (unknown = budget cap,
allowed).  `linear` re-admitted 2026-07-30 after the packed int64
config-set rewrite (VERDICT r03 item 6); per-algorithm cumulative time is
reported and the gate asserts linear stays within 10x of wgl overall.
Env: FUZZ_N (default 150), FUZZ_SEED.
"""
import signal, sys, random, time
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from jepsen_tpu.utils.backend import force_cpu_backend
force_cpu_backend()
import jax
from jepsen_tpu.checkers.knossos import competition
from jepsen_tpu.models import cas_register
from jepsen_tpu.workloads import synth


class CaseTimeout(Exception):
    pass


def _alarm(sig, frame):
    raise CaseTimeout()


signal.signal(signal.SIGALRM, _alarm)

rng = random.Random(int(os.environ.get("FUZZ_SEED", 5150)))
n_fail = n_to = 0
t_algo = {}
t_start = time.time()
N = int(os.environ.get("FUZZ_N", 150))
for case in range(N):
    if case % 10 == 9:
        # wide-mask regime (round 5): enough concurrency+infos that the
        # peak slot count can exceed 57, exercising
        # linear._search_packed_wide in the same agreement gate
        params = dict(
            n_ops=rng.choice([60, 90]),
            concurrency=rng.choice([40, 70]),
            stale_read_prob=rng.choice([0.0, 0.2]),
            info_prob=rng.choice([0.2, 0.4]),
            cas_prob=rng.choice([0.0, 0.3]),
            seed=rng.randrange(1 << 30),
        )
    else:
        params = dict(
            n_ops=rng.choice([12, 24, 40]),
            concurrency=rng.choice([2, 3]),
            stale_read_prob=rng.choice([0.0, 0.0, 0.2, 0.5]),
            info_prob=rng.choice([0.0, 0.05, 0.15]),
            cas_prob=rng.choice([0.0, 0.2, 0.5]),
            seed=rng.randrange(1 << 30),
        )
    h = synth.lin_register_history(**params)
    cur_algo, t_a = None, 0.0
    try:
        signal.alarm(120)
        rs = {}
        for algo in ("wgl", "linear", "device", "competition"):
            cur_algo, t_a = algo, time.time()
            rs[algo] = competition.analysis(
                h, cas_register(), algorithm=algo,
                max_configs=200_000)["valid?"]
            t_algo[algo] = t_algo.get(algo, 0.0) + time.time() - t_a
        signal.alarm(0)
        definitive = {k: v for k, v in rs.items() if v != "unknown"}
        if len(set(definitive.values())) > 1:
            n_fail += 1
            print(f"MISMATCH case={case} params={params}: {rs}", flush=True)
    except CaseTimeout:
        n_to += 1
        if cur_algo is not None:
            # charge the burned time to the algorithm that hung, so the
            # perf gate can't be dodged by timing out
            t_algo[cur_algo] = t_algo.get(cur_algo, 0.0) + time.time() - t_a
        print(f"TIMEOUT case={case} (in {cur_algo}) params={params}",
              flush=True)
    except Exception as e:
        signal.alarm(0)
        n_fail += 1
        print(f"ERROR case={case} params={params}: "
              f"{type(e).__name__}: {e}", flush=True)
    if case % 25 == 24:
        jax.clear_caches()
        print(f"[{case+1}/{N}] {time.time()-t_start:.0f}s "
              f"mismatches={n_fail} timeouts={n_to}", flush=True)
print(f"DONE {N} cases, {n_fail} mismatches, {n_to} timeouts, "
      f"{time.time()-t_start:.0f}s", flush=True)
print("per-algo seconds: " +
      " ".join(f"{k}={v:.1f}" for k, v in sorted(t_algo.items())), flush=True)
if t_algo.get("wgl") and t_algo.get("linear"):
    ratio = t_algo["linear"] / max(t_algo["wgl"], 1e-9)
    print(f"linear/wgl ratio = {ratio:.2f}x (gate: <= 10x)", flush=True)
    if ratio > 10:
        n_fail += 1
sys.exit(1 if n_fail else 0)
