"""On-chip cache-key component spy (PROFILE.md §-1f open question).

Runs the staged pair at small shapes on the axon backend and logs every
cache-key component hash for the jit__infer_stage / jit__sweep_stage
programs.  Run TWICE in fresh processes and diff the outputs: whichever
component differs between runs is what makes on-chip staged-infer keys
unstable (three different keys for one program observed 2026-08-01).

Usage (tunnel up): python scripts/chip_key_spy.py >> scripts/chip_key_spy.log
"""

import hashlib
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jepsen_tpu.utils.backend import enable_compile_cache  # noqa: E402

enable_compile_cache()

import jax  # noqa: E402
from jax._src import cache_key as ck  # noqa: E402

_orig = ck.get


def spy(module, devices, compile_options, backend, *a, **kw):
    key = _orig(module, devices, compile_options, backend, *a, **kw)
    name = str(module.operation.attributes["sym_name"])
    if "_infer_stage" in name or "_sweep_stage" in name:
        canon = ck._canonicalize_ir(module, ck.IgnoreCallbacks.NO)
        opts = compile_options.SerializeAsString()
        print(f"[{time.strftime('%H:%M:%S')}] {name}", flush=True)
        print("  canon-ir:", hashlib.sha256(canon).hexdigest()[:16],
              f"({len(canon)} B)", flush=True)
        print("  opts:", hashlib.sha256(opts).hexdigest()[:16],
              f"({len(opts)} B)", flush=True)
        print("  platver:", hashlib.sha256(
            backend.platform_version.encode()).hexdigest()[:16], flush=True)
        print("  key:", key[-16:], flush=True)
        # persist the raw options for byte-level diffing across runs
        tag = "infer" if "_infer_stage" in name else "sweep"
        with open(os.path.join(REPO, "scripts",
                               f"opts_{tag}_{os.getpid()}.bin"), "wb") as f:
            f.write(opts)
        with open(os.path.join(REPO, "scripts",
                               f"canon_{tag}_{os.getpid()}.bin"), "wb") as f:
            f.write(canon)
    return key


ck.get = spy

from jepsen_tpu.checkers.elle.device_core import core_check_staged  # noqa: E402
from jepsen_tpu.checkers.elle.device_infer import pad_packed  # noqa: E402
from jepsen_tpu.workloads import synth  # noqa: E402

p = synth.packed_la_history(n_txns=512, n_keys=16, seed=0)
h = jax.device_put(pad_packed(p))
jax.block_until_ready(h)
t0 = time.perf_counter()
bits, over = core_check_staged(h, p.n_keys)
jax.block_until_ready(bits)
print(f"pid {os.getpid()} done {time.perf_counter()-t0:.1f}s "
      f"backend={jax.default_backend()}", flush=True)
