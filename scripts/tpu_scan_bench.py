"""Validate + microbench the Pallas segmented scan on the real TPU.

1. Differential: compiled kernel vs lax `_seg_scan` on adversarial
   layouts (bitwise).
2. Microbench: kernel vs the Hillis-Steele loop scan at chain-pass
   shapes (n = 2^21 rows x 128 lanes, the 1M-txn regime).

Usage: python scripts/tpu_scan_bench.py   (needs the TPU free)
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.ops import pallas_scan
from jepsen_tpu.ops.segments import _seg_scan, _seg_scan_loop
from jepsen_tpu.utils.backend import enable_compile_cache


def main():
    enable_compile_cache()
    print("backend:", jax.default_backend(), jax.devices()[0])
    assert jax.default_backend() == "tpu", "needs the real chip"

    rng = np.random.default_rng(0)
    print("— differential (compiled Mosaic kernel vs lax) —")
    for n, k, p, blk in [(300, 128, 0.05, 64), (4096, 128, 0.01, 1024),
                         (1024, 16, 0.3, 256), (1 << 17, 128, 0.001, 2048)]:
        vals = jnp.asarray((rng.random((n, k)) < 0.08).astype(np.int8))
        starts = np.zeros(n, bool)
        starts[0] = True
        starts |= rng.random(n) < p
        starts = jnp.asarray(starts)
        want = np.asarray(_seg_scan(vals, starts))
        got = np.asarray(pallas_scan.seg_or_pallas(vals, starts, block=blk))
        ok = (want == got).all()
        print(f"  n={n} k={k} block={blk}: {'OK' if ok else 'MISMATCH'}")
        if not ok:
            sys.exit(1)

    print("— microbench at chain-pass shapes —")
    n, k = 1 << 21, 128
    vals = jnp.asarray((rng.random((n, k)) < 0.05).astype(np.int8))
    starts = np.zeros(n, bool)
    starts[0] = True
    starts |= rng.random(n) < 0.001
    starts = jnp.asarray(starts)
    vals, starts = jax.device_put(vals), jax.device_put(starts)

    loop = jax.jit(_seg_scan_loop)
    pal = jax.jit(lambda v, s: pallas_scan.seg_or_pallas(v, s))

    for name, fn in [("loop-scan", loop), ("pallas", pal)]:
        t0 = time.perf_counter()
        out = fn(vals, starts)
        jax.block_until_ready(out)
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(vals, starts))
            best = min(best, time.perf_counter() - t0)
        gbs = 2 * n * k / best / 1e9
        print(f"  {name:10s} compile+warm {compile_s:7.2f}s  "
              f"steady {best * 1e3:8.2f} ms  ({gbs:6.1f} GB/s eff)")
        if name == "pallas":
            same = (np.asarray(out) == np.asarray(loop(vals, starts))).all()
            print(f"  bitwise equal at bench shapes: {same}")
            if not same:
                sys.exit(1)


if __name__ == "__main__":
    main()
