#!/bin/sh
# log tunnel liveness every ~4 min
while true; do
  t0=$(date +%s)
  if timeout 200 python -c "import jax; jax.devices()" 2>/dev/null; then
    echo "$(date +%H:%M:%S) UP ($(( $(date +%s) - t0 ))s)"
  else
    echo "$(date +%H:%M:%S) down"
  fi
  sleep 220
done
