"""Config-4 attempt: a 10M-txn list-append check on the single real TPU
chip (PROFILE.md §2b did this on CPU only: 353 s steady, 26.8 GB host).

HBM accounting at padded shapes T=2^24, M=2^26, R=2^27:
  mop arrays   6 x 2^26 x 4B int32 + kinds/masks  ≈ 1.7 GB
  rd_elems     2^27 x 4B                          ≈ 0.5 GB
  label plane  (2^25, 128) int8                   ≈ 4   GB
  sort workspaces (XLA)                           ≈ transient
Should fit a 16 GB v5e chip; the open risks are compile time at these
shapes and sort scratch.  The number (even a DNF with a reason) is the
deliverable.

Usage: python scripts/tpu_10m.py [n_txns]  (default 10M; needs TPU free)
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, ".")

import jax
import numpy as np

from jepsen_tpu.utils.backend import enable_compile_cache


def main():
    n_txns = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    enable_compile_cache()
    print("backend:", jax.default_backend(), flush=True)

    from jepsen_tpu.checkers.elle.device_core import (core_check,
                                                      core_check_staged)
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.utils import prestage

    t0 = time.perf_counter()
    p = prestage.la_history(n_txns=n_txns, n_keys=max(64, n_txns // 8))
    print(f"gen {time.perf_counter() - t0:.1f}s", flush=True)

    t0 = time.perf_counter()
    h = jax.device_put(pad_packed(p))
    jax.block_until_ready(h)
    print(f"pad+stage {time.perf_counter() - t0:.1f}s "
          f"T={h.txn_type.shape[0]} M={h.mop_txn.shape[0]} "
          f"R={h.rd_elems.shape[0]}", flush=True)

    # HBM headroom knob: max_k sizes the (2T, max_k) label plane (4 GiB
    # at 10M shapes with max_k=128) and the (C, max_k) chain gather —
    # the two largest sweep allocations on a 16 GiB chip.  Default 32
    # (1 GiB plane): the prestaged 10M history has zero backward edges,
    # and aot_warm.py's la_10m_staged warms the SAME specialization (a
    # different max_k is a different executable).
    max_k = int(os.environ.get("JT_10M_MAX_K", 32))
    # staged (default): two separately-compiled programs — the fused
    # single program kills the axon remote-compile service at
    # 2^24-txn shapes (PROFILE.md §-1d, "Unexpected EOF" x3 attempts);
    # JT_10M_MODE=fused retries the one-program form
    mode = os.environ.get("JT_10M_MODE", "staged")
    if mode not in ("staged", "fused"):
        raise SystemExit(f"JT_10M_MODE must be staged|fused, got {mode!r}")
    check = (core_check if mode == "fused" else partial(
        core_check_staged, verbose=True))
    print(f"mode: {mode}", flush=True)

    t0 = time.perf_counter()
    bits, over = check(h, p.n_keys, max_k=max_k)
    jax.block_until_ready(bits)
    print(f"compile+first {time.perf_counter() - t0:.1f}s "
          f"converged={int(np.asarray(bits)[-1])} "
          f"over={int(np.asarray(over))} max_k={max_k}", flush=True)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        bits, over = check(h, p.n_keys, max_k=max_k)
        jax.block_until_ready(bits)
        best = min(best, time.perf_counter() - t0)
    print(f"steady {best:.2f}s = {n_txns / best:,.0f} txns/s "
          f"(target: 10M in 60s on v5e-8; single chip share = "
          f"{n_txns / best / (10_000_000 / 60 / 8):.2f}x)", flush=True)

    stats = jax.devices()[0].memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    if peak:
        print(f"HBM peak {peak / 2**30:.2f} GiB "
              f"(limit {stats.get('bytes_limit', 0) / 2**30:.2f} GiB)",
              flush=True)


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001
        # the campaign records 3000 chars of stdout but only 1000 of
        # stderr, and axon/libtpu log spam can push the actual error
        # out of that window (attempt 1 on 2026-08-01 was undiagnosable
        # from the record) — put the traceback where it survives
        import traceback

        print("FAILED:", type(e).__name__, str(e)[:1500], flush=True)
        traceback.print_exc(limit=5, file=sys.stdout)
        sys.stdout.flush()
        raise
