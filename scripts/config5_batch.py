"""Config 5 at its stated scale (BASELINE.json: batched 100 x 1M):
100 one-million-txn list-append histories, checked as a checkpointed
batch on the 8-virtual-device CPU mesh, with seeded-invalid members and
a deliberate mid-run kill + resume (VERDICT r03 item 4).

Two-invocation protocol (driven by the caller):
  1. C5_KILL_AFTER_GROUPS=k python scripts/config5_batch.py
       -> os._exit(1) after k durable group checkpoints (the "crash")
  2. python scripts/config5_batch.py
       -> resumes from the checkpoint, finishes, verifies verdicts
          (every 10th history carries a seeded duplicate-append and must
          come back invalid; the rest valid), writes the artifact.

Artifact: scripts/config5_r04.json — per-group wall times, resume
bookkeeping (how many groups were skipped), verdict tallies, peak RSS.
Env: C5_N (100), C5_TXNS (1_000_000), C5_GROUP (8), C5_CKPT, C5_OUT,
C5_KILL_AFTER_GROUPS.
"""

import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.utils.backend import enable_compile_cache, force_cpu_backend

N = int(os.environ.get("C5_N", 100))
TXNS = int(os.environ.get("C5_TXNS", 1_000_000))
GROUP = int(os.environ.get("C5_GROUP", 8))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CKPT = os.environ.get("C5_CKPT", os.path.join(REPO, "store",
                                              "config5_r04.ckpt"))
OUT = os.environ.get("C5_OUT", os.path.join(REPO, "scripts",
                                            "config5_r04.json"))
KILL_AFTER = int(os.environ.get("C5_KILL_AFTER_GROUPS", 0))


def seed_invalid(p):
    """Flip one observed append's writer txn to FAIL — an aborted read
    (G1a) every reader of that value exposes.  Invalid AND convergent:
    unlike a seeded duplicate-append (which perturbs the version order
    into sweep-budget growth and a ~30-min exact-rerun recompile per
    group at 1M shapes, measured in this run's first attempt), a failed
    writer only flips counts, so the batched verdict stays exact with no
    rerun."""
    import numpy as np

    from jepsen_tpu.history.soa import MOP_READ, TXN_FAIL, TXN_OK

    kinds = np.asarray(p.mop_kind)
    keys = np.asarray(p.mop_key)
    vals = np.asarray(p.mop_val)
    txns = np.asarray(p.mop_txn)
    app = np.flatnonzero(kinds != MOP_READ)
    reads = np.flatnonzero((kinds == MOP_READ) & (p.mop_rd_len > 0))
    for r in reads[:500]:
        start, ln = int(p.mop_rd_start[r]), int(p.mop_rd_len[r])
        for off in range(ln):
            vid = p.rd_elems[start + off]
            for wi in app[(vals[app] == vid) & (keys[app] == keys[r])]:
                wt = int(txns[wi])
                if wt != int(txns[r]) and p.txn_type[wt] == TXN_OK \
                        and p.txn_type[int(txns[r])] == TXN_OK:
                    p.txn_type[wt] = TXN_FAIL
                    return p
    raise AssertionError("no seedable observed append found")


def main():
    force_cpu_backend(8)
    enable_compile_cache()
    import jax

    from jepsen_tpu.parallel.batch import check_batch_checkpointed, make_mesh
    from jepsen_tpu.workloads import synth

    os.makedirs(os.path.dirname(CKPT), exist_ok=True)
    t0 = time.monotonic()
    print(f"[config5] generating {N} x {TXNS} histories "
          f"(every 10th seeded-invalid)", flush=True)
    ps = []
    for i in range(N):
        p = synth.packed_la_history(n_txns=TXNS, n_keys=max(64, TXNS // 8),
                                    mops_per_txn=4, read_frac=0.25, seed=i)
        if i % 10 == 9:
            p = seed_invalid(p)
        ps.append(p)
        if i % 10 == 9:
            print(f"[config5] gen {i + 1}/{N} "
                  f"({time.monotonic() - t0:.0f}s)", flush=True)
    t_gen = time.monotonic() - t0

    had_ckpt_groups = 0
    if os.path.exists(CKPT):
        with open(CKPT) as f:
            had_ckpt_groups = sum(1 for line in f if line.strip())

    groups = []

    def on_group(info):
        groups.append(info)
        print(f"[config5] group {info['group']} ok in {info['wall_s']}s "
              f"({info['done']}/{N} done)", flush=True)
        if KILL_AFTER and len(groups) >= KILL_AFTER:
            print(f"[config5] simulated crash after "
                  f"{KILL_AFTER} groups", flush=True)
            os._exit(1)

    mesh = make_mesh(8)
    t1 = time.monotonic()
    results = check_batch_checkpointed(ps, CKPT, mesh=mesh,
                                       group_size=GROUP, on_group=on_group)
    t_check = time.monotonic() - t1

    bad = [i for i, r in enumerate(results) if r["valid?"] is not False
           and i % 10 == 9]
    good = [i for i, r in enumerate(results) if r["valid?"] is not True
            and i % 10 != 9]
    ok = not bad and not good
    art = {
        "metric": "config5-batched-check",
        "n_histories": N,
        "txns_each": TXNS,
        "mesh": "8-virtual-cpu",
        "group_size": GROUP,
        "gen_s": round(t_gen, 1),
        "check_s": round(t_check, 1),
        "groups_this_run": groups,
        "resumed_with_records": had_ckpt_groups,
        "seeded_invalid_caught": not bad,
        "valid_verdicts_correct": not good,
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20, 2),
        "ok": ok,
    }
    with open(OUT, "w") as f:
        f.write(json.dumps(art, indent=1) + "\n")
    print(json.dumps(art), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
