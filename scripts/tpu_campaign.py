"""Persistent TPU measurement campaign — treat the flaky tunnel as part
of the problem (VERDICT r03 item 1).

Loops forever: probe the TPU backend in a subprocess (it can HANG, not
just fail, when the axon tunnel is down); when the chip answers, run the
measurement ladder stage by stage, each in its own subprocess with a
deadline so a mid-stage tunnel drop can't wedge the loop.  Every stage
result (success or failure) is appended to scripts/tpu_campaign.jsonl;
completed stages are skipped on later passes, failed stages retried up
to MAX_ATTEMPTS.  Exits 0 once every stage is done.

Stages (in order — each also pre-warms the persistent compile cache at
exactly the shapes the driver's bench.py will request):
  la_100k  bench.py BENCH_TXNS=100000   (ladder rung 1)
  la_1m    bench.py BENCH_TXNS=1000000  (the north star, post-sort-cut)
  rw_1m    scripts/tpu_rw_1m.py         (config 3)
  la_10m   scripts/tpu_10m.py           (config 4, cold+steady+HBM)

Usage: nohup python scripts/tpu_campaign.py >> scripts/tpu_campaign.log 2>&1 &
Env: CAMPAIGN_PROBE_EVERY_S (default 240), CAMPAIGN_MAX_ATTEMPTS (3),
CAMPAIGN_PROBE_TIMEOUT_S (default 300 — cold dials measured ~140 s).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "scripts", "tpu_campaign.jsonl")
PROBE_EVERY = float(os.environ.get("CAMPAIGN_PROBE_EVERY_S", 240))
MAX_ATTEMPTS = int(os.environ.get("CAMPAIGN_MAX_ATTEMPTS", 3))
# The axon tunnel can take >2 min just to dial on a cold backend init
# (measured 140 s on 2026-07-31); a 120 s probe misreads that as down.
PROBE_TIMEOUT = float(os.environ.get("CAMPAIGN_PROBE_TIMEOUT_S", 300))

STAGES = [
    # (name, argv, extra_env, deadline_s)
    ("la_100k", [sys.executable, "bench.py"],
     {"BENCH_TXNS": "100000", "BENCH_DEADLINE": "3600"}, 3700),
    ("la_1m", [sys.executable, "bench.py"],
     {"BENCH_TXNS": "1000000", "BENCH_DEADLINE": "5400"}, 5500),
    ("rw_1m", [sys.executable, "scripts/tpu_rw_1m.py"], {}, 3600),
    ("la_10m", [sys.executable, "scripts/tpu_10m.py"], {}, 14400),
    # --- round-5 session-2 additions (fresh names = fresh attempts) ---
    # does a FRESH process hit the warm fused 1M entries?  (never
    # verified on the axon backend; if this recompiles ~1161 s the
    # driver bench relies on the 2700 s deadline, PROFILE.md §-1f)
    ("warmcheck_1m", [sys.executable, "bench.py"],
     {"BENCH_TXNS": "1000000", "BENCH_REPEATS": "1",
      "BENCH_DEADLINE": "3000"}, 3100),
    # two spy runs: diff scripts/chip_key_spy.log across pids to find
    # the cache-key component that varies per process on-chip
    ("key_spy_a", [sys.executable, "scripts/chip_key_spy.py"], {}, 1800),
    ("key_spy_b", [sys.executable, "scripts/chip_key_spy.py"], {}, 1800),
    # config 4 via the staged two-program split (these are tpu_10m.py's
    # defaults too; explicit so the stage can't drift with them)
    ("la_10m_staged", [sys.executable, "scripts/tpu_10m.py"],
     {"JT_10M_MODE": "staged", "JT_10M_MAX_K": "32"}, 14400),
]


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def record(rec):
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def probe(timeout_s=PROBE_TIMEOUT) -> str:
    """'' when the default backend is a live TPU, else an error string."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired:
        return "probe hung"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:]
        return f"probe rc={r.returncode}: {' '.join(tail)}"
    plat = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "?"
    return "" if plat == "tpu" else f"platform={plat}"


def run_stage(name, argv, extra_env, deadline_s):
    env = dict(os.environ, **extra_env)
    t0 = time.time()
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=deadline_s, cwd=REPO, env=env)
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"")
        out = out.decode() if isinstance(out, bytes) else out
        record({"stage": name, "ok": False, "wall_s": round(time.time() - t0),
                "error": f"deadline {deadline_s}s", "stdout_tail": out[-2000:]})
        return False
    wall = round(time.time() - t0, 1)
    payload = None
    for line in reversed((r.stdout or "").strip().splitlines()):
        if line.startswith("{"):
            try:
                payload = json.loads(line)
            except ValueError:
                pass
            break
    ok = r.returncode == 0
    if payload is not None and payload.get("backend") not in (None, "tpu"):
        ok = False  # tunnel dropped between probe and run: CPU fallback ran
    if "backend: cpu" in (r.stdout or ""):
        ok = False  # plain-print scripts: same CPU-fallback guard
    record({"stage": name, "ok": ok, "rc": r.returncode, "wall_s": wall,
            "result": payload,
            "stdout_tail": (r.stdout or "")[-3000:],
            "stderr_tail": (r.stderr or "")[-1000:] if not ok else ""})
    return ok


def main():
    done = set()
    attempts = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("stage"):
                    attempts[rec["stage"]] = attempts.get(rec["stage"], 0) + 1
                    if rec.get("ok"):
                        done.add(rec["stage"])
    log(f"campaign start: done={sorted(done)}")
    while True:
        todo = [s for s in STAGES
                if s[0] not in done and attempts.get(s[0], 0) < MAX_ATTEMPTS]
        if not todo:
            all_done = done >= {s[0] for s in STAGES}
            log("campaign complete" if all_done else
                "attempts exhausted with failures; exiting")
            record({"stage": "_campaign", "ok": all_done,
                    "done": sorted(done)})
            return 0 if all_done else 1
        err = probe()
        if err:
            log(f"tunnel down ({err}); todo={[s[0] for s in todo]}; "
                f"sleeping {PROBE_EVERY:.0f}s")
            time.sleep(PROBE_EVERY)
            continue
        name, argv, extra_env, deadline_s = todo[0]
        attempts[name] = attempts.get(name, 0) + 1
        log(f"tunnel UP — running stage {name} "
            f"(attempt {attempts[name]}/{MAX_ATTEMPTS}, "
            f"deadline {deadline_s}s)")
        if run_stage(name, argv, extra_env, deadline_s):
            done.add(name)
            log(f"stage {name} OK")
        else:
            log(f"stage {name} FAILED — re-probing")


if __name__ == "__main__":
    sys.exit(main())
