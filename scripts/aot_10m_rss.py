"""Compile the 10M staged pair via deviceless v5e topology while
sampling this process's peak RSS: measures the compile-memory footprint
that OOM-kills the axon remote compile helper (PROFILE.md §-1f), and
lands the executables in the local cache as a bonus."""
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Compile the CHIP program: JT_PALLAS=1 forces the Pallas LOCF path that
# `fill_enabled()` would otherwise gate OFF under the forced-CPU default
# backend — without it this measures a different (lax-path) program than
# the one that OOM-killed the remote helper (the round-5 session-2
# "silent defeat #2", PROFILE.md §-1f).
os.environ["JT_PALLAS"] = "1"

from jepsen_tpu.utils.backend import enable_compile_cache, force_cpu_backend

force_cpu_backend()
enable_compile_cache()

import jax
import jax._src.xla_bridge as _xb

# register the local libtpu as the `tpu` platform (compile-only, no
# tunnel) so pallas lowering rules resolve; single-process only — libtpu
# takes /tmp/libtpu_lockfile
_xb.register_plugin(
    "tpu",
    library_path="/opt/venv/lib/python3.12/site-packages/libtpu/libtpu.so",
    priority=0)

from jax.experimental import topologies
from jax.sharding import SingleDeviceSharding


def rss_gb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 2**20
    return 0.0


PEAK = [0.0]


def sampler():
    while True:
        PEAK[0] = max(PEAK[0], rss_gb())
        time.sleep(2)


threading.Thread(target=sampler, daemon=True).start()


def main():
    n_txns = int(os.environ.get("RSS_TXNS", 10_000_000))
    max_k = int(os.environ.get("JT_10M_MAX_K", 32))
    from jepsen_tpu.checkers.elle.device_core import (_infer_stage,
                                                      _sweep_stage)
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.utils import prestage

    p = prestage.la_history(n_txns=n_txns, n_keys=max(64, n_txns // 8))
    h = pad_packed(p)
    topo = topologies.get_topology_desc(topology_name="v5e:2x2",
                                        platform="tpu")
    dev = topo.devices[0]
    sh = SingleDeviceSharding(dev)
    hs = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh), h)
    del h
    print(f"baseline rss {rss_gb():.1f} GB", flush=True)
    t0 = time.perf_counter()
    low = _infer_stage.lower(hs, p.n_keys)
    print(f"infer lowered {time.perf_counter()-t0:.0f}s "
          f"rss {rss_gb():.1f} GB", flush=True)
    t0 = time.perf_counter()
    low.compile()
    print(f"infer compiled {time.perf_counter()-t0:.0f}s "
          f"peak rss {PEAK[0]:.1f} GB", flush=True)
    out_sd = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        jax.eval_shape(_infer_stage, hs, p.n_keys))
    t0 = time.perf_counter()
    low2 = _sweep_stage.lower(out_sd, max_k=max_k, max_rounds=64)
    low2.compile()
    print(f"sweep compiled {time.perf_counter()-t0:.0f}s "
          f"peak rss {PEAK[0]:.1f} GB", flush=True)


if __name__ == "__main__":
    main()
