"""Deviceless AOT compile of the TPU ladder programs (VERDICT r04 item
1c): populate the persistent XLA compile cache for v5e *without the
tunnel*, so an open window pays dial + run only.

How: `jax.experimental.topologies.get_topology_desc("v5e:2x2")` gives
compile-only TPU devices through the local libtpu — no device, no
tunnel.  Lowering the exact module-level jitted callables the ladder
scripts invoke (`core_check`, `rw_core_check`) at the exact prestaged
padded shapes produces the same serialized computation + compile
options, hence the same persistent-cache key, as the in-window call —
provided the tunnel backend reports the same libtpu platform version.
If it does not, the in-window run simply compiles as before; cache
warming is a pure hedge.

Stages mirror scripts/tpu_campaign.py.  Each stage is recorded in
scripts/aot_warm.jsonl; completed stages are skipped on re-runs (keyed
by shape signature, so a program change re-warms).

Usage: nohup python scripts/aot_warm.py >> scripts/aot_warm.log 2>&1 &
Env: AOT_STAGES=la_100k,la_1m,... (default: all).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "scripts", "aot_warm.jsonl")

from jepsen_tpu.utils.backend import enable_compile_cache, force_cpu_backend

force_cpu_backend()  # numpy/pad work runs on CPU; axon must not dial

import jax  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import SingleDeviceSharding  # noqa: E402


def record(rec):
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _sds(tree, dev):
    sh = SingleDeviceSharding(dev)
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh), tree)


def la_stage(n_txns):
    from jepsen_tpu.checkers.elle.device_core import core_check
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.utils import prestage

    p = prestage.la_history(n_txns=n_txns, n_keys=max(64, n_txns // 8),
                            save=True)
    h = pad_packed(p)
    sig = f"T{h.txn_type.shape[0]}_M{h.mop_txn.shape[0]}_" \
          f"R{h.rd_elems.shape[0]}_k{p.n_keys}"
    return core_check, (h, p.n_keys), {}, sig


def rw_stage(n_txns):
    from jepsen_tpu.checkers.elle.device_rw import pad_packed, rw_core_check
    from jepsen_tpu.utils import prestage

    p = prestage.rw_history(n_txns=n_txns, n_keys=max(64, n_txns // 8),
                            save=True)
    h = pad_packed(p)
    m = h.mop_txn.shape[0]
    sig = f"T{h.txn_type.shape[0]}_M{m}_k{h.n_keys}"
    return rw_core_check, (h, h.n_keys), \
        {"max_k": 128, "max_rounds": 64, "rw_cap": m}, sig


STAGES = {
    "la_100k": lambda: la_stage(100_000),
    "la_1m": lambda: la_stage(1_000_000),
    "rw_1m": lambda: rw_stage(1_000_000),
    "la_10m": lambda: la_stage(10_000_000),
}


def main():
    cache_dir = enable_compile_cache()
    done = set()
    if os.path.exists(OUT):
        with open(OUT) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("ok"):
                    done.add((rec.get("stage"), rec.get("sig")))

    topo = topologies.get_topology_desc(topology_name="v5e:2x2",
                                        platform="tpu")
    dev = topo.devices[0]
    names = [s.strip() for s in os.environ.get(
        "AOT_STAGES", "la_100k,la_1m,rw_1m,la_10m").split(",") if s.strip()]
    for name in names:
        t0 = time.perf_counter()
        fn, (h, static), kw, sig = STAGES[name]()
        if (name, sig) in done:
            print(f"{name}: already warm ({sig})", flush=True)
            continue
        prep_s = time.perf_counter() - t0
        hs = _sds(h, dev)
        del h  # drop the multi-GB padded arrays before the long compile
        print(f"{name}: lowering at {sig} (prep {prep_s:.0f}s)", flush=True)
        try:
            t0 = time.perf_counter()
            lowered = fn.lower(hs, static, **kw)
            lower_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            lowered.compile()
            compile_s = time.perf_counter() - t0
        except Exception as e:
            record({"stage": name, "sig": sig, "ok": False,
                    "error": f"{type(e).__name__}: {e}"})
            print(f"{name}: FAILED {type(e).__name__}: {e}", flush=True)
            continue
        record({"stage": name, "sig": sig, "ok": True,
                "lower_s": round(lower_s, 1),
                "compile_s": round(compile_s, 1),
                "cache_dir": cache_dir})
        print(f"{name}: compiled in {compile_s:.0f}s", flush=True)


if __name__ == "__main__":
    main()
