"""Deviceless AOT compile of the TPU ladder programs (VERDICT r04 item
1c): populate the persistent XLA compile cache for v5e *without the
tunnel*, so an open window pays dial + run only.

How: `jax.experimental.topologies.get_topology_desc("v5e:2x2")` gives
compile-only TPU devices through the local libtpu — no device, no
tunnel.  Lowering the exact module-level jitted callables the ladder
scripts invoke (`core_check`, `rw_core_check`) at the exact prestaged
padded shapes produces the same serialized computation + compile
options, hence the same persistent-cache key, as the in-window call —
provided the tunnel backend reports the same libtpu platform version.
If it does not, the in-window run simply compiles as before; cache
warming is a pure hedge.

Stages mirror scripts/tpu_campaign.py.  Each stage is recorded in
scripts/aot_warm.jsonl; completed stages are skipped on re-runs (keyed
by shape signature, so a program change re-warms).

Usage: nohup python scripts/aot_warm.py >> scripts/aot_warm.log 2>&1 &
Env: AOT_STAGES=la_100k,la_1m,... (default: all).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "scripts", "aot_warm.jsonl")

# Compile the CHIP program: the Pallas LOCF/scan gates check
# `default_backend() == "tpu"`, which is False in this forced-CPU
# process — without JT_PALLAS=1 every stage silently lowers the lax-path
# program the chip never runs (the round-5 session-2 "silent defeat #2",
# PROFILE.md §-1f).
os.environ["JT_PALLAS"] = "1"

from jepsen_tpu.utils.backend import enable_compile_cache, force_cpu_backend

force_cpu_backend()  # numpy/pad work runs on CPU; axon must not dial

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# local libtpu as the `tpu` platform (compile-only, no tunnel) so pallas
# lowering rules resolve; libtpu takes /tmp/libtpu_lockfile — one
# compile process at a time
_xb.register_plugin(
    "tpu",
    library_path="/opt/venv/lib/python3.12/site-packages/libtpu/libtpu.so",
    priority=0)

from jax.experimental import topologies  # noqa: E402
from jax.sharding import SingleDeviceSharding  # noqa: E402


def match_axon_fingerprint():
    """Make deviceless-AOT cache keys identical to the axon tunnel's.

    Measured (scripts/cache_key_probe.py, 2026-08-01): of the 8 cache-key
    components only TWO differ between this path and the in-tunnel
    compile — `backend version` (axon prepends "axon 0.1.0;
    SerializedExecutable v9; ..." and its terminal libtpu build string
    differs from the local one) and `accelerator_config` (axon
    serializes its topology as that same version string; the local
    topology path serializes a real PjRtTopology proto).  Hash the
    axon-side values (captured live into scripts/axon_fingerprint.json)
    in their place and the keys match, so entries compiled HERE — on a
    125 GB-RAM host — are hit by the tunnel run whose remote compile
    helper is OOM-killed at 2^24-txn shapes (PROFILE.md §-1f).
    Compatibility of the loaded executable is the terminal runtime's
    call ("compat c49"): validated end-to-end on a fresh shape before
    trusting it for the 10M programs."""
    import base64

    fp_path = os.path.join(REPO, "scripts", "axon_fingerprint.json")
    with open(fp_path) as f:
        fp = json.load(f)
    ver = fp["platform_version"]
    topo_bytes = base64.b64decode(fp["topology_b64"])
    from jax._src import cache_key as _ck

    def _hash_platform(hash_obj, backend):
        _ck._hash_string(hash_obj, "tpu")
        _ck._hash_string(hash_obj, ver)

    def _hash_accelerator_config(hash_obj, accelerators):
        hash_obj.update(topo_bytes)

    _ck._hash_platform = _hash_platform
    _ck._hash_accelerator_config = _hash_accelerator_config
    print(f"aot_warm: cache keys pinned to axon fingerprint "
          f"({ver.splitlines()[1][:40]}...)", flush=True)


def record(rec):
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _sds(tree, dev):
    sh = SingleDeviceSharding(dev)
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh), tree)


def la_stage(n_txns):
    from jepsen_tpu.checkers.elle.device_core import core_check
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.utils import prestage

    p = prestage.la_history(n_txns=n_txns, n_keys=max(64, n_txns // 8),
                            save=True)
    h = pad_packed(p)
    sig = f"T{h.txn_type.shape[0]}_M{h.mop_txn.shape[0]}_" \
          f"R{h.rd_elems.shape[0]}_k{p.n_keys}"
    return core_check, (h, p.n_keys), {}, sig


def rw_stage(n_txns):
    from jepsen_tpu.checkers.elle.device_rw import pad_packed, rw_core_check
    from jepsen_tpu.utils import prestage

    p = prestage.rw_history(n_txns=n_txns, n_keys=max(64, n_txns // 8),
                            save=True)
    h = pad_packed(p)
    m = h.mop_txn.shape[0]
    sig = f"T{h.txn_type.shape[0]}_M{m}_k{h.n_keys}"
    return rw_core_check, (h, h.n_keys), \
        {"max_k": 128, "max_rounds": 64, "rw_cap": m}, sig


def la_staged_pair(n_txns, max_k):
    """The two-program staged split (device_core.core_check_staged) —
    the form that survives the axon remote-compile helper's OOM SIGKILL
    at 2^24-txn shapes... except the infer program ALSO kills it
    (measured 2026-08-01, HTTP 500 SIGKILL 9), hence this local AOT
    route: this box has 125 GB RAM, the remote helper has a cap."""
    from jepsen_tpu.checkers.elle.device_core import (_infer_stage,
                                                      _sweep_stage)
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.utils import prestage

    p = prestage.la_history(n_txns=n_txns, n_keys=max(64, n_txns // 8),
                            save=True)
    h = pad_packed(p)
    from jepsen_tpu.ops import pallas_fill

    # program-variant marker: a lax-path warm is useless to the chip and
    # must not satisfy the resume skip for the kernel-bearing program
    variant = "pl1" if pallas_fill.fill_enabled() else "lax"
    sig = f"staged_T{h.txn_type.shape[0]}_M{h.mop_txn.shape[0]}_" \
          f"R{h.rd_elems.shape[0]}_k{p.n_keys}_mk{max_k}_{variant}"
    return (_infer_stage, _sweep_stage), (h, p.n_keys), \
        {"max_k": max_k, "max_rounds": 64}, sig


STAGES = {
    "la_100k": lambda: la_stage(100_000),
    "la_1m": lambda: la_stage(1_000_000),
    "rw_1m": lambda: rw_stage(1_000_000),
    "la_10m": lambda: la_stage(10_000_000),
    # staged pairs: max_k must match what the on-chip caller will
    # request (a different max_k is a different static-arg
    # specialization = different executable).  tpu_10m.py and this
    # stage share the same JT_10M_MAX_K default so they can't drift.
    "la_100k_staged": lambda: la_staged_pair(
        100_000, int(os.environ.get("JT_AOT_MAX_K", 128))),
    "la_200k_staged": lambda: la_staged_pair(
        200_000, int(os.environ.get("JT_AOT_MAX_K", 128))),
    "la_1m_staged": lambda: la_staged_pair(
        1_000_000, int(os.environ.get("JT_AOT_MAX_K", 128))),
    "la_10m_staged": lambda: la_staged_pair(
        10_000_000, int(os.environ.get("JT_10M_MAX_K", 32))),
}


def main():
    cache_dir = enable_compile_cache()
    if os.environ.get("AOT_MATCH_AXON"):
        match_axon_fingerprint()
    done = set()
    if os.path.exists(OUT):
        with open(OUT) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("ok"):
                    done.add((rec.get("stage"), rec.get("sig")))

    topo = topologies.get_topology_desc(topology_name="v5e:2x2",
                                        platform="tpu")
    dev = topo.devices[0]
    names = [s.strip() for s in os.environ.get(
        "AOT_STAGES", "la_100k,la_1m,rw_1m,la_10m").split(",") if s.strip()]
    for name in names:
        t0 = time.perf_counter()
        fn, (h, static), kw, sig = STAGES[name]()
        if os.environ.get("AOT_MATCH_AXON"):
            sig += "_axonkey"
        if (name, sig) in done:
            print(f"{name}: already warm ({sig})", flush=True)
            continue
        prep_s = time.perf_counter() - t0
        hs = _sds(h, dev)
        del h  # drop the multi-GB padded arrays before the long compile
        if isinstance(fn, tuple):
            # staged pair: sweep consumes infer's outputs — lower it at
            # eval_shape of the infer stage (abstract, no execution)
            infer_fn, sweep_fn = fn
            out_sd = _sds(jax.eval_shape(infer_fn, hs, static), dev)
            programs = [("infer", infer_fn, (hs, static), {}),
                        ("sweep", sweep_fn, (out_sd,), kw)]
        else:
            programs = [("fused", fn, (hs, static), kw)]
        print(f"{name}: lowering at {sig} (prep {prep_s:.0f}s)", flush=True)
        times = {}
        failed = False
        for pname, pfn, pargs, pkw in programs:
            try:
                t0 = time.perf_counter()
                lowered = pfn.lower(*pargs, **pkw)
                lower_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                lowered.compile()
                times[pname] = {"lower_s": round(lower_s, 1),
                                "compile_s": round(
                                    time.perf_counter() - t0, 1)}
                print(f"{name}/{pname}: compiled in "
                      f"{times[pname]['compile_s']:.0f}s", flush=True)
            except Exception as e:
                record({"stage": name, "sig": sig, "ok": False,
                        "program": pname,
                        "error": f"{type(e).__name__}: {e}"})
                print(f"{name}/{pname}: FAILED {type(e).__name__}: {e}",
                      flush=True)
                failed = True
                break
        if failed:
            continue
        record({"stage": name, "sig": sig, "ok": True, "programs": times,
                "cache_dir": cache_dir})


if __name__ == "__main__":
    main()
