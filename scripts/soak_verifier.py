#!/usr/bin/env python
"""Verifier soak: concurrent session waves under chaos, with
/metrics-cited saturation curves (ISSUE 7, grown by ISSUE 13).

Spins up one in-process `VerifierService` in production shape —
maintenance thread (multi-tenant batched sweeps + GC), journal
auto-compaction, sealed-session archival — then drives it with WAVES of
concurrent client threads (a saturation curve: each wave doubles the
session count).  Every client streams segments over the real cursor
protocol while a seeded `FaultPlan` fires transients/stalls on the
guarded ``verifier.ingest`` / ``verifier.sweep`` / ``verifier.seal``
seams; clients also poll rolling verdicts mid-stream so
verdict-freshness is a live quantity, and every session seals
``incremental == batch`` at the end.

Per wave, the soak samples the Prometheus exposition (the SAME text a
scraper would see) and reports: sessions active, ingest ops/s,
verdict-freshness p95, journal bytes.  The payload prints as one
BENCH-shaped JSON line (ingestable via ``cli obs ingest --bench``).

The run FAILS unless every session sealed equal, at least one
compaction cycle ran (bounding journal bytes), and sealed sessions were
archived (bounding /metrics series count).

Usage::

    python scripts/soak_verifier.py --fast           # tier-1 smoke
    python scripts/soak_verifier.py                  # default soak
    python scripts/soak_verifier.py --sessions 200 --txns 300 \\
        --fault-p 0.05 --seed 3                      # the long one

Exit 0 iff the acceptance holds.
"""

import argparse
import json
import os
import re
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu import telemetry  # noqa: E402
from jepsen_tpu.resilience import faults  # noqa: E402
from jepsen_tpu.telemetry import prometheus  # noqa: E402
from jepsen_tpu.verifier import VerifierService  # noqa: E402
from jepsen_tpu.workloads import synth  # noqa: E402


def client(svc, name, segments, txns, seed, inject, errors, stats,
           verdict_every=0):
    """One streaming client: generate a history, chop it into
    line-boundary-agnostic byte segments, push them with cursor
    resume (polling the rolling verdict along the way), then seal."""
    h = synth.la_history(n_txns=txns, n_keys=6, concurrency=5,
                         seed=seed, fail_prob=0.05, info_prob=0.05)
    if inject:
        getattr(synth, inject)(h)
    body = b"".join(json.dumps(op.to_dict()).encode() + b"\n"
                    for op in h)
    seg_bytes = max(64, len(body) // segments)
    cur = 0
    retries = 0
    sent_segs = 0
    while cur < len(body):
        # deliberately NOT line-aligned: the server acks only complete
        # lines and the client always resends from the acked cursor
        chunk = body[cur:cur + seg_bytes]
        code, r = svc.ingest(name, chunk, cursor=cur)
        if code == 503:
            retries += 1
            if retries > 50:
                errors.append(f"{name}: too many 503s")
                return
            time.sleep(0.01)
            continue
        if code != 200:
            errors.append(f"{name}: ingest rc={code} {r}")
            return
        if r["cursor"] == cur and len(chunk) == seg_bytes:
            # a whole segment with no complete line would wedge the
            # loop — only possible with absurdly tiny seg_bytes
            seg_bytes *= 2
        cur = max(cur, r["cursor"])
        sent_segs += 1
        if verdict_every and sent_segs % verdict_every == 0:
            code, _v = svc.verdict(name)  # rolling verdict keeps the
            # freshness gauge live; 503s here are chaos, ignored

    def retrying(fn, what):
        # 503 = a persistent injected fault survived the guard's own
        # retries; the chaos targets verifier.sweep/seal too, so the
        # client must retry those exactly like the ingest path
        for _ in range(50):
            code, doc = fn()
            if code != 503:
                return code, doc
            time.sleep(0.01)
        errors.append(f"{name}: {what} still 503 after retries")
        return 503, doc

    code, v = retrying(lambda: svc.verdict(name), "verdict")
    if code != 200:
        if code != 503:
            errors.append(f"{name}: verdict rc={code} {v}")
        return
    code, sealed = retrying(lambda: svc.seal(name), "seal")
    if code != 200 or sealed.get("equal") is not True:
        if code != 503:
            errors.append(f"{name}: seal rc={code} {sealed}")
        return
    stats.append({"session": name, "txns": sealed["txns"],
                  "valid?": sealed["verdict"].get("valid?"),
                  "anomalies": sealed["verdict"].get("anomaly-types"),
                  "retries-503": retries})


# ---------------------------------------------------------------- metrics

def scrape(reg):
    """Parse the Prometheus exposition text into {name: value} and
    {name: [labeled values]} — the saturation numbers are CITED from
    the same surface a scraper reads, not from internals."""
    text = prometheus.render_registry(reg)
    flat, labeled = {}, {}
    pat = re.compile(r"^(\w+)(\{[^}]*\})? (\S+)$")
    for line in text:
        if line.startswith("#"):
            continue
        m = pat.match(line)
        if not m:
            continue
        name, labels, val = m.groups()
        try:
            v = float(val)
        except ValueError:
            continue
        if labels:
            labeled.setdefault(name, []).append(v)
        else:
            flat[name] = v
    return flat, labeled


def p95(vals):
    if not vals:
        return None
    vs = sorted(vals)
    return round(vs[min(len(vs) - 1, int(0.95 * (len(vs) - 1)))], 3)


def run_wave(svc, n_sessions, args, wave_idx, errors, stats):
    """One saturation-curve point: n_sessions concurrent clients,
    metrics sampled from the exposition before/after."""
    reg = telemetry.registry()
    flat0, _ = scrape(reg)
    ing0 = flat0.get("jepsen_verifier_ops_ingested_total", 0.0)
    t0 = time.time()
    injectors = [None, "inject_wr_cycle", "inject_g1a",
                 "inject_rw_cycle"]
    peak_fresh = []
    stop_sample = threading.Event()

    def sampler():
        while not stop_sample.wait(0.2):
            _f, lab = scrape(reg)
            fr = lab.get("jepsen_verifier_verdict_freshness_s")
            if fr:
                peak_fresh.append(p95(fr))

    st = threading.Thread(target=sampler, daemon=True)
    st.start()
    threads = [
        threading.Thread(
            target=client,
            args=(svc, f"soak-w{wave_idx}-{i}", args.segments,
                  args.txns, args.seed * 1000 + wave_idx * 100 + i,
                  injectors[i % len(injectors)], errors, stats),
            kwargs={"verdict_every": max(2, args.segments // 2)})
        for i in range(n_sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop_sample.set()
    st.join(timeout=2)
    wall = time.time() - t0
    # a wave can finish inside one maintenance interval: refresh the
    # journal gauge so the curve row cites a real byte count
    svc._journal_gauge()
    flat1, lab1 = scrape(reg)
    ing1 = flat1.get("jepsen_verifier_ops_ingested_total", 0.0)
    return {
        "sessions": n_sessions,
        "wall_s": round(wall, 3),
        "ingest_ops_s": round((ing1 - ing0) / max(wall, 1e-9), 1),
        "verdict_freshness_p95_s": p95([v for v in peak_fresh
                                        if v is not None]) or 0.0,
        "journal_bytes": flat1.get("jepsen_verifier_journal_bytes"),
        "sessions_active_peak": flat1.get(
            "jepsen_verifier_sessions_active"),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sessions", "--clients", type=int, default=24,
                    dest="sessions",
                    help="peak concurrent sessions (the last wave)")
    ap.add_argument("--segments", type=int, default=8)
    ap.add_argument("--txns", type=int, default=200)
    ap.add_argument("--fault-p", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--compact-bytes", type=int, default=16384,
                    help="per-session journal budget before "
                         "auto-compaction")
    ap.add_argument("--store", default=None,
                    help="store dir (default: a temp dir)")
    ap.add_argument("--bench-out", default=None,
                    help="also write the BENCH payload to this path")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 smoke: waves of 2+4 sessions x 4 "
                         "segments x 80 txns")
    args = ap.parse_args()
    if args.fast:
        args.sessions, args.segments, args.txns = 4, 4, 80
        args.fault_p = max(args.fault_p, 0.3)  # few calls: chaos lands
    base = args.store
    if base is None:
        import tempfile

        base = tempfile.mkdtemp(prefix="verifier-soak-")
    svc = VerifierService(base, default_config={
        "compact-bytes": args.compact_bytes,
        # retention: sealed sessions archive promptly so the /metrics
        # series count is bounded across waves, open-but-abandoned
        # sessions expire
        "archive-sealed-s": 0.5,
        "gc-idle-s": 120.0,
    })
    svc.start_maintenance(interval_s=0.3)
    plan = faults.FaultPlan(
        seed=args.seed, p=args.fault_p,
        kinds=("oom", "xla", "stall"), stall_s=0.01,
        sites=("verifier.ingest", "verifier.sweep", "verifier.seal"))
    errors, stats = [], []
    # the saturation curve: doubling waves up to --sessions
    waves = []
    n = max(2, args.sessions // 4)
    while n < args.sessions:
        waves.append(n)
        n *= 2
    waves.append(args.sessions)
    t0 = time.time()
    curve = []
    reg = telemetry.registry()
    series0 = len(scrape(reg)[1].get(
        "jepsen_verifier_verdict_freshness_s", []))
    with faults.use(plan):
        for wi, n_sessions in enumerate(waves):
            curve.append(run_wave(svc, n_sessions, args, wi, errors,
                                  stats))
            print(f"wave {wi}: {json.dumps(curve[-1])}", flush=True)
    # let the maintenance loop archive the sealed sessions
    deadline = time.time() + 10.0
    while time.time() < deadline:
        flat, lab = scrape(reg)
        series_now = len(lab.get("jepsen_verifier_verdict_freshness_s",
                                 []))
        if series_now == 0 and \
                flat.get("jepsen_verifier_sessions_active", 1) == 0:
            break
        time.sleep(0.3)
    flat, lab = scrape(reg)
    svc.close()
    wall = time.time() - t0
    total = sum(w for w in waves) * args.txns
    n_compactions = int(flat.get("jepsen_verifier_compactions_total",
                                 0))
    series_final = len(lab.get("jepsen_verifier_verdict_freshness_s",
                               []))
    journal_final = flat.get("jepsen_verifier_journal_bytes", 0)

    for s in sorted(stats, key=lambda s: s["session"])[:8]:
        print(f"  {s['session']}: {s['txns']} txns valid?="
              f"{s['valid?']} anomalies={s['anomalies']} "
              f"503-retries={s['retries-503']}")
    print(f"faults injected: {len(plan.injected)} over "
          f"{plan._n_calls} guarded calls; {n_compactions} journal "
          f"compactions; freshness series {series0} -> {series_final} "
          f"(retired on seal/archive); journal bytes now "
          f"{journal_final}")
    want = sum(waves)
    if errors or len(stats) != want:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        print(f"soak FAILED ({len(stats)}/{want} sealed) "
              f"in {wall:.1f}s", file=sys.stderr)
        return 1
    if args.compact_bytes and n_compactions == 0 and \
            args.txns * 60 > args.compact_bytes:
        print("FAIL: no compaction cycle ran (journal growth "
              "unbounded)", file=sys.stderr)
        return 1
    if series_final != 0:
        print(f"FAIL: {series_final} per-session freshness series "
              "survived archival (metrics cardinality leak)",
              file=sys.stderr)
        return 1
    payload = {
        "metric": "verifier-soak-ingest",
        "value": max(w["ingest_ops_s"] for w in curve),
        "unit": "ops/s",
        "n_txns": total,
        "backend": "cpu",
        "sessions_peak": args.sessions,
        "wall_s": round(wall, 3),
        "compactions": n_compactions,
        "saturation": curve,
        "verdict_freshness_p95_s": max(
            w["verdict_freshness_p95_s"] for w in curve),
    }
    print("BENCH " + json.dumps(payload))
    if args.bench_out:
        with open(args.bench_out, "w") as f:
            json.dump(payload, f, indent=1)
    print(f"soak OK: waves {waves} x {args.segments} segments x "
          f"{args.txns} txns under chaos — every session sealed "
          f"incremental == batch, journals compacted, series retired, "
          f"in {wall:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
