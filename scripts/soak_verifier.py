#!/usr/bin/env python
"""Verifier soak: N concurrent clients x M segments under ingest chaos.

ISSUE 7 satellite.  Spins up one in-process `VerifierService`, then N
client threads each stream M history segments into their own session
with a seeded `FaultPlan` firing synthetic transients (and stalls) on
the guarded ``verifier.ingest`` / ``verifier.sweep`` seams.  Clients
speak the real cursor protocol — a 503 (persistent injected fault
after retries) is retried from the last acked cursor, which must be
idempotent.  At the end every session is sealed and the run FAILS
unless every seal reports ``incremental == batch``.

Usage::

    python scripts/soak_verifier.py --fast          # tier-1 smoke
    python scripts/soak_verifier.py                 # default soak
    python scripts/soak_verifier.py --clients 8 --segments 20 \\
        --txns 400 --fault-p 0.1 --seed 3           # the long one

Exit 0 iff every session sealed equal.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu.resilience import faults  # noqa: E402
from jepsen_tpu.verifier import VerifierService  # noqa: E402
from jepsen_tpu.workloads import synth  # noqa: E402


def client(svc, name, segments, txns, seed, inject, errors, stats):
    """One streaming client: generate a history, chop it into
    line-boundary-agnostic byte segments, push them with cursor
    resume, then seal."""
    h = synth.la_history(n_txns=txns, n_keys=6, concurrency=5,
                         seed=seed, fail_prob=0.05, info_prob=0.05)
    if inject:
        getattr(synth, inject)(h)
    body = b"".join(json.dumps(op.to_dict()).encode() + b"\n"
                    for op in h)
    seg_bytes = max(64, len(body) // segments)
    cur = 0
    retries = 0
    while cur < len(body):
        # deliberately NOT line-aligned: the server acks only complete
        # lines and the client always resends from the acked cursor
        chunk = body[cur:cur + seg_bytes]
        code, r = svc.ingest(name, chunk, cursor=cur)
        if code == 503:
            retries += 1
            if retries > 50:
                errors.append(f"{name}: too many 503s")
                return
            time.sleep(0.01)
            continue
        if code != 200:
            errors.append(f"{name}: ingest rc={code} {r}")
            return
        if r["cursor"] == cur and len(chunk) == seg_bytes:
            # a whole segment with no complete line would wedge the
            # loop — only possible with absurdly tiny seg_bytes
            seg_bytes *= 2
        cur = max(cur, r["cursor"])
    def retrying(fn, what):
        # 503 = a persistent injected fault survived the guard's own
        # retries; the chaos targets verifier.sweep/seal too, so the
        # client must retry those exactly like the ingest path
        for _ in range(50):
            code, doc = fn()
            if code != 503:
                return code, doc
            time.sleep(0.01)
        errors.append(f"{name}: {what} still 503 after retries")
        return 503, doc

    code, v = retrying(lambda: svc.verdict(name), "verdict")
    if code != 200:
        if code != 503:
            errors.append(f"{name}: verdict rc={code} {v}")
        return
    code, sealed = retrying(lambda: svc.seal(name), "seal")
    if code != 200 or sealed.get("equal") is not True:
        if code != 503:
            errors.append(f"{name}: seal rc={code} {sealed}")
        return
    stats.append({"session": name, "txns": sealed["txns"],
                  "valid?": sealed["verdict"].get("valid?"),
                  "anomalies": sealed["verdict"].get("anomaly-types"),
                  "retries-503": retries})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--segments", type=int, default=8)
    ap.add_argument("--txns", type=int, default=200)
    ap.add_argument("--fault-p", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--store", default=None,
                    help="store dir (default: a temp dir)")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 smoke: 2 clients x 3 segments x 80 "
                         "txns")
    args = ap.parse_args()
    if args.fast:
        args.clients, args.segments, args.txns = 2, 4, 80
        args.fault_p = max(args.fault_p, 0.35)  # few calls: make chaos land
    base = args.store
    if base is None:
        import tempfile

        base = tempfile.mkdtemp(prefix="verifier-soak-")
    svc = VerifierService(base)
    plan = faults.FaultPlan(
        seed=args.seed, p=args.fault_p,
        kinds=("oom", "xla", "stall"), stall_s=0.01,
        sites=("verifier.ingest", "verifier.sweep", "verifier.seal"))
    injectors = [None, "inject_wr_cycle", "inject_g1a",
                 "inject_rw_cycle"]
    errors, stats = [], []
    t0 = time.time()
    with faults.use(plan):
        threads = [
            threading.Thread(
                target=client,
                args=(svc, f"soak-{i}", args.segments, args.txns,
                      args.seed * 1000 + i,
                      injectors[i % len(injectors)], errors, stats))
            for i in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    svc.close()
    wall = time.time() - t0
    for s in sorted(stats, key=lambda s: s["session"]):
        print(f"  {s['session']}: {s['txns']} txns valid?="
              f"{s['valid?']} anomalies={s['anomalies']} "
              f"503-retries={s['retries-503']}")
    print(f"faults injected: {len(plan.injected)} over "
          f"{plan._n_calls} guarded calls")
    if errors or len(stats) != args.clients:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        print(f"soak FAILED ({len(stats)}/{args.clients} sealed) "
              f"in {wall:.1f}s", file=sys.stderr)
        return 1
    print(f"soak OK: {args.clients} clients x {args.segments} segments "
          f"x {args.txns} txns, every session sealed incremental == "
          f"batch, in {wall:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
