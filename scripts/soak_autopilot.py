#!/usr/bin/env python
"""Autopilot soak: unattended generations under kill -9 + rolling
upgrade.  ISSUE 17 acceptance driver.

A **child** process runs the real thing — `fleet.Autopilot` with its
own HTTP control plane and managed ``fleet work`` subprocess pool —
streaming generations of a real bank campaign (telemetry on, spans
recorded).  Generation 2 carries a seeded regression: the mutator
bumps client latency ~2.5x AND installs a skew nemesis window, so the
workload span blows past the gate threshold and the cells go invalid
(a real shrinkable anomaly, not a synthetic record).

The **parent** orchestrates the failure script:

- child A (phase ``a``) streams generations until the parent sees
  generation g0001 mid-flight, then the whole "host" is ``kill -9``'d
  — coordinator AND its managed workers;
- child B (phase ``b``) restarts on the same port + store.  Resume
  must re-admit from the journal with ZERO duplicate cells (the
  constructor digest must equal the parent's independent replay of
  the crashed journal).  It closes the resumed generation, catches the
  seeded regression (gate rc 1 -> quarantine -> REAL auto-shrink to a
  witness).  The watchtower's ``autopilot-gate-regression`` rule goes
  pending -> firing on the autopilot's alert tick and the firing
  notification lands in the FileSink — at which point the parent
  ``kill -9``'s the host AGAIN, mid-firing;
- child C (same ``--child b`` code path) resumes once more.  Its
  alert-journal replay digest must equal the parent's independent
  replay of the crashed ``alerts.jsonl``, and the already-journaled
  notify intent must NOT re-send (zero duplicate notifications).  It
  closes the remaining generations — the quarantine excludes the
  regressed key, the gate goes green, the alert RESOLVES — then flips
  ``worker_version`` v1 -> v2 and runs the last generation through
  the rolling upgrade — one replacement at a time, every cell
  landing, ``jepsen_fleet_host_info`` cardinality flat.

The run FAILS unless: every admitted cell lands exactly one
attributable verdict (done == cells, duplicates == 0), exactly one
cell key is quarantined with a witness-bearing shrink outcome, the
final journal replays to the child's reported digest, the alert
journal shows the full pending -> firing -> resolved arc with exactly
one firing and one resolved notification line, every surviving worker
is v2, and the host_info series count is identical before and after
the upgrade.

Usage::

    python scripts/soak_autopilot.py --fast   # tier-1 acceptance
    python scripts/soak_autopilot.py          # wider soak

Exit 0 iff the acceptance holds.
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

NAME = "ap-soak"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def http_json(url, path, timeout=2.0):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return json.loads(r.read().decode())


def host_info_series(url, timeout=2.0) -> int:
    with urllib.request.urlopen(url + "/metrics",
                                timeout=timeout) as r:
        text = r.read().decode()
    return sum(1 for l in text.splitlines()
               if l.startswith("jepsen_fleet_host_info{"))


def template(seeds):
    """A mini production-traffic mix (specs/production-traffic.json
    shape): the bank pivot every generation runs, plus queue/kafka
    scenarios the rotation walks through one slot at a time."""
    return {"name": NAME,
            "workloads": ["bank",
                          {"name": "queue", "label": "queue"},
                          {"name": "kafka", "label": "kafka",
                           "opts": {"kafka-subscribe-frac": 0.2,
                                    "kafka-txn-frac": 0.3}}],
            "seeds": list(seeds),
            "opts": {"telemetry": True, "time-limit": 0.5,
                     "ops": 200, "concurrency": 3,
                     "client-latency": 0.004}}


def mutate(i, sp):
    """Scenario rotation (ROADMAP 5c) composed with the seeded
    regression: every generation keeps the bank pivot and one
    rotating queue/kafka cell; generation >= 2 regresses — slower
    clients (the span the gate watches) plus a skew window (a real
    anomaly for the shrinker).  Attribution can only land on a key
    present in BOTH generations, i.e. the pivot."""
    from jepsen_tpu.fleet import scenario_rotation

    sp = scenario_rotation(pivot=("bank",), slots=1)(i, sp)
    if i >= 2:
        o = sp.setdefault("opts", {})
        o["client-latency"] = 0.01
        o["nemesis-windows"] = [{"pos": 0, "fault": "skew",
                                 "at_s": 0.0, "dur_s": 0.4}]
    return sp


# ------------------------------------------------------------- child

def notif_path(store):
    return os.path.join(store, "alert-notifications.jsonl")


def build(args, version):
    from jepsen_tpu.fleet import Autopilot
    from jepsen_tpu.telemetry.alerts import FileSink

    return Autopilot(
        template(args.seed_list), args.store,
        lease_s=2.0, generations=args.gens, spans=("workload",),
        mutate=mutate,
        coordinator_url=f"http://127.0.0.1:{args.port}",
        min_workers=2, max_workers=3, worker_version=version,
        scale_interval_s=0.25, worker_poll_s=0.05,
        shrink_knobs={"probe-deadline": 15.0}, poll_s=0.05,
        alert_sinks=[FileSink(notif_path(args.store))])


def child_a(args) -> int:
    from jepsen_tpu import web

    ap = build(args, "v1")
    web.serve(args.port, args.store, fleet=ap.coordinator,
              background=True)
    print(f"CHILD-A-UP digest={ap.journal.digest()}", flush=True)
    ap.run()  # the parent kill -9s us mid-loop
    return 0


def child_b(args) -> int:
    from jepsen_tpu import web

    ap = build(args, "v1")
    web.serve(args.port, args.store, fleet=ap.coordinator,
              background=True)
    url = f"http://127.0.0.1:{args.port}"
    print(f"CHILD-B-RESUMED digest={ap.journal.digest()} "
          f"alerts={ap.alerts.journal.digest()}", flush=True)

    # close every generation but the last (resumes the crashed one,
    # then catches + quarantines + shrinks the seeded regression —
    # the gate-regression alert fires on the closing step's alert
    # tick, which is where the parent kill -9s phase b)
    while len(ap.journal.closed_labels()) < args.gens - 1:
        out = ap.step()
        print(f"CHILD-B-GEN {json.dumps(out, default=str)}",
              flush=True)
        if out.get("stopped"):
            return 1

    # warm the pool before taking the pre-upgrade cardinality
    # baseline: a fresh resume (phase c skips the loop above) has no
    # live workers yet, so host_info would read 0
    warm = time.time() + 60.0
    while time.time() < warm:
        ap._scale_tick()
        live = [n for n in ap._live_workers()
                if not ap.workers[n]["draining"]]
        if len(live) >= ap.min_workers \
                and all(ap._worker_alive(n) for n in live) \
                and host_info_series(url) == len(live):
            break
        time.sleep(0.25)
    pre = host_info_series(url)
    if len(ap.journal.closed_labels()) < args.gens:
        ap.worker_version = "v2"  # the rolling upgrade rides last gen
        out = ap.step()
        print(f"CHILD-B-GEN {json.dumps(out, default=str)}",
              flush=True)

    # settle: tick the scaler until the pool is all-v2 per the
    # COORDINATOR's view and the old workers' series have retired
    deadline = time.time() + 90.0
    flat = None
    while time.time() < deadline:
        ap._scale_tick()
        live = [n for n in ap._live_workers()
                if not ap.workers[n]["draining"]]
        if len(live) >= ap.min_workers and \
                all(ap.workers[n]["version"] == "v2" for n in live) \
                and all(ap._worker_alive(n) for n in live):
            flat = host_info_series(url)
            if flat == pre == len(live):
                break
        time.sleep(0.25)
    finals = {n: ap.workers[n]["version"]
              for n in ap._live_workers()
              if not ap.workers[n]["draining"]}
    summary = {
        "digest": ap.journal.digest(),
        "closed": ap.journal.closed_labels(),
        "quarantined": {k: dict(v) for k, v in
                        ap.journal.quarantined.items()},
        "shrinks": {k: dict(v) for k, v in
                    ap.journal.shrinks.items()},
        "counts": ap.coordinator.queue.counts(),
        "host-info-pre": pre, "host-info-post": flat,
        "workers-final": finals,
        "alerts": ap.alerts.status_doc(),
    }
    print(f"CHILD-B-SUMMARY {json.dumps(summary)}", flush=True)
    ap.close()
    return 0


# ------------------------------------------------------------ parent

def wait_for(pred, deadline_s, what):
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        v = pred()
        if v:
            return v
        time.sleep(0.05)
    raise SystemExit(f"FAIL: timed out waiting for {what}")


def spawn_streaming(cmd, env):
    """Run a child with stdout piped through to ours while a side
    buffer keeps every line for post-hoc parsing (the parent polls
    the control plane concurrently, so a blocking read won't do)."""
    p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                         text=True)
    lines = []

    def pump():
        for line in p.stdout:
            sys.stdout.write(line)
            sys.stdout.flush()
            lines.append(line)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return p, lines, t


def parse_resumed(lines):
    """(autopilot digest, alert digest) from a CHILD-B-RESUMED line."""
    for line in lines:
        if line.startswith("CHILD-B-RESUMED"):
            toks = dict(t.split("=", 1) for t in line.split()[1:])
            return toks.get("digest"), toks.get("alerts")
    return None, None


def kill_host(proc, pids):
    """The whole-'host' kill -9: coordinator process and every
    managed worker it reported."""
    for pid in [proc.pid] + pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    proc.wait(timeout=10)
    # belt-and-braces: reap any worker spawned inside the scrape->kill
    # window (it would otherwise idle-poll the port forever and claim
    # cells from child B as an unmanaged v1 straggler)
    try:
        out = subprocess.run(
            ["pgrep", "-f", f"--name ap-{proc.pid}-"],
            capture_output=True, text=True)
        for pid in out.stdout.split():
            os.kill(int(pid), signal.SIGKILL)
    except (OSError, ValueError):
        pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 acceptance config")
    ap.add_argument("--gens", type=int, default=None)
    ap.add_argument("--seeds", type=int, default=None)
    ap.add_argument("--store", default=None)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--child", choices=["a", "b"], default=None)
    args = ap.parse_args()
    args.gens = args.gens or (4 if args.fast else 5)
    args.seeds = args.seeds or (3 if args.fast else 4)
    args.seed_list = list(range(args.seeds))

    if args.child:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return child_a(args) if args.child == "a" else child_b(args)

    from jepsen_tpu.fleet import AutopilotJournal, WorkQueue, \
        autopilot_path, fleet_path
    from jepsen_tpu.telemetry.alerts import AlertJournal, alerts_path

    base = args.store or tempfile.mkdtemp(prefix="soak-autopilot-")
    port = args.port or free_port()
    url = f"http://127.0.0.1:{port}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, os.path.abspath(__file__),
           "--gens", str(args.gens), "--seeds", str(args.seeds),
           "--store", base, "--port", str(port)]

    t_start = time.time()
    a = subprocess.Popen(cmd + ["--child", "a"], env=env)
    try:
        def mid_g0001():
            try:
                st = http_json(url, "/fleet/status")
            except OSError:
                return None
            apst = st.get("autopilot") or {}
            if apst.get("generations-closed", 0) >= 1 \
                    and st.get("done", 0) > 2 * args.seeds:
                return st
            return None

        st = wait_for(mid_g0001, 180, "generation g0001 mid-flight")
        pids = [w["pid"] for w in
                (st["autopilot"].get("workers") or {}).values()
                if w.get("running")]
        print(f"parent: killing host mid-{st['autopilot']['generation']}"
              f" (coordinator pid {a.pid} + workers {pids})",
              flush=True)
        kill_host(a, pids)
    except BaseException:
        kill_host(a, [])
        raise

    d_crash = AutopilotJournal(autopilot_path(NAME, base)).digest()

    # phase b: resume, catch the seeded regression, quarantine; the
    # parent waits for the gate-regression alert to go FIRING (and
    # its notification line to land), then kill -9s the host again
    b, blines, bt = spawn_streaming(cmd + ["--child", "b"], env)
    try:
        def firing():
            try:
                st = http_json(url, "/fleet/status")
            except OSError:
                return None
            al = (st.get("autopilot") or {}).get("alerts") or {}
            if "autopilot-gate-regression" not in al.get("firing", []):
                return None
            try:
                with open(notif_path(base)) as f:
                    sent = [json.loads(l) for l in f if l.strip()]
            except OSError:
                return None
            if any(n["alertname"] == "autopilot-gate-regression"
                   and n["state"] == "firing" for n in sent):
                return st
            return None

        st = wait_for(firing, 240, "gate-regression alert firing")
        pids = [w["pid"] for w in
                (st["autopilot"].get("workers") or {}).values()
                if w.get("running")]
        print(f"parent: killing host MID-FIRING "
              f"(coordinator pid {b.pid} + workers {pids})",
              flush=True)
        kill_host(b, pids)
    except BaseException:
        kill_host(b, [])
        raise
    bt.join(timeout=10)
    resumed, _ = parse_resumed(blines)

    d_crash2 = AutopilotJournal(autopilot_path(NAME, base)).digest()
    d_alert_crash = AlertJournal(alerts_path(base)).digest()

    # phase c: resume mid-firing, close out (quarantine excludes the
    # regressed key -> gate green -> alert RESOLVES), roll the upgrade
    c, clines, ct = spawn_streaming(cmd + ["--child", "b"], env)
    try:
        rc = c.wait(timeout=300)
    except BaseException:
        c.kill()
        raise
    ct.join(timeout=10)
    resumed_c, alerts_c = parse_resumed(clines)
    summary = None
    for line in clines:
        if line.startswith("CHILD-B-SUMMARY "):
            summary = json.loads(line.split("CHILD-B-SUMMARY ", 1)[1])
    if rc != 0 or summary is None:
        print(f"FAIL: child C rc={rc}, summary={summary is not None}")
        return 1

    fails = []
    if resumed != d_crash:
        fails.append(f"resume digest {resumed} != independent replay "
                     f"of the crashed journal {d_crash}")
    if resumed_c != d_crash2:
        fails.append(f"mid-firing resume digest {resumed_c} != "
                     f"independent replay {d_crash2}")
    if alerts_c != d_alert_crash:
        fails.append(f"mid-firing alert digest {alerts_c} != "
                     f"independent replay of the crashed alerts "
                     f"journal {d_alert_crash}")
    d_final = AutopilotJournal(autopilot_path(NAME, base)).digest()
    if summary["digest"] != d_final:
        fails.append(f"final digest {summary['digest']} != replay "
                     f"{d_final}")
    c = summary["counts"]
    q = len(summary["quarantined"])
    # 2 workloads per generation (bank pivot + 1 rotated slot) x
    # seeds, minus the quarantined pivot key's post-quarantine gens
    expect_cells = args.gens * 2 * args.seeds - q * (args.gens - 3)
    if c["duplicates"] != 0:
        fails.append(f"{c['duplicates']} duplicate verdicts")
    if c["done"] != c["cells"] or c["cells"] != expect_cells:
        fails.append(f"cells {c['cells']} done {c['done']} != "
                     f"expected {expect_cells} (zero lost/extra)")
    if q != 1:
        fails.append(f"expected exactly 1 quarantined key, got "
                     f"{sorted(summary['quarantined'])}")
    key = next(iter(summary["quarantined"]), "")
    sk = (summary["shrinks"].get(key) or {}).get("outcome") or {}
    if sk.get("error") or not sk.get("digest"):
        fails.append(f"shrink outcome lacks a witness: {sk}")
    wq = WorkQueue(fleet_path(NAME, base))
    unattr = [r for r, cell in wq.cells.items()
              if cell["state"] == "done"
              and not (cell.get("record") or {}).get("key")]
    if unattr:
        fails.append(f"{len(unattr)} unattributed verdicts")
    if wq.counts()["duplicates"] != 0:
        fails.append("ledger replay shows duplicates")
    finals = summary["workers-final"]
    if not finals or any(v != "v2" for v in finals.values()):
        fails.append(f"pool not fully upgraded: {finals}")
    if summary["host-info-pre"] != summary["host-info-post"] or \
            summary["host-info-pre"] != len(finals):
        fails.append(
            f"host_info cardinality moved: "
            f"{summary['host-info-pre']} -> "
            f"{summary['host-info-post']} (workers {len(finals)})")

    # the watchtower arc: the final alert journal replays to the
    # child's reported digest, the gate-regression rule walked
    # pending -> firing -> resolved, intents are at-most-once, and
    # the FileSink carries exactly one firing + one resolved line
    # despite the mid-firing kill -9
    aj = AlertJournal(alerts_path(base))
    al = summary.get("alerts") or {}
    if al.get("digest") != aj.digest():
        fails.append(f"final alert digest {al.get('digest')} != "
                     f"replay {aj.digest()}")
    if al.get("firing"):
        fails.append(f"alerts still firing at end: {al['firing']}")
    arc, intents = [], {}
    with open(alerts_path(base)) as f:
        for line in f:
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn tail
            if ev.get("rule") != "autopilot-gate-regression":
                continue
            if ev.get("ev") == "state":
                arc.append(ev.get("state"))
            elif ev.get("ev") == "notify":
                k = (ev["rule"], ev["seq"])
                intents[k] = intents.get(k, 0) + 1
    if arc != ["pending", "firing", "resolved"]:
        fails.append(f"gate-regression arc {arc} != "
                     f"['pending', 'firing', 'resolved']")
    if any(n > 1 for n in intents.values()):
        fails.append(f"duplicate notify intents: {intents}")
    sent = {}
    with open(notif_path(base)) as f:
        for line in f:
            n = json.loads(line)
            k = (n["alertname"], n["state"])
            sent[k] = sent.get(k, 0) + 1
    gr = "autopilot-gate-regression"
    if sent.get((gr, "firing")) != 1 or sent.get((gr, "resolved")) != 1:
        fails.append(f"notification lines for {gr}: {sent} — want "
                     f"exactly one firing and one resolved")
    if any(n > 1 for n in sent.values()):
        fails.append(f"duplicate notifications delivered: {sent}")

    wall = time.time() - t_start
    if fails:
        for f in fails:
            print(f"FAIL: {f}")
        return 1
    print(f"SOAK PASS gens={len(summary['closed'])} "
          f"cells={c['cells']} duplicates={c['duplicates']} "
          f"quarantined={key} witness-ops={sk.get('witness-ops')} "
          f"alert-arc=pending->firing->resolved "
          f"notifications={sum(sent.values())} upgrade=v1->v2 "
          f"host-info={summary['host-info-pre']}->"
          f"{summary['host-info-post']} wall={wall:.1f}s")
    if not args.store:
        shutil.rmtree(base, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
