#!/usr/bin/env python
"""Seeded chaos sweep over the resilience layer (ISSUE 2 satellite).

For each seed, builds a deterministic FaultPlan and runs the elle
list-append check, the elle rw-register check, and a knossos
linearizability analysis under it, asserting the resilience invariant:

    every faulted run terminates with a verdict, and that verdict
    either equals the fault-free one or is an attributable unknown
    (deadline-exceeded / budget exhaustion) — never a crash, never a
    hang, never a silently wrong answer.

Usage:
    JAX_PLATFORMS=cpu python scripts/fuzz_faults.py --rounds 20
    python scripts/fuzz_faults.py --rounds 5 --p 0.3 --deadline 30
    python scripts/fuzz_faults.py --compilecache --rounds 5
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu.utils.backend import force_cpu_backend  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu" \
        or os.environ.get("JT_FORCE_CPU"):
    force_cpu_backend()


def run_one(seed: int, p: float, deadline_s: float) -> dict:
    from jepsen_tpu.checkers.elle import list_append, rw_register
    from jepsen_tpu.checkers.knossos import analysis
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.resilience import Deadline, FaultPlan, RetryPolicy, use
    from jepsen_tpu.workloads import synth

    policy = RetryPolicy(max_attempts=3, base_delay_s=0.0, seed=seed)
    row = {"seed": seed, "injected": 0, "degraded": 0, "unknown": 0}

    def verify(name, clean, faulted):
        assert "valid?" in faulted, f"{name}: no verdict ({faulted})"
        if faulted["valid?"] == "unknown":
            # unknowns must be attributable, not silent
            assert faulted.get("error") or faulted.get("reason"), \
                f"{name}: unattributed unknown ({faulted})"
            row["unknown"] += 1
        else:
            assert faulted["valid?"] == clean["valid?"], \
                f"{name}: verdict flipped under faults " \
                f"({clean['valid?']} -> {faulted['valid?']})"
        if faulted.get("degraded"):
            row["degraded"] += 1

    # --- elle list-append (every other round carries a real anomaly) --
    h = synth.la_history(n_txns=50, seed=seed)
    if seed % 2:
        synth.inject_wr_cycle(h)
    clean = list_append.check(h)
    plan = FaultPlan(seed=seed, p=p, kinds=("oom", "xla", "stall"),
                     stall_s=0.01)
    faulted = list_append.check(h, plan=plan, policy=policy,
                                deadline=Deadline(deadline_s))
    verify("list-append", clean, faulted)
    row["injected"] += len(plan.injected)

    # --- elle rw-register (fused fast path forced on) ------------------
    hrw = synth.rw_history(n_txns=40, seed=seed)
    clean_rw = rw_register.check(hrw)
    plan_rw = FaultPlan(seed=seed + 1, p=p, kinds=("oom", "xla"))
    orig_min = rw_register.FUSED_MIN_TXNS
    rw_register.FUSED_MIN_TXNS = 1
    try:
        faulted_rw = rw_register.check(hrw, plan=plan_rw, policy=policy,
                                       deadline=Deadline(deadline_s))
    finally:
        rw_register.FUSED_MIN_TXNS = orig_min
    verify("rw-register", clean_rw, faulted_rw)
    row["injected"] += len(plan_rw.injected)

    # --- knossos (fault plan active process-wide during analysis) ------
    hl = synth.lin_register_history(n_ops=40, concurrency=3,
                                    info_prob=0.05, seed=seed)
    clean_k = analysis(hl, cas_register())
    plan_k = FaultPlan(seed=seed + 2, p=p, kinds=("oom", "xla"))
    with use(plan_k):
        faulted_k = analysis(hl, cas_register(),
                             deadline=Deadline(deadline_s))
    verify("knossos", clean_k, faulted_k)
    row["injected"] += len(plan_k.injected)

    # --- parallel batch path (multi-device seam, ISSUE 3 satellite) ----
    # the guarded `parallel.batch` dispatch has no host fallback: a
    # transient fault must be retried away (same verdicts), and an
    # exhausted retry budget must surface as the attributable
    # FaultInjected — never a silent wrong answer
    from jepsen_tpu.history.soa import pack_txns
    from jepsen_tpu.parallel.batch import check_batch
    from jepsen_tpu.resilience import FaultInjected

    ps = [pack_txns(synth.la_history(n_txns=30, seed=seed * 10 + i),
                    "list-append") for i in range(3)]
    clean_b = check_batch(ps)
    plan_b = FaultPlan(seed=seed + 3, p=p, kinds=("oom", "xla"))
    try:
        faulted_b = check_batch(ps, plan=plan_b, policy=policy,
                                deadline=Deadline(deadline_s))
        assert faulted_b == clean_b, \
            "parallel.batch verdicts changed under faults"
    except FaultInjected:
        row["exhausted"] = row.get("exhausted", 0) + 1
    row["injected"] += len(plan_b.injected)

    # --- interpreter client-side chaos (ISSUE 4 satellite) -------------
    # the "interpreter" fault site must be strictly opt-in (named in
    # sites), inject stalls (latency) and info outcomes (crash kinds)
    # without ever losing the history, and fire deterministically per
    # (seed, worker op stream) — pinned with a single-worker run pair
    from jepsen_tpu import core as jcore
    from jepsen_tpu.generator import core as g
    from jepsen_tpu.generator import interpreter
    from jepsen_tpu.workloads.mem import MemClient

    import random as _random

    def interp_run(concurrency: int):
        plan_i = FaultPlan(seed=seed + 4, p=0.3,
                           kinds=("stall", "oom"), stall_s=0.001,
                           sites=("interpreter",))
        test = jcore.noop_test(
            name="interp-chaos", concurrency=concurrency,
            client=MemClient(),
            generator=g.clients(g.limit(
                30, synth.la_generator(
                    n_keys=3, rng=_random.Random(seed + 4)))),
            faults=plan_i)
        return plan_i, interpreter.run(test)

    p1, h1 = interp_run(1)
    p2, h2 = interp_run(1)
    assert p1.injected == p2.injected, \
        "interpreter injections not deterministic (single worker)"

    def shape(h):  # op times are wall-clock; compare everything else
        return [(op.type, op.process, op.f, op.value, op.error)
                for op in h]

    assert shape(h1) == shape(h2), \
        "interpreter chaos history not deterministic (single worker)"
    p3, h3 = interp_run(3)
    assert len(h3) > 0, "interpreter chaos lost the whole history"
    crashed = [op for op in h3
               if op.type == "info"
               and str(op.error or "").startswith("fault-injected")]
    row["injected"] += len(p1.injected) + len(p3.injected)
    row["client-infos"] = row.get("client-infos", 0) + len(crashed)

    # --- invariants workloads under sim nemeses (ISSUE 10 satellite) ---
    # bank / long-fork campaign cells under the clock-skew or
    # membership nemesis, with checker-seam chaos on top: every run
    # must terminate with an attributable verdict — and a skewed bank
    # run that goes invalid must be invalid for the right reason
    import tempfile as _tf

    from jepsen_tpu.campaign.plan import RunSpec, build_test

    nem = {"faults": ["skew"] if seed % 2 else ["membership"],
           "interval": 0.08}
    for wlname in ("bank", "long-fork"):
        rs = RunSpec(
            run_id=f"fuzz-{wlname}-s{seed}", campaign="fuzz-inv",
            workload=wlname, seed=seed,
            opts={"time-limit": 0.4, "concurrency": 3, "nemesis": nem})
        t = build_test(rs, _tf.mkdtemp(prefix="fuzz-inv-"))
        t["faults"] = {"seed": seed + 6, "p": p, "kinds": "oom|xla"}
        done = jcore.run(t)
        res = done.get("results") or {}
        assert "valid?" in res, f"{wlname}+{nem['faults'][0]}: no verdict"
        if res["valid?"] == "unknown":
            assert res.get("error"), \
                f"{wlname}: unattributed unknown ({res})"
            row["unknown"] += 1
        elif res["valid?"] is False:
            assert res.get("anomaly-types"), \
                f"{wlname}: invalid with no anomaly attribution ({res})"
        if res.get("degraded"):
            row["degraded"] += 1
        row["nemesis-runs"] = row.get("nemesis-runs", 0) + 1

    # --- cross-host fault-window ddmin (ISSUE 11 satellite) ------------
    # a merged multi-host nemesis schedule: two hosts ran the same
    # window position, only host A's instance makes the (fault-
    # sensitive) checker fail.  The ddmin must drop host B's window,
    # keep host A's as reproduction-necessary, attribute it by host,
    # and produce the identical digest at any probe worker count —
    # every probe verdict attributable, never a crash
    from jepsen_tpu import minimize
    from jepsen_tpu.checkers.api import FnChecker

    import tempfile as _tf2

    xtest = {"name": "cross-host-ddmin",
             "store-dir": _tf2.mkdtemp(prefix="fuzz-xhost-"),
             "history": synth.cross_host_window_history(
                 "hostA", "hostB", bad_sum_delta=3 + seed % 3)}
    host_sensitive = synth.cross_host_sensitive_check("hostA")
    xres = {}
    for workers in (1, 3):
        s = minimize.shrink(
            dict(xtest), checker=FnChecker(host_sensitive, "x-host"),
            workers=workers, force=True)
        assert s.get("valid?") is False, \
            f"cross-host ddmin lost the verdict ({s})"
        fw = s.get("fault-windows") or []
        assert [ (w.get("host"), w.get("kept")) for w in fw] == \
            [("hostA", "necessary")], \
            f"cross-host witness must keep exactly host A's window " \
            f"as reproduction-necessary, got {fw}"
        xres[workers] = (s["digest"],
                         [w.get("digest") for w in fw])
    assert xres[1] == xres[3], \
        f"cross-host witness not digest-stable across worker counts " \
        f"({xres})"
    row["cross-host-windows"] = 1

    # --- flight recorder under chaos (ISSUE 5 satellite) ---------------
    # every faulted / deadline-killed TELEMETRIC run must still leave a
    # well-formed (tail-truncated at worst) events.jsonl: parseable,
    # replayable, with a fault event for every injection the plan made
    import tempfile

    from jepsen_tpu import store
    from jepsen_tpu.telemetry import stream as tel_stream
    from jepsen_tpu.workloads.append import AppendChecker

    base = tempfile.mkdtemp(prefix="fuzz-recorder-")
    plan_r = FaultPlan(seed=seed + 5, p=0.4,
                       kinds=("oom", "xla", "stall"), stall_s=0.001)
    test = jcore.noop_test(
        name="recorder-chaos", concurrency=2, client=MemClient(),
        generator=g.clients(g.limit(
            24, synth.la_generator(n_keys=3,
                                   rng=_random.Random(seed + 5)))),
        checker=AppendChecker(), telemetry=True, faults=plan_r)
    test["store-dir"] = base
    if seed % 3 == 0:
        # some rounds are deadline-killed mid-analysis on purpose
        test["checker-time-limit"] = 0.0
    done = jcore.run(test)
    assert "valid?" in (done.get("results") or {}), \
        "recorder-chaos run lost its verdict"
    d = store.test_dir(done)
    evs = tel_stream.read_events(os.path.join(d, "events.jsonl"))
    assert evs and evs[0]["ev"] == "start", "events.jsonl unreadable"
    st = tel_stream.replay(evs)
    assert st["ended"], "completed run must close its event stream"
    assert st["faults"] == len(plan_r.injected), \
        f"streamed {st['faults']} fault events, plan injected " \
        f"{len(plan_r.injected)}"
    if seed % 3 == 0:
        assert st["deadlines"] >= 1 or \
            done["results"].get("error") is None, \
            "deadline-killed run streamed no deadline event"
    tel_stream.render_tail(evs)  # renders without crashing
    row["events"] = st["events"]
    return row


def autopilot_chaos_round(seed: int, p: float = 0.35) -> dict:
    """Chaos on the autopilot's own decision loop (ISSUE 17): a
    seeded FaultPlan on every ``autopilot.*`` seam — enqueue, gate,
    shrink, scale — while a synthetic fleet drains generations.  The
    invariant: the loop never wedges and never loses attribution —
    every generation closes, every verdict carries its to-gen and an
    rc in {0, 1, 2}, and an independent journal replay reaches the
    identical digest.  `scripts/soak_autopilot.py` imports this as its
    chaos round; ``--autopilot`` runs it standalone."""
    import tempfile as _tf
    import threading as _th

    from jepsen_tpu.fleet import Autopilot, AutopilotJournal, \
        autopilot_path
    from jepsen_tpu.resilience import FaultPlan, use

    base = _tf.mkdtemp(prefix="fuzz-autopilot-")
    spec = {"name": "fuzz-ap", "workloads": ["bank"],
            "seeds": [0, 1, 2], "opts": {"time-limit": 0.2}}
    ap = Autopilot(spec, base, generations=2, spans=("workload",),
                   poll_s=0.02)

    def drain():
        while not ap.stop.is_set():
            code, out = ap.coordinator.claim({"worker": "syn"})
            sp = out.get("spec") if code == 200 else None
            if not sp:
                time.sleep(0.01)
                continue
            key = (f'{sp["workload_label"]}|{sp["fault_label"]}'
                   f'|s{sp["seed"]}')
            ap.coordinator.complete({
                "worker": "syn", "run": sp["run_id"],
                "record": {"run": sp["run_id"], "key": key,
                           "workload": sp["workload_label"],
                           "fault": sp["fault_label"],
                           "seed": sp["seed"], "valid?": True,
                           "spans": {"workload": 0.1}}})

    t = _th.Thread(target=drain, daemon=True)
    t.start()
    plan = FaultPlan(seed=seed, p=p, kinds=("oom", "stall"),
                     stall_s=0.005,
                     sites="autopilot.enqueue|autopilot.gate"
                           "|autopilot.shrink|autopilot.scale")
    try:
        with use(plan):
            out = ap.run()
    finally:
        ap.stop.set()
        t.join(timeout=5)
        ap.coordinator.close()
    assert out["generations"] == 2, \
        f"autopilot wedged under seam chaos ({out})"
    for label in ap.journal.closed_labels():
        for v in ap.journal.gens[label]["verdicts"]:
            assert v.get("to-gen") == label and \
                v.get("rc") in (0, 1, 2), \
                f"unattributable verdict under chaos: {v}"
    replay = AutopilotJournal(
        autopilot_path("fuzz-ap", base)).digest()
    assert replay == ap.journal.digest(), \
        "journal replay diverged under seam chaos"
    return {"seed": seed, "injected": len(plan.injected),
            "generations": out["generations"]}


def alerts_chaos_round(seed: int, p: float = 0.4) -> dict:
    """Chaos on the watchtower's seams (ISSUE 20): a seeded FaultPlan
    on ``alerts.evaluate`` + ``alerts.notify`` while the autopilot
    drains generations with a rule pack guaranteed to fire (threshold
    on the generations-closed gauge) and two sinks — a file sink and a
    dead webhook.  The invariants: a failed evaluation tick or dead
    webhook never wedges the loop (every generation still closes); an
    independent journal replay reaches the identical alert-state
    digest; notify intents are at-most-once per (rule, seq) so a
    replayed engine re-fed the same breaching signals sends NOTHING
    new; the file sink never holds more deliveries than journaled
    intents."""
    import json as _json
    import tempfile as _tf
    import threading as _th

    from jepsen_tpu.fleet import Autopilot
    from jepsen_tpu.resilience import FaultPlan, use
    from jepsen_tpu.telemetry import alerts as alerts_mod

    base = _tf.mkdtemp(prefix="fuzz-alerts-")
    notif = os.path.join(base, "notifications.jsonl")
    rules = alerts_mod.load_rules([
        {"name": "gen-closed", "kind": "threshold", "severity": "info",
         "signal": "gauge:fleet-autopilot-generations",
         "op": ">=", "value": 1.0, "for": 0.0}])
    sinks = [alerts_mod.FileSink(notif),
             # nothing listens on the discard port: every webhook send
             # dies in connect(), exercising the failure audit path
             alerts_mod.WebhookSink("http://127.0.0.1:9/dead",
                                    timeout=0.2)]
    spec = {"name": "fuzz-alerts-ap", "workloads": ["bank"],
            "seeds": [0, 1, 2], "opts": {"time-limit": 0.2}}
    ap = Autopilot(spec, base, generations=2, spans=("workload",),
                   poll_s=0.02, alert_rules=rules, alert_sinks=sinks)

    def drain():
        while not ap.stop.is_set():
            code, out = ap.coordinator.claim({"worker": "syn"})
            sp = out.get("spec") if code == 200 else None
            if not sp:
                time.sleep(0.01)
                continue
            key = (f'{sp["workload_label"]}|{sp["fault_label"]}'
                   f'|s{sp["seed"]}')
            ap.coordinator.complete({
                "worker": "syn", "run": sp["run_id"],
                "record": {"run": sp["run_id"], "key": key,
                           "workload": sp["workload_label"],
                           "fault": sp["fault_label"],
                           "seed": sp["seed"], "valid?": True,
                           "spans": {"workload": 0.1}}})

    t = _th.Thread(target=drain, daemon=True)
    t.start()
    plan = FaultPlan(seed=seed, p=p, kinds=("oom", "stall"),
                     stall_s=0.005,
                     sites="alerts.evaluate|alerts.notify")
    try:
        with use(plan):
            out = ap.run()
    finally:
        ap.stop.set()
        t.join(timeout=5)
        ap.coordinator.close()
    assert out["generations"] == 2,         f"autopilot wedged under alert-seam chaos ({out})"

    jpath = alerts_mod.alerts_path(base)
    replay = alerts_mod.AlertJournal(jpath)
    assert replay.digest() == ap.alerts.journal.digest(),         "alert journal replay diverged under seam chaos"

    # at-most-once: each (rule, seq) transition journals its notify
    # intent at most once, ever
    intents: dict = {}
    with open(jpath, "rb") as f:
        for line in f:
            try:
                ev = _json.loads(line)
            except ValueError:
                continue
            if ev.get("ev") == "notify":
                k = (ev["rule"], ev["seq"])
                intents[k] = intents.get(k, 0) + 1
    assert intents and all(n == 1 for n in intents.values()),         f"duplicate notify intents under chaos: {intents}"

    # the file sink can hold FEWER deliveries than intents (a faulted
    # send is dropped, never retried past the policy) but never more
    delivered = 0
    if os.path.exists(notif):
        with open(notif) as f:
            delivered = sum(1 for ln in f if ln.strip())
    assert delivered <= len(intents),         f"sink over-delivered: {delivered} > {len(intents)} intents"

    # a replayed engine re-fed the same breaching signal must send
    # nothing new: the journaled seq already covers the transition
    class _Counting:
        n = 0

        def send(self, payload):
            _Counting.n += 1

    eng2 = alerts_mod.AlertEngine(base, rules=rules,
                                  sinks=[_Counting()])
    eng2.evaluate(signals={"gauge:fleet-autopilot-generations": 2.0})
    assert _Counting.n == 0,         "engine double-fired after journal replay"
    assert eng2.journal.digest() == replay.digest(),         "steady-state re-evaluation moved the digest"
    return {"seed": seed, "injected": len(plan.injected),
            "intents": len(intents), "delivered": delivered,
            "webhook-failures": replay.sends_failed}


def compilecache_chaos_round(seed: int, p: float = 0.5) -> dict:
    """Chaos on the AOT compile-cache seams (ISSUE 18): a seeded
    FaultPlan naming ``compilecache.load`` / ``.compile`` / ``.warm``
    (the seams are strictly opt-in — a plan must name them) while the
    elle device checks and the bucket warmer run against a fresh
    store.  The invariant: every faulted call falls through to plain
    jit with the IDENTICAL verdict (``compilecache_degraded``-stamped
    at worst), the warmer records failed rungs instead of wedging, and
    the on-disk store is never corrupted — every entry that survives
    still verifies, and a fault-free rerun serves the same store with
    zero fall-throughs."""
    import shutil as _sh
    import tempfile as _tf

    from jepsen_tpu import compilecache
    from jepsen_tpu.checkers.elle import list_append, rw_register
    from jepsen_tpu.compilecache import store as cc_store
    from jepsen_tpu.compilecache import warm as cc_warm
    from jepsen_tpu.resilience import FaultPlan, use
    from jepsen_tpu.workloads import synth

    h = synth.la_history(n_txns=50, seed=seed)
    if seed % 2:
        synth.inject_wr_cycle(h)
    hrw = synth.rw_history(n_txns=40, seed=seed)

    orig_min = rw_register.FUSED_MIN_TXNS
    rw_register.FUSED_MIN_TXNS = 1  # force the fused device path
    # reference verdicts: fault-free, cache pinned memory-only
    compilecache.set_cache_dir(None)
    compilecache.clear()
    try:
        clean = list_append.check(h)
        clean_rw = rw_register.check(hrw)

        # chaos run against a FRESH empty store per round — a prior
        # round's surviving disk entry would let .load succeed before
        # the faulted .compile seam ever fires, hiding it
        d = _tf.mkdtemp(prefix="fuzz-cc-")
        row = {"seed": seed, "injected": 0, "fallthroughs": 0,
               "entries": 0}
        try:
            compilecache.set_cache_dir(d)
            compilecache.clear()
            compilecache.reset_stats()
            plan = FaultPlan(
                seed=seed, p=max(p, 0.4),
                kinds=("oom", "xla", "stall"), stall_s=0.001,
                sites="compilecache.load|compilecache.compile"
                      "|compilecache.warm")
            with use(plan):
                recs = cc_warm.warm_ladder(sizes=(64,), max_k=64)
                assert recs, "warm ladder returned no records"
                faulted = list_append.check(h)
                faulted_rw = rw_register.check(hrw)
            assert faulted["valid?"] == clean["valid?"], \
                f"list-append verdict changed under cache chaos " \
                f"({clean['valid?']} -> {faulted['valid?']})"
            assert faulted_rw["valid?"] == clean_rw["valid?"], \
                f"rw-register verdict changed under cache chaos " \
                f"({clean_rw['valid?']} -> {faulted_rw['valid?']})"
            row["injected"] = len(plan.injected)
            row["fallthroughs"] = compilecache.stats()["fallthroughs"]
            # never corrupt: every surviving entry still verifies
            ents = cc_store.entries(d)
            row["entries"] = len(ents)
            for e in ents:
                with open(os.path.join(d, e["name"]), "rb") as f:
                    assert cc_store.unpack_entry(f.read()) is not None, \
                        f"corrupt entry survived chaos: {e['name']}"
            # and a fault-free pass over the SAME store serves it
            # cleanly — whatever the faulted pass left behind must be
            # usable, not wedged
            compilecache.clear()
            compilecache.reset_stats()
            again = list_append.check(h)
            assert again["valid?"] == clean["valid?"], \
                "verdict changed on the post-chaos store"
            assert compilecache.stats()["fallthroughs"] == 0, \
                "fault-free rerun fell through on the post-chaos store"
        finally:
            compilecache.set_cache_dir(None)
            compilecache.clear()
            _sh.rmtree(d, ignore_errors=True)
    finally:
        rw_register.FUSED_MIN_TXNS = orig_min
    return row


def queue_chaos_round(seed: int, p: float = 0.3,
                      deadline_s: float = 60.0) -> dict:
    """Chaos on the queue family's two seams at once (ISSUE 19).

    Client seam: a seeded FaultPlan naming the adversarial ``client.*``
    sites drives a full kafka run through `core.run` — the broker
    applies duplicate-request, reorder, zombie-resend and torn-send
    damage, and the run's verdict must ATTRIBUTE what was applied
    (every applied duplicate-shape injection ends in a ``duplicate``
    anomaly; the run never crashes or hangs).

    Checker seam: the SAME chaos history is then re-checked with a
    plan naming ``queue.check`` — the device pass must absorb the
    faults via host fallback with the IDENTICAL verdict (full dict
    equality against both the packed host path and the legacy scan
    twin), or surface an attributed deadline unknown.  A mem-store
    total-queue leg runs the same bar over the fifo checker."""
    import random as _random

    from jepsen_tpu import core as jcore
    from jepsen_tpu import telemetry
    from jepsen_tpu.checkers import api as checker_api
    from jepsen_tpu.checkers.queue import fifo as q_fifo
    from jepsen_tpu.checkers.queue import kafka as q_kafka
    from jepsen_tpu.generator import core as g
    from jepsen_tpu.history.ops import history as mk_history
    from jepsen_tpu.resilience import Deadline, FaultPlan, RetryPolicy
    from jepsen_tpu.workloads import kafka as wk
    from jepsen_tpu.workloads.mem import MemClient, MemStore

    row = {"seed": seed, "client_injected": 0, "checker_injected": 0,
           "applied": {}, "anomalies": [], "degraded": 0, "unknown": 0}
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.0, seed=seed)
    reg = telemetry.registry()
    shapes = ("dup-send", "reorder-send", "zombie-resend", "torn-send")

    def _adv_counts():
        return {s: reg.counter("queue-adversarial-injections",
                               shape=s).value for s in shapes}

    # --- kafka leg: adversarial client under a full harness run --------
    wl = wk.workload(rng=_random.Random(seed), subscribe_frac=0.3,
                     txn_frac=0.4, crash_frac=0.05)
    t = {
        "name": f"queue-chaos-{seed}", "nodes": ["n1", "n2"],
        "client": wk.KafkaClient(rng=_random.Random(seed + 1)),
        "concurrency": 4, "store-dir": None,
        "kafka-key-count": wl["kafka-key-count"],
        "workload-kind": "kafka",
        "generator": g.clients(g.limit(120, wl["generator"])),
        "final-generator": wl["final-generator"],
        "checker": wl["checker"],
        "faults": {"seed": seed, "p": max(p, 0.25), "kinds": ["oom"],
                   "sites": "|".join(sorted(wk.ADVERSARY_SITES))},
    }
    before = _adv_counts()
    done = jcore.run(t)
    applied = {s: int(v - before[s]) for s, v in _adv_counts().items()
               if v > before[s]}
    row["applied"] = applied
    plan = done.get("faults-plan")
    row["client_injected"] = len(plan.injected) if plan is not None else 0
    res = done["results"]
    assert "valid?" in res, f"kafka chaos run has no verdict ({res})"
    row["anomalies"] = sorted(res.get("anomaly-types") or [])
    if applied.get("dup-send") or applied.get("zombie-resend"):
        # duplicate applications are fully observable (the final drain
        # assigns every key and polls to quiet), so the verdict MUST
        # attribute them — a silent pass here is a checker bug
        assert res["valid?"] is False and "duplicate" in row["anomalies"], \
            f"applied {applied} but verdict did not attribute a " \
            f"duplicate ({res.get('anomaly-types')})"

    # --- checker seam: device==twin on the SAME chaos history ----------
    hist = done["history"]
    twin = wk.KafkaChecker().check(None, hist, {})
    host = q_kafka.check(hist, use_device=False)
    assert host == twin, "packed host path diverged from the scan twin"
    chaos = FaultPlan(seed=seed + 2, p=max(p, 0.6),
                      kinds=("oom", "xla", "stall"), stall_s=0.005,
                      sites="queue.check")
    dev = q_kafka.check(hist, plan=chaos, policy=policy,
                        deadline=Deadline(deadline_s))
    row["checker_injected"] += len(chaos.injected)
    if dev.pop("degraded", None):
        row["degraded"] += 1
    if dev.get("valid?") == "unknown" and dev.get("error"):
        row["unknown"] += 1
    else:
        assert dev == twin, \
            "kafka device verdict changed under queue.check chaos"

    # --- total-queue leg: mem-store adversarial knobs + checker seam ---
    rng = _random.Random(seed + 3)
    mc = MemClient(MemStore(), rng=_random.Random(seed + 4),
                   dup_enqueue_p=0.15, lose_enqueue_p=0.1,
                   reorder_dequeue_p=0.25).open(None, "n1")
    raw, idx, counter = [], 0, 0
    for i in range(100):
        if rng.random() < 0.45:
            op = {"f": "enqueue", "value": counter}
            counter += 1
        else:
            op = {"f": "dequeue", "value": None}
        op = dict(op, process=i % 3, index=idx, type="invoke")
        idx += 1
        raw.append(op)
        out = dict(mc.invoke(None, dict(op)), index=idx)
        idx += 1
        raw.append(out)
    for i in range(counter):  # drain
        op = {"f": "dequeue", "value": None, "process": 3,
              "index": idx, "type": "invoke"}
        idx += 1
        raw.append(op)
        out = dict(mc.invoke(None, dict(op)), index=idx)
        idx += 1
        raw.append(out)
        if out["type"] == "fail":
            break
    qh = mk_history(raw, reindex=False)
    tq_twin = checker_api.TotalQueueChecker().check(None, qh, {})
    tq_host = q_fifo.check(qh, fifo=True, use_device=False)
    for k, v in tq_twin.items():
        assert tq_host[k] == v, \
            f"total-queue host path diverged from twin on {k!r}"
    chaos_q = FaultPlan(seed=seed + 5, p=max(p, 0.6),
                        kinds=("oom", "xla", "stall"), stall_s=0.005,
                        sites="queue.check")
    tq_dev = q_fifo.check(qh, fifo=True, plan=chaos_q, policy=policy,
                          deadline=Deadline(deadline_s))
    row["checker_injected"] += len(chaos_q.injected)
    if tq_dev.pop("degraded", None):
        row["degraded"] += 1
    if tq_dev.get("valid?") == "unknown" and tq_dev.get("error"):
        row["unknown"] += 1
    else:
        assert tq_dev == tq_host, \
            "total-queue device verdict changed under queue.check chaos"
    return row


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--p", type=float, default=0.2,
                    help="per-call fault probability")
    ap.add_argument("--deadline", type=float, default=60.0,
                    help="per-check deadline seconds")
    ap.add_argument("--autopilot", action="store_true",
                    help="run the autopilot seam-chaos rounds instead")
    ap.add_argument("--compilecache", action="store_true",
                    help="run the AOT compile-cache seam-chaos rounds "
                         "instead (load/compile/warm fall-through)")
    ap.add_argument("--queue", action="store_true",
                    help="run the queue-family chaos rounds instead "
                         "(adversarial client sites + queue.check seam)")
    ap.add_argument("--alerts", action="store_true",
                    help="run the watchtower seam-chaos rounds instead "
                         "(alerts.evaluate/alerts.notify: no wedge, no "
                         "double-fire after replay)")
    args = ap.parse_args()

    if args.alerts:
        t0 = time.time()
        inj = intents = delivered = wf = 0
        for seed in range(args.seed0, args.seed0 + args.rounds):
            row = alerts_chaos_round(seed, max(args.p, 0.3))
            inj += row["injected"]
            intents += row["intents"]
            delivered += row["delivered"]
            wf += row["webhook-failures"]
            print(f"seed {seed}: injected={row['injected']} "
                  f"intents={row['intents']} "
                  f"delivered={row['delivered']} "
                  f"webhook-failures={row['webhook-failures']}")
        print(f"\n{args.rounds} alert rounds in {time.time() - t0:.1f}s: "
              f"{inj} seam faults injected, {intents} notify intents "
              f"({delivered} delivered, {wf} webhook failures audited) "
              "— no wedge, no double-fire, replay digest identical")
        return 0

    if args.queue:
        t0 = time.time()
        inj = cinj = 0
        shape_totals: dict = {}
        for seed in range(args.seed0, args.seed0 + args.rounds):
            row = queue_chaos_round(seed, max(args.p, 0.25),
                                    args.deadline)
            inj += row["client_injected"]
            cinj += row["checker_injected"]
            for s, n in row["applied"].items():
                shape_totals[s] = shape_totals.get(s, 0) + n
            print(f"seed {seed}: client-injected={row['client_injected']} "
                  f"applied={row['applied']} "
                  f"checker-injected={row['checker_injected']} "
                  f"anomalies={row['anomalies']} "
                  f"degraded={row['degraded']} unknown={row['unknown']}")
        assert shape_totals, \
            "no adversarial shape was ever applied — raise --p or --rounds"
        print(f"\n{args.rounds} queue rounds in {time.time() - t0:.1f}s: "
              f"{inj} client-site faults, {cinj} checker-seam faults, "
              f"shapes applied {shape_totals} — every round terminated "
              "with an attributable verdict, device == twin throughout")
        return 0

    if args.compilecache:
        t0 = time.time()
        inj = ft = 0
        for seed in range(args.seed0, args.seed0 + args.rounds):
            row = compilecache_chaos_round(seed, max(args.p, 0.4))
            inj += row["injected"]
            ft += row["fallthroughs"]
            print(f"seed {seed}: injected={row['injected']} "
                  f"fallthroughs={row['fallthroughs']} "
                  f"entries={row['entries']}")
        print(f"\n{args.rounds} compile-cache rounds in "
              f"{time.time() - t0:.1f}s: {inj} seam faults injected, "
              f"{ft} fall-throughs to plain jit — identical verdicts, "
              "no wedge, no corrupt entries")
        return 0

    if args.autopilot:
        t0 = time.time()
        inj = 0
        for seed in range(args.seed0, args.seed0 + args.rounds):
            row = autopilot_chaos_round(seed, max(args.p, 0.3))
            inj += row["injected"]
            print(f"seed {seed}: injected={row['injected']} "
                  f"generations={row['generations']}")
        print(f"\n{args.rounds} autopilot rounds in "
              f"{time.time() - t0:.1f}s: {inj} seam faults injected, "
              "every generation closed with attributable verdicts")
        return 0

    t0 = time.time()
    totals = {"injected": 0, "degraded": 0, "unknown": 0}
    for seed in range(args.seed0, args.seed0 + args.rounds):
        row = run_one(seed, args.p, args.deadline)
        for k in totals:
            totals[k] += row[k]
        print(f"seed {seed}: injected={row['injected']} "
              f"degraded={row['degraded']} unknown={row['unknown']}")
    print(f"\n{args.rounds} rounds in {time.time() - t0:.1f}s: "
          f"{totals['injected']} faults injected, "
          f"{totals['degraded']} host fallbacks, "
          f"{totals['unknown']} attributed unknowns — every run "
          "terminated with a verdict")
    return 0


if __name__ == "__main__":
    sys.exit(main())
