#!/usr/bin/env python
"""obs-gate smoke (ISSUE 6 satellite): end-to-end regression gating.

Runs a real telemetric mini-campaign into a throwaway store, then
synthesizes two more generations from its ledger records — one
unchanged (span durations jittered +-2%) and one carrying an injected
+50% p95 regression — ingests everything into the sqlite warehouse
(`cli obs ingest`), and drives `cli obs gate` through its real argv
surface, asserting the CI contract:

    unchanged pair   -> exit 0 (PASS)
    injected +50%    -> exit 1 (REGRESSION)
    unknown span     -> exit 2 (cannot evaluate)

Each gate decision is checked twice — BEFORE the warehouse exists
(jsonl scan fallback) and AFTER `obs ingest` (SQL fast path) — and the
two backends must agree.  Exercised by tier-1 via
tests/test_warehouse.py's subprocess smoke.

Usage:
    JAX_PLATFORMS=cpu python scripts/gate_bench.py
    python scripts/gate_bench.py --runs 6 --keep-store
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu.utils.backend import force_cpu_backend  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu" \
        or os.environ.get("JT_FORCE_CPU"):
    force_cpu_backend()


def synthesize_generations(path: str, scale: float, rng) -> int:
    """Append two synthetic generations to a campaign ledger, anchored
    at the REAL runs' per-span median (the first real generation
    carries jit-warmup outliers that would drown a rank test at small
    n): gen ``same`` draws median * U(0.9, 1.1) per run, and gen
    ``regress`` is the SAME jittered samples * ``scale`` — a pure
    injected regression, nothing else changed."""
    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    first_gen = records[0].get("gen")
    base = [r for r in records
            if r.get("gen") == first_gen and r.get("spans")]
    med = {}
    for name in {n for r in base for n in r["spans"]}:
        vals = sorted(r["spans"][name] for r in base if name in r["spans"])
        med[name] = vals[len(vals) // 2]
    jitter = [{name: m * rng.uniform(0.9, 1.1)
               for name, m in med.items()} for _ in base]
    with open(path, "a") as f:
        for gen, mult in (("same", 1.0), ("regress", scale)):
            for rec, spans in zip(base, jitter):
                clone = dict(rec)
                clone["gen"] = gen
                clone["run"] = f"{rec.get('run')}@{gen}"
                clone["spans"] = {name: round(v * mult, 9)
                                  for name, v in spans.items()}
                f.write(json.dumps(clone) + "\n")
    return len(base)


def gate(disp, base: str, campaign: str, span: str, pair=None) -> int:
    from jepsen_tpu import cli

    argv = ["--store-dir", base, "obs", "gate",
            "--campaign", campaign, "--span", span]
    if pair:
        argv += ["--from-gen", pair[0], "--to-gen", pair[1]]
    return cli.run(disp, argv)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--runs", type=int, default=4,
                    help="campaign cells (= samples per generation); "
                         "the Mann-Whitney gate needs >= 3")
    ap.add_argument("--keep-store", action="store_true",
                    help="leave the throwaway store on disk")
    args = ap.parse_args()

    from jepsen_tpu import campaign, cli

    base = tempfile.mkdtemp(prefix="jepsen-gate-smoke-")
    t0 = time.time()
    try:
        spec = {"name": "gate-smoke", "workloads": ["set"],
                "seeds": list(range(args.runs)),
                "opts": {"time-limit": 0.2, "telemetry": True,
                         "concurrency": 2}}
        summary = campaign.run_campaign(spec, base, workers=2)
        assert summary["executed"] == args.runs, summary
        path = summary["index"]

        # pick a real checker span from the ledger to gate on
        with open(path) as f:
            recs = [json.loads(line) for line in f if line.strip()]
        spans = sorted({name for r in recs
                        for name in (r.get("spans") or ())
                        if name.startswith("check:")})
        span = spans[0] if spans else "workload"
        real_gen = recs[0]["gen"]

        n_synth = synthesize_generations(path, 1.5, random.Random(0))
        assert n_synth == args.runs
        print(f"ledger: {args.runs} real runs (gen {real_gen}) "
              f"+ {n_synth} unchanged + {n_synth} regressed (x1.5), "
              f"gating span {span!r}")

        disp = cli.single_test_cmd(lambda o: {})
        results = {}
        for label in ("jsonl-scan", "warehouse"):
            rc_pass = gate(disp, base, "gate-smoke", span,
                           (real_gen, "same"))
            rc_reg = gate(disp, base, "gate-smoke", span,
                          ("same", "regress"))
            rc_default = gate(disp, base, "gate-smoke", span)
            rc_unknown = gate(disp, base, "gate-smoke", "no-such-span")
            results[label] = (rc_pass, rc_reg, rc_default, rc_unknown)
            if label == "jsonl-scan":  # second lap: the SQL fast path
                assert cli.run(disp, ["--store-dir", base,
                                      "obs", "ingest"]) == 0
        assert results["jsonl-scan"] == results["warehouse"], \
            f"backends disagree: {results}"
        rc_pass, rc_reg, rc_default, rc_unknown = results["warehouse"]
        assert rc_pass == 0, f"unchanged pair gated rc={rc_pass}, want 0"
        assert rc_reg == 1, f"+50% regression gated rc={rc_reg}, want 1"
        assert rc_default == 1, \
            f"default pair (two latest) gated rc={rc_default}, want 1"
        assert rc_unknown == 2, \
            f"unknown span gated rc={rc_unknown}, want 2"
        print(f"gate smoke OK in {time.time() - t0:.1f}s: pass=0 "
              "regression=1 unknown=2, scan == warehouse")
        return 0
    finally:
        if args.keep_store:
            print(f"store kept at {base}")
        else:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
