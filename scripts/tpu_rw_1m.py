"""Time the fused rw-register device check at config-3 scale (1M txns)
on the real TPU (PROFILE.md §4 had CPU numbers only; tunnel was down).

Usage: python scripts/tpu_rw_1m.py [n_txns]
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from jepsen_tpu.utils.backend import enable_compile_cache


def main():
    n_txns = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    enable_compile_cache()
    print("backend:", jax.default_backend())

    from jepsen_tpu.checkers.elle import device_rw
    from jepsen_tpu.utils import prestage

    t0 = time.perf_counter()
    p = prestage.rw_history(n_txns=n_txns, n_keys=max(64, n_txns // 8))
    print(f"gen {time.perf_counter() - t0:.1f}s; n_txns={p.n_txns}")

    from jepsen_tpu.checkers.elle.device_rw import pad_packed

    t0 = time.perf_counter()
    h = jax.device_put(pad_packed(p))
    jax.block_until_ready(h)
    print(f"pad+stage {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    res = device_rw.check(h)
    print(f"compile+first {time.perf_counter() - t0:.1f}s; "
          f"valid?={res['valid?']} exact={res['exact']}")

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        res = device_rw.check(h)
        best = min(best, time.perf_counter() - t0)
    print(f"steady {best:.2f}s = {n_txns / best:,.0f} txns/s")


if __name__ == "__main__":
    main()
