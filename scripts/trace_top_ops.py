"""Aggregate device-op time from a JAX profiler trace directory.

`profiling.trace` (and bench.py's BENCH_PROFILE_DIR) write a Perfetto /
Chrome-trace JSON under <dir>/plugins/profile/<run>/*.trace.json.gz.
This summarizes where the device time goes without TensorBoard: top HLO
ops by total duration, grouped by fusion/op name, per device pid.

Usage: python scripts/trace_top_ops.py /tmp/jax-trace [top_n]
"""

import gzip
import glob
import json
import os
import sys
from collections import defaultdict


def find_trace(root):
    pats = [os.path.join(root, "plugins", "profile", "*", "*.trace.json.gz"),
            os.path.join(root, "**", "*.trace.json.gz")]
    for pat in pats:
        hits = sorted(glob.glob(pat, recursive=True))
        if hits:
            return hits[-1]  # latest run
    raise SystemExit(f"no *.trace.json.gz under {root}")


def main():
    root = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    path = find_trace(root)
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])

    # map pid -> process name (device rows are "/device:TPU:0" etc.)
    pid_name = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e["pid"]] = e.get("args", {}).get("name", "")

    device_pids = {p for p, n in pid_name.items()
                   if "device:" in n.lower() or "tpu" in n.lower()
                   or "xla" in n.lower()}
    # fall back: any pid with complete ("X") events that isn't python/host
    if not device_pids:
        device_pids = {p for p, n in pid_name.items() if "python" not in
                       n.lower() and "host" not in n.lower()}

    agg = defaultdict(lambda: [0.0, 0])  # name -> [total_us, count]
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        dur = float(e.get("dur", 0))
        name = e.get("name", "?")
        agg[name][0] += dur
        agg[name][1] += 1
        total += dur

    print(f"trace: {path}")
    print(f"device pids: {sorted((p, pid_name.get(p)) for p in device_pids)}")
    print(f"total device-op time: {total/1e6:.3f} s over {len(agg)} "
          f"distinct ops\n")
    print(f"{'total_s':>9} {'%':>5} {'count':>7}  name")
    for name, (us, cnt) in sorted(agg.items(), key=lambda kv: -kv[1][0])[:top_n]:
        print(f"{us/1e6:9.3f} {100*us/max(total,1e-9):5.1f} {cnt:7d}  "
              f"{name[:110]}")


if __name__ == "__main__":
    main()
