"""Differential fuzz: list-append device checker vs host oracle.

Random history parameters x injected anomalies; every definitive verdict
and anomaly set must match exactly (SURVEY.md §4 generative-testing
strategy).  Campaign of 2026-07-30: 300/300 exact matches (after fixing
detect_cycles round growth, found by case 0 of the first run).
Env: FUZZ_N (cases, default 300), FUZZ_SEED.
"""
import sys, random, time
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from jepsen_tpu.utils.backend import force_cpu_backend
force_cpu_backend()
import jax
from jepsen_tpu.checkers.elle import list_append, oracle
from jepsen_tpu.workloads import synth

MODELS_POOL = [["strict-serializable"], ["serializable"],
               ["snapshot-isolation"], ["read-committed"]]
rng = random.Random(int(os.environ.get("FUZZ_SEED", 2024)))
n_fail = 0
t_start = time.time()
N = int(os.environ.get("FUZZ_N", 300))
for case in range(N):
    params = dict(
        n_txns=rng.choice([20, 60, 150, 400, 900]),
        n_keys=rng.choice([1, 2, 5, 16, 64]),
        concurrency=rng.choice([1, 3, 8, 16]),
        fail_prob=rng.choice([0.0, 0.05, 0.2]),
        info_prob=rng.choice([0.0, 0.05, 0.2]),
        multi_append_prob=rng.choice([0.0, 0.2, 0.5]),
        seed=rng.randrange(1 << 30),
    )
    h = synth.la_history(**params)
    inject = rng.choice([None, "g1a", "wr", "rw", "wr+rw", "many"])
    if inject == "g1a":
        synth.inject_g1a(h)
    elif inject == "wr":
        synth.inject_wr_cycle(h)
    elif inject == "rw":
        synth.inject_rw_cycle(h)
    elif inject == "wr+rw":
        synth.inject_wr_cycle(h); synth.inject_rw_cycle(h)
    elif inject == "many":
        for _ in range(4):
            synth.inject_wr_cycle(h); synth.inject_rw_cycle(h)
    models = rng.choice(MODELS_POOL)
    try:
        r_o = oracle.check(h, models)
        r_d = list_append.check(h, models, _force_no_fallback=True)
        if r_o["valid?"] != r_d["valid?"] or \
           set(r_o["anomaly-types"]) != set(r_d["anomaly-types"]):
            n_fail += 1
            print(f"MISMATCH case={case} params={params} inject={inject} "
                  f"models={models}\n  oracle={r_o['valid?']} {sorted(r_o['anomaly-types'])}"
                  f"\n  device={r_d['valid?']} {sorted(r_d['anomaly-types'])}",
                  flush=True)
    except Exception as e:
        n_fail += 1
        print(f"ERROR case={case} params={params} inject={inject}: "
              f"{type(e).__name__}: {e}", flush=True)
    if case % 25 == 24:
        jax.clear_caches()
        print(f"[{case+1}/{N}] {time.time()-t_start:.0f}s "
              f"mismatches={n_fail}", flush=True)
print(f"DONE {N} cases, {n_fail} mismatches, {time.time()-t_start:.0f}s",
      flush=True)
sys.exit(1 if n_fail else 0)
