"""Differential fuzz: list-append device checker vs host oracle.

Random history parameters x injected anomalies; verdicts must match and
anomaly sets must match exactly EXCEPT the budget-limited G-nonadjacent
family, where the device may legitimately find more on large dense
graphs (see the in-loop comment; SURVEY.md §4 generative-testing
strategy).  Campaigns of 2026-07-30: 300/300 + 100/100 (after fixing
detect_cycles round growth, found by case 0 of the first run; the one
seed-999 flag was the tolerated nonadjacent asymmetry).
Env: FUZZ_N (cases, default 300), FUZZ_SEED.
"""
import sys, random, time
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from jepsen_tpu.utils.backend import force_cpu_backend
force_cpu_backend()
import jax
from jepsen_tpu.checkers.elle import list_append, oracle
from jepsen_tpu.checkers.elle.specs import NONADJACENT_FAMILY
from jepsen_tpu.workloads import synth

MODELS_POOL = [["strict-serializable"], ["serializable"],
               ["snapshot-isolation"], ["read-committed"],
               # round 5: session-aware requests — exercises
               # sessions.check_la + the coverage contract on both
               # sides of the differential
               ["causal"], ["PRAM"], ["monotonic-reads"]]


def _valid_nonadjacent_witness(entry):
    """Structural spec check on a device-reported nonadjacent cycle,
    mirroring tests/test_device_la.py: >= 2 rw edges, no two rw edges
    cyclically adjacent, every edge Explainer-justified.  Guards the
    fuzz exemption against a device false-positive regression."""
    cycle = entry.get("cycle") or []
    rels = [e.get("rel") for e in cycle]
    if rels.count("rw") < 2:
        return False
    for i, rel in enumerate(rels):
        if rel == "rw" and rels[(i + 1) % len(rels)] == "rw":
            return False
    return all(e.get("why") for e in cycle)
rng = random.Random(int(os.environ.get("FUZZ_SEED", 2024)))
n_fail = 0
t_start = time.time()
N = int(os.environ.get("FUZZ_N", 300))
for case in range(N):
    params = dict(
        n_txns=rng.choice([20, 60, 150, 400, 900]),
        n_keys=rng.choice([1, 2, 5, 16, 64]),
        concurrency=rng.choice([1, 3, 8, 16]),
        fail_prob=rng.choice([0.0, 0.05, 0.2]),
        info_prob=rng.choice([0.0, 0.05, 0.2]),
        multi_append_prob=rng.choice([0.0, 0.2, 0.5]),
        seed=rng.randrange(1 << 30),
    )
    h = synth.la_history(**params)
    inject = rng.choice([None, "g1a", "wr", "rw", "wr+rw", "many"])
    if inject == "g1a":
        synth.inject_g1a(h)
    elif inject == "wr":
        synth.inject_wr_cycle(h)
    elif inject == "rw":
        synth.inject_rw_cycle(h)
    elif inject == "wr+rw":
        synth.inject_wr_cycle(h); synth.inject_rw_cycle(h)
    elif inject == "many":
        for _ in range(4):
            synth.inject_wr_cycle(h); synth.inject_rw_cycle(h)
    models = rng.choice(MODELS_POOL)
    try:
        r_o = oracle.check(h, models)
        r_d = list_append.check(h, models, _force_no_fallback=True)
        so = set(r_o["anomaly-types"])
        sd = set(r_d["anomaly-types"])
        # One tolerated asymmetry, in one direction, on large graphs
        # only: the nonadjacent family's search is a BUDGETED
        # simple-cycle DFS, and on dense graphs the device's small
        # witness regions can crack what the oracle's whole-SCC DFS
        # gives up on (900-txn case pinned in tests/test_device_la.py).
        # A device MISS, or any disagreement on a small graph where the
        # oracle's budget is authoritative, still fails.
        extra = sd - so
        if params["n_txns"] >= 400 and extra and \
                extra <= NONADJACENT_FAMILY and \
                all(any(_valid_nonadjacent_witness(ent)
                        for ent in r_d["anomalies"].get(name, []))
                    for name in extra):
            so |= sd & NONADJACENT_FAMILY
        if r_o["valid?"] != r_d["valid?"] or so != sd:
            n_fail += 1
            print(f"MISMATCH case={case} params={params} inject={inject} "
                  f"models={models}\n  oracle={r_o['valid?']} {sorted(r_o['anomaly-types'])}"
                  f"\n  device={r_d['valid?']} {sorted(r_d['anomaly-types'])}",
                  flush=True)
    except Exception as e:
        n_fail += 1
        print(f"ERROR case={case} params={params} inject={inject}: "
              f"{type(e).__name__}: {e}", flush=True)
    if case % 25 == 24:
        jax.clear_caches()
        print(f"[{case+1}/{N}] {time.time()-t_start:.0f}s "
              f"mismatches={n_fail}", flush=True)
print(f"DONE {N} cases, {n_fail} mismatches, {time.time()-t_start:.0f}s",
      flush=True)
sys.exit(1 if n_fail else 0)
