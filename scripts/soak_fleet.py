#!/usr/bin/env python
"""Fleet soak: N workers x M-cell campaign under control-plane chaos.

ISSUE 9 acceptance driver.  Starts a real coordinator subprocess
(``fleet serve``) and N real worker subprocesses (``fleet work``) over
HTTP, then turns the framework's own nemeses on its control plane:

- seeded ``JEPSEN_FAULTS`` plans drop (synthetic transients) and stall
  the ``fleet.claim`` / ``fleet.heartbeat`` / ``fleet.complete`` seams
  on BOTH sides (server 503s + client-side injection before send);
- one worker is ``kill -9``'d while it holds a lease — the lease
  lapses and its cell requeues and completes elsewhere;
- the coordinator is ``kill -9``'d mid-campaign and restarted — the
  ledger replays to the identical queue state (digest compared against
  an independent in-process replay of the pre-restart ledger) while
  the surviving workers ride out the ECONNREFUSED window on retries;
- (full mode) a worker is SIGSTOP'd past its lease and SIGCONT'd — the
  zombie's eventual completion must be discarded as a duplicate.

The run FAILS unless every cell lands **exactly one** attributable
verdict record (zero lost, zero duplicated) and the distributed result
set equals a single-process ``run_campaign`` of the same spec on
verdict keys.

Then the **coordinated-chaos round** (ISSUE 11 acceptance): a campaign
whose spec carries a ``"nemesis-schedule"`` (a synchronized
skew+partition window pair per generation) runs distributed over 3
workers under the same control-plane chaos, and must produce — per
generation — the same minimal witness set (same fault-window digests,
host-attributed) as a single-process ``run_campaign`` of the identical
spec + seed, with every verdict attributable and every observed
worker window tick synced to the coordinator's authoritative set.

Then the **federation + live-check round** (ISSUE 13 acceptance): an
append campaign live-streamed into the coordinator's verifier
(``--ingest``) by workers on PRIVATE store bases uploading run dirs
over the artifact endpoint (``--upload``) — no shared filesystem —
under chaos widened to the upload/live seams plus one kill -9 each
side; verdicts must equal the single-process stored-history run, every
run dir must land on the coordinator, and every non-degraded live
session must seal incremental == batch.

Usage::

    python scripts/soak_fleet.py --fast      # tier-1 smoke (the
                                             # acceptance config:
                                             # 12 cells x 3 workers)
    python scripts/soak_fleet.py             # default soak
    python scripts/soak_fleet.py --workers 5 --cells 30 --fault-p 0.2

Exit 0 iff the acceptance holds.
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def get_status(url, timeout=2.0):
    with urllib.request.urlopen(url + "/fleet/status",
                                timeout=timeout) as r:
        return json.loads(r.read().decode())


def scrape_metrics(url, timeout=2.0):
    """The coordinator's prometheus exposition ("" when unreachable) —
    the federated `jepsen_fleet_host_*{host=}` series live here."""
    try:
        with urllib.request.urlopen(url + "/metrics",
                                    timeout=timeout) as r:
            return r.read().decode()
    except Exception:  # noqa: BLE001 — chaos windows 503/refuse
        return ""


def wait_status(url, pred, deadline_s, what):
    """Poll /fleet/status until pred(status) (chaos 503s and restart
    windows are ridden out); returns the matching status."""
    t_end = time.time() + deadline_s
    last = None
    while time.time() < t_end:
        try:
            last = get_status(url)
            if pred(last):
                return last
        except Exception:
            pass
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {what}; last status: "
                       f"{json.dumps(last, indent=1, default=str)}")


def spawn_coordinator(base, spec_path, port, lease, env, extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu", "--store-dir", base,
         "fleet", "serve", spec_path, "--port", str(port),
         "--lease", str(lease), *extra],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


#: the control-plane fault sites client-side chaos targets; the
#: federation round widens this to the upload + live-check seams
CHAOS_SITES = "fleet.claim|fleet.heartbeat|fleet.complete"


def spawn_worker(base, url, name, seed, fault_p, env, extra=(),
                 sites=CHAOS_SITES):
    wenv = dict(env)
    # client-side chaos: drops (transients the retry policy clears) and
    # stalls on the control-plane seams only — the workload itself
    # stays clean so the distributed verdicts equal the single-process
    # reference run
    wenv["JEPSEN_FAULTS"] = (
        f"seed={seed},p={fault_p},kinds=oom|stall,stall_s=0.02,"
        f"sites={sites}")
    return subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu", "--store-dir", base,
         "fleet", "work", "--coordinator", url, "--name", name,
         "--poll", "0.1", *extra],
        env=wenv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


#: the coordinated-chaos spec (ISSUE 11): generation-scoped
#: synchronized windows — skew first (it corrupts sim reads, making
#: cells reliably invalid), then a partition window after the client
#: ops drain (droppable, so ddmin must drop it on every host alike)
def chaos_spec(cells: int) -> dict:
    return {
        "name": "fleetchaos", "workloads": ["bank"],
        "seeds": list(range(cells)),
        "nemesis-schedule": {"faults": ["skew", "partition"],
                             "windows": 2, "interval": 0.02,
                             "duration": 0.2, "seed": 5},
        "opts": {"time-limit": 1.0, "ops": 240, "concurrency": 3,
                 "client-latency": 0.002,
                 "shrink": {"host-oracle": True, "probe-deadline": 20}},
    }


def witness_windows(rec) -> list:
    """(digest, kept) pairs of a record's surviving fault windows —
    host-free, so a fleet cell and its single-process twin compare
    equal iff the SAME schedule windows survived for the same
    reasons."""
    wit = rec.get("witness") or {}
    return sorted((w.get("digest"), w.get("kept"))
                  for w in wit.get("fault-windows") or ())


def coordinated_chaos_round(args, env) -> list:
    """Distributed nemesis-schedule campaign vs its single-process
    twin; returns failure strings (empty = round passed)."""
    import tempfile as _tf

    from jepsen_tpu import campaign
    from jepsen_tpu.campaign import core as ccore
    from jepsen_tpu.campaign.index import Index

    failures = []
    cells = 3
    n_workers = 3
    spec = chaos_spec(cells)
    base = _tf.mkdtemp(prefix="fleet-chaos-")
    spec_path = os.path.join(base, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    port = free_port()
    url = f"http://127.0.0.1:{port}"
    lease = max(args.lease, 4.0)  # shrink runs inside the lease
    coord = spawn_coordinator(base, spec_path, port, lease, env)
    workers = {}
    sightings = []  # (worker, digest, synced)
    try:
        s = wait_status(url, lambda s: s.get("nemesis-schedule"), 60,
                        "chaos coordinator up with a schedule")
        auth = s["nemesis-schedule"]["digest-by-gen"]
        for i in range(n_workers):
            workers[f"cw{i}"] = spawn_worker(
                base, url, f"cw{i}", args.seed * 100 + i, args.fault_p,
                env)

        def record_ticks(st):
            for w, d in (st.get("workers") or {}).items():
                wd = d.get("windows")
                if wd:
                    sightings.append((w, wd.get("digest"),
                                      bool(wd.get("synced"))))
            return st.get("finished")

        wait_status(url, record_ticks, 240, "chaos campaign finished")
    finally:
        for p in list(workers.values()) + [coord]:
            if p.poll() is None:
                p.kill()
    desynced = [s for s in sightings if not s[2]]
    if not sightings:
        failures.append("coordinated chaos: no worker window ticks "
                        "observed on /fleet/status")
    if desynced:
        failures.append(f"coordinated chaos: DESYNCED worker window "
                        f"ticks: {desynced[:5]}")
    idx = Index(ccore.index_path("fleetchaos", base))
    got = idx.latest_by_run()
    bad = [r for r in got.values()
           if r.get("valid?") not in (True, False, "unknown")]
    if bad:
        failures.append(f"coordinated chaos: unattributable verdicts "
                        f"{bad}")
    wrong_install = [
        r["run"] for r in got.values()
        if r.get("windows-digest") != auth.get(str(r.get("seed")))]
    if wrong_install:
        failures.append(
            f"coordinated chaos: cells ran with a window set other "
            f"than the authoritative one: {wrong_install}")
    # the acceptance: same minimal witness set as single-process
    ref_base = _tf.mkdtemp(prefix="fleet-chaos-ref-")
    ref = campaign.run_campaign(spec, ref_base, workers=2)
    ref_by_key = {r["key"]: r for r in ref["rows"]}
    got_by_key = {r["key"]: r for r in got.values()}
    for key in sorted(set(ref_by_key) | set(got_by_key)):
        g, r = got_by_key.get(key, {}), ref_by_key.get(key, {})
        if g.get("valid?") != r.get("valid?"):
            failures.append(
                f"coordinated chaos: verdict mismatch at {key}: "
                f"fleet {g.get('valid?')} vs single-process "
                f"{r.get('valid?')}")
            continue
        if witness_windows(g) != witness_windows(r):
            failures.append(
                f"coordinated chaos: witness fault-window mismatch at "
                f"{key}: fleet {witness_windows(g)} vs single-process "
                f"{witness_windows(r)}")
    if not failures:
        hosts = sorted({w.get("host") for r in got.values()
                        for w in (r.get("witness") or {}).get(
                            "fault-windows") or ()})
        print(f"coordinated chaos OK: synchronized windows across "
              f"{n_workers} workers ({len(sightings)} synced tick "
              f"sightings), witness windows match single-process "
              f"({cells}/{cells} generations; surviving windows "
              f"host-attributed to {hosts})")
        shutil.rmtree(base, ignore_errors=True)
        shutil.rmtree(ref_base, ignore_errors=True)
    else:
        print(f"coordinated chaos round FAILED (store: {base})",
              file=sys.stderr)
    return failures


def federation_round(args, env) -> list:
    """Live verification at fleet scale (ISSUE 13 acceptance): an
    append campaign whose cells stream their interpreters into the
    coordinator's verifier (``--ingest``) while the workers run on
    PRIVATE store bases and upload run dirs over the artifact endpoint
    (``--upload``) — no shared filesystem anywhere — under
    control-plane chaos widened to the upload + live-check seams, plus
    one kill -9 each side.  Accepts iff verdicts equal the
    single-process stored-history run of the same spec, every landed
    run dir is browsable on the coordinator, and every non-degraded
    live session sealed incremental == batch."""
    import tempfile as _tf

    from jepsen_tpu import campaign
    from jepsen_tpu.campaign import core as ccore
    from jepsen_tpu.campaign.index import Index
    from jepsen_tpu.campaign.plan import expand
    from jepsen_tpu.verifier import scan_sessions

    failures = []
    cells, n_workers = 4, 2
    port = free_port()
    url = f"http://127.0.0.1:{port}"
    spec = {
        "name": "fedlive", "workloads": ["append"],
        "seeds": list(range(cells)),
        "opts": {"time-limit": None, "ops": 240, "concurrency": 3,
                 "client-latency": 0.002,
                 # telemetry on: the uploaded run dirs then carry the
                 # trace-stamped telemetry.json the timeline assertion
                 # (ISSUE 14) stitches host-attributed phases from
                 "telemetry": True,
                 # the live stream must ride out the coordinator's
                 # kill -9 + restart window: generous outage budget
                 "live-check": {"url": url, "budget-s": 20.0,
                                "timeout-s": 3.0}},
    }
    cbase = _tf.mkdtemp(prefix="fleet-fed-")
    wbases = {f"fw{i}": _tf.mkdtemp(prefix=f"fleet-fed-w{i}-")
              for i in range(n_workers + 1)}  # +1 replacement
    spec_path = os.path.join(cbase, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    sites = CHAOS_SITES + "|fleet.artifact|verifier.live"
    lease = max(args.lease, 4.0)
    coord = spawn_coordinator(cbase, spec_path, port, lease, env,
                              extra=("--ingest",))
    workers = {}
    killed = []
    try:
        wait_status(url, lambda s: True, 60,
                    "federation coordinator up")
        for i in range(n_workers):
            name = f"fw{i}"
            workers[name] = spawn_worker(
                wbases[name], url, name, args.seed * 77 + i,
                args.fault_p, env, extra=("--upload",), sites=sites)

        # kill -9 one worker while it holds a lease (its private base
        # dies with it; the cell requeues and re-executes elsewhere)
        def holding(s):
            alive = [w for w, p in workers.items() if p.poll() is None]
            for one in s.get("leases") or []:
                if one["worker"] in alive:
                    return one["worker"]
            return None

        s = wait_status(url, holding, 60,
                        "a federation worker holding a lease")
        victim = holding(s)
        workers[victim].send_signal(signal.SIGKILL)
        workers[victim].wait()
        killed.append(victim)
        print(f"federation: killed -9 worker {victim} mid-lease "
              "(its run dirs die with its private base)")
        sub = f"fw{n_workers}"
        workers[sub] = spawn_worker(
            wbases[sub], url, sub, args.seed * 77 + 50, args.fault_p,
            env, extra=("--upload",), sites=sites)

        # kill -9 the coordinator once something landed; uploads and
        # live streams in flight resume against the restarted process
        wait_status(url, lambda s: s["done"] >= 1, 120,
                    "a federation cell done before coordinator kill")
        coord.send_signal(signal.SIGKILL)
        coord.wait()
        print("federation: killed -9 coordinator mid-campaign "
              "(mid-upload partials + live sessions must resume)")
        time.sleep(0.5)
        coord = spawn_coordinator(cbase, spec_path, port, lease, env,
                                  extra=("--ingest",))
        final = wait_status(url, lambda s: s["finished"], 300,
                            "federation campaign finished")
        print(f"federation campaign finished: {final['done']}/"
              f"{final['total']} cells, "
              f"{final['counts']['requeues']} requeues")
        for w, p in workers.items():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.terminate()
    finally:
        for p in list(workers.values()) + [coord]:
            if p.poll() is None:
                p.kill()

    idx = Index(ccore.index_path("fedlive", cbase))
    got = idx.latest_by_run()
    spec_ids = {rs.run_id for rs in expand(spec)}
    if set(got) != spec_ids:
        failures.append(
            f"federation: {len(spec_ids - set(got))} cell(s) lost, "
            f"{len(set(got) - spec_ids)} unknown")
    # every landed record's run dir must be browsable on the
    # COORDINATOR's store — the workers' private bases are gone as far
    # as this process is concerned
    live_stats = {"ok": 0, "degraded": 0, "missing-dir": 0}
    for rec in got.values():
        rel = rec.get("dir")
        d = os.path.join(cbase, rel) if rel else None
        if not (d and os.path.isdir(d)):
            live_stats["missing-dir"] += 1
            failures.append(
                f"federation: run dir for {rec.get('run')} never "
                f"landed on the coordinator store ({rel})")
            continue
        try:
            with open(os.path.join(d, "results.json")) as f:
                res = json.load(f)
        except (OSError, ValueError) as e:
            failures.append(f"federation: unreadable results.json in "
                            f"landed dir {rel}: {e}")
            continue
        lc = res.get("live-check") or {}
        state = lc.get("state")
        if state == "ok":
            live_stats["ok"] += 1
            if (lc.get("seal") or {}).get("equal") is not True:
                failures.append(
                    f"federation: live session {lc.get('session')} "
                    f"sealed UNEQUAL to batch: {lc.get('seal')}")
        elif state == "degraded":
            live_stats["degraded"] += 1  # allowed: stored-history
            # verdicts stand alone, equality asserted below
        else:
            failures.append(f"federation: cell {rec.get('run')} "
                            f"carries no live-check stamp ({state})")
    if live_stats["ok"] == 0:
        failures.append("federation: every live session degraded — "
                        "live checking never actually ran")
    sealed = [m for _n, m in scan_sessions(cbase)
              if m.get("state") == "sealed"]
    bad_seals = [m["session"] for m in sealed
                 if (m.get("seal") or {}).get("equal") is not True]
    if len(sealed) < live_stats["ok"]:
        failures.append(
            f"federation: {live_stats['ok']} ok live stamps but only "
            f"{len(sealed)} sealed sessions on the coordinator")
    if bad_seals:
        failures.append(f"federation: sealed sessions unequal to "
                        f"batch: {bad_seals}")
    # stored-history authority: fleet+live verdicts == single-process
    # WITHOUT live checking, key for key
    ref_spec = json.loads(json.dumps(spec))
    del ref_spec["opts"]["live-check"]
    ref_base = _tf.mkdtemp(prefix="fleet-fed-ref-")
    ref = campaign.run_campaign(ref_spec, ref_base, workers=2)
    ref_verdicts = {r["key"]: r["valid?"] for r in ref["rows"]}
    got_verdicts = {r["key"]: r["valid?"] for r in got.values()}
    if got_verdicts != ref_verdicts:
        diff = {k: (got_verdicts.get(k), ref_verdicts.get(k))
                for k in set(got_verdicts) | set(ref_verdicts)
                if got_verdicts.get(k) != ref_verdicts.get(k)}
        failures.append(f"federation: live-checked fleet != "
                        f"single-process stored-history: {diff}")
    # -- timeline completeness (ISSUE 14 acceptance): the coordinator
    # AND the verifier died kill -9 mid-campaign above, yet every
    # relanded/replayed run's stitched timeline must carry ONE trace
    # id (derived from the stable run id) with zero orphan spans, and
    # cover the control-plane + execute + upload story end to end
    from jepsen_tpu.telemetry import spans as spans_mod
    from jepsen_tpu.telemetry import warehouse as wmod

    wh = wmod.open_or_create(cbase)
    wh.ingest_store(cbase)
    stitched = 0
    for rec in got.values():
        run = rec.get("run")
        tl = wh.trace_timeline(run)
        want = spans_mod.trace_id_for(run)
        tids = {s["trace_id"] for s in tl["spans"]}
        if tl["orphans"] or (tids and tids != {want}):
            failures.append(
                f"federation: timeline for {run} is not single-trace: "
                f"{len(tl['orphans'])} orphan span(s), trace ids "
                f"{sorted(tids | {o['trace_id'] for o in tl['orphans']})}")
            continue
        names = {s["name"] for s in tl["spans"]}
        need = {"fleet:enqueue-wait", "fleet:claim-to-start",
                "fleet:execute", "fleet:upload", "run:workload"}
        missing = need - names
        if missing:
            failures.append(
                f"federation: timeline for {run} is missing "
                f"segments {sorted(missing)} (has {sorted(names)})")
            continue
        if rec.get("trace") != want:
            failures.append(
                f"federation: index record for {run} carries trace "
                f"{rec.get('trace')} != derived {want}")
            continue
        stitched += 1
    if stitched == 0:
        failures.append("federation: no run produced a complete "
                        "stitched timeline")
    # live-sweep overlap: at least the sealed (non-degraded) sessions
    # must contribute trace-stitched live-session segments
    live_segs = wh.query(
        "SELECT COUNT(*) FROM trace_spans "
        "WHERE name = 'verifier:live-session'")[1][0][0]
    if live_stats["ok"] and not live_segs:
        failures.append(
            f"federation: {live_stats['ok']} ok live sessions but no "
            "verifier:live-session trace segments stitched")
    if not failures:
        print(f"federation round OK: {cells} live-checked cells over "
              f"{n_workers} workers on private bases (no shared "
              f"filesystem), worker + coordinator kill -9 — "
              f"{live_stats['ok']} live sessions sealed incremental "
              f"== batch ({live_stats['degraded']} degraded to "
              f"stored-history), every run dir landed on the "
              f"coordinator, verdicts == single-process; "
              f"{stitched}/{cells} stitched timelines single-trace "
              f"with zero orphan spans ({live_segs} live-session "
              f"segments)")
        shutil.rmtree(cbase, ignore_errors=True)
        shutil.rmtree(ref_base, ignore_errors=True)
        for d in wbases.values():
            shutil.rmtree(d, ignore_errors=True)
    else:
        print(f"federation round FAILED (coordinator store: {cbase})",
              file=sys.stderr)
    return failures


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--cells", type=int, default=24)
    ap.add_argument("--fault-p", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--lease", type=float, default=2.0)
    ap.add_argument("--time-limit", type=float, default=0.4,
                    help="seconds of workload per cell")
    ap.add_argument("--store", default=None,
                    help="store dir (default: a temp dir)")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 smoke: the 12-cell x 3-worker "
                         "acceptance config, no SIGSTOP round")
    args = ap.parse_args()
    if args.fast:
        args.workers, args.cells = 3, 12
        args.fault_p = max(args.fault_p, 0.15)
    base = args.store or tempfile.mkdtemp(prefix="fleet-soak-")
    spec = {"name": "fleetsoak", "workloads": ["set"],
            "seeds": list(range(args.cells)),
            "opts": {"time-limit": args.time_limit}}
    spec_path = os.path.join(base, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    port = free_port()
    url = f"http://127.0.0.1:{port}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # server-side chaos too: the coordinator's own endpoints 503/stall
    env["JEPSEN_FAULTS"] = (
        f"seed={args.seed + 999},p={args.fault_p / 2},"
        "kinds=oom|stall,stall_s=0.02,"
        "sites=fleet.claim|fleet.heartbeat|fleet.complete")
    t0 = time.time()
    failures = []
    coord = spawn_coordinator(base, spec_path, port, args.lease, env)
    workers = {}
    try:
        wait_status(url, lambda s: True, 60, "coordinator up")
        for i in range(args.workers):
            workers[f"w{i}"] = spawn_worker(
                base, url, f"w{i}", args.seed * 1000 + i, args.fault_p,
                env)

        # -- nemesis 1: kill -9 a worker while it holds a lease -------
        def holding(s, names):
            alive = [w for w in names if workers[w].poll() is None]
            for lease in s.get("leases") or []:
                if lease["worker"] in alive:
                    return lease["worker"]
            return None

        requeued = False
        for attempt in range(2):
            names = list(workers)
            s = wait_status(url, lambda s: holding(s, names), 60,
                            "a worker holding a lease")
            victim = holding(s, names)
            workers[victim].send_signal(signal.SIGKILL)
            workers[victim].wait()
            print(f"killed -9 worker {victim} mid-lease")
            # replacement keeps the fleet >= workers-1 strong
            sub = f"{victim}r{attempt}"
            workers[sub] = spawn_worker(
                base, url, sub, args.seed * 1000 + 50 + attempt,
                args.fault_p, env)
            try:
                wait_status(
                    url, lambda s: (s["counts"]["requeues"] > 0
                                    or s["finished"]),
                    3 * args.lease + 30, "lease expiry requeue")
            except TimeoutError:
                continue
            requeued = True
            break
        if not requeued:
            failures.append("no lease-expiry requeue observed after "
                            "2 worker kills")

        # -- watermark retirement (ISSUE 16 satellite) ----------------
        # the killed worker's federated host series — including the
        # worker-rss-peak-bytes watermark — must retire with its
        # liveness window; a scrape that kept publishing dead workers'
        # peaks would grow monotonically across every kill -9 round
        if requeued:
            t_end = time.time() + 3 * args.lease + 30
            retired = False
            while time.time() < t_end:
                expo = scrape_metrics(url)
                if expo and f'host="{victim}"' not in expo:
                    retired = True
                    break
                time.sleep(0.5)
            if not retired:
                failures.append(
                    f"federated series for killed worker {victim} did "
                    "not retire within its liveness window (watermarks "
                    "would grow monotonically across kill -9 rounds)")
            elif "jepsen_fleet_host_worker_rss_peak_bytes" not in expo:
                # retirement must not be vacuous: alive workers still
                # publish the peak-RSS watermark series
                failures.append(
                    "no federated worker-rss-peak-bytes series for "
                    "alive workers after the kill -9 round")
            else:
                print(f"federated watermarks retired with {victim}'s "
                      "liveness; alive workers still publish peaks")

        # -- nemesis 2 (full mode): SIGSTOP a worker past its lease ---
        zombie = None
        if not args.fast:
            names = list(workers)
            s = wait_status(url, lambda s: holding(s, names), 60,
                            "a worker to freeze")
            zombie = holding(s, names)
            workers[zombie].send_signal(signal.SIGSTOP)
            print(f"SIGSTOP worker {zombie} (partition one worker)")
            time.sleep(2.5 * args.lease)
            workers[zombie].send_signal(signal.SIGCONT)
            print(f"SIGCONT worker {zombie} — its completion is now "
                  "a zombie's")

        # -- nemesis 3: kill -9 the coordinator + restart -------------
        wait_status(url, lambda s: s["done"] >= max(2, args.cells // 6),
                    120, "some cells done before coordinator kill")
        coord.send_signal(signal.SIGKILL)
        coord.wait()
        print("killed -9 coordinator mid-campaign")
        # independent replay of the dead coordinator's ledger: the
        # restarted process must reach this exact state
        from jepsen_tpu.fleet import WorkQueue, fleet_path

        frozen = os.path.join(base, "ledger-at-kill.jsonl")
        shutil.copy(fleet_path("fleetsoak", base), frozen)
        expect_digest = WorkQueue(frozen).digest()
        time.sleep(0.5)
        coord = spawn_coordinator(base, spec_path, port, args.lease,
                                  env)
        s = wait_status(url, lambda s: True, 60,
                        "coordinator restart")
        if s["boot-digest"] != expect_digest:
            failures.append(
                f"replay digest mismatch after coordinator kill -9: "
                f"boot {s['boot-digest']} != replayed {expect_digest}")
        else:
            print(f"coordinator replayed to identical state "
                  f"(digest {expect_digest})")

        # -- drain ----------------------------------------------------
        final = wait_status(url, lambda s: s["finished"], 300,
                            "campaign finished")
        print(f"campaign finished: {final['done']}/{final['total']} "
              f"cells, {final['counts']['requeues']} requeues, "
              f"{final['counts']['duplicates']} duplicate completions "
              "discarded")
        for w, p in workers.items():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.terminate()
    finally:
        for p in list(workers.values()) + [coord]:
            if p.poll() is None:
                p.kill()

    # -- acceptance: exactly one attributable verdict per cell --------
    from jepsen_tpu import campaign
    from jepsen_tpu.campaign import core as ccore
    from jepsen_tpu.campaign.index import Index
    from jepsen_tpu.campaign.plan import expand

    idx = Index(ccore.index_path("fleetsoak", base))
    per_run = {}
    for rec in idx.records:
        if "valid?" in rec:
            per_run[rec["run"]] = per_run.get(rec["run"], 0) + 1
    spec_ids = {rs.run_id for rs in expand(spec)}
    missing = spec_ids - set(per_run)
    extra = {r: n for r, n in per_run.items() if n != 1}
    if missing:
        failures.append(f"{len(missing)} cell(s) LOST: "
                        f"{sorted(missing)[:3]}...")
    if extra:
        failures.append(f"cells with != 1 record (duplicated): {extra}")
    unattributed = [r for rec in idx.records
                    if (r := rec.get("run")) and rec.get("valid?")
                    not in (True, False, "unknown")]
    if unattributed:
        failures.append(f"unattributable verdicts: {unattributed}")

    # -- acceptance: distributed == single-process on verdict keys ----
    ref_base = tempfile.mkdtemp(prefix="fleet-soak-ref-")
    ref = campaign.run_campaign(spec, ref_base, workers=2)
    ref_verdicts = {r["key"]: r["valid?"] for r in ref["rows"]}
    got_verdicts = {rec["key"]: rec["valid?"]
                    for rec in idx.latest_by_run().values()}
    if ref_verdicts != got_verdicts:
        diff = {k: (got_verdicts.get(k), ref_verdicts.get(k))
                for k in set(ref_verdicts) | set(got_verdicts)
                if got_verdicts.get(k) != ref_verdicts.get(k)}
        failures.append(f"distributed != single-process verdicts: "
                        f"{diff}")

    # -- the coordinated-chaos round (ISSUE 11 acceptance) ------------
    failures += coordinated_chaos_round(args, env)

    # -- the federation + live-check round (ISSUE 13 acceptance) ------
    failures += federation_round(args, env)

    wall = time.time() - t0
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        print(f"fleet soak FAILED in {wall:.1f}s (store: {base})",
              file=sys.stderr)
        return 1
    print(f"fleet soak OK: {args.cells} cells x {args.workers} workers "
          f"under chaos (worker kill -9, coordinator kill -9 + "
          f"restart{', zombie freeze' if zombie else ''}) + a "
          f"coordinated nemesis-schedule round + a store-federation "
          f"live-checking round — exactly one verdict per cell, "
          f"distributed == single-process, in {wall:.1f}s")
    if args.store is None:
        shutil.rmtree(base, ignore_errors=True)
        shutil.rmtree(ref_base, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
