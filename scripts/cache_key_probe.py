"""Diff the persistent-compile-cache key components between the axon
tunnel backend and the deviceless v5e topology backend (same tiny
program, same shapes): the deviceless AOT hedge only pays off if its
cache keys match what the in-tunnel run computes.  Run each mode in a
fresh process:

    python scripts/cache_key_probe.py axon
    JAX_PLATFORMS=cpu python scripts/cache_key_probe.py topo

Also saves the axon backend's platform strings + serialized topology to
scripts/axon_fingerprint.json for aot_warm.py's key-matching mode.
"""

import base64
import json
import logging
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

mode = sys.argv[1] if len(sys.argv) > 1 else "axon"

if mode == "topo":
    from jepsen_tpu.utils.backend import force_cpu_backend

    force_cpu_backend()

import jax
import jax.numpy as jnp
import numpy as np
from jax._src import cache_key as ck
from jax._src.lib import xla_client

logging.basicConfig(stream=sys.stderr)
ck.logger.setLevel(logging.DEBUG)


def tiny(x):
    return jnp.cumsum(x * 2)[-1]


def main():
    if mode == "axon":
        backend = jax.devices()[0].client
        devs = np.array(jax.devices()[:1])
    else:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(topology_name="v5e:2x2",
                                            platform="tpu")
        devs = np.array(topo.devices[:1])
        backend = None

    from jax.sharding import SingleDeviceSharding

    sh = SingleDeviceSharding(devs.flat[0])
    xs = jax.ShapeDtypeStruct((1024,), jnp.int32, sharding=sh)
    lowered = jax.jit(tiny).lower(xs)
    module = lowered._lowering.stablehlo_module() if hasattr(
        lowered._lowering, "stablehlo_module") else \
        lowered.compiler_ir("stablehlo")
    opts = lowered._lowering.compile_args["executable_build_options"] \
        if "executable_build_options" in getattr(
            lowered._lowering, "compile_args", {}) else None
    # the canonical route: what compiler.py passes
    from jax._src import compiler
    compile_options = lowered._lowering.compile_args.get("compile_options") \
        if hasattr(lowered._lowering, "compile_args") else None
    if compile_options is None:
        compile_options = xla_client.CompileOptions()
    if mode == "axon":
        key = ck.get(module, devs, compile_options, backend)
    else:
        # topology compile path: backend object for key purposes is the
        # topology client jax uses in AOT; emulate with a shim exposing
        # platform/platform_version like compiler.py sees
        class TopoShim:
            platform = devs.flat[0].platform
            platform_version = getattr(devs.flat[0].client,
                                       "platform_version", "")

        key = ck.get(module, devs, compile_options, TopoShim)
    print(f"[{mode}] key:", key)
    info = {
        "mode": mode,
        "platform": getattr(devs.flat[0], "platform", "?"),
        "device_kind": devs.flat[0].device_kind,
    }
    try:
        topo_ser = xla_client.get_topology_for_devices(
            list(devs.flat)).serialize()
        info["topology_b64"] = base64.b64encode(topo_ser).decode()
    except Exception as e:
        info["topology_error"] = str(e)
    if mode == "axon":
        info["platform_version"] = backend.platform_version
        with open(os.path.join(REPO, "scripts", "axon_fingerprint.json"),
                  "w") as f:
            json.dump(info, f)
    else:
        info["platform_version"] = TopoShim.platform_version
    print(json.dumps({k: (v[:80] + "..." if isinstance(v, str) and
                          len(v) > 80 else v)
                      for k, v in info.items()}, indent=1))


if __name__ == "__main__":
    main()
