"""Differential fuzz: rw-register device checker vs host path.

Campaign of 2026-07-30: 200/200 exact matches.
Env: FUZZ_N (default 200), FUZZ_SEED.
"""
import sys, random, time
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from jepsen_tpu.utils.backend import force_cpu_backend
force_cpu_backend()
import jax
from jepsen_tpu.checkers.elle import rw_register
from jepsen_tpu.workloads import synth

MODELS_POOL = [["strict-serializable"], ["serializable"],
               ["snapshot-isolation"]]
rng = random.Random(int(os.environ.get("FUZZ_SEED", 77)))
n_fail = 0
t_start = time.time()
N = int(os.environ.get("FUZZ_N", 200))
for case in range(N):
    params = dict(
        n_txns=rng.choice([20, 60, 150, 400]),
        n_keys=rng.choice([1, 2, 5, 16]),
        concurrency=rng.choice([1, 3, 8]),
        fail_prob=rng.choice([0.0, 0.05, 0.2]),
        info_prob=rng.choice([0.0, 0.05, 0.2]),
        seed=rng.randrange(1 << 30),
    )
    h = synth.rw_history(**params)
    models = rng.choice(MODELS_POOL)
    try:
        r_d = rw_register.check(h, models, use_device=True)
        r_h = rw_register.check(h, models, use_device=False)
        if r_d["valid?"] != r_h["valid?"] or \
           set(r_d["anomaly-types"]) != set(r_h["anomaly-types"]):
            n_fail += 1
            print(f"MISMATCH case={case} params={params} models={models}\n"
                  f"  host={r_h['valid?']} {sorted(r_h['anomaly-types'])}\n"
                  f"  dev ={r_d['valid?']} {sorted(r_d['anomaly-types'])}",
                  flush=True)
    except Exception as e:
        n_fail += 1
        print(f"ERROR case={case} params={params}: "
              f"{type(e).__name__}: {e}", flush=True)
    if case % 25 == 24:
        jax.clear_caches()
        print(f"[{case+1}/{N}] {time.time()-t_start:.0f}s "
              f"mismatches={n_fail}", flush=True)
print(f"DONE {N} cases, {n_fail} mismatches, {time.time()-t_start:.0f}s",
      flush=True)
sys.exit(1 if n_fail else 0)
