"""Pre-generate every TPU-ladder bench input to disk (VERDICT r04 item
1a): run while the tunnel is down so an open window pays zero generation
time.  Idempotent — existing files are kept.

Usage: JAX_PLATFORMS=cpu python scripts/prestage_inputs.py
(CPU platform: generation is pure numpy; don't dial the tunnel.)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JT_PRESTAGE_SAVE", "1")

from jepsen_tpu.utils import prestage  # noqa: E402

LADDER = [
    ("la", 100_000), ("la", 1_000_000), ("la", 10_000_000),
    ("rw", 1_000_000),
]


def main():
    for kind, n in LADDER:
        t0 = time.perf_counter()
        if kind == "la":
            p = prestage.la_history(n_txns=n, n_keys=max(64, n // 8),
                                    save=True)
        else:
            p = prestage.rw_history(n_txns=n, n_keys=max(64, n // 8),
                                    save=True)
        print(f"{kind}_{n}: n_txns={p.n_txns} n_mops={p.n_mops} "
              f"rd_elems={len(p.rd_elems)} in {time.perf_counter()-t0:.1f}s",
              flush=True)
    print("prestage dir:", prestage.prestage_dir())


if __name__ == "__main__":
    main()
