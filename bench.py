"""Benchmark: list-append check throughput (the north-star metric).

Generates strict-serializable packed list-append histories, runs the
fused device core check (edge inference + 5 projection cycle sweeps),
and reports verified ops/sec.  Baseline = the BASELINE.json target of a
10M-op history in 60 s on a v5e-8 (166,667 ops/s); vs_baseline > 1
beats it.

Progressive sizing: the bench climbs a size ladder (default 100k -> 1M
txns) and reports the LARGEST size that completed.  XLA:TPU compile at
1M-txn shapes measured ~26 min cold (PROFILE.md §2) — with a warm
persistent cache the 1M rung completes in ~1 min, but on a cold cache
the 100k rung (~1 min compile) still lands a real number before the
deadline instead of a zero-valued DNF (what happened in round 2).

Robustness contract: ALWAYS prints exactly ONE JSON line on stdout, even
when the TPU backend fails to initialize or hangs — backend init is probed
in a subprocess with a timeout, a hard deadline watchdog emits the best
completed rung (or an error line) if anything blocks past it, and on
failure the bench falls back to the CPU backend (recorded in the
"backend"/"error" fields).

Env knobs: BENCH_TXNS (single fixed size, disables the ladder),
BENCH_SIZES (comma-separated ladder, default "100000,1000000"),
BENCH_KEYS, BENCH_REPEATS, BENCH_FORCE_CPU=1, BENCH_INIT_TIMEOUT (s,
default 180), BENCH_TPU_RETRY_S (keep re-probing a down TPU tunnel for
this long before the CPU fallback, default 450), BENCH_DEADLINE (s,
default 2700), BENCH_CACHE_DIR (persistent XLA compilation cache,
default <repo>/.jax_cache).

Sharded rows (``--shards N`` argv or BENCH_SHARDS, ISSUE 12
satellite): on the CPU backend the bench boots N virtual host devices
and, after the headline (sharded-default) measurement, times the SAME
padded history through the single-device path and the N-shard default,
asserting identical verdict bits — the per-shard-count rows land under
``"shards"`` in the payload.  Caveat: XLA:CPU's GSPMD compile of the
sharded program is very slow at >= 2^16-txn shapes (absorbed once into
the persistent cache); real accelerator backends compile on-device.

Streaming mode (``--streaming`` argv or BENCH_STREAMING=1, ISSUE 7
satellite): additionally feeds each rung's history through the
incremental ``verifier.VerifierSession`` in BENCH_STREAM_SEG-txn
segments (default 100000) and reports incremental ops/s next to the
batch number under ``"streaming"`` in the payload — the
batch-vs-always-on throughput comparison, self-ingested into the
warehouse with the rest of the payload.

Warm-twice mode (``--warm-twice`` argv or BENCH_WARM_TWICE=1, ISSUE 18
satellite): after each rung completes, drop every in-memory executable
(``jax.clear_caches()`` + the AOT mem table) and run the rung again —
the second run must reload its executables from the persistent AOT
compile cache (``jepsen_tpu.compilecache``), so its
``compile_or_warmup_s`` collapses to ~dispatch time.  The comparison
lands under ``"warm_twice"`` in the payload (self-ingested with the
rest); a cold second run or any cache fall-through fails the bench
(rc 1).  BENCH_AOT_CACHE overrides the AOT store directory (default
``<repo>/.aot_cache_bench`` when no store is configured).

Exit status: 0 with a real value; 1 on any error/deadline path with no
completed rung (the JSON line is still printed — consumers may read
either the rc or the "error" field).
"""

import json
import os
import sys
import threading
import time
import traceback

BASELINE_OPS_PER_SEC = 10_000_000 / 60.0  # BASELINE.json: 10M ops in 60 s


def _shards_arg() -> int:
    """--shards N argv (or BENCH_SHARDS): bench the sharded-by-default
    path over N virtual host devices on the CPU backend (real devices
    shard automatically on TPU).  0 = unset."""
    if "--shards" in sys.argv:
        try:
            return int(sys.argv[sys.argv.index("--shards") + 1])
        except (ValueError, IndexError):
            return 0
    try:
        return int(os.environ.get("BENCH_SHARDS", 0))
    except ValueError:
        return 0


def _force_cpu_backend():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from jepsen_tpu.utils.backend import force_cpu_backend

    force_cpu_backend(_shards_arg() or None)


def _probe_default_backend(timeout_s: float) -> str:
    """Probe default-backend init in a subprocess (it can HANG, not just
    raise, when the TPU tunnel is down).  Returns "" on success or an
    error string."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return f"backend init hung > {timeout_s:.0f}s"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:]
        return f"backend init rc={r.returncode}: {' '.join(tail)}"
    return ""


def _init_backend():
    """Initialize a jax backend: probe the default (TPU via axon),
    re-probing across a retry window (BENCH_TPU_RETRY_S — the tunnel
    flaps on the scale of minutes, r01-r03 evidence), then fall back to
    CPU.  Returns (platform, anomaly_or_None) where the anomaly is a
    STRUCTURED dict ({error, probes, wait_s}) — r05 recorded a 544 s
    backend-init hang only as a free-text field; the structured form
    feeds the resilience-env-anomalies counter, the warehouse, and
    /metrics (ISSUE 6 satellite)."""
    if os.environ.get("BENCH_FORCE_CPU"):
        _force_cpu_backend()
        import jax

        return jax.devices()[0].platform, None

    # cold axon dials have measured ~140 s (2026-07-31); 120 s misreads
    # a slow-but-live tunnel as down
    probe_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", 180))
    # default window: ~2-3 probes when each hangs the full 180 s, while
    # leaving most of the default 2700 s deadline for the CPU fallback
    retry_window = float(os.environ.get("BENCH_TPU_RETRY_S", 450))
    t_start = time.monotonic()
    n_probes = 0
    while True:
        last_err = _probe_default_backend(probe_timeout)
        n_probes += 1
        if not last_err:
            # the probe warmed the tunnel; main-process init is protected
            # by the deadline watchdog in main()
            import jax

            anomaly = None
            if n_probes > 1:  # recovered, but only after failed probes
                anomaly = {"error": "recovered after failed probes",
                           "probes": n_probes,
                           "wait_s": round(time.monotonic() - t_start, 1),
                           "recovered": True}
            return jax.devices()[0].platform, anomaly
        elapsed = time.monotonic() - t_start
        if elapsed >= retry_window:
            break
        # a hang just burned probe_timeout seconds; a clean failure may
        # clear quickly — space clean-failure retries out a little
        time.sleep(2.0 if "hung" in last_err else 30.0)
    _force_cpu_backend()
    import jax

    return jax.devices()[0].platform, {
        "error": last_err, "probes": n_probes,
        "wait_s": round(time.monotonic() - t_start, 1)}


_BEST = [None]  # best completed rung payload; single-slot atomic rebind


def _arm_watchdog(deadline_s: float):
    """If the bench hasn't finished by the deadline (e.g. main-process
    backend init hung after a successful probe, or a cold compile at the
    biggest rung), emit the best COMPLETED rung — or the JSON error line
    if none — and hard-exit so the driver still gets a parseable
    result."""
    done = threading.Event()

    def fire():
        if not done.wait(deadline_s):
            best = _BEST[0]  # single read: rebind in main() is atomic
            if best is not None:
                payload = dict(best)
                payload["note"] = (f"deadline {deadline_s:.0f}s hit while "
                                   "running a larger size; value is the "
                                   "largest completed size")
                _emit(payload)
                os._exit(0)
            _emit({"metric": "elle-list-append-check-throughput",
                   "value": 0, "unit": "ops/sec", "vs_baseline": 0,
                   "error": f"bench exceeded {deadline_s:.0f}s deadline"})
            os._exit(1)

    threading.Thread(target=fire, daemon=True).start()
    return done


_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _emit(payload):
    """Print the result line exactly once, even when the deadline
    watchdog and the main thread race at the boundary."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return
        _EMITTED = True
        print(json.dumps(payload))
        sys.stdout.flush()


def _span_durations_s(doc):
    """Flatten a telemetry snapshot's span forest into
    {name: [durations_s...]} (bench spans repeat per timed run)."""
    out = {}

    def walk(sp):
        d = sp.get("dur_ns")
        if d is not None:
            out.setdefault(sp["name"], []).append(d / 1e9)
        for c in sp.get("children") or []:
            walk(c)

    for r in doc.get("spans", []):
        walk(r)
    return out


def _memory_section():
    """Peak-memory snapshot for a completed rung (ISSUE 16): process
    RSS + the kernel's VmHWM high watermark, and per-device
    bytes-in-use / peak from ``memory_stats()`` — so a rung's footprint
    rides in the BENCH payload (and the warehouse) next to its ops/s.
    Never fails the bench."""
    try:
        from jepsen_tpu.telemetry.stream import (_device_memory_stats,
                                                 _hwm_bytes, _rss_bytes)

        out = {}
        rss = _rss_bytes()
        if rss:
            out["rss_bytes"] = rss
        hwm = _hwm_bytes()
        if hwm or rss:
            out["rss_peak_bytes"] = max(hwm or 0, rss or 0)
        devices = {}
        for dev, (used, pk) in _device_memory_stats().items():
            row = {"bytes_in_use": used}
            if pk is not None:
                row["peak_bytes_in_use"] = pk
            devices[dev] = row
        if devices:
            out["devices"] = devices
        return out or None
    except Exception:  # noqa: BLE001 — observability only
        return None


def _run_size(n_txns: int, repeats: int):
    """One ladder rung: returns the result payload (raises on failure)."""
    import jax

    from jepsen_tpu import telemetry
    from jepsen_tpu.checkers.elle.device_core import core_check_auto as check
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.utils import prestage

    # keys scale with size so per-key list lengths stay bounded (~12
    # appends/key) — matching how real list-append workloads bound
    # read-list growth (elle's gen rotates keys)
    n_keys = int(os.environ.get("BENCH_KEYS", max(64, n_txns // 8)))

    # telemetry rides along (ISSUE 1 satellite): checker span durations
    # + ops/s land in the BENCH_*.json payload so the perf trajectory
    # is machine-readable from PR 1 onward
    coll = telemetry.activate()
    try:
        # prestaged inputs (scripts/prestage_inputs.py) load in seconds; a
        # cold miss falls back to generation (~153 s at 10M)
        t_gen = time.perf_counter()
        with telemetry.span("bench.gen", n_txns=n_txns):
            p = prestage.la_history(n_txns=n_txns, n_keys=n_keys,
                                    verbose=False)
            h = pad_packed(p)
        t_gen = time.perf_counter() - t_gen

        # stage inputs on device BEFORE timing: first dispatch otherwise
        # pays a synchronous host->device transfer of every padded array
        # (measured ~30 s at 100k txns in round 2)
        t_stage = time.perf_counter()
        with telemetry.span("bench.stage"):
            h = jax.device_put(h)
            jax.block_until_ready(h)
        t_stage = time.perf_counter() - t_stage

        # warmup (compile — or persistent-cache hit on reruns)
        t_compile = time.perf_counter()
        with telemetry.span("bench.compile-or-warmup"):
            bits, over = check(h, p.n_keys)
            jax.block_until_ready(bits)
        t_compile = time.perf_counter() - t_compile
        assert int(bits[-1]) == 1, "sweep did not converge on bench history"
        assert int(bits[:12].sum()) == 0, "bench history must be valid"

        from jepsen_tpu.utils.profiling import trace

        best = float("inf")
        with trace(os.environ.get("BENCH_PROFILE_DIR")):
            for _ in range(repeats):
                t0 = time.perf_counter()
                with telemetry.span("bench.check", n_txns=n_txns):
                    bits, over = check(h, p.n_keys)
                    jax.block_until_ready(bits)
                best = min(best, time.perf_counter() - t0)

        ops_per_sec = n_txns / best
        telemetry.registry().gauge(
            "checker-ops-per-s", checker="device-core").set(
            round(ops_per_sec, 1))
        # --shards: quote single-device vs sharded-default on the SAME
        # padded history, verdict-asserted identical (ISSUE 12)
        shard_rows = (_run_shard_rows(h, p, repeats, check)
                      if _shards_arg() > 1 else None)
        streaming = (_run_streaming(p, n_txns)
                     if _streaming_enabled() else None)
        doc = telemetry.snapshot(coll)
    finally:
        telemetry.deactivate(coll)
    spans = _span_durations_s(doc)
    out = {
        "metric": "elle-list-append-check-throughput",
        "value": round(ops_per_sec, 1),
        "unit": "ops/sec",
        "vs_baseline": round(ops_per_sec / BASELINE_OPS_PER_SEC, 3),
        "n_txns": n_txns,
        "wall_s": round(best, 3),
        "gen_s": round(t_gen, 2),
        "stage_s": round(t_stage, 2),
        "compile_or_warmup_s": round(t_compile, 2),
        "telemetry": {
            "checker_span_s": {name: round(min(ds), 6)
                               for name, ds in sorted(spans.items())},
            "checker_span_runs": {name: len(ds)
                                  for name, ds in sorted(spans.items())},
            "check_ops_per_s": round(ops_per_sec, 1),
        },
    }
    memory = _memory_section()
    if memory is not None:
        out["memory"] = memory
    if shard_rows is not None:
        out["shards"] = shard_rows
    if streaming is not None:
        out["streaming"] = streaming
    return out


def _run_shard_rows(h, p, repeats: int, check):
    """Per-shard-count rows: the same padded history through the
    single-device path (JEPSEN_SHARDS=1) and the sharded default
    (all visible devices), bits asserted identical."""
    import jax
    import numpy as np

    n_dev = len(jax.devices())
    rows = {}
    ref = None
    for n in (1, n_dev):
        prev = os.environ.get("JEPSEN_SHARDS")
        os.environ["JEPSEN_SHARDS"] = str(n)
        try:
            bits, _ = check(h, p.n_keys)  # warm / compile
            jax.block_until_ready(bits)
            best = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                bits, _ = check(h, p.n_keys)
                jax.block_until_ready(bits)
                best = min(best, time.perf_counter() - t0)
            b = np.asarray(bits)
            if ref is None:
                ref = b
            else:
                assert np.array_equal(b, ref), \
                    "sharded verdict bits != single-device bits"
            rows[str(n)] = {"value": round(p.n_txns / best, 1),
                            "unit": "ops/sec", "wall_s": round(best, 3)}
        finally:
            if prev is None:
                os.environ.pop("JEPSEN_SHARDS", None)
            else:
                os.environ["JEPSEN_SHARDS"] = prev
    return {"devices": n_dev, "rows": rows}


def _streaming_enabled():
    return "--streaming" in sys.argv or os.environ.get("BENCH_STREAMING")


def _warm_twice_enabled():
    return ("--warm-twice" in sys.argv
            or os.environ.get("BENCH_WARM_TWICE"))


def _ensure_aot_dir():
    """--warm-twice needs a persistent AOT store to reload from; when
    the default resolution lands memory-only (no ./store dir, no
    JT_COMPILECACHE path), pin one next to the XLA cache."""
    from jepsen_tpu import compilecache

    if compilecache.cache_dir() is None:
        compilecache.set_cache_dir(
            os.environ.get("BENCH_AOT_CACHE")
            or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".aot_cache_bench"))
    return compilecache.cache_dir()


def _warm_twice_rerun(n_txns, repeats, first_payload):
    """ISSUE 18 satellite: run the rung AGAIN with every in-memory
    executable dropped (jit caches + the AOT mem table) but the
    persistent AOT store intact — the second run's
    compile_or_warmup_s then measures deserialize-and-load, not
    compile.  `ok` demands it collapse (≤ max(3 s, 30% of the first
    run), no cache fall-throughs, at least one AOT hit)."""
    import jax

    from jepsen_tpu import compilecache

    compilecache.clear()
    jax.clear_caches()
    compilecache.reset_stats()
    second = _run_size(n_txns, repeats)
    st = compilecache.stats()
    w1 = first_payload["compile_or_warmup_s"]
    w2 = second["compile_or_warmup_s"]
    ok = (w2 <= max(3.0, 0.3 * w1)
          and st.get("fallthroughs", 0) == 0
          and st.get("hits", 0) > 0)
    return {
        "first_compile_s": w1,
        "second_compile_s": w2,
        "second_value": second["value"],
        "ok": bool(ok),
        "cache": {k: st.get(k, 0)
                  for k in ("hits", "misses", "fallthroughs")},
    }


def _run_streaming(p, n_txns):
    """ISSUE 7 satellite: the same history through the incremental
    VerifierSession in segments — incremental ops/s next to batch
    ops/s.  The final rolling verdict must be valid (the generator
    emits strict-serializable histories)."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.verifier import VerifierSession, iter_packed_segments

    seg = int(os.environ.get("BENCH_STREAM_SEG", 100_000))
    ses = VerifierSession("bench", ("strict-serializable",))
    n_segs = 0
    t0 = time.perf_counter()
    with telemetry.span("bench.streaming", n_txns=n_txns, seg=seg):
        for cols, rd, base in iter_packed_segments(p, seg):
            ses.append_columns(cols, rd_elems=rd, rd_base=base)
            ses.verdict()  # rolling: sweep at every segment boundary
            n_segs += 1
        verdict = ses.verdict()
    wall = time.perf_counter() - t0
    return {
        "value": round(n_txns / wall, 1),
        "unit": "ops/sec",
        "wall_s": round(wall, 3),
        "segments": n_segs,
        "segment_txns": seg,
        "valid?": verdict.get("valid?"),
    }


def _ingest_warehouse(payload):
    """Best-effort: land the completed bench payload in the store's
    sqlite warehouse (ISSUE 6) so the throughput trajectory is a
    queryable series, not loose BENCH_*.json files.  Target:
    BENCH_WAREHOUSE (explicit opt-in), else <cwd>/store/
    warehouse.sqlite ONLY when a store/ dir already exists — the
    bench's documented contract is one JSON line on stdout, so it
    never grows a new filesystem footprint by itself.  Never fails
    the bench."""
    try:
        path = os.environ.get("BENCH_WAREHOUSE")
        if path is None:
            if not os.path.isdir("store"):
                return
            path = os.path.join("store", "warehouse.sqlite")
        if not path:
            return
        from jepsen_tpu.telemetry.warehouse import Warehouse

        tag = "bench@" + time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        Warehouse(path).ingest_bench(payload, source=tag)
    except Exception:  # noqa: BLE001 — the JSON line is the contract
        pass


def emit_campaign_spec(path, sizes=None, seeds=(0,)):
    """Write the bench ladder as a `jepsen_tpu.campaign` spec, so BENCH
    trajectories and soak runs drive the same fleet engine (`cli
    campaign run <spec>`): one labeled list-append workload entry per
    rung, op-count-bound (no wall-clock cap), telemetry on so the
    campaign index accumulates checker span durations across
    generations (`Index.span_trend`)."""
    if sizes is None:
        sizes = [int(s) for s in os.environ.get(
            "BENCH_SIZES", "100000,1000000").split(",") if s.strip()]
    spec = {
        "name": "bench-ladder",
        "workloads": [
            {"name": "append", "label": f"la-{n}",
             "opts": {"ops": n, "time-limit": None}}
            for n in sizes
        ],
        "faults": [None],
        "seeds": list(seeds),
        "opts": {"telemetry": True,
                 "checker-time-limit": float(
                     os.environ.get("BENCH_DEADLINE", 2700))},
    }
    with open(path, "w") as f:
        json.dump(spec, f, indent=1)
    return spec


def main():
    # emit-spec mode: no backend init, no watchdog — just the ladder as
    # campaign data (BENCH_EMIT_CAMPAIGN_SPEC=<path>)
    emit_path = os.environ.get("BENCH_EMIT_CAMPAIGN_SPEC")
    if emit_path:
        spec = emit_campaign_spec(emit_path)
        _emit({"campaign_spec": emit_path,
               "runs": len(spec["workloads"]) * len(spec["seeds"])})
        return 0

    # arm the watchdog before anything that can raise or hang — the
    # one-JSON-line contract must survive malformed env knobs too.
    # Default 2700 s: a COLD 1M TPU compile measured 1161 s on the
    # round-5 box (1834-2104 s on the previous one) — 1500 s left no
    # headroom if the persistent cache misses (axon cache keys have
    # been observed unstable across processes, PROFILE.md §-1f), and
    # the watchdog still emits the best completed rung on breach.
    try:
        deadline = float(os.environ.get("BENCH_DEADLINE", 2700))
    except ValueError:
        deadline = 2700.0
    done = _arm_watchdog(deadline)
    platform = "unknown"
    try:
        if os.environ.get("BENCH_TXNS"):
            sizes = [int(os.environ["BENCH_TXNS"])]
        else:
            sizes = [int(s) for s in os.environ.get(
                "BENCH_SIZES", "100000,1000000").split(",") if s.strip()]
        if not sizes:
            raise ValueError("BENCH_SIZES is empty")
        repeats = int(os.environ.get("BENCH_REPEATS", 3))

        platform, backend_err = _init_backend()
        if backend_err:
            # structured resilience signal, not just a free-text field:
            # the counter lands in the telemetry registry (and /metrics)
            # and the dict rides in the payload + warehouse
            from jepsen_tpu.resilience import env_anomaly

            env_anomaly("backend-init",
                        kind=("retried" if backend_err.get("recovered")
                              else "fallback"),
                        **backend_err)

        # Persistent compilation cache: driver reruns (and repeated
        # rungs at the same padded shapes) skip XLA compile — round 2's
        # DNF was a 125.8 s compile at 100k shapes, and 1M shapes
        # compile in ~26 min cold on the TPU backend (PROFILE.md §2).
        from jepsen_tpu.utils.backend import enable_compile_cache

        enable_compile_cache()
        if _warm_twice_enabled():
            _ensure_aot_dir()
    except Exception as e:
        done.set()
        _emit({"metric": "elle-list-append-check-throughput", "value": 0,
               "unit": "ops/sec", "vs_baseline": 0, "backend": platform,
               "error": f"bench setup failed: {type(e).__name__}: {e}",
               "trace": traceback.format_exc(limit=3)})
        return 1

    last_err = None
    last_err_tb = ""
    for n_txns in sizes:
        try:
            payload = _run_size(n_txns, repeats)
            payload["backend"] = platform
            if _warm_twice_enabled():
                payload["warm_twice"] = _warm_twice_rerun(
                    n_txns, repeats, payload)
            if backend_err:
                # compat free-text field + the structured anomaly list
                payload["backend_init_retried"] = (
                    f"{backend_err.get('error')} "
                    f"({backend_err.get('probes')} probes over "
                    f"{backend_err.get('wait_s')}s)")
                payload["env_anomalies"] = [
                    {"site": "backend-init", **backend_err}]
            if _BEST[0] is None or payload["n_txns"] > _BEST[0]["n_txns"]:
                _BEST[0] = payload  # atomic rebind, watchdog-safe
        except Exception as e:
            last_err = f"{type(e).__name__}: {e}"
            last_err_tb = traceback.format_exc(limit=3)
            break

    done.set()
    if _BEST[0] is not None:
        payload = dict(_BEST[0])
        if last_err:
            payload["larger_size_error"] = last_err
        wt = payload.get("warm_twice")
        if wt is not None and not wt.get("ok"):
            payload["error"] = (
                "warm-twice: second run not warm "
                f"({wt['second_compile_s']}s vs {wt['first_compile_s']}s"
                f", cache {wt['cache']})")
        _ingest_warehouse(payload)
        _emit(payload)
        return 1 if "error" in payload else 0
    _emit({"metric": "elle-list-append-check-throughput", "value": 0,
           "unit": "ops/sec", "vs_baseline": 0, "backend": platform,
           "error": last_err or "no size completed",
           "trace": last_err_tb})
    return 1


if __name__ == "__main__":
    sys.exit(main())
