"""Benchmark: list-append check throughput (the north-star metric).

Generates a strict-serializable packed list-append history, runs the fused
device core check (edge inference + 5 projection cycle sweeps), and
reports verified ops/sec.  Baseline = the BASELINE.json target of a 10M-op
history in 60 s on a v5e-8 (166,667 ops/s); vs_baseline > 1 beats it.

Env knobs: BENCH_TXNS (default 1,000,000), BENCH_KEYS, BENCH_REPEATS.
Prints exactly ONE JSON line.
"""

import json
import os
import sys
import time


def main():
    n_txns = int(os.environ.get("BENCH_TXNS", 1_000_000))
    # keys scale with size so per-key list lengths stay bounded (~12
    # appends/key) — matching how real list-append workloads bound
    # read-list growth (elle's gen rotates keys)
    n_keys = int(os.environ.get("BENCH_KEYS", max(64, n_txns // 8)))
    repeats = int(os.environ.get("BENCH_REPEATS", 3))

    import jax

    from jepsen_tpu.checkers.elle.device_core import core_check
    from jepsen_tpu.checkers.elle.device_infer import pad_packed
    from jepsen_tpu.workloads import synth

    p = synth.packed_la_history(n_txns=n_txns, n_keys=n_keys,
                                mops_per_txn=4, read_frac=0.25, seed=7)
    h = pad_packed(p)

    # warmup (compile)
    bits, over = core_check(h, p.n_keys)
    jax.block_until_ready(bits)
    assert int(bits[-1]) == 1, "sweep did not converge on bench history"
    assert int(bits[:12].sum()) == 0, "bench history must be valid"

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        bits, over = core_check(h, p.n_keys)
        jax.block_until_ready(bits)
        best = min(best, time.perf_counter() - t0)

    ops_per_sec = n_txns / best
    baseline = 10_000_000 / 60.0  # BASELINE.json: 10M ops under 60 s
    print(json.dumps({
        "metric": "elle-list-append-check-throughput",
        "value": round(ops_per_sec, 1),
        "unit": "ops/sec",
        "vs_baseline": round(ops_per_sec / baseline, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
