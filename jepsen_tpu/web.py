"""Web UI for browsing the test store.

Equivalent of the reference's `jepsen/src/jepsen/web.clj` (SURVEY.md §2.1,
§3.5): a small threaded HTTP server over the store directory — a run table
(name, timestamp, verdict), per-run file browsing, and zip download of a
whole run.  Stdlib-only (http.server), replacing the reference's http-kit.
"""

from __future__ import annotations

import html
import io
import json
import logging
import os
import threading
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import quote, unquote, urlparse

from . import store

logger = logging.getLogger("jepsen.web")


def _run_summary(d: str) -> Dict[str, Any]:
    """Cheap summary of one run dir: verdict comes from results.json (fast
    path) or the .jepsen results block."""
    out: Dict[str, Any] = {
        "dir": d,
        "name": os.path.basename(os.path.dirname(d)),
        "timestamp": os.path.basename(d),
        "valid?": "?",
    }
    rj = os.path.join(d, "results.json")
    try:
        if os.path.exists(rj):
            with open(rj) as f:
                out["valid?"] = json.load(f).get("valid?", "?")
        else:
            res = store.load(d).get("results")
            if res:
                out["valid?"] = res.get("valid?", "?")
    except Exception:  # noqa: BLE001 — a corrupt run still gets listed
        out["valid?"] = "corrupt"
    return out


def _verdict_cell(v: Any) -> str:
    color = {"True": "#9ce29c", "False": "#f2a3a3",
             "unknown": "#ffd37a"}.get(str(v), "#ddd")
    return f'<td style="background:{color};text-align:center">{html.escape(str(v))}</td>'


class _Handler(BaseHTTPRequestHandler):
    base: str = store.BASE  # overridden per-server

    # -- helpers ----------------------------------------------------------

    def _send(self, code: int, content: bytes,
              ctype: str = "text/html; charset=utf-8",
              extra: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(content)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(content)

    def _safe_path(self, rel: str) -> Optional[str]:
        """Resolve a store-relative path, refusing traversal outside it."""
        base = os.path.realpath(self.base)
        p = os.path.realpath(os.path.join(base, rel))
        if p == base or p.startswith(base + os.sep):
            return p
        return None

    # -- routes -----------------------------------------------------------

    def do_GET(self):  # noqa: N802 (stdlib API)
        try:
            path = unquote(urlparse(self.path).path)
            if path in ("/", "/index.html"):
                return self._index()
            if path.startswith("/files/"):
                return self._files(path[len("/files/"):])
            if path.startswith("/zip/"):
                return self._zip(path[len("/zip/"):])
            if path.startswith("/telemetry/"):
                return self._telemetry(path[len("/telemetry/"):])
            self._send(404, b"not found", "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            logger.exception("web handler error")
            self._send(500, f"error: {e}".encode(), "text/plain")

    def _index(self):
        rows = []
        for d in store.tests(base=self.base):
            s = _run_summary(d)
            rel = os.path.relpath(d, self.base)
            tel = (f'<td><a href="/telemetry/{quote(rel)}">trace</a></td>'
                   if os.path.exists(os.path.join(d, "telemetry.json"))
                   else "<td></td>")
            rows.append(
                "<tr>"
                f'<td><a href="/files/{quote(rel)}/">{html.escape(s["name"])}</a></td>'
                f'<td><a href="/files/{quote(rel)}/">{html.escape(s["timestamp"])}</a></td>'
                f"{_verdict_cell(s['valid?'])}"
                f"{tel}"
                f'<td><a href="/zip/{quote(rel)}">zip</a></td>'
                "</tr>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>jepsen-tpu</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
</style></head><body>
<h1>jepsen-tpu runs</h1>
<table><tr><th>test</th><th>time</th><th>valid?</th><th>telemetry</th><th>download</th></tr>
{"".join(rows)}</table></body></html>"""
        self._send(200, doc.encode())

    def _telemetry(self, rel: str):
        """Per-run telemetry page: the span-tree/metrics summary plus
        links to the raw artifacts (trace.json loads in Perfetto)."""
        p = self._safe_path(rel.rstrip("/"))
        if p is None or not os.path.isdir(p) or \
                not os.path.exists(os.path.join(p, "telemetry.json")):
            return self._send(404, b"no telemetry for this run",
                              "text/plain")
        from .telemetry import export as tel_export
        try:
            summary = tel_export.summarize(p)
        except Exception as e:  # noqa: BLE001 — corrupt file still 200s
            summary = f"telemetry.json unreadable: {e}"
        rel = rel.rstrip("/")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>telemetry — {html.escape(rel)}</title>
<style>body {{ font-family: sans-serif; margin: 2em; }}
pre {{ background: #f6f6f6; padding: 1em; overflow-x: auto; }}</style>
</head><body>
<p><a href="/">&larr; runs</a> &middot;
<a href="/files/{quote(rel)}/telemetry.json">telemetry.json</a> &middot;
<a href="/files/{quote(rel)}/trace.json">trace.json</a>
(open in <a href="https://ui.perfetto.dev">ui.perfetto.dev</a>)</p>
<pre>{html.escape(summary)}</pre></body></html>"""
        self._send(200, doc.encode())

    def _files(self, rel: str):
        p = self._safe_path(rel.rstrip("/"))
        if p is None or not os.path.exists(p):
            return self._send(404, b"not found", "text/plain")
        if os.path.isdir(p):
            entries = sorted(os.listdir(p))
            items = "".join(
                f'<li><a href="/files/{quote(os.path.join(rel.rstrip("/"), e))}'
                f'{"/" if os.path.isdir(os.path.join(p, e)) else ""}">'
                f"{html.escape(e)}</a></li>" for e in entries)
            doc = (f"<html><body><h2>{html.escape(rel)}</h2>"
                   f'<p><a href="/">&larr; runs</a></p><ul>{items}</ul>'
                   f"</body></html>")
            return self._send(200, doc.encode())
        ctype = {
            ".html": "text/html; charset=utf-8",
            ".json": "application/json",
            ".png": "image/png",
            ".svg": "image/svg+xml",
            ".log": "text/plain; charset=utf-8",
            ".edn": "text/plain; charset=utf-8",
        }.get(os.path.splitext(p)[1], "application/octet-stream")
        with open(p, "rb") as f:
            self._send(200, f.read(), ctype)

    def _zip(self, rel: str):
        p = self._safe_path(rel)
        if p is None or not os.path.isdir(p):
            return self._send(404, b"not found", "text/plain")
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for root, _dirs, files in os.walk(p):
                for fn in files:
                    full = os.path.join(root, fn)
                    z.write(full, os.path.relpath(full, os.path.dirname(p)))
        name = rel.replace(os.sep, "-") + ".zip"
        self._send(200, buf.getvalue(), "application/zip",
                   {"Content-Disposition": f'attachment; filename="{name}"'})

    def log_message(self, fmt, *args):  # quiet by default
        logger.debug("web: " + fmt, *args)


def serve(port: int = 8080, base: Optional[str] = None, *,
          host: str = "127.0.0.1",
          background: bool = False) -> ThreadingHTTPServer:
    """Serve the store dir (reference `web/serve!`).  Binds localhost by
    default — stored test maps can hold cluster details; pass
    host="0.0.0.0" explicitly to expose.  With background=True, runs in a
    daemon thread and returns the server (tests use this)."""
    handler = type("Handler", (_Handler,), {"base": base or store.BASE})
    srv = ThreadingHTTPServer((host, port), handler)
    logger.info("serving store %s on port %d", base or store.BASE, port)
    if background:
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return srv
