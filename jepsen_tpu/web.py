"""Web UI for browsing the test store.

Equivalent of the reference's `jepsen/src/jepsen/web.clj` (SURVEY.md §2.1,
§3.5): a small threaded HTTP server over the store directory — a run table
(name, timestamp, verdict), per-run file browsing, and zip download of a
whole run.  Stdlib-only (http.server), replacing the reference's http-kit.
"""

from __future__ import annotations

import html
import io
import json
import logging
import os
import threading
import time
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import quote, unquote, urlparse

from . import store

logger = logging.getLogger("jepsen.web")


def _run_summary(d: str) -> Dict[str, Any]:
    """Cheap summary of one run dir: verdict + attribution flags
    (deadline-expired, degraded-to-host) from results.json (fast path)
    or the .jepsen results block."""
    out: Dict[str, Any] = {
        "dir": d,
        "name": os.path.basename(os.path.dirname(d)),
        "timestamp": os.path.basename(d),
        "valid?": "?",
        "error": None,
        "degraded": None,
        "deadline": False,
    }
    rj = os.path.join(d, "results.json")
    try:
        if os.path.exists(rj):
            with open(rj) as f:
                res = json.load(f)
        else:
            res = store.load(d).get("results")
        if res:
            from .campaign.core import result_flags

            out["valid?"] = res.get("valid?", "?")
            out.update(result_flags(res))
    except Exception:  # noqa: BLE001 — a corrupt run still gets listed
        out["valid?"] = "corrupt"
    return out


#: shared badge CSS — every page that renders verdict cells embeds it
# /live pages stop auto-refreshing after this much write silence —
# crashed runs never emit "end", and the refresh re-parses the whole
# stream server-side each time
_LIVE_STALE_S = 300.0

_BADGE_CSS = """
.b { padding: 1px 7px; border-radius: 3px; white-space: nowrap; }
.b-true { background: #9ce29c; }
.b-false { background: #f2a3a3; }
.b-unknown { background: #ffd37a; }
.b-deadline { background: #ffb347; border: 1px solid #c07a2d; }
.b-degraded { background: #a8c8f0; border: 1px solid #5a82b4;
              font-size: 85%; margin-left: 4px; }
.b-witness { background: #e6d5f5; border: 1px solid #9a6fc0;
             font-size: 85%; margin-left: 4px; }
.b-other { background: #ddd; }
"""


def _verdict_badges(v: Any, error: Any = None, degraded: Any = None,
                    deadline: Any = None) -> str:
    """Verdict badge HTML: unknown-because-deadline and degraded-to-
    host runs get DISTINCT badges so they're tellable apart from plain
    unknowns/valids at a glance (ROADMAP open item).  `deadline` takes
    a precomputed flag (campaign index records carry one); when absent
    it is derived from `error` with the canonical marker."""
    if deadline is None:
        from .resilience import DEADLINE_ERROR

        deadline = isinstance(error, str) and DEADLINE_ERROR in error
    cls = {"True": "b-true", "False": "b-false",
           "unknown": "b-unknown"}.get(str(v), "b-other")
    label = str(v)
    if deadline:
        cls = "b-deadline"
        label = f"{v} · deadline"
    out = f'<span class="b {cls}">{html.escape(label)}</span>'
    if degraded:
        out += (f'<span class="b b-degraded" title="device pipeline '
                f'degraded">{html.escape(str(degraded))}</span>')
    return out


def _verdict_cell(v: Any, error: Any = None, degraded: Any = None,
                  deadline: Any = None) -> str:
    return ('<td style="text-align:center">'
            f"{_verdict_badges(v, error, degraded, deadline)}</td>")


def _model_anomaly_html(e: Any, name: str = "") -> str:
    """Model-specific witness evidence (the invariants family + the
    queue family): bank bad-reads, long-fork/write-skew pairs, session
    violations, and kafka lost/duplicate/stale messages get readable
    renderings; anything unrecognized falls back to JSON."""
    if name == "lost-write" and isinstance(e, (list, tuple)) \
            and len(e) == 3:
        k, off, v = e
        return (f"<li>message <code>{html.escape(json.dumps(v))}</code> "
                f"on key <code>{html.escape(json.dumps(k))}</code>, acked "
                f"at offset <b>{off}</b>, was never polled although later "
                f"offsets of that key were — a lost write</li>")
    if name == "duplicate" and isinstance(e, (list, tuple)) \
            and len(e) == 3:
        k, v, offs = e
        return (f"<li>message <code>{html.escape(json.dumps(v))}</code> "
                f"on key <code>{html.escape(json.dumps(k))}</code> was "
                f"delivered at {len(offs)} distinct offsets "
                f"<code>{html.escape(json.dumps(list(offs)))}</code> — a "
                f"duplicate delivery</li>")
    if name == "inconsistent-offsets" and isinstance(e, (list, tuple)) \
            and len(e) == 3:
        k, off, vals = e
        return (f"<li>offset <b>{off}</b> of key "
                f"<code>{html.escape(json.dumps(k))}</code> was observed "
                f"holding {len(vals)} different values "
                f"<code>{html.escape(json.dumps(list(vals)))}</code></li>")
    if name == "stale-consumer-group" and isinstance(e, dict) \
            and "generation" in e:
        return (f"<li>consumer group generation <b>{e['generation']}</b> "
                f"re-polled key "
                f"<code>{html.escape(json.dumps(e.get('key')))}</code> from "
                f"offset <b>{e.get('start')}</b> {e.get('polls')} times "
                f"while the log moved past its window ({e.get('behind')} "
                f"poll(s) behind the key's head) — a stale consumer "
                f"group</li>")
    if not isinstance(e, dict):
        return f"<pre>{html.escape(json.dumps(e, indent=1))}</pre>"
    if "why" in e:  # long-fork / write-skew carry their own sentence
        extra = ""
        if e.get("keys") is not None:
            extra = f" <code>keys={html.escape(json.dumps(e['keys']))}</code>"
        return (f"<li>{html.escape(str(e['why']))}{extra}</li>")
    if "expected-total" in e:  # bank bad-read
        neg = (f"; negative balances on accounts "
               f"{html.escape(json.dumps(e['negative']))}"
               if e.get("negative") else "")
        return (f"<li>read at op {e.get('op-index')} (process "
                f"{e.get('process')}) summed to <b>{e.get('total')}</b>, "
                f"expected <b>{e.get('expected-total')}</b>{neg}</li>")
    if "key" in e and "process" in e and ("rank" in e or "read" in e
                                          or "wrote" in e):
        # session-guarantee violation (vectorized or walker entry)
        what = e.get("kind") or ("write" if "wrote" in e else "read")
        detail = ", ".join(
            f"{k}={json.dumps(e[k])}" for k in
            ("read", "wrote", "rank", "after-reading", "after-writing",
             "cross-key-dependency", "cross-key-prior-write")
            if k in e)
        return (f"<li>process {e.get('process')}, op {e.get('op')}: "
                f"{html.escape(what)} of key "
                f"<code>{html.escape(json.dumps(e.get('key')))}</code> "
                f"broke the guarantee ({html.escape(detail)})</li>")
    return f"<pre>{html.escape(json.dumps(e, indent=1))}</pre>"


class _Handler(BaseHTTPRequestHandler):
    base: str = store.BASE  # overridden per-server
    verifier = None         # VerifierService when served with --ingest
    fleet = None            # FleetCoordinator when served via fleet serve

    # -- helpers ----------------------------------------------------------

    def _send(self, code: int, content: bytes,
              ctype: str = "text/html; charset=utf-8",
              extra: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(content)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(content)

    def _send_json(self, code: int, doc: Any) -> None:
        self._send(code, json.dumps(doc, indent=1, sort_keys=True,
                                    default=str).encode(),
                   "application/json")

    def _read_body(self) -> bytes:
        try:
            n = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            n = 0
        return self.rfile.read(n) if n > 0 else b""

    def _safe_path(self, rel: str) -> Optional[str]:
        """Resolve a store-relative path, refusing traversal outside it."""
        base = os.path.realpath(self.base)
        p = os.path.realpath(os.path.join(base, rel))
        if p == base or p.startswith(base + os.sep):
            return p
        return None

    # -- routes -----------------------------------------------------------

    def do_GET(self):  # noqa: N802 (stdlib API)
        try:
            path = unquote(urlparse(self.path).path)
            if path in ("/", "/index.html"):
                return self._index()
            if path == "/metrics":
                return self._metrics()
            if path.startswith("/files/"):
                return self._files(path[len("/files/"):])
            if path.startswith("/zip/"):
                return self._zip(path[len("/zip/"):])
            if path.startswith("/telemetry/"):
                return self._telemetry(path[len("/telemetry/"):])
            if path.startswith("/live/"):
                return self._live(path[len("/live/"):])
            if path.startswith("/run/"):
                rel = path[len("/run/"):]
                if rel.rstrip("/").endswith("/witness"):
                    return self._witness(
                        rel.rstrip("/")[:-len("/witness")])
                return self._run(rel)
            if path in ("/campaigns", "/campaigns/"):
                return self._campaigns()
            if path.startswith("/campaign/"):
                rel = path[len("/campaign/"):].rstrip("/")
                if rel.endswith("/live"):
                    return self._campaign_live(rel[:-len("/live")])
                if rel.endswith("/witness-diff"):
                    return self._witness_diff(rel[:-len("/witness-diff")])
                if rel.endswith("/trend"):
                    return self._trend(rel[:-len("/trend")])
                if rel.endswith("/forensics"):
                    return self._forensics(rel[:-len("/forensics")])
                return self._campaign(rel)
            if path.startswith("/profile/"):
                return self._profile(path[len("/profile/"):])
            if path.startswith("/verdict/"):
                return self._verdict_json(path[len("/verdict/"):])
            if path in ("/verifier", "/verifier/"):
                return self._verifier_list()
            if path.startswith("/verifier/"):
                return self._verifier_session(path[len("/verifier/"):])
            if path in ("/alerts", "/alerts/"):
                return self._alerts_page()
            if path in ("/fleet", "/fleet/"):
                return self._fleet_page()
            if path == "/fleet/status":
                return self._fleet_status()
            if path in ("/fleet/cache", "/fleet/cache/"):
                return self._fleet_cache("")
            if path.startswith("/fleet/cache/"):
                return self._fleet_cache(
                    path[len("/fleet/cache/"):].strip("/"))
            if path.startswith("/timeline/"):
                return self._timeline(path[len("/timeline/"):])
            self._send(404, b"not found", "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            logger.exception("web handler error")
            self._send(500, f"error: {e}".encode(), "text/plain")

    def do_POST(self):  # noqa: N802 (stdlib API)
        """The verifier ingest surface (docs/VERIFIER.md) and the
        fleet control plane (docs/FLEET.md) — each only routed when
        the server was started with that service attached (``cli
        serve --ingest`` / ``cli fleet serve``).  Every POST runs
        under the request's ``Jepsen-Trace`` context (ISSUE 14): the
        header, when present, is parsed and installed thread-locally
        so the coordinator, verifier, and artifact store stitch the
        request onto its run's distributed trace."""
        from .telemetry import spans as spans_mod

        ctx = spans_mod.parse_trace_header(
            self.headers.get(spans_mod.TRACE_HEADER))
        with spans_mod.trace_scope(ctx):
            self._do_post()

    def _do_post(self):
        try:
            parsed = urlparse(self.path)
            path = unquote(parsed.path)
            if path.startswith("/fleet/artifact/"):
                return self._fleet_artifact(
                    path[len("/fleet/artifact/"):].strip("/"),
                    parsed.query or "")
            if path.startswith("/fleet/"):
                return self._fleet_post(path[len("/fleet/"):].strip("/"))
            if self.verifier is None:
                return self._send_json(
                    404, {"error": "no verifier service (start with "
                          "`serve --ingest`)"})
            if path.startswith("/ingest/"):
                name = path[len("/ingest/"):].strip("/")
                cursor = None
                for part in (parsed.query or "").split("&"):
                    if part.startswith("cursor="):
                        try:
                            cursor = int(part[len("cursor="):])
                        except ValueError:
                            return self._send_json(
                                400, {"error": "bad cursor"})
                code, doc = self.verifier.ingest(
                    name, self._read_body(), cursor=cursor)
                return self._send_json(code, doc)
            if path.startswith("/verifier/"):
                rest = path[len("/verifier/"):].strip("/")
                name, _, verb = rest.partition("/")
                if verb == "open":
                    cfg = None
                    body = self._read_body()
                    if body.strip():
                        try:
                            cfg = json.loads(body)
                        except ValueError:
                            return self._send_json(
                                400, {"error": "bad config json"})
                    code, doc = self.verifier.open(name, cfg)
                elif verb == "seal":
                    code, doc = self.verifier.seal(name)
                elif verb == "compact":
                    code, doc = self.verifier.compact(name)
                elif verb == "expire":
                    code, doc = self.verifier.expire(name)
                else:
                    code, doc = 404, {"error": f"unknown verb {verb!r}"}
                return self._send_json(code, doc)
            self._send_json(404, {"error": "not found"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            logger.exception("web POST handler error")
            self._send_json(500, {"error": str(e)})

    def _index(self):
        from .telemetry import alerts as alerts_mod
        from .telemetry import stream as tel_stream

        rows = []
        for d in store.tests(base=self.base):
            s = _run_summary(d)
            rel = os.path.relpath(d, self.base)
            links = []
            if os.path.exists(os.path.join(d, "telemetry.json")):
                links.append(f'<a href="/telemetry/{quote(rel)}">trace</a>')
            if tel_stream.events_path(d):
                # in-flight (or killed) streaming runs have events but
                # possibly no exported telemetry yet — the live view is
                # how those are inspected at all
                links.append(f'<a href="/live/{quote(rel)}">live</a>')
            tel = f"<td>{' '.join(links)}</td>"
            rows.append(
                "<tr>"
                f'<td><a href="/run/{quote(rel)}">{html.escape(s["name"])}</a></td>'
                f'<td><a href="/files/{quote(rel)}/">{html.escape(s["timestamp"])}</a></td>'
                f"{_verdict_cell(s['valid?'], s['error'], s['degraded'], s['deadline'])}"
                f"{tel}"
                f'<td><a href="/zip/{quote(rel)}">zip</a></td>'
                "</tr>")
        links = []
        if os.path.isdir(os.path.join(self.base, "campaigns")):
            links.append('<a href="/campaigns">campaigns</a>')
        if self.verifier is not None or \
                os.path.isdir(os.path.join(self.base, "verifier")):
            links.append('<a href="/verifier">verifier</a>')
        if self.fleet is not None:
            links.append('<a href="/fleet">fleet</a>')
        if os.path.exists(alerts_mod.alerts_path(self.base)):
            links.append('<a href="/alerts">alerts</a>')
        links.append('<a href="/metrics">metrics</a>')
        camp = "<p>" + " &middot; ".join(links) + "</p>"
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>jepsen-tpu</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<h1>jepsen-tpu runs</h1>{camp}
<table><tr><th>test</th><th>time</th><th>valid?</th><th>telemetry</th><th>download</th></tr>
{"".join(rows)}</table></body></html>"""
        self._send(200, doc.encode())

    def _run(self, rel: str):
        """Per-run page: the verdict (with deadline/degraded badges),
        the results map, and links to the artifacts."""
        rel = rel.rstrip("/")
        p = self._safe_path(rel)
        if p is None or not os.path.isdir(p):
            return self._send(404, b"no such run", "text/plain")
        s = _run_summary(p)
        results = None
        rj = os.path.join(p, "results.json")
        if os.path.exists(rj):
            try:
                with open(rj) as f:
                    results = json.dumps(json.load(f), indent=1,
                                         sort_keys=True)
            except (OSError, ValueError) as e:
                results = f"results.json unreadable: {e}"
        tel = (f'&middot; <a href="/telemetry/{quote(rel)}">telemetry</a> '
               if os.path.exists(os.path.join(p, "telemetry.json"))
               else "")
        from .telemetry import stream as tel_stream
        live = (f'&middot; <a href="/live/{quote(rel)}">live</a> '
                if tel_stream.events_path(p) else "")
        wit = (f'&middot; <a href="/run/{quote(rel)}/witness">witness</a> '
               if os.path.exists(os.path.join(p, "witness.json"))
               else "")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>{html.escape(rel)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
pre {{ background: #f6f6f6; padding: 1em; overflow-x: auto; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 3px 8px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/">&larr; runs</a></p>
<h2>{html.escape(s["name"])} <small>{html.escape(s["timestamp"])}</small>
{_verdict_badges(s["valid?"], s["error"], s["degraded"], s["deadline"])}</h2>
<p><a href="/files/{quote(rel)}/">files</a> {tel}{live}{wit}&middot;
<a href="/zip/{quote(rel)}">zip</a></p>
{self._warehouse_spans_html(rel)}
<pre>{html.escape(results or "no results.json (run still in flight, "
                             "or it crashed before analysis)")}</pre>
</body></html>"""
        self._send(200, doc.encode())

    def _warehouse_spans_html(self, rel: str) -> str:
        """Span totals for one run from the warehouse's ``run_spans``
        table (when it's been ingested) — the run page then shows its
        span profile without re-parsing telemetry.json per request."""
        try:
            from .telemetry import warehouse as wmod

            wh = wmod.open_if_exists(self.base)
            if wh is None:
                return ""
            rows = wh.run_spans(rel)
        except Exception:  # noqa: BLE001 — decorative, never 500 a page
            return ""
        if not rows:
            return ""
        trs = "".join(
            f"<tr><td><code>{html.escape(name)}</code></td>"
            f"<td>{total:.4f}</td><td>{count}</td></tr>"
            for name, total, count in rows)
        return ("<h3>spans <small>(warehouse)</small></h3>"
                "<table><tr><th>span</th><th>total s</th><th>count</th>"
                f"</tr>{trs}</table>")

    def _witness(self, rel: str):
        """Minimal-witness page (docs/MINIMIZE.md): the shrunk failing
        history op by op, then each surviving anomaly's explained cycle
        — every edge rendered with the Explainer's evidence (key,
        values, the "why" sentence)."""
        from .minimize import load_witness

        rel = rel.rstrip("/")
        p = self._safe_path(rel)
        if p is None or not os.path.isdir(p):
            return self._send(404, b"no such run", "text/plain")
        w = load_witness(p)
        if w is None:
            return self._send(404, b"no witness for this run (run "
                              b"`cli shrink <dir>` first)", "text/plain")
        op_rows = []
        for op in w["history"]:
            err = op.error if op.error is not None else ""
            op_rows.append(
                f"<tr><td>{op.index}</td><td>{html.escape(str(op.process))}"
                f"</td><td>{html.escape(str(op.type))}</td>"
                f"<td>{html.escape(str(op.f))}</td>"
                f"<td><code>{html.escape(json.dumps(op.value))}</code></td>"
                f"<td>{html.escape(str(err))}</td></tr>")
        anom_html = []
        for name, entries in sorted((w.get("anomalies") or {}).items()):
            anom_html.append(f"<h3><code>{html.escape(name)}</code></h3>")
            items: list = []  # consecutive <li> fragments -> one <ul>

            def flush_items():
                if items:
                    anom_html.append(f"<ul>{''.join(items)}</ul>")
                    items.clear()

            for e in entries if isinstance(entries, list) else []:
                cyc = e.get("cycle") if isinstance(e, dict) else None
                if not cyc:
                    frag = _model_anomaly_html(e, name)
                    if frag.startswith("<li>"):
                        items.append(frag)
                    else:
                        flush_items()
                        anom_html.append(frag)
                    continue
                flush_items()
                steps = []
                for edge in cyc:
                    why = edge.get("why") or json.dumps(
                        {k: v for k, v in edge.items() if k != "rel"})
                    steps.append(
                        f"<li><b>{html.escape(str(edge.get('rel')))}"
                        f"</b> — {html.escape(str(why))}</li>")
                anom_html.append(f"<ol>{''.join(steps)}</ol>")
            flush_items()
        windows_html = ""
        fw = w.get("fault-windows") or []
        if fw:
            def _wcell(win, k):
                v = win.get(k)
                return "&mdash;" if v is None else html.escape(str(v))

            rows = "".join(
                f"<tr><td><code>{html.escape(str(win.get('f')))}</code>"
                f"</td><td>{win.get('span', ['?', '?'])[0]}&ndash;"
                f"{win.get('span', ['?', '?'])[1]}</td>"
                f"<td>{len(win.get('ops') or ())} ops</td>"
                f"<td>{_wcell(win, 'pos')}</td>"
                f"<td><code>{_wcell(win, 'digest')}</code></td>"
                f"<td>{_wcell(win, 'host')}</td>"
                f"<td>{_wcell(win, 'kept')}</td></tr>"
                for win in fw)
            windows_html = (
                "<h2>surviving fault windows</h2>"
                "<p>the nemesis-schedule ddmin kept these windows "
                "(reproduction-necessary or overlapping the witness "
                "ops); spans are source-history op indices; scheduled "
                "windows carry their schedule position/digest and the "
                "executing host (the cross-host attribution)</p>"
                "<table><tr><th>fault</th><th>span</th><th>ops</th>"
                "<th>pos</th><th>digest</th><th>host</th><th>kept</th>"
                f"</tr>{rows}</table>")
        quant = " ".join(
            f"{k.replace('_', ' ')}={w[k]}" for k in
            ("probe_p50_s", "probe_p95_s") if w.get(k) is not None)
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>witness — {html.escape(rel)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 3px 8px; }}
pre {{ background: #f6f6f6; padding: 1em; overflow-x: auto; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/run/{quote(rel)}">&larr; run</a></p>
<h1>minimal witness
{_verdict_badges(w.get("valid?"))}</h1>
<p>{w.get("ops")} ops (shrunk from {w.get("source-ops")}) &middot;
anomalies: <code>{html.escape(", ".join(w.get("anomaly-types") or ()))}
</code> &middot; checker {html.escape(str(w.get("checker")))} &middot;
{w.get("rounds")} rounds / {w.get("probes")} probes {html.escape(quant)}
&middot; digest <code>{html.escape(str(w.get("digest")))}</code></p>
<table><tr><th>#</th><th>process</th><th>type</th><th>f</th>
<th>value</th><th>error</th></tr>{"".join(op_rows)}</table>
<h2>evidence</h2>
{"".join(anom_html) or "<p>(no anomaly evidence reported)</p>"}
{windows_html}
<p><a href="/files/{quote(rel)}/witness.json">witness.json</a> &middot;
<a href="/files/{quote(rel)}/witness.jsonl">witness.jsonl</a></p>
</body></html>"""
        self._send(200, doc.encode())

    def _autoingest(self) -> None:
        """When a warehouse exists, incrementally ingest any campaign
        ledger growth before a campaign page renders
        (docs/TELEMETRY.md): the byte cursors make an unchanged ledger
        a no-op, and the Index fast paths then answer from indexed SQL
        instead of re-parsing the jsonl per request.  Ledgers ONLY —
        everything these pages render comes from campaign_records;
        run-dir/event ingest (which stats every run dir in the store)
        stays with `cli obs ingest`.  No warehouse -> no-op (the read
        surfaces never create one implicitly)."""
        try:
            from .telemetry import warehouse as wmod

            wh = wmod.open_if_exists(self.base)
            if wh is None:
                return
            cdir = os.path.join(self.base, "campaigns")
            if os.path.isdir(cdir):
                for fn in sorted(os.listdir(cdir)):
                    if fn.endswith(".jsonl"):
                        wh.ingest_ledger(os.path.join(cdir, fn),
                                         self.base)
        except Exception:  # noqa: BLE001 — rendering must survive
            logger.debug("warehouse auto-ingest failed", exc_info=True)

    def _metrics(self):
        """Prometheus text exposition (docs/TELEMETRY.md): the live
        registry's counters/gauges/histograms, federated fleet worker
        series (ISSUE 14: ``host=``-labeled, retired with worker
        liveness), campaign heartbeat freshness, and warehouse rollup
        gauges."""
        from .telemetry import prometheus as prom

        body = prom.exposition(base=self.base, fleet=self.fleet)
        self._send(200, body.encode(), prom.CONTENT_TYPE)

    def _campaigns(self):
        """Campaign list: every jsonl ledger under <store>/campaigns."""
        from .campaign.index import Index

        self._autoingest()
        cdir = os.path.join(self.base, "campaigns")
        rows = []
        if os.path.isdir(cdir):
            for fn in sorted(os.listdir(cdir)):
                if not fn.endswith(".jsonl"):
                    continue
                name = fn[:-len(".jsonl")]
                try:
                    idx = Index(os.path.join(cdir, fn))
                    c = idx.verdict_counts()
                    n_reg = len(idx.regressions())
                except Exception:  # noqa: BLE001 — list corrupt ledgers too
                    c, n_reg = {}, 0
                reg = (f'<td style="background:#f2a3a3">{n_reg}</td>'
                       if n_reg else "<td>0</td>")
                rows.append(
                    "<tr>"
                    f'<td><a href="/campaign/{quote(name)}">'
                    f"{html.escape(name)}</a></td>"
                    f"<td>{c.get('true', '?')}</td>"
                    f"<td>{c.get('false', '?')}</td>"
                    f"<td>{c.get('unknown', '?')}</td>"
                    f"<td>{c.get('degraded', '?')}</td>"
                    f"<td>{c.get('deadline', '?')}</td>"
                    f"{reg}</tr>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>campaigns</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/">&larr; runs</a></p><h1>campaigns</h1>
<table><tr><th>campaign</th><th>ok</th><th>invalid</th><th>unknown</th>
<th>degraded</th><th>deadline</th><th>regressions</th></tr>
{"".join(rows)}</table></body></html>"""
        self._send(200, doc.encode())

    def _campaign(self, name: str):
        """Campaign dashboard: the workload × fault × seed verdict grid
        (cells link to the run pages; degraded / deadline-expired runs
        carry distinct badges), plus regressions and span aggregates."""
        from .campaign.index import Index

        self._autoingest()
        name = unquote(name).rstrip("/")
        path = self._safe_path(os.path.join("campaigns", name + ".jsonl"))
        if path is None or not os.path.exists(path):
            return self._send(404, b"no such campaign", "text/plain")
        idx = Index(path)
        # warehouse-backed when fresh: the grid, the regression list,
        # and the span aggregates below then never parse the jsonl
        latest = idx.latest_by_run()
        seeds = sorted({r.get("seed") for r in latest.values()
                        if r.get("seed") is not None})
        grid: Dict[tuple, Dict[Any, Dict[str, Any]]] = {}
        for r in latest.values():
            grid.setdefault((str(r.get("workload")), str(r.get("fault"))),
                            {})[r.get("seed")] = r
        rows = []
        for (wl, fl), cells in sorted(grid.items()):
            tds = []
            for s in seeds:
                r = cells.get(s)
                if r is None:
                    tds.append("<td>-</td>")
                    continue
                badge = _verdict_badges(
                    r.get("valid?"), r.get("error"), r.get("degraded"),
                    r.get("deadline"))
                if r.get("dir"):
                    badge = (f'<a href="/run/{quote(str(r["dir"]))}">'
                             f"{badge}</a>")
                w = r.get("witness")
                if isinstance(w, dict) and w.get("ops") and r.get("dir"):
                    # the auto-shrink witness column: invalid cells
                    # link straight to their minimal witness
                    badge += (f' <a class="b b-witness" title="minimal '
                              f'witness ({w["ops"]} ops)" '
                              f'href="/run/{quote(str(r["dir"]))}/witness">'
                              f'w:{w["ops"]}</a>')
                if r.get("run") and r.get("trace"):
                    # trace-stamped cells (ISSUE 14) link to their
                    # stitched cross-host waterfall
                    badge += (f' <a class="b b-other" title="cross-'
                              f'host timeline" href="/timeline/'
                              f'{quote(str(r["run"]))}">tl</a>')
                tds.append(f'<td style="text-align:center">{badge}</td>')
            rows.append(f"<tr><td>{html.escape(wl)}</td>"
                        f"<td>{html.escape(fl)}</td>{''.join(tds)}</tr>")
        regs = idx.regressions()
        reg_html = ""
        if regs:
            items = "".join(
                f"<li><code>{html.escape(str(r['key']))}</code>: "
                f"{html.escape(str(r['from']))} &rarr; "
                f"{html.escape(str(r['to']))} ({html.escape(str(r.get('when') or ''))})</li>"
                for r in regs)
            reg_html = (f'<h2 style="color:#b03030">regressions</h2>'
                        f"<ul>{items}</ul>")
        stats = idx.span_stats()
        stat_rows = "".join(
            f"<tr><td>{html.escape(n)}</td><td>{st['count']}</td>"
            f"<td>{st['p50']:.4f}</td><td>{st['p95']:.4f}</td>"
            f"<td>{st['max']:.4f}</td></tr>"
            for n, st in list(stats.items())[:24])
        stat_html = (f"<h2>checker span durations (s)</h2><table>"
                     f"<tr><th>span</th><th>n</th><th>p50</th><th>p95</th>"
                     f"<th>max</th></tr>{stat_rows}</table>"
                     if stat_rows else "")
        head = "".join(f"<th>s{s}</th>" for s in seeds)
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>campaign {html.escape(name)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
a {{ text-decoration: none; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/campaigns">&larr; campaigns</a> &middot;
<a href="/campaign/{quote(name)}/live">live</a> &middot;
<a href="/campaign/{quote(name)}/witness-diff">witness diff</a> &middot;
<a href="/campaign/{quote(name)}/trend">trend</a></p>
<h1>campaign {html.escape(name)}</h1>
<table><tr><th>workload</th><th>fault</th>{head}</tr>
{"".join(rows)}</table>
{reg_html}{stat_html}</body></html>"""
        self._send(200, doc.encode())

    def _telemetry(self, rel: str):
        """Per-run telemetry page: the span-tree/metrics summary plus
        links to the raw artifacts (trace.json loads in Perfetto)."""
        p = self._safe_path(rel.rstrip("/"))
        if p is None or not os.path.isdir(p) or \
                not os.path.exists(os.path.join(p, "telemetry.json")):
            return self._send(404, b"no telemetry for this run",
                              "text/plain")
        from .telemetry import export as tel_export
        doc_j = None
        try:
            with open(os.path.join(p, "telemetry.json")) as f:
                doc_j = json.load(f)
            summary = tel_export.summarize(p, doc=doc_j)
        except Exception as e:  # noqa: BLE001 — corrupt file still 200s
            summary = f"telemetry.json unreadable: {e}"
        # latency percentiles from the fixed-bucket histograms
        # (ROADMAP telemetry open item: p50/p95/p99, not bucket dumps)
        hist_rows = []
        try:
            for h in ((doc_j or {}).get("metrics") or {}).get(
                    "histograms", []):
                if not h.get("count"):
                    continue
                quant = tel_export.histogram_quantiles(
                    h.get("buckets") or [], h.get("counts") or [])
                lbl = ",".join(f"{k}={v}" for k, v in
                               sorted((h.get("labels") or {}).items()))
                hist_rows.append(
                    f"<tr><td><code>{html.escape(h['name'])}"
                    f"{{{html.escape(lbl)}}}</code></td>"
                    f"<td>{h['count']}</td><td>{h['sum']:.6g}</td>"
                    + "".join(f"<td>{quant.get(k, '')}</td>"
                              for k in ("p50", "p95", "p99"))
                    + "</tr>")
        except Exception:  # noqa: BLE001 — percentiles are best-effort
            hist_rows = []
        hist_html = ""
        if hist_rows:
            hist_html = (
                "<h2>latency percentiles</h2><table>"
                "<tr><th>histogram</th><th>n</th><th>sum</th>"
                "<th>p50</th><th>p95</th><th>p99</th></tr>"
                + "".join(hist_rows) + "</table>")
        rel = rel.rstrip("/")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>telemetry — {html.escape(rel)}</title>
<style>body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 3px 8px; }}
pre {{ background: #f6f6f6; padding: 1em; overflow-x: auto; }}</style>
</head><body>
<p><a href="/">&larr; runs</a> &middot;
<a href="/files/{quote(rel)}/telemetry.json">telemetry.json</a> &middot;
<a href="/files/{quote(rel)}/trace.json">trace.json</a>
(open in <a href="https://ui.perfetto.dev">ui.perfetto.dev</a>)</p>
{hist_html}<pre>{html.escape(summary)}</pre></body></html>"""
        self._send(200, doc.encode())

    def _live(self, rel: str):
        """Live run view (the flight recorder, docs/TELEMETRY.md): the
        streamed events.jsonl rendered as progress lines + the replayed
        end state (open-span chain, resource gauges, counters).  Auto-
        refreshes while the run is in flight; a crashed/killed run
        shows its partial trace — this page exists precisely for runs
        that never reached store.save_1."""
        from .telemetry import stream as tel_stream

        rel = rel.rstrip("/")
        p = self._safe_path(rel)
        if p is None or not os.path.isdir(p):
            return self._send(404, b"no such run", "text/plain")
        path = tel_stream.events_path(p)
        if path is None:
            return self._send(404, b"no events.jsonl for this run (run "
                              b"with --telemetry to stream)", "text/plain")
        evs = tel_stream.read_events(path)
        st = tel_stream.replay(evs)
        t0 = st["t0"]
        lines = [tel_stream.render_line(e, t0) for e in evs[-60:]]
        # stop auto-refreshing once the stream goes quiet: a crashed
        # run never emits "end", and a forgotten tab re-parsing an
        # unbounded events.jsonl every 2 s forever is pure waste
        try:
            stale = time.time() - os.path.getmtime(path) > _LIVE_STALE_S
        except OSError:
            stale = True
        refresh = ("" if st["ended"] or stale else
                   '<meta http-equiv="refresh" content="2">')
        if st["ended"]:
            status = '<span class="b b-true">ended</span>'
        elif st["open"]:
            chain = " &gt; ".join(html.escape(str(s["name"]))
                                  for s in st["open"])
            badge = ('<span class="b b-other">stream idle</span>'
                     if stale else
                     '<span class="b b-unknown">in flight</span>')
            status = f"{badge} open: <code>{chain}</code>"
        else:
            status = '<span class="b b-other">stream truncated</span>'
        if not st["ended"] and stale:
            status += (f" &middot; no events for &gt;{_LIVE_STALE_S:.0f}s"
                       " — auto-refresh off (reload to re-check)")
        counters = "".join(
            f"<tr><td><code>{html.escape(k)}</code></td><td>{v}</td></tr>"
            for k, v in sorted(st["counters"].items()))
        gauges = "".join(
            f"<tr><td><code>{html.escape(k)}</code></td><td>{v}</td></tr>"
            for k, v in sorted(st["gauges"].items()))
        metric_html = ""
        if counters or gauges:
            metric_html = (
                "<h2>metrics (latest streamed values)</h2>"
                "<table><tr><th>instrument</th><th>value</th></tr>"
                + counters + gauges + "</table>")
        res = ""
        if st["faults"] or st["retries"] or st["fallbacks"] or \
                st["deadlines"]:
            res = (f"<p>resilience: {st['faults']} faults, "
                   f"{st['retries']} retries, {st['fallbacks']} "
                   f"fallbacks, {st['deadlines']} deadline expiries</p>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
{refresh}<title>live — {html.escape(rel)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 3px 8px; }}
pre {{ background: #f6f6f6; padding: 1em; overflow-x: auto; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/run/{quote(rel)}">&larr; run</a> &middot;
<a href="/files/{quote(rel)}/">files</a></p>
<h1>live — {html.escape(st["meta"].get("name") or rel)}</h1>
<p>{status} &middot; {st["events"]} events, {st["spans_closed"]} spans
closed</p>{res}{metric_html}
<h2>event tail</h2>
<pre>{html.escape(chr(10).join(lines))}</pre>
</body></html>"""
        self._send(200, doc.encode())

    def _campaign_live(self, name: str):
        """Live fleet dashboard: the scheduler's heartbeat state file —
        which runs each worker holds right now, done/total progress —
        next to the latest indexed verdicts.  Auto-refreshes until the
        campaign's heartbeat says finished."""
        from .campaign.core import live_path
        from .telemetry import Heartbeat

        name = unquote(name).rstrip("/")
        path = self._safe_path(os.path.relpath(
            live_path(name, self.base), self.base))
        hb = Heartbeat.load(path) if path else None
        if hb is None:
            return self._send(404, b"no live state for this campaign "
                              b"(never run, or pre-flight-recorder)",
                              "text/plain")
        # same stale guard as /live/<rel>: a killed scheduler never
        # writes finished=True, and its dashboard must not refresh
        # forever
        upd = hb.get("updated")
        stale = (not isinstance(upd, (int, float))
                 or time.time() - upd > _LIVE_STALE_S)
        refresh = ("" if hb.get("finished") or stale else
                   '<meta http-equiv="refresh" content="2">')
        total = hb.get("total") or 0
        done = hb.get("done") or 0
        pct = f" ({100.0 * done / total:.0f}%)" if total else ""
        wrows = []
        now = time.time()
        for wid, w in sorted((hb.get("workers") or {}).items()):
            age = (f"{now - w['since']:.1f}s"
                   if isinstance(w.get("since"), (int, float)) else "?")
            wrows.append(
                f"<tr><td>{html.escape(wid)}</td>"
                f"<td><code>{html.escape(str(w.get('run')))}</code></td>"
                f"<td>{html.escape(str(w.get('workload')))}</td>"
                f"<td>{html.escape(str(w.get('fault')))}</td>"
                f"<td>{html.escape(str(w.get('seed')))}</td>"
                f"<td>{html.escape(str(w.get('slot')))}</td>"
                f"<td>{age}</td></tr>")
        workers = ("<table><tr><th>worker</th><th>run</th><th>workload</th>"
                   "<th>fault</th><th>seed</th><th>slot</th><th>running "
                   "for</th></tr>" + "".join(wrows) + "</table>"
                   if wrows else "<p>(no runs in flight)</p>")
        last = hb.get("last") or {}
        last_html = ""
        if last.get("run"):
            last_html = (f"<p>last finished: <code>"
                         f"{html.escape(str(last['run']))}</code> "
                         f"{_verdict_badges(last.get('valid?'))}</p>")
        state = ("finished" if hb.get("finished")
                 else "stalled? (heartbeat idle — auto-refresh off)"
                 if stale else "running")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
{refresh}<title>live — campaign {html.escape(name)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/campaign/{quote(name)}">&larr; campaign</a></p>
<h1>campaign {html.escape(name)} — live</h1>
<p>{state}: {done}/{total} runs done{pct}</p>
{last_html}<h2>in flight</h2>{workers}
</body></html>"""
        self._send(200, doc.encode())

    def _trend(self, name: str):
        """Span-duration trend page: per span site, the p95 per
        campaign generation (the `span_trend` query, warehouse-backed
        when fresh) — the data `cli obs gate` turns into a CI check."""
        from .campaign.index import Index

        self._autoingest()
        name = unquote(name).rstrip("/")
        path = self._safe_path(os.path.join("campaigns", name + ".jsonl"))
        if path is None or not os.path.exists(path):
            return self._send(404, b"no such campaign", "text/plain")
        idx = Index(path)
        stats = idx.span_stats()
        trends: Dict[str, Dict[str, float]] = {}
        for span in stats:
            for g, p95 in idx.span_trend(span):
                trends.setdefault(span, {})[g] = p95
        # column order must be chronological across ALL spans — gens
        # are run_campaign UTC timestamps, so a lexical sort IS time
        # order ("?" last); per-span first-seen order would scramble
        # columns when spans cover different generation subsets and
        # the >25% highlight would compare non-adjacent generations
        gens = sorted({g for t in trends.values() for g in t},
                      key=lambda g: (g == "?", g))
        rows = []
        for span in sorted(trends):
            cells = []
            prev = None
            for g in gens:
                v = trends[span].get(g)
                if v is None:
                    cells.append("<td>-</td>")
                    prev = None  # gap: don't compare across it — the
                    # highlight promises ADJACENT-generation deltas
                    continue
                mark = ""
                if prev is not None and prev > 0:
                    delta = (v - prev) / prev
                    if delta > 0.25:
                        mark = ' style="background:#f2a3a3"'
                    elif delta < -0.25:
                        mark = ' style="background:#9ce29c"'
                cells.append(f"<td{mark}>{v:.4f}</td>")
                prev = v
            rows.append(f"<tr><td><code>{html.escape(span)}</code></td>"
                        + "".join(cells) + "</tr>")
        head = "".join(f"<th>{html.escape(g)}</th>" for g in gens)
        body = ("<table><tr><th>span</th>" + head + "</tr>"
                + "".join(rows) + "</table>" if rows else
                "<p>no span samples indexed yet (runs need "
                "<code>\"telemetry\": true</code>).</p>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>trend — {html.escape(name)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/campaign/{quote(name)}">&larr; campaign</a></p>
<h1>span p95 trend — {html.escape(name)}</h1>
<p>p95 span duration (s) per campaign generation; a &gt;25% step vs
the previous generation is highlighted.  Enforce with
<code>cli obs gate --campaign {html.escape(name)} --span &lt;name&gt;
</code> (docs/TELEMETRY.md).  Drill down:
<a href="/profile/{quote(name)}">device-call profile</a> &middot;
<a href="/campaign/{quote(name)}/forensics">regression forensics</a>.
</p>
{body}</body></html>"""
        self._send(200, doc.encode())

    def _profile(self, name: str):
        """Device-call profile treemap (ISSUE 16): per (site,
        shape-class, host) compile/execute/dispatch self-time over the
        campaign's runs — the web twin of ``cli obs profile``."""
        from .campaign.index import Index

        self._autoingest()
        name = unquote(name).rstrip("/")
        path = self._safe_path(os.path.join("campaigns", name + ".jsonl"))
        if path is None or not os.path.exists(path):
            return self._send(404, b"no such campaign", "text/plain")
        rows = Index(path).profile()
        by_site: Dict[str, list] = {}
        for r in rows:
            by_site.setdefault(r["site"], []).append(r)
        site_total = {s: sum(r["compile_s"] + r["execute_s"] for r in rs)
                      for s, rs in by_site.items()}
        grand = sum(site_total.values()) or 1e-12
        parts = []
        for site in sorted(by_site, key=lambda s: -site_total[s]):
            pct = site_total[site] / grand * 100.0
            cells = "".join(
                f"<tr><td><code>{html.escape(r['shape'])}</code></td>"
                f"<td>{html.escape(r['host'] or '-')}</td>"
                f"<td>{r['calls']}</td><td>{r['compile_s']:.3f}</td>"
                f"<td>{r['execute_s']:.3f}</td>"
                f"<td>{r['device_dispatch_s']:.3f}</td></tr>"
                for r in sorted(by_site[site],
                                key=lambda r: -(r["compile_s"]
                                                + r["execute_s"])))
            parts.append(
                f"<h2><code>{html.escape(site)}</code> — "
                f"{pct:.1f}% of device time</h2>"
                f'<div class="bar"><div style="width:{pct:.1f}%">'
                "</div></div>"
                "<table><tr><th>shape-class</th><th>host</th>"
                "<th>calls</th><th>compile s</th><th>execute s</th>"
                f"<th>dispatch s</th></tr>{cells}</table>")
        body = ("".join(parts) if parts else
                "<p>no device-call profile yet (runs need "
                "<code>\"telemetry\": true</code>; re-run "
                "<code>cli obs ingest</code> after runs land).</p>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>profile — {html.escape(name)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
.bar {{ background: #eee; width: 60%; height: 10px; margin: 4px 0; }}
.bar div {{ background: #4a90d9; height: 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/campaign/{quote(name)}">&larr; campaign</a> &middot;
<a href="/campaign/{quote(name)}/trend">trend</a> &middot;
<a href="/campaign/{quote(name)}/forensics">forensics</a></p>
<h1>device-call profile — {html.escape(name)}</h1>
<p>Per (site, shape-class, host): jit compile / execute /
dispatch-only self-time summed over the campaign's telemetric runs
(<code>cli obs profile {html.escape(name)}</code>).</p>
{body}</body></html>"""
        self._send(200, doc.encode())

    def _forensics(self, name: str):
        """Cross-generation regression forensics panel (ISSUE 16): the
        latest generation pair gated span by span, each regression's
        delta attributed across phase buckets + forensic counters —
        the web twin of ``cli obs diff``."""
        from .telemetry import forensics

        self._autoingest()
        name = unquote(name).rstrip("/")
        path = self._safe_path(os.path.join("campaigns", name + ".jsonl"))
        if path is None or not os.path.exists(path):
            return self._send(404, b"no such campaign", "text/plain")
        report = forensics.run_diff(self.base, name)
        status = report.get("status") or "?"
        color = {"regression": "#f2a3a3", "pass": "#9ce29c"}.get(
            status, "#eee")
        parts = [f'<p>generations <code>{html.escape(str(report.get("from-gen", "?")))}'
                 f"</code> &rarr; <code>"
                 f'{html.escape(str(report.get("to-gen", "?")))}</code>: '
                 f'<span style="background:{color};padding:2px 8px">'
                 f"{html.escape(status)}</span>"
                 + (f" — {html.escape(str(report['reason']))}"
                    if report.get("reason") else "") + "</p>"]
        for e in report.get("spans") or []:
            mark = {"regression": "#f2a3a3",
                    "pass": "#9ce29c"}.get(e["status"], "#eee")
            rel = e.get("rel_delta")
            rel_txt = f"{rel * 100:+.0f}%" if isinstance(
                rel, (int, float)) else "?"
            head = (f'<h2><span style="background:{mark};'
                    f'padding:1px 6px">{html.escape(e["status"])}'
                    f"</span> <code>{html.escape(e['span'])}</code> "
                    f"{rel_txt} (mean {e['mean_from']:.4f}s &rarr; "
                    f"{e['mean_to']:.4f}s)</h2>")
            parts.append(head)
            if e["status"] != "regression":
                continue
            rows = "".join(
                f"<tr><td><code>{html.escape(p['bucket'])}</code></td>"
                f"<td>{p['from_s']:.4f}</td><td>{p['to_s']:.4f}</td>"
                f"<td>{p['delta_s']:+.4f}</td>"
                + (f"<td>{p['share'] * 100:.1f}%</td>"
                   if isinstance(p.get("share"), (int, float))
                   else "<td>-</td>") + "</tr>"
                for p in e.get("phases") or [])
            if rows:
                parts.append(
                    "<table><tr><th>phase bucket</th><th>from s</th>"
                    "<th>to s</th><th>&Delta; s</th>"
                    f"<th>share of delta</th></tr>{rows}</table>")
            crows = "".join(
                f"<tr><td><code>{html.escape(c['name'])}</code></td>"
                f"<td>{c['from']:g}</td><td>{c['to']:g}</td>"
                f"<td>{c['delta']:+g}</td></tr>"
                for c in (e.get("counters") or [])[:12])
            if crows:
                parts.append(
                    "<table><tr><th>counter</th><th>from</th>"
                    f"<th>to</th><th>&Delta;</th></tr>{crows}</table>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>forensics — {html.escape(name)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/campaign/{quote(name)}">&larr; campaign</a> &middot;
<a href="/campaign/{quote(name)}/trend">trend</a> &middot;
<a href="/profile/{quote(name)}">profile</a></p>
<h1>regression forensics — {html.escape(name)}</h1>
<p>Latest generation pair gated span by span (Mann-Whitney + p95
threshold); each regression's delta attributed across the phase
buckets (<code>cli obs diff {html.escape(name)}</code>).</p>
{"".join(parts)}</body></html>"""
        self._send(200, doc.encode())

    def _witness_diff(self, name: str):
        """Witness drift across campaign generations (ROADMAP open
        item): per regression key, how the auto-shrunk minimal witness
        changed between consecutive generations — op count, digest, and
        anomaly-set deltas.  A changed digest under an unchanged spec
        means the minimal repro MOVED: a different failure, even when
        the verdict grid still just shows False."""
        from .campaign.index import Index

        self._autoingest()
        name = unquote(name).rstrip("/")
        path = self._safe_path(os.path.join("campaigns", name + ".jsonl"))
        if path is None or not os.path.exists(path):
            return self._send(404, b"no such campaign", "text/plain")
        diffs = Index(path).witness_diffs()
        rows = []
        for d in diffs:
            digest = ("changed" if d["digest-changed"] else "same")
            style = ' style="background:#ffe9c9"' if d["changed"] else ""
            anoms = []
            for a in d["anomalies-added"]:
                anoms.append(f"+{a}")
            for a in d["anomalies-removed"]:
                anoms.append(f"&minus;{a}")
            rows.append(
                f"<tr{style}><td><code>{html.escape(str(d['key']))}"
                f"</code></td>"
                f"<td>{html.escape(str(d['from-gen']))} &rarr; "
                f"{html.escape(str(d['to-gen']))}</td>"
                f"<td>{d['from-ops']} &rarr; {d['to-ops']} "
                f"({d['ops-delta']:+d})</td>"
                f"<td>{digest}</td>"
                f"<td>{html.escape(' '.join(anoms)) or '-'}</td>"
                f"<td><code>{html.escape(str(d['from-digest'])[:12])} "
                f"&rarr; {html.escape(str(d['to-digest'])[:12])}"
                f"</code></td></tr>")
        body = ("<table><tr><th>key</th><th>generations</th><th>ops</th>"
                "<th>digest</th><th>anomaly deltas</th><th>digests</th>"
                "</tr>" + "".join(rows) + "</table>" if rows else
                "<p>no witness pairs yet — witness diffs need the same "
                "key auto-shrunk (<code>\"shrink\": true</code>) in at "
                "least two campaign generations (<code>--rerun</code>)."
                "</p>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>witness diff — {html.escape(name)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/campaign/{quote(name)}">&larr; campaign</a></p>
<h1>witness diff — {html.escape(name)}</h1>
<p>how each key's minimal witness moved between consecutive
generations (highlighted rows changed)</p>
{body}</body></html>"""
        self._send(200, doc.encode())

    # -- verifier pages (docs/VERIFIER.md) --------------------------------

    def _verifier_rows(self):
        """Session summaries: first-hand from the attached service, or
        read-only from the on-disk ``session.json`` snapshots."""
        if self.verifier is not None:
            return self.verifier.sessions()
        from .verifier import scan_sessions

        return [dict(meta, live=False)
                for _n, meta in scan_sessions(self.base)]

    def _verdict_json(self, name: str):
        """``GET /verdict/<session>`` — the rolling verdict as JSON.
        With a service attached this sweeps dirty work first (rolling
        verdicts out); read-only servers answer from the snapshot."""
        name = name.strip("/")
        if self.verifier is not None:
            code, doc = self.verifier.verdict(name)
            return self._send_json(code, doc)
        from .verifier import VerifierService, read_meta

        if not VerifierService.valid_name(name):
            # same sanitization as the service path: the name is about
            # to be joined into a filesystem path
            return self._send_json(400, {"error": "bad session name"})
        meta = read_meta(os.path.join(self.base, "verifier", name))
        if meta is None:
            return self._send_json(
                404, {"error": f"no such session {name!r}"})
        return self._send_json(200, dict(meta.get("verdict") or {},
                                         session=name, snapshot=True,
                                         digest=meta.get("digest")))

    def _verifier_list(self):
        """Session table: state, rolling verdict, ingest freshness —
        the fleet view of the always-on checker."""
        rows = []
        now = time.time()
        for s in self._verifier_rows():
            name = str(s.get("session") or "?")
            v = (s.get("verdict") or {})
            upd = s.get("updated")
            age = (f"{now - upd:.0f}s"
                   if isinstance(upd, (int, float)) else "?")
            links = [f'<a href="/verifier/{quote(name)}">session</a>']
            d = os.path.join(self.base, "verifier", name)
            from .telemetry import stream as tel_stream
            if tel_stream.events_path(d):
                links.append(
                    f'<a href="/live/{quote("verifier/" + name)}">live</a>')
            state = str(s.get("state") or "?")
            if s.get("live"):
                state += " &middot; in memory"
            rows.append(
                "<tr>"
                f"<td><code>{html.escape(name)}</code></td>"
                f"<td>{state}</td>"
                f"{_verdict_cell(v.get('valid?', '?'), v.get('error'))}"
                f"<td>{html.escape(', '.join(v.get('anomaly-types') or []) or '-')}</td>"
                f"<td>{s.get('txns', '?')}</td>"
                f"<td>{s.get('ops', '?')}</td>"
                f"<td>{age}</td>"
                f"<td>{' '.join(links)}</td></tr>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>verifier sessions</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/">&larr; runs</a></p><h1>verifier sessions</h1>
<p>the always-on incremental checker: stream histories in
(<code>POST /ingest/&lt;session&gt;</code>), rolling verdicts out
(<code>GET /verdict/&lt;session&gt;</code>); see docs/VERIFIER.md</p>
<table><tr><th>session</th><th>state</th><th>valid?</th>
<th>anomalies</th><th>txns</th><th>ops</th><th>updated</th>
<th>links</th></tr>
{"".join(rows) or '<tr><td colspan="8">(no sessions)</td></tr>'}</table>
</body></html>"""
        self._send(200, doc.encode())

    def _verifier_session(self, name: str):
        """Per-session page: rolling verdict with badges, anomaly
        first-seen table, seal result.  Auto-refreshes while the
        session is open and the snapshot is fresh (same stale guard as
        /live)."""
        name = unquote(name).rstrip("/")
        if not name or "/" in name:
            return self._send(404, b"no such session", "text/plain")
        # READ-ONLY rendering, service or not: a browser tab
        # auto-refreshing every 2 s must not run sweeps, append verdict
        # events, rewrite session.json, or zero the freshness gauge —
        # the mutating rolling-verdict contract lives on GET /verdict
        meta = None
        if self.verifier is not None:
            meta = {s.get("session"): s
                    for s in self.verifier.sessions()}.get(name)
        if meta is None:
            from .verifier import VerifierService, read_meta

            if not VerifierService.valid_name(name):
                return self._send(404, b"no such session", "text/plain")
            meta = read_meta(os.path.join(self.base, "verifier", name))
            if meta is None:
                return self._send(404, b"no such session", "text/plain")
        verdict: Dict[str, Any] = dict(meta.get("verdict") or {})
        if "digest" not in verdict and meta.get("digest"):
            verdict["digest"] = meta.get("digest")
        state = str(meta.get("state") or "?")
        upd = meta.get("updated")
        stale = (not isinstance(upd, (int, float))
                 or time.time() - upd > _LIVE_STALE_S)
        refresh = ("" if state == "sealed" or stale else
                   '<meta http-equiv="refresh" content="2">')
        fs = verdict.get("first-seen") or {}
        anom_rows = "".join(
            f"<tr><td><code>{html.escape(a)}</code></td>"
            f"<td>{fs.get(a, '')}</td></tr>"
            for a in (verdict.get("anomaly-types") or []))
        anom_html = (f"<h2>anomalies (first seen)</h2><table>"
                     f"<tr><th>anomaly</th><th>first seen (epoch s)</th>"
                     f"</tr>{anom_rows}</table>" if anom_rows else
                     "<p>no anomalies observed</p>")
        seal = meta.get("seal") or {}
        seal_html = ""
        if state == "sealed":
            seal_html = (
                "<h2>seal</h2><p>incremental == batch: "
                f"<b>{seal.get('equal')}</b> &middot; digest "
                f"<code>{html.escape(str(seal.get('digest')))}</code>"
                "</p>")
        edge_rows = "".join(
            f"<tr><td>{html.escape(r)}</td><td>{n}</td></tr>"
            for r, n in sorted((verdict.get("edge-counts")
                                or {}).items()))
        edges_html = (f"<h2>dependency edges</h2><table><tr><th>rel</th>"
                      f"<th>count</th></tr>{edge_rows}</table>"
                      if edge_rows else "")
        d = os.path.join(self.base, "verifier", name)
        from .telemetry import stream as tel_stream
        live_link = (
            f'&middot; <a href="/live/{quote("verifier/" + name)}">live'
            '</a> ' if tel_stream.events_path(d) else "")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
{refresh}<title>verifier — {html.escape(name)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/verifier">&larr; sessions</a> {live_link}&middot;
<a href="/files/{quote("verifier/" + name)}/">files</a> &middot;
<a href="/verdict/{quote(name)}">verdict.json</a></p>
<h1>verifier session <code>{html.escape(name)}</code>
{_verdict_badges(verdict.get("valid?", "?"), verdict.get("error"))}</h1>
<p>state: <b>{html.escape(state)}</b> &middot;
{meta.get("txns", "?")} txns / {meta.get("ops", "?")} ops over
{meta.get("segments", "?")} segments &middot; journal cursor
{meta.get("cursor", "?")} &middot; verdict digest
<code>{html.escape(str(verdict.get("digest")
                       or meta.get("digest") or "?"))}</code></p>
{anom_html}{seal_html}{edges_html}
</body></html>"""
        self._send(200, doc.encode())

    # -- fleet control plane (docs/FLEET.md) ------------------------------

    def _fleet_post(self, verb: str):
        """``POST /fleet/<verb>`` — the coordinator's control plane:
        register / claim / heartbeat / complete / release.  JSON in,
        JSON out; only routed when a `FleetCoordinator` is attached
        (``cli fleet serve``)."""
        if self.fleet is None:
            return self._send_json(
                404, {"error": "no fleet coordinator (start with "
                      "`fleet serve <spec.json>`)"})
        handlers = {
            "register": self.fleet.register,
            "claim": self.fleet.claim,
            "heartbeat": self.fleet.heartbeat,
            "complete": self.fleet.complete,
            "release": self.fleet.release,
        }
        fn = handlers.get(verb)
        if fn is None:
            return self._send_json(404,
                                   {"error": f"unknown verb {verb!r}"})
        body = self._read_body()
        doc: Dict[str, Any] = {}
        if body.strip():
            try:
                doc = json.loads(body)
            except ValueError:
                return self._send_json(400, {"error": "bad json body"})
            if not isinstance(doc, dict):
                return self._send_json(400,
                                       {"error": "body must be a dict"})
        code, out = fn(doc)
        self._send_json(code, out)

    def _fleet_artifact(self, run_id: str, query: str):
        """``POST /fleet/artifact/<run-id>`` — the store-federation
        upload seam (docs/FLEET.md): chunked run-dir upload, resumable
        by byte cursor, digest-verified, idempotent."""
        if self.fleet is None:
            return self._send_json(
                404, {"error": "no fleet coordinator (start with "
                      "`fleet serve <spec.json>`)"})
        from urllib.parse import parse_qs

        from .fleet.artifacts import MAX_ARTIFACT_BYTES

        # cap BEFORE buffering the body: the protocol-level total
        # check runs after the read, which would let one oversized
        # POST balloon the coordinator's RSS past the artifact cap
        try:
            clen = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            clen = 0
        if clen > MAX_ARTIFACT_BYTES:
            return self._send_json(
                413, {"error": "request body exceeds the artifact "
                      "size cap"})
        params = {k: v[0] for k, v in parse_qs(query).items()}
        code, doc = self.fleet.artifact(run_id, params,
                                        self._read_body())
        self._send_json(code, doc)

    def _fleet_cache(self, name: str):
        """``GET /fleet/cache`` (entry advert JSON) and ``GET
        /fleet/cache/<name>`` (one verified AOT entry as
        octet-stream) — the compile-cache distribution surface
        (docs/COMPILECACHE.md); only routed with a coordinator
        attached."""
        if self.fleet is None:
            return self._send_json(
                404, {"error": "no fleet coordinator (start with "
                      "`fleet serve <spec.json>`)"})
        if not name:
            code, doc = self.fleet.cache_index()
            return self._send_json(code, doc)
        code, doc = self.fleet.cache_blob(name)
        blob = doc.pop("_blob", None)
        mac = doc.pop("_mac", None)
        if code == 200 and isinstance(blob, bytes):
            from jepsen_tpu.compilecache import fleet as cc_fleet

            extra = {cc_fleet.MAC_HEADER: mac} if mac else None
            return self._send(200, blob, "application/octet-stream",
                              extra)
        return self._send_json(code, doc)

    def _fleet_status_doc(self):
        """The coordinator's status, enriched with the co-hosted
        verifier's per-host verdict freshness (ISSUE 14 satellite):
        the one join the fleet dashboard was missing — last heartbeat
        age says a worker is ALIVE, verdict freshness says its
        live-check stream is actually being VERIFIED (ingest lag on
        the verifier's own clock, no worker clock correction
        needed)."""
        code, doc = self.fleet.status()
        if code == 200 and self.verifier is not None:
            try:
                fresh = self.verifier.host_freshness()
                if fresh:
                    doc["verifier-freshness"] = fresh
                for w, row in (doc.get("workers") or {}).items():
                    if w in fresh:
                        row["verdict-freshness-s"] = \
                            fresh[w]["freshness-s"]
                        row["live-sessions"] = fresh[w]["sessions"]
            except Exception:  # noqa: BLE001 — decorative join
                logger.debug("verifier freshness join failed",
                             exc_info=True)
        return code, doc

    def _fleet_status(self):
        if self.fleet is None:
            return self._send_json(
                404, {"error": "no fleet coordinator (start with "
                      "`fleet serve <spec.json>`)"})
        code, doc = self._fleet_status_doc()
        self._send_json(code, doc)

    def _fleet_page(self):
        """Fleet dashboard: queue counts, active leases, and worker
        liveness — the control-plane view next to the campaign's
        /campaign/<n>/live run view."""
        if self.fleet is None:
            return self._send(404, b"no fleet coordinator (start with "
                              b"`fleet serve <spec.json>`)",
                              "text/plain")
        code, s = self._fleet_status_doc()
        if code != 200:
            return self._send_json(code, s)
        c = s.get("counts") or {}

        def _fresh_cell(d):
            f = d.get("verdict-freshness-s")
            if f is None:
                return "&mdash;"
            n = d.get("live-sessions")
            return (f"{f}s over {n} session(s)" if n else f"{f}s")

        def _wwin(d):
            """Installed-window cell: digest + open positions, red when
            the worker's reported digest disagrees with the
            coordinator's authoritative set (a desynced worker must be
            visible at a glance)."""
            wd = d.get("windows")
            if not wd:
                return "&mdash;"
            open_ = ",".join(str(o.get("pos"))
                             for o in wd.get("open") or ()) or "-"
            cell = (f"<code>{html.escape(str(wd.get('digest')))}</code>"
                    f" open={html.escape(open_)}")
            if not wd.get("synced"):
                cell += ' <b style="color:#b00">DESYNCED</b>'
            return cell

        wrows = "".join(
            f"<tr><td>{html.escape(w)}</td>"
            f"<td>{html.escape(str(d.get('host')))}</td>"
            f"<td>{html.escape(str(d.get('backend')))}</td>"
            f"<td>{html.escape(str(d.get('version') or '—'))}</td>"
            f"<td>{d.get('device-slots')}</td>"
            f"<td>{d.get('age-s')}s</td>"
            f"<td>{'alive' if d.get('alive') else 'silent'}</td>"
            f"<td>{_fresh_cell(d)}</td>"
            f"<td>{_wwin(d)}</td></tr>"
            for w, d in sorted((s.get("workers") or {}).items()))
        lrows = "".join(
            f"<tr><td><code>{html.escape(str(l['run']))}</code></td>"
            f"<td>{html.escape(str(l['worker']))}</td>"
            f"<td>{l['deadline']}</td></tr>"
            for l in s.get("leases") or [])
        sched_html = ""
        sched = s.get("nemesis-schedule")
        if sched:
            grows = []
            gens = sched.get("gens") or {}
            digests = sched.get("digest-by-gen") or {}
            for g in sorted(gens, key=lambda x: int(x)):
                wins = " ".join(
                    f"[{w.get('pos')}:{html.escape(str(w.get('fault')))}"
                    f"@{w.get('at_s')}s+{w.get('dur_s')}s]"
                    for w in gens[g])
                grows.append(
                    f"<tr><td>{html.escape(str(g))}</td>"
                    f"<td><code>{html.escape(str(digests.get(g)))}"
                    f"</code></td><td>{wins}</td></tr>")
            sched_html = (
                "<h2>nemesis schedule</h2>"
                f"<p>{sched.get('windows')} synchronized window(s) per "
                f"generation over "
                f"<code>{html.escape('|'.join(sched.get('faults')))}"
                "</code> &mdash; every host's cell for a generation "
                "installs the same seeded set (workers table shows "
                "installed digests)</p>"
                "<table><tr><th>gen</th><th>digest</th>"
                f"<th>windows</th></tr>{''.join(grows)}</table>")
        ap_html = ""
        ap = s.get("autopilot")
        if ap:
            # the autopilot panel (ISSUE 17): generation counter,
            # quarantine set, last gate verdicts, managed workers
            qrows = "".join(
                f"<tr><td><code>{html.escape(k)}</code></td>"
                f"<td>{html.escape(str(q.get('span')))}</td>"
                f"<td>{q.get('rel-delta')}</td>"
                f"<td>{html.escape(str(q.get('gen')))}</td></tr>"
                for k, q in sorted(
                    (ap.get("quarantined") or {}).items()))
            vrows = "".join(
                f"<tr><td>{html.escape(str(v.get('span')))}</td>"
                f"<td>{html.escape(str(v.get('status')))}</td>"
                f"<td>{v.get('rc')}</td>"
                f"<td>{html.escape(str(v.get('reason') or ''))}</td>"
                "</tr>"
                for v in ap.get("last-verdicts") or [])
            arows = "".join(
                f"<tr><td>{html.escape(n)}</td>"
                f"<td>{html.escape(str(w.get('version')))}</td>"
                f"<td>{w.get('pid')}</td>"
                f"<td>{'running' if w.get('running') else 'exited'}"
                f"{' (draining)' if w.get('draining') else ''}"
                "</td></tr>"
                for n, w in sorted((ap.get("workers") or {}).items()))
            ap_html = (
                "<h2>autopilot</h2>"
                f"<p>generation <b>{html.escape(str(ap.get('generation')))}</b> "
                f"({ap.get('generations-closed')} closed) &middot; "
                f"target worker version "
                f"<code>{html.escape(str(ap.get('worker-version')))}</code> "
                f"&middot; journal "
                f"<code>{html.escape(str(ap.get('journal-digest')))}</code></p>"
                "<h3>quarantined cells</h3>"
                "<table><tr><th>key</th><th>span</th><th>rel delta</th>"
                f"<th>since gen</th></tr>{qrows or '<tr><td colspan=4>(none)</td></tr>'}</table>"
                "<h3>last gate verdicts</h3>"
                "<table><tr><th>span</th><th>status</th><th>rc</th>"
                f"<th>reason</th></tr>{vrows or '<tr><td colspan=4>(no closed generation yet)</td></tr>'}</table>"
                "<h3>managed workers</h3>"
                "<table><tr><th>worker</th><th>version</th><th>pid</th>"
                f"<th>state</th></tr>{arows or '<tr><td colspan=4>(none)</td></tr>'}</table>")
            al = ap.get("alerts") or {}
            alrows = "".join(
                f"<tr><td><code>{html.escape(str(a.get('rule')))}"
                "</code></td>"
                f"<td>{html.escape(str(a.get('severity')))}</td>"
                f"<td><b style=\"color:{'#b00' if a.get('state') == 'firing' else '#b60'}\">"
                f"{html.escape(str(a.get('state')))}</b></td>"
                f"<td>{a.get('value')}</td></tr>"
                for a in al.get("active") or [])
            ap_html += (
                '<h3><a href="/alerts">alerts</a></h3>'
                f"<p>{al.get('rules', 0)} rule(s) &middot; "
                f"{len(al.get('firing') or [])} firing, "
                f"{len(al.get('pending') or [])} pending &middot; "
                f"notifications {al.get('sends-ok', 0)} ok / "
                f"{al.get('sends-failed', 0)} failed &middot; journal "
                f"<code>{html.escape(str(al.get('digest')))}</code></p>"
                "<table><tr><th>rule</th><th>severity</th><th>state</th>"
                f"<th>value</th></tr>{alrows or '<tr><td colspan=4>(quiet)</td></tr>'}</table>")
        name = str(s.get("campaign"))
        state = "finished" if s.get("finished") else "running"
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>fleet — {html.escape(name)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/">&larr; runs</a> &middot;
<a href="/campaign/{quote(name)}">campaign</a> &middot;
<a href="/campaign/{quote(name)}/live">live</a> &middot;
<a href="/fleet/status">status.json</a></p>
<h1>fleet — {html.escape(name)}</h1>
<p>{state}: {s.get("done")}/{s.get("total")} cells done &middot;
queue depth {s.get("queue-depth")}, claim-latency p95
{s.get("claim-latency-p95-s") if s.get("claim-latency-p95-s")
 is not None else "&mdash;"}s &middot;
{c.get("claimed")} claimed &middot;
{c.get("requeues")} requeues, {c.get("duplicates")} duplicate
completions discarded &middot; queue digest
<code>{html.escape(str(s.get("digest")))}</code></p>
{ap_html}
<h2>workers</h2>
<table><tr><th>worker</th><th>host</th><th>backend</th>
<th>version</th>
<th>device slots</th><th>last seen</th><th></th>
<th>verdict freshness</th>
<th>installed windows</th></tr>{wrows or
'<tr><td colspan="9">(none registered)</td></tr>'}</table>
<h2>active leases</h2>
<table><tr><th>run</th><th>worker</th><th>deadline</th></tr>{lrows or
'<tr><td colspan="3">(none)</td></tr>'}</table>
{sched_html}
</body></html>"""
        self._send(200, doc.encode())

    def _alerts_page(self):
        """``/alerts`` (ISSUE 20): the watchtower view — every rule's
        current state from the durable ``alerts.jsonl`` journal (replayed
        read-only, so the page works on a dead store too), firing first."""
        from .telemetry import alerts as alerts_mod

        path = alerts_mod.alerts_path(self.base)
        jr = alerts_mod.AlertJournal(path) if os.path.exists(path) \
            else None
        states = dict(jr.states) if jr is not None else {}
        order = {"firing": 0, "pending": 1, "resolved": 2}

        def _hist(rule):
            return (f'<a href="/metrics">ALERTS{{alertname='
                    f'&quot;{html.escape(rule)}&quot;}}</a>')

        rows = "".join(
            f"<tr><td><code>{html.escape(r)}</code></td>"
            f"<td>{html.escape(str(d.get('severity')))}</td>"
            f"<td><b style=\"color:"
            f"{'#b00' if d.get('state') == 'firing' else '#b60' if d.get('state') == 'pending' else '#080'}\">"
            f"{html.escape(str(d.get('state')))}</b></td>"
            f"<td>{d.get('value')}</td>"
            f"<td>{d.get('since')}</td>"
            f"<td>{d.get('seq')}</td>"
            f"<td>{_hist(r)}</td></tr>"
            for r, d in sorted(
                states.items(),
                key=lambda kv: (order.get(kv[1].get("state"), 3),
                                kv[0])))
        meta = ""
        if jr is not None:
            meta = (f"<p>journal <code>{html.escape(jr.digest())}"
                    "</code> &middot; notifications "
                    f"{jr.sends_ok} ok / {jr.sends_failed} failed "
                    f"&middot; <code>{html.escape(path)}</code></p>")
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>alerts</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #bbb; padding: 4px 10px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/">&larr; runs</a>{' &middot; <a href="/fleet">fleet</a>'
 if self.fleet is not None else ''} &middot;
<a href="/metrics">metrics</a></p>
<h1>alerts</h1>
{meta}
<table><tr><th>rule</th><th>severity</th><th>state</th><th>value</th>
<th>since</th><th>seq</th><th>series</th></tr>{rows or
'<tr><td colspan="7">(no alert journal yet)</td></tr>'}</table>
</body></html>"""
        self._send(200, doc.encode())

    def _timeline(self, key: str):
        """``/timeline/<run-id>`` (ISSUE 14 tentpole c): the run's
        stitched cross-host waterfall — enqueue wait, claim latency,
        execute phases, live-sweep overlap, upload, landing — one bar
        per host-attributed segment on absolute time, from the
        warehouse's ``trace_spans`` view (`cli obs ingest` feeds it)."""
        from .telemetry import warehouse as wmod

        key = unquote(key).rstrip("/")
        if not key:
            return self._send(404, b"timeline needs a run id",
                              "text/plain")
        wh = wmod.open_if_exists(self.base)
        if wh is None:
            return self._send(
                404, b"no warehouse (run `cli obs ingest` first)",
                "text/plain")
        tl = wh.trace_timeline(key)
        if not tl.get("spans") and not tl.get("orphans"):
            return self._send(
                404, b"no trace spans for this run (run `cli obs "
                b"ingest` after it lands; traced runs need telemetry "
                b"or a fleet ledger)", "text/plain")
        lay = wmod.Warehouse.timeline_layout(tl)
        spans, hosts, wall = lay["spans"], lay["hosts"], lay["wall"]
        palette = ("#6b8fc9", "#74b474", "#c9a35a", "#b07fc9",
                   "#c97b7b", "#6bbcbc")
        color = {h: palette[i % len(palette)]
                 for i, h in enumerate(hosts)}
        rows = []
        for s in spans:
            dur = s.get("dur_s") or 0.0
            left = 100.0 * s["frac_left"]
            width = max(100.0 * s["frac_width"], 0.3)
            host = str(s.get("host") or "-")
            rows.append(
                "<tr>"
                f"<td><code>{html.escape(host)}</code></td>"
                f"<td><code>{html.escape(str(s.get('name')))}</code>"
                f"</td><td>{s['off']:+.3f}s</td><td>{dur:.3f}s</td>"
                f'<td class="lane"><div class="bar" style="margin-left:'
                f"{left:.2f}%;width:{min(width, 100.0 - left):.2f}%;"
                f'background:{color.get(host, "#999")}"></div></td>'
                "</tr>")
        orphans = tl.get("orphans") or []
        orphan_html = ""
        if orphans:
            items = "".join(
                f"<li><code>{html.escape(str(o.get('trace_id')))}"
                f"</code> {html.escape(str(o.get('name')))} "
                f"host={html.escape(str(o.get('host')))}</li>"
                for o in orphans)
            orphan_html = (
                '<h2 style="color:#b03030">orphan spans</h2>'
                "<p>recorded against this run under a DIFFERENT trace "
                "id — the stitching contract (one run, one trace) is "
                f"broken</p><ul>{items}</ul>")
        legend = " ".join(
            f'<span class="b" style="background:{color[h]}">'
            f"{html.escape(h)}</span>" for h in hosts)
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>timeline — {html.escape(key)}</title><style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; width: 100%; }}
td, th {{ border: 1px solid #bbb; padding: 3px 8px;
          white-space: nowrap; }}
td.lane {{ width: 55%; background: #f6f6f6; }}
.bar {{ height: 12px; border-radius: 2px; }}
{_BADGE_CSS}</style></head><body>
<p><a href="/">&larr; runs</a></p>
<h1>timeline — <code>{html.escape(str(tl.get("run") or key))}</code></h1>
<p>trace <code>{html.escape(str(tl["trace-id"]))}</code> &middot;
{len(spans)} spans over {len(hosts) or 1} host(s) &middot;
{wall:.3f}s wall &middot; {legend}</p>
<table><tr><th>host</th><th>segment</th><th>start</th><th>dur</th>
<th>timeline</th></tr>{"".join(rows)}</table>
{orphan_html}
<p>per-segment durations are queryable: <code>cli obs sql "SELECT
host, name, dur_s FROM trace_spans WHERE run = '{html.escape(str(
    tl.get("run") or key))}' ORDER BY t0"</code>; gate control-plane
segments like any span: <code>cli obs gate --span
fleet:claim-to-start</code> (docs/TELEMETRY.md)</p>
</body></html>"""
        self._send(200, doc.encode())

    def _files(self, rel: str):
        p = self._safe_path(rel.rstrip("/"))
        if p is None or not os.path.exists(p):
            return self._send(404, b"not found", "text/plain")
        if os.path.isdir(p):
            entries = sorted(os.listdir(p))
            items = "".join(
                f'<li><a href="/files/{quote(os.path.join(rel.rstrip("/"), e))}'
                f'{"/" if os.path.isdir(os.path.join(p, e)) else ""}">'
                f"{html.escape(e)}</a></li>" for e in entries)
            doc = (f"<html><body><h2>{html.escape(rel)}</h2>"
                   f'<p><a href="/">&larr; runs</a></p><ul>{items}</ul>'
                   f"</body></html>")
            return self._send(200, doc.encode())
        ctype = {
            ".html": "text/html; charset=utf-8",
            ".json": "application/json",
            ".png": "image/png",
            ".svg": "image/svg+xml",
            ".log": "text/plain; charset=utf-8",
            ".edn": "text/plain; charset=utf-8",
        }.get(os.path.splitext(p)[1], "application/octet-stream")
        with open(p, "rb") as f:
            self._send(200, f.read(), ctype)

    def _zip(self, rel: str):
        p = self._safe_path(rel)
        if p is None or not os.path.isdir(p):
            return self._send(404, b"not found", "text/plain")
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for root, _dirs, files in os.walk(p):
                for fn in files:
                    full = os.path.join(root, fn)
                    z.write(full, os.path.relpath(full, os.path.dirname(p)))
        name = rel.replace(os.sep, "-") + ".zip"
        self._send(200, buf.getvalue(), "application/zip",
                   {"Content-Disposition": f'attachment; filename="{name}"'})

    def log_message(self, fmt, *args):  # quiet by default
        logger.debug("web: " + fmt, *args)


def serve(port: int = 8080, base: Optional[str] = None, *,
          host: str = "127.0.0.1",
          background: bool = False,
          verifier: Any = None,
          fleet: Any = None) -> ThreadingHTTPServer:
    """Serve the store dir (reference `web/serve!`).  Binds localhost by
    default — stored test maps can hold cluster details; pass
    host="0.0.0.0" explicitly to expose.  With background=True, runs in a
    daemon thread and returns the server (tests use this).  Pass a
    `verifier.VerifierService` to route the ingest endpoints
    (`cli serve --ingest`; docs/VERIFIER.md), and/or a
    `fleet.FleetCoordinator` to route the fleet control plane
    (`cli fleet serve`; docs/FLEET.md)."""
    handler = type("Handler", (_Handler,), {"base": base or store.BASE,
                                            "verifier": verifier,
                                            "fleet": fleet})
    srv = ThreadingHTTPServer((host, port), handler)
    logger.info("serving store %s on port %d%s%s", base or store.BASE,
                port,
                " (verifier ingest on)" if verifier is not None else "",
                " (fleet control plane on)" if fleet is not None else "")
    if background:
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        # make server_close STOP the loop first: closing the socket
        # under a live serve_forever leaves that thread select()ing on
        # a closed fd — which returns ready instantly, i.e. a leaked
        # CPU-spinning thread per server.  A suite's worth of those was
        # measurably convoying every GIL-releasing C call (sqlite,
        # sockets) in the process — the source of its "ambient load"
        # timing flakes.
        orig_close = srv.server_close

        def _close_and_stop() -> None:
            srv.shutdown()       # returns once serve_forever exited
            t.join(timeout=5)
            orig_close()

        srv.server_close = _close_and_stop  # type: ignore[assignment]
        return srv
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return srv
