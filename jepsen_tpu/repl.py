"""REPL conveniences.

Equivalent of the reference's `jepsen/src/jepsen/repl.clj` (SURVEY.md
§2.1): one-liners for poking at stored runs from an interactive session::

    >>> from jepsen_tpu import repl
    >>> t = repl.latest("demo-append")
    >>> repl.summary(t)
    >>> h = repl.history(t)
    >>> repl.recheck(t, AppendChecker())
"""

from __future__ import annotations

from typing import Any, Optional

from . import core, report, store
from .history.ops import History


def latest(name: Optional[str] = None, *, base: Optional[str] = None) -> dict:
    """Load the most recent run (of a name, or overall)."""
    d = store.latest(name, base=base)
    if d is None:
        raise FileNotFoundError(f"no stored runs for {name!r}")
    return store.load(d)


def history(test: dict) -> History:
    """The (materialized) history of a loaded test."""
    h = test.get("history")
    if h is None:
        raise ValueError("test has no history")
    return h if isinstance(h, History) else h.materialize()


def summary(test: dict) -> None:
    report.print_report(test)


def recheck(test: dict, checker) -> dict:
    """Re-run a checker and re-save results (reference: REPL re-analysis
    path)."""
    return core.analyze(test, checker=checker)


def runs(name: Optional[str] = None, *, base: Optional[str] = None):
    """List stored run directories, newest first."""
    return store.tests(name, base=base)
