"""Single-history checking sharded across a device mesh (config 4).

The reference's scaling wall is ONE giant history on ONE JVM (SURVEY.md
§2.7 "SCC / cycle search": bifurcan's Tarjan is single-threaded; upstream
`elle/txn.clj cycles!` runs it on the whole graph).  This module is the
TPU answer for that axis — BASELINE.json config 4, a 10M-op list-append
history on a v5e-8 — decomposed TPU-first rather than by translating
Tarjan:

1. **Edge inference** runs under one jit whose *inputs are sharded along
   the op/mop axes* (GSPMD): XLA partitions the elementwise scans and
   segment ops and inserts the collectives the data flow needs.  The
   packing order guarantees mops of one txn are contiguous, so sorted-run
   computations parallelize along the mop axis naturally.

2. **Cycle sweep** is sharded over the *backward-edge axis* K with
   shard_map: each device owns K/n_dev backward edges and propagates only
   their (N, K/n_dev) reachability label planes — columns are fully
   independent (the expensive part: at 10M ops the full label planes are
   (20M x 128) int8 = 2.5 GB *per projection*; sharding K divides both
   that memory and the propagation FLOPs by the mesh size).  The only
   cross-device coupling is the (K, K) meta-graph — assembled with one ICI
   `all_gather` of the local meta rows, after which every device computes
   the trivial closure redundantly.  Convergence flags combine with a
   `psum`.

Verdicts are bitwise-identical to the single-device `core_check` — tested
differentially (tests/test_parallel.py) per the determinism-as-oracle
rule (SURVEY.md §5).

Since ISSUE 12 this module is the ENGINE under the sharded-by-default
path: `device_core.core_check_auto` / `core_check_exact` /
`list_append.check` resolve a mesh via `parallel.slots.default_mesh`
and dispatch through `_core_check_sharded` + `shard_padded` directly.
`check_sharded` remains as the explicit opt-in wrapper (superseded as
an entry point — docs/IR.md).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu.checkers.elle.device_core import (
    COUNT_NAMES,
    PROJECTIONS,
    grow_until_exact,
)
from jepsen_tpu.checkers.elle.device_infer import PaddedLA, infer, pad_packed
from jepsen_tpu.history.soa import PackedTxns
from jepsen_tpu.ops.cycle_sweep import _sweep_window
from jepsen_tpu.utils.backend import get_shard_map

shard_map = get_shard_map()


def projection_sweep_bits(out, max_k: int, sweep):
    """The 5-projection scan over an inferred edge set, with `sweep` a
    callable (rank, e_src, e_dst, mask, chain_nodes, chain_starts,
    chain_mask, back_pre, back_tables) -> (has_cycle, witness, n_back,
    converged); back_pre is the hoisted backward enumeration (is_back,
    back_id, n_back) and back_tables the searchsorted-built (max_k,)
    (bsrc, bdst) endpoint pair that `_sweep_window` consumes directly.

    One sweep instantiation scanned over the 5 projections — same
    compile-time + label-plane-memory rationale as device_core.core_check
    (5 inlined while_loop kernels measured 125.8 s of XLA compile at
    100k-txn shapes in round 2).  Shared by the K-axis sharded path and
    the 2D hybrid (dcn x k) path (`parallel/hybrid.py`).  Since round 5
    this delegates to `cycle_sweep.projection_scan` — family-include
    flags plus ONE shared E-sized backward cumsum — instead of
    materializing (5, E)/(5, C) mask stacks and re-running 5 cumsums
    (VERDICT r04 item 2; the single-device paths migrated in round 4,
    PROFILE.md §0b).
    """
    edges = out["edges"]
    chains = out["chains"]
    rank = jnp.concatenate([out["ranks"]["txn"], out["ranks"]["barrier"]])
    e_src = jnp.concatenate([edges[k][0] for k in ("ww", "wr", "rw", "tb",
                                                   "bt")])
    e_dst = jnp.concatenate([edges[k][1] for k in ("ww", "wr", "rw", "tb",
                                                   "bt")])

    pc_nodes, pc_starts, pc_mask = chains["process"]
    bc_nodes, bc_starts, bc_mask = chains["barrier"]
    chain_nodes = jnp.concatenate([pc_nodes, bc_nodes])
    chain_starts = jnp.concatenate([pc_starts, bc_starts])

    from jepsen_tpu.checkers.elle.device_core import (
        chain_include_stack,
        proj_include_stack,
    )
    from jepsen_tpu.ops.cycle_sweep import projection_scan

    # max_rounds is owned by the sweep closure (unused when sweep is set)
    conv_all, overflow, cyc_bits = projection_scan(
        rank.shape[0], max_k, 0, rank, e_src, e_dst,
        [edges[k][2] for k in ("ww", "wr", "rw", "tb", "bt")],
        proj_include_stack(PROJECTIONS),
        chain_nodes, chain_starts, [pc_mask, bc_mask],
        chain_include_stack(PROJECTIONS), sweep=sweep)

    counts = jnp.stack([out["counts"][n].astype(jnp.int32)
                        for n in COUNT_NAMES])
    bits = jnp.concatenate(
        [counts, cyc_bits, conv_all.astype(jnp.int32)[None]])
    return bits, overflow


@partial(jax.jit,
         static_argnames=("n_keys", "mesh", "axis", "max_k", "max_rounds"))
def _core_check_sharded(h: PaddedLA, n_keys: int, mesh: Mesh, axis: str,
                        max_k: int = 128, max_rounds: int = 64):
    """core_check with the sweep's backward-edge axis sharded over the
    mesh.  Same bit layout as device_core.core_check."""
    n_shards = mesh.shape[axis]
    assert max_k % n_shards == 0, (max_k, n_shards)
    k_local = max_k // n_shards

    out = infer(h, n_keys)
    T = h.txn_type.shape[0]
    rep = P()

    @partial(shard_map, mesh=mesh,
             in_specs=(rep,) * 12, out_specs=(rep, rep, rep, rep))
    def sharded_sweep(rank_, e_src_, e_dst_, m_, cn_, cs_, cm_,
                      ib_, bid_, nb_, bsrc_, bdst_):
        off = jax.lax.axis_index(axis) * k_local
        return _sweep_window(2 * T, max_k, k_local, max_rounds,
                             rank_, e_src_, e_dst_, m_, cn_, cs_, cm_,
                             k_offset=off, axis_name=axis,
                             back_pre=(ib_, bid_, nb_),
                             back_tables=(bsrc_, bdst_))

    return projection_sweep_bits(
        out, max_k,
        lambda r, s, d, m, cn, cs, cm, bp, bt: sharded_sweep(
            r, s, d, m, cn, cs, cm, *bp, *bt))


def shard_padded(h: PaddedLA, mesh: Mesh, axis: str = "dp"
                 ) -> tuple[PaddedLA, bool]:
    """device_put a padded history with its op/mop/element axes sharded
    along the mesh axis (GSPMD input shardings for edge inference).

    Arrays whose leading dim doesn't divide the mesh (padded capacities
    are powers of two, so e.g. a 6-device mesh never divides) are
    replicated instead — inference then runs unsharded but the K-axis
    sweep sharding (the dominant cost at scale) still applies.  Returns
    (placed history, inference_sharded) — False means every array was
    replicated, a fact callers must surface (a user on a 6-device mesh
    should be able to see that input sharding didn't happen)."""
    n = mesh.shape[axis]
    sharded = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())
    any_sharded = False

    def put(x):
        nonlocal any_sharded
        divisible = x.ndim > 0 and x.shape[0] % n == 0
        any_sharded = any_sharded or divisible
        return jax.device_put(x, sharded if divisible else replicated)

    placed = jax.tree_util.tree_map(put, h)
    return placed, any_sharded


def check_sharded(p: PackedTxns | PaddedLA, mesh: Optional[Mesh] = None,
                  axis: str = "dp", max_k: int = 128,
                  max_rounds: int = 64, deadline=None, plan=None,
                  policy=None) -> dict:
    """Check ONE history sharded across the mesh; summary dict like a
    `check_batch` row.  Falls back to growing budgets (like
    `core_check_exact`) when the sweep overflows.  `deadline` bounds
    the grow loop (resilience contract; expiry raises
    `DeadlineExceeded`); the sharded dispatch itself is a guarded
    fault-plan site (``parallel.op-shard``), so JEPSEN_FAULTS chaos
    reaches the K-axis sharded sweep too."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.parallel.batch import _stage_bytes

    if mesh is None:
        mesh = Mesh(np.array(jax.devices()), (axis,))
    h = p if isinstance(p, PaddedLA) else pad_packed(p)
    n_keys = h.n_keys
    n_shards = mesh.shape[axis]
    with telemetry.span("parallel.op-shard", shards=n_shards,
                        max_k=max_k) as sp:
        h, infer_sharded = shard_padded(h, mesh, axis)
        _stage_bytes(sp, h)
        sp.set_attr(inference_sharded=infer_sharded)
        if max_k % n_shards:
            # non-power-of-two meshes: round the budget up to a mesh
            # multiple
            max_k = ((max_k // n_shards) + 1) * n_shards

        bits, over = grow_until_exact(
            lambda k, r: _core_check_sharded(h, n_keys, mesh, axis,
                                             max_k=k, max_rounds=r),
            max_k, max_rounds, round_to=n_shards, deadline=deadline,
            site="parallel.op-shard", plan=plan, policy=policy)
        over_i = int(np.asarray(over))

    row = np.asarray(bits)
    counts = {n: int(row[j]) for j, n in enumerate(COUNT_NAMES)}
    cycles = [bool(x) for x in row[len(COUNT_NAMES):-1]]
    converged = bool(row[-1]) and over_i == 0
    invalid = any(v > 0 for v in counts.values()) or any(cycles)
    return {
        "valid?": (not invalid) if converged else "unknown",
        "counts": counts,
        "cycles": {
            "G0": cycles[0], "G1c": cycles[1], "G2-family": cycles[2],
            "G2-family-process": cycles[3],
            "G2-family-realtime": cycles[4],
        },
        "exact": converged,
        # False = input arrays were replicated (leading dims don't divide
        # the mesh); the K-axis sweep sharding still applied
        "inference-sharded": infer_sharded,
    }
