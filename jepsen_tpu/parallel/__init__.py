"""Sharded checking: mesh helpers, batched multi-history data parallelism,
op-axis sharding (SURVEY.md §2.7)."""
