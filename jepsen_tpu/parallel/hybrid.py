"""Hybrid 2D checking: batch data-parallelism × K-axis sweep sharding.

The multi-host shape (SURVEY.md §5 "Distributed communication backend":
ICI collectives within a host/pod slice, DCN across hosts; §2.7 "Batched
multi-history DP").  The mesh has two axes:

  dcn — one batch shard per row (across hosts on a real pod: the only
        cross-row traffic is the final per-history bit vectors, so this
        axis can ride the slow DCN links)
  k   — the backward-edge windows of `parallel/op_shard.py` within a
        row (the per-round meta-graph all_gather + convergence psum stay
        on ICI)

Each (dcn-row, history) pair runs the full fused inference locally
(replicated along `k`, like `op_shard.shard_padded`'s fallback) and
sweeps only its (N, max_k/n_k) label-plane window — so a 100 × 1M-op
batch (BASELINE config 5) divides both ways: histories across rows,
label-plane memory across `k`.

On a real multi-host pod build the mesh with
`jax.experimental.mesh_utils.create_hybrid_device_mesh((n_k,), (n_dcn,))`
so `dcn` crosses hosts; on one host `make_hybrid_mesh` reshapes the
local devices.  Verdicts are bitwise-identical to unsharded
`check_batch` (differential-tested on the virtual mesh,
tests/test_parallel.py).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu import resilience
from jepsen_tpu.checkers.elle.device_infer import infer
from jepsen_tpu.history.soa import PackedTxns
from jepsen_tpu.ops.cycle_sweep import _sweep_window
from jepsen_tpu.parallel.batch import (
    batch_caps,
    pad_batch,
    summarize_batch_bits,
)
from jepsen_tpu.parallel.op_shard import projection_sweep_bits
from jepsen_tpu.utils.backend import get_shard_map

shard_map = get_shard_map()


def make_hybrid_mesh(n_dcn: int, n_k: int, devices=None) -> Mesh:
    devs = np.asarray(devices if devices is not None else jax.devices())
    assert devs.size >= n_dcn * n_k, (devs.size, n_dcn, n_k)
    return Mesh(devs[:n_dcn * n_k].reshape(n_dcn, n_k), ("dcn", "k"))


@partial(jax.jit, static_argnames=("n_keys", "mesh", "max_k", "max_rounds"))
def _hybrid_core(batch, n_keys: int, mesh: Mesh, max_k: int = 128,
                 max_rounds: int = 64):
    n_k = mesh.shape["k"]
    assert max_k % n_k == 0, (max_k, n_k)
    k_local = max_k // n_k
    T = batch.txn_type.shape[1]

    bspec = P("dcn")

    @partial(shard_map, mesh=mesh, in_specs=(bspec,),
             out_specs=(bspec, bspec))
    def rows(b):
        def one(h):
            out = infer(h, n_keys)

            def sweep(rank_, e_src_, e_dst_, m_, cn_, cs_, cm_, bp_, bt_):
                off = jax.lax.axis_index("k") * k_local
                return _sweep_window(2 * T, max_k, k_local, max_rounds,
                                     rank_, e_src_, e_dst_, m_, cn_, cs_,
                                     cm_, k_offset=off, axis_name="k",
                                     back_pre=bp_, back_tables=bt_)

            return projection_sweep_bits(out, max_k, sweep)

        return jax.vmap(one)(b)

    return rows(batch)


def check_batch_hybrid(ps: Sequence[PackedTxns], mesh: Mesh,
                       max_k: int = 128, max_rounds: int = 64,
                       deadline=None, plan=None, policy=None
                       ) -> List[dict]:
    """Check a batch of histories over a 2D ("dcn", "k") mesh; one
    summary dict per history (the `check_batch` row shape).

    The batch is padded to a multiple of the dcn axis with copies of the
    first history (dropped from the results).  Inexact verdicts
    (overflow / non-convergence) are re-run alone through the exact
    single-device path rather than approximated.  The 2D dispatch is a
    guarded fault-plan site (``parallel.hybrid``) like the other
    sharded seams.
    """
    from jepsen_tpu import telemetry
    from jepsen_tpu.parallel.batch import _stage_bytes

    n_dcn = mesh.shape["dcn"]
    n_k = mesh.shape["k"]
    if max_k % n_k:
        max_k = ((max_k // n_k) + 1) * n_k

    caps = batch_caps(ps)
    n_real = len(ps)
    fill = (-n_real) % n_dcn
    with telemetry.span("parallel.hybrid", histories=n_real,
                        dcn=n_dcn, k=n_k, max_k=max_k) as sp:
        batch = pad_batch(list(ps) + [ps[0]] * fill, caps)
        batch = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P("dcn"))),
            batch)
        _stage_bytes(sp, batch)

        from jepsen_tpu import compilecache

        bits, over = resilience.device_call(
            "parallel.hybrid",
            lambda: compilecache.call(
                "parallel.hybrid", _hybrid_core, batch,
                n_keys=batch.n_keys, mesh=mesh, max_k=max_k,
                max_rounds=max_rounds),
            deadline=deadline, plan=plan, policy=policy)
        return summarize_batch_bits(bits, over, batch, batch.n_keys,
                                    n_real, k_floor=max_k)
