"""Default-mesh resolution + campaign device-slot mesh slices.

The sharded-by-default decision point (ISSUE 12): every single-history
device check resolves its mesh here —

- the visible device set is this process's **slot slice** when a
  campaign/fleet scheduler assigned one (`set_active_slot`, or the
  ``JEPSEN_CAMPAIGN_DEVICE_SLOT``/``..._SLOTS`` env pair the subprocess
  runner exports), so one host drives N sub-meshes concurrently;
- ``JEPSEN_SHARDS`` forces a shard count (``1`` disables sharding);
- otherwise a history is checked sharded over ALL visible devices as
  a 1-D ``Mesh(("batch",))`` once it is big enough to amortize the
  partitioning overhead (``JEPSEN_SHARD_MIN_TXNS``, default 65536 —
  below that the single-device program wins on every backend we
  measured).

Keeping this module import-light matters: it is consulted from the
checker hot path and from the campaign scheduler threads.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

__all__ = ["set_active_slot", "active_slot", "slot_devices",
           "default_mesh", "place_sharded", "SHARD_MIN_TXNS"]

#: below this many (padded) txns the sharded program's partitioning
#: overhead exceeds its win — the single-device path is the default
SHARD_MIN_TXNS = 65536

_local = threading.local()
_mesh_cache: dict = {}


def set_active_slot(slot: Optional[int], n_slots: int = 1) -> None:
    """Pin this THREAD's device slice to campaign slot `slot` of
    `n_slots` (None clears).  The campaign scheduler calls this around
    each device run; the subprocess runner exports the env pair
    instead."""
    _local.slot = None if slot is None else (int(slot), max(1, int(n_slots)))


def set_forced_shards(n: Optional[int]) -> None:
    """Pin this THREAD's shard count (None clears) — the thread-safe
    form of JEPSEN_SHARDS, used by fleet workers running cells with a
    pinned ``opts["mesh"]`` (several workers may share one process)."""
    _local.shards = None if n is None else int(n)


def _forced_shards() -> Optional[int]:
    n = getattr(_local, "shards", None)
    if n is not None:
        return n
    env = os.environ.get("JEPSEN_SHARDS")
    if env is None:
        return None
    try:
        return int(env)
    except ValueError:
        return None


def active_slot() -> Optional[Tuple[int, int]]:
    """(slot, n_slots) for this thread, the env pair, or None."""
    sl = getattr(_local, "slot", None)
    if sl is not None:
        return sl
    env = os.environ.get("JEPSEN_CAMPAIGN_DEVICE_SLOT")
    if env is None:
        return None
    try:
        return (int(env),
                max(1, int(os.environ.get(
                    "JEPSEN_CAMPAIGN_DEVICE_SLOTS", 1))))
    except ValueError:
        return None


def slot_devices(slot: int, n_slots: int, devices=None) -> List:
    """Contiguous device slice for `slot` of `n_slots` sub-meshes.
    With fewer devices than slots, slots round-robin single devices
    (a 1-device slice = the plain single-device path)."""
    import jax

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n == 0:
        return devs
    if n < n_slots:
        return [devs[slot % n]]
    per = n // n_slots
    lo = (slot % n_slots) * per
    return devs[lo:lo + per]


def _visible_devices() -> List:
    import jax

    devs = jax.devices()
    sl = active_slot()
    if sl is not None:
        devs = slot_devices(sl[0], sl[1], devs)
    return devs


def default_mesh(n_txns: Optional[int] = None):
    """The 1-D ("batch",) mesh this check should shard over, or None
    for the single-device path.  `n_txns` (padded txn capacity) gates
    the size threshold; None skips the gate (caller forces).

    On the CPU backend, "multiple devices" are virtual host devices on
    the same cores, so unforced sharding can only lose (and XLA:CPU's
    GSPMD compile of the big checker programs is pathologically slow at
    >= 2^16-txn shapes — measured >20 min on the 1-core dev box, for
    the opt-in `parallel/` paths too, a pre-existing property).  There
    the sharded default activates only when explicitly forced
    (``JEPSEN_SHARDS``) or slot-assigned (a campaign/fleet mesh slice);
    real accelerator backends shard by default."""
    forced = _forced_shards()
    devs = _visible_devices()
    if forced is not None:
        if forced <= 1:
            return None
        devs = devs[:forced]
    else:
        try:
            min_txns = int(os.environ.get("JEPSEN_SHARD_MIN_TXNS",
                                          SHARD_MIN_TXNS))
        except ValueError:
            min_txns = SHARD_MIN_TXNS
        if n_txns is not None and n_txns < min_txns:
            return None
        import jax

        if jax.default_backend() == "cpu" and active_slot() is None:
            return None
    if len(devs) < 2:
        return None
    key = tuple(id(d) for d in devs)
    mesh = _mesh_cache.get(key)
    if mesh is None:
        import jax
        import numpy as np

        mesh = _mesh_cache[key] = jax.sharding.Mesh(
            np.array(devs), ("batch",))
    return mesh


def place_sharded(x, mesh=None):
    """device_put `x` with NamedSharding(P("batch")) on its leading
    axis when a default mesh is active and the axis divides; replicate
    otherwise.  The cheap GSPMD on-ramp for the embarrassingly
    shardable invariants reductions (bank row sums, session cummax
    inputs)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        # the leading dim stands in for n_txns so the SHARD_MIN size
        # gate applies to these small reductions too
        mesh = default_mesh(x.shape[0] if getattr(x, "ndim", 0) >= 1
                            else 0)
    if mesh is None:
        return jax.numpy.asarray(x)
    n = mesh.devices.size
    divisible = getattr(x, "ndim", 0) >= 1 and x.shape[0] % n == 0
    return jax.device_put(
        x, NamedSharding(mesh, P("batch") if divisible else P()))
