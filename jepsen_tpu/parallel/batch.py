"""Batched multi-history checking sharded over a device mesh.

The reference's closest analogue is `jepsen.independent` (checking per-key
sub-histories "independently" on one JVM, SURVEY.md §2.1); here it becomes
true data parallelism: a batch of histories is sharded over the mesh's
`dp` axis with `shard_map`, each device runs the full single-jit core
check (`device_core.core_check`) on its shard via `vmap`, and the per-
history anomaly bitmaps are combined with an ICI `all_gather` — the
BASELINE.json config-5 shape (100 x 1M-op histories on a v5e-8).

Histories in a batch share padded capacities (pad to the max; the packed
generator or the store's chunked loader provides equal-shaped arrays).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu import resilience, telemetry
from jepsen_tpu.checkers.elle.device_core import core_check
from jepsen_tpu.checkers.elle.device_infer import PaddedLA, pad_packed
from jepsen_tpu.history.soa import PackedTxns
from jepsen_tpu.utils.backend import get_shard_map

shard_map = get_shard_map()


def make_mesh(n_devices: int = 0, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    if n_devices:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def stack_padded(hs: Sequence[PaddedLA]) -> PaddedLA:
    """Stack equal-shaped padded histories along a leading batch axis."""
    first = hs[0]
    out = {}
    for f in ("txn_type", "txn_process", "txn_invoke_pos",
              "txn_complete_pos", "txn_mask", "mop_txn", "mop_kind",
              "mop_key", "mop_val", "mop_rd_start", "mop_rd_len", "mop_mask",
              "rd_elems", "rd_elem_mask"):
        out[f] = jnp.stack([getattr(h, f) for h in hs])
    # IR derived-order columns stack only when every member carries them
    # at the same shape (else the program derives in-program, as before)
    for f in ("run_sort", "inv_run", "key_ord_len", "key_ord_read",
              "proc_order", "barrier_order", "barrier_bi"):
        vals = [getattr(h, f) for h in hs]
        if all(v is not None for v in vals) and \
                len({v.shape for v in vals}) == 1:
            out[f] = jnp.stack(vals)
    # static layout facts must hold for EVERY stacked history (vmap shares
    # one program): AND the flags, take the widest run bucket/capacity
    return PaddedLA(
        n_keys=first.n_keys, n_vals=first.n_vals,
        txn_major=all(h.txn_major for h in hs),
        run_cap=(max(h.run_cap for h in hs)
                 if all(h.run_cap for h in hs) else 0),
        complete_monotone=all(h.complete_monotone for h in hs),
        v_cap=(max(h.v_cap for h in hs)
               if all(h.v_cap for h in hs) else 0),
        o_cap=(max(h.o_cap for h in hs)
               if all(h.o_cap for h in hs) else 0),
        app_val_mono=all(h.app_val_mono for h in hs),
        rd_start_mono=all(h.rd_start_mono for h in hs),
        proc_seq=all(h.proc_seq for h in hs),
        **out)


def batch_caps(ps: Sequence[PackedTxns]) -> tuple:
    """The shared padded capacities (T, M, R, n_keys, V, O) for a batch.
    V/O are the IR value-table / order-table capacities (the batch must
    share ONE executable, so per-history capacities are maxed)."""
    from jepsen_tpu.checkers.elle.device_infer import _ir_facts, \
        pow2_at_least

    T = pow2_at_least(max(p.n_txns for p in ps))
    M = pow2_at_least(max(p.n_mops for p in ps))
    R = pow2_at_least(max(max(len(p.rd_elems), p.n_vals, p.n_keys + 1)
                          for p in ps))
    nk = max(p.n_keys for p in ps)
    facts = {id(p): _ir_facts(p) for p in ps}
    vs = [f["v_cap"] for f in facts.values()]
    os_ = [f["o_cap"] for f in facts.values()]
    V = max(vs) if all(vs) else 0
    O = max(os_) if all(os_) else 0
    caps = (T, M, R, nk, min(V, R), min(O, R))
    return _BatchCaps(caps, facts)


class _BatchCaps(tuple):
    """The (T, M, R, nk, V, O) capacity tuple, carrying the per-history
    `_ir_facts` so `pad_batch` doesn't re-derive them (they are full
    O(n_mops) host scans).  Plain tuples remain accepted everywhere."""

    def __new__(cls, caps, facts):
        self = super().__new__(cls, caps)
        self.facts = facts
        return self


def pad_batch(ps: Sequence[PackedTxns], caps: tuple = None) -> PaddedLA:
    """Pad a list of PackedTxns to shared capacities and stack them.

    `caps` (from `batch_caps`) overrides the per-call maxima so several
    groups of one larger batch share one compiled executable.  Legacy
    4-tuples (T, M, R, nk) are accepted; V/O then derive per batch."""
    if caps is None:
        caps = batch_caps(ps)
    facts = getattr(caps, "facts", {})
    if len(caps) == 4:
        caps = (*caps, 0, 0)
    T, M, R, nk, V, O = caps
    padded = []
    for p in ps:
        h = pad_packed(p, t_pad=T, m_pad=M, r_pad=R, v_pad=V, o_pad=O,
                       ir_facts=facts.get(id(p)))
        h.n_keys = nk
        padded.append(h)
    return stack_padded(padded)


@partial(jax.jit, static_argnames=("n_keys",))
def _batched_core(batch: PaddedLA, n_keys: int):
    return jax.vmap(lambda h: core_check(h, n_keys))(batch)


@partial(jax.jit, static_argnames=("n_keys", "mesh", "axis"))
def _batched_sharded(batch: PaddedLA, *, n_keys: int, mesh: Mesh,
                     axis: str):
    """The mesh branch of check_batch as a module-level jit (statics by
    keyword) so the AOT compile cache can key and serialize it — same
    shard_map program the old per-call closure built."""
    spec = P(axis)

    @partial(shard_map, mesh=mesh, in_specs=(spec,),
             out_specs=(spec, spec))
    def rows(b):
        return jax.vmap(lambda h: core_check(h, n_keys))(b)

    return rows(batch)


def check_batch(ps: Sequence[PackedTxns], mesh: Mesh = None,
                axis: str = "dp", caps: tuple = None,
                deadline=None, plan=None, policy=None) -> List[dict]:
    """Check a batch of histories, sharded across the mesh if given.

    Returns one summary dict per history: {"valid?", "bits", "exact"}.
    Batches that don't divide the mesh axis are padded internally (padding
    rows are dropped from the results).  Histories whose sweep overflowed
    the default backward-edge budget are re-run alone with a grown budget,
    so verdicts are definitive whenever the caps allow.  `caps` pins the
    padded capacities (see `batch_caps`).

    The device dispatch runs under the resilience guard: `deadline` is
    polled before it, transient failures retry per `policy`, and the
    active `plan` (explicit > JEPSEN_FAULTS chaos) fires its synthetic
    faults at the ``parallel.batch`` site — the multi-device paths are
    inside the chaos perimeter, not around it.
    """
    n_real = len(ps)
    if mesh is not None:
        # pad the batch with copies of history 0 so it divides the mesh;
        # padding rows are dropped by summarize_batch_bits (the same
        # pre-stack fill check_batch_hybrid and _checkpointed use)
        ps = list(ps) + [ps[0]] * ((-n_real) % mesh.devices.size)
    # one child span per sharded dispatch (ROADMAP telemetry open item:
    # the parallel/ paths were span-invisible, so shrink probes and
    # campaign cells over them were unattributable); bytes staged is
    # what the mesh actually holds resident during the check
    with telemetry.span("parallel.batch", histories=n_real,
                        shards=(mesh.devices.size if mesh is not None
                                else 0)) as sp:
        batch = pad_batch(ps, caps)
        n_keys = batch.n_keys
        _stage_bytes(sp, batch)

        from jepsen_tpu import compilecache

        if mesh is None:
            bits, over = resilience.device_call(
                "parallel.batch",
                lambda: compilecache.call("parallel.batch",
                                          _batched_core, batch,
                                          n_keys=n_keys),
                deadline=deadline, plan=plan, policy=policy)
        else:
            in_shard = NamedSharding(mesh, P(axis))

            def put(x):
                return jax.device_put(x, in_shard)

            batch = jax.tree_util.tree_map(put, batch)
            bits, over = resilience.device_call(
                "parallel.batch",
                lambda: compilecache.call("parallel.batch",
                                          _batched_sharded, batch,
                                          n_keys=n_keys, mesh=mesh,
                                          axis=axis),
                deadline=deadline, plan=plan, policy=policy)

        return summarize_batch_bits(bits, over, batch, n_keys, n_real)


def _stage_bytes(sp, tree) -> None:
    """Attach the staged-array byte total to a dispatch span + the
    device-bytes-staged counter (no-op when telemetry is off)."""
    if not telemetry.enabled():
        return
    n = sum(int(getattr(x, "nbytes", 0))
            for x in jax.tree_util.tree_leaves(tree))
    sp.set_attr(bytes_staged=n)
    telemetry.registry().counter("device-bytes-staged").inc(n)


def summarize_batch_bits(bits, over, batch, n_keys: int, n_real: int,
                         k_floor: int = 128) -> List[dict]:
    """Per-history summary rows from batched (bits, over) outputs, with
    the exact-rerun fallback: any inexact verdict (backward-edge
    overflow or fixpoint truncation) re-runs that history alone through
    `core_check_exact`, seeding the budget past the observed overflow so
    the failed config isn't repeated.  Shared by `check_batch` and the
    hybrid 2D path (verdicts stay identical by construction)."""
    from jepsen_tpu.checkers.elle.device_core import COUNT_NAMES, \
        core_check_exact

    bits = np.array(bits)   # writable copies — np.asarray of a jax
    over = np.array(over)   # array is a read-only view
    out: List[dict] = []
    for i in range(n_real):
        row = bits[i]
        counts = {n: int(row[j]) for j, n in enumerate(COUNT_NAMES)}
        # a positive count is computed BEFORE the cycle sweep and is
        # exact regardless of sweep convergence: the history is
        # definitively invalid, so skip the (compile-heavy at 1M-op
        # shapes) exact rerun — it could only refine the cycle list
        invalid_by_counts = any(v > 0 for v in counts.values())
        if (int(over[i]) > 0 or int(row[-1]) != 1) \
                and not invalid_by_counts:
            from jepsen_tpu.checkers.elle.device_infer import pow2_at_least

            k0 = pow2_at_least(k_floor + int(over[i]), floor=k_floor)
            h_i = jax.tree_util.tree_map(lambda x: x[i], batch)
            b2, o2 = core_check_exact(h_i, n_keys, max_k=k0)
            row = np.asarray(b2)
            over[i] = max(0, int(np.asarray(o2)))
            counts = {n: int(row[j]) for j, n in enumerate(COUNT_NAMES)}
        cycles = [bool(x) for x in row[len(COUNT_NAMES):-1]]
        converged = bool(row[-1]) and int(over[i]) == 0
        invalid = any(v > 0 for v in counts.values()) or any(cycles)
        out.append({
            "valid?": False if invalid else
                      (True if converged else "unknown"),
            "counts": counts,
            "cycles": {
                "G0": cycles[0], "G1c": cycles[1], "G2-family": cycles[2],
                "G2-family-process": cycles[3],
                "G2-family-realtime": cycles[4],
            },
            # the VERDICT is exact when the sweep converged or when the
            # invalidity stands on counts alone (the cycle dict may
            # then be under-reported — counts already decide validity)
            "exact": bool(converged or invalid),
        })
    return out


def check_batch_checkpointed(ps: Sequence[PackedTxns], ckpt_path: str,
                             mesh: Mesh = None, axis: str = "dp",
                             group_size: int = 0,
                             on_group=None) -> List[dict]:
    """`check_batch` with chunk-level progress markers (SURVEY.md §5
    checkpoint/resume: "checkpointable device checking … since a 10M-op
    SCC run is minutes").

    The batch is processed in groups of `group_size` histories (default:
    one mesh row, or 8 unsharded); after each group its verdicts are
    appended to `ckpt_path` as JSON lines {"i": …, "result": …} and
    fsync'd.  A rerun with the same path skips every history already
    judged — a crashed control process resumes mid-batch instead of
    repaying the full device run.  Grouping also bounds device memory:
    one group's padded arrays are resident at a time, not the whole
    batch (the config-5 regime: 100 x 1M-op histories).

    The checkpoint records per-history content digests; a resume against
    different histories at the same path raises instead of mixing runs.

    `on_group(info)` (optional) is called after each group's checkpoint
    record is durable, with {"group", "indices", "wall_s", "done"} —
    progress reporting and crash-injection for the config-5 artifact.
    """
    import hashlib
    import json
    import os
    import time as _time

    def digest(p: PackedTxns) -> str:
        # every packed column that inference reads: two runs with the
        # same op content but a different interleaving (process
        # assignment, invoke/complete order, read segments) must NOT
        # share a digest — process/realtime cycle bits depend on them
        h = hashlib.sha256()
        # declared metadata first: n_keys/n_vals feed padding caps and
        # inference sentinels, so identical arrays under different
        # declared spaces must not share a digest
        h.update(np.int64([p.n_keys, p.n_vals, p.n_txns,
                           p.n_mops]).tobytes())
        for a in (p.txn_type, p.txn_process, p.txn_invoke_pos,
                  p.txn_complete_pos, p.mop_txn, p.mop_kind, p.mop_key,
                  p.mop_val, p.mop_rd_start, p.mop_rd_len, p.rd_elems):
            h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()[:16]

    if not group_size:
        group_size = mesh.devices.size if mesh is not None else 8
    done: dict = {}
    if os.path.exists(ckpt_path):
        good_bytes = 0
        with open(ckpt_path, "rb") as f:
            for line in f:
                if not line.strip():
                    good_bytes += len(line)
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # torn trailing record from a crash mid-append — the
                    # exact scenario checkpoints exist for; drop it and
                    # resume from the last durable record
                    break
                if not line.endswith(b"\n"):
                    # parseable but unterminated: a later append would
                    # fuse with it — treat as torn too
                    break
                done[rec["i"]] = rec
                good_bytes += len(line)
        with open(ckpt_path, "r+b") as f:
            f.truncate(good_bytes)
    out: List[dict] = [None] * len(ps)
    digests = [digest(p) for p in ps]
    for i, rec in done.items():
        if i >= len(ps) or rec["digest"] != digests[i]:
            raise ValueError(
                f"checkpoint {ckpt_path} is from a different batch "
                f"(history {i} digest mismatch); refusing to mix runs")
        out[i] = rec["result"]

    # one set of padded capacities across groups: per-group maxima would
    # recompile the check whenever a group's largest history crosses a
    # pow2 bucket (a ~19 min cold compile at TPU 1M-op shapes)
    caps = batch_caps(ps)
    with open(ckpt_path, "a") as f:
        for g0 in range(0, len(ps), group_size):
            idx = [i for i in range(g0, min(g0 + group_size, len(ps)))
                   if out[i] is None]
            if not idx:
                continue
            # pad partial/resumed groups to a fixed batch dim (copies of
            # the first member, dropped below): a smaller leading dim
            # would recompile _batched_core — the very cost caps pin down
            group = [ps[i] for i in idx]
            group += [group[0]] * (group_size - len(group))
            t_g = _time.monotonic()
            results = check_batch(group, mesh=mesh, axis=axis,
                                  caps=caps)[:len(idx)]
            for i, r in zip(idx, results):
                out[i] = r
                f.write(json.dumps(
                    {"i": i, "digest": digests[i], "result": r}) + "\n")
            f.flush()
            os.fsync(f.fileno())
            if on_group is not None:
                on_group({"group": g0 // group_size, "indices": idx,
                          "wall_s": round(_time.monotonic() - t_g, 2),
                          "done": sum(r is not None for r in out)})
    return out
