"""Batched multi-history checking sharded over a device mesh.

The reference's closest analogue is `jepsen.independent` (checking per-key
sub-histories "independently" on one JVM, SURVEY.md §2.1); here it becomes
true data parallelism: a batch of histories is sharded over the mesh's
`dp` axis with `shard_map`, each device runs the full single-jit core
check (`device_core.core_check`) on its shard via `vmap`, and the per-
history anomaly bitmaps are combined with an ICI `all_gather` — the
BASELINE.json config-5 shape (100 x 1M-op histories on a v5e-8).

Histories in a batch share padded capacities (pad to the max; the packed
generator or the store's chunked loader provides equal-shaped arrays).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu.checkers.elle.device_core import core_check
from jepsen_tpu.checkers.elle.device_infer import PaddedLA, pad_packed
from jepsen_tpu.history.soa import PackedTxns


def make_mesh(n_devices: int = 0, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    if n_devices:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def stack_padded(hs: Sequence[PaddedLA]) -> PaddedLA:
    """Stack equal-shaped padded histories along a leading batch axis."""
    first = hs[0]
    out = {}
    for f in ("txn_type", "txn_process", "txn_invoke_pos",
              "txn_complete_pos", "txn_mask", "mop_txn", "mop_kind",
              "mop_key", "mop_val", "mop_rd_start", "mop_rd_len", "mop_mask",
              "rd_elems", "rd_elem_mask"):
        out[f] = jnp.stack([getattr(h, f) for h in hs])
    return PaddedLA(n_keys=first.n_keys, n_vals=first.n_vals, **out)


def pad_batch(ps: Sequence[PackedTxns]) -> PaddedLA:
    """Pad a list of PackedTxns to shared capacities and stack them."""
    from jepsen_tpu.checkers.elle.device_infer import pow2_at_least

    T = pow2_at_least(max(p.n_txns for p in ps))
    M = pow2_at_least(max(p.n_mops for p in ps))
    R = pow2_at_least(max(max(len(p.rd_elems), p.n_vals, p.n_keys + 1)
                          for p in ps))
    nk = max(p.n_keys for p in ps)
    padded = []
    for p in ps:
        h = pad_packed(p, t_pad=T, m_pad=M, r_pad=R)
        h.n_keys = nk
        padded.append(h)
    return stack_padded(padded)


@partial(jax.jit, static_argnames=("n_keys",))
def _batched_core(batch: PaddedLA, n_keys: int):
    return jax.vmap(lambda h: core_check(h, n_keys))(batch)


def check_batch(ps: Sequence[PackedTxns], mesh: Mesh = None,
                axis: str = "dp") -> List[dict]:
    """Check a batch of histories, sharded across the mesh if given.

    Returns one summary dict per history: {"valid?", "bits", "exact"}.
    Batches that don't divide the mesh axis are padded internally (padding
    rows are dropped from the results).  Histories whose sweep overflowed
    the default backward-edge budget are re-run alone with a grown budget,
    so verdicts are definitive whenever the caps allow.
    """
    batch = pad_batch(ps)
    n_keys = batch.n_keys

    if mesh is None:
        bits, over = _batched_core(batch, n_keys)
    else:
        n_dev = mesh.devices.size
        n_real = len(ps)
        if n_real % n_dev:
            # pad the batch with copies of history 0 so it divides the
            # mesh; padding rows are dropped below
            n_fill = n_dev - (n_real % n_dev)
            fill = jax.tree_util.tree_map(
                lambda x: jnp.concatenate(
                    [x, jnp.broadcast_to(x[:1], (n_fill,) + x.shape[1:])]),
                batch)
            batch = fill
        spec = P(axis)
        in_shard = NamedSharding(mesh, spec)

        def put(x):
            return jax.device_put(x, in_shard)

        batch = jax.tree_util.tree_map(put, batch)

        @partial(jax.shard_map, mesh=mesh, in_specs=(spec,),
                 out_specs=(spec, spec))
        def sharded(b):
            bits, over = jax.vmap(lambda h: core_check(h, n_keys))(b)
            return bits, over

        bits, over = sharded(batch)

    bits = np.array(bits)
    over = np.array(over)
    out = []
    from jepsen_tpu.checkers.elle.device_core import COUNT_NAMES, \
        core_check_exact
    for i in range(len(ps)):
        row = bits[i]
        if int(over[i]) > 0 or int(row[-1]) != 1:
            # inexact (backward-edge overflow or fixpoint truncation):
            # re-run this history alone, seeding the budget past the
            # overflow already observed so the failed config isn't repeated
            from jepsen_tpu.checkers.elle.device_infer import pow2_at_least

            k0 = pow2_at_least(128 + int(over[i]), floor=128)
            h_i = jax.tree_util.tree_map(lambda x: x[i], batch)
            b2, o2 = core_check_exact(h_i, n_keys, max_k=k0)
            row = np.asarray(b2)
            over[i] = max(0, int(np.asarray(o2)))
        counts = {n: int(row[j]) for j, n in enumerate(COUNT_NAMES)}
        cycles = [bool(x) for x in row[len(COUNT_NAMES):-1]]
        converged = bool(row[-1]) and int(over[i]) == 0
        invalid = any(v > 0 for v in counts.values()) or any(cycles)
        out.append({
            "valid?": (not invalid) if converged else "unknown",
            "counts": counts,
            "cycles": {
                "G0": cycles[0], "G1c": cycles[1], "G2-family": cycles[2],
                "G2-family-process": cycles[3],
                "G2-family-realtime": cycles[4],
            },
            "exact": converged,
        })
    return out
