"""Value codec for persisted tests and histories.

Equivalent of the reference's fressian read/write handlers
(`jepsen/src/jepsen/store/fressian.clj`, SURVEY.md §2.1): a tagged-JSON
encoding that round-trips the value types op histories actually contain —
tuples (micro-ops like ``("append", k, v)``), dicts with non-string keys
(read results ``{k: v}``), sets, bytes, and numpy scalars — none of which
plain JSON preserves.

Tags use a "§" prefix, which cannot collide with workload data keys in
practice; a literal dict key starting with "§" is itself escaped.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

_TUPLE = "§t"
_DICT = "§d"  # dict with non-string keys, as [[k, v], ...]
_SET = "§s"
_FSET = "§fs"
_BYTES = "§b"
_ESCAPE = "§§"  # literal dict whose keys start with §


def _encode(v: Any) -> Any:
    if isinstance(v, tuple):
        return {_TUPLE: [_encode(x) for x in v]}
    if isinstance(v, frozenset):
        return {_FSET: [_encode(x) for x in sorted(v, key=repr)]}
    if isinstance(v, set):
        return {_SET: [_encode(x) for x in sorted(v, key=repr)]}
    if isinstance(v, (bytes, bytearray)):
        return {_BYTES: bytes(v).hex()}
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, np.ndarray):
        return {_TUPLE: [_encode(x) for x in v.tolist()]}
    if isinstance(v, dict):
        if all(isinstance(k, str) for k in v):
            if any(k.startswith("§") for k in v):
                return {_ESCAPE: [[k, _encode(x)] for k, x in v.items()]}
            return {k: _encode(x) for k, x in v.items()}
        return {_DICT: [[_encode(k), _encode(x)] for k, x in v.items()]}
    if isinstance(v, list):
        return [_encode(x) for x in v]
    return v


def _decode(v: Any) -> Any:
    if isinstance(v, dict):
        if len(v) == 1:
            ((tag, payload),) = v.items()
            if tag == _TUPLE:
                return tuple(_decode(x) for x in payload)
            if tag == _SET:
                return set(_decode(x) for x in payload)
            if tag == _FSET:
                return frozenset(_decode(x) for x in payload)
            if tag == _BYTES:
                return bytes.fromhex(payload)
            if tag == _DICT:
                return {_decode(k): _decode(x) for k, x in payload}
            if tag == _ESCAPE:
                return {k: _decode(x) for k, x in payload}
        return {k: _decode(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode(x) for x in v]
    return v


def dumps(v: Any) -> bytes:
    """Encode a value to tagged-JSON bytes."""
    return json.dumps(_encode(v), separators=(",", ":"), default=_fallback).encode()


def loads(b: bytes) -> Any:
    """Decode tagged-JSON bytes back to the original value."""
    return _decode(json.loads(b.decode()))


def _fallback(v: Any) -> Any:
    # Non-data objects in a test map (clients, DBs, generators) are not
    # persisted structurally; store a readable placeholder, as the reference
    # does for unserializable test-map entries.
    return {"§obj": f"{type(v).__module__}.{type(v).__qualname__}"}
