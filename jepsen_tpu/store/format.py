"""Block-structured binary ``.jepsen`` file format.

Equivalent of the reference's `jepsen/src/jepsen/store/format.clj`
(SURVEY.md §2.1): a single on-disk file holding a test run, built from
checksummed blocks, with

- a **partial test block** (the test map minus its history and results, so
  loading a test for browsing never deserializes 10M ops),
- **chunked history blocks** (~16k ops per chunk) referenced from a history
  index block, loaded lazily one chunk at a time,
- **in-place append of results**: `save_1` appends a results block and a new
  root block and rewrites only the fixed-size root pointer at the file head —
  history blocks are never rewritten.

Layout::

    magic "JPTPUv1\\n" | u64 root-offset | block*
    block := u8 type | u64 payload-len | u32 crc32(payload) | payload

The chunked layout is what lets the TPU checker stream a long history to the
device chunk-by-chunk (host staging buffers -> PCIe) without materialising
the whole run in host memory, mirroring the reference's big-vector blocks +
soft-reference chunks (`jepsen/history/core.clj`).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Any, Iterator, List, Optional, Sequence

from ..history.ops import History, Op
from . import codec

MAGIC = b"JPTPUv1\n"
_ROOT_SLOT = len(MAGIC)  # offset of the u64 root pointer
_HEADER_LEN = _ROOT_SLOT + 8

# Block types.
B_ROOT = 1  # codec map {"test": off, "history": off, "results": off}
B_TEST = 2  # codec map: partial test (no history/results)
B_HISTORY_INDEX = 3  # codec map {"count": n, "chunks": [off, ...]}
B_HISTORY_CHUNK = 4  # codec list of op dicts
B_RESULTS = 5  # codec map

CHUNK_SIZE = 16384  # ops per history chunk, as in the reference (~16k)

_BLOCK_HDR = struct.Struct("<BQI")


class FormatError(Exception):
    pass


def _write_block(f, btype: int, payload: bytes) -> int:
    """Append one block at EOF; returns its offset."""
    f.seek(0, os.SEEK_END)
    off = f.tell()
    f.write(_BLOCK_HDR.pack(btype, len(payload), zlib.crc32(payload)))
    f.write(zlib.compress(payload, 1))
    return off


def _read_block(f, off: int, expect: Optional[int] = None) -> bytes:
    f.seek(off)
    hdr = f.read(_BLOCK_HDR.size)
    if len(hdr) < _BLOCK_HDR.size:
        raise FormatError(f"truncated block header at {off}")
    btype, plen, crc = _BLOCK_HDR.unpack(hdr)
    if expect is not None and btype != expect:
        raise FormatError(f"expected block type {expect} at {off}, got {btype}")
    # Compressed payload runs to the next block; decompressObj consumes
    # exactly one zlib stream so we can read generously.
    d = zlib.decompressobj()
    chunks: List[bytes] = []
    try:
        while True:
            raw = f.read(1 << 20)
            if not raw:
                break
            chunks.append(d.decompress(raw))
            if d.eof:
                break
    except zlib.error as e:
        raise FormatError(f"block at {off}: corrupt payload ({e})") from e
    payload = b"".join(chunks)
    if len(payload) != plen:
        raise FormatError(f"block at {off}: length {len(payload)} != {plen}")
    if zlib.crc32(payload) != crc:
        raise FormatError(f"block at {off}: checksum mismatch")
    return payload


def _set_root(f, off: int) -> None:
    f.seek(_ROOT_SLOT)
    f.write(struct.pack("<Q", off))
    f.flush()
    os.fsync(f.fileno())


def _get_root(f) -> int:
    f.seek(_ROOT_SLOT)
    (off,) = struct.unpack("<Q", f.read(8))
    return off


class LazyHistory:
    """Chunk-lazy view of a stored history.

    Indexable and iterable like :class:`History`; chunks are decoded on
    demand and a small LRU of decoded chunks is kept (the soft-reference
    analogue).  `materialize()` returns a fully-loaded History.
    """

    def __init__(self, path: str, chunk_offsets: Sequence[int], count: int):
        self._path = path
        self._chunks = list(chunk_offsets)
        self._count = count
        self._cache: dict = {}
        self._cache_order: List[int] = []
        self._max_cached = 8

    def __len__(self) -> int:
        return self._count

    def _load_chunk(self, ci: int) -> List[Op]:
        if ci in self._cache:
            return self._cache[ci]
        with open(self._path, "rb") as f:
            payload = _read_block(f, self._chunks[ci], B_HISTORY_CHUNK)
        ops = [Op.from_dict(d) for d in codec.loads(payload)]
        self._cache[ci] = ops
        self._cache_order.append(ci)
        while len(self._cache_order) > self._max_cached:
            evict = self._cache_order.pop(0)
            self._cache.pop(evict, None)
        return ops

    def __getitem__(self, i: int) -> Op:
        if i < 0:
            i += self._count
        if not 0 <= i < self._count:
            raise IndexError(i)
        return self._load_chunk(i // CHUNK_SIZE)[i % CHUNK_SIZE]

    def __iter__(self) -> Iterator[Op]:
        for ci in range(len(self._chunks)):
            yield from self._load_chunk(ci)

    def iter_chunks(self) -> Iterator[List[Op]]:
        """Stream decoded chunks in order — the device-staging entry point."""
        for ci in range(len(self._chunks)):
            yield self._load_chunk(ci)

    def materialize(self) -> History:
        return History(list(self), reindex=False)


class JepsenFile:
    """Reader/writer for one ``.jepsen`` file."""

    def __init__(self, path: str):
        self.path = path

    # -- writing -----------------------------------------------------------

    # Never persisted: credentials would otherwise be readable by anyone
    # with store access (incl. the web UI's file browser).
    SECRET_KEYS = ("password", "private_key_path")

    def write_test(self, test: dict, history: Optional[History]) -> None:
        """Phase-0 write: partial test + chunked history + root."""
        partial = {
            k: v for k, v in test.items()
            if k not in ("history", "results") and k not in self.SECRET_KEYS
        }
        with open(self.path, "w+b") as f:
            f.write(MAGIC)
            f.write(struct.pack("<Q", 0))
            test_off = _write_block(f, B_TEST, codec.dumps(partial))
            hist_off = -1
            if history is not None:
                chunk_offs = []
                ops = list(history)
                for i in range(0, len(ops), CHUNK_SIZE):
                    chunk = [op.to_dict() for op in ops[i : i + CHUNK_SIZE]]
                    chunk_offs.append(
                        _write_block(f, B_HISTORY_CHUNK, codec.dumps(chunk))
                    )
                hist_off = _write_block(
                    f,
                    B_HISTORY_INDEX,
                    codec.dumps({"count": len(ops), "chunks": chunk_offs}),
                )
            root_off = _write_block(
                f,
                B_ROOT,
                codec.dumps({"test": test_off, "history": hist_off, "results": -1}),
            )
            _set_root(f, root_off)

    def append_results(self, results: dict) -> None:
        """Phase-1 write: append results + new root; history untouched."""
        with open(self.path, "r+b") as f:
            root = codec.loads(_read_block(f, _get_root(f), B_ROOT))
            res_off = _write_block(f, B_RESULTS, codec.dumps(results))
            root["results"] = res_off
            new_root = _write_block(f, B_ROOT, codec.dumps(root))
            _set_root(f, new_root)

    # -- reading -----------------------------------------------------------

    def _root(self, f) -> dict:
        if f.read(len(MAGIC)) != MAGIC:
            raise FormatError(f"{self.path}: bad magic")
        off = _get_root(f)
        if off == 0:
            raise FormatError(f"{self.path}: no root written")
        return codec.loads(_read_block(f, off, B_ROOT))

    def read_test(self) -> dict:
        """Load the partial test map (no history/results decode)."""
        with open(self.path, "rb") as f:
            root = self._root(f)
            return codec.loads(_read_block(f, root["test"], B_TEST))

    def read_history(self) -> Optional[LazyHistory]:
        with open(self.path, "rb") as f:
            root = self._root(f)
            if root["history"] < 0:
                return None
            idx = codec.loads(_read_block(f, root["history"], B_HISTORY_INDEX))
        return LazyHistory(self.path, idx["chunks"], idx["count"])

    def read_results(self) -> Optional[dict]:
        with open(self.path, "rb") as f:
            root = self._root(f)
            if root["results"] is None or root["results"] < 0:
                return None
            return codec.loads(_read_block(f, root["results"], B_RESULTS))

    def read(self) -> dict:
        """Full load: test map with :history (lazy) and :results attached."""
        test = self.read_test()
        h = self.read_history()
        if h is not None:
            test["history"] = h
        res = self.read_results()
        if res is not None:
            test["results"] = res
        return test
