"""Persistent test store.

Equivalent of `jepsen/src/jepsen/store.clj` (SURVEY.md §2.1): each run gets a
directory ``store/<test-name>/<timestamp>/`` containing

- ``test.jepsen``  — the block-structured binary file (test + chunked
  history + results; see :mod:`jepsen_tpu.store.format`),
- ``history.json`` / ``results.json`` — human-readable mirrors,
- ``jepsen.log``   — the run log (wired by `core.run`),
- downloaded node logs under ``<node>/``.

Two-phase writes, exactly as the reference: :func:`save_0` persists the test
and history *before* analysis (so a crashed checker loses nothing), and
:func:`save_1` appends results afterwards without rewriting history blocks.
A ``latest`` symlink per test name and a global ``current`` symlink track the
most recent run.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Any, Iterator, List, Optional

from ..history.ops import History
from . import codec
from .format import JepsenFile, LazyHistory

logger = logging.getLogger("jepsen.store")

BASE = "store"
TEST_FILE = "test.jepsen"


def _base(test_or_opts: Optional[dict] = None) -> str:
    if test_or_opts and test_or_opts.get("store-dir"):
        return test_or_opts["store-dir"]
    return BASE


def sanitize(name: str) -> str:
    s = "".join(c if c.isalnum() or c in "-_. " else "_" for c in name)
    if not s or set(s) <= {"."}:  # "." / ".." would escape the store root
        return "test"
    return s


def timestamp(t: Optional[float] = None) -> str:
    # UTC so directory names sort chronologically even across DST shifts.
    t = time.time() if t is None else t
    return time.strftime("%Y%m%dT%H%M%S", time.gmtime(t)) + f".{int(t * 1000) % 1000:03d}Z"


def test_dir(test: dict) -> str:
    """The run directory for a test, creating it (and the timestamp) on
    first use; cached in the test map under "start-time-str"."""
    name = sanitize(test.get("name", "test"))
    ts = test.get("start-time-str")
    if ts is None:
        ts = timestamp(test.get("start-time"))
        test["start-time-str"] = ts
    d = os.path.join(_base(test), name, ts)
    os.makedirs(d, exist_ok=True)
    return d


def path(test: dict, *components: str) -> str:
    return os.path.join(test_dir(test), *components)


def _relink(link: str, target: str) -> None:
    tmp = link + ".tmp"
    try:
        if os.path.lexists(tmp):
            os.remove(tmp)
        os.symlink(target, tmp)
        os.replace(tmp, link)
    except OSError:
        pass  # symlinks unsupported (exotic fs); non-fatal


def update_symlinks(test: dict) -> None:
    d = test_dir(test)
    name = sanitize(test.get("name", "test"))
    _relink(os.path.join(_base(test), name, "latest"), os.path.basename(d))
    _relink(os.path.join(_base(test), "current"), os.path.join(name, os.path.basename(d)))


def _normalized_history(test: dict) -> Optional[History]:
    hist = test.get("history")
    if hist is not None and not isinstance(hist, History):
        hist = History([op if hasattr(op, "to_dict") else _op_from(op) for op in hist],
                       reindex=False)
    return hist


def _op_from(d: dict):
    from ..history.ops import Op

    return Op.from_dict(d)


def save_0(test: dict) -> dict:
    """Phase 0: persist test map + history before analysis."""
    d = test_dir(test)
    hist = _normalized_history(test)
    JepsenFile(os.path.join(d, TEST_FILE)).write_test(test, hist)
    if hist is not None:
        with open(os.path.join(d, "history.json"), "w") as f:
            for op in hist:
                f.write(codec.dumps(op.to_dict()).decode() + "\n")
    update_symlinks(test)
    return test


def save_1(test: dict) -> dict:
    """Phase 1: append results after analysis; history blocks untouched.
    A telemetric run (collector attached by `core.run`/`core.analyze`)
    also persists ``telemetry.json`` + Chrome ``trace.json`` here."""
    d = test_dir(test)
    results = test.get("results", {})
    jf = JepsenFile(os.path.join(d, TEST_FILE))
    if not os.path.exists(jf.path):
        jf.write_test(test, _normalized_history(test))
    jf.append_results(results)
    with open(os.path.join(d, "results.json"), "w") as f:
        f.write(codec.dumps(results).decode())
    _save_telemetry(test, d)
    update_symlinks(test)
    return test


def _save_telemetry(test: dict, d: str) -> None:
    coll = test.get("telemetry-collector")
    if coll is None or not getattr(coll, "enabled", False):
        return
    try:
        from .. import telemetry

        # an analyze pass writes telemetry-analyze.json / trace-analyze
        # .json so the original run's artifacts survive the re-check
        import socket

        meta = {
            "name": test.get("name"),
            "start-time": test.get("start-time"),
            "concurrency": test.get("concurrency"),
            # cross-host stitching (ISSUE 14): which host executed,
            # which run/trace this artifact belongs to.  A fleet
            # cell's identity is its WORKER name (the same host label
            # the fleet ledger and live-check session carry), so one
            # worker's segments land on one timeline lane
            "host": test.get("fleet-host") or socket.gethostname(),
        }
        if test.get("campaign-run-id"):
            meta["run-id"] = test["campaign-run-id"]
        if test.get("trace-id"):
            meta["trace-id"] = test["trace-id"]
        telemetry.write_run(d, coll, meta=meta,
                            suffix=test.get("telemetry-artifact-suffix",
                                            ""))
    except Exception as e:  # noqa: BLE001 — telemetry must not fail a save
        logger.warning("telemetry export failed: %s", e)


def load(name_or_dir: str, ts: Optional[str] = None, *, base: Optional[str] = None) -> dict:
    """Load a stored test.  `load(dir)` or `load(name, timestamp)`;
    timestamp defaults to "latest".  History comes back lazy."""
    if ts is None and os.path.isdir(name_or_dir):
        d = name_or_dir
    else:
        d = os.path.join(base or BASE, sanitize(name_or_dir), ts or "latest")
        if (ts is None or ts == "latest") and not os.path.isdir(d):
            # symlinks unavailable on this fs — fall back to the dir scan
            found = latest(name_or_dir, base=base)
            if found is None:
                raise FileNotFoundError(f"no stored runs for {name_or_dir!r}")
            d = found
    d = os.path.realpath(d)
    return JepsenFile(os.path.join(d, TEST_FILE)).read()


def load_results(name: str, ts: Optional[str] = None, *, base: Optional[str] = None) -> Optional[dict]:
    t = load(name, ts, base=base)
    return t.get("results")


def tests(name: Optional[str] = None, *, base: Optional[str] = None) -> List[str]:
    """List run directories, newest first (lazy dir scan, as jepsen.web)."""
    b = base or BASE
    out: List[str] = []
    if not os.path.isdir(b):
        return out
    names = [sanitize(name)] if name else sorted(os.listdir(b))
    for n in names:
        nd = os.path.join(b, n)
        # skip the base-level "current" symlink (and anything like it):
        # only real per-name directories hold runs — and the campaigns/
        # + verifier/ + fleet/ subtrees (ledgers and verifier session
        # dirs, not run dirs), _archive/ (runs retired by `gc_runs`
        # retention: archived, out of every live scan), and
        # compilecache/ (AOT entries + in-flight fleet push batches)
        if os.path.islink(nd) or not os.path.isdir(nd) \
                or n in ("campaigns", "verifier", "fleet", "_archive",
                         "compilecache"):
            continue
        for ts in os.listdir(nd):
            d = os.path.join(nd, ts)
            # dot-prefixed dirs are in-flight artifact-upload staging
            # (fleet store federation unpacks there, then atomically
            # renames into place) — not run dirs, for this scan OR the
            # warehouse ingest riding on it
            if ts != "latest" and not ts.startswith(".") \
                    and os.path.isdir(d) and not os.path.islink(d):
                out.append(d)
    # newest run first regardless of test name: order by the timestamp
    # basename, not the full path (sorting full paths would rank runs by
    # lexicographically-greatest *name* first)
    return sorted(out, key=lambda d: os.path.basename(d), reverse=True)


def latest(name: Optional[str] = None, *, base: Optional[str] = None) -> Optional[str]:
    ds = tests(name, base=base)
    return ds[0] if ds else None


def delete(name: str, ts: Optional[str] = None, *, base: Optional[str] = None) -> None:
    """Delete one run, or all runs of a test name."""
    b = base or BASE
    d = os.path.join(b, sanitize(name)) if ts is None else os.path.join(b, sanitize(name), ts)
    if os.path.isdir(d):
        shutil.rmtree(d)


def archive_dir(base: Optional[str] = None) -> str:
    """Where `gc_runs` retires run dirs: ``<base>/_archive/<name>/<ts>``
    — inside the store (same filesystem, atomic ``os.replace``) but
    outside every live scan (`tests` skips ``_archive``, and the
    warehouse ingest rides `tests`)."""
    return os.path.join(base or BASE, "_archive")


def _run_dir_age_s(d: str, now: float) -> float:
    """A run dir's age from its UTC timestamp basename
    (``YYYYmmddTHHMMSS.mmmZ``), falling back to mtime for
    foreign-named dirs."""
    ts = os.path.basename(d)
    try:
        import calendar

        t = calendar.timegm(time.strptime(ts[:15], "%Y%m%dT%H%M%S"))
        return now - t
    except (ValueError, OverflowError):
        try:
            return now - os.path.getmtime(d)
        except OSError:
            return 0.0


def gc_runs(base: Optional[str] = None, *, retention_s: float,
            now: Optional[float] = None) -> dict:
    """Retention for run dirs (``cli obs gc --retention <s>``, ISSUE 17
    satellite / ROADMAP 5c): archive **landed** runs older than
    `retention_s` to ``_archive/`` — the verifier's session-archival
    discipline (atomic ``os.replace``, millisecond suffix on
    collision) applied to the store itself, so months of autopilot
    don't grow the live store monotonically.  Unlanded dirs (no
    ``results.json`` yet: still executing, or crashed mid-run — the
    warehouse's ``status='running'`` rule) are never archived
    regardless of age; a post-mortem owns them.  Returns
    ``{"archived", "kept", "skipped"}`` counts."""
    b = base or BASE
    t = time.time() if now is None else now
    stats = {"archived": 0, "kept": 0, "skipped": 0}
    for d in tests(base=b):
        if _run_dir_age_s(d, t) < retention_s:
            stats["kept"] += 1
            continue
        if not os.path.exists(os.path.join(d, "results.json")):
            stats["skipped"] += 1
            continue
        name = os.path.basename(os.path.dirname(d))
        dst_dir = os.path.join(archive_dir(b), name)
        os.makedirs(dst_dir, exist_ok=True)
        dst = os.path.join(dst_dir, os.path.basename(d))
        if os.path.exists(dst):
            dst = f"{dst}.{int(t * 1000)}"
        os.replace(d, dst)
        stats["archived"] += 1
        # tidy the per-name dir: drop a now-dangling "latest" symlink
        # and the dir itself if nothing is left
        nd = os.path.dirname(d)
        link = os.path.join(nd, "latest")
        if os.path.islink(link) and not os.path.exists(link):
            try:
                os.unlink(link)
            except OSError:
                pass
        try:
            os.rmdir(nd)
        except OSError:
            pass  # still holds runs (or the refreshed symlink)
    return stats
