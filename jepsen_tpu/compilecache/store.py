"""The persistent AOT entry store: ``<store>/compilecache/*.aotx``.

One file per executable, named by its content fingerprint (program
HLO digest x shape class x backend/platform string — the key
discipline ``scripts/cache_key_probe.py`` validated).  File format::

    JTCC1\\n  <sha256-hex of payload>\\n  <payload>

where payload is a pickle of ``{"meta": {...}, "payload":
serialize_executable.serialize(...) tuple}``.  The digest line makes
every read self-verifying: a truncated or bit-flipped entry fails the
check, is deleted, and the caller falls through to a fresh compile
that re-serializes it — the chaos round's "never wedge or corrupt"
contract.

Writes are atomic (tmp + ``os.replace``), so a ``kill -9`` mid-put
leaves either no entry or a whole one; concurrent writers of the same
fingerprint converge on identical content.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("jepsen.compilecache")

__all__ = ["SUFFIX", "entry_path", "put", "get", "delete", "entries",
           "total_bytes", "pack_entry", "unpack_entry", "file_digest"]

MAGIC = b"JTCC1\n"
SUFFIX = ".aotx"


def entry_path(cache_dir: str, fingerprint: str) -> str:
    return os.path.join(cache_dir, fingerprint + SUFFIX)


def pack_entry(meta: Dict[str, Any], payload: Any) -> bytes:
    """Serialize one entry to its on-disk bytes (magic + digest +
    pickle)."""
    body = pickle.dumps({"meta": meta, "payload": payload},
                        protocol=pickle.HIGHEST_PROTOCOL)
    digest = hashlib.sha256(body).hexdigest().encode()
    return MAGIC + digest + b"\n" + body


def unpack_entry(blob: bytes) -> Optional[Dict[str, Any]]:
    """Parse + verify one entry's bytes; None on any corruption (bad
    magic, digest mismatch, unpicklable body)."""
    if not blob.startswith(MAGIC):
        return None
    rest = blob[len(MAGIC):]
    nl = rest.find(b"\n")
    if nl != 64:  # sha256 hex
        return None
    digest, body = rest[:nl].decode("ascii", "replace"), rest[nl + 1:]
    if hashlib.sha256(body).hexdigest() != digest:
        return None
    try:
        doc = pickle.loads(body)
    except Exception:  # noqa: BLE001 — corrupt pickle = corrupt entry
        return None
    return doc if isinstance(doc, dict) and "payload" in doc else None


def put(cache_dir: str, fingerprint: str, meta: Dict[str, Any],
        payload: Any) -> int:
    """Atomically write one entry; returns bytes written."""
    os.makedirs(cache_dir, exist_ok=True)
    blob = pack_entry(meta, payload)
    path = entry_path(cache_dir, fingerprint)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(blob)


def get(cache_dir: str, fingerprint: str
        ) -> Optional[Tuple[Dict[str, Any], int]]:
    """Read + verify one entry: ``(doc, size_bytes)`` or None.  A
    corrupt entry is DELETED on sight so the caller's re-compile can
    re-serialize a good one in its place."""
    path = entry_path(cache_dir, fingerprint)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    doc = unpack_entry(blob)
    if doc is None:
        logger.warning("compilecache: corrupt entry %s dropped", path)
        delete(cache_dir, fingerprint)
        return None
    return doc, len(blob)


def delete(cache_dir: str, fingerprint: str) -> bool:
    try:
        os.remove(entry_path(cache_dir, fingerprint))
        return True
    except OSError:
        return False


def entries(cache_dir: str) -> List[Dict[str, Any]]:
    """List the store's entries: ``[{"name", "size"}...]`` sorted by
    name.  Names are fingerprints + :data:`SUFFIX`."""
    out: List[Dict[str, Any]] = []
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return out
    for fn in sorted(names):
        if not fn.endswith(SUFFIX):
            continue
        try:
            size = os.path.getsize(os.path.join(cache_dir, fn))
        except OSError:
            continue
        out.append({"name": fn, "size": size})
    return out


def total_bytes(cache_dir: str) -> int:
    return sum(e["size"] for e in entries(cache_dir))


def file_digest(path: str) -> Optional[str]:
    """sha256 of an entry FILE's bytes — the fleet transport digest
    (distinct from the in-file payload digest, which covers only the
    pickle body)."""
    h = hashlib.sha256()
    try:
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    except OSError:
        return None
    return h.hexdigest()
