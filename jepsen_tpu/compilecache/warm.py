"""Bucket-ladder pre-warm: populate the AOT store at service start.

The graft of ``scripts/aot_warm.py`` into supported machinery: instead
of a one-off script lowering the 10M TPU programs, :func:`warm_ladder`
walks the default bucket ladder (:data:`bucket.LADDER`, overridable
via ``--sizes``, capped/extended to ``--max-txns``'s bucket) and
ensures every rung's checker
executables exist in the persistent store — so the first shrink probe,
campaign cell, or fleet claim of a known shape class pays dispatch,
not compile.

Per rung and family it warms the same programs the live dispatchers
route (the warmed class label must equal the live one, or the warm is
useless — pinned by tests/test_compilecache.py):

- ``la``: `elle.infer` (the classification pipeline's program) and the
  fused `elle.core-check` — or, when `parallel.slots.default_mesh`
  resolves a mesh for the rung, the sharded `parallel.op-shard`
  program the auto path would dispatch;
- ``rw``: the fused `elle.rw-core-check`.

Fused/infer programs are lowered at abstract ``ShapeDtypeStruct``
shapes (aot_warm's ``_sds`` idiom — no multi-GB arrays held through
the compile); the sharded program is lowered from concretely placed
shards, since its executable bakes the input shardings.

Every rung is individually guarded: a failed warm records the error
and moves on (``compilecache.warm`` is a chaos seam —
``fuzz_faults.py --compilecache`` pins that injected warm faults never
wedge the ladder or corrupt the store).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional

from jepsen_tpu import compilecache
from jepsen_tpu.compilecache import bucket

__all__ = ["warm_ladder", "warm_one"]

#: generator defaults shared with `utils.prestage` — warming any other
#: shape would populate classes no default cell ever dispatches
_LA_KW = dict(concurrency=10, mops_per_txn=4, read_frac=0.25, seed=7)
_RW_KW = dict(concurrency=10, mops_per_txn=3, read_frac=0.5, seed=11)


def _keys_for(n_txns: int) -> int:
    return max(64, n_txns // 8)


def _sds(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def warm_one(family: str, n_txns: int, max_k: int = 128,
             max_rounds: int = 64) -> List[Dict[str, Any]]:
    """Warm one rung of one family; one record per program ensured."""
    from jepsen_tpu.workloads import synth

    compilecache._fire(compilecache.SITE_WARM)
    recs: List[Dict[str, Any]] = []
    nk = _keys_for(n_txns)
    if family == "la":
        from jepsen_tpu.checkers.elle.device_core import core_check
        from jepsen_tpu.checkers.elle.device_infer import infer, \
            pad_packed
        from jepsen_tpu.parallel import slots

        p = synth.packed_la_history(n_txns=n_txns, n_keys=nk, **_LA_KW)
        h = pad_packed(p)
        mesh = slots.default_mesh(h.txn_type.shape[0])
        hs = _sds(h)
        recs.append(_ensure("elle.infer", infer, (hs,),
                            {"n_keys": p.n_keys}))
        if mesh is not None:
            from jepsen_tpu.parallel.op_shard import \
                _core_check_sharded, shard_padded

            n = mesh.shape["batch"]
            mk = max_k if max_k % n == 0 else ((max_k // n) + 1) * n
            h2, _ = shard_padded(h, mesh, "batch")
            recs.append(_ensure(
                "parallel.op-shard", _core_check_sharded, (h2,),
                {"n_keys": p.n_keys, "mesh": mesh, "axis": "batch",
                 "max_k": mk, "max_rounds": max_rounds}))
        else:
            recs.append(_ensure(
                "elle.core-check", core_check, (hs,),
                {"n_keys": p.n_keys, "max_k": max_k,
                 "max_rounds": max_rounds}))
        del h, hs
    elif family == "rw":
        from jepsen_tpu.checkers.elle.device_rw import pad_packed, \
            rw_core_check

        p = synth.packed_rw_history(n_txns=n_txns, n_keys=nk, **_RW_KW)
        h = pad_packed(p)
        recs.append(_ensure(
            "elle.rw-core-check", rw_core_check, (_sds(h),),
            {"n_keys": h.n_keys, "max_k": max_k,
             "max_rounds": max_rounds, "rw_cap": h.mop_txn.shape[0]}))
        del h
    else:
        raise ValueError(f"unknown warm family {family!r}")
    return recs


def _ensure(site: str, jitfn, args: tuple,
            static: dict) -> Dict[str, Any]:
    t0 = time.perf_counter()
    rec = {"site": site,
           "class": bucket.class_label(site, args, static)}
    try:
        rec["how"] = compilecache.ensure(site, jitfn, *args, **static)
    except Exception as e:  # noqa: BLE001 — a rung must not stop the
        # ladder (the chaos contract); the error is the record
        rec["how"] = "error"
        rec["error"] = f"{type(e).__name__}: {e}"
    rec["seconds"] = round(time.perf_counter() - t0, 3)
    return rec


def warm_ladder(sizes: Optional[Iterable[int]] = None,
                max_txns: Optional[int] = None,
                families: Iterable[str] = ("la", "rw"),
                max_k: int = 128, max_rounds: int = 64,
                verbose: bool = False) -> List[Dict[str, Any]]:
    """Warm every (rung, family) cell of the ladder; returns one record
    per rung with its program records + wall seconds."""
    out: List[Dict[str, Any]] = []
    for n in bucket.ladder(max_txns=max_txns, sizes=sizes):
        for fam in families:
            t0 = time.perf_counter()
            try:
                programs = warm_one(fam, n, max_k=max_k,
                                    max_rounds=max_rounds)
                rec = {"rung": n, "family": fam, "ok": all(
                    p.get("how") != "error" for p in programs),
                    "programs": programs}
            except Exception as e:  # noqa: BLE001 — see warm_one
                rec = {"rung": n, "family": fam, "ok": False,
                       "error": f"{type(e).__name__}: {e}"}
            rec["seconds"] = round(time.perf_counter() - t0, 3)
            out.append(rec)
            if verbose:
                print(f"cache warm: {fam}@{n} "
                      f"{'ok' if rec['ok'] else 'FAILED'} "
                      f"({rec['seconds']:.1f}s)", flush=True)
    return out
