"""Shape-bucket policy: which executable does a call share?

The anti-amortization shape (ROADMAP item 1) is many small
heterogeneous probes — every distinct padded shape is a distinct XLA
program, and per-shape jit was minutes-for-500-ops.  The cure is a
single bucketing POLICY: every device entry point pads its arrays to
power-of-two capacities (``pad_packed``, the verifier sweep's
``_pow2``, the streamed-staging caps all already do), so a shrink
probe at 300 txns and a campaign cell at 500 land in the SAME
(site, dtype-signature, padded-dims) class and share one executable.

This module is that policy made first-class:

- :func:`pow2_at_least` — the one rounding rule (identical to
  ``device_infer.pow2_at_least``; a unit test pins them equal so the
  two can't drift);
- :func:`signature` — a call's dtype-signature + padded dims, read
  straight off the (already bucketed) argument pytree.  Abstract
  ``ShapeDtypeStruct`` leaves sign identically to concrete arrays, so
  a pre-warm at abstract shapes populates the same class a live call
  looks up;
- :func:`class_label` — the compact ``(site, signature, statics)``
  label used by cache fingerprints, telemetry, and docs;
- :data:`LADDER` / :func:`ladder` — the default pre-warm rungs (txn
  counts; each rung pads to its pow2 class, so warming the ladder
  covers every history up to the top rung).
"""

from __future__ import annotations

import hashlib
from typing import Any, Iterable, List, Tuple

__all__ = ["pow2_at_least", "signature", "static_signature",
           "class_label", "class_digest", "LADDER", "ladder"]


def pow2_at_least(n: int, floor: int = 8) -> int:
    """The bucket rounding rule: smallest power of two >= n, floored.
    Must stay equal to ``device_infer.pow2_at_least`` (pinned by
    test_compilecache's drift test)."""
    x = floor
    while x < n:
        x *= 2
    return x


def _leaves(args: Iterable[Any]) -> List[Tuple[str, str]]:
    """(shape, dtype) of every array-like leaf in the args pytree.
    Uses jax's flattening so registered containers (PaddedLA, dicts of
    stage outputs) enumerate deterministically; ShapeDtypeStructs and
    concrete arrays produce identical entries."""
    import jax

    out: List[Tuple[str, str]] = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            out.append((str(tuple(shape)),
                        str(getattr(leaf, "dtype", ""))))
    return out


def signature(args: tuple) -> Tuple[Tuple[str, str], ...]:
    """The call's shape class: (shape, dtype) per array leaf.  The
    arrays are expected to be bucket-padded already (``pad_packed`` /
    ``_pow2``); this just reads the class off them."""
    return tuple(_leaves(args))


def static_signature(static: dict) -> Tuple[Tuple[str, str], ...]:
    """Sorted (name, repr) of the static arguments — part of the
    class: a different ``max_k`` is a different specialization, hence
    a different executable."""
    return tuple(sorted((str(k), repr(v)) for k, v in static.items()))


def class_label(site: str, args: tuple, static: dict) -> str:
    """Human/SQL-stable label for a call class, e.g.
    ``elle.core-check|(512,):int8+...|max_k=128``."""
    sig = "+".join(f"{s}:{d}" if d else s for s, d in signature(args))
    st = ",".join(f"{k}={v}" for k, v in static_signature(static))
    return f"{site}|{sig or 'scalar'}" + (f"|{st}" if st else "")


def class_digest(site: str, args: tuple, static: dict) -> str:
    """Short stable digest of the class label — the shape-class half
    of a cache fingerprint."""
    return hashlib.sha256(
        class_label(site, args, static).encode()).hexdigest()[:16]


#: default pre-warm rungs (txn counts).  Each rung's history pads to
#: its pow2 class, so the warmed executables cover every history whose
#: padded capacities land on the same rungs: shrink ladders (tens to
#: hundreds of ops), unit/campaign cells (hundreds), and the small
#: bench sizes.  Large rungs (1M+) stay opt-in via ``cli cache warm
#: --sizes`` — warming them costs the very compile the cache then
#: amortizes.
LADDER: Tuple[int, ...] = (64, 128, 256, 512, 1024)


def ladder(max_txns: int | None = None,
           sizes: Iterable[int] | None = None) -> List[int]:
    """The pre-warm rung list: explicit `sizes`, else the default
    ladder capped at ``max_txns``'s bucket — rungs above it are
    dropped, and when the bucket exceeds the default top the ladder
    extends to it by doubling.  ``ladder(max_txns=128) == [64, 128]``;
    ``ladder(max_txns=5000)`` runs 64..8192."""
    if sizes is not None:
        return sorted({pow2_at_least(int(s)) for s in sizes})
    rungs = set(LADDER)
    if max_txns:
        top = pow2_at_least(int(max_txns))
        r = max(LADDER)
        while r < top:
            r *= 2
            rungs.add(r)
        rungs = {r for r in rungs if r <= top} or {top}
    return sorted(rungs)
