"""Shape-bucketed AOT executable cache (ROADMAP item 1).

``scripts/aot_warm.py`` + ``scripts/cache_key_probe.py`` prototyped
compile-cost amortization as one-off scripts; this package is the
supported machinery.  Three layers:

- :mod:`.bucket` — the shape-class policy: calls over pow2-padded
  arrays key into (site, dtype-signature, padded dims) classes, so
  shrink probes, campaign cells, verifier sweep chunks, and fleet
  workers share executables instead of compiling per exact shape;
- :mod:`.store` — the persistent entries under
  ``<store>/compilecache/``: AOT-serialized executables keyed by a
  content fingerprint (program HLO digest x shape class x
  backend/platform string x jax version), self-verifying on read;
- this module — the guarded load-or-compile seam, :func:`call`:
  in-memory executable table hit -> dispatch the cached ``Compiled``
  directly; miss -> lower, try the disk entry
  (``compilecache.load`` fault seam), else compile + serialize
  (``compilecache.compile`` fault seam).  ANY failure anywhere —
  injected fault, corrupt entry, version/topology skew, serialization
  gap — falls through to the plain jit call, stamped
  ``compilecache_degraded`` on the open span: the cache can make a
  run faster, never wrong, and never wedge it.

Enablement: on by default.  ``JT_COMPILECACHE=0|off`` disables;
``JT_COMPILECACHE=mem`` keeps the in-process executable table but no
disk persistence; ``JT_COMPILECACHE=<path>`` pins the store
directory.  Unset, the store lives at ``<store>/compilecache/`` when
the store directory exists, else memory-only — the same "never grows
a new filesystem footprint by itself" rule as the warehouse.

The in-memory table is LRU-bounded (``JT_COMPILECACHE_MEM``, default
64 executables) and :func:`clear`-able — tests clear it between
modules alongside ``jax.clear_caches()`` so held executables can't
defeat the suite's memory cap.

Metrics (live registry, federated over the fleet heartbeat):
``compile-cache-hits`` / ``compile-cache-misses`` /
``compile-cache-bytes`` counters + the ``compile-cache-entries``
gauge.  :func:`stats` mirrors them process-locally for tests.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from jepsen_tpu.compilecache import bucket, store
from jepsen_tpu.resilience import faults as faults_mod

logger = logging.getLogger("jepsen.compilecache")

__all__ = ["call", "ensure", "enabled", "cache_dir", "set_cache_dir",
           "adopt_base", "clear", "stats", "reset_stats", "bucket",
           "store", "SITE_LOAD", "SITE_COMPILE", "SITE_WARM"]

#: the chaos seams (`scripts/fuzz_faults.py --compilecache`): strictly
#: opt-in — a plan must NAME them (sites= / persistent=) to fire here,
#: so a bare p= checker-chaos plan doesn't double-fire its counter
SITE_LOAD = "compilecache.load"
SITE_COMPILE = "compilecache.compile"
SITE_WARM = "compilecache.warm"

_UNSET = object()

_lock = threading.Lock()
_mem: "OrderedDict[Tuple, Any]" = OrderedDict()
_dir_override: Any = _UNSET
_stats = {"hits": 0, "misses": 0, "bytes": 0, "fallthroughs": 0}


def _registry():
    from jepsen_tpu import telemetry

    return telemetry.registry()


def _mem_cap() -> int:
    try:
        return max(1, int(os.environ.get("JT_COMPILECACHE_MEM", "64")))
    except ValueError:
        return 64


def enabled() -> bool:
    return os.environ.get("JT_COMPILECACHE", "").strip().lower() \
        not in ("0", "off", "no", "false")


def cache_dir() -> Optional[str]:
    """The persistent store directory, or None for memory-only mode."""
    if _dir_override is not _UNSET:
        return _dir_override
    env = os.environ.get("JT_COMPILECACHE", "").strip()
    low = env.lower()
    if low in ("0", "off", "no", "false", "mem"):
        return None
    if env and low not in ("1", "on", "true"):
        return env  # an explicit path
    from jepsen_tpu import store as jstore

    if os.path.isdir(jstore.BASE):
        return os.path.join(jstore.BASE, "compilecache")
    return None


def set_cache_dir(path: Optional[str]) -> None:
    """Pin (or, with None, disable) the persistent directory for this
    process — overrides env/default resolution.  Tests and the fleet
    worker use this."""
    global _dir_override
    _dir_override = path


def adopt_base(base: str) -> Optional[str]:
    """Point the persistent store at ``<base>/compilecache`` unless an
    explicit JT_COMPILECACHE path (or a prior override) already pinned
    one — the fleet worker's store-base adoption."""
    env = os.environ.get("JT_COMPILECACHE", "").strip()
    if _dir_override is not _UNSET:
        return cache_dir()
    if env and env.lower() not in ("1", "on", "true"):
        return cache_dir()
    d = os.path.join(base, "compilecache")
    set_cache_dir(d)
    return d


def clear() -> None:
    """Drop the in-memory executable table and the fleet digest memo
    (disk entries persist).  Conftest calls this alongside
    ``jax.clear_caches()``; ``cli cache clear`` calls it after
    deleting entries so no stale digest outlives its file."""
    with _lock:
        _mem.clear()
    from jepsen_tpu.compilecache import fleet as cc_fleet

    cc_fleet.clear_digest_memo()


def stats() -> Dict[str, int]:
    with _lock:
        out = dict(_stats)
    out["mem_entries"] = len(_mem)
    d = cache_dir()
    out["entries"] = len(store.entries(d)) if d else out["mem_entries"]
    return out


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0


def _bump(key: str, n: int = 1) -> None:
    with _lock:
        _stats[key] += n


def _count(name: str, n: float = 1) -> None:
    try:
        _registry().counter(name).inc(n)
    except Exception:  # noqa: BLE001 — observability only
        pass


def _set_entries_gauge() -> None:
    try:
        d = cache_dir()
        n = len(store.entries(d)) if d else len(_mem)
        _registry().gauge("compile-cache-entries").set(n)
    except Exception:  # noqa: BLE001 — observability only
        pass


def _annotate(**attrs: Any) -> None:
    try:
        from jepsen_tpu import telemetry

        sp = telemetry.current()
        if sp is not None:
            sp.set_attr(**attrs)
    except Exception:  # noqa: BLE001 — observability only
        pass


def _fire(seam: str) -> None:
    """Fire the active fault plan at a compilecache seam — opt-in only
    (the plan must name the site), so cache plumbing never perturbs a
    checker-chaos plan's deterministic call counter."""
    plan = faults_mod.active_plan()
    if plan is not None and plan.targets_site(seam):
        plan.fire(seam)


def _fn_ident(jitfn: Callable) -> str:
    w = getattr(jitfn, "__wrapped__", jitfn)
    return f"{getattr(w, '__module__', '?')}." \
           f"{getattr(w, '__qualname__', repr(w))}"


def _platform() -> str:
    import jax

    try:
        ver = jax.devices()[0].client.platform_version
    except Exception:  # noqa: BLE001 — backend-specific attr
        ver = "?"
    return f"{jax.default_backend()}|{ver}|jax-{jax.__version__}"


def _fingerprint(lowered: Any, site: str, args: tuple,
                 static: dict) -> str:
    """The content fingerprint: program HLO digest x shape class x
    backend/platform string (the cache_key_probe discipline — of the
    probe's 8 key components only platform/accelerator vary across
    backends, so these three factors are the sufficient key)."""
    hlo = hashlib.sha256(lowered.as_text().encode()).hexdigest()
    cls = bucket.class_digest(site, args, static)
    plat = hashlib.sha256(_platform().encode()).hexdigest()[:16]
    return hashlib.sha256(
        f"{hlo}|{cls}|{plat}".encode()).hexdigest()[:40]


def _mem_key(site: str, jitfn: Callable, args: tuple,
             static: dict) -> Optional[Tuple]:
    try:
        return (site, _fn_ident(jitfn), bucket.signature(args),
                bucket.static_signature(static))
    except Exception:  # noqa: BLE001 — exotic args must not fail a call
        return None


def _mem_get(key: Optional[Tuple]) -> Any:
    if key is None:
        return None
    with _lock:
        ent = _mem.get(key)
        if ent is not None:
            _mem.move_to_end(key)
        return ent


def _mem_put(key: Optional[Tuple], compiled: Any) -> None:
    if key is None:
        return
    cap = _mem_cap()
    with _lock:
        _mem[key] = compiled
        _mem.move_to_end(key)
        while len(_mem) > cap:
            _mem.popitem(last=False)


def _mem_drop(key: Optional[Tuple]) -> None:
    if key is None:
        return
    with _lock:
        _mem.pop(key, None)


def _obtain(site: str, jitfn: Callable, args: tuple, static: dict
            ) -> Tuple[Any, str, Optional[Tuple[str, str]]]:
    """Lower, then load-or-compile:
    ``(Compiled, "loaded"|"compiled", (cache_dir, fingerprint)|None)``.
    The third element locates the persistent entry so :func:`call` can
    delete it if a *loaded* executable then raises at dispatch (skew
    that only surfaces at execute time must self-heal like
    deserialize failures do).  Raises on any failure — callers map
    that to plain-jit fall-through (:func:`call`) or a skipped rung
    (:mod:`.warm`)."""
    from jax.experimental import serialize_executable as _se

    _fire(SITE_LOAD)
    lowered = jitfn.lower(*args, **static)
    d = cache_dir()
    fp = _fingerprint(lowered, site, args, static) if d else None
    if d and fp:
        got = store.get(d, fp)
        if got is not None:
            doc, size = got
            try:
                compiled = _se.deserialize_and_load(*doc["payload"])
                _bump("bytes", size)
                _count("compile-cache-bytes", size)
                return compiled, "loaded", (d, fp)
            except Exception:  # noqa: BLE001 — skew/corruption: the
                # entry deserialized but won't load here (topology or
                # jaxlib drift inside one fingerprint epoch) — drop it
                # so the recompile below re-serializes a good one
                logger.warning("compilecache: entry %s failed to "
                               "load; recompiling", fp, exc_info=True)
                store.delete(d, fp)
    _fire(SITE_COMPILE)
    compiled = lowered.compile()
    if d and fp:
        try:
            payload = _se.serialize(compiled)
            n = store.put(d, fp, {
                "site": site,
                "class": bucket.class_label(site, args, static),
                "platform": _platform(),
            }, payload)
            _bump("bytes", n)
            _count("compile-cache-bytes", n)
        except Exception:  # noqa: BLE001 — an unserializable program
            # still runs from the in-memory table; persistence is an
            # optimization, not a contract
            logger.warning("compilecache: serialize of %s failed",
                           site, exc_info=True)
    return compiled, "compiled", (d, fp) if d and fp else None


def call(site: str, jitfn: Callable, *args: Any, **static: Any) -> Any:
    """Dispatch one bucketed device call through the cache.

    `jitfn` is a ``jax.jit``-wrapped callable; `args` are the dynamic
    (array) arguments, `static` the static keyword arguments.  Fast
    path: the in-memory table already holds this class's ``Compiled``
    — dispatch it directly (statics are baked in at lowering).  Miss:
    :func:`_obtain` loads the disk entry or compiles + persists one.
    Any failure falls through to ``jitfn(*args, **static)`` — the
    exact call every caller made before this seam existed."""
    if not enabled() or not hasattr(jitfn, "lower"):
        return jitfn(*args, **static)
    mk = _mem_key(site, jitfn, args, static)
    compiled = _mem_get(mk)
    if compiled is not None:
        try:
            out = compiled(*args)
        except Exception:  # noqa: BLE001 — a stale executable (device
            # set changed under us) must not fail the call
            _mem_drop(mk)
            return _fallthrough(site, jitfn, args, static)
        _bump("hits")
        _count("compile-cache-hits")
        return out
    try:
        compiled, how, loc = _obtain(site, jitfn, args, static)
    except Exception:  # noqa: BLE001 — injected fault, corrupt entry,
        # serialization gap: plain jit is always correct
        return _fallthrough(site, jitfn, args, static)
    try:
        out = compiled(*args)
    except Exception:  # noqa: BLE001 — plain jit is always correct
        if how == "loaded" and loc:
            # the entry deserialized but its executable raises at
            # dispatch ("Symbols not found"-style skew can surface
            # here too): delete it, mirroring the deserialize-failure
            # path, so the next call recompiles and re-serializes a
            # good one instead of paying deserialize + fall-through
            # forever
            logger.warning("compilecache: loaded entry %s raised at "
                           "dispatch; dropped", loc[1], exc_info=True)
            store.delete(*loc)
        return _fallthrough(site, jitfn, args, static)
    _mem_put(mk, compiled)
    if how == "loaded":
        _bump("hits")
        _count("compile-cache-hits")
    else:
        _bump("misses")
        _count("compile-cache-misses")
    _set_entries_gauge()
    return out


def _fallthrough(site: str, jitfn: Callable, args: tuple,
                 static: dict) -> Any:
    """The degradation tail: count + stamp, then run the plain jit —
    bitwise the same program, just without amortization."""
    _bump("fallthroughs")
    try:
        _registry().counter("compile-cache-fallthrough",
                            site=site).inc()
    except Exception:  # noqa: BLE001 — observability only
        pass
    _annotate(compilecache_degraded=site)
    logger.debug("compilecache: %s fell through to plain jit", site,
                 exc_info=True)
    return jitfn(*args, **static)


def ensure(site: str, jitfn: Callable, *args: Any,
           **static: Any) -> str:
    """Warm one class WITHOUT executing: `args` may be abstract
    (``ShapeDtypeStruct``) — lowering works on either, and the
    in-memory key signs identically, so a later concrete call is a
    straight table hit.  Returns "cached" | "loaded" | "compiled";
    raises on failure (the warmer skips the rung)."""
    if not enabled() or not hasattr(jitfn, "lower"):
        return "disabled"
    mk = _mem_key(site, jitfn, args, static)
    if _mem_get(mk) is not None:
        return "cached"
    compiled, how, _loc = _obtain(site, jitfn, args, static)
    _mem_put(mk, compiled)
    if how == "loaded":
        _bump("hits")
        _count("compile-cache-hits")
    else:
        _bump("misses")
        _count("compile-cache-misses")
    _set_entries_gauge()
    return how
