"""Fleet distribution of AOT cache entries (docs/COMPILECACHE.md).

Entries travel over PR 13's chunked, digest-verified artifact channel
plus one small GET surface, so a worker's FIRST claim of a known shape
class is warm:

- **advert** — the coordinator's claim response carries
  :func:`export_index`: ``[{"name", "digest", "size"}...]`` for every
  entry under its ``<base>/compilecache/``.  File digests are cached
  by ``(name, size, mtime)`` so a busy claim path never re-hashes an
  unchanged store.
- **pull** — :func:`pull_missing`: the worker fetches entries it lacks
  from ``GET /fleet/cache/<name>``, sha256-verifies each blob against
  the advert digest AND the entry's own self-verifying framing
  (`store.unpack_entry`), then installs atomically (tmp +
  ``os.replace``) — a torn pull never lands.
- **push** — :func:`push_new`: after a cell, the worker spools any
  entries it minted into a batch dir, tars it through
  `fleet.artifacts.pack_run_dir_file`, and streams it over the
  worker's existing resumable ``_upload_spooled`` seam under
  ``rel=compilecache/cc-<digest12>`` (a rel `_safe_rel` admits).
- **absorb** — the coordinator's artifact handler calls
  :func:`absorb` when a ``compilecache/*`` rel lands: each ``*.aotx``
  is re-verified and moved up into the flat ``<base>/compilecache/``
  store (fingerprint-named, so concurrent workers pushing the same
  class converge on one entry), and the batch dir is removed.

**Authentication.**  An entry body is a pickle (JAX's AOT
serialization is pickle-based end to end — ``deserialize_and_load``
unpickles even the inner payload), so unpickling bytes that arrived
over the unauthenticated fleet HTTP surface would be remote code
execution for anyone who can reach the endpoints.  Every transfer is
therefore HMAC-SHA256-authenticated with the fleet shared secret
(:func:`shared_secret`): the coordinator signs served blobs
(``X-Jepsen-Cache-MAC`` response header), the worker signs pushed
entries (``<name>.mac`` sidecars in the batch), and BOTH sides verify
with :func:`hmac.compare_digest` *before* any ``pickle.loads``.  The
in-file sha256 framing still guards integrity; the MAC guards origin.
No secret → no transfer: pull/push/absorb refuse (counted
``unauthenticated``) and the worker simply compiles locally.  The
secret is ``$JEPSEN_FLEET_SECRET`` (set it on every host of a
multi-host fleet), else ``<base>/fleet/secret`` — the coordinator
mints one at startup, so single-host fleets sharing a store base
authenticate with zero configuration.

Everything here is best-effort: a failed pull/push/absorb logs and
moves on — the worker just compiles locally, exactly as before the
cache existed.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import logging
import os
import secrets as secrets_mod
import shutil
import tempfile
import threading
import urllib.request
from typing import Any, Dict, List, Optional, Set, Tuple
from urllib.parse import quote

from jepsen_tpu.compilecache import store

logger = logging.getLogger("jepsen.compilecache")

__all__ = ["export_index", "entry_names", "read_entry", "absorb",
           "pull_missing", "push_new", "shared_secret", "entry_mac",
           "MAX_ADVERT_ENTRIES", "MAC_HEADER", "MAC_SUFFIX",
           "SECRET_ENV"]

#: cap on entries a claim response adverts — a claim is a hot-path
#: control message, not a directory dump
MAX_ADVERT_ENTRIES = 128

#: HTTP response header carrying the coordinator's HMAC of a served
#: entry blob
MAC_HEADER = "X-Jepsen-Cache-MAC"
#: per-entry MAC sidecar suffix inside a pushed batch
MAC_SUFFIX = ".mac"
#: the fleet shared secret env override (multi-host fleets set this
#: on every host; single-host fleets get ``<base>/fleet/secret``)
SECRET_ENV = "JEPSEN_FLEET_SECRET"

_digest_lock = threading.Lock()
#: entry PATH -> (size, mtime_ns, digest): the by-stat digest memo.
#: Keyed by full path (tests switch cache dirs), pruned against the
#: live listing on every export, cleared by ``compilecache.clear()``.
_digests: Dict[str, Tuple[int, int, str]] = {}


def clear_digest_memo() -> None:
    with _digest_lock:
        _digests.clear()


def shared_secret(base: Optional[str],
                  create: bool = False) -> Optional[bytes]:
    """The fleet cache-transfer HMAC key: ``$JEPSEN_FLEET_SECRET``,
    else the ``<base>/fleet/secret`` file.  With ``create=True`` (the
    coordinator) a missing file is minted (0600, atomic) so
    shared-base workers pick it up with zero configuration.  None
    means unauthenticated — every transfer refuses."""
    env = os.environ.get(SECRET_ENV, "").strip()
    if env:
        return env.encode()
    if not base:
        return None
    path = os.path.join(base, "fleet", "secret")
    try:
        with open(path, "rb") as f:
            return f.read().strip() or None
    except OSError:
        pass
    if not create:
        return None
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(secrets_mod.token_hex(32).encode())
        os.replace(tmp, path)
        # re-read: a concurrent minter may have won the replace race
        with open(path, "rb") as f:
            return f.read().strip() or None
    except OSError:
        logger.warning("fleet secret mint at %s failed", path,
                       exc_info=True)
        return None


def entry_mac(secret: bytes, blob: bytes) -> str:
    """HMAC-SHA256 hex of one entry's file bytes under the fleet
    shared secret — origin authentication for the transfer surfaces
    (the in-file sha256 covers integrity only)."""
    return hmac_mod.new(secret, blob, hashlib.sha256).hexdigest()


def _registry():
    from jepsen_tpu import telemetry

    return telemetry.registry()


def _count(state: str, n: int = 1) -> None:
    try:
        _registry().counter("compile-cache-transfers",
                            state=state).inc(n)
    except Exception:  # noqa: BLE001 — observability only
        pass


def _safe_name(name: str) -> bool:
    return (name.endswith(store.SUFFIX) and "/" not in name
            and "\\" not in name and not name.startswith(".")
            and name == os.path.basename(name))


def export_index(cache_dir: Optional[str],
                 limit: int = MAX_ADVERT_ENTRIES
                 ) -> List[Dict[str, Any]]:
    """The advert: every entry's ``{"name", "digest", "size"}``,
    digests memoized by (size, mtime) so repeated claims stat, not
    hash."""
    if not cache_dir:
        return []
    out: List[Dict[str, Any]] = []
    listed = store.entries(cache_dir)
    for e in listed[:max(0, int(limit))]:
        name, size = e["name"], e["size"]
        path = os.path.join(cache_dir, name)
        try:
            mtime_ns = os.stat(path).st_mtime_ns
        except OSError:
            continue
        with _digest_lock:
            memo = _digests.get(path)
        if memo is not None and memo[0] == size \
                and memo[1] == mtime_ns:
            digest = memo[2]
        else:
            digest = store.file_digest(path)
            if digest is None:
                continue
            with _digest_lock:
                _digests[path] = (size, mtime_ns, digest)
        out.append({"name": name, "digest": digest, "size": size})
    # bound the memo: drop keys under this dir whose entry is gone
    # (cache clear, test teardown) — the memo tracks live files only
    live = {os.path.join(cache_dir, e["name"]) for e in listed}
    prefix = cache_dir.rstrip(os.sep) + os.sep
    with _digest_lock:
        for path in [p for p in _digests
                     if p.startswith(prefix) and p not in live]:
            del _digests[path]
    return out


def entry_names(cache_dir: Optional[str]) -> Set[str]:
    if not cache_dir:
        return set()
    return {e["name"] for e in store.entries(cache_dir)}


def read_entry(cache_dir: Optional[str],
               name: str) -> Optional[bytes]:
    """One entry's raw file bytes for ``GET /fleet/cache/<name>``;
    None for unsafe names, missing files, or corrupt framing."""
    if not cache_dir or not _safe_name(name):
        return None
    try:
        with open(os.path.join(cache_dir, name), "rb") as f:
            blob = f.read()
    except OSError:
        return None
    if store.unpack_entry(blob) is None:
        return None
    return blob


def _install(cache_dir: str, name: str, blob: bytes) -> bool:
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, name)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def absorb(base: str, rel: str) -> int:
    """Coordinator side: a landed ``compilecache/<batch>`` artifact dir
    becomes flat store entries.  Each ``*.aotx`` must carry a valid
    ``<name>.mac`` sidecar (HMAC under the fleet secret — verified
    BEFORE the body is ever unpickled) and parse as a well-formed
    entry; failures are dropped, not installed.  Survivors move up
    into ``<base>/compilecache/``; the batch dir is removed.  Returns
    the number of entries absorbed."""
    batch = os.path.join(base, rel)
    dest = os.path.join(base, "compilecache")
    secret = shared_secret(base, create=True)
    absorbed = 0
    try:
        names = sorted(os.listdir(batch))
    except OSError:
        return 0
    if secret is None:
        # no key to verify origin with: never unpickle the push
        logger.warning("compilecache: no fleet secret; pushed batch "
                       "%s dropped unabsorbed (set %s)", rel,
                       SECRET_ENV)
        _count("unauthenticated", max(1, len(names)))
        shutil.rmtree(batch, ignore_errors=True)
        return 0
    for fn in names:
        src = os.path.join(batch, fn)
        if not _safe_name(fn) or not os.path.isfile(src):
            continue
        if os.path.exists(os.path.join(dest, fn)):
            continue  # fingerprint collision = identical content
        try:
            with open(src, "rb") as f:
                blob = f.read()
            with open(src + MAC_SUFFIX, "rb") as f:
                mac = f.read().strip().decode("ascii", "replace")
        except OSError:
            logger.warning("compilecache: pushed entry %s unreadable "
                           "or missing its .mac sidecar; dropped", fn)
            _count("push-rejected")
            continue
        if not hmac_mod.compare_digest(entry_mac(secret, blob), mac):
            logger.warning("compilecache: pushed entry %s failed HMAC "
                           "verification; dropped", fn)
            _count("push-rejected")
            continue
        if store.unpack_entry(blob) is None:
            logger.warning("compilecache: pushed entry %s corrupt; "
                           "dropped", fn)
            continue
        if _install(dest, fn, blob):
            absorbed += 1
            _count("absorbed")
    shutil.rmtree(batch, ignore_errors=True)
    if absorbed:
        logger.info("compilecache: absorbed %d fleet entries from %s",
                    absorbed, rel)
        try:
            _registry().gauge("compile-cache-entries").set(
                len(store.entries(dest)))
        except Exception:  # noqa: BLE001 — observability only
            pass
    return absorbed


def pull_missing(base_url: str, advert: Any,
                 cache_dir: Optional[str],
                 secret: Optional[bytes] = None,
                 timeout_s: float = 10.0) -> int:
    """Worker side: fetch advertised entries absent locally.  Each
    blob's :data:`MAC_HEADER` must verify under the fleet `secret`
    (checked BEFORE the body is ever unpickled), then the blob must
    match the advert's sha256 AND parse as a well-formed entry before
    the atomic install; failures skip the entry (the worker compiles
    that class locally).  No secret → no pull.  Returns entries
    installed."""
    if not cache_dir or not isinstance(advert, list) or not advert:
        return 0
    if secret is None:
        logger.warning("compilecache: no fleet secret; skipping pull "
                       "of %d advertised entries (set %s)",
                       len(advert), SECRET_ENV)
        _count("unauthenticated", len(advert))
        return 0
    have = entry_names(cache_dir)
    pulled = 0
    for row in advert:
        if not isinstance(row, dict):
            continue
        name = str(row.get("name") or "")
        want = str(row.get("digest") or "")
        if not _safe_name(name) or name in have or not want:
            continue
        url = f"{base_url.rstrip('/')}/fleet/cache/{quote(name)}"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                blob = r.read()
                mac = str(r.headers.get(MAC_HEADER) or "")
        except Exception as e:  # noqa: BLE001 — a cache pull must
            # never fail a cell
            logger.warning("compilecache: pull of %s failed (%s)",
                           name, e)
            _count("pull-failed")
            continue
        if not hmac_mod.compare_digest(entry_mac(secret, blob), mac):
            logger.warning("compilecache: pulled entry %s failed HMAC "
                           "verification; dropped", name)
            _count("pull-rejected")
            continue
        if hashlib.sha256(blob).hexdigest() != want \
                or store.unpack_entry(blob) is None:
            logger.warning("compilecache: pulled entry %s failed "
                           "verification; dropped", name)
            _count("pull-rejected")
            continue
        if _install(cache_dir, name, blob):
            pulled += 1
            _count("pulled")
    if pulled:
        logger.info("compilecache: pulled %d entries from %s",
                    pulled, base_url)
    return pulled


def push_new(worker: Any, new_names: Set[str],
             cache_dir: Optional[str],
             secret: Optional[bytes] = None) -> bool:
    """Worker side: ship freshly minted entries to the coordinator as
    ONE batch artifact over the resumable upload seam, each with a
    ``<name>.mac`` HMAC sidecar the coordinator's :func:`absorb`
    verifies before unpickling.  No secret → no push.  ``worker`` is
    a `fleet.worker.FleetWorker` (duck-typed: `_upload_spooled`)."""
    if not cache_dir or not new_names:
        return False
    if secret is None:
        logger.warning("compilecache: no fleet secret; %d minted "
                       "entries not pushed (set %s)", len(new_names),
                       SECRET_ENV)
        _count("unauthenticated", len(new_names))
        return False
    from jepsen_tpu.fleet.artifacts import pack_run_dir_file

    with tempfile.TemporaryDirectory(prefix="jepsen-cc-push-") as td:
        staged = 0
        for name in sorted(new_names):
            if not _safe_name(name):
                continue
            blob = read_entry(cache_dir, name)
            if blob is None:
                continue
            with open(os.path.join(td, name), "wb") as f:
                f.write(blob)
            with open(os.path.join(td, name + MAC_SUFFIX), "wb") as f:
                f.write(entry_mac(secret, blob).encode())
            staged += 1
        if not staged:
            return False
        with tempfile.TemporaryFile(prefix="jepsen-cc-spool-") as sp:
            total, digest = pack_run_dir_file(td, sp)
            batch = f"cc-{digest[:12]}"
            try:
                ok = bool(worker._upload_spooled(
                    batch, f"compilecache/{batch}", sp, total, digest))
            except Exception as e:  # noqa: BLE001 — push is an
                # optimization; the verdict path never depends on it
                logger.warning("compilecache: push failed (%s)", e)
                ok = False
    _count("pushed" if ok else "push-failed", staged if ok else 1)
    if ok:
        logger.info("compilecache: pushed %d entries to %s",
                    staged, getattr(worker, "url", "?"))
    return ok
