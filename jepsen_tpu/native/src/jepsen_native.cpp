// Native host oracles for jepsen_tpu.
//
// TPU-native equivalents of the reference's JVM-native components
// (SURVEY.md §2.5): bifurcan's Java Tarjan SCC (#1) and Knossos's
// packed-bitset WGL search state (#2), rebuilt in C++ as the exact
// host-side anchors that double-check the device kernels.  Exposed via a
// plain C ABI for ctypes (no pybind11 in this image).
//
// Build: see ../build.py or ../Makefile (g++ -O2 -shared -fPIC).

#include <cstdint>
#include <cstring>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Tarjan SCC, iterative (explicit stack), over CSR adjacency.
// comp[v] gets a component id; ids are assigned in completion order
// (reverse topological for the condensation), matching what Elle needs.

struct TarjanFrame {
  int64_t v;
  int64_t edge;  // next out-edge offset to try
};

}  // namespace

extern "C" {

// Returns the number of SCCs.  indptr has n+1 entries; indices has
// indptr[n] entries; comp has n entries (output).
int64_t jt_scc(int64_t n, const int64_t* indptr, const int64_t* indices,
               int64_t* comp) {
  std::vector<int64_t> index(n, -1), low(n, 0);
  std::vector<uint8_t> on_stack(n, 0);
  std::vector<int64_t> stack;       // Tarjan's node stack
  std::vector<TarjanFrame> frames;  // DFS stack
  stack.reserve(n);
  frames.reserve(64);
  int64_t next_index = 0, n_comps = 0;

  for (int64_t root = 0; root < n; ++root) {
    if (index[root] != -1) continue;
    frames.push_back({root, indptr[root]});
    index[root] = low[root] = next_index++;
    stack.push_back(root);
    on_stack[root] = 1;

    while (!frames.empty()) {
      TarjanFrame& f = frames.back();
      int64_t v = f.v;
      if (f.edge < indptr[v + 1]) {
        int64_t w = indices[f.edge++];
        if (index[w] == -1) {
          index[w] = low[w] = next_index++;
          stack.push_back(w);
          on_stack[w] = 1;
          frames.push_back({w, indptr[w]});
        } else if (on_stack[w] && index[w] < low[v]) {
          low[v] = index[w];
        }
      } else {
        if (low[v] == index[v]) {
          int64_t w;
          do {
            w = stack.back();
            stack.pop_back();
            on_stack[w] = 0;
            comp[w] = n_comps;
          } while (w != v);
          ++n_comps;
        }
        frames.pop_back();
        if (!frames.empty()) {
          int64_t parent = frames.back().v;
          if (low[v] < low[parent]) low[parent] = low[v];
        }
      }
    }
  }
  return n_comps;
}

// ---------------------------------------------------------------------------
// Shortest cycle through `start` (BFS over successors back to start) on a
// CSR graph restricted to nodes where mask[v] != 0.  Writes the cycle as
// node ids into out (capacity out_cap), returns its length, 0 if none.

int64_t jt_bfs_cycle(int64_t n, const int64_t* indptr,
                     const int64_t* indices, const uint8_t* mask,
                     int64_t start, int64_t* out, int64_t out_cap) {
  std::vector<int64_t> parent(n, -2);  // -2 unvisited
  std::vector<int64_t> queue;
  queue.reserve(256);
  queue.push_back(start);
  parent[start] = -1;
  for (size_t qi = 0; qi < queue.size(); ++qi) {
    int64_t v = queue[qi];
    for (int64_t e = indptr[v]; e < indptr[v + 1]; ++e) {
      int64_t w = indices[e];
      if (mask && !mask[w]) continue;
      if (w == start) {
        // reconstruct path start..v, then close the loop
        std::vector<int64_t> path;
        for (int64_t x = v; x != -1; x = parent[x]) path.push_back(x);
        int64_t len = static_cast<int64_t>(path.size());
        if (len + 1 > out_cap) return -1;  // caller's buffer too small
        for (int64_t i = 0; i < len; ++i) out[i] = path[len - 1 - i];
        out[len] = start;
        return len + 1;
      }
      if (parent[w] == -2) {
        parent[w] = v;
        queue.push_back(w);
      }
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// WGL linearizability search with memoized model (int transition table),
// dynamic bitsets (n_ops of any size), and a visited set of packed
// (linearized-set, state) configs — the C++ rebuild of Knossos's
// JVM BitSet configs.

namespace {

struct VecHash {
  size_t operator()(const std::vector<uint64_t>& v) const {
    uint64_t h = 1469598103934665603ull;  // FNV-1a
    for (uint64_t x : v) {
      h ^= x;
      h *= 1099511628211ull;
    }
    return static_cast<size_t>(h);
  }
};

}  // namespace

// op_sym[i]: memoized symbol of op i.  invokes/returns: positions in the
// total order; returns[i] >= never  <=>  op i crashed (:info) and may
// linearize or not.  table[state * n_syms + sym] -> next state or -1.
// abort_flag (may be null): polled every 1024 configs; a nonzero value
// aborts the search — lets a competition kill the losing contestant
// (knossos/search.clj ctl semantics) instead of letting the C++ run to
// its full config budget after the verdict.
// Returns 1 linearizable, 0 not, -1 config budget exhausted, -2 aborted.
int32_t jt_wgl(int64_t n_ops, const int32_t* op_sym, const int64_t* invokes,
               const int64_t* returns, int64_t never, const int32_t* table,
               int64_t n_states, int64_t n_syms, int32_t init_state,
               int64_t max_configs, int64_t* explored_out,
               const volatile int32_t* abort_flag) {
  (void)n_states;
  const int64_t words = (n_ops + 63) / 64;

  auto test_bit = [&](const std::vector<uint64_t>& S, int64_t i) {
    return (S[i >> 6] >> (i & 63)) & 1ull;
  };

  // must-linearize mask (ops with real returns)
  std::vector<uint64_t> must(words, 0);
  for (int64_t i = 0; i < n_ops; ++i)
    if (returns[i] < never) must[i >> 6] |= 1ull << (i & 63);

  auto covers_must = [&](const std::vector<uint64_t>& S) {
    for (int64_t w = 0; w < words; ++w)
      if ((S[w] & must[w]) != must[w]) return false;
    return true;
  };

  auto candidates = [&](const std::vector<uint64_t>& S,
                        std::vector<int64_t>& out) {
    out.clear();
    int64_t minret = never + 1;
    for (int64_t i = 0; i < n_ops; ++i)
      if (!test_bit(S, i) && returns[i] < minret) minret = returns[i];
    for (int64_t i = 0; i < n_ops; ++i)
      if (!test_bit(S, i) && invokes[i] < minret) out.push_back(i);
  };

  struct Frame {
    std::vector<uint64_t> S;
    int32_t state;
    std::vector<int64_t> cands;
    size_t ci;
  };

  // visited keys: S words + state appended
  std::unordered_set<std::vector<uint64_t>, VecHash> seen;
  auto key_of = [&](const std::vector<uint64_t>& S, int32_t state) {
    std::vector<uint64_t> k(S);
    k.push_back(static_cast<uint64_t>(static_cast<uint32_t>(state)));
    return k;
  };

  std::vector<Frame> stack;
  Frame f0{std::vector<uint64_t>(words, 0), init_state, {}, 0};
  candidates(f0.S, f0.cands);
  seen.insert(key_of(f0.S, f0.state));
  stack.push_back(std::move(f0));

  int64_t explored = 0;
  while (!stack.empty()) {
    Frame& f = stack.back();
    if (covers_must(f.S)) {
      if (explored_out) *explored_out = explored;
      return 1;
    }
    if (f.ci >= f.cands.size()) {
      stack.pop_back();
      continue;
    }
    int64_t i = f.cands[f.ci++];
    int32_t s2 = table[static_cast<int64_t>(f.state) * n_syms + op_sym[i]];
    if (s2 < 0) continue;
    std::vector<uint64_t> S2(f.S);
    S2[i >> 6] |= 1ull << (i & 63);
    auto key = key_of(S2, s2);
    if (!seen.insert(std::move(key)).second) continue;
    if (++explored > max_configs) {
      if (explored_out) *explored_out = explored;
      return -1;
    }
    if (abort_flag && (explored & 1023) == 0 && *abort_flag) {
      if (explored_out) *explored_out = explored;
      return -2;
    }
    Frame nf{std::move(S2), s2, {}, 0};
    candidates(nf.S, nf.cands);
    stack.push_back(std::move(nf));
  }
  if (explored_out) *explored_out = explored;
  return 0;
}

}  // extern "C"
