"""Native C++ host oracles, loaded via ctypes.

TPU-native equivalents of the reference's JVM-native pieces (SURVEY.md
§2.5 #1/#2): Tarjan SCC (bifurcan's `Graphs.stronglyConnectedComponents`)
and the WGL packed-bitset search (Knossos `wgl.clj` + `BitSet` configs),
compiled from ``src/jepsen_native.cpp`` with g++ on first use (no
pybind11 in this image — plain C ABI + ctypes, per the environment
contract).

Degrades gracefully: if no compiler is available or the build fails,
:func:`available` returns False and callers fall back to the pure-Python
implementations (`elle.graph.tarjan_scc`, `knossos.wgl`), which remain
the semantic source of truth (differential tests pin C++ == Python).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger("jepsen.native")

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "src", "jepsen_native.cpp")
_LIB = os.path.join(_DIR, "libjepsen_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    """Compile the shared library if missing/stale.  Returns success."""
    global _build_failed
    try:
        if (os.path.exists(_LIB)
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return True
        # build into a temp file then atomically replace, so concurrent
        # processes never load a half-written .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               "-o", tmp, _SRC]
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=120)
        if res.returncode != 0:
            logger.warning("native build failed:\n%s", res.stderr[-2000:])
            os.unlink(tmp)
            _build_failed = True
            return False
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build unavailable: %s", e)
        _build_failed = True
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            # stale/corrupt/wrong-arch .so: force one rebuild, then give up
            logger.warning("native lib unloadable; rebuilding")
            try:
                os.unlink(_LIB)
            except OSError:
                pass
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError as e:
                logger.warning("native lib still unloadable: %s", e)
                _build_failed = True
                return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.jt_scc.restype = ctypes.c_int64
        lib.jt_scc.argtypes = [ctypes.c_int64, i64p, i64p, i64p]
        lib.jt_bfs_cycle.restype = ctypes.c_int64
        lib.jt_bfs_cycle.argtypes = [ctypes.c_int64, i64p, i64p, u8p,
                                     ctypes.c_int64, i64p, ctypes.c_int64]
        lib.jt_wgl.restype = ctypes.c_int32
        lib.jt_wgl.argtypes = [ctypes.c_int64, i32p, i64p, i64p,
                               ctypes.c_int64, i32p, ctypes.c_int64,
                               ctypes.c_int64, ctypes.c_int32,
                               ctypes.c_int64, i64p, i32p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _i64(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


def _as(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _csr(n: int, src: np.ndarray, dst: np.ndarray
         ) -> Tuple[np.ndarray, np.ndarray]:
    src = _i64(src)
    dst = _i64(dst)
    order = np.argsort(src, kind="stable")
    indices = dst[order]
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.add.at(indptr, src + 1, 1)
    np.cumsum(indptr, out=indptr)
    return indptr, indices


def scc(n: int, src, dst) -> Optional[np.ndarray]:
    """Component label per node via C++ Tarjan, or None if unavailable.
    Same contract as `elle.graph.tarjan_scc`."""
    lib = _load()
    if lib is None:
        return None
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    indptr, indices = _csr(n, src, dst)
    comp = np.empty(n, dtype=np.int64)
    lib.jt_scc(n, _as(indptr, ctypes.c_int64), _as(indices, ctypes.c_int64),
               _as(comp, ctypes.c_int64))
    return comp


def bfs_cycle(n: int, src, dst, start: int,
              mask: Optional[np.ndarray] = None,
              max_len: int = 4096) -> Optional[np.ndarray]:
    """Shortest cycle through `start` (node list, closed: path[0] ==
    path[-1] == start), or None if no cycle / native unavailable."""
    lib = _load()
    if lib is None or n == 0:
        return None
    indptr, indices = _csr(n, src, dst)
    m = (np.ascontiguousarray(mask, dtype=np.uint8)
         if mask is not None else None)
    while True:
        out = np.empty(max_len, dtype=np.int64)
        ln = lib.jt_bfs_cycle(
            n, _as(indptr, ctypes.c_int64), _as(indices, ctypes.c_int64),
            _as(m, ctypes.c_uint8) if m is not None else None,
            start, _as(out, ctypes.c_int64), max_len)
        if ln == -1:  # buffer too small; a cycle is at most n+1 nodes
            if max_len > n:
                return None  # can't happen, but never loop forever
            max_len = n + 1
            continue
        if ln <= 0:
            return None
        return out[:ln].copy()


def wgl(op_sym, invokes, returns, never: int, table: np.ndarray,
        init_state: int, max_configs: int = 5_000_000,
        abort_flag: Optional[np.ndarray] = None
        ) -> Optional[Tuple[Optional[bool], int, bool]]:
    """Memoized WGL search.  Returns (verdict, explored, aborted) where
    verdict is True/False/None (budget exhausted or aborted), or None if
    native unavailable.  `abort_flag` is a shared (1,) int32 array the
    C++ polls (ctypes releases the GIL, so another thread can set it —
    the competition's loser-abort path)."""
    lib = _load()
    if lib is None:
        return None
    op_sym = np.ascontiguousarray(op_sym, dtype=np.int32)
    invokes = _i64(invokes)
    returns = _i64(returns)
    table = np.ascontiguousarray(table, dtype=np.int32)
    n_states, n_syms = table.shape
    explored = np.zeros(1, dtype=np.int64)
    if abort_flag is not None and (abort_flag.dtype != np.int32
                                   or abort_flag.size < 1
                                   or not abort_flag.flags["C_CONTIGUOUS"]):
        raise TypeError("abort_flag must be a contiguous int32 array "
                        f"of size >= 1, got {abort_flag.dtype} "
                        f"size {abort_flag.size}")
    rc = lib.jt_wgl(len(op_sym), _as(op_sym, ctypes.c_int32),
                    _as(invokes, ctypes.c_int64),
                    _as(returns, ctypes.c_int64), never,
                    _as(table, ctypes.c_int32), n_states, n_syms,
                    init_state, max_configs,
                    _as(explored, ctypes.c_int64),
                    _as(abort_flag, ctypes.c_int32)
                    if abort_flag is not None else None)
    verdict = {1: True, 0: False, -1: None, -2: None}[int(rc)]
    return verdict, int(explored[0]), int(rc) == -2
