"""Test orchestration — the spine.

Equivalent of the reference's `jepsen/src/jepsen/core.clj` (SURVEY.md §2.1,
§3.1): :func:`run` takes a test map and returns it completed with
``history`` and ``results``, wiring every layer in order:

    logging → node sessions → OS setup → DB setup → nemesis setup
    → generator interpreter (the workload)
    → nemesis/DB teardown → log download
    → store.save_0 (history persisted BEFORE analysis)
    → checker.check_safe → store.save_1

Also :func:`analyze`, the re-check entry point for a stored run (reference
`jepsen.core/analyze!`-style path), and :func:`noop_test`, the base test
map everything merges into (reference `jepsen.tests/noop-test`).

The checking step is where the TPU comes in: checkers hand the history to
the device pipeline (`jepsen_tpu.checkers.elle.*`); everything before it is
host-side orchestration, exactly as in the reference where L2–L3 are pure
and L1/L4b are imperative.
"""

from __future__ import annotations

import logging
import os
import time
import traceback
from typing import Any, Dict, Optional

from . import db as db_proto
from . import net as net_proto
from . import os_setup, store, telemetry
from .checkers import api as checker_api
from .control import api as control
from .control.core import Remote, Session
from .generator import core as gen_core
from .generator import interpreter
from .history.ops import History
from .utils import profiling

logger = logging.getLogger("jepsen.core")


def noop_test(**overrides) -> dict:
    """The base test map (reference `jepsen.tests/noop-test`): runs no ops
    against no cluster and is always valid.  Merge overrides in."""
    t: Dict[str, Any] = {
        "name": "noop",
        "nodes": [],
        "concurrency": 1,
        "os": os_setup.noop,
        "db": db_proto.Noop(),
        "net": net_proto.noop,
        "client": None,
        "nemesis": None,
        "generator": None,
        "checker": None,
        "start-time": None,
    }
    t.update(overrides)
    return t


def _start_logging(test: dict) -> Optional[logging.Handler]:
    """Write the run log into the store dir (reference
    `store/start-logging!` → `jepsen.log`)."""
    try:
        path = store.path(test, "jepsen.log")
    except OSError:
        return None
    h = logging.FileHandler(path)
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    h.setLevel(logging.INFO)
    root = logging.getLogger("jepsen")
    root.addHandler(h)
    if root.level == logging.NOTSET or root.level > logging.INFO:
        root.setLevel(logging.INFO)
    return h


def _stop_logging(h: Optional[logging.Handler]) -> None:
    if h is not None:
        logging.getLogger("jepsen").removeHandler(h)
        h.close()


def _open_sessions(test: dict) -> Dict[str, Session]:
    remote: Optional[Remote] = test.get("remote")
    if remote is None or not test.get("nodes"):
        return {}
    opts = control._node_opts(test)
    return {n: remote.connect(n, opts) for n in test["nodes"]}


def _close_sessions(sessions: Dict[str, Session]) -> None:
    for s in sessions.values():
        try:
            s.disconnect()
        except Exception as e:  # noqa: BLE001
            logger.warning("session disconnect failed: %s", e)


def _db_setup(test: dict) -> None:
    db = test.get("db")
    if db is None:
        return
    control.on_nodes(test, db.setup)
    if db_proto.supports(db, db_proto.Primary):
        prims = db.primaries(test) or test["nodes"][:1]
        if prims:
            control.on_nodes(
                test, lambda t, n: db.setup_primary(t, n), prims[:1])


def _db_teardown(test: dict) -> None:
    db = test.get("db")
    if db is None or test.get("leave-db-running"):
        return
    control.on_nodes(test, db.teardown)


def _download_logs(test: dict) -> None:
    """Pull db log files into the store dir, one subdir per node
    (reference: `core/run!`'s log snarfing via `db/log-files`)."""
    db = test.get("db")
    if db is None or not db_proto.supports(db, db_proto.LogFiles):
        return

    def snarf(t: dict, node: str) -> None:
        files = list(db.log_files(t, node) or ())
        if not files:
            return
        dest = store.path(t, node)
        os.makedirs(dest, exist_ok=True)
        try:
            control.download(files, dest)
        except Exception as e:  # noqa: BLE001
            logger.warning("log download from %s failed: %s", node, e)

    control.on_nodes(test, snarf)


def run(test: dict) -> dict:
    """Run a full test: setup, workload, teardown, analysis, storage.

    Returns the test map with ``history`` (a History) and ``results``
    (``{"valid?": True|False|"unknown", ...}``) attached.  Exceptions in
    setup/workload propagate after best-effort teardown; exceptions in
    checkers are captured by `check_safe` as invalid results, and the
    phase-0 store write has already preserved the history by then.
    """
    test = {**noop_test(), **test}
    if test.get("start-time") is None:
        test["start-time"] = time.time()
    # telemetry: a fresh collector per run when opted in (test map key,
    # telemetry.enable(), or JEPSEN_TELEMETRY); the NOOP singleton
    # otherwise — every span below is then a shared no-op object.  A
    # "profile-dir" run is implicitly telemetric: its spans bridge to
    # the JAX profiler as TraceAnnotations of the same names
    profile_dir = test.get("profile-dir")
    tel = (telemetry.activate()
           if telemetry.wanted_for(test) or profile_dir
           else telemetry.NOOP)
    recorder = None
    # distributed trace context (ISSUE 14): a cell executed by a fleet
    # worker (or any caller stamping test["trace-id"]) runs its whole
    # body as ONE segment of the run's cross-host trace — stamped into
    # span attrs, the event stream meta, and telemetry.json, and
    # readable by the live-check client and every control-plane call
    # made from this thread.  Derivable from the campaign run id too,
    # so single-process campaign cells stitch identically.
    tctx = None
    tid = test.get("trace-id") or (
        telemetry.trace_id_for(test["campaign-run-id"])
        if test.get("campaign-run-id") else None)
    if tid:
        tctx = telemetry.trace_context(str(tid), "run")
        test["trace-id"] = str(tid)
    if tel.enabled:
        test["telemetry-collector"] = tel
        # a full run always writes the unsuffixed artifacts, even for a
        # test map reloaded from a store dir that was later analyzed
        test.pop("telemetry-artifact-suffix", None)
        tel.annotate = bool(profile_dir)
        tel.trace = tctx
        # the flight recorder: stream span/metric/resilience events to
        # <run-dir>/events.jsonl as they happen, so a killed run still
        # leaves a readable partial trace (docs/TELEMETRY.md)
        try:
            import socket as _socket

            mb = test.get("events-max-bytes")
            meta = {"name": test.get("name"),
                    # fleet cells identify by worker name — one lane
                    # per worker on the stitched timeline
                    "host": test.get("fleet-host")
                    or _socket.gethostname()}
            if tctx is not None:
                meta["trace-id"] = tctx.trace_id
            recorder = telemetry.attach_stream(
                tel, store.test_dir(test),
                meta=meta,
                interval_s=float(
                    test.get("telemetry-sample-interval", 1.0)),
                max_bytes=int(mb) if mb else None,
                keep=test.get("events-keep"))
        except Exception as e:  # noqa: BLE001 — never fail a run for it
            logger.warning("flight recorder unavailable: %s", e)
    prev_trace = telemetry.set_trace(tctx) if tctx is not None else None
    try:
        with profiling.trace(profile_dir):
            with tel.span("run", name=test.get("name"),
                          nodes=len(test.get("nodes") or ()),
                          concurrency=test.get("concurrency")):
                return _run_phases(test, tel)
    finally:
        if tctx is not None:
            telemetry.set_trace(prev_trace)
        if recorder is not None:
            recorder.close(
                valid=(test.get("results") or {}).get("valid?"))
        if tel.enabled:
            telemetry.deactivate(tel)


def _run_phases(test: dict, tel) -> dict:
    """The body of :func:`run`, one telemetry span per phase."""
    log_handler = _start_logging(test)
    logger.info("Running test %s on nodes %s", test.get("name"),
                test.get("nodes"))
    sessions: Dict[str, Session] = {}
    nemesis = test.get("nemesis")
    # live checking (ISSUE 13): opt-in via the "live-check" test key
    # (campaign spec opts pass through build_test).  The interpreter
    # streams every history event into the client's sink; a verifier
    # partitioned past the budget degrades the client and the ordinary
    # stored-history check below stands alone — the run never depends
    # on the live path.
    live = None
    if test.get("live-check"):
        from .verifier.client import live_check_for

        try:
            with tel.span("live-check.open"):
                live = live_check_for(test)
        except Exception as e:  # noqa: BLE001 — opt-in accelerant
            logger.warning("live-check unavailable: %s", e)
        if live is not None:
            test["op-sink"] = live.feed
    try:
        sessions = _open_sessions(test)
        test["sessions"] = sessions
        try:
            if test.get("nodes") and test.get("remote") is not None:
                os_ = test.get("os") or os_setup.noop
                with tel.span("os-setup"):
                    control.on_nodes(test, os_.setup)
                with tel.span("db-setup"):
                    _db_setup(test)
            if nemesis is not None:
                with tel.span("nemesis-setup"):
                    test["nemesis"] = nemesis = \
                        nemesis.setup(test) or nemesis

            logger.info("Starting workload")
            fg = test.get("final-generator")
            if fg is not None:
                # quiesce, then the final phase (reference: run! drives
                # :generator then :final-generator once clients settle)
                test["generator"] = gen_core.phases(
                    test.get("generator"), fg)
            with tel.span("workload") as w_span:
                hist = interpreter.run(test)
                w_span.set_attr(ops=len(hist))
            test["history"] = hist
            logger.info("Workload complete: %d ops", len(hist))
        except BaseException as e:
            if live is not None:
                _quietly("live-check close", live.close)
                live = None
            log_run_failure(test, e)
            raise
        finally:
            # Best-effort teardown runs whether the workload completed or
            # died mid-setup: faults must be healed and dbs stopped either
            # way, and node logs are most valuable for crashed runs.
            if nemesis is not None:
                with tel.span("nemesis-teardown"):
                    _quietly("nemesis teardown",
                             lambda: nemesis.teardown(test))
            if test.get("nodes") and test.get("remote") is not None:
                with tel.span("log-download"):
                    _quietly("log download", lambda: _download_logs(test))
                with tel.span("db-teardown"):
                    _quietly("db teardown", lambda: _db_teardown(test))
                os_ = test.get("os") or os_setup.noop
                with tel.span("os-teardown"):
                    _quietly("os teardown",
                             lambda: control.on_nodes(test, os_.teardown))
    finally:
        _close_sessions(sessions)
        test.pop("sessions", None)

    test.pop("op-sink", None)  # the feed hook must not persist
    try:
        with tel.span("store.save_0"):
            store.save_0(test)
        # the check phase gets one span per (composed) checker, opened
        # inside checker_api.check_safe with the checker's name attached
        test["results"] = _check(test, test.get("history"))
        if live is not None:
            # drain + verdict (+seal) the live session; a degraded
            # stream stamps {"state": "degraded"} and the stored-
            # history verdict above is the sole authority.  In-proc
            # services run their sweeps here, so verifier.sweep spans
            # land in this run's telemetry.
            with tel.span("live-check.finish"):
                summary = live.finish()
            live = None  # finished — the close-on-error below is moot
            if isinstance(test.get("results"), dict):
                test["results"]["live-check"] = summary
            (logger.info if summary.get("state") == "ok"
             else logger.warning)(
                "live-check %s: state=%s ops=%s", summary.get("session"),
                summary.get("state"), summary.get("ops"))
        with tel.span("store.save_1"):
            store.save_1(test)
        valid = test["results"].get("valid?")
        (logger.info if valid is True else logger.warning)(
            "Analysis complete: valid? = %s", valid)
    finally:
        if live is not None:
            # save_0/_check raised before finish(): a long-lived fleet
            # worker must not leak the sender thread / in-proc service
            _quietly("live-check close", live.close)
        _stop_logging(log_handler)
    return test


def _quietly(what: str, thunk) -> None:
    try:
        thunk()
    except Exception as e:  # noqa: BLE001
        logger.warning("%s failed: %s", what, e)


def _check(test: dict, hist: Optional[History]) -> dict:
    chk = test.get("checker")
    if chk is None or hist is None:
        return {"valid?": True}
    return checker_api.check_safe(chk, test, hist)


def analyze(test_or_dir, checker=None) -> dict:
    """Re-run analysis on a stored test (reference: load a stored test and
    re-check).  Accepts a loaded test map or a store directory path; the
    lazy history is materialized, the checker re-run, results re-saved."""
    test = store.load(test_or_dir) if isinstance(test_or_dir, str) else test_or_dir
    hist = test.get("history")
    if hist is not None and not isinstance(hist, History):
        hist = hist.materialize()
        test["history"] = hist
    if checker is not None:
        test["checker"] = checker
    chk = test.get("checker")
    if chk is None or not hasattr(chk, "check"):
        # stored tests persist checkers only as "§obj" placeholders
        raise ValueError(
            "no checker: stored tests don't persist checker objects; "
            "pass one to analyze(test, checker)")
    tel = (telemetry.activate() if telemetry.wanted_for(test)
           else telemetry.NOOP)
    if tel.enabled:
        test["telemetry-collector"] = tel
        # keep the original run's telemetry.json/trace.json intact:
        # the re-check writes *-analyze.json artifacts instead
        test["telemetry-artifact-suffix"] = "-analyze"
    try:
        with tel.span("analyze", name=test.get("name")):
            test["results"] = checker_api.check_safe(chk, test, hist)
            with tel.span("store.save_1"):
                store.save_1(test)
    finally:
        if tel.enabled:
            telemetry.deactivate(tel)
    return test


def log_run_failure(test: dict, e: BaseException) -> None:
    """Record a crashed run (what the reference's run! logs before
    rethrowing)."""
    logger.error("Test run failed: %s\n%s", e,
                 "".join(traceback.format_exception(type(e), e, e.__traceback__)))
