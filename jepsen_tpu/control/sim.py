"""Simulated remote for unit tests.

Records every command each node was asked to run and lets tests script
responses — how we test nemeses/net/db logic with no cluster, mirroring the
reference's strategy of keeping SSH out of its unit tests (SURVEY.md §4).
"""

from __future__ import annotations

import fnmatch
import threading
from typing import Callable, Dict, List, Optional, Tuple

from jepsen_tpu.control.core import Action, CmdResult, Remote, Session


class SimNode:
    """Shared per-host log + scripted responses."""

    def __init__(self, host: str):
        self.host = host
        self.log: List[Action] = []
        self.uploads: List[Tuple[object, str]] = []
        self.downloads: List[Tuple[object, str]] = []
        self.responders: List[Tuple[str, Callable[[Action], CmdResult]]] = []
        self.lock = threading.Lock()

    def respond(self, glob: str, fn_or_out) -> None:
        """Script a response for commands matching `glob` (fnmatch over the
        wrapped command line).  `fn_or_out` is a string stdout or a
        callable(action) -> CmdResult."""
        if callable(fn_or_out):
            fn = fn_or_out
        else:
            def fn(a, out=fn_or_out):
                return CmdResult(cmd=a.wrapped_cmd(), out=out, err="",
                                 exit_status=0)
        self.responders.append((glob, fn))

    def cmds(self) -> List[str]:
        return [a.wrapped_cmd() for a in self.log]


class SimSession(Session):
    def __init__(self, node: SimNode):
        self.node = node

    def execute(self, action: Action) -> CmdResult:
        with self.node.lock:
            self.node.log.append(action)
            cmd = action.wrapped_cmd()
            for glob, fn in self.node.responders:
                if fnmatch.fnmatch(cmd, glob):
                    return fn(action)
        return CmdResult(cmd=cmd, out="", err="", exit_status=0)

    def upload(self, local_paths, remote_path):
        with self.node.lock:
            self.node.uploads.append((local_paths, remote_path))

    def download(self, remote_paths, local_dir):
        with self.node.lock:
            self.node.downloads.append((remote_paths, local_dir))


class SimRemote(Remote):
    def __init__(self):
        self.nodes: Dict[str, SimNode] = {}
        self._lock = threading.Lock()

    def node(self, host: str) -> SimNode:
        with self._lock:
            if host not in self.nodes:
                self.nodes[host] = SimNode(host)
            return self.nodes[host]

    def connect(self, host: str, opts: Optional[dict] = None) -> Session:
        return SimSession(self.node(host))

    def all_cmds(self) -> Dict[str, List[str]]:
        return {h: n.cmds() for h, n in self.nodes.items()}
