"""Cluster control plane (reference: `jepsen/control*.clj`, SURVEY.md §1 L1).

Host-side only — never touches the TPU.  The checkers (L2-L3) are pure and
device-resident; this layer runs setup/teardown/faults on db nodes through
a pluggable `Remote` transport (loopback subprocess, OpenSSH CLI, docker,
kubectl, or an in-memory simulated cluster for tests).
"""

from jepsen_tpu.control.api import (cd, download, exec_, exec_result,
                                    file_contents, host, on_many, on_nodes,
                                    session, sudo, upload, with_env,
                                    with_session, write_file)
from jepsen_tpu.control.core import (Action, CmdResult, ConnectionError_,
                                     Remote, RemoteError, RetryRemote,
                                     Session, escape, join_cmd, lit)
from jepsen_tpu.control.local import LoopbackRemote
from jepsen_tpu.control.sim import SimRemote

__all__ = [
    "Action", "CmdResult", "ConnectionError_", "Remote", "RemoteError",
    "RetryRemote", "Session", "escape", "join_cmd", "lit",
    "cd", "download", "exec_", "exec_result", "host", "on_many", "on_nodes",
    "session", "sudo", "upload", "with_env", "with_session",
    "file_contents", "write_file",
    "LoopbackRemote", "SimRemote",
]
