"""SSH remote via the OpenSSH client binaries.

Equivalent of the reference's `jepsen/control/sshj.clj` + `control/scp.clj`
(SURVEY.md §2.1): persistent per-node sessions, exec with stdin/env/sudo,
scp upload/download.  The reference embeds a Java SSH library (sshj); we
drive the system `ssh`/`scp` binaries with a ControlMaster socket per node,
which gives the same persistent-session behavior without a Python SSH
dependency.  Gated: raises a clear error when no `ssh` binary exists (this
build image has none — tests use the loopback/docker remotes instead,
mirroring how the reference's test suite avoids real SSH, SURVEY.md §4).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import tempfile
from typing import Optional

from jepsen_tpu.control.core import (Action, CmdResult, ConnectionError_,
                                     Remote, Session)


def ssh_available() -> bool:
    return shutil.which("ssh") is not None


class SshSession(Session):
    def __init__(self, host: str, opts: dict):
        if not ssh_available():
            raise ConnectionError_(
                "no `ssh` binary on PATH — install OpenSSH client, or use "
                "LoopbackRemote / DockerRemote for clusterless operation")
        self.host = host
        self.opts = opts
        self.user = opts.get("username", "root")
        self.port = int(opts.get("port", 22))
        self.timeout_s = float(opts.get("timeout_s", 60.0))
        if opts.get("password"):
            raise ConnectionError_(
                "password auth is not supported by the OpenSSH-CLI remote "
                "(no TTY); use private_key_path / an ssh agent instead")
        self._ctl_dir = tempfile.mkdtemp(prefix="jepsen-ssh-")
        self._ctl = os.path.join(self._ctl_dir, "ctl")
        # options shared by ssh and scp; NOTE ssh takes -p <port> but scp
        # takes -P <port>, so the port flag is added per-command below
        self._base = ["-o", "StrictHostKeyChecking=" +
                      ("yes" if opts.get("strict_host_key_checking")
                       else "no"),
                      "-o", "UserKnownHostsFile=/dev/null",
                      "-o", "LogLevel=ERROR",
                      "-o", "BatchMode=yes",
                      "-o", f"ControlPath={self._ctl}",
                      "-o", "ControlMaster=auto",
                      "-o", "ControlPersist=120"]
        if opts.get("private_key_path"):
            self._base += ["-i", opts["private_key_path"]]
        self._ssh_base = [*self._base, "-p", str(self.port)]
        self._scp_base = [*self._base, "-P", str(self.port)]
        # Open the master connection eagerly so connect errors surface here.
        r = self._run_ssh("true")
        if r.exit_status != 0:
            raise ConnectionError_(
                f"ssh to {self.user}@{host}:{self.port} failed: {r.err}")

    def _run_ssh(self, cmd: str, in_: Optional[str] = None) -> CmdResult:
        argv = ["ssh", *self._ssh_base, f"{self.user}@{self.host}", cmd]
        try:
            proc = subprocess.run(argv, input=in_, text=True,
                                  capture_output=True,
                                  timeout=self.timeout_s)
        except subprocess.TimeoutExpired as e:
            raise ConnectionError_(f"ssh timed out: {cmd}", cmd=cmd) from e
        return CmdResult(cmd=cmd, out=proc.stdout, err=proc.stderr,
                         exit_status=proc.returncode)

    def execute(self, action: Action) -> CmdResult:
        return self._run_ssh(action.wrapped_cmd(), action.in_)

    def upload(self, local_paths, remote_path: str) -> None:
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        argv = ["scp", *self._scp_base, "-r", *map(str, local_paths),
                f"{self.user}@{self.host}:{remote_path}"]
        try:
            proc = subprocess.run(argv, capture_output=True, text=True,
                                  timeout=self.timeout_s)
        except subprocess.TimeoutExpired as e:
            raise ConnectionError_("scp upload timed out") from e
        if proc.returncode != 0:
            raise ConnectionError_(f"scp upload failed: {proc.stderr}")

    def download(self, remote_paths, local_dir: str) -> None:
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        os.makedirs(local_dir, exist_ok=True)
        srcs = [f"{self.user}@{self.host}:{p}" for p in remote_paths]
        try:
            proc = subprocess.run(
                ["scp", *self._scp_base, "-r", *srcs, local_dir],
                capture_output=True, text=True, timeout=self.timeout_s)
        except subprocess.TimeoutExpired as e:
            raise ConnectionError_("scp download timed out") from e
        if proc.returncode != 0:
            raise ConnectionError_(f"scp download failed: {proc.stderr}")

    def disconnect(self) -> None:
        try:
            subprocess.run(["ssh", *self._ssh_base, "-O", "exit",
                            f"{self.user}@{self.host}"],
                           capture_output=True, timeout=10)
        except Exception:
            pass
        shutil.rmtree(self._ctl_dir, ignore_errors=True)


class SshRemote(Remote):
    def __init__(self, **default_opts):
        self.default_opts = default_opts

    def connect(self, host: str, opts: Optional[dict] = None) -> Session:
        return SshSession(host, {**self.default_opts, **(opts or {})})
