"""Control-plane DSL: per-thread node context + exec + cluster fan-out.

Equivalent of the reference's `jepsen/control.clj` (SURVEY.md §2.1): the
dynamic environment (`*host*`, `*session*`, `*dir*`, `*sudo*`, `*remote*`),
`exec` (shell-escaped command execution on the current node), `su`/`sudo`
and `cd` scoping, `upload`/`download`, and `on_nodes` — parallel map over
nodes with a per-node session.  The reference uses Clojure dynamic vars;
we use a `threading.local` stack so `on_nodes` worker threads each see
their own binding.
"""

from __future__ import annotations

import concurrent.futures as _fut
import contextlib
import posixpath
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from jepsen_tpu.control.core import (Action, CmdResult, Remote, RemoteError,
                                     Session, join_cmd, lit)

__all__ = ["exec_", "exec_result", "cd", "sudo", "with_env", "upload",
           "download", "with_session", "session", "host", "on_nodes",
           "on_many", "lit", "file_contents", "write_file"]

_ctx = threading.local()


def _frame() -> dict:
    stack = getattr(_ctx, "stack", None)
    if not stack:
        raise RemoteError("no node session bound on this thread — use "
                          "with_session(...) or on_nodes(...)")
    return stack[-1]


def _push(frame: dict):
    if not hasattr(_ctx, "stack"):
        _ctx.stack = []
    _ctx.stack.append(frame)


def _pop():
    _ctx.stack.pop()


@contextlib.contextmanager
def with_session(host_: str, session_: Session, *,
                 dir: Optional[str] = None, sudo_user: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None):
    """Bind a node session on this thread."""
    _push({"host": host_, "session": session_, "dir": dir,
           "sudo": sudo_user, "env": env})
    try:
        yield
    finally:
        _pop()


def _rebind(**changes):
    f = dict(_frame())
    f.update(changes)

    @contextlib.contextmanager
    def scope():
        _push(f)
        try:
            yield
        finally:
            _pop()

    return scope()


def cd(dir: str):
    """Scope: run subsequent exec_ calls in `dir`."""
    return _rebind(dir=dir)


def sudo(user: str = "root"):
    """Scope: run subsequent exec_ calls as `user`."""
    return _rebind(sudo=user)


def with_env(**env):
    """Scope: add environment variables to subsequent exec_ calls."""
    f = _frame()
    merged = {**(f.get("env") or {}), **env}
    return _rebind(env=merged)


def host() -> str:
    return _frame()["host"]


def session() -> Session:
    return _frame()["session"]


def exec_result(*args: Any, in_: Optional[str] = None) -> CmdResult:
    """Run a command on the current node; return the full CmdResult
    without throwing on nonzero exit."""
    f = _frame()
    action = Action(cmd=join_cmd(args), in_=in_, dir=f.get("dir"),
                    sudo=f.get("sudo"), env=f.get("env"))
    return f["session"].execute(action)


def exec_(*args: Any, in_: Optional[str] = None) -> str:
    """Run a command on the current node; return trimmed stdout; raise
    RemoteError on nonzero exit (reference: `jepsen.control/exec`)."""
    return exec_result(*args, in_=in_).throw_on_nonzero().out.strip()


def upload(local_paths, remote_path: str) -> None:
    session().upload(local_paths, remote_path)


def download(remote_paths, local_dir: str) -> None:
    session().download(remote_paths, local_dir)


def file_contents(path: str) -> str:
    return exec_("cat", path)


def write_file(path: str, content: str) -> None:
    parent = posixpath.dirname(path)
    if parent:
        exec_("mkdir", "-p", parent)
    exec_("tee", path, in_=content)


def _node_opts(test: dict) -> dict:
    return {k: test[k] for k in ("username", "password", "port",
                                 "private_key_path", "strict_host_key_checking")
            if k in test}


def on_nodes(test: dict, fn: Callable[[dict, str], Any],
             nodes: Optional[Sequence[str]] = None) -> Dict[str, Any]:
    """Run `fn(test, node)` on each node in parallel, with a session for
    that node bound on the worker thread.  Returns {node: result}.

    Reference: `jepsen.control/on-nodes`.  Sessions come from
    `test["sessions"]` when `core.run` already opened them, else are opened
    (and closed) here from `test["remote"]`.
    """
    nodes = list(nodes if nodes is not None else test["nodes"])
    if not nodes:
        return {}
    remote: Remote = test["remote"]
    sessions: Dict[str, Session] = test.get("sessions") or {}

    def work(node: str) -> Any:
        sess = sessions.get(node)
        opened = False
        if sess is None:
            sess = remote.connect(node, _node_opts(test))
            opened = True
        try:
            with with_session(node, sess,
                              sudo_user=test.get("sudo"),
                              dir=test.get("dir")):
                return fn(test, node)
        finally:
            if opened:
                sess.disconnect()

    with _fut.ThreadPoolExecutor(max_workers=len(nodes)) as ex:
        results = list(ex.map(work, nodes))
    return dict(zip(nodes, results))


def on_many(test: dict, nodes: Sequence[str], thunk: Callable[[], Any]
            ) -> Dict[str, Any]:
    """Like on_nodes but takes a zero-arg thunk using the bound context."""
    return on_nodes(test, lambda _t, _n: thunk(), nodes)
