"""Remote protocol: how the control plane talks to a node.

Equivalent of the reference's `jepsen/control/core.clj` (SURVEY.md §2.1):
the `Remote` protocol — `connect`, `execute`, `upload`, `download`,
`disconnect` — plus shell escaping, command/result types, error handling,
and a retrying wrapper remote (reference: `control/retry.clj`).

Remotes are *factories*: `connect(host, opts)` returns a live session bound
to one node; sessions are used concurrently from at most one thread each
(the reference holds one sshj session per node under a lock; we hold one
session per node per `on_nodes` worker thread).
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Any, Dict, List, Optional, Sequence


class RemoteError(Exception):
    """A command failed (nonzero exit) or the transport broke."""

    def __init__(self, msg: str, *, cmd: Optional[str] = None,
                 exit_status: Optional[int] = None,
                 out: str = "", err: str = ""):
        super().__init__(msg)
        self.cmd = cmd
        self.exit_status = exit_status
        self.out = out
        self.err = err


class ConnectionError_(RemoteError):
    """Could not reach the node / transport unavailable."""


@dataclasses.dataclass
class Action:
    """A command to run on a node.

    Mirrors the reference's action maps: `cmd` is the (already-escaped)
    shell string; `in_` optional stdin; `dir` working directory; `sudo`
    user to become; `env` extra environment.
    """

    cmd: str
    in_: Optional[str] = None
    dir: Optional[str] = None
    sudo: Optional[str] = None
    env: Optional[Dict[str, str]] = None

    def wrapped_cmd(self) -> str:
        """The full shell line: env + cd + sudo wrapping, like the
        reference's `jepsen.control/wrap-cd`/`wrap-sudo`/`env`."""
        c = self.cmd
        if self.env:
            exports = " ".join(f"{k}={escape(str(v))}"
                               for k, v in sorted(self.env.items()))
            c = f"env {exports} {c}"
        if self.dir:
            c = f"cd {escape(self.dir)} && {c}"
        if self.sudo:
            # -n: never prompt — stdin belongs to the command (`in_`), not
            # to sudo; passworded sudo fails fast with a clear error
            c = f"sudo -n -u {escape(self.sudo)} bash -c {escape(c)}"
        return c


@dataclasses.dataclass
class CmdResult:
    cmd: str
    out: str
    err: str
    exit_status: int

    def throw_on_nonzero(self) -> "CmdResult":
        if self.exit_status != 0:
            raise RemoteError(
                f"command returned exit status {self.exit_status}\n"
                f"cmd: {self.cmd}\nout: {self.out[-2000:]}\n"
                f"err: {self.err[-2000:]}",
                cmd=self.cmd, exit_status=self.exit_status,
                out=self.out, err=self.err)
        return self


_UNSAFE = re.compile(r"[^A-Za-z0-9_/.,:=+@%^-]")


class Lit:
    """A literal shell fragment that must NOT be escaped (reference:
    `jepsen.control/lit`)."""

    def __init__(self, s: str):
        self.s = s

    def __repr__(self):
        return f"lit({self.s!r})"


def lit(s: str) -> Lit:
    return Lit(s)


def escape(x: Any) -> str:
    """Escape one token for the shell, like `jepsen.control/escape`."""
    if isinstance(x, Lit):
        return x.s
    s = str(x)
    if s == "":
        return "''"
    if _UNSAFE.search(s):
        return "'" + s.replace("'", "'\\''") + "'"
    return s


def join_cmd(args: Sequence[Any]) -> str:
    """Escape and join a token sequence into one shell line."""
    return " ".join(escape(a) for a in args)


class Session:
    """A live connection to one node."""

    def execute(self, action: Action) -> CmdResult:
        raise NotImplementedError

    def upload(self, local_paths, remote_path: str) -> None:
        raise NotImplementedError

    def download(self, remote_paths, local_dir: str) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass


class Remote:
    """Remote factory protocol."""

    def connect(self, host: str, opts: Optional[dict] = None) -> Session:
        raise NotImplementedError


class RetrySession(Session):
    """Wraps a session, retrying failed operations with backoff and
    reconnecting on connection errors (reference: `control/retry.clj`)."""

    def __init__(self, remote: Remote, host: str, opts: Optional[dict],
                 session: Session, *, retries: int = 5,
                 backoff_s: float = 0.2):
        self.remote = remote
        self.host = host
        self.opts = opts
        self.session = session
        self.retries = retries
        self.backoff_s = backoff_s

    def _with_retry(self, fn):
        delay = self.backoff_s
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            try:
                return fn()
            except ConnectionError_ as e:
                last = e
                if attempt == self.retries:
                    break
                time.sleep(delay)
                delay *= 2
                try:
                    self.session.disconnect()
                except Exception:
                    pass
                try:
                    self.session = self.remote.connect(self.host, self.opts)
                except Exception as e2:  # reconnect failed; keep retrying
                    last = ConnectionError_(str(e2))
        raise last  # type: ignore[misc]

    def execute(self, action: Action) -> CmdResult:
        return self._with_retry(lambda: self.session.execute(action))

    def upload(self, local_paths, remote_path):
        return self._with_retry(
            lambda: self.session.upload(local_paths, remote_path))

    def download(self, remote_paths, local_dir):
        return self._with_retry(
            lambda: self.session.download(remote_paths, local_dir))

    def disconnect(self):
        self.session.disconnect()


class RetryRemote(Remote):
    def __init__(self, remote: Remote, *, retries: int = 5,
                 backoff_s: float = 0.2):
        self.remote = remote
        self.retries = retries
        self.backoff_s = backoff_s

    def connect(self, host, opts=None):
        return RetrySession(self.remote, host, opts,
                            self.remote.connect(host, opts),
                            retries=self.retries, backoff_s=self.backoff_s)
