"""Node administration helpers built on the control DSL.

Equivalent of the reference's `jepsen/control/util.clj` (SURVEY.md §2.1):
daemon lifecycle (`start_daemon`/`stop_daemon` with pidfiles),
`grepkill`, archive install, cached wget, temp dirs, existence checks.
All of these run *on the current node* via the bound session.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Sequence

from jepsen_tpu.control import api as c
from jepsen_tpu.control.core import RemoteError, escape, lit


def exists(path: str) -> bool:
    return c.exec_result("test", "-e", path).exit_status == 0


def ls(dir: str = ".") -> List[str]:
    out = c.exec_result("ls", "-1", dir).throw_on_nonzero().out
    return [l for l in out.splitlines() if l]


def tmp_dir() -> str:
    """Create and return a fresh temp dir on the node."""
    return c.exec_("mktemp", "-d", "-t", "jepsen.XXXXXX")


def start_daemon(bin_: str, *args: Any, logfile: str, pidfile: str,
                 chdir: Optional[str] = None,
                 env: Optional[dict] = None,
                 make_pidfile: bool = True) -> None:
    """Start a long-running process on the node, recording its pid.

    Reference: `control/util.clj start-daemon!` (start-stop-daemon).  We
    use setsid + nohup + `$!` which needs only POSIX sh, since db images
    may lack start-stop-daemon.
    """
    envs = " ".join(f"{escape(k)}={escape(str(v))}"
                    for k, v in (env or {}).items())
    cmdline = " ".join(escape(a) for a in (bin_, *args))
    script = (f"{'cd ' + escape(chdir) + ' && ' if chdir else ''}"
              f"setsid nohup env {envs} {cmdline} "
              f">> {escape(logfile)} 2>&1 & "
              + (f"echo $! > {escape(pidfile)}" if make_pidfile else "true"))
    c.exec_("bash", "-c", script)


def daemon_running(pidfile: str) -> bool:
    p = escape(pidfile)
    r = c.exec_result("bash", "-c", f"test -e {p} && kill -0 $(cat {p})")
    return r.exit_status == 0


def stop_daemon(pidfile: str, *, signal: str = "TERM",
                wait_s: float = 5.0) -> None:
    """Kill the process recorded in pidfile (then KILL), remove pidfile.
    Reference: `control/util.clj stop-daemon!`."""
    p = escape(pidfile)
    script = (f"if test -e {p}; then "
              f"pid=$(cat {p}); "
              f"kill -{signal} $pid 2>/dev/null || true; "
              f"for i in $(seq 1 {int(wait_s * 10)}); do "
              f"kill -0 $pid 2>/dev/null || break; sleep 0.1; done; "
              f"kill -KILL $pid 2>/dev/null || true; "
              f"rm -f {p}; fi")
    c.exec_("bash", "-c", script)


def grepkill(pattern: str, signal: str = "KILL") -> None:
    """Kill all processes matching `pattern` (reference: `grepkill!`).

    The invoking shell's own cmdline contains the pattern, so pkill would
    match (and kill) it; filter out $$ and $PPID instead.
    """
    c.exec_("bash", "-c",
            f"for p in $(pgrep -f -- {escape(pattern)}); do "
            f'[ "$p" != "$$" ] && [ "$p" != "$PPID" ] '
            f"&& kill -{signal} $p 2>/dev/null; done; true")


def install_archive(url: str, dest_dir: str, *,
                    force: bool = False) -> str:
    """Download (with cache) and unpack a tar/zip archive into dest_dir.
    Reference: `control/util.clj install-archive!`."""
    if exists(dest_dir) and not force:
        return dest_dir
    cache = cached_wget(url)
    c.exec_("rm", "-rf", dest_dir)
    c.exec_("mkdir", "-p", dest_dir)
    name = os.path.basename(url)
    if name.endswith(".zip"):
        # match the tar branch's layout: strip a single top-level dir
        c.exec_("unzip", "-q", "-o", cache, "-d", dest_dir + ".unzip")
        c.exec_("bash", "-c",
                f"src={escape(dest_dir + '.unzip')}; "
                f"dst={escape(dest_dir)}; "
                "entries=$(ls -1 \"$src\" | wc -l); "
                "if [ \"$entries\" = 1 ] && "
                "[ -d \"$src/$(ls -1 \"$src\")\" ]; then "
                "mv \"$src\"/*/* \"$dst\"/ 2>/dev/null; "
                "mv \"$src\"/*/.[!.]* \"$dst\"/ 2>/dev/null; true; "
                "else mv \"$src\"/* \"$dst\"/; fi; rm -rf \"$src\"")
    else:
        c.exec_("tar", "-xf", cache, "-C", dest_dir,
                "--strip-components", "1")
    return dest_dir


def cached_wget(url: str, *, cache_dir: str = "/tmp/jepsen/cache",
                force: bool = False) -> str:
    """Fetch url once per node; return the cached file path.
    Reference: `control/util.clj cached-wget!`."""
    name = os.path.basename(url) or "download"
    path = f"{cache_dir}/{name}"
    if force or not exists(path):
        c.exec_("mkdir", "-p", cache_dir)
        try:
            c.exec_("wget", "-q", "-O", path + ".part", url)
        except RemoteError:
            c.exec_("curl", "-fsSL", "-o", path + ".part", url)
        c.exec_("mv", path + ".part", path)
    return path


def signal_process(pattern_or_pid, sig: str) -> None:
    if isinstance(pattern_or_pid, int):
        c.exec_("kill", f"-{sig}", str(pattern_or_pid))
    else:
        c.exec_("bash", "-c",
                f"pkill -{sig} -f {escape(pattern_or_pid)} "
                "2>/dev/null || true")
