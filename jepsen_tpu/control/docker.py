"""Docker / Kubernetes exec remotes.

Equivalent of the reference's `jepsen/control/docker.clj` and
`control/k8s.clj` (SURVEY.md §2.1): run node commands with `docker exec` /
`kubectl exec` instead of SSH, letting tests target containerized clusters
with no SSH daemon.  Gated on the respective binary existing.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional

from jepsen_tpu.control.core import (Action, CmdResult, ConnectionError_,
                                     Remote, Session)


class _ExecSession(Session):
    """Shared machinery: a session that runs `<argv-prefix> <shell -c cmd>`
    and copies files with a cp-style subcommand."""

    def __init__(self, host: str, timeout_s: float):
        self.host = host
        self.timeout_s = timeout_s

    def _exec_argv(self, cmd: str) -> List[str]:
        raise NotImplementedError

    def _cp_argv(self, src: str, dst: str) -> List[str]:
        """argv for copying src -> dst, where one side is host:path."""
        raise NotImplementedError

    def execute(self, action: Action) -> CmdResult:
        cmd = action.wrapped_cmd()
        try:
            proc = subprocess.run(self._exec_argv(cmd), input=action.in_,
                                  text=True, capture_output=True,
                                  timeout=self.timeout_s)
        except subprocess.TimeoutExpired as e:
            raise ConnectionError_(f"exec timed out: {cmd}", cmd=cmd) from e
        return CmdResult(cmd=cmd, out=proc.stdout, err=proc.stderr,
                         exit_status=proc.returncode)

    def _cp(self, src: str, dst: str) -> None:
        argv = self._cp_argv(src, dst)
        try:
            proc = subprocess.run(argv, capture_output=True, text=True,
                                  timeout=self.timeout_s)
        except subprocess.TimeoutExpired as e:
            raise ConnectionError_(f"{argv[0]} cp timed out") from e
        if proc.returncode != 0:
            raise ConnectionError_(f"{argv[0]} cp failed: {proc.stderr}")

    def upload(self, local_paths, remote_path: str) -> None:
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        for lp in local_paths:
            self._cp(str(lp), f"{self.host}:{remote_path}")

    def download(self, remote_paths, local_dir: str) -> None:
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        os.makedirs(local_dir, exist_ok=True)
        for rp in remote_paths:
            self._cp(f"{self.host}:{rp}",
                     os.path.join(local_dir, os.path.basename(str(rp))))


class DockerSession(_ExecSession):
    def _exec_argv(self, cmd):
        return ["docker", "exec", "-i", self.host, "bash", "-c", cmd]

    def _cp_argv(self, src, dst):
        return ["docker", "cp", src, dst]


class DockerRemote(Remote):
    def __init__(self, timeout_s: float = 60.0):
        if shutil.which("docker") is None:
            raise ConnectionError_("no `docker` binary on PATH")
        self.timeout_s = timeout_s

    def connect(self, host: str, opts: Optional[dict] = None) -> Session:
        return DockerSession(host, self.timeout_s)


class K8sSession(_ExecSession):
    def __init__(self, host: str, namespace: str, container: Optional[str],
                 timeout_s: float):
        super().__init__(host, timeout_s)
        self.namespace = namespace
        self.container = container

    def _exec_argv(self, cmd):
        argv = ["kubectl", "-n", self.namespace, "exec", "-i", self.host]
        if self.container:
            argv += ["-c", self.container]
        return [*argv, "--", "bash", "-c", cmd]

    def _cp_argv(self, src, dst):
        return ["kubectl", "-n", self.namespace, "cp", src, dst]


class K8sRemote(Remote):
    def __init__(self, namespace: str = "default",
                 container: Optional[str] = None, timeout_s: float = 60.0):
        if shutil.which("kubectl") is None:
            raise ConnectionError_("no `kubectl` binary on PATH")
        self.namespace = namespace
        self.container = container
        self.timeout_s = timeout_s

    def connect(self, host: str, opts: Optional[dict] = None) -> Session:
        return K8sSession(host, self.namespace, self.container,
                          self.timeout_s)
