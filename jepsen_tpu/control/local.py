"""Loopback remote: run "node" commands as local subprocesses.

No reference equivalent file — the reference gets no-SSH operation from its
docker/k8s exec remotes (`control/docker.clj`, `control/k8s.clj`); this is
the same idea taken one step further so the whole control plane is testable
on a single machine with zero infrastructure.  Each logical node gets a
private root directory; uploads/downloads are copies into/out of it.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Optional

from jepsen_tpu.control.core import (Action, CmdResult, ConnectionError_,
                                     Remote, Session)


class LoopbackSession(Session):
    def __init__(self, host: str, root: Optional[str], timeout_s: float):
        self.host = host
        self.root = root
        self.timeout_s = timeout_s

    def execute(self, action: Action) -> CmdResult:
        cmd = action.wrapped_cmd()
        env = dict(os.environ)
        if self.root:
            env["JEPSEN_NODE_ROOT"] = self.root
            env["JEPSEN_NODE"] = self.host
        try:
            proc = subprocess.run(
                ["bash", "-c", cmd], input=action.in_, text=True,
                capture_output=True, timeout=self.timeout_s,
                cwd=self.root or None, env=env)
        except subprocess.TimeoutExpired as e:
            raise ConnectionError_(f"command timed out: {cmd}", cmd=cmd) \
                from e
        return CmdResult(cmd=cmd, out=proc.stdout, err=proc.stderr,
                         exit_status=proc.returncode)

    def _resolve(self, path: str) -> str:
        # Relative paths are node-local (sandboxed); absolute paths refer to
        # the real filesystem — the same rule execute() follows (commands run
        # with cwd=root, so their relative paths land in the sandbox too).
        if self.root and not os.path.isabs(path):
            return os.path.join(self.root, path)
        return path

    def upload(self, local_paths, remote_path: str) -> None:
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        dest = self._resolve(remote_path)
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        for lp in local_paths:
            if os.path.isdir(lp):
                shutil.copytree(lp, dest, dirs_exist_ok=True)
            elif len(local_paths) == 1 and not os.path.isdir(dest):
                shutil.copyfile(lp, dest)
            else:
                os.makedirs(dest, exist_ok=True)
                shutil.copyfile(lp, os.path.join(dest, os.path.basename(lp)))

    def download(self, remote_paths, local_dir: str) -> None:
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        os.makedirs(local_dir, exist_ok=True)
        for rp in remote_paths:
            src = self._resolve(str(rp))
            if not os.path.exists(src):
                continue
            dst = os.path.join(local_dir, os.path.basename(src))
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                shutil.copyfile(src, dst)


class LoopbackRemote(Remote):
    """`base_dir=None` executes in the real filesystem (like running the
    control plane on the node itself); otherwise each host is sandboxed in
    `base_dir/<host>/`."""

    def __init__(self, base_dir: Optional[str] = None,
                 timeout_s: float = 60.0):
        self.base_dir = base_dir
        self.timeout_s = timeout_s

    def connect(self, host: str, opts: Optional[dict] = None) -> Session:
        root = None
        if self.base_dir:
            root = os.path.join(self.base_dir, host)
            os.makedirs(root, exist_ok=True)
        return LoopbackSession(host, root, self.timeout_s)
