"""Sequential datatype models for linearizability checking.

Equivalent of the reference's `knossos/model.clj` (SURVEY.md §2.4): a
`Model` steps through operations, returning the next model or
`Inconsistent`.  Models are pure and hashable — the property the memoizer
(`checkers.knossos.memo`) exploits to canonicalize reachable states into
dense ints and precompute the state x op transition table that both the
host WGL search and the TPU batched frontier search consume.

Ops are (f, value) pairs; a read with value None matches any state
(unknown result, e.g. a crashed read), as in the reference.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple


class Inconsistent:
    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg

    def __repr__(self):
        return f"Inconsistent({self.msg!r})"


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


class Model:
    """Base sequential model.  Subclasses implement `step(f, value)` and
    must be value-objects: __eq__/__hash__ over their state."""

    def step(self, f: str, value: Any):
        raise NotImplementedError

    # default identity = type + __dict__ tuple
    def _key(self) -> Tuple:
        return tuple(sorted(self.__dict__.items(),
                            key=lambda kv: kv[0]))

    def __eq__(self, other):
        return type(self) is type(other) and self._key() == other._key()

    def __hash__(self):
        return hash((type(self).__name__, self._key()))

    def __repr__(self):
        return f"{type(self).__name__}({self.__dict__})"


class Register(Model):
    """A read/write register (reference `model/register`)."""

    def __init__(self, value=None):
        self.value = value

    def step(self, f, v):
        if f == "write":
            return Register(v)
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"read {v!r}, expected {self.value!r}")
        return inconsistent(f"unknown op {f!r}")


class CASRegister(Model):
    """A compare-and-set register (reference `model/cas-register`)."""

    def __init__(self, value=None):
        self.value = value

    def step(self, f, v):
        if f == "write":
            return CASRegister(v)
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"read {v!r}, expected {self.value!r}")
        if f == "cas":
            old, new = v
            if self.value == old:
                return CASRegister(new)
            return inconsistent(f"cas {old!r}->{new!r} on {self.value!r}")
        return inconsistent(f"unknown op {f!r}")


class Mutex(Model):
    """A lock (reference `model/mutex`)."""

    def __init__(self, locked: bool = False):
        self.locked = locked

    def step(self, f, v):
        if f == "acquire":
            if self.locked:
                return inconsistent("already held")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return inconsistent("not held")
            return Mutex(False)
        return inconsistent(f"unknown op {f!r}")


class FIFOQueue(Model):
    """A FIFO queue (reference `model/fifo-queue`)."""

    def __init__(self, items: Tuple = ()):
        self.items = tuple(items)

    def step(self, f, v):
        if f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            head, rest = self.items[0], self.items[1:]
            if v is None or v == head:
                return FIFOQueue(rest)
            return inconsistent(f"dequeued {v!r}, expected {head!r}")
        return inconsistent(f"unknown op {f!r}")


class UnorderedQueue(Model):
    """A bag/unordered queue (reference `model/unordered-queue`)."""

    def __init__(self, items: Tuple = ()):
        self.items = tuple(sorted(items, key=repr))

    def step(self, f, v):
        if f == "enqueue":
            return UnorderedQueue(self.items + (v,))
        if f == "dequeue":
            if v is None:
                if not self.items:
                    return inconsistent("dequeue from empty queue")
                return UnorderedQueue(self.items[1:])
            if v in self.items:
                items = list(self.items)
                items.remove(v)
                return UnorderedQueue(tuple(items))
            return inconsistent(f"dequeued {v!r} not in queue")
        return inconsistent(f"unknown op {f!r}")


class GrowOnlySet(Model):
    """A grow-only set with reads (reference `model/set`)."""

    def __init__(self, items: Tuple = ()):
        self.items = tuple(sorted(set(items), key=repr))

    def step(self, f, v):
        if f == "add":
            return GrowOnlySet(self.items + (v,))
        if f == "read":
            if v is None or set(v) == set(self.items):
                return self
            return inconsistent(f"read {v!r}, expected {self.items!r}")
        return inconsistent(f"unknown op {f!r}")


def register(value=None) -> Register:
    return Register(value)


def cas_register(value=None) -> CASRegister:
    return CASRegister(value)


def mutex() -> Mutex:
    return Mutex()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def grow_only_set() -> GrowOnlySet:
    return GrowOnlySet()
