"""regd per-DB suite: real OS processes under the full control plane.

The round-5 closure of VERDICT r04 item 6 ("no suite drives the L1
control plane against a real OS process").  Every lifecycle step goes
through `jepsen_tpu.control` exactly the way the reference's suites go
through `jepsen.control`:

  install   — the daemon source is `c.upload`-ed into a per-node dir
  start     — `control/util.start_daemon` (setsid + nohup + pidfile)
  kill      — `control/util.grepkill` (SIGKILL by pattern: the crash)
  restart   — start_daemon again; the WAL replay proves durability
  teardown  — `control/util.stop_daemon`
  logs      — `DB.log_files` -> core.run's log download into the store

The client talks real TCP to the node's daemon.  Completion semantics
(the part per-DB suites must get right): connection refused / reply
before commit -> :fail; socket death after the request is on the wire
-> :info (indeterminate); `indeterminate` proxy replies -> :info.

Reference analogues: `jepsen/db.clj` + `control/util.clj` +
any monorepo suite (e.g. the etcd tutorial's `db/setup!` +
`start-daemon!`).
"""

from __future__ import annotations

import json
import os
import socket
from typing import Any, Dict, List, Optional

from jepsen_tpu import db as db_proto
from jepsen_tpu.client import Client
from jepsen_tpu.control import api as c
from jepsen_tpu.control import util as cu

DAEMON_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "regd.py")


class RegDB(db_proto.DB, db_proto.Process, db_proto.Primary,
            db_proto.LogFiles):
    """Deploys one regd daemon per node through the control plane."""

    def __init__(self, base_port: int = 7610, stale_reads: bool = False):
        self.base_port = base_port
        self.stale_reads = stale_reads

    # ---- layout ---------------------------------------------------------
    def port(self, test: dict, node: str) -> int:
        return self.base_port + test["nodes"].index(node)

    def node_dir(self, test: dict, node: str) -> str:
        from jepsen_tpu import store

        return os.path.join(store.test_dir(test), "regd", node)

    def _paths(self, test, node):
        d = self.node_dir(test, node)
        return {
            "dir": d,
            "bin": os.path.join(d, "regd.py"),
            "wal": os.path.join(d, "wal.jsonl"),
            "log": os.path.join(d, "regd.log"),
            "pid": os.path.join(d, "regd.pid"),
        }

    def _pattern(self, test, node) -> str:
        """grepkill pattern: unique per node AND per suite instance
        (the port embeds base_port, so concurrent suites on different
        ports never cross-kill each other's daemons)."""
        return f"regd.py --name {node} --port {self.port(test, node)} "

    # ---- DB protocol ----------------------------------------------------
    def setup(self, test, node):
        p = self._paths(test, node)
        c.exec_("mkdir", "-p", p["dir"])
        # install: ship the daemon source through the control plane
        c.upload([DAEMON_SRC], p["bin"])
        self.start_and_await(test, node)

    def teardown(self, test, node):
        p = self._paths(test, node)
        cu.stop_daemon(p["pid"])
        cu.grepkill(self._pattern(test, node))

    def start(self, test, node):
        import sys

        p = self._paths(test, node)
        peers = [f"--peer={n}:{self.port(test, n)}"
                 for n in test["nodes"] if n != node]
        args = [p["bin"], "--name", node, "--port",
                str(self.port(test, node)), "--primary",
                test["nodes"][0], "--wal", p["wal"], *peers]
        if self.stale_reads:
            args.append("--stale-reads")
        cu.start_daemon(sys.executable, *args,
                        logfile=p["log"], pidfile=p["pid"])

    def start_and_await(self, test, node):
        """Start the daemon and block until it answers pings — the
        sequence both setup and restart nemeses need (readiness policy
        lives in exactly one place)."""
        self.start(test, node)
        self._await_ready(test, node)

    def kill(self, test, node):
        # the crash path: SIGKILL by pattern, no graceful anything
        cu.grepkill(self._pattern(test, node))

    def running(self, test, node) -> bool:
        return cu.daemon_running(self._paths(test, node)["pid"])

    def primaries(self, test) -> List[str]:
        return [test["nodes"][0]]

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        p = self._paths(test, node)
        return [p["log"], p["wal"]]

    # ---- helpers --------------------------------------------------------
    def _await_ready(self, test, node, timeout_s: float = 60.0):
        # generous: bare python startup measured 4.5 s on this box while
        # an XLA compile owned the single core
        import time

        deadline = time.monotonic() + timeout_s
        last = None
        while time.monotonic() < deadline:
            try:
                r = request(self.port(test, node), {"op": "ping"},
                            timeout_s=1.0)
                if r.get("ok"):
                    return
                last = r
            except OSError as e:
                last = e
            time.sleep(0.1)
        raise RuntimeError(f"regd on {node} not ready: {last}")


def request(port: int, req: dict, timeout_s: float = 5.0) -> dict:
    """One JSON-lines request/reply over a fresh TCP connection."""
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout_s) as s:
        s.sendall(json.dumps(req).encode() + b"\n")
        line = s.makefile().readline()
    if not line:
        raise ConnectionError("empty reply")
    return json.loads(line)


class RegClient(Client):
    """Real-TCP client bound to one node's daemon."""

    FAIL_ERRORS = ("not-primary", "primary-unreachable", "blocked")

    def __init__(self, db: RegDB):
        self.db = db
        self.node: Optional[str] = None
        self.port: Optional[int] = None

    def open(self, test, node):
        c = RegClient(self.db)
        c.node = node
        c.port = self.db.port(test, node)
        return c

    def invoke(self, test, op):
        mops: List[List[Any]] = op["value"]
        writes = any(m[0] == "append" for m in mops)
        try:
            resp = request(self.port, {"op": "txn", "txn": mops})
        except ConnectionRefusedError:
            return dict(op, type="fail", error="connection refused")
        except OSError as e:
            # the request may have reached a daemon that then died:
            # writes are indeterminate, reads never changed anything
            t = "info" if writes else "fail"
            return dict(op, type=t, error=f"socket: {e}")
        if resp.get("ok"):
            return dict(op, type="ok", value=resp["txn"])
        err = resp.get("error", "?")
        if err == "indeterminate":
            return dict(op, type="info", error=err)
        return dict(op, type="fail", error=err)

    def close(self, test):
        pass


def _make_test(opts: Dict[str, Any], name: str, stale_reads: bool
               ) -> Dict[str, Any]:
    from jepsen_tpu.control.local import LoopbackRemote
    from jepsen_tpu.generator import core as g
    from jepsen_tpu.workloads import append

    # thread the requested models into the checker: backup staleness is
    # LEGAL under plain serializable (reads serialize early); only a
    # realtime-aware model makes the stale-read hole visible
    models = tuple(opts.get("consistency-models",
                            ("strict-serializable",)))
    wl = append.workload(consistency_models=models)
    database = RegDB(base_port=int(opts.get("base-port", 7610)),
                     stale_reads=stale_reads)
    test = dict(opts)
    if test.get("remote") is None:
        test["remote"] = LoopbackRemote()
    test.update({
        "name": name,
        "nodes": opts.get("nodes") or ["n1", "n2", "n3"],
        "db": database,
        "client": RegClient(database),
        "generator": g.stagger(0.003, wl["generator"]),
        "checker": wl["checker"],
    })
    test.setdefault("consistency-models", ("strict-serializable",))
    return test


def append_test(opts: Dict[str, Any], stale_reads: bool = False
                ) -> Dict[str, Any]:
    """List-append over a real multi-process regd cluster."""
    return _make_test(opts, "regd-append", stale_reads)


if __name__ == "__main__":
    from jepsen_tpu import cli

    cli.main(cli.test_all_cmd({"append": append_test},
                              prog="python -m jepsen_tpu.dbs.regd_suite"))
