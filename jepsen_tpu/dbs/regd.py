"""regd — a real, standalone list-append store daemon for control-plane
integration testing (VERDICT r04 item 6: every reference per-DB suite
drives `jepsen.control` against real OS processes; both round-4 suites
were in-process).

One `python -m jepsen_tpu.dbs.regd` process per node:

- JSON-lines protocol over TCP (one request object per line).
- Durable write-ahead log: every applied txn is appended + fsync'd
  before the reply, and replayed on restart — so `kill -9` + restart
  keeps the history linearizable (the integration suite kills nodes
  mid-run and the checker verifies exactly this).
- Primary/backup replication: the configured primary applies txns and
  synchronously forwards them to every reachable backup; backups serve
  local reads (stale under partition — a deliberate, checkable
  consistency hole when the suite requests strong models).
- Socket-level fault injection: the admin `block`/`heal` commands make
  a node drop replication connections from named peers — the same Net
  protocol surface as iptables (`net.py`), available where the test
  runner lacks root.  Reference analogue: `jepsen.nemesis` partitions
  via iptables; the *protocol* is what the harness exercises.

The daemon is deliberately dependency-free (stdlib only): it must start
via `control/util.start_daemon` from a bare install dir.

Protocol requests (one JSON object per line):
  {"op": "txn", "txn": [["append", k, v], ["r", k, null]]}
      -> {"ok": true, "txn": [...completed mops...]}
  {"op": "block", "peers": [...]} / {"op": "heal"} -> {"ok": true}
  {"op": "ping"} -> {"ok": true, "role": "primary"|"backup",
                     "applied": N}
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import socketserver
import sys
import threading


class Store:
    """Durable list-append store: dict key -> list, WAL-backed."""

    def __init__(self, wal_path: str):
        self.wal_path = wal_path
        self.data = {}
        self.applied = 0
        self.lock = threading.Lock()
        good_end = self._replay()
        if good_end is not None:
            # truncate a torn tail before appending: a new record
            # concatenated onto a partial line would make the NEXT
            # replay drop it and everything after it — silently losing
            # fsync-acknowledged commits
            with open(self.wal_path, "rb+") as f:
                f.truncate(good_end)
        self.wal = open(wal_path, "ab")

    def _replay(self):
        """Replay the WAL; returns the byte offset after the last
        parseable record (None if the file doesn't exist)."""
        if not os.path.exists(self.wal_path):
            return None
        pos = 0
        with open(self.wal_path, "rb") as f:
            for line in f:
                stripped = line.strip()
                if stripped:
                    try:
                        rec = json.loads(stripped)
                    except ValueError:
                        break  # torn tail: fsync'd prefix is safe
                    self._apply(rec["txn"], results=False)
                    self.applied += 1
                pos += len(line)
        return pos

    def _apply(self, txn, results=True):
        out = []
        for f, k, v in txn:
            if f == "append":
                self.data.setdefault(k, []).append(v)
                out.append([f, k, v])
            elif f == "r":
                out.append([f, k, list(self.data.get(k, []))])
            else:
                raise ValueError(f"unknown mop {f!r}")
        return out if results else None

    def commit(self, txn):
        """Apply + durably log (fsync before returning)."""
        with self.lock:
            out = self._apply(txn)
            self.wal.write(json.dumps({"txn": txn}).encode() + b"\n")
            self.wal.flush()
            os.fsync(self.wal.fileno())
            self.applied += 1
            return out

    def read_only(self, txn):
        with self.lock:
            return self._apply(txn)


class Node:
    def __init__(self, name, port, peers, primary, wal_path,
                 stale_reads=False):
        self.name = name
        self.port = port
        self.peers = peers          # {name: port} of OTHER nodes
        self.primary = primary      # name of the configured primary
        self.store = Store(wal_path)
        self.stale_reads = stale_reads
        self.blocked = set()
        self.lock = threading.Lock()
        # serializes commit+forward so backups apply txns in the
        # primary's WAL order (without it, two handler threads can
        # forward in the opposite order and a backup diverges
        # PERMANENTLY — order corruption, not the documented staleness)
        self.write_lock = threading.Lock()

    @property
    def is_primary(self):
        return self.name == self.primary

    def forward(self, txn):
        """Primary -> backups: synchronous best-effort replication.
        Unreachable/blocked backups are skipped (they fall behind; with
        --stale-reads their local reads expose it — the checkable
        hole)."""
        with self.lock:
            blocked = set(self.blocked)
        for peer, port in self.peers.items():
            if peer in blocked:
                continue
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=2.0) as s:
                    s.sendall(json.dumps(
                        {"op": "replicate", "from": self.name,
                         "txn": txn}).encode() + b"\n")
                    s.makefile().readline()
            except OSError:
                pass

    def proxy_to_primary(self, req, writes):
        with self.lock:
            blocked = self.primary in self.blocked
        port = self.peers.get(self.primary)
        if blocked or port is None:
            return {"ok": False, "error": "primary-unreachable"}
        sent = False
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=2.0) as s:
                s.sendall(json.dumps(req).encode() + b"\n")
                sent = True
                line = s.makefile().readline()
        except OSError:
            line = None
        if line:
            try:
                return json.loads(line)
            except ValueError:
                pass
        # a write that reached the wire but got no reply may have landed
        return {"ok": False, "error":
                "indeterminate" if (sent and writes)
                else "primary-unreachable"}

    def handle(self, req):
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "role":
                    "primary" if self.is_primary else "backup",
                    "applied": self.store.applied}
        if op == "block":
            with self.lock:
                self.blocked |= set(req.get("peers", []))
            return {"ok": True}
        if op == "heal":
            with self.lock:
                self.blocked.clear()
            return {"ok": True}
        if op == "replicate":
            with self.lock:
                if req.get("from") in self.blocked:
                    return {"ok": False, "error": "blocked"}
            return {"ok": True,
                    "txn": self.store.commit(req["txn"])}
        if op == "txn":
            txn = req["txn"]
            writes = any(f == "append" for f, _, _ in txn)
            if self.is_primary:
                if writes:
                    with self.write_lock:
                        out = self.store.commit(txn)
                        self.forward(txn)
                else:
                    out = self.store.read_only(txn)
                return {"ok": True, "txn": out}
            if not writes and self.stale_reads:
                # local reads on a backup: stale under lag/partition —
                # the deliberate consistency hole the checker must catch
                return {"ok": True, "txn": self.store.read_only(txn)}
            return self.proxy_to_primary(req, writes)
        return {"ok": False, "error": f"unknown op {op!r}"}


def serve(node: Node):
    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for line in self.rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    resp = node.handle(json.loads(line))
                except Exception as e:  # noqa: BLE001 — protocol error reply
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                self.wfile.write(json.dumps(resp).encode() + b"\n")
                self.wfile.flush()

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Server(("127.0.0.1", node.port), Handler) as srv:
        print(f"regd {node.name} listening on {node.port} "
              f"(primary={node.primary})", flush=True)
        srv.serve_forever()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--primary", required=True)
    ap.add_argument("--peer", action="append", default=[],
                    help="name:port of another node (repeatable)")
    ap.add_argument("--wal", required=True)
    ap.add_argument("--stale-reads", action="store_true")
    a = ap.parse_args(argv)
    peers = {}
    for p in a.peer:
        name, port = p.rsplit(":", 1)
        peers[name] = int(port)
    serve(Node(a.name, a.port, peers, a.primary, a.wal,
               stale_reads=a.stale_reads))


if __name__ == "__main__":
    main()
