"""SQLite test suite — a real ACID database, testable with no cluster.

Equivalent in shape to the reference's per-DB suites (SURVEY.md §2.6:
each suite = DB setup + Client over the shared workloads, e.g. the etcd
tutorial suite wiring `jepsen.tests.cycle.append` over an etcd client).
SQLite is the one real database every environment has: a single shared
file, WAL or rollback journaling, SERIALIZABLE by default, plus a
deliberately unsafe `read_uncommitted` knob — so the suite exercises
both "checker proves it valid" and "checker catches the misconfig".

Workloads: list-append (`la` table, one row per appended element) and
rw-register (`kv` table), both through real transactions:

  BEGIN IMMEDIATE; ... ; COMMIT          (write txns take the write lock
                                          up front — SQLITE_BUSY surfaces
                                          at BEGIN, a clean :fail)

Completion semantics (the part per-DB suites must get right):
  - BUSY/locked at BEGIN or mid-txn -> rollback -> :fail (not applied)
  - error during COMMIT itself       -> :info (indeterminate — the
    commit may have landed; checkers treat the op as forever-concurrent)
"""

from __future__ import annotations

import os
import sqlite3
from typing import Any, Dict, List, Optional

from jepsen_tpu import db as db_proto
from jepsen_tpu.client import Client


class SqliteDB(db_proto.DB, db_proto.LogFiles):
    """The "cluster": one SQLite database file shared by every node.

    setup creates the schema; teardown removes the file (unless the test
    sets `leave-db-running`).
    """

    def __init__(self, path: Optional[str] = None, *, wal: bool = True):
        self.path = path
        self.wal = wal

    def _db_path(self, test: dict) -> str:
        if self.path:
            return self.path
        from jepsen_tpu import store

        return os.path.join(store.test_dir(test), "sqlite.db")

    def setup(self, test, node):
        # one-time schema; racing nodes are harmless (IF NOT EXISTS)
        conn = sqlite3.connect(self._db_path(test), timeout=5.0)
        try:
            if self.wal:
                conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("CREATE TABLE IF NOT EXISTS la ("
                         "k INTEGER, pos INTEGER, v INTEGER, "
                         "PRIMARY KEY (k, pos))")
            conn.execute("CREATE TABLE IF NOT EXISTS kv ("
                         "k INTEGER PRIMARY KEY, v INTEGER)")
            conn.commit()
        finally:
            conn.close()

    def teardown(self, test, node):
        if test.get("leave-db-running"):
            return
        p = self._db_path(test)
        for suffix in ("", "-wal", "-shm"):
            try:
                os.unlink(p + suffix)
            except FileNotFoundError:
                pass

    def log_files(self, test, node):
        return []


class SqliteClient(Client):
    """One connection per process over the shared database file.

    `isolation`: "serializable" (default; SQLite's normal behavior) or
    "read_uncommitted" (shared-cache dirty reads — the misconfig the
    checker must catch).  `txn_kind` picks how "r" mops resolve: the
    list-append table or the kv register table (same convention as
    `workloads.mem.MemClient`).
    """

    def __init__(self, db: SqliteDB, *, isolation: str = "serializable",
                 busy_timeout_ms: int = 200,
                 txn_kind: str = "list-append"):
        self.db = db
        self.isolation = isolation
        self.busy_timeout_ms = busy_timeout_ms
        self.txn_kind = txn_kind
        self.conn: Optional[sqlite3.Connection] = None
        self._path: Optional[str] = None

    def open(self, test, node):
        c = SqliteClient(self.db, isolation=self.isolation,
                         busy_timeout_ms=self.busy_timeout_ms,
                         txn_kind=self.txn_kind)
        c._path = self.db._db_path(test)
        uri = f"file:{c._path}"
        if self.isolation == "read_uncommitted":
            uri += "?cache=shared"
        c.conn = sqlite3.connect(uri, uri=True,
                                 timeout=self.busy_timeout_ms / 1000.0,
                                 isolation_level=None,  # explicit BEGIN
                                 check_same_thread=False)
        if self.isolation == "read_uncommitted":
            c.conn.execute("PRAGMA read_uncommitted=1")
        return c

    def invoke(self, test, op):
        mops: List[List[Any]] = op["value"]
        conn = self.conn
        writes = any(m[0] in ("append", "w") for m in mops)
        try:
            conn.execute("BEGIN IMMEDIATE" if writes else "BEGIN DEFERRED")
        except sqlite3.OperationalError:
            return dict(op, type="fail", error="busy")  # never started
        done: List[List[Any]] = []
        try:
            for f, k, v in mops:
                if f == "append":
                    conn.execute(
                        "INSERT INTO la (k, pos, v) VALUES (?, "
                        "1 + COALESCE((SELECT MAX(pos) FROM la WHERE k=?),"
                        " 0), ?)", (k, k, v))
                    done.append([f, k, v])
                elif f == "r" and self.txn_kind == "list-append":
                    rows = conn.execute(
                        "SELECT v FROM la WHERE k=? ORDER BY pos",
                        (k,)).fetchall()
                    done.append([f, k, [r[0] for r in rows]])
                elif f == "r":  # rw-register read
                    row = conn.execute("SELECT v FROM kv WHERE k=?",
                                       (k,)).fetchone()
                    done.append([f, k, row[0] if row else None])
                elif f == "w":
                    conn.execute(
                        "INSERT INTO kv (k, v) VALUES (?, ?) "
                        "ON CONFLICT(k) DO UPDATE SET v=excluded.v", (k, v))
                    done.append([f, k, v])
                else:
                    raise ValueError(f"unknown mop {f!r}")
        except sqlite3.Error as e:
            # mid-txn failure (busy, integrity, …): nothing committed —
            # clean abort so the reused connection is left outside a txn
            try:
                conn.execute("ROLLBACK")
            except sqlite3.Error:
                pass
            return dict(op, type="fail", error=str(e))
        except BaseException:
            # non-SQLite error (e.g. unknown mop): the txn is still open
            # on the reused connection — roll back before propagating or
            # every later BEGIN fails with "within a transaction"
            try:
                conn.execute("ROLLBACK")
            except sqlite3.Error:
                pass
            raise
        try:
            conn.execute("COMMIT")
        except sqlite3.OperationalError as e:
            # COMMIT itself failed: SQLite leaves the txn open on BUSY —
            # roll back and report :fail only if rollback succeeds;
            # anything murkier is indeterminate
            try:
                conn.execute("ROLLBACK")
                return dict(op, type="fail", error=f"commit-busy: {e}")
            except sqlite3.OperationalError:
                return dict(op, type="info", error=f"commit: {e}")
        return dict(op, type="ok", value=done)

    def close(self, test):
        if self.conn is not None:
            self.conn.close()
            self.conn = None


def _make_test(opts: Dict[str, Any], name: str, wl: Dict[str, Any],
               txn_kind: str) -> Dict[str, Any]:
    from jepsen_tpu.generator import core as g

    database = SqliteDB()
    test = dict(opts)
    if test.get("remote") is None:
        from jepsen_tpu.control.local import LoopbackRemote

        # a real remote so the full spine (OS/DB setup, teardown, log
        # download) engages — the "nodes" are local for SQLite
        test["remote"] = LoopbackRemote()
    test.update({
        "name": name,
        "nodes": opts.get("nodes") or ["local"],
        "db": database,
        "client": SqliteClient(database, txn_kind=txn_kind),
        "generator": g.clients(wl["generator"]),
        "checker": wl["checker"],
    })
    return test


def append_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    """List-append over SQLite (the elle flagship on a real DB)."""
    from jepsen_tpu.workloads import append

    return _make_test(opts, "sqlite-append", append.workload(),
                      "list-append")


def wr_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    """rw-register over SQLite."""
    from jepsen_tpu.workloads import wr

    return _make_test(opts, "sqlite-wr", wr.workload(), "rw-register")


if __name__ == "__main__":
    from jepsen_tpu import cli

    cli.main(cli.test_all_cmd({"append": append_test, "wr": wr_test},
                              prog="python -m jepsen_tpu.dbs.sqlite"))
