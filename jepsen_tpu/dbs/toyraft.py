"""Toy Raft: an in-process replicated list-append store with real
membership, leader election, and partition sensitivity.

The second per-DB suite (reference monorepo pattern: each database gets a
suite wiring DB + Client + workloads + nemeses; SURVEY.md §2.6).  SQLite
exercised the single-node ACID path; this engine exercises the parts
sqlite cannot: the `Primary` facet, the membership nemesis's staged
view/resolution machinery, and partition nemeses whose grudges must
actually change quorum outcomes.

Protocol (deliberately small, but honest about the safety-relevant
parts of Raft):
- **Election**: on demand.  A node can lead iff it is alive, in the
  current config, and can round-trip to a majority of the config; among
  the eligible, the vote rule applies — its (last-term, last-index) must
  be >= that of every node in some reachable majority.  New leader gets
  a fresh term.
- **Replication**: the leader ships its full log to reachable members;
  a follower accepts iff the leader's term >= its own (full-log replace
  — log matching is trivial, and the vote rule keeps committed prefixes
  safe).  An entry commits when a majority of the config holds it;
  committed entries apply in order to the key -> list state machine.
- **Transactions**: every client txn (even read-only) is ONE log entry;
  reads are evaluated at apply time on the leader, so a committed txn is
  atomic and linearizable.  A txn that reaches some followers but not a
  majority completes **info** — it genuinely may commit after a heal.
- **Membership**: a config-change entry; commits under a majority of
  the UNION of old and new configs (conservative joint consensus).
  Removed nodes stop counting for quorum and stop receiving entries.
- **Faults**: `ToyRaftNet` implements the standard `Net` protocol over a
  directed blocked-links set (the partitioner nemesis drives it
  unchanged); `ToyRaftDB` implements `Process` kill/start (volatile
  state lost, log+term durable) and `Primary`.

A `stale_reads=True` mode answers read-only txns from the local node's
applied state without a quorum — a real consistency bug the elle
checker must catch under partitions (used by the test suite to prove
end-to-end bug-finding, the reference's "known-bug" suite pattern).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu import db as db_proto
from jepsen_tpu.client import Client
from jepsen_tpu.net import Net
from jepsen_tpu.nemesis.membership import MembershipState


class _Entry:
    __slots__ = ("term", "kind", "txn", "members", "eid")

    def __init__(self, term: int, kind: str, txn=None, members=None,
                 eid: int = -1):
        self.term = term
        self.kind = kind          # "txn" | "config"
        self.txn = txn            # list of mops for kind="txn"
        self.members = members    # list of nodes for kind="config"
        self.eid = eid            # unique entry id (result lookup)


class _Node:
    def __init__(self, name: str, members: Sequence[str]):
        self.name = name
        self.alive = True
        # durable
        self.term = 0
        self.log: List[_Entry] = [_Entry(0, "config",
                                         members=list(members), eid=0)]
        # volatile (rebuilt from log)
        self.commit_index = 0
        self.applied_index = -1
        self.state: Dict[Any, list] = {}
        self.members: List[str] = list(members)

    def last(self) -> Tuple[int, int]:
        return (self.log[-1].term, len(self.log) - 1)

    def rebuild(self):
        """Reapply the committed prefix after a restart."""
        self.state = {}
        self.members = list(self.log[0].members)
        self.applied_index = -1
        for i in range(self.commit_index + 1):
            self._apply(i, results=None)

    def _apply(self, i: int, results: Optional[dict]):
        e = self.log[i]
        if e.kind == "config":
            self.members = list(e.members)
        else:
            out = []
            for f, k, v in e.txn:
                if f == "append":
                    self.state.setdefault(k, []).append(v)
                    out.append([f, k, v])
                else:
                    out.append([f, k, list(self.state.get(k, []))])
            if results is not None:
                results[e.eid] = out
        self.applied_index = i


class ToyRaftCluster:
    """The cluster: nodes + directed blocked links + the raft rules."""

    def __init__(self, nodes: Sequence[str], stale_reads: bool = False):
        self.lock = threading.RLock()
        self.nodes: Dict[str, _Node] = {n: _Node(n, nodes) for n in nodes}
        self.blocked: Set[Tuple[str, str]] = set()  # (src, dst)
        self.leader: Optional[str] = None
        self.stale_reads = stale_reads
        self.next_eid = 1
        self.results: Dict[int, list] = {}  # eid -> read results at apply

    # ---- connectivity ----------------------------------------------------
    def _can_rt(self, a: str, b: str) -> bool:
        """Round trip a->b->a with both ends alive."""
        if a == b:
            return self.nodes[a].alive
        return (self.nodes[a].alive and self.nodes[b].alive and
                (a, b) not in self.blocked and (b, a) not in self.blocked)

    def _majority_reachable(self, a: str, config: Sequence[str]
                            ) -> Optional[List[str]]:
        reach = [n for n in config if self._can_rt(a, n)]
        return reach if len(reach) > len(config) // 2 else None

    # ---- election --------------------------------------------------------
    def _config_of(self, n: "_Node") -> List[str]:
        return n.members

    def ensure_leader(self) -> Optional[str]:
        """Return a usable leader, electing one if needed."""
        with self.lock:
            if self.leader is not None:
                ld = self.nodes[self.leader]
                cfg = self._config_of(ld)
                if ld.alive and self.leader in cfg and \
                        self._majority_reachable(self.leader, cfg):
                    return self.leader
                self.leader = None
            # election: deterministic order for reproducibility
            for name in sorted(self.nodes):
                cand = self.nodes[name]
                if not cand.alive:
                    continue
                cfg = self._config_of(cand)
                if name not in cfg:
                    continue
                voters = self._majority_reachable(name, cfg)
                if voters is None:
                    continue
                # vote rule: candidate log must be >= every voter's
                if any(self.nodes[v].last() > cand.last() for v in voters):
                    continue
                cand.term = max(self.nodes[v].term for v in voters) + 1
                self.leader = name
                self._replicate(name)  # assert leadership / sync logs
                return name
            return None

    # ---- replication -----------------------------------------------------
    def _replicate(self, leader: str) -> int:
        """Ship the leader's log to reachable members; recompute commit.
        Returns the count of members holding the leader's full log."""
        ld = self.nodes[leader]
        cfg = self._config_of(ld)
        # conservative joint consensus: an uncommitted config entry must
        # be acked by a majority of old AND new configs
        union_cfg = set(cfg)
        for e in ld.log[ld.commit_index + 1:]:
            if e.kind == "config":
                union_cfg |= set(e.members)
        holders = []
        for n in sorted(union_cfg):
            if n == leader:
                holders.append(n)
                continue
            if n not in self.nodes or not self._can_rt(leader, n):
                continue
            fl = self.nodes[n]
            if fl.term > ld.term:
                continue  # stale leader: cannot replicate here
            new_log = list(ld.log)
            prefix_ok = len(new_log) > fl.applied_index and all(
                new_log[i].eid == fl.log[i].eid
                for i in range(fl.applied_index + 1))
            fl.term = ld.term
            fl.log = new_log
            fl.commit_index = min(fl.commit_index, len(new_log) - 1)
            if not prefix_ok:
                fl.rebuild()  # applied prefix diverged: replay the log
            holders.append(n)
        # commit: majority of current config (and of the union when a
        # config entry is in flight) hold the full log
        need = {frozenset(cfg)}
        if union_cfg != set(cfg):
            need.add(frozenset(union_cfg))
        committed = all(
            sum(1 for n in grp if n in holders) > len(grp) // 2
            for grp in need)
        if committed:
            new_commit = len(ld.log) - 1
            if new_commit > ld.commit_index:
                for i in range(ld.commit_index + 1, new_commit + 1):
                    if ld.applied_index < i:
                        ld._apply(i, self.results)
                ld.commit_index = new_commit
                for n in holders:
                    if n != leader:
                        fl = self.nodes[n]
                        for i in range(fl.commit_index + 1, new_commit + 1):
                            if fl.applied_index < i:
                                fl._apply(i, None)
                        fl.commit_index = new_commit
        return len(holders)

    # ---- client surface --------------------------------------------------
    def submit_txn(self, txn: List[list]) -> Tuple[str, Any]:
        """Returns (status, payload): ("ok", results) | ("fail", why) |
        ("info", why)."""
        with self.lock:
            leader = self.ensure_leader()
            if leader is None:
                return "fail", "no-quorum"  # nothing entered any log
            ld = self.nodes[leader]
            eid = self.next_eid
            self.next_eid += 1
            ld.log.append(_Entry(ld.term, "txn", txn=txn, eid=eid))
            self._replicate(leader)
            if eid in self.results:
                return "ok", self.results.pop(eid)
            # entered ≥1 log but did not commit: genuinely indeterminate
            return "info", "no-commit-quorum"

    def read_local(self, node: str, txn: List[list]
                   ) -> Tuple[str, Any]:
        """The stale_reads bug: serve reads from local applied state."""
        with self.lock:
            nd = self.nodes[node]
            if not nd.alive:
                return "fail", "down"
            out = [[f, k, list(nd.state.get(k, []))] for f, k, _ in txn]
            return "ok", out

    # ---- membership surface ---------------------------------------------
    def submit_config(self, members: List[str]) -> Tuple[str, Any]:
        with self.lock:
            leader = self.ensure_leader()
            if leader is None:
                return "fail", "no-quorum"
            ld = self.nodes[leader]
            ld.log.append(_Entry(ld.term, "config", members=list(members),
                                 eid=self.next_eid))
            self.next_eid += 1
            self._replicate(leader)
            ok = ld.commit_index == len(ld.log) - 1
            return ("ok", members) if ok else ("info", "no-commit-quorum")

    def committed_members(self, node: str) -> Optional[List[str]]:
        with self.lock:
            nd = self.nodes[node]
            if not nd.alive:
                return None
            return list(nd.members)

    # ---- fault surface ---------------------------------------------------
    def kill(self, node: str):
        with self.lock:
            self.nodes[node].alive = False
            if self.leader == node:
                self.leader = None

    def start(self, node: str):
        with self.lock:
            nd = self.nodes[node]
            if not nd.alive:
                nd.alive = True
                nd.rebuild()

    def block(self, src: str, dst: str):
        with self.lock:
            self.blocked.add((src, dst))
            self.leader = None  # force re-validation of quorum

    def heal(self):
        with self.lock:
            self.blocked.clear()


class ToyRaftNet(Net):
    """Standard Net protocol over the cluster's blocked-links set, so the
    stock partitioner nemesis (grudges via drop_all) works unchanged.
    Accepts the DB (cluster resolved lazily — it exists after db.setup,
    and nemesis setup runs after DB setup in the core spine) or a
    cluster directly."""

    def __init__(self, target):
        self._target = target

    @property
    def cluster(self) -> ToyRaftCluster:
        c = getattr(self._target, "cluster", self._target)
        if c is None:
            raise RuntimeError("ToyRaftNet used before db.setup")
        return c

    def drop_(self, test, src, dst):
        self.cluster.block(src, dst)

    def drop_all(self, test, grudge: Dict[str, Sequence[str]]):
        for dst, srcs in grudge.items():
            for src in srcs:
                self.cluster.block(src, dst)

    def heal(self, test):
        self.cluster.heal()

    def slow(self, test, **kw):
        pass  # no timing model in the synchronous toy

    def flaky(self, test, **kw):
        pass

    def fast(self, test):
        pass

    def shape(self, test, behaviors):
        pass


class ToyRaftDB(db_proto.DB, db_proto.Primary, db_proto.Process):
    """DB facets over the cluster (Primary + Process kill/start)."""

    def __init__(self, stale_reads: bool = False):
        self.stale_reads = stale_reads
        self.cluster: Optional[ToyRaftCluster] = None
        self._setup_lock = threading.Lock()

    def setup(self, test, node):
        # one shared in-process cluster; created on the first node's setup
        # (on_nodes may fan setup out concurrently)
        with self._setup_lock:
            if self.cluster is None:
                self.cluster = ToyRaftCluster(test["nodes"],
                                              stale_reads=self.stale_reads)

    def teardown(self, test, node):
        pass

    def primaries(self, test):
        if self.cluster is None:
            return []
        with self.cluster.lock:
            ld = self.cluster.ensure_leader()
        return [ld] if ld else []

    def start(self, test, node):
        self.cluster.start(node)

    def kill(self, test, node):
        self.cluster.kill(node)


class ToyRaftClient(Client):
    """Client bound to one node; txns go through the raft log."""

    def __init__(self, database: ToyRaftDB):
        self.database = database
        self.node: Optional[str] = None

    def open(self, test, node):
        c = ToyRaftClient(self.database)
        c.node = node
        return c

    def invoke(self, test, op):
        cluster = self.database.cluster
        txn = op["value"]
        read_only = all(f == "r" for f, _, _ in txn)
        if self.database.stale_reads and read_only:
            status, payload = cluster.read_local(self.node, txn)
        else:
            status, payload = cluster.submit_txn(txn)
        if status == "ok":
            return dict(op, type="ok", value=payload)
        if status == "fail":
            return dict(op, type="fail", error=payload)
        return dict(op, type="info", error=payload)


class ToyRaftMembers(MembershipState):
    """Staged membership protocol over committed config views."""

    def __init__(self, database: ToyRaftDB, min_size: int = 3):
        self.database = database
        self.min_size = min_size

    # views -----------------------------------------------------------------
    def node_view(self, test, node):
        return self.database.cluster.committed_members(node)

    def merge_views(self, test, views):
        # the longest-log node wins in real systems; committed configs
        # only differ by lag, so take the most common non-None view
        best, best_n = None, -1
        counts: Dict[tuple, int] = {}
        for v in views:
            if v is None:
                continue
            key = tuple(v)
            counts[key] = counts.get(key, 0) + 1
            if counts[key] > best_n:
                best, best_n = v, counts[key]
        return best

    # ops --------------------------------------------------------------------
    def possible_ops(self, test, view):
        if not view:
            return []
        ops = []
        all_nodes = list(test["nodes"])
        absent = [n for n in all_nodes if n not in view]
        if absent:
            ops.append({"type": "invoke", "f": "join-node",
                        "value": absent[0]})
        if len(view) > self.min_size:
            ops.append({"type": "invoke", "f": "leave-node",
                        "value": sorted(view)[-1]})
        return ops

    def apply_op(self, test, op):
        from jepsen_tpu.nemesis.membership import merged_view

        cluster = self.database.cluster
        view = merged_view(self, test)
        if not view:
            return {"status": "fail", "members": None}
        if op["f"] == "leave-node":
            members = [n for n in view if n != op["value"]]
        else:
            members = sorted(set(view) | {op["value"]})
        status, payload = cluster.submit_config(members)
        return {"status": status, "members": members}

    def resolve_op(self, test, op, result, view):
        if view is None:
            return False
        if op["f"] == "leave-node":
            return op["value"] not in view
        return op["value"] in view


def append_test(opts: Dict[str, Any], *, stale_reads: bool = False
                ) -> Dict[str, Any]:
    """A list-append test map over the toy raft (mirror of
    `dbs/sqlite.append_test`)."""
    from jepsen_tpu.generator import core as g
    from jepsen_tpu.workloads import append as append_wl

    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    database = ToyRaftDB(stale_reads=stale_reads)
    wl = append_wl.workload(
        consistency_models=opts.get("consistency-models",
                                    ("strict-serializable",)))
    test = dict(opts)
    if test.get("remote") is None:
        from jepsen_tpu.control.sim import SimRemote

        test["remote"] = SimRemote()
    test.update({
        "name": opts.get("name", "toyraft-append"),
        "nodes": nodes,
        "db": database,
        "net": ToyRaftNet(database),
        "client": ToyRaftClient(database),
        "generator": g.clients(wl["generator"]),
        "checker": wl["checker"],
    })
    return test
