"""Per-database test suites.

The reference monorepo carries per-DB suites at its top level (etcd,
zookeeper, … — SURVEY.md §2.6 "Per-DB suites"): each wires a DB's
setup/client over the shared workloads.  This package holds ours.
`sqlite` is the suite that runs anywhere (stdlib driver, real ACID
engine, real isolation knobs); suites for networked DBs follow the same
shape with `control`-based DB setup.
"""
