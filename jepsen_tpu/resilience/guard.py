"""The device-call guard: retry transients, degrade to host, stay observable.

One entry point, :func:`device_call`, wraps every device pipeline seam
(elle infer, cycle sweeps, the knossos device WGL, the fused rw check):

1. polls the cooperative :class:`~.policy.Deadline` before each attempt;
2. consults the active :class:`~.faults.FaultPlan` (chaos mode / test
   harness) — the plan may raise a synthetic device fault here;
3. retries transient JAX/XLA failures per :class:`~.policy.RetryPolicy`
   with seeded backoff;
4. re-raises once the policy is exhausted (or the failure is
   non-transient) so the caller can degrade to its host oracle via
   :func:`with_fallback`, stamping ``"degraded": "host-fallback"``.

Every retry/fallback increments a telemetry counter and annotates the
innermost open span, so a degraded run is diagnosable straight from
``telemetry.json``.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from jepsen_tpu.resilience import faults as faults_mod
from jepsen_tpu.resilience.policy import (
    DEFAULT_POLICY,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
)

logger = logging.getLogger("jepsen.resilience")

__all__ = ["device_call", "with_fallback", "degrade_to_host",
           "env_anomaly", "DEGRADED_HOST", "NO_PLAN",
           "compile_cache_stats", "reset_compile_cache_stats"]

DEGRADED_HOST = "host-fallback"

#: sentinel for "definitely no fault plan": a hot loop that resolved the
#: plan ONCE (and found none) passes this so device_call skips the
#: per-call plan_for/env lookup entirely — plan=None means "resolve"
NO_PLAN = object()


def _registry():
    from jepsen_tpu import telemetry

    return telemetry.registry()


def _stream_event(ev: str, **fields: Any) -> None:
    from jepsen_tpu import telemetry

    telemetry.stream_event(ev, **fields)


def env_anomaly(site: str, kind: str = "anomaly", **fields: Any) -> None:
    """Record an ENVIRONMENT anomaly — a backend-init hang survived by
    retrying, a flapping tunnel, a degraded accelerator — as a
    structured resilience signal instead of a free-text field (ISSUE 6
    satellite: bench r05 buried a 544 s backend-init hang in a prose
    string).  Bumps the ``resilience-env-anomalies`` counter (visible
    on ``/metrics`` and in telemetry snapshots) and streams an
    ``env-anomaly`` event (visible to ``cli tail`` / ``/live`` and
    counted by ``replay()``).  Never raises."""
    try:
        _registry().counter("resilience-env-anomalies", site=site,
                            kind=kind).inc()
        _stream_event("env-anomaly", site=site, kind=kind, **fields)
    except Exception:  # noqa: BLE001 — observability must not fail work
        logger.debug("env_anomaly(%s) failed", site, exc_info=True)


def _annotate(**attrs: Any) -> None:
    from jepsen_tpu import telemetry

    sp = telemetry.current()
    if sp is not None:
        sp.set_attr(**attrs)


# ---------------------------------------------------------------------------
# Compile-cost observability (ISSUE 14 satellite — ROADMAP item 2
# groundwork).  jax.jit recompiles per argument-shape class; the first
# call of a (site, shape-vocabulary) pair therefore pays compile +
# execute while repeats pay execute only.  Tracking first sightings
# process-wide gives the AOT-cache PR its measured baseline: how much
# wall time is compile (`compile_s` span attrs, warehouse-queryable),
# how many distinct executables the process accumulated
# (`jit-cache-entries`), how often a new shape missed
# (`compile-cache-miss`).
# ---------------------------------------------------------------------------

_compile_lock = threading.Lock()
_seen_shapes: set = set()
_compile_misses = 0


def _shape_key(args: tuple, kw: dict) -> Tuple:
    """The call's shape-class key: (shape, dtype) of every array-like
    leaf one or two levels down — the same facts jax.jit keys its
    executable cache on (weak types and static args aside, close
    enough for attribution)."""
    parts = []

    def add(v: Any, depth: int) -> None:
        shape = getattr(v, "shape", None)
        if shape is not None:
            parts.append((str(tuple(shape)),
                          str(getattr(v, "dtype", ""))))
        elif depth < 2 and isinstance(v, (list, tuple)):
            for x in v[:8]:
                add(x, depth + 1)

    for a in args:
        add(a, 0)
    for v in kw.values():
        add(v, 0)
    return tuple(parts)


def compile_cache_stats() -> Dict[str, int]:
    """Process-wide jit shape-cache stats: distinct (site, shape)
    classes seen (= executables the process holds warm) and total
    first-sighting misses."""
    with _compile_lock:
        return {"entries": len(_seen_shapes),
                "misses": _compile_misses}


def reset_compile_cache_stats() -> None:
    """Tests only — the live set mirrors jax's own cache, which is not
    reset between runs either."""
    global _compile_misses
    with _compile_lock:
        _seen_shapes.clear()
        _compile_misses = 0


def _peek_shape(site: str, args: tuple, kw: dict) -> Optional[Tuple]:
    """This call's shape-class key — WITHOUT recording it.  The commit
    happens only after the attempt SUCCEEDS (:func:`_commit_shape`): a
    transient failure before compile completed must leave the shape
    unseen, so the retry that actually pays the compile is the one
    booked as ``compile_s``."""
    try:
        return (site,) + _shape_key(args, kw)
    except Exception:  # noqa: BLE001 — exotic args must not fail a call
        return None


def _commit_shape(key: Optional[Tuple]) -> bool:
    """Record a successfully-executed shape class; True if this commit
    was its first."""
    global _compile_misses
    if key is None:
        return False
    with _compile_lock:
        if key in _seen_shapes:
            return False
        _seen_shapes.add(key)
        _compile_misses += 1
        return True


def _shape_label(shape_key: Optional[Tuple]) -> str:
    """A compact human/SQL-stable label for a call's shape class —
    the ``shape`` column of the warehouse ``span_profile`` table.
    ``shape_key`` is ``(site, (shape, dtype), ...)``; scalars-only
    calls label as ``scalar``."""
    if not shape_key or len(shape_key) < 2:
        return "scalar"
    return "+".join(f"{s}:{d}" if d else s for s, d in shape_key[1:])


def _stamp_device_time(site: str, fn: Callable, args: tuple,
                       kw: dict) -> Any:
    """Run one device attempt, stamping its block-until-ready wall time
    onto the enclosing telemetry span as ``device_time_ns`` (summed
    across calls under that span) — the device-time attribution that
    puts host spans and XLA work on one timeline.  Only reached when
    telemetry is enabled; device failures surfacing at the sync point
    propagate to the caller's retry/fallback classifier."""
    from jepsen_tpu import telemetry

    shape_key = _peek_shape(site, args, kw)
    t0 = time.perf_counter_ns()
    out = fn(*args, **kw)
    # dispatch wall: tracing + executable lookup + async enqueue — what
    # the call cost BEFORE the sync point forced device completion
    disp = time.perf_counter_ns() - t0
    jx = sys.modules.get("jax")
    if jx is not None:
        try:  # force completion so the delta covers the device work
            jx.block_until_ready(out)
        except (TypeError, AttributeError):  # non-blockable results
            pass
        # anything else (XlaRuntimeError, RESOURCE_EXHAUSTED, ...) is a
        # REAL device failure surfacing at the sync point — let it reach
        # device_call's retry/fallback classifier instead of returning
        # the poisoned value as success
    dt = time.perf_counter_ns() - t0
    # commit only now: the attempt survived its sync point, so THIS is
    # the attempt that compiled (a transient failure above leaves the
    # shape unseen for the retry to claim)
    first = _commit_shape(shape_key)
    sp = telemetry.current()
    if sp is not None and sp.attrs is not None:
        try:
            sp.attrs["device_time_ns"] = \
                int(sp.attrs.get("device_time_ns", 0)) + dt
            # compile vs execute attribution (ISSUE 14 satellite): a
            # first-call-per-shape attempt's wall is compile-dominated
            # — stamped separately so "where did this cell's 40 s go"
            # can answer "32 s of it was XLA compiles"
            k = "compile_s" if first else "execute_s"
            sp.attrs[k] = float(sp.attrs.get(k, 0.0)) + dt / 1e9
            sp.attrs["device_dispatch_s"] = float(
                sp.attrs.get("device_dispatch_s", 0.0)) + disp / 1e9
            # per-(site, shape-class) profile (ISSUE 16 tentpole a):
            # accumulated on the span, exploded into the warehouse's
            # span_profile table at ingest — the `cli obs profile`
            # treemap's raw material
            prof = sp.attrs.get("profile")
            if not isinstance(prof, dict):
                prof = sp.attrs["profile"] = {}
            cell = prof.setdefault(
                f"{site}|{_shape_label(shape_key)}",
                {"calls": 0, "compile_s": 0.0, "execute_s": 0.0,
                 "device_dispatch_s": 0.0})
            cell["calls"] += 1
            cell[k] = float(cell.get(k, 0.0)) + dt / 1e9
            cell["device_dispatch_s"] = float(
                cell.get("device_dispatch_s", 0.0)) + disp / 1e9
        except Exception:  # noqa: BLE001 — noop-span attrs are shared
            pass
    reg = telemetry.registry()
    reg.counter("device-time-ns", site=site).inc(dt)
    if first:
        reg.counter("compile-cache-miss", site=site).inc()
    with _compile_lock:
        n = len(_seen_shapes)
    reg.gauge("jit-cache-entries").set(n)
    return out


def device_call(site: str, fn: Callable, *args: Any,
                policy: Optional[RetryPolicy] = None,
                deadline: Optional[Deadline] = None,
                plan: Optional[faults_mod.FaultPlan] = None,
                test: Optional[dict] = None,
                **kw: Any) -> Any:
    """Run a device entry point under the resilience policy.

    `site` names the seam for fault targeting and telemetry labels
    (e.g. ``"elle.infer"``).  `plan` defaults to the run's resolved
    plan (`faults.plan_for(test)` — explicit install > test map >
    JEPSEN_FAULTS); pass ``plan=...`` to pin one.  Raises the last
    error when retries are exhausted or the failure is non-transient;
    :class:`DeadlineExceeded` always propagates immediately.
    """
    policy = policy or DEFAULT_POLICY
    if plan is NO_PLAN:
        plan = None
    elif plan is None:
        plan = faults_mod.plan_for(test)
    delays = policy.delays()
    attempt = 0
    while True:
        if deadline is not None:
            deadline.check(site)
        attempt += 1
        try:
            if plan is not None:
                plan.fire(site)
            from jepsen_tpu import telemetry

            if telemetry.enabled():
                return _stamp_device_time(site, fn, args, kw)
            return fn(*args, **kw)
        except DeadlineExceeded:
            raise
        except Exception as e:  # noqa: BLE001 — classified below
            if not policy.classify(e):
                raise
            delay = next(delays, None)
            if delay is None:  # attempts exhausted: the original error
                _annotate(retries=attempt - 1, retry_exhausted=True)
                raise
            _registry().counter("resilience-retries", site=site,
                                kind=type(e).__name__).inc()
            _stream_event("retry", site=site, attempt=attempt,
                          kind=type(e).__name__)
            _annotate(retries=attempt)
            logger.warning("transient device failure at %s (attempt "
                           "%d/%d), retrying in %.3fs: %s", site, attempt,
                           policy.max_attempts, delay, e)
            if deadline is not None:
                delay = deadline.bound_sleep(delay)
            if delay > 0:
                time.sleep(delay)


def degrade_to_host(site: str, host_fn: Callable[[], Any],
                    exc: BaseException, *,
                    deadline: Optional[Deadline] = None) -> Any:
    """The shared degradation tail every device->host fallback goes
    through: count the fallback, annotate the open span, poll the
    deadline (an expired budget must NOT be converted into a possibly
    much slower host run — expiry raises :class:`DeadlineExceeded`),
    run the host oracle, and stamp dict results with
    ``"degraded": "host-fallback"`` plus the device error."""
    _registry().counter("resilience-fallbacks", site=site).inc()
    _stream_event("fallback", site=site, error=type(exc).__name__)
    _annotate(degraded=DEGRADED_HOST, device_error=type(exc).__name__)
    logger.warning("persistent device failure at %s; degrading to "
                   "host oracle: %s", site, exc)
    if deadline is not None:
        deadline.check(site)
    res = host_fn()
    if isinstance(res, dict):
        res["degraded"] = DEGRADED_HOST
        res["device-error"] = f"{type(exc).__name__}: {exc}"
    return res


def with_fallback(site: str, device_fn: Callable[[], Any],
                  host_fn: Callable[[], Any], *,
                  policy: Optional[RetryPolicy] = None,
                  deadline: Optional[Deadline] = None,
                  plan: Optional[faults_mod.FaultPlan] = None,
                  test: Optional[dict] = None
                  ) -> Tuple[Any, Optional[str]]:
    """Run `device_fn` under :func:`device_call`; on persistent device
    failure run `host_fn` via :func:`degrade_to_host`.  Returns
    ``(result, degraded)`` where `degraded` is None on the device path
    and :data:`DEGRADED_HOST` after the oracle fallback (dict results
    also carry the stamp).  Only :class:`DeadlineExceeded` escapes."""
    try:
        return device_call(site, device_fn, policy=policy,
                           deadline=deadline, plan=plan, test=test), None
    except DeadlineExceeded:
        raise
    except Exception as e:  # noqa: BLE001 — any persistent device failure
        return degrade_to_host(site, host_fn, e,
                               deadline=deadline), DEGRADED_HOST
