"""Deterministic, seeded fault injection for the checking pipeline.

The framework's whole business is injecting faults into systems under
test; this module points the same weapon at our own device pipeline.  A
:class:`FaultPlan` wraps the device entry points (via
``guard.device_call``'s pre-call hook) and raises synthetic
OOM/XlaRuntimeError-shaped failures — or injects stalls — at chosen
call indices.  Seeded and deterministic: the same plan spec over the
same call sequence injects the same faults, so a chaos run that found a
bug replays exactly.

Doubles as:

- the test harness for the resilience layer (inject a persistent
  device fault, assert the checker degrades to the host oracle and
  still produces the fault-free verdict);
- a chaos mode for whole runs — enable per test map
  (``test["faults"] = {...spec...}``) or process-wide via the
  ``JEPSEN_FAULTS`` env var (``"seed=7,p=0.05,kinds=oom|xla"``).

Spec keys (dict or ``k=v,k=v`` env string):

    seed         int, default 0 — drives the probabilistic decisions
    p            float, default 0 — per-call fault probability
    kinds        iterable / "|"-joined — any of {"oom", "xla",
                 "device-lost", "stall"}; default ("oom", "xla")
    at           {call_index: kind} — explicit injections (exact runs)
    persistent   iterable of site names (or True for all sites) that
                 fault on EVERY call — the degradation-drill mode
    max_faults   int — stop injecting after this many faults
    stall_s      float, default 0.05 — stall duration
    sites        iterable — restrict injection to these site names
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

__all__ = ["FaultInjected", "FaultPlan", "parse_spec", "seeded_for",
           "plan_for", "use", "install", "clear", "active_plan",
           "KIND_MESSAGES"]

#: synthetic messages mimic the real jaxlib failure strings so the
#: transient classifier (policy.is_transient) exercises its production
#: match rules, not a test-only backdoor
KIND_MESSAGES = {
    "oom": ("RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "synthetic fault (injected)"),
    "xla": ("INTERNAL: Compilation failure: synthetic XLA compile flake "
            "(injected)"),
    "device-lost": "UNAVAILABLE: device lost (injected)",
    "stall": "stall",  # not raised; injected as a sleep
}

#: kinds a retry could clear; "device-lost" persists until re-dial, so
#: a plan can model both regimes
_TRANSIENT_KINDS = {"oom": True, "xla": True, "device-lost": False}


class FaultInjected(RuntimeError):
    """A synthetic device fault.  Carries its own transience verdict so
    the classifier needs no special-casing, plus the injection site and
    call index for attribution in logs/telemetry."""

    def __init__(self, kind: str, site: str, index: int,
                 transient: bool = True):
        super().__init__(f"{KIND_MESSAGES.get(kind, kind)} "
                         f"[site={site} call={index}]")
        self.kind = kind
        self.site = site
        self.index = index
        self.transient = transient


def _split(v: Union[str, Iterable[str], None]) -> Optional[List[str]]:
    if v is None:
        return None
    if isinstance(v, str):
        return [s for s in v.replace("|", ",").split(",") if s]
    return list(v)


def parse_spec(spec: Union[str, dict, None]) -> Optional[dict]:
    """Normalize a fault spec: env-string form to a dict; dicts pass
    through (copied).  Returns None for empty/falsy specs."""
    if not spec:
        return None
    if isinstance(spec, dict):
        return dict(spec)
    out: Dict[str, Any] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad JEPSEN_FAULTS entry {part!r} "
                             "(want key=value[,key=value...])")
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out or None


def seeded_for(spec: Union[str, dict, None], salt: int
               ) -> Optional[dict]:
    """Derive a spec whose seed mixes in `salt` — the campaign-level
    idiom (ISSUE 11): one ``"plan"`` template in a nemesis schedule
    yields a distinct-but-replayable FaultPlan per generation, and
    every host installing generation *g*'s plan injects identically.
    The mix is a plain XOR of the normalized template seed, so
    ``seeded_for(s, 0)`` keeps the template's own stream."""
    d = parse_spec(spec)
    if d is None:
        return None
    d["seed"] = int(d.get("seed", 0)) ^ int(salt)
    return d


class FaultPlan:
    """Deterministic schedule of synthetic device faults.

    Each guarded call asks :meth:`fire` with its site name; the plan
    keeps one global call counter (thread-safe) and decides from its
    seed/spec whether that call faults.  Decisions depend only on
    (seed, call index, site filters) — never on wall time — so two runs
    over the same call sequence inject identically.
    """

    def __init__(self, seed: int = 0, p: float = 0.0,
                 kinds: Iterable[str] = ("oom", "xla"),
                 at: Optional[Dict[int, str]] = None,
                 persistent: Union[bool, Iterable[str], None] = None,
                 max_faults: Optional[int] = None,
                 stall_s: float = 0.05,
                 sites: Optional[Iterable[str]] = None):
        self.seed = int(seed)
        self.p = float(p)
        self.kinds = tuple(_split(kinds) or ())
        for k in self.kinds:
            if k not in KIND_MESSAGES:
                raise ValueError(f"unknown fault kind {k!r} "
                                 f"(have {sorted(KIND_MESSAGES)})")
        self.at = {int(k): v for k, v in (at or {}).items()}
        if persistent is True or persistent in ("1", "true", "all"):
            self.persistent: Union[bool, frozenset] = True
        else:
            self.persistent = frozenset(_split(persistent) or ())
        self.max_faults = int(max_faults) if max_faults is not None else None
        self.stall_s = float(stall_s)
        self.sites = frozenset(_split(sites) or ()) or None
        self._lock = threading.Lock()
        self._n_calls = 0
        #: injection log: (call_index, site, kind) — determinism tests
        #: and chaos-sweep reports read this
        self.injected: List[Tuple[int, str, str]] = []

    @classmethod
    def from_spec(cls, spec: Union[str, dict, None]
                  ) -> Optional["FaultPlan"]:
        d = parse_spec(spec)
        if d is None:
            return None
        return cls(**d)

    # -- decision ----------------------------------------------------------

    def _decide(self, index: int, site: str,
                salt: int = 0) -> Optional[str]:
        """The pure decision function: which fault (if any) fires at
        this (index, site)?  Hash-seeded per call index so decisions
        are order-independent across sites with the same counter.
        `salt` separates index SPACES: caller-indexed streams
        (:meth:`fire_at`) draw from a different probabilistic sequence
        than the global counter, so e.g. interpreter worker 0's op k
        does not fault in lockstep with device call k."""
        if self.sites is not None and site not in self.sites:
            return None
        if self.persistent is True or \
                (self.persistent and site in self.persistent):
            return self.kinds[0] if self.kinds else "oom"
        if index in self.at:
            return self.at[index]
        if self.p > 0.0 and self.kinds:
            import random
            rng = random.Random((self.seed << 20) ^ index ^ salt)
            if rng.random() < self.p:
                return self.kinds[rng.randrange(len(self.kinds))]
        return None

    # -- the guard-facing hook ---------------------------------------------

    def fire(self, site: str) -> None:
        """Called by the guard before each device entry point: count the
        call and inject the planned fault (raise, or sleep for stalls).
        """
        with self._lock:
            index = self._n_calls
            self._n_calls += 1
        self._fire_decided(site, index, salt=0)

    #: rng-stream salt for caller-indexed decisions (fire_at): keeps
    #: the interpreter's per-worker streams independent of the global
    #: device-call counter's stream for the same seed
    _CALLER_SPACE_SALT = 0x5EED5A17

    def fire_at(self, site: str, index: int) -> None:
        """Like :meth:`fire`, but the decision index is supplied by the
        caller instead of the plan's global counter — the per-worker
        idiom (ISSUE 4 satellite): each interpreter worker derives its
        own index stream from (thread id, local op count), so
        injections are seeded-deterministic regardless of thread
        interleaving.  ``max_faults`` and the injection log stay
        plan-wide (lock-shared).  Probabilistic decisions draw from a
        salted stream so they don't correlate with the global
        counter's; explicit ``at`` indices are interpreted in the
        CALLER's index space — a plan mixing ``at`` with both guard
        and interpreter sites should use ``sites`` filters to
        disambiguate."""
        self._fire_decided(site, index, salt=self._CALLER_SPACE_SALT)

    def _fire_decided(self, site: str, index: int, salt: int) -> None:
        """Shared decide-log-execute tail of fire/fire_at: the
        max_faults gate and the injection-log append stay atomic under
        the plan lock; the execution (raise / stall) happens outside
        it."""
        with self._lock:
            if self.max_faults is not None and \
                    len(self.injected) >= self.max_faults:
                return
            kind = self._decide(index, site, salt=salt)
            if kind is None:
                return
            self.injected.append((index, site, kind))
        self._execute(kind, site, index)

    def targets_site(self, site: str) -> bool:
        """Does this plan EXPLICITLY name `site`?  Sites outside the
        device-call guard (the interpreter's client-side chaos seam)
        are strictly opt-in: a bare ``p=0.2`` checker-chaos plan must
        not silently start crashing client ops."""
        if self.sites is not None and site in self.sites:
            return True
        return isinstance(self.persistent, frozenset) and \
            site in self.persistent

    def _execute(self, kind: str, site: str, index: int) -> None:
        from jepsen_tpu import telemetry

        telemetry.registry().counter("resilience-faults-injected",
                                     site=site, kind=kind).inc()
        telemetry.stream_event("fault", site=site, kind=kind, index=index)
        if kind == "stall":
            import time
            time.sleep(self.stall_s)
            return
        raise FaultInjected(kind, site, index,
                            transient=_TRANSIENT_KINDS.get(kind, True))

    def __repr__(self) -> str:
        return (f"<FaultPlan seed={self.seed} p={self.p} "
                f"kinds={self.kinds} at={self.at} "
                f"persistent={self.persistent!r} "
                f"calls={self._n_calls} injected={len(self.injected)}>")


# ---------------------------------------------------------------------------
# Activation: explicit install > test map > JEPSEN_FAULTS env.
# ---------------------------------------------------------------------------

_active: Optional[FaultPlan] = None
_env_plan: Optional[FaultPlan] = None
_env_spec_seen: Optional[str] = None


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install a process-wide plan (None clears).  Returns the plan."""
    global _active
    _active = plan
    return plan


def clear() -> None:
    install(None)


class use:
    """Context manager: install a plan for a block, restoring after —
    the unit-test idiom (`with faults.use(plan): ...`)."""

    def __init__(self, plan: Optional[FaultPlan]):
        self.plan = plan
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> Optional[FaultPlan]:
        global _active
        self._prev = _active
        _active = self.plan
        return self.plan

    def __exit__(self, *exc) -> bool:
        global _active
        _active = self._prev
        return False


def active_plan() -> Optional[FaultPlan]:
    """The explicitly installed plan, else the JEPSEN_FAULTS env plan
    (parsed once per distinct spec value), else None."""
    if _active is not None:
        return _active
    global _env_plan, _env_spec_seen
    spec = os.environ.get("JEPSEN_FAULTS", "").strip()
    if not spec:
        return None
    if spec != _env_spec_seen:
        _env_spec_seen = spec
        _env_plan = FaultPlan.from_spec(spec)
    return _env_plan


def plan_for(test: Optional[dict]) -> Optional[FaultPlan]:
    """Resolve the fault plan for a run: the test map's ``"faults"``
    resilience spec (cached on the map so every checker in the run
    shares ONE call counter), else :func:`active_plan`.

    Note: `nemesis/combined.py` also reads ``opts["faults"]`` as a SET
    of package names ({"partition", "kill", ...}); a set/sequence there
    is the nemesis vocabulary, not a resilience spec — only dict/str
    specs (or a FaultPlan) select device-fault injection."""
    if test:
        spec = test.get("faults")
        if isinstance(spec, FaultPlan):
            return spec
        if isinstance(spec, (dict, str)) and spec:
            cached = test.get("faults-plan")
            if isinstance(cached, FaultPlan):
                return cached
            plan = FaultPlan.from_spec(spec)
            test["faults-plan"] = plan
            return plan
    return active_plan()
