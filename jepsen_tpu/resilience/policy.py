"""Retry policies and cooperative deadlines — the resilience primitives.

The checking pipeline's core promise (ROADMAP north star) is that a run
always terminates with an attributable verdict.  Two primitives make
that hold when faults hit the *checker* itself:

- :class:`RetryPolicy` — bounded retries with exponential backoff and
  *seeded* jitter (same seed -> same delay sequence, so faulted runs
  replay bit-identically) plus a transient-error classifier tuned for
  the JAX/XLA failure taxonomy (RESOURCE_EXHAUSTED, device lost,
  compile flakes).

- :class:`Deadline` — a cooperative wall-clock budget that long
  host-side loops poll (`expired()`/`check()`); expiry surfaces as
  :class:`DeadlineExceeded`, which `checkers.api.check_safe` converts
  into ``{"valid?": "unknown", "error": "deadline-exceeded"}`` instead
  of an unbounded hang.

No jax imports here: classification is string/type-name based so the
module stays importable (and testable) without a device runtime.
"""

from __future__ import annotations

import random
import socket
import time
from typing import Any, Callable, Dict, Iterator, Optional

__all__ = ["Deadline", "DeadlineExceeded", "RetryPolicy", "is_transient",
           "is_transient_http", "DEADLINE_ERROR", "deadline_result"]

DEADLINE_ERROR = "deadline-exceeded"


class DeadlineExceeded(Exception):
    """A cooperative checker deadline expired.  `check_safe` maps this
    to an "unknown" verdict; internal loops use it for early unwind."""

    def __init__(self, what: str = "", remaining: Optional[float] = None):
        super().__init__(what or DEADLINE_ERROR)
        self.what = what


class Deadline:
    """A wall-clock budget polled cooperatively by long-running loops.

    ``Deadline(5.0)`` expires 5 s from construction; ``Deadline(None)``
    never expires (every poll is cheap and False).  Monotonic-clock
    based, so shareable across threads; sharing ONE deadline object
    across a composed checker run is what makes the budget cover the
    whole analysis rather than restarting per sub-checker.
    """

    __slots__ = ("t_end",)

    def __init__(self, seconds: Optional[float] = None):
        self.t_end = (time.monotonic() + float(seconds)
                      if seconds is not None else None)

    @classmethod
    def resolve(cls, opts: Optional[dict], test: Optional[dict] = None
                ) -> Optional["Deadline"]:
        """The one rule for where a checker deadline comes from: an
        already-created ``opts["deadline"]`` (shared by composed
        checkers), else ``opts["time-limit"]`` (per-check opt), else
        the test map's ``"checker-time-limit"``.  None when unbounded.
        """
        opts = opts or {}
        dl = opts.get("deadline")
        if isinstance(dl, Deadline):
            return dl
        limit = opts.get("time-limit")
        if limit is None:
            limit = (test or {}).get("checker-time-limit")
        return cls(float(limit)) if limit is not None else None

    def remaining(self) -> Optional[float]:
        """Seconds left, clamped at 0; None when unbounded."""
        if self.t_end is None:
            return None
        return max(0.0, self.t_end - time.monotonic())

    def expired(self) -> bool:
        return self.t_end is not None and time.monotonic() >= self.t_end

    def check(self, what: str = "") -> None:
        """Raise :class:`DeadlineExceeded` if the budget is spent —
        the poll long loops drop into their iteration step."""
        if self.expired():
            _count_deadline(what)
            raise DeadlineExceeded(what)

    def bound_sleep(self, seconds: float) -> float:
        """Clamp a backoff sleep so it never overshoots the deadline."""
        rem = self.remaining()
        return seconds if rem is None else min(seconds, rem)

    def __repr__(self) -> str:
        r = self.remaining()
        return f"<Deadline {'unbounded' if r is None else f'{r:.3f}s left'}>"


def deadline_result(**partial: Any) -> Dict[str, Any]:
    """The canonical deadline verdict: unknown + deadline-exceeded, with
    whatever partial stats the interrupted checker already computed."""
    return {"valid?": "unknown", "error": DEADLINE_ERROR, **partial}


def _count_deadline(what: str) -> None:
    from jepsen_tpu import telemetry

    telemetry.registry().counter("resilience-deadline-expired",
                                 site=what or "unspecified").inc()
    telemetry.stream_event("deadline", site=what or "unspecified")


# ---------------------------------------------------------------------------
# Transient-error classification for JAX/XLA device failures.
# ---------------------------------------------------------------------------

#: exception type names that mark device-side failures (jaxlib does not
#: export a stable hierarchy; names are its de-facto ABI)
_DEVICE_ERROR_TYPES = frozenset({
    "XlaRuntimeError",
    "ResourceExhaustedError",
    "InternalError",
    "UnavailableError",
    "AbortedError",
    "FaultInjected",  # our own synthetic faults (faults.py)
})

#: message substrings that mark a *transient* device failure — worth a
#: bounded retry before degrading to the host oracle
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",       # device OOM: allocator pressure often clears
    "out of memory",
    "Out of memory",
    "device lost",              # preemption / tunnel blip
    "DEVICE_LOST",
    "UNAVAILABLE",              # remote-compile / PJRT service hiccup
    "ABORTED",
    "DATA_LOSS",
    "failed to compile",        # compile flakes (axon drops, PROFILE §-1d)
    "Compilation failure",
    "remote_compile",
    "Unexpected EOF",
)


def is_transient(exc: BaseException) -> bool:
    """Is this a transient JAX/XLA failure a retry could clear?

    Deliberately conservative: a Python-side bug (TypeError, bad shape
    assert) is never transient — retrying it would just burn the budget
    before the fallback; and :class:`DeadlineExceeded` is never
    transient (the budget IS the thing that expired)."""
    if isinstance(exc, DeadlineExceeded):
        return False
    transient = getattr(exc, "transient", None)
    if transient is not None:  # synthetic faults carry their own verdict
        return bool(transient)
    if type(exc).__name__ not in _DEVICE_ERROR_TYPES:
        return False
    msg = str(exc)
    return any(m in msg for m in _TRANSIENT_MARKERS)


#: HTTP statuses a control-plane client should retry: the server (or a
#: gateway in front of it) said "not now", not "never"
_TRANSIENT_HTTP = frozenset({502, 503, 504})


def is_transient_http(exc: BaseException) -> bool:
    """Transient classifier for control-plane HTTP clients (the fleet
    worker's coordinator calls, ISSUE 9): everything
    :func:`is_transient` accepts, plus connection-level failures and
    5xx overload/gateway responses.

    A coordinator restart window looks like ECONNREFUSED and a
    partition like a timeout — both must be ridden out with bounded
    backoff, while 4xx protocol errors are real bugs (bad cursor, bad
    body) and propagate immediately.  :class:`DeadlineExceeded` stays
    non-retryable via the :func:`is_transient` delegation order."""
    if isinstance(exc, DeadlineExceeded):
        return False
    if is_transient(exc):
        return True
    import urllib.error

    if isinstance(exc, urllib.error.HTTPError):  # before URLError: subclass
        return exc.code in _TRANSIENT_HTTP
    # URLError wraps the socket-level reason; raw socket errors appear
    # when the failure races the response read
    return isinstance(exc, (urllib.error.URLError, ConnectionError,
                            TimeoutError, socket.timeout, OSError))


class RetryPolicy:
    """Bounded retries with exponential backoff and seeded jitter.

    ``max_attempts`` counts total tries (1 = no retry).  Delay before
    retry i (0-based) is ``base_delay_s * multiplier**i`` capped at
    ``max_delay_s``, scaled by a jitter factor drawn uniformly from
    ``[1 - jitter, 1 + jitter]`` by a ``random.Random(seed)`` — the
    seed makes a faulted run's timing schedule reproducible, the same
    determinism contract as :class:`faults.FaultPlan`.

    ``classify(exc) -> bool`` decides retryability; default
    :func:`is_transient`.
    """

    __slots__ = ("max_attempts", "base_delay_s", "multiplier",
                 "max_delay_s", "jitter", "seed", "classify")

    def __init__(self, max_attempts: int = 3, *,
                 base_delay_s: float = 0.05, multiplier: float = 2.0,
                 max_delay_s: float = 2.0, jitter: float = 0.5,
                 seed: int = 0,
                 classify: Callable[[BaseException], bool] = is_transient):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.multiplier = multiplier
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.seed = seed
        self.classify = classify

    def delays(self) -> Iterator[float]:
        """The (max_attempts - 1) backoff delays, jitter included.  A
        fresh iterator restarts the seeded sequence — one per guarded
        call, so concurrent guarded calls don't interleave draws."""
        rng = random.Random(self.seed)
        for i in range(self.max_attempts - 1):
            d = min(self.base_delay_s * (self.multiplier ** i),
                    self.max_delay_s)
            yield max(0.0, d * (1.0 + self.jitter * rng.uniform(-1.0, 1.0)))


DEFAULT_POLICY = RetryPolicy()
