"""Resilience layer (ISSUE 2): the checking pipeline survives faults in
*itself* — transient XLA errors, device OOM, pathological histories —
and always terminates with an attributable verdict.

Three pieces, wired through the elle and knossos checking stacks:

- :mod:`~.policy` — :class:`RetryPolicy` (seeded backoff + JAX/XLA
  transient classifier) and the cooperative :class:`Deadline`
  (`check_safe` honors ``opts["time-limit"]`` / test
  ``"checker-time-limit"`` and converts expiry into
  ``{"valid?": "unknown", "error": "deadline-exceeded"}``);
- :mod:`~.faults` — the deterministic seeded :class:`FaultPlan`
  (chaos mode via test ``"faults"`` spec / ``JEPSEN_FAULTS``, and the
  resilience layer's own test harness);
- :mod:`~.guard` — :func:`device_call` / :func:`with_fallback`, the
  seam wrapper that retries transients and degrades to the host oracle
  with a ``"degraded": "host-fallback"`` stamp.

See ``docs/RESILIENCE.md``.
"""

from jepsen_tpu.resilience.faults import (
    FaultInjected,
    FaultPlan,
    active_plan,
    parse_spec,
    plan_for,
    use,
)
from jepsen_tpu.resilience.guard import (
    DEGRADED_HOST,
    NO_PLAN,
    degrade_to_host,
    device_call,
    env_anomaly,
    with_fallback,
)
from jepsen_tpu.resilience.policy import (
    DEADLINE_ERROR,
    DEFAULT_POLICY,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    deadline_result,
    is_transient,
    is_transient_http,
)

__all__ = [
    "Deadline", "DeadlineExceeded", "RetryPolicy", "is_transient",
    "is_transient_http", "DEADLINE_ERROR", "DEFAULT_POLICY",
    "deadline_result",
    "FaultPlan", "FaultInjected", "parse_spec", "plan_for", "use",
    "active_plan",
    "device_call", "with_fallback", "degrade_to_host", "env_anomaly",
    "DEGRADED_HOST", "NO_PLAN",
]
