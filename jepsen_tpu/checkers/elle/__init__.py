"""Elle-style transactional isolation checking (SURVEY.md §2.3).

`oracle` is the exact host reference implementation (clarity over speed);
`device` is the TPU pipeline (edge inference + blocked-scan cycle kernel)
differentially tested against it.
"""
