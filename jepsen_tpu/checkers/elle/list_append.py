"""TPU list-append checker — the flagship device pipeline.

`check()` here is API-compatible with `jepsen_tpu.checkers.elle.oracle.check`
(the exact host reference) and with the capability surface of the
reference's `elle.list-append/check` (SURVEY.md §2.3): same anomaly
taxonomy, same consistency-model verdicts.

Split of labor (mirrors the reference's SCC-on-graph / search-in-SCC split,
relocated to TPU):
  device — SoA packing -> `device_infer.infer` (version orders, non-cycle
           anomaly scans, ww/wr/rw/process/realtime edges) -> per-projection
           cycle detection via the rank-sweep kernel (`ops.cycle_sweep`).
  host   — only when a projection reports a cycle: extract the small
           offending region around witness backward edges (numpy frontier
           BFS) and classify/render the exact cycle per anomaly spec with
           the shared rel-constrained search (`graph.find_cycle`).

Fast path: a valid history never leaves the device except for O(1) flags.

If the sweep fails to converge (adversarial alternation depth; see
ops/cycle_sweep.py) the checker falls back to the host oracle — verdicts
are never approximated.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from jepsen_tpu import resilience, telemetry
from jepsen_tpu.checkers.elle import consistency, coverage, oracle
from jepsen_tpu.checkers.elle.device_infer import PaddedLA, infer, pad_packed
from jepsen_tpu.checkers.elle.graph import (
    REL_NAMES,
    REL_PROCESS,
    REL_REALTIME,
    REL_RW,
    REL_WR,
    REL_WW,
    CycleSpec,
    EdgeList,
    find_cycle,
)
from jepsen_tpu.checkers.elle.specs import CYCLE_ANOMALY_SPECS, SPEC_ORDER
from jepsen_tpu.history.ir import HistoryIR
from jepsen_tpu.history.soa import TXN_OK, PackedTxns, pack_txns
from jepsen_tpu.ops.cycle_sweep import SweepGraph, detect_cycles

# first-call-in-process tracking per jitted stage: telemetry's span
# attr for "this duration probably includes jit trace+compile"
_WARM: Dict[str, bool] = {}


def check(history, consistency_models: Sequence[str] = ("serializable",),
          anomalies: Sequence[str] = (), max_reported: int = 8,
          _force_no_fallback: bool = False, deadline=None, policy=None,
          plan=None) -> Dict[str, Any]:
    """Check a list-append history on device.  Accepts History / op list /
    PackedTxns.

    Resilience (ISSUE 2): `deadline` (a `resilience.Deadline`) is polled
    between device stages and per sweep projection — expiry returns
    ``{"valid?": "unknown", "error": "deadline-exceeded"}`` with
    whatever anomaly counts inference already produced.  The device
    entry points (infer, cycle sweep) run under the resilience guard:
    transient XLA failures retry per `policy`; a persistent device
    failure degrades to the host oracle with ``"degraded":
    "host-fallback"`` stamped into the result.  `plan` pins a fault
    plan (tests/chaos); default is the process-active one."""
    try:
        return _check_device(history, consistency_models, anomalies,
                             max_reported, _force_no_fallback, deadline,
                             policy, plan)
    except resilience.DeadlineExceeded:
        # expiry before/inside a device stage: the canonical unknown —
        # the sweep loop returns richer partial stats on its own
        return resilience.deadline_result(checker="list-append")
    except Exception as e:  # noqa: BLE001 — persistent device failure
        if _force_no_fallback:
            raise
        try:
            # shared degradation tail: counter + span attr + deadline
            # poll + "degraded"/"device-error" stamps (guard.py) — an
            # expired budget is never converted into a host run
            return resilience.degrade_to_host(
                "elle.list-append",
                lambda: oracle.check(history, consistency_models,
                                     anomalies,
                                     max_reported=max_reported,
                                     deadline=deadline),
                e, deadline=deadline)
        except resilience.DeadlineExceeded:
            return resilience.deadline_result(checker="list-append")


def _check_device(history, consistency_models, anomalies, max_reported,
                  _force_no_fallback, deadline, policy, plan
                  ) -> Dict[str, Any]:
    def poll(site: str) -> None:
        if deadline is not None:
            deadline.check(site)

    def dev(site: str, fn, *args):
        # guarded seam: synthetic faults fire here, transients retry;
        # a persistent failure raises out to check()'s oracle fallback
        return resilience.device_call(site, fn, *args, policy=policy,
                                      deadline=deadline, plan=plan)

    # phase spans matching the host oracle's stage names (device=True
    # distinguishes them in one trace); "warm" records whether this
    # process already traced/compiled the infer program — the closest
    # cheap proxy for jit compile vs execute time
    ph = telemetry.phases()
    ir = history if isinstance(history, HistoryIR) else None
    if isinstance(history, PackedTxns):
        p = history
    else:
        ph.start("elle.pack", device=True)
        p = (ir.packed("list-append") if ir is not None
             else pack_txns(history, "list-append"))
    if ir is not None and ir.packed_only:
        # packed-only IR: downstream consumers (oracle fallback, session
        # coverage) must see the bare PackedTxns degradation semantics
        history = p
    if p.n_txns == 0 or not (p.txn_type == TXN_OK).any():
        ph.end()
        return {"valid?": "unknown", "anomaly-types": [], "anomalies": {},
                "not": [], "also-not": []}

    poll("elle.infer")
    ph.start("elle.infer", device=True, txns=p.n_txns,
             warm=_WARM.get("infer", False))
    _WARM["infer"] = True
    # the IR caches the padded layout (capacity facts + derived-order
    # columns): repeat checks over one history skip the pad entirely
    h = ir.padded("list-append") if ir is not None else pad_packed(p)
    # sharded-by-default (ISSUE 12): with >1 visible device and a large
    # enough history, op arrays go up with NamedSharding(P("batch")) so
    # GSPMD partitions inference, and each projection sweep runs the
    # K-axis shard_map kernel
    from jepsen_tpu.parallel import slots as _slots

    mesh = _slots.default_mesh(h.txn_type.shape[0])
    if mesh is not None:
        from jepsen_tpu.parallel.op_shard import shard_padded

        h, _ = shard_padded(h, mesh, "batch")
    if telemetry.enabled():
        telemetry.registry().counter("device-bytes-staged").inc(
            sum(int(np.asarray(a).nbytes) for a in (
                h.txn_type, h.txn_process, h.txn_invoke_pos,
                h.txn_complete_pos, h.txn_mask, h.mop_txn, h.mop_kind,
                h.mop_key, h.mop_val, h.mop_rd_start, h.mop_rd_len,
                h.mop_mask, h.rd_elems, h.rd_elem_mask)))
    # infer rides the AOT compile cache: shrink probes and campaign
    # cells over same-bucket histories (pad_packed pads to pow2
    # classes) share one executable instead of compiling per shape
    from jepsen_tpu import compilecache

    out = dev("elle.infer",
              lambda: compilecache.call("elle.infer", infer, h,
                                        n_keys=h.n_keys))

    found: Dict[str, List[Any]] = {}
    counts = {k: int(v) for k, v in out["counts"].items()}
    for name, cnt in counts.items():
        if cnt > 0:
            found[name] = [{"count": cnt}]

    # which anomalies to search/report
    want = set(consistency.anomalies_for_models(
        [consistency.canonical(m) for m in consistency_models]))
    want |= set(anomalies)
    want |= {"duplicate-appends", "duplicate-elements", "incompatible-order"}


    # ---- cycle anomalies: group specs by rel projection -------------------
    ph.start("elle.graph-build", device=True)
    specs = [(name, CYCLE_ANOMALY_SPECS[name]) for name in SPEC_ORDER
             if name in want]
    projections: Dict[frozenset, List[Tuple[str, CycleSpec]]] = {}
    for name, spec in specs:
        projections.setdefault(spec.rels, []).append((name, spec))

    T = h.txn_type.shape[0]
    edges = out["edges"]
    chains = out["chains"]
    rank = jnp.concatenate([out["ranks"]["txn"], out["ranks"]["barrier"]])

    # static concatenated edge arrays; per-projection masks
    e_src = jnp.concatenate([edges[k][0] for k in ("ww", "wr", "rw", "tb",
                                                   "bt")])
    e_dst = jnp.concatenate([edges[k][1] for k in ("ww", "wr", "rw", "tb",
                                                   "bt")])
    sizes = [edges[k][0].shape[0] for k in ("ww", "wr", "rw", "tb", "bt")]
    rel_of = np.concatenate([
        np.full(sizes[0], REL_WW), np.full(sizes[1], REL_WR),
        np.full(sizes[2], REL_RW), np.full(sizes[3], REL_REALTIME),
        np.full(sizes[4], REL_REALTIME)]).astype(np.int8)
    base_mask = jnp.concatenate([edges[k][2] for k in ("ww", "wr", "rw",
                                                       "tb", "bt")])
    rel_arr = jnp.asarray(rel_of)

    pc_nodes, pc_starts, pc_mask = chains["process"]
    bc_nodes, bc_starts, bc_mask = chains["barrier"]
    chain_nodes = jnp.concatenate([pc_nodes, bc_nodes])
    chain_starts = jnp.concatenate([pc_starts, bc_starts])

    host_edges: EdgeList = None  # lazily materialized for classification
    explainer = None             # lazily built per-edge Explainer
    needs_fallback = False
    ph.start("elle.cycle-sweep", device=True,
             projections=len(projections))
    for rels, group in projections.items():
        # deadline poll per projection: the sweep fixpoint retries
        # (grow max_k/max_rounds) can stretch a pathological history —
        # expiry returns unknown + the counts inference already found
        # (via check(), not bare expired(), so the telemetry counter
        # records the expiry site)
        if deadline is not None:
            try:
                deadline.check("elle.cycle-sweep")
            except resilience.DeadlineExceeded:
                ph.end()
                return resilience.deadline_result(
                    **{"anomaly-types": sorted(found),
                       "anomalies": found, "not": [], "also-not": [],
                       "partial": "cycle-sweep interrupted"})
        sel = jnp.zeros_like(base_mask)
        for r in rels:
            sel = sel | (rel_arr == r)
        mask = base_mask & sel
        cmask = jnp.concatenate([
            pc_mask & (REL_PROCESS in rels),
            bc_mask & (REL_REALTIME in rels)])
        g = SweepGraph(n_nodes=2 * T, rank=rank, nc_src=e_src, nc_dst=e_dst,
                       nc_mask=mask, chain_nodes=chain_nodes,
                       chain_starts=chain_starts, chain_mask=cmask)
        res = dev("elle.cycle-sweep",
                  lambda g=g: detect_cycles(g, deadline=deadline,
                                            mesh=mesh))
        if not res.converged:
            needs_fallback = True
            break
        if not res.has_cycle:
            continue
        # ---- host classification over witness regions --------------------
        if host_edges is None:
            host_edges = _materialize_host_edges(
                e_src, e_dst, base_mask, rel_of, chains, T)
        proj = host_edges.project(_expand_rels(rels))
        regions = _witness_regions(
            proj, np.asarray(e_src), np.asarray(e_dst), res.witness_edge_ids,
            2 * T, limit=16)
        for name, spec in group:
            hit = None
            for region in regions:
                hit = find_cycle(region, proj, _spec_with_chains(spec))
                if hit is not None:
                    break
            if hit is not None:
                if explainer is None:
                    from jepsen_tpu.checkers.elle.explain import la_explainer

                    explainer = la_explainer(
                        p, {k: np.asarray(v)
                            for k, v in out["order"].items()})
                found.setdefault(name, []).append(
                    {"cycle": _render(hit, p, T, explainer),
                     "witnesses": int(len(res.witness_edge_ids))})

    if needs_fallback:
        ph.end()
        if _force_no_fallback:
            raise RuntimeError("cycle sweep did not converge")
        poll("elle.host-fallback")
        # pass the ORIGINAL input: an op-level history keeps its session
        # checkability through the fallback (packing drops it); the
        # budget follows — the oracle polls it itself now
        return oracle.check(history, consistency_models, anomalies,
                            max_reported=max_reported, deadline=deadline)

    # session-guarantee tokens run the dedicated per-process checker —
    # after the fallback decision, so a non-converged sweep doesn't do
    # the (host-side) session walk twice (see coverage.py for the
    # PackedTxns degradation rule)
    poll("elle.sessions")
    ph.start("elle.sessions", device=False)
    sess_found, sess_checked = coverage.run_la_sessions(
        history, want, isinstance(history, PackedTxns),
        max_reported=max_reported)
    for k, v in sess_found.items():
        found.setdefault(k, []).extend(v)
    ph.end()

    # shared verdict tail (oracle.boundary_verdict): the device pipeline
    # reached this point only with committed txns (the no-ok case early-
    # returned unknown above), so has_ok is True by construction
    return oracle.boundary_verdict(found, consistency_models, want,
                                   has_ok=True, sess_checked=sess_checked)


def _expand_rels(rels: frozenset) -> Set[int]:
    """Projection rel set for host classification (chains share rel codes)."""
    return set(rels)


def _spec_with_chains(spec: CycleSpec) -> CycleSpec:
    return spec


def _materialize_host_edges(e_src, e_dst, mask, rel_of, chains, T
                            ) -> EdgeList:
    """Pull device edges + chain-implied edges into a host EdgeList."""
    src = np.asarray(e_src)
    dst = np.asarray(e_dst)
    m = np.asarray(mask)
    parts_s = [src[m]]
    parts_d = [dst[m]]
    parts_r = [rel_of[m]]
    for cname, rel in (("process", REL_PROCESS), ("barrier", REL_REALTIME)):
        nodes, starts, cm = (np.asarray(x) for x in chains[cname])
        ok = cm[:-1] & cm[1:] & ~starts[1:]
        parts_s.append(nodes[:-1][ok])
        parts_d.append(nodes[1:][ok])
        parts_r.append(np.full(int(ok.sum()), rel, np.int8))
    e = EdgeList()
    e.src = np.concatenate(parts_s).astype(np.int32)
    e.dst = np.concatenate(parts_d).astype(np.int32)
    e.rel = np.concatenate(parts_r).astype(np.int8)
    return e


def _csr(n: int, src: np.ndarray, dst: np.ndarray):
    order = np.argsort(src, kind="stable")
    ss, dd = src[order], dst[order]
    starts = np.searchsorted(ss, np.arange(n + 1))
    return dd, starts


def _bfs_reach(n: int, src, dst, roots: np.ndarray) -> np.ndarray:
    """Boolean reachability from roots via numpy frontier expansion."""
    dd, starts = _csr(n, src, dst)
    seen = np.zeros(n, bool)
    seen[roots] = True
    frontier = np.unique(roots)
    while len(frontier):
        outs = np.concatenate([dd[starts[v]:starts[v + 1]] for v in frontier]) \
            if len(frontier) < 1024 else _expand_all(dd, starts, frontier)
        outs = outs[~seen[outs]]
        if not len(outs):
            break
        seen[outs] = True
        frontier = np.unique(outs)
    return seen


def _expand_all(dd, starts, frontier):
    counts = starts[frontier + 1] - starts[frontier]
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dd.dtype)
    idx = np.repeat(starts[frontier], counts) + \
        (np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts))
    return dd[idx]


def _witness_regions(proj: EdgeList, e_src, e_dst, witness_ids, n_nodes,
                     limit: int = 16) -> List[np.ndarray]:
    """Nodes on cycles through each witness backward edge (u -> w):
    forward-reach(w) ∩ reverse-reach(u) in the projection."""
    regions = []
    for wid in witness_ids[:limit]:
        u, w = int(e_src[wid]), int(e_dst[wid])
        fwd = _bfs_reach(n_nodes, proj.src, proj.dst, np.array([w]))
        bwd = _bfs_reach(n_nodes, proj.dst, proj.src, np.array([u]))
        nodes = np.nonzero(fwd & bwd)[0]
        if len(nodes):
            regions.append(nodes.astype(np.int64))
    return regions


def _render(cyc, p: PackedTxns, T: int, explainer=None):
    """Collapse barrier hops and emit reported edges, each carrying the
    Explainer's per-edge justification (key, values, why) — the
    reference's `elle/core.clj` Explainer output shape.  Single shared
    implementation in `txn_cycles._render_cycle`."""
    from jepsen_tpu.checkers.elle.txn_cycles import _render_cycle

    return _render_cycle(cyc, explainer, T, np.asarray(p.txn_orig_index))
