"""Exact host reference checker for list-append histories.

This is the semantic ground truth the device pipeline is differentially
tested against — the `elle/list_append.clj` equivalent (SURVEY.md §2.3),
written for clarity, not speed (use on histories up to ~10^5 ops; the TPU
path in `jepsen_tpu.checkers.elle.list_append` is the at-scale engine).

Implements:
 - non-cycle anomalies: duplicate-elements, duplicate-appends, internal,
   G1a (aborted read), G1b (intermediate read), dirty-update,
   incompatible-order;
 - per-key version-order inference (longest ok-read prefix; every read must
   be a prefix of it);
 - ww / wr / rw dependency edges + process + realtime (barrier) orders;
 - cycle anomalies per CYCLE_ANOMALY_SPECS via Tarjan SCC + rel-constrained
   BFS (elle.txn/cycles! analogue);
 - consistency-model verdicts via the lattice.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import telemetry
from jepsen_tpu.resilience import DeadlineExceeded, deadline_result
from jepsen_tpu.checkers.elle import consistency
from jepsen_tpu.checkers.elle.graph import (
    REL_NAMES,
    REL_RW,
    REL_WR,
    REL_WW,
    EdgeList,
    nontrivial_sccs,
    find_cycle,
    process_edges,
)
from jepsen_tpu.checkers.elle.specs import CYCLE_ANOMALY_SPECS, SPEC_ORDER
from jepsen_tpu.history.soa import (
    MOP_APPEND,
    MOP_READ,
    TXN_FAIL,
    TXN_INFO,
    TXN_OK,
    PackedTxns,
    pack_txns,
)


class Txn:
    """Unpacked view of one transaction (oracle-side convenience)."""

    __slots__ = ("i", "type", "process", "invoke_pos", "complete_pos",
                 "orig_index", "mops")

    def __init__(self, i, type_, process, invoke_pos, complete_pos, orig_index):
        self.i = i
        self.type = type_
        self.process = process
        self.invoke_pos = invoke_pos
        self.complete_pos = complete_pos
        self.orig_index = orig_index
        # mops: (kind, key, val, read_list_or_None)
        self.mops: List[Tuple[int, int, int, Optional[List[int]]]] = []


def boundary_verdict(found: Dict[str, List[Any]],
                     consistency_models: Sequence[str],
                     want, has_ok: bool, sess_checked: bool,
                     edge_counts: Optional[Dict[str, int]] = None
                     ) -> Dict[str, Any]:
    """THE list-append verdict tail, shared by the batch oracle, the
    device pipeline, and the incremental verifier session: filter found
    anomalies to the requested set, derive the friendly model boundary,
    decide ``valid?`` (unknown when no txn ever committed), and apply
    the coverage contract.  One implementation so a checker pair that
    agrees on the anomaly set cannot disagree on the verdict."""
    from jepsen_tpu.checkers.elle import coverage

    found = {k: v for k, v in found.items() if k in want}
    anomaly_types = sorted(found.keys())
    boundary = consistency.friendly_boundary(anomaly_types)
    bad = set(boundary["not"]) | set(boundary["also-not"])
    requested_bad = bad & {consistency.canonical(m)
                           for m in consistency_models}
    valid: Any = "unknown" if not has_ok else not requested_bad
    res: Dict[str, Any] = {
        "valid?": valid,
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
    }
    if edge_counts is not None:
        res["edge-counts"] = edge_counts
    return coverage.finalize_la(res, want, sess_checked)


def _unpack(p: PackedTxns) -> List[Txn]:
    txns = [
        Txn(i, int(p.txn_type[i]), int(p.txn_process[i]),
            int(p.txn_invoke_pos[i]), int(p.txn_complete_pos[i]),
            int(p.txn_orig_index[i]))
        for i in range(p.n_txns)
    ]
    for m in range(p.n_mops):
        t = txns[int(p.mop_txn[m])]
        kind = int(p.mop_kind[m])
        key = int(p.mop_key[m])
        val = int(p.mop_val[m])
        if kind == MOP_READ:
            s, ln = int(p.mop_rd_start[m]), int(p.mop_rd_len[m])
            rd = None if ln < 0 else [int(x) for x in p.rd_elems[s:s + ln]]
            t.mops.append((kind, key, val, rd))
        else:
            t.mops.append((kind, key, val, None))
    return txns


def check(history, consistency_models: Sequence[str] = ("serializable",),
          anomalies: Sequence[str] = (), max_cycle_steps: int = 2_000_000,
          max_reported: int = 8, deadline=None) -> Dict[str, Any]:
    """Check a list-append history.  Accepts a History / op list / PackedTxns.

    `deadline` (a `resilience.Deadline`, e.g. the shared
    ``opts["deadline"]`` placed by `check_safe`) is polled between
    stages and inside the per-txn / per-key / per-spec loops: expiry
    returns ``{"valid?": "unknown", "error": "deadline-exceeded"}``
    carrying whatever anomalies the interrupted stages already found,
    instead of running unbounded — the host oracle honors the same
    budget contract as the device pipelines it backs up."""
    # sequential phase spans (telemetry, no-op when disabled): the same
    # infer / graph-build / cycle-sweep stage names as the device
    # pipeline, so host-vs-device time is comparable in one trace
    ph = telemetry.phases()
    if isinstance(history, PackedTxns):
        p = history
    else:
        ph.start("elle.pack", device=False)
        p = pack_txns(history, "list-append")
    ph.start("elle.infer", device=False, txns=p.n_txns)
    txns = _unpack(p)
    found: Dict[str, List[Any]] = {}
    try:
        return _check_body(history, p, txns, found, consistency_models,
                           anomalies, max_cycle_steps, max_reported,
                           deadline, ph)
    except DeadlineExceeded as e:
        ph.end()
        return deadline_result(
            checker="elle-oracle",
            **{"anomaly-types": sorted(found), "anomalies": found,
               "not": [], "also-not": [],
               "partial": f"interrupted at {e.what or 'oracle'}"})


def _check_body(history, p: PackedTxns, txns, found,
                consistency_models, anomalies, max_cycle_steps,
                max_reported, deadline, ph) -> Dict[str, Any]:
    # cooperative budget: cheap monotonic poll every POLL_EVERY
    # iterations of the hot loops, and once per stage boundary
    POLL_EVERY = 256
    n_polls = [0]

    def poll(site: str, every: int = 1) -> None:
        if deadline is None:
            return
        n_polls[0] += 1
        if n_polls[0] % every == 0:
            deadline.check(site)

    def report(name: str, item: Any):
        found.setdefault(name, [])
        if len(found[name]) < max_reported:
            found[name].append(item)

    # -- writer map: val -> (txn index, is_final_append_of_txn_for_key) -----
    writer: Dict[int, int] = {}
    final_append: Dict[int, bool] = {}
    for t in txns:
        poll("elle.infer", POLL_EVERY)
        last_per_key: Dict[int, int] = {}
        for (kind, key, val, _) in t.mops:
            if kind == MOP_APPEND:
                if val in writer:
                    report("duplicate-appends",
                           {"value": p.val_names[val], "txns":
                            [txns[writer[val]].orig_index, t.orig_index]})
                else:
                    writer[val] = t.i
                last_per_key[key] = val
        for v in [v for (k2, k, v, _) in t.mops
                  if k2 == MOP_APPEND and writer.get(v) == t.i]:
            final_append[v] = False
        for key, val in last_per_key.items():
            if writer.get(val) == t.i:
                final_append[val] = True

    # -- internal consistency + duplicate elements (ok txns only) ----------
    for t in txns:
        poll("elle.internal", POLL_EVERY)
        if t.type != TXN_OK:
            continue
        cur: Dict[int, Optional[List[int]]] = {}
        suffix: Dict[int, List[int]] = {}
        for mi, (kind, key, val, rd) in enumerate(t.mops):
            if kind == MOP_APPEND:
                if cur.get(key) is not None:
                    cur[key] = cur[key] + [val]
                else:
                    suffix.setdefault(key, []).append(val)
            else:
                if rd is None:
                    continue
                if len(set(rd)) != len(rd):
                    report("duplicate-elements",
                           {"op": t.orig_index, "mop": mi,
                            "key": p.key_names[key]})
                c = cur.get(key)
                if c is not None:
                    if rd != c:
                        report("internal", {"op": t.orig_index, "mop": mi,
                                            "expected": c, "got": rd})
                else:
                    sfx = suffix.get(key, [])
                    if sfx and (len(rd) < len(sfx) or rd[-len(sfx):] != sfx):
                        report("internal", {"op": t.orig_index, "mop": mi,
                                            "expected-suffix": sfx, "got": rd})
                cur[key] = list(rd)

    # -- G1a (aborted read) / G1b (intermediate read) -----------------------
    for t in txns:
        poll("elle.g1", POLL_EVERY)
        if t.type != TXN_OK:
            continue
        for mi, (kind, key, val, rd) in enumerate(t.mops):
            if kind != MOP_READ or not rd:
                continue
            for v in rd:
                w = writer.get(v)
                if w is not None and txns[w].type == TXN_FAIL:
                    report("G1a", {"op": t.orig_index, "mop": mi,
                                   "value": p.val_names[v],
                                   "writer": txns[w].orig_index})
            last = rd[-1]
            w = writer.get(last)
            if (w is not None and w != t.i
                    and not final_append.get(last, True)):
                report("G1b", {"op": t.orig_index, "mop": mi,
                               "value": p.val_names[last],
                               "writer": txns[w].orig_index})

    # -- per-key version orders (longest ok-read; prefix compatibility) ----
    # reads: (key, tuple(rd), txn, mop index)
    reads_by_key: Dict[int, List[Tuple[List[int], int, int]]] = {}
    for t in txns:
        if t.type != TXN_OK:
            continue
        for mi, (kind, key, val, rd) in enumerate(t.mops):
            if kind == MOP_READ and rd is not None:
                reads_by_key.setdefault(key, []).append((rd, t.i, mi))

    version_order: Dict[int, List[int]] = {}
    for key, reads in reads_by_key.items():
        poll("elle.version-order", 64)
        longest = max(reads, key=lambda r: len(r[0]))[0]
        for (rd, ti, mi) in reads:
            if rd != longest[: len(rd)]:
                report("incompatible-order",
                       {"key": p.key_names[key],
                        "read": rd, "longest": longest,
                        "op": txns[ti].orig_index, "mop": mi})
        version_order[key] = longest

    # -- dirty-update: committed write follows an aborted one ---------------
    for key, order in version_order.items():
        for a, b in zip(order[:-1], order[1:]):
            wa, wb = writer.get(a), writer.get(b)
            if (wa is not None and wb is not None
                    and txns[wa].type == TXN_FAIL and txns[wb].type == TXN_OK):
                report("dirty-update",
                       {"key": p.key_names[key], "aborted-value":
                        p.val_names[a], "committed-value": p.val_names[b],
                        "aborted-writer": txns[wa].orig_index,
                        "committed-writer": txns[wb].orig_index})

    # -- dependency edges ---------------------------------------------------
    poll("elle.graph-build")
    ph.start("elle.graph-build", device=False)

    def graph_txn(i: int) -> bool:
        return txns[i].type in (TXN_OK, TXN_INFO)

    ww_s: List[int] = []; ww_d: List[int] = []
    wr_s: List[int] = []; wr_d: List[int] = []
    rw_s: List[int] = []; rw_d: List[int] = []
    for key, order in version_order.items():
        poll("elle.graph-build", 64)
        for a, b in zip(order[:-1], order[1:]):
            wa, wb = writer.get(a), writer.get(b)
            if (wa is not None and wb is not None and wa != wb
                    and graph_txn(wa) and graph_txn(wb)):
                ww_s.append(wa); ww_d.append(wb)
    for key, reads in reads_by_key.items():
        poll("elle.graph-build", 64)
        order = version_order[key]
        for (rd, ti, mi) in reads:
            if rd != order[: len(rd)]:
                continue  # incompatible read; already reported
            if rd:
                w = writer.get(rd[-1])
                if w is not None and w != ti and graph_txn(w):
                    wr_s.append(w); wr_d.append(ti)
            if len(rd) < len(order):
                nxt = writer.get(order[len(rd)])
                if nxt is not None and nxt != ti and graph_txn(nxt):
                    rw_s.append(ti); rw_d.append(nxt)

    def mk(src, dst, rel):
        e = EdgeList()
        e.src = np.asarray(src, dtype=np.int32)
        e.dst = np.asarray(dst, dtype=np.int32)
        e.rel = np.full(len(src), rel, dtype=np.int8)
        return e

    ok_info = np.array([t.type in (TXN_OK, TXN_INFO) for t in txns], dtype=bool)
    proc = np.asarray([t.process for t in txns], dtype=np.int64)
    inv = np.asarray([t.invoke_pos for t in txns], dtype=np.int64)
    comp = np.asarray([t.complete_pos for t in txns], dtype=np.int64)

    # process edges over ok/info txns only
    pe_all = process_edges(np.where(ok_info, proc, -10**9 - np.arange(len(txns))),
                           inv)
    # realtime: barriers from ok completions; in-edges to ok/info invokes
    ok_ids = np.nonzero(np.array([t.type == TXN_OK for t in txns]))[0]
    n_nodes = len(txns)
    rt = EdgeList(); n_barriers = 0
    if len(ok_ids):
        rt, n_barriers = _realtime_with_subset(
            inv, comp, ok_ids, ok_info, n_nodes)

    edges = EdgeList.concat([
        mk(ww_s, ww_d, REL_WW), mk(wr_s, wr_d, REL_WR), mk(rw_s, rw_d, REL_RW),
        pe_all, rt,
    ]).dedup()

    total_nodes = n_nodes + n_barriers

    # -- cycle anomalies ----------------------------------------------------
    # Only anomalies relevant to the requested models (plus explicitly
    # requested ones) are searched and reported, as in the reference;
    # structural breakdowns of version inference are always reported.
    want = set(consistency.anomalies_for_models(
        [consistency.canonical(m) for m in consistency_models]))
    want |= set(anomalies)
    want |= {"duplicate-appends", "duplicate-elements", "incompatible-order"}

    # session-guarantee tokens: dedicated per-process checker on
    # op-level input; coverage.py owns the degradation rule
    from jepsen_tpu.checkers.elle import coverage

    poll("elle.sessions")
    ph.start("elle.sessions", device=False)
    sess_found, sess_checked = coverage.run_la_sessions(
        history, want, isinstance(history, PackedTxns),
        max_reported=max_reported)
    for k, v in sess_found.items():
        found.setdefault(k, []).extend(v)

    cycle_specs = [s for s in SPEC_ORDER
                   if s in want and s in CYCLE_ANOMALY_SPECS]

    ph.start("elle.cycle-sweep", device=False, specs=len(cycle_specs))
    for name in cycle_specs:
        # per-spec poll: the SCC + rel-constrained search is the
        # unbounded part of the host path — the budget must bite here
        poll("elle.cycle-sweep")
        spec = CYCLE_ANOMALY_SPECS[name]
        proj = edges.project(spec.rels)
        if not len(proj):
            continue
        sccs = nontrivial_sccs(total_nodes, proj.src, proj.dst)
        for scc in sccs:
            poll("elle.cycle-sweep", 16)
            cyc = find_cycle(scc, proj, spec, max_steps=max_cycle_steps)
            if cyc is not None:
                report(name, {"cycle": _render_cycle(cyc, txns, n_nodes),
                              "scc-size": int(len(scc))})
                break  # one witness per spec, like the reference's default

    ph.end()
    return boundary_verdict(
        found, consistency_models, want,
        has_ok=any(t.type == TXN_OK for t in txns),
        sess_checked=sess_checked,
        edge_counts={REL_NAMES[r]: int((edges.rel == r).sum())
                     for r in np.unique(edges.rel)} if len(edges) else {})


def _realtime_with_subset(inv, comp, ok_ids, ok_info, n_nodes):
    """Realtime barrier edges where only ok txns complete, ok/info invoke."""
    ok_comp = comp[ok_ids]
    order = np.argsort(ok_comp, kind="stable")
    comp_sorted = ok_comp[order]
    n_b = len(ok_ids)
    src: List[np.ndarray] = []
    dst: List[np.ndarray] = []
    src.append(ok_ids[order].astype(np.int32))
    dst.append((n_nodes + np.arange(n_b)).astype(np.int32))
    if n_b > 1:
        src.append((n_nodes + np.arange(n_b - 1)).astype(np.int32))
        dst.append((n_nodes + np.arange(1, n_b)).astype(np.int32))
    cand = np.nonzero(ok_info)[0]
    b_idx = np.searchsorted(comp_sorted, inv[cand], side="left") - 1
    mask = b_idx >= 0
    if mask.any():
        src.append((n_nodes + b_idx[mask]).astype(np.int32))
        dst.append(cand[mask].astype(np.int32))
    e = EdgeList()
    e.src = np.concatenate(src)
    e.dst = np.concatenate(dst)
    from jepsen_tpu.checkers.elle.graph import REL_REALTIME
    e.rel = np.full(len(e.src), REL_REALTIME, dtype=np.int8)
    return e, n_b


def _render_cycle(cyc, txns, n_txns):
    """Render a cycle, contracting realtime-barrier pseudo-nodes into single
    txn->txn realtime steps (barriers are an internal encoding detail)."""
    # rotate so the cycle starts at a txn node (one must exist: barrier-only
    # cycles are impossible — the barrier chain is acyclic)
    k = next(i for i, (s, _, _) in enumerate(cyc) if s < n_txns)
    cyc = cyc[k:] + cyc[:k]
    out = []
    pend_src = None
    for (s, rel, d) in cyc:
        if d >= n_txns:  # entering/along barriers: remember the txn source
            if s < n_txns:
                pend_src = s
            continue
        src = s if s < n_txns else pend_src
        out.append({
            "src": txns[src].orig_index,
            "rel": REL_NAMES[rel],
            "dst": txns[d].orig_index,
        })
    return out
