"""Host-side dependency-graph machinery.

Equivalent of the reference's `elle/graph.clj` + the bifurcan Java layer
(SURVEY.md §2.3, §2.5 #1): SCC computation, rel-constrained shortest-cycle
search (the `elle.bfs` analogue), and the sparse realtime-order construction.

The reference uses bifurcan's sequential Tarjan; here Tarjan is an iterative
host implementation used (a) as the exact oracle and (b) to classify the
small offending subgraphs that the device cycle kernel reports as witnesses.
The at-scale cycle *detection* path is the device kernel in
`jepsen_tpu.ops.cycle_sweep`.

Rel codes are shared with the device pipeline.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

# Dependency relationship codes (device + host shared).
REL_WW = 0
REL_WR = 1
REL_RW = 2
REL_PROCESS = 3
REL_REALTIME = 4

REL_NAMES = {REL_WW: "ww", REL_WR: "wr", REL_RW: "rw",
             REL_PROCESS: "process", REL_REALTIME: "realtime"}
REL_CODES = {v: k for k, v in REL_NAMES.items()}


class EdgeList:
    """A typed edge list over integer node ids (txns + barrier nodes)."""

    def __init__(self, src=(), dst=(), rel=()):
        self.src = np.asarray(src, dtype=np.int32)
        self.dst = np.asarray(dst, dtype=np.int32)
        self.rel = np.asarray(rel, dtype=np.int8)

    def __len__(self):
        return len(self.src)

    @staticmethod
    def concat(parts: Sequence["EdgeList"]) -> "EdgeList":
        parts = [p for p in parts if len(p)]
        if not parts:
            return EdgeList()
        e = EdgeList()
        e.src = np.concatenate([p.src for p in parts])
        e.dst = np.concatenate([p.dst for p in parts])
        e.rel = np.concatenate([p.rel for p in parts])
        return e

    def project(self, rels: Iterable[int]) -> "EdgeList":
        rels = set(rels)
        mask = np.isin(self.rel, list(rels))
        e = EdgeList()
        e.src, e.dst, e.rel = self.src[mask], self.dst[mask], self.rel[mask]
        return e

    def dedup(self) -> "EdgeList":
        if not len(self):
            return self
        key = np.stack([self.src.astype(np.int64), self.dst.astype(np.int64),
                        self.rel.astype(np.int64)], axis=1)
        _, idx = np.unique(key, axis=0, return_index=True)
        e = EdgeList()
        e.src, e.dst, e.rel = self.src[idx], self.dst[idx], self.rel[idx]
        return e


def realtime_edges(invoke_pos: np.ndarray, complete_pos: np.ndarray,
                   node_offset: int = 0) -> Tuple[EdgeList, int]:
    """Sparse realtime order via barrier nodes.

    The reference's `elle.core/realtime-graph` links each completed op to ops
    invoked after it; materializing that relation is O(n * concurrency)
    edges.  We instead thread a chain of *barrier* nodes through the history
    — one per completion event — giving an O(n)-edge graph whose transitive
    closure restricted to txn nodes equals the realtime relation exactly:

        txn T  --(completes at event e)-->  barrier(e)
        barrier(e) --> barrier(e')          (consecutive completions)
        barrier(e) --> txn U                (latest completion event < U's invoke)

    Barrier node ids start at `node_offset` (pass n_txns).  Returns the
    edges and the number of barrier nodes created.
    """
    n = len(invoke_pos)
    if n == 0:
        return EdgeList(), 0
    e, n_b, _ = realtime_edges_subset(invoke_pos, complete_pos,
                                      np.arange(n), np.ones(n, bool),
                                      node_offset)
    return e, n_b


def realtime_edges_subset(inv: np.ndarray, comp: np.ndarray,
                          ok_ids: np.ndarray, in_mask: np.ndarray,
                          n_nodes: int) -> Tuple[EdgeList, int, np.ndarray]:
    """Barrier-mediated realtime edges where only `ok_ids` complete and
    nodes with `in_mask` receive in-edges (invoked).  Barrier node ids
    start at n_nodes; returns (edges, n_barriers, barrier_ranks).  Barrier
    i corresponds to the i-th completion in completion order; its rank
    (2*comp+1) interleaves with txn ranks 2*comp."""
    ok_comp = comp[ok_ids]
    order = np.argsort(ok_comp, kind="stable")
    comp_sorted = ok_comp[order]
    n_b = len(ok_ids)
    if n_b == 0:
        return EdgeList(), 0, np.zeros(0, np.int64)
    src: List[np.ndarray] = [ok_ids[order].astype(np.int32)]
    dst: List[np.ndarray] = [(n_nodes + np.arange(n_b)).astype(np.int32)]
    if n_b > 1:
        src.append((n_nodes + np.arange(n_b - 1)).astype(np.int32))
        dst.append((n_nodes + np.arange(1, n_b)).astype(np.int32))
    cand = np.nonzero(in_mask)[0]
    b_idx = np.searchsorted(comp_sorted, inv[cand], side="left") - 1
    mask = b_idx >= 0
    if mask.any():
        src.append((n_nodes + b_idx[mask]).astype(np.int32))
        dst.append(cand[mask].astype(np.int32))
    e = EdgeList()
    e.src = np.concatenate(src)
    e.dst = np.concatenate(dst)
    e.rel = np.full(len(e.src), REL_REALTIME, dtype=np.int8)
    return e, n_b, (2 * comp_sorted + 1).astype(np.int64)


def process_edges(process: np.ndarray, invoke_pos: np.ndarray) -> EdgeList:
    """Chain each process's txns in invocation order (elle.core/process-graph)."""
    if len(process) == 0:
        return EdgeList()
    order = np.lexsort((invoke_pos, process))
    same = process[order[:-1]] == process[order[1:]]
    s = order[:-1][same].astype(np.int32)
    d = order[1:][same].astype(np.int32)
    e = EdgeList()
    e.src, e.dst = s, d
    e.rel = np.full(len(s), REL_PROCESS, dtype=np.int8)
    return e


def _adjacency(n: int, src: np.ndarray, dst: np.ndarray):
    """CSR-ish adjacency: sorted-by-src edge array + per-node slices."""
    order = np.argsort(src, kind="stable")
    ss, dd = src[order], dst[order]
    starts = np.searchsorted(ss, np.arange(n))
    ends = np.searchsorted(ss, np.arange(n), side="right")
    return dd, starts, ends, order


def tarjan_scc(n: int, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    """Iterative Tarjan SCC.  Returns component label per node (arbitrary ids).

    Host equivalent of bifurcan `Graphs.stronglyConnectedComponents`
    (SURVEY.md §2.5 #1).  Iterative to survive deep graphs.  Uses the C++
    native implementation (`jepsen_tpu.native`) when available — the
    Python body below is the semantic anchor it is differentially tested
    against (and the fallback when no compiler exists).
    """
    import os
    if n and not os.environ.get("JT_NO_NATIVE"):
        from jepsen_tpu import native
        comp_native = native.scc(n, src, dst)
        if comp_native is not None:
            return comp_native
    adj_dst, starts, ends, _ = _adjacency(n, src, dst)
    UNVISITED = -1
    index = np.full(n, UNVISITED, dtype=np.int64)
    low = np.zeros(n, dtype=np.int64)
    on_stack = np.zeros(n, dtype=bool)
    comp = np.full(n, -1, dtype=np.int64)
    stack: List[int] = []
    next_index = 0
    n_comps = 0
    ptr = starts.copy().astype(np.int64)

    for root in range(n):
        if index[root] != UNVISITED:
            continue
        work = [root]
        while work:
            v = work[-1]
            if index[v] == UNVISITED:
                index[v] = low[v] = next_index
                next_index += 1
                stack.append(v)
                on_stack[v] = True
            advanced = False
            while ptr[v] < ends[v]:
                w = int(adj_dst[ptr[v]])
                ptr[v] += 1
                if index[w] == UNVISITED:
                    work.append(w)
                    advanced = True
                    break
                elif on_stack[w]:
                    if index[w] < low[v]:
                        low[v] = index[w]
            if advanced:
                continue
            # all neighbors done
            work.pop()
            if work:
                u = work[-1]
                if low[v] < low[u]:
                    low[u] = low[v]
            if low[v] == index[v]:
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp[w] = n_comps
                    if w == v:
                        break
                n_comps += 1
    return comp


def nontrivial_sccs(n: int, src: np.ndarray, dst: np.ndarray) -> List[np.ndarray]:
    """SCCs with >1 node, or a single node with a self-loop."""
    comp = tarjan_scc(n, src, dst)
    out: List[np.ndarray] = []
    if n == 0:
        return out
    order = np.argsort(comp, kind="stable")
    cs = comp[order]
    bounds = np.nonzero(np.diff(cs))[0] + 1
    groups = np.split(order, bounds)
    self_loop_nodes = set(src[src == dst].tolist())
    for g in groups:
        if len(g) > 1 or int(g[0]) in self_loop_nodes:
            out.append(g.astype(np.int64))
    return out


# ---------------------------------------------------------------------------
# Rel-constrained shortest-cycle search (the elle.bfs analogue).
#
# A cycle spec constrains which rels may appear and how rw (anti-dependency)
# edges may occur:
#   rw_mode: "any"          — no constraint on rw count
#            "none"         — no rw edges at all
#            "single"       — exactly one rw edge            (G-single)
#            "multi-nonadj" — >= 2 rw edges, no two adjacent (G-nonadjacent)
#            "some"         — >= 1 rw edge                   (G2-item)
# ---------------------------------------------------------------------------


class CycleSpec:
    def __init__(self, rels: Iterable[int], rw_mode: str = "any"):
        self.rels = frozenset(rels)
        self.rw_mode = rw_mode

    def __repr__(self):
        return f"CycleSpec({sorted(self.rels)}, {self.rw_mode})"


class _Adj:
    """Adjacency over a filtered edge list restricted to a node set."""

    def __init__(self, nodes: np.ndarray, edges: EdgeList,
                 rels: Optional[frozenset] = None,
                 drop_rels: Optional[frozenset] = None):
        self.node_set = set(int(x) for x in nodes)
        mask = np.isin(edges.src, nodes) & np.isin(edges.dst, nodes)
        if rels is not None:
            mask &= np.isin(edges.rel, list(rels))
        if drop_rels:
            mask &= ~np.isin(edges.rel, list(drop_rels))
        es, ed, er = edges.src[mask], edges.dst[mask], edges.rel[mask]
        order = np.argsort(es, kind="stable")
        self.src = es[order]
        self.dst = ed[order]
        self.rel = er[order]
        self._starts: Dict[int, int] = {}
        self._ends: Dict[int, int] = {}
        prev = None
        for i, s in enumerate(self.src.tolist()):
            if s != prev:
                self._starts[s] = i
                prev = s
        prev = None
        for i in range(len(self.src) - 1, -1, -1):
            s = int(self.src[i])
            if s != prev:
                self._ends[s] = i + 1
                prev = s

    def __len__(self):
        return len(self.src)

    def neighbors(self, v: int):
        a = self._starts.get(v)
        if a is None:
            return ()
        b = self._ends[v]
        return zip(self.dst[a:b].tolist(), self.rel[a:b].tolist())

    def rw_edges(self):
        m = self.rel == REL_RW
        return zip(self.src[m].tolist(), self.dst[m].tolist())


def _bfs_path(adj: _Adj, src: int, dst: int, budget: List[int]
              ) -> Optional[List[Tuple[int, int, int]]]:
    """Shortest (simple) path src -> dst; list of (u, rel, v) steps.
    src == dst finds a shortest cycle through src."""
    parents: Dict[int, Tuple[int, int]] = {}
    q = deque([src])
    seen = {src} if src != dst else set()
    while q:
        v = q.popleft()
        for (w, rel) in adj.neighbors(v):
            budget[0] -= 1
            if budget[0] <= 0:
                return None
            if w == dst:
                path = [(v, rel, w)]
                while v != src:
                    pv, prel = parents[v]
                    path.append((pv, prel, v))
                    v = pv
                path.reverse()
                return path
            if w not in seen:
                seen.add(w)
                parents[w] = (v, rel)
                q.append(w)
    return None


def find_cycle(nodes: np.ndarray, edges: EdgeList, spec: CycleSpec,
               max_steps: int = 2_000_000) -> Optional[List[Tuple[int, int, int]]]:
    """Shortest simple cycle within `nodes` satisfying `spec`.

    Returns a list of (src, rel, dst) steps forming the cycle, or None.
    Exact, per-mode strategies (all produce *simple* cycles — Adya phenomena
    are simple cycles in the DSG, and closed non-simple walks must not be
    reported; cf. the reference's elle.txn cycle search):

      any          — shortest cycle through any node (plain BFS).
      single       — for each rw edge (a, b): shortest b->a path avoiding rw;
                     BFS paths are simple and rw-free, so edge + path is a
                     simple cycle with exactly one rw.
      some         — same but the return path may use any rel (>=1 rw).
      multi-nonadj — NFA-guided BFS; a found walk is verified simple, else a
                     budgeted DFS over simple paths; None if budget exhausts
                     (conservative: never a false positive).
    """
    budget = [max_steps]
    mode = spec.rw_mode
    if mode in ("any", "none"):
        adj = _Adj(nodes, edges, spec.rels,
                   drop_rels=frozenset([REL_RW]) if mode == "none" else None)
        if not len(adj):
            return None
        for start in (int(x) for x in nodes):
            path = _bfs_path(adj, start, start, budget)
            if path is not None:
                return path
            if budget[0] <= 0:
                return None
        return None
    if mode in ("single", "some"):
        adj_full = _Adj(nodes, edges, spec.rels)
        ret_adj = (_Adj(nodes, edges, spec.rels, drop_rels=frozenset([REL_RW]))
                   if mode == "single" else adj_full)
        for (a, b) in adj_full.rw_edges():
            path = _bfs_path(ret_adj, b, a, budget)
            if path is not None:
                return path + [(a, REL_RW, b)]
            if budget[0] <= 0:
                return None
        return None
    if mode == "multi-nonadj":
        return _find_nonadjacent_cycle(nodes, edges, spec, budget)
    raise ValueError(mode)


def _find_nonadjacent_cycle(nodes, edges, spec, budget):
    """Simple cycle with >=2 rw edges, no two cyclically adjacent.

    DFS over simple paths with on-path visited set, pruned by the
    nonadjacency NFA.  Budgeted: gives up (returns None) rather than
    reporting a non-simple walk.
    """
    adj = _Adj(nodes, edges, spec.rels)
    if not len(adj):
        return None
    # start DFS only at rw edge tails: every qualifying cycle has one
    for (a0, b0) in adj.rw_edges():
        # path so far: a0 -rw-> b0 ... ; states: rw_count, last_was_rw
        stack = [(b0, [(a0, REL_RW, b0)], {a0, b0}, 1, True)]
        while stack:
            if budget[0] <= 0:
                return None
            v, path, on_path, rw_n, last_rw = stack.pop()
            for (w, rel) in adj.neighbors(v):
                budget[0] -= 1
                is_rw = rel == REL_RW
                if is_rw and last_rw:
                    continue  # adjacent rw
                if w == a0:
                    # closing edge: wraparound adjacency vs the initial rw
                    if is_rw:
                        continue
                    if rw_n >= 2:
                        return path + [(v, rel, a0)]
                    continue
                if w in on_path:
                    continue
                stack.append((w, path + [(v, rel, w)], on_path | {w},
                              rw_n + (1 if is_rw else 0), is_rw))
    return None
