"""Cycle-anomaly specs — the `elle.txn/cycle-anomaly-specs` equivalent.

Each spec names a cycle-shaped anomaly, the dependency rels whose projection
to search, and the constraint on rw (anti-dependency) edges in the cycle
(SURVEY.md §2.3 cycle taxonomy engine).
"""

from __future__ import annotations

from typing import Dict

from jepsen_tpu.checkers.elle.graph import (
    REL_PROCESS,
    REL_REALTIME,
    REL_RW,
    REL_WR,
    REL_WW,
    CycleSpec,
)

_BASE = {REL_WW, REL_WR, REL_RW}

CYCLE_ANOMALY_SPECS: Dict[str, CycleSpec] = {
    # write cycles
    "G0": CycleSpec({REL_WW}, "any"),
    "G0-process": CycleSpec({REL_WW, REL_PROCESS}, "any"),
    "G0-realtime": CycleSpec({REL_WW, REL_REALTIME}, "any"),
    # circular information flow
    "G1c": CycleSpec({REL_WW, REL_WR}, "any"),
    "G1c-process": CycleSpec({REL_WW, REL_WR, REL_PROCESS}, "any"),
    "G1c-realtime": CycleSpec({REL_WW, REL_WR, REL_REALTIME}, "any"),
    # single anti-dependency cycles
    "G-single": CycleSpec(_BASE, "single"),
    "G-single-process": CycleSpec(_BASE | {REL_PROCESS}, "single"),
    "G-single-realtime": CycleSpec(_BASE | {REL_REALTIME}, "single"),
    # non-adjacent anti-dependency cycles
    "G-nonadjacent": CycleSpec(_BASE, "multi-nonadj"),
    "G-nonadjacent-process": CycleSpec(_BASE | {REL_PROCESS}, "multi-nonadj"),
    "G-nonadjacent-realtime": CycleSpec(_BASE | {REL_REALTIME}, "multi-nonadj"),
    # item anti-dependency cycles
    "G2-item": CycleSpec(_BASE, "some"),
    "G2-item-process": CycleSpec(_BASE | {REL_PROCESS}, "some"),
    "G2-item-realtime": CycleSpec(_BASE | {REL_REALTIME}, "some"),
}

#: the one anomaly family whose search is a budgeted simple-cycle DFS
#: ("never a false positive, may give up"): differential comparisons may
#: see a legitimate device-vs-oracle asymmetry here on dense graphs
NONADJACENT_FAMILY = frozenset({
    "G-nonadjacent", "G-nonadjacent-process", "G-nonadjacent-realtime"})

# Search order: report the strongest (most specific / weakest-model-violating)
# anomalies first, as the reference does.
SPEC_ORDER = [
    "G0", "G0-process", "G0-realtime",
    "G1c", "G1c-process", "G1c-realtime",
    "G-single", "G-single-process", "G-single-realtime",
    "G-nonadjacent", "G-nonadjacent-process", "G-nonadjacent-realtime",
    "G2-item", "G2-item-process", "G2-item-realtime",
]
