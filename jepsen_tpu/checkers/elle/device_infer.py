"""Device-side edge inference + anomaly scans for list-append histories.

The TPU half of the `elle/list_append.clj` equivalent: everything here runs
under one `jax.jit` over the SoA history arrays (SURVEY.md §7 stage 2a/2b).

Design notes (TPU-first, not a translation):
- The reference builds per-key version orders with per-key Clojure maps and
  unions bifurcan graphs.  Here every per-key computation is a flat
  *segment op* over arrays sorted by key — the vmap-over-keys equivalent
  that stays dense under Zipfian key skew (no ragged padding).  All scans
  are parallel (cumsum / cummax / associative_scan); nothing sequential.
- Version order per key = the longest ok-read of that key (reads must be
  prefix-compatible; violations are flagged, as in the reference).
- Dependency edges come out as fixed-capacity masked COO arrays, ready for
  the cycle sweep kernel:
    ww  — consecutive version writers  (capacity: read-element slots)
    wr  — final-version writer -> reader (capacity: mop slots)
    rw  — reader -> next-version writer  (capacity: mop slots)
  plus chain inputs: per-process order and the realtime barrier chain (the
  exact O(n)-edge transitive encoding of the realtime relation).
- Non-cycle anomaly scans (duplicate-elements/appends, incompatible-order,
  G1a, G1b, internal, dirty-update) are elementwise flags with counts and
  argmax witnesses.  `internal` is exact whenever reads are
  prefix-compatible; under incompatible-order the history is already
  invalid and both checkers report it.

All shapes static; padding is masked.  Pure function of its inputs — safe
to vmap / shard_map over a batch of histories.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.history.soa import (
    MOP_APPEND,
    MOP_READ,
    TXN_FAIL,
    TXN_INFO,
    TXN_OK,
    PackedTxns,
)
from jepsen_tpu.ops import pallas_fill
from jepsen_tpu.ops.segments import (
    segment_ids_from_starts,
    segmented_cummax,
    segmented_cumsum,
)

BIG = jnp.int32(2 ** 30)
BIG_I = 2 ** 30  # host-side twin (the IR column derivation)


@dataclasses.dataclass
class PaddedLA:
    """Padded device inputs for a list-append history.

    T/M/R are padded capacities; *_mask mark real rows.  val ids < R.
    """

    txn_type: jnp.ndarray          # (T,) i8 (0 = padding)
    txn_process: jnp.ndarray       # (T,) i32
    txn_invoke_pos: jnp.ndarray    # (T,) i32
    txn_complete_pos: jnp.ndarray  # (T,) i32
    txn_mask: jnp.ndarray          # (T,) bool
    mop_txn: jnp.ndarray           # (M,) i32
    mop_kind: jnp.ndarray          # (M,) i8
    mop_key: jnp.ndarray           # (M,) i32
    mop_val: jnp.ndarray           # (M,) i32 (append value id or -1)
    mop_rd_start: jnp.ndarray      # (M,) i32
    mop_rd_len: jnp.ndarray        # (M,) i32 (-1 unknown)
    mop_mask: jnp.ndarray          # (M,) bool
    rd_elems: jnp.ndarray          # (R,) i32
    rd_elem_mask: jnp.ndarray      # (R,) bool
    n_keys: int                    # static
    n_vals: int                    # static
    # Static layout facts, host-verified at padding time (False/0 = unknown,
    # infer falls back to device sorts).  They hold by construction for
    # TxnPacker output; pad_packed re-checks so hand-built PackedTxns with
    # exotic layouts stay correct through the fallback.
    txn_major: bool = False        # static: mop_txn nondecreasing, valid
    #                                mops contiguous before the padding tail
    run_cap: int = 0               # static: pow2 bucket >= max mops/txn
    #                                (0 = unknown or > _RUN_CAP_MAX)
    complete_monotone: bool = False  # static: txn_complete_pos strictly
    #                                  increasing over valid txns
    # IR v2 capacity/layout facts (history/ir.py).  0/False = unknown:
    # infer falls back to the legacy R-sized tables / unsorted scatters.
    v_cap: int = 0                 # static: pow2 > max value id — the
    #                                value-table capacity (legacy: R)
    o_cap: int = 0                 # static: pow2 >= total version-order
    #                                slots (sum of per-key longest-read
    #                                lengths; legacy: R)
    app_val_mono: bool = False     # static: append mop val ids
    #                                nondecreasing in mop order
    rd_start_mono: bool = False    # static: rd_start strictly increasing
    #                                and in-bounds over has-elems reads
    proc_seq: bool = False         # static: within each process,
    #                                invoke_pos increases with txn row
    # IR derived-order columns (history/ir.py, docs/IR.md): computed
    # ONCE host-side at pad time and reused by every check over the same
    # history — the in-program sorts/scatters they replace are the top
    # steady-state inference costs on scatter-hostile backends.  None =
    # derive in-program (legacy; exact either way, pinned by the IR
    # round-trip differentials).
    run_sort: Optional[jnp.ndarray] = None      # (M,) i32 (txn,key,pos) order
    inv_run: Optional[jnp.ndarray] = None       # (M,) i32 its inverse
    key_ord_len: Optional[jnp.ndarray] = None   # (K,) i32 longest known read
    key_ord_read: Optional[jnp.ndarray] = None  # (K,) i32 its mop (-1 none)
    proc_order: Optional[jnp.ndarray] = None    # (T,) i32 (process, invoke)
    barrier_order: Optional[jnp.ndarray] = None  # (T,) i32 ok-completion
    barrier_bi: Optional[jnp.ndarray] = None    # (T,) i32 barrier index
    #                                             before each invoke (-1)


jax.tree_util.register_dataclass(
    PaddedLA,
    data_fields=["txn_type", "txn_process", "txn_invoke_pos",
                 "txn_complete_pos", "txn_mask", "mop_txn", "mop_kind",
                 "mop_key", "mop_val", "mop_rd_start", "mop_rd_len",
                 "mop_mask", "rd_elems", "rd_elem_mask", "run_sort",
                 "inv_run", "key_ord_len", "key_ord_read", "proc_order",
                 "barrier_order", "barrier_bi"],
    meta_fields=["n_keys", "n_vals", "txn_major", "run_cap",
                 "complete_monotone", "v_cap", "o_cap", "app_val_mono",
                 "rd_start_mono", "proc_seq"],
)

# Above this many mops in one txn the shifted-compare ranking (2*(cap-1)
# M-sized passes) stops beating the O(M log^2 M) bitonic sort it replaces.
_RUN_CAP_MAX = 32


def pow2_at_least(n: int, floor: int = 8) -> int:
    x = floor
    while x < n:
        x *= 2
    return x


def run_cap_of(longest: int) -> int:
    """Pow2 bucket for the longest per-txn mop run; 0 = too long, use the
    device-sort fallback.  Single definition so the pad_packed and
    streamed-staging paths can't drift apart on compile-cache keys."""
    return pow2_at_least(max(longest, 1), floor=1) \
        if longest <= _RUN_CAP_MAX else 0


def _layout_facts(p: PackedTxns) -> tuple[bool, int, bool]:
    """Host-verify the packing-layout invariants that let `infer` skip
    device sorts (cheap numpy scans; ~ms at 1M txns)."""
    txn_major = bool(
        p.n_mops == 0
        or (np.all(np.diff(p.mop_txn) >= 0)
            and p.mop_txn[0] >= 0 and p.mop_txn[-1] < p.n_txns))
    run_cap = 0
    if txn_major:
        longest = int(np.bincount(
            p.mop_txn, minlength=max(p.n_txns, 1)).max()) if p.n_mops \
            else 1
        run_cap = run_cap_of(longest)
    complete_monotone = bool(np.all(np.diff(p.txn_complete_pos) > 0)) \
        if p.n_txns > 1 else True
    return txn_major, run_cap, complete_monotone


def _ir_facts(p: PackedTxns) -> dict:
    """Host-verify the IR v2 capacity/layout facts (cheap numpy; ~50 ms
    at 1M txns).  Every fact degrades to the legacy path when False/0,
    so exotic hand-built histories stay exact.

    The capacities are the big lever on this class of backend: the
    legacy layout sized the value table and the version-order table at R
    (the read-element capacity, 2^24 at 1M txns) when the data needs
    2^22 — and XLA:CPU scatters cost per *update*, so the order-table
    passes were 4x oversized (ISSUE 12).

    NOT memoized on the instance: hand-built tests (and shrink probes)
    mutate PackedTxns arrays in place and re-pad — a cache would serve
    stale facts for a different history.  Batch paths avoid the double
    computation by passing `batch_caps`'s facts into `pad_packed`
    explicitly (`ir_facts=`)."""
    nk = max(p.n_keys, 1)
    kind = p.mop_kind
    # ---- v_cap: one past the max value id anywhere ----------------------
    mx = p.n_vals - 1
    if p.n_mops:
        mx = max(mx, int(p.mop_val.max()))
    if len(p.rd_elems):
        mx = max(mx, int(p.rd_elems.max()))
    v_cap = pow2_at_least(mx + 1, floor=8)
    # ---- o_cap: sum of per-key longest known-read lengths ---------------
    # only when every real mop key is in range: the program's scatter
    # semantics for out-of-range keys (wrap/drop) are not worth
    # emulating host-side — fall back to the legacy R-sized table
    o_cap = 0
    keys_ok = p.n_mops == 0 or (
        int(p.mop_key.min()) >= 0 and int(p.mop_key.max()) < nk)
    if keys_ok:
        rd = (kind == MOP_READ) & (p.mop_rd_len >= 0)
        total = 0
        if rd.any():
            mk = np.zeros(nk, np.int64)
            np.maximum.at(mk, p.mop_key[rd], p.mop_rd_len[rd])
            total = int(mk.sum())
        o_cap = pow2_at_least(max(total, 1), floor=8)
    # ---- append-val monotonicity ----------------------------------------
    app = (kind == MOP_APPEND) & (p.mop_val >= 0)
    app_val_mono = bool(np.all(np.diff(p.mop_val[app]) >= 0)) \
        if app.any() else True
    # ---- read-element allocation monotonicity ---------------------------
    he = (kind == MOP_READ) & (p.mop_rd_len > 0)
    if he.any():
        hs = p.mop_rd_start[he]
        rd_start_mono = bool(
            hs[0] >= 0 and np.all(np.diff(hs) > 0)
            and int(hs[-1] + p.mop_rd_len[he][-1]) <= len(p.rd_elems))
    else:
        rd_start_mono = True
    # ---- per-process invoke order == row order --------------------------
    if p.n_txns > 1:
        order = np.argsort(p.txn_process, kind="stable")
        inv_s = p.txn_invoke_pos[order]
        same = p.txn_process[order][1:] == p.txn_process[order][:-1]
        proc_seq = bool(np.all(inv_s[1:][same] > inv_s[:-1][same]))
    else:
        proc_seq = True
    return {"v_cap": v_cap, "o_cap": o_cap, "app_val_mono": app_val_mono,
            "rd_start_mono": rd_start_mono, "proc_seq": proc_seq}


def _ir_columns(p: PackedTxns, T: int, M: int, txn_major: bool,
                run_cap: int) -> Optional[dict]:
    """Host-derive the IR order columns over the PADDED index spaces,
    bit-for-bit replicating the orders `infer` would compute in-program
    (same sentinel placement, same stable tie-breaks).  Returns None
    when the packing is too exotic to replicate safely (ids out of
    range) — infer then derives everything in-program, exactly as
    before."""
    n, m = p.n_txns, p.n_mops
    nk = max(p.n_keys, 1)
    if m and (int(p.mop_txn.min()) < 0 or int(p.mop_txn.max()) >= max(n, 1)
              or int(p.mop_key.min()) < 0 or int(p.mop_key.max()) >= nk):
        return None

    # ---- (txn, key, pos) run permutation --------------------------------
    # padded tail carries the same (T, nk) sentinels the device sort
    # keys use, so it lands after every valid row in position order
    if txn_major and run_cap:
        # within-txn counting by shifted compares (the device fast
        # path's exact host twin) — ~10x cheaper than a full lexsort
        te = p.mop_txn.astype(np.int64)
        ke = p.mop_key.astype(np.int64)
        rank = np.zeros(m, np.int64)
        for d in range(1, run_cap):
            same = te[d:] == te[:-d]
            rank[d:] += same & (ke[:-d] <= ke[d:])
            rank[:-d] += same & (ke[d:] < ke[:-d])
        first_mop = np.searchsorted(te, np.arange(n, dtype=np.int64))
        inv_v = first_mop[te] + rank
    else:
        inv_v = np.empty(m, np.int64)
        inv_v[np.lexsort((np.arange(m), p.mop_key.astype(np.int64),
                          p.mop_txn.astype(np.int64)))] = np.arange(m)
    inv_run = np.concatenate([inv_v, np.arange(m, M)]).astype(np.int32)
    run_sort = np.zeros(M, np.int32)
    run_sort[inv_run] = np.arange(M, dtype=np.int32)

    # ---- per-key longest known read -------------------------------------
    ok = p.txn_type == TXN_OK
    K = pow2_at_least(nk, floor=8)
    kl = np.zeros(K, np.int64)
    kr_read = np.full(K, M, np.int64)
    if m:
        kr = (p.mop_kind == MOP_READ) & (p.mop_rd_len >= 0) & ok[p.mop_txn]
        np.maximum.at(kl, p.mop_key[kr], p.mop_rd_len[kr])
        longest = kr & (p.mop_rd_len == kl[p.mop_key])
        np.minimum.at(kr_read, p.mop_key[longest],
                      np.nonzero(longest)[0])
    key_ord_read = np.where(kr_read < M, kr_read, -1).astype(np.int32)

    # ---- process / realtime orders --------------------------------------
    graph = ok | (p.txn_type == TXN_INFO)
    pslot = np.full(T, BIG_I, np.int64)
    pslot[:n] = np.where(graph, p.txn_process, BIG_I)
    inv_pad = np.zeros(T, np.int64)
    inv_pad[:n] = p.txn_invoke_pos
    proc_order = np.lexsort((np.arange(T), inv_pad, pslot)).astype(np.int32)
    bslot = np.full(T, BIG_I, np.int64)
    bslot[:n] = np.where(ok, p.txn_complete_pos, BIG_I)
    border = np.argsort(bslot, kind="stable").astype(np.int32)
    comp_sorted = np.where(bslot[border] < BIG_I, bslot[border], BIG_I)
    bi = (np.searchsorted(comp_sorted, inv_pad, side="left") - 1) \
        .astype(np.int32)
    return {
        "run_sort": run_sort, "inv_run": inv_run,
        "key_ord_len": kl.astype(np.int32), "key_ord_read": key_ord_read,
        "proc_order": proc_order, "barrier_order": border,
        "barrier_bi": bi,
    }


def pad_packed(p: PackedTxns, t_pad: int = 0, m_pad: int = 0,
               r_pad: int = 0, v_pad: int = 0, o_pad: int = 0,
               ir_facts: Optional[dict] = None) -> PaddedLA:
    """Pad a PackedTxns to pow2 capacities (host-side, cheap numpy).

    `v_pad`/`o_pad` pin the value-table / order-table capacities (batch
    paths share one executable across groups); 0 = derive from the data
    (`_ir_facts`).  `ir_facts` (a dict `_ir_facts(p)` produced for THIS
    packing) skips re-deriving the facts — batch paths computed them in
    `batch_caps` already."""
    T = t_pad or pow2_at_least(p.n_txns)
    M = m_pad or pow2_at_least(p.n_mops)
    R = r_pad or pow2_at_least(max(len(p.rd_elems), p.n_vals, p.n_keys + 1))
    txn_major, run_cap, complete_monotone = _layout_facts(p)
    ir = dict(ir_facts) if ir_facts is not None else _ir_facts(p)
    if v_pad:
        ir["v_cap"] = v_pad
    if o_pad:
        ir["o_cap"] = o_pad
    # capacities never exceed R (the legacy sizing): a degenerate history
    # whose id space outruns its element table keeps the old layout
    ir["v_cap"] = min(ir["v_cap"], R) if ir["v_cap"] else 0
    ir["o_cap"] = min(ir["o_cap"], R) if ir["o_cap"] else 0
    cols = _ir_columns(p, T, M, txn_major, run_cap)
    if cols is not None:
        ir.update({k: jnp.asarray(v) for k, v in cols.items()})

    def pad(a, n, fill=0):
        out = np.full(n, fill, dtype=a.dtype)
        out[: len(a)] = a
        return jnp.asarray(out)

    return PaddedLA(
        txn_type=pad(p.txn_type, T),
        txn_process=pad(p.txn_process, T),
        txn_invoke_pos=pad(p.txn_invoke_pos, T),
        txn_complete_pos=pad(p.txn_complete_pos, T),
        txn_mask=jnp.asarray(np.arange(T) < p.n_txns),
        mop_txn=pad(p.mop_txn, M),
        mop_kind=pad(p.mop_kind, M, fill=-1),
        mop_key=pad(p.mop_key, M),
        mop_val=pad(p.mop_val, M, fill=-1),
        mop_rd_start=pad(p.mop_rd_start, M, fill=-1),
        mop_rd_len=pad(p.mop_rd_len, M, fill=-1),
        mop_mask=jnp.asarray(np.arange(M) < p.n_mops),
        rd_elems=pad(p.rd_elems, R, fill=-1),
        rd_elem_mask=jnp.asarray(np.arange(R) < len(p.rd_elems)),
        n_keys=p.n_keys,
        n_vals=p.n_vals,
        txn_major=txn_major,
        run_cap=run_cap,
        complete_monotone=complete_monotone,
        **ir,
    )


@partial(jax.jit, static_argnames=("n_keys",))
def infer(h: PaddedLA, n_keys: int) -> Dict[str, dict]:
    """Full inference: anomaly flags + dependency edges + chains + ranks."""
    T = h.txn_type.shape[0]
    M = h.mop_txn.shape[0]
    R = h.rd_elems.shape[0]
    # value-id / version-order-table capacities: the host-verified IR
    # facts size these at pow2(actual need) — 4x under R at 1M bench
    # shapes, and XLA:CPU scatters cost per update (0 = legacy layout)
    V = h.v_cap or R
    O = h.o_cap or R
    nk = max(n_keys, 1)

    ok = h.txn_type == TXN_OK
    graph_txn = ok | (h.txn_type == TXN_INFO)  # fail txns carry no edges

    is_append = h.mop_mask & (h.mop_kind == MOP_APPEND) & (h.mop_val >= 0)
    is_read = h.mop_mask & (h.mop_kind == MOP_READ)
    mop_txn_c = jnp.clip(h.mop_txn, 0, T - 1)
    reader_ok = ok[mop_txn_c]
    known_read = is_read & (h.mop_rd_len >= 0) & reader_ok
    mop_pos = jnp.arange(M, dtype=jnp.int32)

    # ---- writers ---------------------------------------------------------
    if h.app_val_mono:
        # append val ids are nondecreasing in mop order (host-verified):
        # forward-fill gives a globally nondecreasing index vector whose
        # non-append rows carry a no-op payload, unlocking XLA's
        # sorted-scatter path (~3.5x the unsorted one on this CPU)
        w_idx = jnp.clip(
            jax.lax.cummax(jnp.where(is_append, h.mop_val, -1)), 0, V)
        writer = jnp.full(V + 1, -1, jnp.int32).at[w_idx].max(
            jnp.where(is_append, h.mop_txn, -1),
            indices_are_sorted=True)[:V]
        app_count = jnp.zeros(V + 1, jnp.int32).at[w_idx].add(
            is_append.astype(jnp.int32), indices_are_sorted=True)[:V]
    else:
        val_slot = jnp.where(is_append, h.mop_val, V)
        writer = jnp.full(V + 1, -1, jnp.int32).at[val_slot].max(
            jnp.where(is_append, h.mop_txn, -1))[:V]
        app_count = jnp.zeros(V + 1, jnp.int32).at[val_slot].add(
            is_append.astype(jnp.int32))[:V]
    writer_type = jnp.where(
        writer >= 0, h.txn_type[jnp.clip(writer, 0, T - 1)], 0)
    duplicate_appends = jnp.sum((app_count > 1).astype(jnp.int32))

    # ---- (txn, key, pos) run order ---------------------------------------
    # shared by final-append detection and the internal-consistency pass
    # (historically two separate M-sized lexsorts; M-sorts are a top
    # cost).  Two sort keys, not three: a STABLE sort breaks (txn, key)
    # ties in operand order, which is already mop position — and the
    # sorted iota payload IS the permutation.
    txn_eff = jnp.where(h.mop_mask, h.mop_txn, T)
    key_eff = jnp.where(h.mop_mask, h.mop_key, nk)
    if h.run_sort is not None:
        # IR columns (pad-time host derivation, docs/IR.md): the
        # permutation arrives as input — no in-program ranking or
        # inverse-permutation scatter at all
        run_sort = h.run_sort
        inv_run = h.inv_run
        t2 = txn_eff[run_sort]
        k2 = key_eff[run_sort]
    elif h.txn_major and h.run_cap:
        # Sort-free: mops are packed txn-major (host-verified static
        # flag), so the global (txn, key, pos) order decomposes into a
        # within-txn ranking by (key, pos) over runs of <= run_cap mops.
        # rank(i) = |{j in txn(i): (key_j, j) < (key_i, i)}| via
        # 2*(run_cap-1) shifted compares — O(M * run_cap) elementwise
        # work instead of an O(M log^2 M) device bitonic sort (the top
        # TPU inference cost at 1M shapes, PROFILE.md §2d).  Exactness:
        # stability matches lax.sort (earlier pos wins key ties: the
        # backward compare uses <=, the forward one <), and the padding
        # tail maps to itself, exactly where the masked sort keys
        # (T, nk) would stably place it.
        rank = jnp.zeros(M, jnp.int32)
        for d in range(1, h.run_cap):
            same_p = txn_eff[d:] == txn_eff[:-d]
            zpad = jnp.zeros(d, bool)
            le_p = key_eff[:-d] <= key_eff[d:]
            lt_n = key_eff[d:] < key_eff[:-d]
            rank += jnp.concatenate([zpad, same_p & le_p]).astype(jnp.int32) \
                + jnp.concatenate([same_p & lt_n, zpad]).astype(jnp.int32)
        # txn_major: mop_txn is nondecreasing with the padding tail at T,
        # so the scatter indices are sorted — tell XLA
        first_mop = jnp.full(T + 1, M, jnp.int32).at[
            jnp.where(h.mop_mask, mop_txn_c, T)].min(
            jnp.where(h.mop_mask, mop_pos, M), indices_are_sorted=True)[:T]
        inv_run = jnp.where(h.mop_mask, first_mop[mop_txn_c] + rank,
                            mop_pos)
        run_sort = jnp.zeros(M, jnp.int32).at[inv_run].set(mop_pos)
        t2 = txn_eff[run_sort]
        k2 = key_eff[run_sort]
    else:
        t2, k2, run_sort = jax.lax.sort(
            (txn_eff, key_eff, mop_pos), num_keys=2, is_stable=True)
        inv_run = jnp.zeros(M, jnp.int32).at[run_sort].set(mop_pos)
    app2 = is_append[run_sort]
    known2 = known_read[run_sort]
    len2 = h.mop_rd_len[run_sort]
    val2 = h.mop_val[run_sort]
    run_start = jnp.concatenate([jnp.ones(1, bool),
                                 (t2[1:] != t2[:-1]) | (k2[1:] != k2[:-1])])
    run_end = jnp.concatenate([run_start[1:], jnp.ones(1, bool)])
    q = jnp.arange(M, dtype=jnp.int32)

    # final vs intermediate appends: an append is final iff it is the last
    # append of its (txn, key) run — i.e. its run's exclusive suffix holds
    # no append.  Reverse segmented cummax of append positions (scan the
    # reversed axis; segment starts there are the reversed run ends).
    suf_app_q = segmented_cummax(
        jnp.where(app2, q, -1)[::-1], run_end[::-1],
        exclusive=True, neutral=-1)[::-1]
    run_final = app2 & (suf_app_q < 0)
    if h.app_val_mono:
        # scatter in mop order through the same sorted index vector the
        # writer table uses (run_final gathered back via inv_run)
        is_final = jnp.zeros(V + 1, bool).at[w_idx].max(
            is_append & run_final[inv_run], indices_are_sorted=True)[:V]
    else:
        is_final = jnp.zeros(V + 1, bool).at[
            jnp.where(app2, val2, V)].max(run_final)[:V]

    # ---- version orders (longest known read per key) ---------------------
    if h.key_ord_len is not None and h.key_ord_len.shape[0] >= nk:
        # IR columns: per-key longest-read table precomputed at pad time
        ord_len = h.key_ord_len[:nk]
        ord_read = h.key_ord_read[:nk]
    else:
        key_slot = jnp.where(known_read, h.mop_key, nk)
        ord_len = jnp.zeros(nk + 1, jnp.int32).at[key_slot].max(
            jnp.where(known_read, h.mop_rd_len, 0))[:nk]
        # pick one longest read per key (two-pass scatter; no 64-bit
        # packing); ties take the earliest read, matching the host oracle
        is_longest = known_read & (h.mop_rd_len == ord_len[
            jnp.clip(h.mop_key, 0, nk - 1)])
        ord_read_raw = jnp.full(nk + 1, M, jnp.int32).at[
            jnp.where(is_longest, h.mop_key, nk)].min(
            jnp.where(is_longest, mop_pos, M))[:nk]
        ord_read = jnp.where(ord_read_raw < M, ord_read_raw, -1)
    ord_start = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(ord_len)[:-1].astype(jnp.int32)])
    total_ord = jnp.sum(ord_len)

    # materialize ord_elems: slot j belongs to key k(j) at offset o(j).
    # slot_key = max key whose segment start <= slot (starts are monotone;
    # zero-length keys share a start and the scatter-max picks the last,
    # which is the containing one) — a scatter + cummax forward fill, an
    # O(O) replacement for the former O(O log nk) searchsorted.  The
    # whole table lives in the O-capacity space (sum of per-key longest
    # reads), not R: at 1M bench shapes that is 2^22 vs 2^24.
    slot = jnp.arange(O, dtype=jnp.int32)
    slot_valid = slot < total_ord
    if nk == 1:
        # single key: every slot is key 0.  Also dodges a real compile
        # cost: with nk == 1 the scatter seed below is compile-time
        # constant (ord_start == [0], key_ids == [0]) and XLA:CPU
        # constant-folds the cummax's R-sized reduce-window tree
        # interpretively — measured 1-18 s of compile per shape.
        slot_key = jnp.zeros(O, jnp.int32)
        slot_off = slot
        src_read0 = ord_read[0]
        src_start = jnp.where(
            src_read0 >= 0,
            h.mop_rd_start[jnp.clip(src_read0, 0, M - 1)], 0)
    elif pallas_fill.fill_enabled():
        # TPU: the three slot_key-indexed expansions (slot_key itself,
        # ord_start[slot_key], rd_start[ord_read[slot_key]]) are
        # monotone/segment-constant fills — seed per-key values at the
        # segment starts and forward-fill with the single-pass Pallas
        # LOCF kernel instead of R-sized gathers (measured ~0.45 s per
        # gather at R = 2^24 on chip).  slot_key's seed is scatter-MAX
        # over possibly-shared starts (zero-length keys) exactly as the
        # lax path, and its seeded values are non-decreasing, so LOCF
        # is bitwise cummax.  The value channels seed only n_elems > 0
        # keys (unique starts): every valid slot's containing key has
        # elements, and invalid slots are masked by slot_valid.
        key_ids = jnp.arange(nk, dtype=jnp.int32)
        sk_seed = jnp.full(O + 1, -1, jnp.int32).at[
            jnp.clip(ord_start, 0, O)].max(
            key_ids, indices_are_sorted=True)[:O]
        slot_key = jnp.clip(pallas_fill.locf_flat(sk_seed), 0, nk - 1)
        nonempty = ord_len > 0
        pos_ne = jnp.where(nonempty, ord_start, O)
        osv_seed = jnp.full(O + 1, -1, jnp.int32).at[
            jnp.clip(pos_ne, 0, O)].max(
            jnp.where(nonempty, ord_start, -1))[:O]
        # per-key rd_start of the chosen longest read (ord_len > 0
        # implies ord_read >= 0)
        srcst_k = h.mop_rd_start[jnp.clip(ord_read, 0, M - 1)]
        srcst_seed = jnp.full(O + 1, -1, jnp.int32).at[
            jnp.clip(pos_ne, 0, O)].max(
            jnp.where(nonempty, srcst_k, -1))[:O]
        ord_start_f = pallas_fill.locf_flat(osv_seed)
        src_start = pallas_fill.locf_flat(srcst_seed)
        slot_off = slot - jnp.where(ord_start_f >= 0, ord_start_f, 0)
        src_start = jnp.where(src_start >= 0, src_start, 0)
    else:
        key_ids = jnp.arange(nk, dtype=jnp.int32)
        # ord_start is a cumsum, so the seed indices are sorted by
        # construction — no layout fact needed
        sk_seed = jnp.full(O + 1, -1, jnp.int32).at[
            jnp.clip(ord_start, 0, O)].max(
            key_ids, indices_are_sorted=True)[:O]
        slot_key = jnp.clip(jax.lax.cummax(sk_seed), 0, nk - 1)
        slot_off = slot - ord_start[slot_key]
        src_read = ord_read[slot_key]
        src_start = jnp.where(src_read >= 0,
                              h.mop_rd_start[jnp.clip(src_read, 0, M - 1)], 0)
    ord_elems = jnp.where(
        slot_valid, h.rd_elems[jnp.clip(src_start + slot_off, 0, R - 1)], -1)
    cv = jnp.clip(ord_elems, 0, V - 1)

    # ---- read-element table ----------------------------------------------
    # elem -> owning read mop: scatter read ids at their start slots, then
    # forward-fill with a parallel cummax (read extents are contiguous and
    # allocated in mop order, so ids are increasing)
    has_elems = known_read & (h.mop_rd_len > 0)
    if h.rd_start_mono:
        # rd_start strictly increases over has-elems reads (host-verified
        # allocation-order fact): forward-fill the masked rows onto the
        # previous read's start (whose payload then loses the max) so
        # the scatter indices are sorted
        seed = jnp.full(R + 1, -1, jnp.int32).at[
            jnp.clip(jax.lax.cummax(
                jnp.where(has_elems, h.mop_rd_start, -1)), 0, R)].max(
            jnp.where(has_elems, mop_pos, -1),
            indices_are_sorted=True)[:R]
    else:
        seed = jnp.full(R + 1, -1, jnp.int32).at[
            jnp.where(has_elems, h.mop_rd_start, R)].max(
            jnp.where(has_elems, mop_pos, -1))[:R]

    def _aseed(vals):
        # value channel seeded at the same (unique) read-start slots
        return jnp.full(R + 1, -1, jnp.int32).at[
            jnp.where(has_elems, h.mop_rd_start, R)].max(
            jnp.where(has_elems, vals.astype(jnp.int32), -1))[:R]

    if pallas_fill.fill_enabled():
        # TPU: forward-fill the owning-read id AND the four per-read
        # table values in one Pallas pass each, replacing lax.cummax
        # plus four R-sized `table[er]` gathers (~0.45 s each at
        # R = 2^24 on chip, PROFILE.md round-5 trace).  elem_read is
        # bitwise cummax (monotone seeds); the value channels replicate
        # the legacy `table[clip(er, 0, M-1)]` exactly, including
        # table[0] on the leading er == -1 prefix.
        elem_read = pallas_fill.locf_flat(seed)
        hole = elem_read < 0
        erd_start = jnp.where(hole, h.mop_rd_start[0],
                              pallas_fill.locf_flat(_aseed(h.mop_rd_start)))
        erd_len = jnp.where(hole, h.mop_rd_len[0],
                            pallas_fill.locf_flat(_aseed(h.mop_rd_len)))
        elem_key = jnp.where(hole, h.mop_key[0],
                             pallas_fill.locf_flat(_aseed(h.mop_key)))
        elem_txn = jnp.where(hole, h.mop_txn[0],
                             pallas_fill.locf_flat(_aseed(h.mop_txn)))
        er = jnp.clip(elem_read, 0, M - 1)
    else:
        elem_read = jax.lax.cummax(seed)
        er = jnp.clip(elem_read, 0, M - 1)
        erd_start = h.mop_rd_start[er]
        erd_len = h.mop_rd_len[er]
        elem_key = h.mop_key[er]
        elem_txn = h.mop_txn[er]
    elem_off = jnp.arange(R, dtype=jnp.int32) - erd_start
    elem_in_read = h.rd_elem_mask & (elem_read >= 0) & (elem_off >= 0) & \
        (elem_off < erd_len)
    ev = jnp.clip(h.rd_elems, 0, V - 1)

    # incompatible-order: element disagrees with its key's version order
    expect = ord_elems[jnp.clip(
        ord_start[jnp.clip(elem_key, 0, nk - 1)] + elem_off, 0, O - 1)]
    incompat = elem_in_read & (h.rd_elems != expect)
    incompatible_order = jnp.sum(incompat.astype(jnp.int32))
    incompat_witness = jnp.argmax(incompat)

    # G1a: reading a failed txn's append
    g1a = elem_in_read & (writer_type[ev] == TXN_FAIL)
    g1a_count = jnp.sum(g1a.astype(jnp.int32))
    g1a_witness = jnp.argmax(g1a)

    # duplicate elements inside one read.  Fast path: value ids are
    # key-scoped (interned per (key, content)), so duplicates in the
    # version ORDERS are one scatter-add over the order table; and when
    # every read element agrees with its key's order
    # (incompatible_order == 0), a read holds a duplicate iff its key's
    # order does (reads are elementwise prefixes of the orders).  Only a
    # disagreeing — already-invalid — history can hide a read-dup from
    # the orders, and only then does the exact per-read R-sized sort run
    # (that sort is ~70% of inference runtime at 1M: PROFILE.md §2d).
    # Caveats: (a) under vmap (the batched checking paths) lax.cond
    # lowers to select_n and BOTH branches run — batched checks keep
    # paying the sort, as before this change, plus the cheap scatter;
    # (b) the reported COUNT is per-order multiplicity on the fast path
    # and per-read adjacent pairs on the slow one — presence (> 0) is
    # the exactness contract, matched against the oracle either way.
    ord_cnt = jnp.zeros(V + 1, jnp.int32).at[
        jnp.where(slot_valid, cv, V)].add(1)[:V]
    dup_fast = jnp.sum(jnp.maximum(ord_cnt - 1, 0))

    def dup_slow(_):
        # adjacent equal (read, value) pairs after one stable single-key
        # sort by value — exact because elem_read is monotone over
        # slots, so within an equal-value block one read's slots stay
        # contiguous
        d_val, d_read = jax.lax.sort(
            (jnp.where(elem_in_read, ev, V),
             jnp.where(elem_in_read, elem_read, M)),
            num_keys=1, is_stable=True)
        dups = (d_read[1:] == d_read[:-1]) & (d_val[1:] == d_val[:-1]) & \
            (d_read[1:] < M)
        return jnp.sum(dups.astype(jnp.int32))

    # presence flag only (0/1): the two branches count different things
    # (per-order multiplicity vs per-read adjacent pairs), so surfacing
    # the raw number would make the same history report path-dependent
    # counts on batched vs single paths — presence is the contract
    duplicate_elements = jnp.minimum(jax.lax.cond(
        incompatible_order > 0, dup_slow, lambda _: dup_fast,
        operand=None), 1)

    # G1b: last element of a read is an intermediate append of another txn
    is_last_elem = elem_in_read & (elem_off == erd_len - 1)
    g1b = is_last_elem & (writer[ev] >= 0) & (~is_final[ev]) & \
        (writer[ev] != elem_txn)
    g1b_count = jnp.sum(g1b.astype(jnp.int32))
    g1b_witness = jnp.argmax(g1b)

    # dirty-update: aborted write immediately followed by a committed one
    nxt_slot_same_key = slot_valid & (slot + 1 < total_ord) & \
        (slot_key == slot_key[jnp.clip(slot + 1, 0, O - 1)])
    nv = jnp.clip(ord_elems[jnp.clip(slot + 1, 0, O - 1)], 0, V - 1)
    dirty = nxt_slot_same_key & (writer_type[cv] == TXN_FAIL) & \
        (writer_type[nv] == TXN_OK)
    dirty_update = jnp.sum(dirty.astype(jnp.int32))

    # ---- internal consistency --------------------------------------------
    # mops sorted by (txn, key, pos) form per-(txn,key) runs.  Within a run:
    #   n_app_before[q]  — appends since the last known read (exclusive)
    #   prev_q[q]        — run position of the last known read before q
    # Then a read of length L with previous read of length P must satisfy
    # L == P + n_app_before, and its elements at offsets [base, base+n)
    # (base = P, or L - n when no previous read) must equal the appended
    # values at run positions q-n .. q-1, in order.  Exact given
    # prefix-compatible reads (see module docstring).
    # (run_sort order and its per-run arrays are computed above, beside
    # the final-append detection that shares them)
    cum_app_excl = segmented_cumsum(app2.astype(jnp.int32), run_start,
                                    exclusive=True)
    prev_q = segmented_cummax(jnp.where(known2, q, -1), run_start,
                              exclusive=True, neutral=-1)
    have_prev = prev_q >= 0
    prev_app_base = jnp.where(
        have_prev,
        (cum_app_excl + app2.astype(jnp.int32))[jnp.clip(prev_q, 0, M - 1)],
        0)
    n_app_before = cum_app_excl - prev_app_base
    prev_len = jnp.where(have_prev, len2[jnp.clip(prev_q, 0, M - 1)], 0)

    bad_len = known2 & have_prev & (len2 != prev_len + n_app_before)
    bad_suffix = known2 & ~have_prev & (len2 < n_app_before)
    internal_len_bad = jnp.sum((bad_len | bad_suffix).astype(jnp.int32))

    # element-side content check: element at offset o of read m belongs to
    # the appends-since-last-read window iff o >= base; it must then equal
    # the append at run position q(m) - n + (o - base)
    if pallas_fill.fill_enabled():
        # same Pallas LOCF expansion as the read-element table above:
        # all four are per-read constants, so compose them per-mop
        # (M-sized gathers, ~4x cheaper than R-sized on chip), seed at
        # the read starts, and fill — replacing four more R-sized
        # gathers.  The leading er == -1 prefix replicates the legacy
        # clip-to-mop-0 values.
        erc = jnp.clip(inv_run, 0, M - 1)
        comp_n = n_app_before[erc]
        comp_have = have_prev[erc].astype(jnp.int32)
        comp_prev_len = prev_len[erc]

        def _rfill(valsM):
            f = pallas_fill.locf_flat(_aseed(valsM))
            return jnp.where(hole, valsM[0].astype(jnp.int32), f)

        er_run = _rfill(inv_run)
        er_n = _rfill(comp_n)
        er_have = _rfill(comp_have) != 0
        er_prev_len = _rfill(comp_prev_len)
    else:
        er_run = inv_run[er]                      # run position of the read
        er_n = n_app_before[jnp.clip(er_run, 0, M - 1)]
        er_have = have_prev[jnp.clip(er_run, 0, M - 1)]
        er_prev_len = prev_len[jnp.clip(er_run, 0, M - 1)]
    base = jnp.where(er_have, er_prev_len, erd_len - er_n)
    j = elem_off - base
    in_window = elem_in_read & (j >= 0) & (j < er_n)
    exp_val = val2[jnp.clip(er_run - er_n + j, 0, M - 1)]
    internal_content = in_window & (h.rd_elems != exp_val)
    internal = internal_len_bad + jnp.sum(internal_content.astype(jnp.int32))

    # ---- dependency edges -------------------------------------------------
    ww_src = jnp.where(slot_valid, writer[cv], -1)
    ww_dst = jnp.where(nxt_slot_same_key, writer[nv], -1)
    ww_ok = nxt_slot_same_key & (ww_src >= 0) & (ww_dst >= 0) & \
        (ww_src != ww_dst) & \
        graph_txn[jnp.clip(ww_src, 0, T - 1)] & \
        graph_txn[jnp.clip(ww_dst, 0, T - 1)]

    last_val = jnp.where(
        has_elems,
        h.rd_elems[jnp.clip(h.mop_rd_start + h.mop_rd_len - 1, 0, R - 1)], -1)
    wr_src = jnp.where(last_val >= 0, writer[jnp.clip(last_val, 0, V - 1)], -1)
    wr_dst = h.mop_txn
    wr_ok = has_elems & (wr_src >= 0) & (wr_src != wr_dst) & \
        graph_txn[jnp.clip(wr_src, 0, T - 1)]

    key_c = jnp.clip(h.mop_key, 0, nk - 1)
    has_next = known_read & (h.mop_rd_len < ord_len[key_c])
    nxt_val = jnp.where(
        has_next,
        ord_elems[jnp.clip(ord_start[key_c] + h.mop_rd_len, 0, O - 1)], -1)
    rw_dst = jnp.where(nxt_val >= 0, writer[jnp.clip(nxt_val, 0, V - 1)], -1)
    rw_src = h.mop_txn
    rw_ok = has_next & (rw_dst >= 0) & (rw_dst != rw_src) & \
        graph_txn[jnp.clip(rw_dst, 0, T - 1)]

    # ---- node ranks -------------------------------------------------------
    # txn = 2*complete_pos (even), barrier = 2*complete_pos + 1 (odd);
    # padding gets unique high ranks with no edges attached
    tidx = jnp.arange(T, dtype=jnp.int32)
    rank_txn = jnp.where(h.txn_mask, 2 * h.txn_complete_pos, BIG + tidx)

    # ---- chains -----------------------------------------------------------
    # process chains: ok/info txns by (process, invoke_pos); complete_pos is
    # monotone along a process chain, so ranks increase as required
    pslot = jnp.where(h.txn_mask & graph_txn, h.txn_process, BIG)
    if h.proc_order is not None:
        # IR column: the (process, invoke) order precomputed at pad time
        porder = h.proc_order
        p_sorted = pslot[porder]
    elif h.proc_seq:
        # within each process, invoke order == txn row order
        # (host-verified: a jepsen process is sequential), so a stable
        # 1-key sort by process reproduces the (process, invoke) order
        # for every chain row; the BIG-keyed masked rows may permute
        # among themselves but never enter the chain (p_mask)
        p_sorted, porder = jax.lax.sort((pslot, tidx), num_keys=1,
                                        is_stable=True)
    else:
        p_sorted, _, porder = jax.lax.sort(
            (pslot, h.txn_invoke_pos, tidx), num_keys=2, is_stable=True)
    p_nodes = porder.astype(jnp.int32)
    p_mask = p_sorted < BIG
    p_starts = jnp.concatenate([jnp.ones(1, bool),
                                p_sorted[1:] != p_sorted[:-1]])

    # realtime barriers: one per ok txn, ordered by completion
    bslot = jnp.where(h.txn_mask & ok, h.txn_complete_pos, BIG)
    if h.barrier_order is not None:
        # IR column: ok-completion order precomputed at pad time
        border = h.barrier_order
    elif h.complete_monotone:
        # complete_pos is strictly increasing over valid txns
        # (host-verified static flag: TxnPacker emits txns in completion
        # order), so argsort(bslot) is a stable partition — ok txns keep
        # index order, everything else follows — an O(T) cumsum+scatter
        # instead of a T-sized device sort
        okm = bslot < BIG
        n_ok_incl = jnp.cumsum(okm.astype(jnp.int32))
        dest_b = jnp.where(
            okm, n_ok_incl - 1,
            n_ok_incl[-1] + jnp.cumsum((~okm).astype(jnp.int32)) - 1)
        border = jnp.zeros(T, jnp.int32).at[dest_b].set(tidx)
    else:
        border = jnp.argsort(bslot)
    b_txn = border.astype(jnp.int32)
    b_mask = bslot[border] < BIG
    barrier_node = (T + tidx).astype(jnp.int32)
    rank_barrier = jnp.where(b_mask, 2 * bslot[border] + 1, BIG + T + tidx)
    b_starts = jnp.concatenate([jnp.ones(1, bool), jnp.zeros(T - 1, bool)])
    tb_src = b_txn
    tb_dst = barrier_node
    tb_ok = b_mask
    if h.barrier_bi is not None:
        bi = h.barrier_bi
    else:
        comp_sorted = jnp.where(b_mask, bslot[border], BIG)
        bi = jnp.searchsorted(comp_sorted, h.txn_invoke_pos,
                              side="left") - 1
    bt_ok = h.txn_mask & graph_txn & (bi >= 0)
    bt_src = (T + jnp.clip(bi, 0, T - 1)).astype(jnp.int32)
    bt_dst = tidx

    return {
        "counts": {
            "duplicate-appends": duplicate_appends,
            "duplicate-elements": duplicate_elements,
            "incompatible-order": incompatible_order,
            "G1a": g1a_count,
            "G1b": g1b_count,
            "dirty-update": dirty_update,
            "internal": internal,
        },
        "witness": {
            "incompatible-order": incompat_witness,
            "G1a": g1a_witness,
            "G1b": g1b_witness,
        },
        "edges": {
            "ww": (ww_src, ww_dst, ww_ok),
            "wr": (wr_src, wr_dst, wr_ok),
            "rw": (rw_src, rw_dst, rw_ok),
            "tb": (tb_src, tb_dst, tb_ok),
            "bt": (bt_src, bt_dst, bt_ok),
        },
        "chains": {
            "process": (p_nodes, p_starts, p_mask),
            "barrier": (barrier_node, b_starts, b_mask),
        },
        "ranks": {
            "txn": rank_txn.astype(jnp.int32),
            "barrier": rank_barrier.astype(jnp.int32),
        },
        "order": {
            "elems": ord_elems, "start": ord_start, "len": ord_len,
            "writer": writer,
        },
    }
