"""Shared cycle-anomaly detection over host-built txn dependency edges.

Used by checkers whose edge inference runs host-side (rw-register) but
whose cycle *detection* still rides the device rank-sweep kernel — the
same split `list_append` uses with device-built edges.  Falls back to host
Tarjan + spec search when the device is unavailable or the sweep doesn't
converge (exactness first).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from jepsen_tpu.checkers.elle.graph import (
    REL_NAMES,
    CycleSpec,
    EdgeList,
    find_cycle,
    nontrivial_sccs,
)
from jepsen_tpu.checkers.elle.specs import CYCLE_ANOMALY_SPECS, SPEC_ORDER


def cycle_anomalies(edges: EdgeList, n_nodes: int, rank: np.ndarray,
                    want: set, use_device: bool = True,
                    max_reported: int = 4, explainer=None,
                    n_txns: int = None,
                    orig_index: np.ndarray = None) -> Dict[str, List[dict]]:
    """Find cycle anomalies among `want` specs over the given edges.

    rank: per-node order where most edges go forward (completion order);
    used by the device sweep.  Returns {anomaly: [witness dicts]}.

    `explainer(src, rel_name, dst) -> dict` (see `explain.py`) adds
    per-edge justification fields to each reported cycle edge — the
    reference's Explainer protocol.  When `n_txns` is given, nodes >=
    n_txns (realtime barrier nodes) are collapsed out of reported
    cycles; `orig_index` maps internal txn ids to history indices.
    """
    specs = [(name, CYCLE_ANOMALY_SPECS[name]) for name in SPEC_ORDER
             if name in want]
    projections: Dict[frozenset, List[Tuple[str, CycleSpec]]] = {}
    for name, spec in specs:
        projections.setdefault(spec.rels, []).append((name, spec))

    found: Dict[str, List[dict]] = {}
    for rels, group in projections.items():
        proj = edges.project(rels)
        if not len(proj):
            continue
        regions = _cycle_regions(proj, n_nodes, rank, use_device)
        if regions is None:
            continue
        for name, spec in group:
            for region in regions[:max_reported * 4]:
                hit = find_cycle(region, proj, spec)
                if hit is not None:
                    found.setdefault(name, []).append(
                        {"cycle": _render_cycle(hit, explainer, n_txns,
                                                orig_index)})
                    break
    return found


def _render_cycle(hit, explainer, n_txns, orig_index) -> List[dict]:
    """Emit reported edges: collapse barrier hops (nodes >= n_txns) into
    single realtime edges, map ids to history indices, and attach the
    Explainer's justification per edge."""
    if n_txns is None:
        return [{"src": int(s), "rel": REL_NAMES[r], "dst": int(d)}
                for (s, r, d) in hit]
    out = []
    pend_src = None
    k = next((i for i, (s, _, _) in enumerate(hit) if s < n_txns), 0)
    hit = hit[k:] + hit[:k]
    for (s, r, d) in hit:
        if d >= n_txns:
            if s < n_txns:
                pend_src = s
            continue
        src = s if s < n_txns else pend_src
        rel_name = REL_NAMES[r]
        edge = {"src": int(orig_index[src]) if orig_index is not None and
                src is not None and src < len(orig_index) else src,
                "rel": rel_name,
                "dst": int(orig_index[d]) if orig_index is not None and
                d < len(orig_index) else int(d)}
        if explainer is not None and src is not None:
            edge.update(explainer(int(src), rel_name, int(d)))
        out.append(edge)
    return out


def _cycle_regions(proj: EdgeList, n_nodes: int, rank: np.ndarray,
                   use_device: bool):
    """Node regions containing cycles, or None if the projection is
    acyclic.  Device path: rank sweep -> witness backward edges -> local
    BFS regions.  Host path: Tarjan SCCs."""
    if use_device:
        try:
            import jax.numpy as jnp

            from jepsen_tpu.ops.cycle_sweep import SweepGraph, detect_cycles

            g = SweepGraph(
                n_nodes=n_nodes, rank=jnp.asarray(rank),
                nc_src=jnp.asarray(proj.src), nc_dst=jnp.asarray(proj.dst),
                nc_mask=jnp.ones(len(proj.src), bool),
                chain_nodes=jnp.zeros(0, jnp.int32),
                chain_starts=jnp.zeros(0, bool),
                chain_mask=jnp.zeros(0, bool))
            res = detect_cycles(g)
            if res.converged:
                if not res.has_cycle:
                    return None
                from jepsen_tpu.checkers.elle.list_append import (
                    _witness_regions,
                )
                regions = _witness_regions(
                    proj, proj.src, proj.dst, res.witness_edge_ids, n_nodes)
                if regions:
                    return regions
        except Exception:
            pass  # fall through to exact host path
    sccs = nontrivial_sccs(n_nodes, proj.src, proj.dst)
    return sccs if sccs else None
