"""Single-jit core verdict function for list-append histories.

`core_check` = device_infer + cycle sweeps over a fixed projection set,
fused into one jittable, vmap-able, shard_map-able function of the padded
SoA arrays.  Returns a compact anomaly bitmap — the form used by the
benchmark, the graft entry point, and the batched/sharded checking path
(BASELINE.json config 5).  Host-side cycle classification (naming the
exact cycle) lives in `list_append.check`; this core answers the
valid/invalid question entirely on device.

Projection set (covers strict-serializable checking, the strongest graded
config):
  0: ww                       (G0)
  1: ww+wr                    (G1c)
  2: ww+wr+rw                 (G-single / G2-item family)
  3: ww+wr+rw+process         (strong-session variants)
  4: ww+wr+rw+realtime        (strict/strong variants)

Bit layout of the result:  [duplicate-appends, duplicate-elements,
incompatible-order, G1a, G1b, dirty-update, internal,
cycle-proj0..cycle-proj4, converged]
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from jepsen_tpu.checkers.elle.device_infer import PaddedLA, infer
# budget caps live with the sweep kernel; re-exported for callers
from jepsen_tpu.ops.cycle_sweep import (  # noqa: F401
    MAX_K_CAP,
    MAX_ROUNDS_CAP,
    projection_scan,
)

N_COUNT_BITS = 7
PROJECTIONS = (
    ("ww",),
    ("ww", "wr"),
    ("ww", "wr", "rw"),
    ("ww", "wr", "rw", "process"),
    ("ww", "wr", "rw", "realtime"),
)
COUNT_NAMES = ("duplicate-appends", "duplicate-elements",
               "incompatible-order", "G1a", "G1b", "dirty-update",
               "internal")


def _cc(site, jitfn, *args, **static):
    """Route one device dispatch through the AOT compile cache: memory
    table -> persisted executable -> compile+persist, falling through
    to the plain jit call on any failure (see jepsen_tpu.compilecache).
    Statics go by keyword so the cached Compiled can be dispatched with
    the dynamic args alone."""
    from jepsen_tpu import compilecache

    return compilecache.call(site, jitfn, *args, **static)


def proj_include_stack(projections=PROJECTIONS) -> jnp.ndarray:
    """(P, 5) family-include flags for the ww/wr/rw/tb/bt edge families
    (tb/bt are the realtime-barrier families)."""
    return jnp.asarray([
        [int("ww" in p), int("wr" in p), int("rw" in p),
         int("realtime" in p), int("realtime" in p)]
        for p in projections], jnp.int32)


def chain_include_stack(projections=PROJECTIONS) -> jnp.ndarray:
    """(P, 2) chain-group include flags for [process, barrier] chains."""
    return jnp.asarray([
        [int("process" in p), int("realtime" in p)]
        for p in projections], jnp.int32)


def _verdict(out, max_k: int, max_rounds: int):
    """Sweep half of the core check: infer output -> (bits, overflowed).
    Plain function — jitted fused with infer by `core_check`, or as its
    own (much smaller) XLA program by `core_check_staged`."""
    T = out["ranks"]["txn"].shape[0]
    edges = out["edges"]
    chains = out["chains"]
    rank = jnp.concatenate([out["ranks"]["txn"], out["ranks"]["barrier"]])
    e_src = jnp.concatenate([edges[k][0] for k in ("ww", "wr", "rw", "tb",
                                                   "bt")])
    e_dst = jnp.concatenate([edges[k][1] for k in ("ww", "wr", "rw", "tb",
                                                   "bt")])
    masks = {k: edges[k][2] for k in ("ww", "wr", "rw", "tb", "bt")}

    pc_nodes, pc_starts, pc_mask = chains["process"]
    bc_nodes, bc_starts, bc_mask = chains["barrier"]
    chain_nodes = jnp.concatenate([pc_nodes, bc_nodes])
    chain_starts = jnp.concatenate([pc_starts, bc_starts])

    # One sweep instantiation scanned over the 5 projections (a Python loop
    # would inline 5 copies of the while_loop kernel and quintuple XLA
    # compile time — measured 125.8 s at 100k-txn shapes in round 2).  The
    # scan keeps exactly one (N, max_k) label plane live (bounds HBM at
    # 10M ops) and consumes family-include flags instead of (5, E) mask
    # stacks — see projection_scan / PROFILE.md §0b for the hoist.
    conv_all, overflow, cyc_bits = projection_scan(
        2 * T, max_k, max_rounds, rank, e_src, e_dst,
        [masks[k] for k in ("ww", "wr", "rw", "tb", "bt")],
        proj_include_stack(PROJECTIONS),
        chain_nodes, chain_starts, [pc_mask, bc_mask],
        chain_include_stack(PROJECTIONS))

    counts = jnp.stack([out["counts"][n].astype(jnp.int32)
                        for n in COUNT_NAMES])
    bits = jnp.concatenate(
        [counts, cyc_bits, conv_all.astype(jnp.int32)[None]])
    return bits, overflow


@partial(jax.jit, static_argnames=("n_keys", "max_k", "max_rounds"))
def core_check(h: PaddedLA, n_keys: int, max_k: int = 128,
               max_rounds: int = 64) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (bits, overflowed):
    bits: (13,) int32 — counts/flags per the module docstring, last slot is
    converged (1 = trustworthy).
    overflowed: int32 — max backward edges seen beyond max_k (0 = exact).
    """
    return _verdict(infer(h, n_keys), max_k, max_rounds)


@partial(jax.jit, static_argnames=("n_keys",))
def _infer_stage(h: PaddedLA, n_keys: int):
    # only the keys _verdict consumes: materializing the full infer dict
    # would keep the R-sized order table (+ witnesses) live in HBM at
    # exactly the 10M shapes this path exists for — the fused program
    # dead-code-eliminates them, so the staged one must drop them too
    out = infer(h, n_keys)
    return {k: out[k] for k in ("counts", "edges", "chains", "ranks")}


@partial(jax.jit, static_argnames=("max_k", "max_rounds"))
def _sweep_stage(out, max_k: int, max_rounds: int):
    return _verdict(out, max_k, max_rounds)


def core_check_staged(h: PaddedLA, n_keys: int, max_k: int = 128,
                      max_rounds: int = 64,
                      verbose: bool = False
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """core_check as TWO separately-compiled XLA programs (infer, then
    sweep) with the intermediate edge/chain arrays materialized on
    device.

    Bitwise-equal to `core_check` (same `_verdict` body; the only
    difference is the stage boundary).  Exists because the axon
    remote-compile service drops the connection on the single fused
    program at 2^24-txn shapes (PROFILE.md §-1d: `remote_compile:
    Network Error: Unexpected EOF` — server-side XLA death, three
    campaign attempts) while 2^20-shape programs compile fine; halving
    per-program complexity is the lever.  Costs on acyclic histories are
    negligible: the steady state is all inference (PROFILE.md §-1c) and
    the lost infer→sweep fusion only re-reads the materialized COO
    edges (~3 GB at 10M shapes, well under a transient of the fused
    program's own sort workspaces)."""
    import time as _time

    t0 = _time.perf_counter()
    out = _cc("elle.core-check.infer", _infer_stage, h, n_keys=n_keys)
    jax.block_until_ready(out)
    if verbose:
        print(f"  staged: infer {_time.perf_counter() - t0:.1f}s",
              flush=True)
    t0 = _time.perf_counter()
    res = _cc("elle.core-check.sweep", _sweep_stage, out, max_k=max_k,
              max_rounds=max_rounds)
    jax.block_until_ready(res)
    if verbose:
        print(f"  staged: sweep {_time.perf_counter() - t0:.1f}s",
              flush=True)
    return res




# Padded txn capacity where the one fused program stops compiling on the
# axon TPU remote-compile service (2^20-shape programs compile fine,
# 2^24-shape ones die server-side — PROFILE.md §-1d).  The staged split
# is bitwise-equal, so past the wall every caller dispatches to it; on
# non-TPU backends there is no remote compiler and fused always works.
STAGED_T_THRESHOLD = 1 << 24


def _use_staged(h: PaddedLA) -> bool:
    """One definition of the fused-vs-staged boundary, shared by
    core_check_auto and core_check_exact so they can't drift."""
    return h.txn_type.shape[0] >= STAGED_T_THRESHOLD and \
        jax.default_backend() == "tpu"


def _sharded_dispatch(h: PaddedLA, n_keys: int, max_k: int,
                      max_rounds: int, mesh):
    """The sharded-by-default core (ISSUE 12): op arrays placed with
    NamedSharding(P("batch")) for GSPMD inference, K-axis sweep under
    shard_map — verdicts bitwise-identical to `core_check`."""
    from jepsen_tpu.parallel.op_shard import _core_check_sharded, \
        shard_padded

    n = mesh.shape["batch"]
    if max_k % n:
        max_k = ((max_k // n) + 1) * n
    h, _ = shard_padded(h, mesh, "batch")
    return _cc("parallel.op-shard", _core_check_sharded, h,
               n_keys=n_keys, mesh=mesh, axis="batch", max_k=max_k,
               max_rounds=max_rounds)


def core_check_auto(h: PaddedLA, n_keys: int, max_k: int = 128,
                    max_rounds: int = 64):
    """Shape-aware dispatch between the mesh-sharded default (>1 visible
    device and a large enough history — `parallel.slots.default_mesh`),
    `core_check` (fused) and `core_check_staged` — the single boundary
    every large-shape caller (bench, stream.py, core_check_exact)
    shares."""
    from jepsen_tpu.parallel import slots

    mesh = slots.default_mesh(h.txn_type.shape[0])
    if mesh is not None:
        return _sharded_dispatch(h, n_keys, max_k, max_rounds, mesh)
    if _use_staged(h):
        return core_check_staged(h, n_keys, max_k=max_k,
                                 max_rounds=max_rounds)
    return _cc("elle.core-check", core_check, h, n_keys=n_keys,
               max_k=max_k, max_rounds=max_rounds)


def grow_until_exact(run, max_k: int = 128, max_rounds: int = 64,
                     round_to: int = 1, deadline=None,
                     site: str = "elle.core-check", plan=None,
                     policy=None):
    """Host-side rebatch policy, shared by every fused-check caller.

    `run(max_k, max_rounds)` -> (bits, overflowed).  If the sweep
    overflows its backward-edge budget, retry with the budget grown past
    the observed count (rounded up to a multiple of `round_to` — mesh
    size for sharded sweeps); if the fixpoint hits max_rounds, retry with
    doubled rounds.  Gives up (returning the last, inexact result) only
    at the caps — callers then fall back to the host oracle.

    `deadline` (a `resilience.Deadline`) is polled before each fixpoint
    retry: the grow loop is the unbounded part of the fused check, and
    a checker time budget must bound it (expiry raises
    `DeadlineExceeded`, which `check_safe` maps to an unknown verdict).
    Each `run` dispatch goes through the resilience guard — transient
    device failures retry, injected faults land here in chaos mode.
    `site`/`plan`/`policy` let callers label and pin that ONE guard
    (e.g. the sharded sweeps use site "parallel.op-shard") — callers
    must NOT wrap `run` in a second device_call: nested guards multiply
    retries (attempts²) and double-advance the fault plan's call
    counter, breaking the deterministic replay contract.
    """
    import numpy as np

    from jepsen_tpu import resilience

    while True:
        if deadline is not None:
            deadline.check("elle.grow-until-exact")
        bits, over = resilience.device_call(
            site, run, max_k, max_rounds, deadline=deadline, plan=plan,
            policy=policy)
        over_i = int(np.asarray(over))
        conv = int(np.asarray(bits)[-1]) == 1
        if over_i > 0 and max_k < MAX_K_CAP:
            need = max_k + over_i
            while max_k < need:
                max_k *= 2
            max_k = min(max_k, MAX_K_CAP)
            if max_k % round_to:
                max_k = ((max_k // round_to) + 1) * round_to
            continue
        if not conv and over_i == 0 and max_rounds < MAX_ROUNDS_CAP:
            max_rounds = min(max_rounds * 2, MAX_ROUNDS_CAP)
            continue
        return bits, over


def core_check_exact(h: PaddedLA, n_keys: int, max_k: int = 128,
                     max_rounds: int = 64, deadline=None):
    """core_check with host-side rebatching until exact.  Returns
    (bits, overflowed) like core_check; exact iff bits[-1] == 1 and
    overflowed == 0.  `deadline` bounds the grow loop (see
    grow_until_exact).  Takes the mesh-sharded default path when
    `parallel.slots.default_mesh` resolves one."""
    from jepsen_tpu.parallel import slots

    mesh = slots.default_mesh(h.txn_type.shape[0])
    if mesh is not None:
        from jepsen_tpu.parallel.op_shard import _core_check_sharded, \
            shard_padded

        n = mesh.shape["batch"]
        h2, _ = shard_padded(h, mesh, "batch")
        if max_k % n:
            max_k = ((max_k // n) + 1) * n
        return grow_until_exact(
            lambda k, r: _cc("parallel.op-shard", _core_check_sharded,
                             h2, n_keys=n_keys, mesh=mesh, axis="batch",
                             max_k=k, max_rounds=r),
            max_k, max_rounds, round_to=n, deadline=deadline)
    if _use_staged(h):
        # staged split: infer is independent of max_k/max_rounds, so a
        # budget retry re-runs only the (cheap-on-acyclic) sweep stage —
        # the fused program had to redo inference every retry
        out = _cc("elle.core-check.infer", _infer_stage, h,
                  n_keys=n_keys)
        jax.block_until_ready(out)
        return grow_until_exact(
            lambda k, r: _cc("elle.core-check.sweep", _sweep_stage, out,
                             max_k=k, max_rounds=r),
            max_k, max_rounds, deadline=deadline)
    return grow_until_exact(
        lambda k, r: _cc("elle.core-check", core_check, h,
                         n_keys=n_keys, max_k=k, max_rounds=r),
        max_k, max_rounds, deadline=deadline)
