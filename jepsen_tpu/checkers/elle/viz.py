"""Anomaly sub-graph visualization.

Equivalent of the reference's `elle/src/elle/viz.clj` (SURVEY.md §2.3):
renders each detected cycle anomaly as an SVG under
``store/<run>/elle/<anomaly>-<i>.svg`` — transactions laid out on a
circle, dependency edges as labeled arrows (ww/wr/rw/rt/proc), with op
summaries so a human can follow the cycle the checker found.

Cycle witnesses are the checkers' rendered edge lists:
``[{"src": hist_index, "rel": "ww", "dst": hist_index}, ...]``.
"""

from __future__ import annotations

import html
import math
import os
from typing import Any, Dict, List, Optional, Sequence

_REL_COLOR = {"ww": "#3A54C6", "wr": "#0F8548", "rw": "#C60F0F",
              "rt": "#666666", "realtime": "#666666",
              "proc": "#A56203", "process": "#A56203"}

_R = 150  # circle radius
_CX = _R + 110
_CY = _R + 60


def _op_label(history, idx: int) -> str:
    if history is None:
        return f"T{idx}"
    try:
        op = history[idx]
    except (IndexError, KeyError, TypeError):
        return f"T{idx}"
    v = repr(op.value)
    if len(v) > 36:
        v = v[:33] + "..."
    return f"{idx}: {op.f} {v}"


def _is_cycle(witness: Any) -> bool:
    return (isinstance(witness, list) and witness
            and all(isinstance(e, dict) and "src" in e and "dst" in e
                    for e in witness))


def render_cycle(cycle: Sequence[dict], path: str,
                 history=None, title: str = "") -> str:
    """One cycle -> one SVG (circle layout)."""
    nodes: List[Any] = []
    for e in cycle:
        for n in (e["src"], e["dst"]):
            if n not in nodes:
                nodes.append(n)
    n = max(len(nodes), 1)
    pos = {v: (_CX + _R * math.cos(2 * math.pi * i / n - math.pi / 2),
               _CY + _R * math.sin(2 * math.pi * i / n - math.pi / 2))
           for i, v in enumerate(nodes)}

    parts: List[str] = [
        '<defs><marker id="arr" viewBox="0 0 10 10" refX="9" refY="5" '
        'markerWidth="7" markerHeight="7" orient="auto-start-reverse">'
        '<path d="M 0 0 L 10 5 L 0 10 z" fill="#333"/></marker></defs>']
    for e in cycle:
        (x0, y0), (x1, y1) = pos[e["src"]], pos[e["dst"]]
        # shorten so arrows don't overlap node circles
        dx, dy = x1 - x0, y1 - y0
        d = math.hypot(dx, dy) or 1.0
        pad = 16
        x0p, y0p = x0 + dx / d * pad, y0 + dy / d * pad
        x1p, y1p = x1 - dx / d * pad, y1 - dy / d * pad
        rel = str(e.get("rel", "?"))
        color = _REL_COLOR.get(rel, "#333")
        why = str(e.get("why", "")) if e.get("why") else ""
        tip = (f"<title>{html.escape(why)}</title>") if why else ""
        parts.append(
            f'<line x1="{x0p:.0f}" y1="{y0p:.0f}" x2="{x1p:.0f}" '
            f'y2="{y1p:.0f}" stroke="{color}" stroke-width="1.6" '
            f'marker-end="url(#arr)">{tip}</line>')
        mx, my = (x0 + x1) / 2, (y0 + y1) / 2
        label = rel
        if e.get("key") is not None:
            label = f'{rel} {e["key"]!r}'
        parts.append(
            f'<text x="{mx:.0f}" y="{my:.0f}" font-size="11" '
            f'fill="{color}" font-weight="bold">{html.escape(label)}'
            f'{tip}</text>')
    for v in nodes:
        x, y = pos[v]
        parts.append(
            f'<circle cx="{x:.0f}" cy="{y:.0f}" r="13" fill="#fff" '
            f'stroke="#333"/>'
            f'<text x="{x:.0f}" y="{y + 4:.0f}" font-size="9" '
            f'text-anchor="middle">{html.escape(str(v))}</text>')
        lx = x + (22 if x >= _CX else -22)
        anchor = "start" if x >= _CX else "end"
        parts.append(
            f'<text x="{lx:.0f}" y="{y + 4:.0f}" font-size="9" '
            f'text-anchor="{anchor}" fill="#555">'
            f'{html.escape(_op_label(history, v))}</text>')
    # Explainer legend: one line per edge naming the key/values evidence
    # (the reference's Explainer output, `elle/core.clj`)
    whys = [str(e["why"]) for e in cycle if e.get("why")]
    w, h = 2 * _CX, 2 * _CY + (14 * len(whys) + 10 if whys else 0)
    for i, why in enumerate(whys):
        parts.append(
            f'<text x="8" y="{2 * _CY + 14 * (i + 1):.0f}" font-size="10" '
            f'fill="#333">{i + 1}. {html.escape(why)}</text>')
    svg = (f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" '
           f'height="{h}" font-family="sans-serif">'
           f'<text x="8" y="16" font-size="13">{html.escape(title)}</text>'
           + "".join(parts) + "</svg>")
    with open(path, "w") as f:
        f.write(svg)
    return path


def write_anomalies(results: Dict[str, Any], out_dir: str,
                    history=None, max_per_type: int = 4) -> List[str]:
    """Render every cycle witness in a check result's anomalies map
    (reference: elle's `viz!` writing under store/.../elle/).  Returns the
    written paths, also recorded in results["viz-files"]."""
    anomalies = results.get("anomalies") or {}
    written: List[str] = []
    for name, witnesses in sorted(anomalies.items()):
        if not isinstance(witnesses, list):
            continue
        count = 0
        for witness in witnesses:
            # checkers report cycle anomalies as {"cycle": [edges], ...}
            if isinstance(witness, dict) and "cycle" in witness:
                witness = witness["cycle"]
            if not _is_cycle(witness):
                continue
            if count >= max_per_type:
                break
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"{name}-{count}.svg")
            render_cycle(witness, path, history=history,
                         title=f"{name} (cycle of {len(witness)} edges)")
            written.append(path)
            count += 1
    if written:
        results["viz-files"] = written
    return written


def viz_for_test(results: Dict[str, Any], test: dict,
                 history=None) -> List[str]:
    """Write anomaly SVGs into the test's store dir under elle/."""
    from ... import store

    if results.get("valid?") is not False:
        return []
    try:
        out_dir = os.path.join(store.test_dir(test), "elle")
    except OSError:
        return []
    return write_anomalies(results, out_dir, history=history)
