"""Elle rw-register checker (write/read registers with unique writes).

Equivalent of the reference's `elle/rw_register.clj` (SURVEY.md §2.3):
txns of ``[:w k v] / [:r k v]`` mops with globally unique writes per key.
Version orders are inferred from the default sources — the initial state
(nil precedes every written version) and transaction-internal structure
(write-after-write and read-then-write sequences) — then lifted to a txn
dependency graph:

  wr — reader of version v  <- writer of v          (exact: writes unique)
  ww — writer of u -> writer of v for direct u << v
  rw — external reader of u -> writer of v for direct u << v

Non-cycle anomalies: internal, G1a (aborted read), G1b (intermediate
read), lost-update (>= 2 txns update the same observed version),
duplicate-writes, cyclic-versions (version inference contradiction).

Edge inference is vectorized numpy on the host (segment scans over
(txn, key)-sorted mops — same shapes as the device list-append path);
cycle detection rides the device rank-sweep via `txn_cycles`, with exact
host fallback.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from jepsen_tpu.checkers.elle import consistency
from jepsen_tpu.checkers.elle.graph import (
    REL_RW,
    REL_WR,
    REL_WW,
    EdgeList,
    nontrivial_sccs,
    process_edges,
    realtime_edges_subset,
)
from jepsen_tpu.checkers.elle.txn_cycles import cycle_anomalies
from jepsen_tpu.history.soa import (
    MOP_APPEND,
    MOP_READ,
    TXN_FAIL,
    TXN_INFO,
    TXN_OK,
    PackedTxns,
    pack_txns,
)

NO_PREV = -3


FUSED_MIN_TXNS = 100_000


def sessions_guarantees():
    from jepsen_tpu.checkers.elle import sessions

    return sessions.GUARANTEES


def check(history, consistency_models: Sequence[str] = ("snapshot-isolation",),
          anomalies: Sequence[str] = (), use_device: bool = True,
          max_reported: int = 8, deadline=None, policy=None,
          plan=None) -> Dict[str, Any]:
    """Check an rw-register history.  Accepts History / op list /
    PackedTxns (packed with workload='rw-register').

    Large histories take the fused device fast path first
    (`device_rw.rw_core_check` — inference AND sweeps on device, config-3
    scale): a clean exact verdict returns without any host inference;
    anything else falls through to this host path, which produces the
    full anomaly report (witness cycles, Explainer edges).

    Resilience: a persistent device failure on the fast path (after
    `policy` retries; synthetic faults per `plan`) degrades to this
    host path with ``"degraded": "host-fallback"`` stamped; `deadline`
    expiry returns the canonical deadline-exceeded unknown."""
    if isinstance(history, PackedTxns):
        p = history
    else:
        from jepsen_tpu.history.ir import HistoryIR

        p = history.packed("rw-register") \
            if isinstance(history, HistoryIR) \
            else pack_txns(history, "rw-register")
    if p.n_txns == 0 or not (p.txn_type == TXN_OK).any():
        return {"valid?": "unknown", "anomaly-types": [], "anomalies": {},
                "not": [], "also-not": []}

    # session-guarantee tokens in the requested set run the dedicated
    # per-process checker (needs the op-level history; a PackedTxns-only
    # caller skips them — the packed form drops per-process sequencing)
    want = set(consistency.anomalies_for_models(
        [consistency.canonical(m) for m in consistency_models]))
    want |= set(anomalies)
    want |= {"duplicate-writes", "cyclic-versions"}
    sess_found: Dict[str, List[Any]] = {}
    suffix = "-violation"
    sess_want = {w for w in want if w.endswith(suffix)
                 and w[:-len(suffix)] in sessions_guarantees()}
    # packed input drops the op-level view the session checker walks: a
    # session-family request then cannot be session-checked.  When the
    # request also proscribes process-edge cycles (strict/strong-session
    # class), per-session ordering violations surface as process-edge
    # cycles in the transactional graph (the reference's own treatment),
    # so the verdict stands; a BARE session request (e.g. just
    # ["monotonic-reads"]) has no such coverage and must degrade to
    # unknown rather than silently report valid
    # only G-single-process qualifies: read-centric session violations
    # (monotonic-reads, RYW) surface through anti-dependency (rw)
    # edges, which the G0-process/G1c-process projections never search
    proc_covered = "G-single-process" in want
    # full "-violation" tokens, matching the la checkers' key shape
    # (coverage.finalize_la) so callers see ONE degradation contract
    sess_unchecked = sorted(sess_want) \
        if (sess_want and isinstance(history, PackedTxns)
            and not proc_covered) else []
    if sess_want and not isinstance(history, PackedTxns):
        from jepsen_tpu.checkers.elle import sessions

        sres = sessions.check(history,
                              guarantees=[w[:-len(suffix)]
                                          for w in sess_want])
        sess_found = sres["anomalies"]

    degraded = None
    device_error = None

    def finalize(result: Dict[str, Any]) -> Dict[str, Any]:
        from jepsen_tpu.checkers.elle import coverage

        if degraded:
            result["degraded"] = degraded
            if device_error:
                result["device-error"] = device_error
        return coverage.apply_unchecked(result, sess_unchecked)

    if use_device and p.n_txns >= FUSED_MIN_TXNS:
        from jepsen_tpu import resilience
        from jepsen_tpu.checkers.elle import device_rw

        try:
            fast = device_rw.check(p, deadline=deadline, policy=policy,
                                   plan=plan)
        except resilience.DeadlineExceeded:
            return resilience.deadline_result(checker="rw-register")
        except Exception as e:  # noqa: BLE001 — persistent device failure
            # the host path below IS the oracle; degrade to it through
            # the shared tail (counter + span attr + deadline poll — an
            # expired budget must not buy an unbounded host run)
            try:
                resilience.degrade_to_host(
                    "elle.rw-register", lambda: None, e,
                    deadline=deadline)
            except resilience.DeadlineExceeded:
                return resilience.deadline_result(checker="rw-register")
            degraded = resilience.DEGRADED_HOST
            device_error = f"{type(e).__name__}: {e}"
            fast = None
        if fast is not None and fast["valid?"] is True and fast["exact"]:
            anomaly_types = sorted(sess_found)
            boundary = consistency.friendly_boundary(anomaly_types)
            bad = set(boundary["not"]) | set(boundary["also-not"])
            requested_bad = bad & {consistency.canonical(m)
                                   for m in consistency_models}
            return finalize({"valid?": not requested_bad,
                             "anomaly-types": anomaly_types,
                             "anomalies": sess_found,
                             "not": boundary["not"],
                             "also-not": boundary["also-not"],
                             "fused-device": True})
        # invalid or inexact: fall through for the detailed host report

    T = p.n_txns
    M = p.n_mops
    V = p.n_vals
    nk = max(p.n_keys, 1)
    found: Dict[str, List[Any]] = {}

    def report(name, item):
        found.setdefault(name, [])
        if len(found[name]) < max_reported:
            found[name].append(item)

    ttype = p.txn_type.astype(np.int32)
    ok = ttype == TXN_OK
    graph_txn = ok | (ttype == TXN_INFO)

    kind = p.mop_kind.astype(np.int32)
    mtxn = p.mop_txn.astype(np.int64)
    mkey = p.mop_key.astype(np.int64)
    mval = p.mop_val.astype(np.int64)
    known = np.where(kind == MOP_READ, p.mop_rd_len >= 0, True)

    # writers (unique by contract; duplicates flagged).  On a duplicate,
    # attribute the value to a *committed* writer when one exists (ok over
    # info over fail) so an aborted duplicate can't fabricate a G1a against
    # readers of the committed write; the broken contract itself is
    # reported as duplicate-writes, which invalidates read-uncommitted.
    writer = np.full(V, -1, np.int64)
    wsel = np.nonzero(kind == MOP_APPEND)[0]
    wvals = mval[wsel]
    dup = np.zeros(0, np.int64)
    if len(wsel):
        prio = np.select([ok[mtxn[wsel]], ttype[mtxn[wsel]] == TXN_INFO],
                         [0, 1], 2)
        order = np.lexsort((wsel, prio, wvals))
        sv = wvals[order]
        first = np.concatenate([[True], sv[1:] != sv[:-1]])
        writer[sv[first]] = mtxn[wsel][order][first]
        dup = np.unique(sv[~first])
    for v in dup[:max_reported]:
        report("duplicate-writes", {"value": p.val_names[int(v)]})

    # final write per (txn, key): last write mop of the run
    run_order = np.lexsort((np.arange(M), mkey, mtxn))
    rt, rk = mtxn[run_order], mkey[run_order]
    rkind = kind[run_order]
    rval = mval[run_order]
    rknown = known[run_order]
    run_start = np.concatenate([[True], (rt[1:] != rt[:-1]) |
                                (rk[1:] != rk[:-1])])
    # is this write the last write in its run?
    wpos = np.where(rkind == MOP_APPEND, np.arange(M), -1)
    # reverse cummax within segments (flip trick)
    seg_id = np.cumsum(run_start) - 1
    last_w = _seg_reverse_max(wpos, seg_id)
    r_final = (rkind == MOP_APPEND) & (np.arange(M) == last_w)
    is_final = np.zeros(V + nk, bool)
    fw = (rkind == MOP_APPEND) & r_final
    is_final[rval[fw]] = True

    # cur version before each mop within its run:
    # defining mops: writes (-> own val), known reads (-> read val)
    defines = (rkind == MOP_APPEND) | ((rkind == MOP_READ) & rknown)
    def_val = np.where(rkind == MOP_APPEND, rval,
                       np.where(rval >= 0, rval, V + rk))
    def_pos = np.where(defines, np.arange(M), -1)
    prev_def = _seg_exclusive_max(def_pos, seg_id)
    cur_before = np.where(prev_def >= 0, def_val[np.maximum(prev_def, 0)],
                          NO_PREV)
    # unknown reads poison (info reads excluded from is_read anyway, and
    # they don't define); nothing else to do

    # external read = first mop of run is a read (no prior define)
    r_is_read = (rkind == MOP_READ) & rknown & ok[rt]
    external_read = r_is_read & (cur_before == NO_PREV)
    ext_read_val = def_val  # for reads, the read value (init-encoded)

    # ---- internal: read disagrees with txn-local state -------------------
    internal_bad = r_is_read & (cur_before != NO_PREV) & \
        (def_val != cur_before)
    for q in np.nonzero(internal_bad)[0][:max_reported]:
        report("internal", {"op": int(p.txn_orig_index[rt[q]])})

    # ---- G1a / G1b on external reads -------------------------------------
    ext_idx = np.nonzero(external_read)[0]
    ev = ext_read_val[ext_idx]
    real = ev < V
    evr = ev[real].astype(np.int64)
    w_of = writer[evr]
    g1a = w_of >= 0
    g1a &= ttype[np.maximum(writer[evr], 0)] == TXN_FAIL
    for i in np.nonzero(g1a)[0][:max_reported]:
        report("G1a", {"op": int(p.txn_orig_index[rt[ext_idx[real][i]]]),
                       "value": p.val_names[int(evr[i])]})
    g1b = (w_of >= 0) & ~is_final[evr] & \
        (w_of != rt[ext_idx[real]])
    for i in np.nonzero(g1b)[0][:max_reported]:
        report("G1b", {"op": int(p.txn_orig_index[rt[ext_idx[real][i]]]),
                       "value": p.val_names[int(evr[i])]})

    # ---- version edges ---------------------------------------------------
    # write with known predecessor u: u -> v; blind write: init(k) -> v
    w_idx = np.nonzero((rkind == MOP_APPEND) & graph_txn[rt])[0]
    u = np.where((cur_before[w_idx] >= 0), cur_before[w_idx],
                 V + rk[w_idx])
    v = rval[w_idx]
    v_src, v_dst = u.astype(np.int64), v.astype(np.int64)

    # cyclic-versions: cycle among version nodes
    if len(v_src):
        vs = nontrivial_sccs(V + nk, v_src.astype(np.int32),
                             v_dst.astype(np.int32))
        if vs:
            report("cyclic-versions",
                   {"scc-size": int(len(vs[0])),
                    "values": [p.val_names[int(x)] for x in vs[0][:6]
                               if int(x) < V]})

    # ---- lost update: >= 2 ok txns externally read u then write k --------
    upd = external_read.copy()
    # txn wrote k after the external read: last write exists in run after q
    upd &= last_w > np.arange(M)
    upd &= ok[rt]
    if upd.any():
        uu = def_val[np.nonzero(upd)[0]]
        ut = rt[np.nonzero(upd)[0]]
        order2 = np.lexsort((ut, uu))
        su, st = uu[order2], ut[order2]
        uniq = np.concatenate([[True], (su[1:] != su[:-1]) |
                               (st[1:] != st[:-1])])
        su, st = su[uniq], st[uniq]
        grp = np.concatenate([[True], su[1:] != su[:-1]])
        gid = np.cumsum(grp) - 1
        counts = np.bincount(gid)
        bad_groups = np.nonzero(counts >= 2)[0]
        for g in bad_groups[:max_reported]:
            vals = su[gid == g]
            txns = st[gid == g]
            report("lost-update",
                   {"version": (p.val_names[int(vals[0])]
                                if vals[0] < V else "nil"),
                    "txns": [int(p.txn_orig_index[t]) for t in txns[:6]]})

    # ---- txn dependency edges --------------------------------------------
    es: List[np.ndarray] = []
    ed: List[np.ndarray] = []
    er: List[np.ndarray] = []

    def add(src, dst, rel):
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        m = (src >= 0) & (dst >= 0) & (src != dst)
        m &= graph_txn[np.maximum(src, 0)] & graph_txn[np.maximum(dst, 0)]
        es.append(src[m].astype(np.int32))
        ed.append(dst[m].astype(np.int32))
        er.append(np.full(int(m.sum()), rel, np.int8))

    # wr: external reader of real v <- writer(v)
    wr_r = rt[ext_idx[real]]
    add(w_of, wr_r, REL_WR)
    # ww: writer(u) -> writer(v) for version edges with real u
    real_u = v_src < V
    ww_src = np.where(real_u, writer[np.minimum(v_src, V - 1)], -1)
    ww_dst = np.where(v_dst < V, writer[np.minimum(v_dst, V - 1)], -1)
    add(ww_src, ww_dst, REL_WW)
    # rw: external readers of u -> writer(v), for each version edge u->v
    # join readers (sorted by value) with version edges (sorted by src)
    if len(ext_idx) and len(v_src):
        rd_vals = ext_read_val[ext_idx]
        rd_txn = rt[ext_idx]
        r_ord = np.argsort(rd_vals, kind="stable")
        rv_sorted = rd_vals[r_ord]
        rt_sorted = rd_txn[r_ord]
        lo = np.searchsorted(rv_sorted, v_src, side="left")
        hi = np.searchsorted(rv_sorted, v_src, side="right")
        cnt = hi - lo
        tot = int(cnt.sum())
        if tot:
            eidx = np.repeat(np.arange(len(v_src)), cnt)
            off = np.arange(tot) - np.repeat(np.cumsum(cnt) - cnt, cnt)
            readers = rt_sorted[lo[eidx] + off]
            wdst = writer[np.minimum(v_dst[eidx], V - 1)]
            wdst = np.where(v_dst[eidx] < V, wdst, -1)
            add(readers, wdst, REL_RW)

    dep = EdgeList()
    dep.src = np.concatenate(es) if es else np.zeros(0, np.int32)
    dep.dst = np.concatenate(ed) if ed else np.zeros(0, np.int32)
    dep.rel = np.concatenate(er) if er else np.zeros(0, np.int8)

    # process + realtime (barrier) orders over ok/info txns
    proc = p.txn_process.astype(np.int64)
    inv = p.txn_invoke_pos.astype(np.int64)
    comp = p.txn_complete_pos.astype(np.int64)
    pe = process_edges(np.where(graph_txn, proc, -10 ** 9 - np.arange(T)),
                       inv)
    ok_ids = np.nonzero(ok)[0]
    rte, n_b, b_ranks = realtime_edges_subset(inv, comp, ok_ids, graph_txn, T)
    edges = EdgeList.concat([dep, pe, rte]).dedup()
    n_nodes = T + n_b
    rank = np.concatenate([2 * comp, b_ranks]).astype(np.int32)

    # ---- cycle anomalies --------------------------------------------------
    found.update(sess_found)
    from jepsen_tpu.checkers.elle.explain import rw_explainer

    expl = rw_explainer(p, writer, v_src, v_dst,
                        ext_read_txn=rt[ext_idx],
                        ext_read_val=ext_read_val[ext_idx])
    found.update(cycle_anomalies(edges, n_nodes, rank, want,
                                 use_device=use_device, explainer=expl,
                                 n_txns=T, orig_index=p.txn_orig_index))

    found = {k: val for k, val in found.items() if k in want}
    anomaly_types = sorted(found.keys())
    boundary = consistency.friendly_boundary(anomaly_types)
    bad = set(boundary["not"]) | set(boundary["also-not"])
    requested_bad = bad & {consistency.canonical(m)
                           for m in consistency_models}
    return finalize({
        "valid?": not requested_bad,
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
    })


def _seg_reverse_max(vals: np.ndarray, seg_id: np.ndarray) -> np.ndarray:
    """Per-segment max over [i, end] (suffix max)."""
    if not len(vals):
        return vals
    rev_vals = vals[::-1]
    # reversed seg ids must stay nondecreasing for the encoding trick
    rev_seg = (seg_id.max() - seg_id)[::-1]
    out = _seg_inclusive_max(rev_vals, rev_seg)
    return out[::-1]


def _seg_inclusive_max(vals: np.ndarray, seg_id: np.ndarray) -> np.ndarray:
    """Vectorized segmented cummax for nondecreasing seg_id and vals in
    [-1, BOUND): encode seg_id*(BOUND+1) + (val+1); a later segment's
    encodings dominate all earlier ones, so a global cummax restricted to
    the encoding stays within-segment after decode."""
    if not len(vals):
        return vals
    bound = int(vals.max(initial=0)) + 2
    enc = seg_id.astype(np.int64) * bound + (vals.astype(np.int64) + 1)
    cm = np.maximum.accumulate(enc)
    return (cm % bound - 1).astype(vals.dtype)


def _seg_exclusive_max(vals: np.ndarray, seg_id: np.ndarray) -> np.ndarray:
    inc = _seg_inclusive_max(vals, seg_id)
    out = np.full_like(vals, -1)
    if len(vals):
        same = np.concatenate([[False], seg_id[1:] == seg_id[:-1]])
        out[same] = inc[:-1][same[1:]]
    return out
