"""Per-edge justifications for reported cycles (the Explainer).

Equivalent of the reference's Explainer protocol (`elle/core.clj`,
SURVEY.md §2.3 "Core analyzers"): each analyzer there yields an explainer
that turns a graph edge into human-readable evidence — which key, which
values, why the edge must exist.  Here an explainer is a plain callable
``(src_txn, rel_name, dst_txn) -> dict`` returning justification fields
merged into the reported cycle edge:

  ww       {key, value, value'}  — src appended value, dst appended
           value', its immediate successor in key's version order
  wr       {key, value}          — dst read a list ending in value, which
           src appended
  rw       {key, value'}         — src read a prefix NOT containing
           value'; dst appended value' (the anti-dependency)
  process  {process}             — same process, program order
  realtime {positions}           — src completed before dst invoked

plus a ``"why"`` sentence rendering the evidence.  Lookups are exact
replays of the inference that created the edge, evaluated lazily on the
(small) reported cycle only — the device returns witnesses, the host
explains them (SURVEY.md §7 "Explanations").
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from jepsen_tpu.history.soa import MOP_READ, PackedTxns

Explainer = Callable[[int, str, int], Dict]


def _vname(p: PackedTxns, v: int):
    """Original (uninterned) value for a value id."""
    if 0 <= v < len(p.val_names):
        return p.val_names[int(v)][1]
    return None


def _kname(p: PackedTxns, k: int):
    return p.key_names[int(k)] if 0 <= k < len(p.key_names) else None


def la_explainer(p: PackedTxns, order: Dict[str, np.ndarray]) -> Explainer:
    """Explainer over a list-append history.

    `order` is the inferred version-order block (`device_infer.infer`'s
    ``out["order"]`` pulled to host): elems/start/len per key plus the
    value->writer map.
    """
    ord_elems = np.asarray(order["elems"])
    ord_start = np.asarray(order["start"])
    ord_len = np.asarray(order["len"])
    writer = np.asarray(order["writer"])
    kind = np.asarray(p.mop_kind)
    mtxn = np.asarray(p.mop_txn)
    mkey = np.asarray(p.mop_key)
    rd_start = np.asarray(p.mop_rd_start)
    rd_len = np.asarray(p.mop_rd_len)
    rd_elems = np.asarray(p.rd_elems)
    orig = np.asarray(p.txn_orig_index)

    nk = len(ord_len)
    V = len(writer)

    def T(t: int):
        return int(orig[t]) if 0 <= t < p.n_txns else t

    # consecutive version pairs (u, v) per key, vectorized once: slot j
    # pairs with j+1 when both lie inside the same key's order segment
    n_slots = len(ord_elems)
    slots = np.arange(max(n_slots - 1, 0))
    if n_slots > 1:
        slot_key = np.clip(
            np.searchsorted(ord_start, slots, side="right") - 1, 0,
            max(nk - 1, 0))
        seg_end = ord_start[slot_key] + ord_len[slot_key]
        pair_ok = (slots + 1 < seg_end)
        pu = ord_elems[:-1]
        pv = ord_elems[1:]
        pair_ok &= (pu >= 0) & (pu < V) & (pv >= 0) & (pv < V)
        pair_wu = np.where(pair_ok, writer[np.clip(pu, 0, V - 1)], -1)
        pair_wv = np.where(pair_ok, writer[np.clip(pv, 0, V - 1)], -1)
    else:
        slot_key = np.zeros(0, np.int64)
        pu = pv = pair_wu = pair_wv = np.zeros(0, np.int64)

    def explain(a: int, rel: str, b: int) -> Dict:
        if rel == "ww":
            # consecutive versions (u, v) of some key with writer(u)=a,
            # writer(v)=b (vectorized: a reported cycle must stay cheap
            # to justify even on 1M-op histories)
            hits = np.nonzero((pair_wu == a) & (pair_wv == b))[0]
            if len(hits):
                j = int(hits[0])
                k, u, v = int(slot_key[j]), int(pu[j]), int(pv[j])
                return {
                    "key": _kname(p, k), "value": _vname(p, u),
                    "value'": _vname(p, v),
                    "why": (f"T{T(a)} appended {_vname(p, u)!r} to "
                            f"key {_kname(p, k)!r}; T{T(b)} appended "
                            f"{_vname(p, v)!r}, its immediate "
                            f"successor in the version order"),
                }
        elif rel == "wr":
            # b read a list whose final element a appended
            for m in np.nonzero((mtxn == b) & (kind == MOP_READ)
                                & (rd_len > 0))[0]:
                last = int(rd_elems[int(rd_start[m]) + int(rd_len[m]) - 1])
                if 0 <= last < V and writer[last] == a:
                    k = int(mkey[m])
                    return {
                        "key": _kname(p, k), "value": _vname(p, last),
                        "mop": int(m),
                        "why": (f"T{T(b)} read key {_kname(p, k)!r} ending "
                                f"in {_vname(p, last)!r}, which T{T(a)} "
                                f"appended"),
                    }
        elif rel == "rw":
            # a read a prefix of k missing the next version, appended by b
            for m in np.nonzero((mtxn == a) & (kind == MOP_READ)
                                & (rd_len >= 0))[0]:
                k = int(mkey[m])
                L = int(rd_len[m])
                if k < nk and L < int(ord_len[k]):
                    succ = int(ord_elems[int(ord_start[k]) + L])
                    if 0 <= succ < V and writer[succ] == b:
                        seen = (_vname(p, int(
                            rd_elems[int(rd_start[m]) + L - 1]))
                            if L > 0 else None)
                        read_desc = (f"up to {seen!r}" if L
                                     else "as empty")
                        return {
                            "key": _kname(p, k), "value'": _vname(p, succ),
                            "mop": int(m),
                            "why": (f"T{T(a)} read key {_kname(p, k)!r} "
                                    f"{read_desc}, before T{T(b)}'s append "
                                    f"of {_vname(p, succ)!r} (unobserved "
                                    f"successor: anti-dependency)"),
                        }
        elif rel in ("process", "proc"):
            pa = int(p.txn_process[a]) if a < p.n_txns else None
            return {
                "process": pa,
                "why": (f"T{T(a)} and T{T(b)} both ran on process {pa}; "
                        f"T{T(a)} completed first (program order)"),
            }
        elif rel in ("realtime", "rt"):
            ca = int(p.txn_complete_pos[a]) if a < p.n_txns else None
            ib = int(p.txn_invoke_pos[b]) if b < p.n_txns else None
            return {
                "completed-at": ca, "invoked-at": ib,
                "why": (f"T{T(a)} completed (event {ca}) before T{T(b)} "
                        f"invoked (event {ib}): a real-time edge"),
            }
        return {}

    return explain


def rw_explainer(p: PackedTxns, writer: np.ndarray,
                 v_src: np.ndarray, v_dst: np.ndarray,
                 ext_read_txn: np.ndarray,
                 ext_read_val: np.ndarray) -> Explainer:
    """Explainer over an rw-register history.

    writer: value id -> writing txn.  (v_src, v_dst): inferred direct
    version edges (value ids; ids >= V encode the initial state).
    ext_read_txn/val: external reads (txn, value-id-or-init).
    """
    orig = np.asarray(p.txn_orig_index)
    V = len(writer)

    def T(t: int):
        return int(orig[t]) if 0 <= t < p.n_txns else t

    def vname(v: int):
        return _vname(p, v) if v < V else None  # init encodes as None

    def key_of_val(v: int):
        if 0 <= v < V:
            return _kname(p, int(p.val_names[int(v)][0]))
        return None

    def explain(a: int, rel: str, b: int) -> Dict:
        if rel == "wr":
            sel = (ext_read_txn == b) & (ext_read_val < V)
            for v in ext_read_val[sel]:
                if writer[int(v)] == a:
                    return {
                        "key": key_of_val(int(v)), "value": vname(int(v)),
                        "why": (f"T{T(b)} read {vname(int(v))!r} of key "
                                f"{key_of_val(int(v))!r}, which T{T(a)} "
                                f"wrote"),
                    }
        elif rel == "ww":
            for u, v in zip(v_src, v_dst):
                u, v = int(u), int(v)
                if u < V and v < V and writer[u] == a and writer[v] == b:
                    return {
                        "key": key_of_val(v), "value": vname(u),
                        "value'": vname(v),
                        "why": (f"T{T(a)} wrote {vname(u)!r}, which T{T(b)} "
                                f"overwrote with {vname(v)!r} (key "
                                f"{key_of_val(v)!r})"),
                    }
        elif rel == "rw":
            for u, v in zip(v_src, v_dst):
                u, v = int(u), int(v)
                if v < V and writer[v] == b:
                    sel = (ext_read_txn == a) & (ext_read_val == u)
                    if sel.any():
                        return {
                            "key": key_of_val(v), "value": vname(u),
                            "value'": vname(v),
                            "why": (f"T{T(a)} read {vname(u)!r}, which "
                                    f"T{T(b)} overwrote with {vname(v)!r} "
                                    f"(key {key_of_val(v)!r}: "
                                    f"anti-dependency)"),
                        }
        elif rel in ("process", "proc"):
            pa = int(p.txn_process[a]) if a < p.n_txns else None
            return {
                "process": pa,
                "why": (f"T{T(a)} and T{T(b)} both ran on process {pa}; "
                        f"T{T(a)} completed first (program order)"),
            }
        elif rel in ("realtime", "rt"):
            ca = int(p.txn_complete_pos[a]) if a < p.n_txns else None
            ib = int(p.txn_invoke_pos[b]) if b < p.n_txns else None
            return {
                "completed-at": ca, "invoked-at": ib,
                "why": (f"T{T(a)} completed (event {ca}) before T{T(b)} "
                        f"invoked (event {ib}): a real-time edge"),
            }
        return {}

    return explain
