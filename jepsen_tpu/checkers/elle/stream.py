"""Stream a stored history to the device chunk-by-chunk and check it.

The missing piece round 2 flagged (VERDICT item 4; SURVEY.md §2.7
"Pipeline" row, §2.2 "Chunked storage"): the reference keeps 10M-op
histories off the heap with big-vector blocks + soft-reference chunks
(`store/format.clj`, `history/core.clj`).  Here the equivalent path is

  .jepsen file -> LazyHistory.iter_chunks() (LRU-bounded decode)
    -> TxnPacker.feed (per-chunk SoA columns, global ids)
    -> jax.device_put per chunk (ASYNC: the transfer of chunk i overlaps
       host decode+pack of chunk i+1 — the host<->device pipeline)
    -> one device-side concatenate + pad to pow2 capacities
    -> core_check (fused inference + cycle sweeps)

so peak host memory holds the pending-invoke table, the interner maps,
and a bounded window of decoded chunks — never the whole op-object list
(a 1M-op history is ~100 MB of packed columns vs multiple GB of Python
Op objects).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.checkers.elle.device_core import (
    COUNT_NAMES,
    core_check_exact,
)
from jepsen_tpu.checkers.elle.device_infer import (
    PaddedLA,
    pow2_at_least,
    run_cap_of,
)
from jepsen_tpu.history.soa import TxnPacker

_FILLS = {
    "txn_type": 0, "txn_process": 0, "txn_invoke_pos": 0,
    "txn_complete_pos": 0, "mop_txn": 0, "mop_kind": -1, "mop_key": 0,
    "mop_val": -1, "mop_rd_start": -1, "mop_rd_len": -1, "rd_elems": -1,
}


def stage_chunks(chunks: Iterable, workload: str = "list-append"
                 ) -> tuple[PaddedLA, TxnPacker]:
    """Pack + transfer history chunks to the device as they stream by.

    `chunks` yields lists of Ops in history order (e.g.
    `LazyHistory.iter_chunks()`).  Each packed chunk is `device_put`
    immediately — dispatch is async, so the PCIe transfer of chunk i
    runs while the host decodes and packs chunk i+1.  Returns the padded
    device-resident history plus the packer (for key/value maps).
    """
    pk = TxnPacker(workload)
    dev_chunks: List[dict] = []
    # verify the sort-free layout facts on the actual host columns as
    # they stream by (cheap numpy diffs per chunk) instead of asserting
    # them — a packer-order regression then degrades to the device-sort
    # fallback rather than corrupting the fast path's permutation scatter
    layout_ok = True
    prev_mop_txn = 0  # also rejects negative sentinels in chunk 0
    prev_cpos = -1
    for ops in chunks:
        cols = pk.feed(ops)
        mt, cp = cols["mop_txn"], cols["txn_complete_pos"]
        if len(mt):
            layout_ok = bool(layout_ok and np.all(np.diff(mt) >= 0)
                             and mt[0] >= prev_mop_txn)
            prev_mop_txn = int(mt[-1])
        if len(cp):
            layout_ok = bool(layout_ok and np.all(np.diff(cp) > 0)
                             and cp[0] > prev_cpos)
            prev_cpos = int(cp[-1])
        dev_chunks.append({k: jax.device_put(v) for k, v in cols.items()
                           if k != "txn_orig_index"})

    # final range bound: every mop_txn must name a real txn
    layout_ok = bool(layout_ok and prev_mop_txn < max(pk.n_txns, 1))

    T = pow2_at_least(max(pk.n_txns, 1))
    M = pow2_at_least(max(pk.n_mops, 1))
    R = pow2_at_least(max(pk.n_rd_elems, len(pk.val_names),
                          len(pk.key_names) + 1))

    def cat(name: str, n: int, total: int, dtype) -> jnp.ndarray:
        parts = [c[name] for c in dev_chunks]
        tail = jnp.full((n - total,), _FILLS[name], dtype)
        return jnp.concatenate([p.astype(dtype) for p in parts] + [tail]) \
            if parts else tail

    h = PaddedLA(
        txn_type=cat("txn_type", T, pk.n_txns, jnp.int8),
        txn_process=cat("txn_process", T, pk.n_txns, jnp.int32),
        txn_invoke_pos=cat("txn_invoke_pos", T, pk.n_txns, jnp.int32),
        txn_complete_pos=cat("txn_complete_pos", T, pk.n_txns, jnp.int32),
        txn_mask=jnp.arange(T) < pk.n_txns,
        mop_txn=cat("mop_txn", M, pk.n_mops, jnp.int32),
        mop_kind=cat("mop_kind", M, pk.n_mops, jnp.int8),
        mop_key=cat("mop_key", M, pk.n_mops, jnp.int32),
        mop_val=cat("mop_val", M, pk.n_mops, jnp.int32),
        mop_rd_start=cat("mop_rd_start", M, pk.n_mops, jnp.int32),
        mop_rd_len=cat("mop_rd_len", M, pk.n_mops, jnp.int32),
        mop_mask=jnp.arange(M) < pk.n_mops,
        rd_elems=cat("rd_elems", R, pk.n_rd_elems, jnp.int32),
        rd_elem_mask=jnp.arange(R) < pk.n_rd_elems,
        n_keys=len(pk.key_names),
        n_vals=len(pk.val_names),
        # layout facts verified on the streamed host columns above
        txn_major=layout_ok,
        run_cap=run_cap_of(pk.max_mops_txn) if layout_ok else 0,
        complete_monotone=layout_ok,
    )
    return h, pk


def check_stored(test_or_dir, workload: str = "list-append",
                 max_k: int = 128, max_rounds: int = 64,
                 deadline=None) -> Dict[str, Any]:
    """Check a STORED list-append run end-to-end without materializing
    its op list: lazy chunks -> streamed device staging -> fused core
    check.  Accepts a store dir path or a loaded test map whose history
    is a LazyHistory.  Returns a summary dict (check_sharded row shape).

    `deadline` (or the test map's ``"checker-time-limit"``) bounds the
    fused check's grow loop — expiry raises `DeadlineExceeded` (callers
    under `check_safe` get the canonical unknown verdict).
    """
    from jepsen_tpu import store
    from jepsen_tpu.resilience import Deadline

    test = store.load(test_or_dir) if isinstance(test_or_dir, str) \
        else test_or_dir
    if deadline is None:
        deadline = Deadline.resolve(None, test)
    hist = test.get("history")
    if hist is None:
        return {"valid?": "unknown", "counts": {}, "cycles": {},
                "exact": False}
    chunks = hist.iter_chunks() if hasattr(hist, "iter_chunks") \
        else _one_chunk(hist)
    h, pk = stage_chunks(chunks, workload)
    if pk.n_txns == 0:
        return {"valid?": "unknown", "counts": {}, "cycles": {},
                "exact": False}

    if workload == "rw-register":
        # rw-packed columns mean something different to list-append
        # inference — route to the fused rw checker (same staged arrays)
        from jepsen_tpu.checkers.elle import device_rw

        res = device_rw.check(h, max_k=max_k, max_rounds=max_rounds,
                              deadline=deadline)
        res["n-txns"] = pk.n_txns
        return res

    bits, over = core_check_exact(h, h.n_keys, max_k=max_k,
                                  max_rounds=max_rounds,
                                  deadline=deadline)
    row = np.asarray(bits)
    over_i = int(np.asarray(over))
    counts = {n: int(row[j]) for j, n in enumerate(COUNT_NAMES)}
    cycles = [bool(x) for x in row[len(COUNT_NAMES):-1]]
    converged = bool(row[-1]) and over_i == 0
    invalid = any(v > 0 for v in counts.values()) or any(cycles)
    return {
        "valid?": (not invalid) if converged else "unknown",
        "counts": counts,
        "cycles": {
            "G0": cycles[0], "G1c": cycles[1], "G2-family": cycles[2],
            "G2-family-process": cycles[3],
            "G2-family-realtime": cycles[4],
        },
        "exact": converged,
        "n-txns": pk.n_txns,
    }


def _one_chunk(hist):
    yield list(hist)
