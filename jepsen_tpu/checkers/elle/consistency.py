"""Consistency-model lattice.

Equivalent of the reference's `elle/consistency_model.clj` (SURVEY.md §2.3):
a DAG of consistency models ordered by strength, a mapping from observed
anomalies to the models they rule out, and `friendly_boundary` reporting —
"not(serializable) but maybe(snapshot-isolation)".

The model set is the load-bearing core of the reference's ~40-model lattice
(Adya PL levels, the snapshot-isolation family, session/strong variants).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Set

# model -> models it directly implies (stronger -> weaker edges)
IMPLIES: Dict[str, List[str]] = {
    "strict-serializable": ["serializable", "strong-session-serializable",
                            "strong-snapshot-isolation", "linearizable"],
    "strong-session-serializable": ["serializable"],
    "serializable": ["repeatable-read", "view-serializable", "read-atomic"],
    "view-serializable": [],
    "repeatable-read": ["cursor-stability", "consistent-view"],
    "strong-snapshot-isolation": ["snapshot-isolation",
                                  "strong-session-snapshot-isolation"],
    "strong-session-snapshot-isolation": ["snapshot-isolation"],
    "snapshot-isolation": ["consistent-view", "monotonic-atomic-view",
                           "read-atomic"],
    "consistent-view": ["monotonic-view"],
    "monotonic-view": ["read-committed"],
    "cursor-stability": ["read-committed"],
    "causal-cerone": ["read-atomic"],
    "parallel-snapshot-isolation": ["causal-cerone"],
    "read-atomic": ["monotonic-atomic-view"],
    "monotonic-atomic-view": ["read-committed"],
    "read-committed": ["read-uncommitted"],
    "read-uncommitted": [],
    "linearizable": [],
}

ALL_MODELS = sorted(IMPLIES.keys())

# Canonical aliases users may pass (reference supports many).
ALIASES = {
    "strict-1SR": "strict-serializable",
    "strong-serializable": "strict-serializable",
    "PL-3": "serializable",
    "PL-2.99": "repeatable-read",
    "PL-2+": "consistent-view",
    "PL-2": "read-committed",
    "PL-1": "read-uncommitted",
    "SI": "snapshot-isolation",
    "serializability": "serializable",
}

# model -> anomalies it directly proscribes (closed downward over IMPLIES:
# a model also proscribes everything its weaker models do).
PROSCRIBED: Dict[str, Set[str]] = {
    "read-uncommitted": {"G0", "duplicate-elements", "incompatible-order",
                         "cyclic-versions", "duplicate-writes"},
    "read-committed": {"G1a", "G1b", "G1c", "dirty-update", "aborted-read",
                       "intermediate-read"},
    "monotonic-atomic-view": {"monotonic-atomic-view-violation"},
    "read-atomic": {"internal", "fractured-read"},
    "causal-cerone": {"G1c-process", "G0-process"},
    "parallel-snapshot-isolation": set(),
    "monotonic-view": set(),
    "consistent-view": {"G-single"},
    "cursor-stability": {"G-cursor", "lost-update"},
    "snapshot-isolation": {"G-single", "G-SI", "lost-update"},
    "repeatable-read": {"G2-item", "lost-update"},
    "serializable": {"G2-item", "G2", "G-nonadjacent", "G-single"},
    "view-serializable": {"G2-item"},
    "strong-session-serializable": {"G2-item-process", "G-single-process",
                                    "G1c-process", "G0-process"},
    "strong-session-snapshot-isolation": {"G-single-process", "G1c-process"},
    "strong-snapshot-isolation": {"G-single-realtime", "G1c-realtime"},
    "strict-serializable": {"G2-item-realtime", "G-single-realtime",
                            "G1c-realtime", "G0-realtime",
                            "G-nonadjacent-realtime"},
    "linearizable": set(),
}


def canonical(model: str) -> str:
    m = ALIASES.get(model, model)
    if m not in IMPLIES:
        raise ValueError(f"unknown consistency model {model!r}")
    return m


def _descendants(model: str) -> Set[str]:
    """All models implied by `model` (including itself)."""
    seen: Set[str] = set()
    stack = [model]
    while stack:
        m = stack.pop()
        if m in seen:
            continue
        seen.add(m)
        stack.extend(IMPLIES[m])
    return seen


_DESC: Dict[str, FrozenSet[str]] = {m: frozenset(_descendants(m)) for m in IMPLIES}


def proscribed_anomalies(model: str) -> Set[str]:
    """Every anomaly that rules out `model` (its own + all weaker models')."""
    out: Set[str] = set()
    for m in _DESC[canonical(model)]:
        out |= PROSCRIBED[m]
    return out


def anomaly_impossible_models(anomalies: Iterable[str]) -> Set[str]:
    """All models ruled out by any of the observed anomalies."""
    obs = set(anomalies)
    return {m for m in IMPLIES if proscribed_anomalies(m) & obs}


def friendly_boundary(anomalies: Iterable[str]) -> Dict[str, List[str]]:
    """Reference `elle.consistency-model/friendly-boundary`:

    {:not        — the weakest violated models (the informative boundary)
     :also-not   — all other violated models}
    """
    impossible = anomaly_impossible_models(anomalies)
    # minimal (weakest) violated: no other violated model is implied by it
    boundary = set()
    for m in impossible:
        weaker = _DESC[m] - {m}
        if not (weaker & impossible):
            boundary.add(m)
    return {
        "not": sorted(boundary),
        "also-not": sorted(impossible - boundary),
    }


def anomalies_for_models(models: Iterable[str]) -> Set[str]:
    """Which anomalies must be searched for to validate `models`."""
    out: Set[str] = set()
    for m in models:
        out |= proscribed_anomalies(m)
    return out
