"""Consistency-model lattice.

Equivalent of the reference's `elle/consistency_model.clj` (SURVEY.md §2.3):
a DAG of consistency models ordered by strength, a mapping from observed
anomalies to the models they rule out, and `friendly_boundary` reporting —
"not(serializable) but maybe(snapshot-isolation)".

The lattice covers the reference's full ~40-model surface: the Adya PL
hierarchy (PL-1 … PL-3, PL-CS, PL-2L, PL-MSR, PL-2+, PL-FCV, PL-3U), the
snapshot-isolation family (incl. prefix-consistent and parallel SI), the
Cerone transactional models (read-atomic, causal-cerone, prefix), the
session-guarantee family (monotonic-reads/writes, read-your-writes,
writes-follow-reads, PRAM, causal, sequential), single-object realtime
(linearizable), and the strong-session / strong (realtime) variants built
from process- and realtime-edge cycle anomalies.

Sources for the implication edges: Adya's thesis (PL hierarchy and G-x
phenomena), Bailis et al. HAT, Cerone et al. (RA/causal/prefix/PSI),
Terry et al. session guarantees, Daudjee & Salem strong-session models,
Viotti & Vukolić's survey (session lattice: linearizable > sequential >
causal > PRAM > {MR, MW, RYW}).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Set

# model -> models it directly implies (stronger -> weaker edges)
IMPLIES: Dict[str, List[str]] = {
    # --- serializability column (Adya PL-3 and realtime/session variants)
    "strict-serializable": ["serializable", "strong-session-serializable",
                            "strong-snapshot-isolation", "linearizable",
                            "conflict-serializable", "strong-read-committed"],
    "strong-session-serializable": ["serializable",
                                    "strong-session-read-committed"],
    "serializable": ["repeatable-read", "view-serializable", "read-atomic",
                     "update-serializable"],
    "conflict-serializable": ["view-serializable"],
    "view-serializable": [],
    # Adya PL-3U: serializability w.r.t. update transactions only
    "update-serializable": ["forward-consistent-view"],
    # Adya PL-FCV / PL-2+ / PL-2L column
    "forward-consistent-view": ["consistent-view"],
    "repeatable-read": ["cursor-stability", "consistent-view"],
    "consistent-view": ["monotonic-view"],
    "monotonic-view": ["read-committed"],
    "monotonic-snapshot-read": ["read-committed"],
    "cursor-stability": ["read-committed"],
    # --- snapshot-isolation family
    "strong-snapshot-isolation": ["snapshot-isolation",
                                  "strong-session-snapshot-isolation"],
    "strong-session-snapshot-isolation":
        ["prefix-consistent-snapshot-isolation"],
    "prefix-consistent-snapshot-isolation": ["snapshot-isolation"],
    "snapshot-isolation": ["consistent-view", "monotonic-atomic-view",
                           "read-atomic", "monotonic-snapshot-read"],
    "parallel-snapshot-isolation": ["causal-cerone"],
    # --- Cerone transactional models
    "causal-cerone": ["read-atomic", "causal"],
    "prefix": ["causal-cerone"],
    "read-atomic": ["monotonic-atomic-view"],
    "monotonic-atomic-view": ["read-committed"],
    # --- weak isolation floor
    "read-committed": ["read-uncommitted"],
    "read-uncommitted": [],
    # --- session guarantees (Terry et al.; Viotti & Vukolić ordering)
    "linearizable": ["sequential"],
    "sequential": ["causal"],
    "causal": ["PRAM", "writes-follow-reads"],
    "PRAM": ["monotonic-reads", "monotonic-writes", "read-your-writes"],
    "monotonic-reads": [],
    "monotonic-writes": [],
    "read-your-writes": [],
    "writes-follow-reads": [],
    # --- strong-session / strong (realtime) weak-isolation variants
    "strong-session-read-uncommitted": ["read-uncommitted"],
    "strong-session-read-committed": ["read-committed",
                                      "strong-session-read-uncommitted"],
    "strong-read-uncommitted": ["strong-session-read-uncommitted"],
    "strong-read-committed": ["read-committed", "strong-read-uncommitted",
                              "strong-session-read-committed"],
}

ALL_MODELS = sorted(IMPLIES.keys())

# Canonical aliases users may pass (reference supports many).
ALIASES = {
    "strict-1SR": "strict-serializable",
    "strong-serializable": "strict-serializable",
    "PL-SS": "strict-serializable",
    "PL-3": "serializable",
    "PL-3U": "update-serializable",
    "PL-FCV": "forward-consistent-view",
    "PL-2.99": "repeatable-read",
    "PL-2+": "consistent-view",
    "PL-2L": "monotonic-view",
    "PL-MSR": "monotonic-snapshot-read",
    "PL-CS": "cursor-stability",
    "PL-2": "read-committed",
    "PL-1": "read-uncommitted",
    "SI": "snapshot-isolation",
    "strong-SI": "strong-snapshot-isolation",
    "strong-session-SI": "strong-session-snapshot-isolation",
    "prefix-consistent-SI": "prefix-consistent-snapshot-isolation",
    "PSI": "parallel-snapshot-isolation",
    "serializability": "serializable",
    "sequential-consistency": "sequential",
    "causal-consistency": "causal",
    "pipelined-RAM": "PRAM",
    "pram": "PRAM",
}

# model -> anomalies it directly proscribes (closed downward over IMPLIES:
# a model also proscribes everything its weaker models do).  The session
# leaves use "<model>-violation" tokens for per-session ordering
# violations (checkers that scan per-process read/write orders emit them).
PROSCRIBED: Dict[str, Set[str]] = {
    "read-uncommitted": {"G0", "duplicate-elements", "incompatible-order",
                         "cyclic-versions", "duplicate-writes"},
    "read-committed": {"G1a", "G1b", "G1c", "dirty-update", "aborted-read",
                       "intermediate-read"},
    "monotonic-atomic-view": {"monotonic-atomic-view-violation"},
    "read-atomic": {"internal", "fractured-read"},
    "causal-cerone": {"G1c-process", "G0-process"},
    "prefix": set(),
    "parallel-snapshot-isolation": set(),
    "monotonic-view": {"G-monotonic"},
    "monotonic-snapshot-read": {"G-MSR"},
    "consistent-view": {"G-single"},
    "forward-consistent-view": {"G-SIb"},
    "update-serializable": {"G-update"},
    "cursor-stability": {"G-cursor", "lost-update"},
    "snapshot-isolation": {"G-single", "G-SI", "G-SIa", "G-SIb",
                           "lost-update"},
    "prefix-consistent-snapshot-isolation": set(),
    "repeatable-read": {"G2-item", "lost-update"},
    "serializable": {"G2-item", "G2", "G-nonadjacent", "G-single"},
    "conflict-serializable": {"G0", "G1c", "G2-item", "G2", "G-single",
                              "G-nonadjacent"},
    "view-serializable": {"G2-item"},
    "strong-session-serializable": {"G2-item-process", "G-single-process",
                                    "G-nonadjacent-process",
                                    "G1c-process", "G0-process"},
    "strong-session-snapshot-isolation": {"G-single-process", "G1c-process"},
    "strong-snapshot-isolation": {"G-single-realtime", "G1c-realtime"},
    "strict-serializable": {"G2-item-realtime", "G-single-realtime",
                            "G1c-realtime", "G0-realtime",
                            "G-nonadjacent-realtime"},
    "linearizable": set(),
    "sequential": set(),
    "causal": set(),
    "PRAM": set(),
    "monotonic-reads": {"monotonic-reads-violation"},
    "monotonic-writes": {"monotonic-writes-violation"},
    "read-your-writes": {"read-your-writes-violation"},
    "writes-follow-reads": {"writes-follow-reads-violation"},
    "strong-session-read-uncommitted": {"G0-process"},
    "strong-session-read-committed": {"G1c-process"},
    "strong-read-uncommitted": {"G0-realtime"},
    "strong-read-committed": {"G1c-realtime"},
}

assert set(PROSCRIBED) == set(IMPLIES), \
    sorted(set(PROSCRIBED) ^ set(IMPLIES))


def canonical(model: str) -> str:
    m = ALIASES.get(model, model)
    if m not in IMPLIES:
        raise ValueError(f"unknown consistency model {model!r}")
    return m


def _descendants(model: str) -> Set[str]:
    """All models implied by `model` (including itself)."""
    seen: Set[str] = set()
    stack = [model]
    while stack:
        m = stack.pop()
        if m in seen:
            continue
        seen.add(m)
        stack.extend(IMPLIES[m])
    return seen


_DESC: Dict[str, FrozenSet[str]] = {m: frozenset(_descendants(m)) for m in IMPLIES}


def proscribed_anomalies(model: str) -> Set[str]:
    """Every anomaly that rules out `model` (its own + all weaker models')."""
    out: Set[str] = set()
    for m in _DESC[canonical(model)]:
        out |= PROSCRIBED[m]
    return out


def anomaly_impossible_models(anomalies: Iterable[str]) -> Set[str]:
    """All models ruled out by any of the observed anomalies."""
    obs = set(anomalies)
    return {m for m in IMPLIES if proscribed_anomalies(m) & obs}


# niche formalisms kept out of the headline "not" line when a friendlier
# violated model exists (they still appear in "also-not") — the
# "friendly" in the reference's friendly-boundary
_NONFRIENDLY = frozenset({"conflict-serializable", "view-serializable"})


def friendly_boundary(anomalies: Iterable[str]) -> Dict[str, List[str]]:
    """Reference `elle.consistency-model/friendly-boundary`:

    {:not        — the weakest violated models (the informative boundary)
     :also-not   — all other violated models}
    """
    impossible = anomaly_impossible_models(anomalies)
    # minimal (weakest) violated: no other violated model is implied by it
    boundary = set()
    for m in impossible:
        weaker = _DESC[m] - {m}
        if not (weaker & impossible):
            boundary.add(m)
    if boundary - _NONFRIENDLY:
        boundary -= _NONFRIENDLY
    return {
        "not": sorted(boundary),
        "also-not": sorted(impossible - boundary),
    }


def anomalies_for_models(models: Iterable[str]) -> Set[str]:
    """Which anomalies must be searched for to validate `models`."""
    out: Set[str] = set()
    for m in models:
        out |= proscribed_anomalies(m)
    return out
