"""Closed-predicate checker: phantoms and predicate anomalies.

Equivalent of the reference's `elle/closed_predicate.clj` (SURVEY.md
§2.3, the last unimplemented component cell): transactions over a keyed
universe with inserts, writes, deletes, and **closed predicate reads** —
reads that return every element matching a predicate and thereby promise
completeness.  That promise is what makes phantoms checkable: a key
MISSING from a predicate read's result set binds that key to a version
that does not match, and later writes that would have matched become
anti-dependencies (the phantom edge).

Mop vocabulary (tuples, like the other workloads):

  ("insert", k, v)   insert k (must be unborn); version :init -> v
  ("w", k, v)        overwrite k with v (unique values per key)
  ("delete", k)      delete k; version v -> :dead
  ("rp", pred, res)  closed predicate read; res = {k: v} of matches.
                     pred: "all" (the whole table) or ("=", v)

Version semantics follow rw-register (unique writes; version edges from
txn-internal read/write chains and the initial state), extended with
:unborn/:dead sentinel versions per key.  Edge derivation for a
predicate read T:

  matched k=v    ->  wr  writer(v) -> T;  rw  T -> writer(next(v))
  unmatched k    ->  the bound version u is the unique non-matching
                     version consistent with the history; when that
                     binding is FORCED (pred = "all": u must be
                     :unborn/:dead; pred = ("=", x) with exactly one
                     possible non-matching version), emit
                     wr writer(u) -> T and the phantom rw T ->
                     writer(next(u)).  Ambiguous bindings emit nothing —
                     exactness first, no false positives.

Cycles are hunted with the shared taxonomy (`txn_cycles`, device rank
sweep + host classification); cycles traversing a phantom edge are
reported with the `-predicate` suffix (G2-predicate etc.), mirroring the
reference's predicate-anomaly naming.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from jepsen_tpu.checkers.elle import consistency
from jepsen_tpu.checkers.elle.graph import (
    REL_RW,
    REL_WR,
    REL_WW,
    EdgeList,
    process_edges,
    realtime_edges_subset,
)
from jepsen_tpu.checkers.elle.txn_cycles import cycle_anomalies
from jepsen_tpu.history.ops import INVOKE, OK, FAIL, History, Op

UNBORN = "unborn"
DEAD = "dead"


class _Key:
    """Per-key version chain built from the serial structure the
    workload controls (insert/write/delete order per key is recoverable
    from unique values + txn-internal chains, as in rw-register)."""

    def __init__(self):
        self.versions: List[Tuple[Any, int]] = [(UNBORN, -1)]  # (val, txn)

    def add(self, val, txn: int):
        self.versions.append((val, txn))

    def index_of(self, val) -> int:
        for i, (v, _) in enumerate(self.versions):
            if v == val:
                return i
        return -1


def _txns_of(h: History):
    """[(txn_id, type, mops, process, invoke_pos, complete_pos, orig)]"""
    out = []
    for pos, op in enumerate(h.ops):
        if op.type == INVOKE or not op.is_client_op():
            continue
        inv = h.invocation(op)
        mops = op.value if op.type == OK else (inv.value if inv is not None
                                               else op.value)
        out.append((len(out), op.type, mops or [], int(op.process),
                    inv.index if inv is not None else pos, pos, op.index))
    return out


def check(history, consistency_models: Sequence[str] = ("serializable",),
          anomalies: Sequence[str] = (), use_device: bool = True,
          max_reported: int = 8) -> Dict[str, Any]:
    """Check a closed-predicate history."""
    h = history if isinstance(history, History) else History(
        list(history), reindex=True)
    txns = _txns_of(h)
    T = len(txns)
    if T == 0 or not any(t[1] == OK for t in txns):
        return {"valid?": "unknown", "anomaly-types": [], "anomalies": {},
                "not": [], "also-not": []}

    found: Dict[str, List[Any]] = {}

    def report(name, item):
        found.setdefault(name, [])
        if len(found[name]) < max_reported:
            found[name].append(item)

    ok = np.array([t[1] == OK for t in txns])
    graph_txn = np.array([t[1] != FAIL for t in txns])

    # ---- version chains (serial recovery over ok/info writes) ----------
    # Writes are applied in completion order — the workload generator's
    # contract (unique values; predicate tests control their universe).
    keys: Dict[Any, _Key] = {}
    writer: Dict[Tuple[Any, Any], int] = {}  # (k, val) -> txn
    for (t, ttype, mops, *_rest) in txns:
        if ttype == FAIL:
            continue
        for m in mops:
            kind = m[0]
            if kind in ("insert", "w"):
                k, v = m[1], m[2]
                keys.setdefault(k, _Key()).add(v, t)
                writer[(k, v)] = t
                if kind == "insert":
                    ver = keys[k].versions
                    if len(ver) >= 2 and ver[-2][0] not in (UNBORN, DEAD):
                        report("insert-of-live-key",
                               {"key": k, "value": v, "txn": txns[t][6]})
            elif kind == "delete":
                k = m[1]
                kk = keys.setdefault(k, _Key())
                kk.add((DEAD, len(kk.versions)), t)
            elif kind == "rp":
                pass
            else:
                raise ValueError(f"unknown mop kind {m[0]!r}")

    def is_dead(v) -> bool:
        return v == UNBORN or (isinstance(v, tuple) and v[0] == DEAD)

    def matches(pred, v) -> bool:
        if is_dead(v):
            return False
        if pred == "all":
            return True
        if isinstance(pred, (tuple, list)) and pred[0] == "=":
            return v == pred[1]
        raise ValueError(f"unknown predicate {pred!r}")

    # ---- predicate read bindings + edges -------------------------------
    es: List[int] = []
    ed: List[int] = []
    er: List[int] = []
    phantom: set = set()

    def add_edge(a: int, b: int, rel: int, is_phantom=False):
        if a < 0 or b < 0 or a == b:
            return
        if not (graph_txn[a] and graph_txn[b]):
            return
        es.append(a)
        ed.append(b)
        er.append(rel)
        if is_phantom:
            phantom.add((a, b))

    # ww edges from the version chains
    for k, kk in keys.items():
        prev_writer = -1
        for (v, t) in kk.versions[1:]:
            if prev_writer >= 0:
                add_edge(prev_writer, t, REL_WW)
            prev_writer = t

    for (t, ttype, mops, *_rest) in txns:
        if ttype != OK:
            continue
        for m in mops:
            if m[0] != "rp":
                continue
            pred, res = m[1], (m[2] or {})
            # matched keys: bind the observed version
            for k, v in res.items():
                kk = keys.get(k)
                if kk is None or kk.index_of(v) < 0:
                    report("predicate-read-of-unwritten",
                           {"key": k, "value": v, "txn": txns[t][6]})
                    continue
                if not matches(pred, v):
                    report("predicate-mismatch",
                           {"key": k, "value": v, "pred": pred,
                            "txn": txns[t][6]})
                vi = kk.index_of(v)
                add_edge(writer.get((k, v), -1), t, REL_WR)
                if vi + 1 < len(kk.versions):
                    add_edge(t, kk.versions[vi + 1][1], REL_RW)
            # unmatched keys: forced bindings only (exactness first)
            for k, kk in keys.items():
                if k in res:
                    continue
                nonmatch = [i for i, (v, _) in enumerate(kk.versions)
                            if not matches(pred, v)]
                if len(nonmatch) != 1:
                    continue  # ambiguous — no edge (sound, incomplete)
                ui = nonmatch[0]
                u_writer = kk.versions[ui][1]
                if u_writer >= 0:
                    add_edge(u_writer, t, REL_WR)
                if ui + 1 < len(kk.versions):
                    # the phantom: a later version WOULD have matched,
                    # so the read anti-depends on its writer
                    add_edge(t, kk.versions[ui + 1][1], REL_RW,
                             is_phantom=True)

    dep = EdgeList()
    dep.src = np.asarray(es, np.int32)
    dep.dst = np.asarray(ed, np.int32)
    dep.rel = np.asarray(er, np.int8)

    proc = np.asarray([t[3] for t in txns], np.int64)
    inv = np.asarray([t[4] for t in txns], np.int64)
    comp = np.asarray([t[5] for t in txns], np.int64)
    pe = process_edges(np.where(graph_txn, proc, -10 ** 9 - np.arange(T)),
                       inv)
    ok_ids = np.nonzero(ok)[0]
    rte, n_b, b_ranks = realtime_edges_subset(inv, comp, ok_ids, graph_txn,
                                              T)
    edges = EdgeList.concat([dep, pe, rte]).dedup()
    n_nodes = T + n_b
    rank = np.concatenate([2 * comp, b_ranks]).astype(np.int32)

    want = set(consistency.anomalies_for_models(
        [consistency.canonical(m) for m in consistency_models]))
    want |= set(anomalies)
    orig_index = np.asarray([t[6] for t in txns], np.int32)
    cyc = cycle_anomalies(edges, n_nodes, rank, want,
                          use_device=use_device, n_txns=T,
                          orig_index=orig_index)

    # cycles through a phantom edge are predicate anomalies — rename,
    # matching the reference's predicate taxonomy
    orig_to_internal = {int(orig_index[i]): i for i in range(T)}
    for name in list(cyc.keys()):
        items = cyc.pop(name)
        for item in items:
            uses_phantom = any(
                e.get("rel") == "rw" and
                (orig_to_internal.get(e.get("src"), -1),
                 orig_to_internal.get(e.get("dst"), -2)) in phantom
                for e in item.get("cycle", []))
            out_name = f"{name}-predicate" if uses_phantom else name
            found.setdefault(out_name, []).append(item)

    found = {k: v for k, v in found.items() if _wanted(k, want)}
    anomaly_types = sorted(found.keys())
    boundary = consistency.friendly_boundary(
        [a.replace("-predicate", "") for a in anomaly_types
         if a.replace("-predicate", "") in want or a in want] +
        [a for a in anomaly_types
         if a in ("insert-of-live-key", "predicate-mismatch",
                  "predicate-read-of-unwritten")])
    bad = set(boundary["not"]) | set(boundary["also-not"])
    requested_bad = bad & {consistency.canonical(m)
                           for m in consistency_models}
    structural = {"insert-of-live-key", "predicate-mismatch",
                  "predicate-read-of-unwritten"} & set(anomaly_types)
    return {
        "valid?": not (requested_bad or structural),
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
    }


def _wanted(name: str, want: set) -> bool:
    if name in ("insert-of-live-key", "predicate-mismatch",
                "predicate-read-of-unwritten"):
        return True
    return name in want or name.replace("-predicate", "") in want
