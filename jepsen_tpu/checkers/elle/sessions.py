"""Session-guarantee checker: monotonic reads / monotonic writes /
read-your-writes / writes-follow-reads over rw-register histories.

Completes the lattice's session family (reference:
`elle/consistency_model.clj` models the guarantees; the checking idea is
the classic Terry et al. formulation over a per-key version order).  The
version order is the same one the rw-register checker infers — per-key
edges u -> v when a committed txn externally reads u (or writes blind,
u = init) and then writes v, chained through the txn's write sequence.
Only *definite* violations are reported: two versions are compared only
when one is an ancestor of the other in the version DAG, so branching
(itself an anomaly, reported elsewhere as cyclic-versions/lost-update)
never manufactures a false session violation.

Guarantees (each emits the lattice's "<model>-violation" token):
- monotonic-reads: a session's successive reads of a key never go
  backwards in the version order.
- read-your-writes: after a session writes v, its later reads of that
  key return v or a successor.
- monotonic-writes: a session's writes to a key are installed in
  session order.
- writes-follow-reads: a session's write to a key is ordered after the
  versions the session previously read from that key (the same-key
  projection of WFR — cross-key propagation needs a global causal
  order; the transactional checkers cover that via G1c-process).

Scope notes: ok txns only (an indeterminate txn's effects are not
session-ordered), external reads only (txn-internal read-own-write is
`internal`'s job), sessions = processes (the reference's convention).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

from jepsen_tpu.checkers.elle import consistency
from jepsen_tpu.history.ops import INVOKE, OK, History

GUARANTEES = ("monotonic-reads", "monotonic-writes", "read-your-writes",
              "writes-follow-reads")

#: the unwritten initial version of every key (a read returning None
#: observes it; it precedes every written version of its key)
INIT = "__init__"


def _sessions(h: History):
    """Per-process list of (invoke_index, [mops]) for ok client txns."""
    out: Dict[Any, List] = {}
    for op in h.ops:
        if op.type != INVOKE or not op.is_client_op():
            continue
        ci = h.pair_index(op.index)
        if ci < 0 or h.ops[ci].type != OK:
            continue
        out.setdefault(op.process, []).append((op.index,
                                               h.ops[ci].value))
    for seq in out.values():
        seq.sort()
    return out


def _version_dag(sessions) -> Dict[Any, Dict[Any, set]]:
    """Per-key successor sets: succ[k][u] = direct successor versions."""
    succ: Dict[Any, Dict[Any, set]] = {}
    for seq in sessions.values():
        for _, mops in seq:
            cur: Dict[Any, Any] = {}  # txn-local last version per key
            for f, k, v in mops:
                if f == "r":
                    if k not in cur:
                        cur[k] = v if v is not None else INIT
                elif f == "w":
                    u = cur.get(k, INIT)
                    succ.setdefault(k, {}).setdefault(
                        u, set()).add(v)
                    cur[k] = v
    return succ


def _ancestors(succ_k: Dict[Any, set]) -> Dict[Any, set]:
    """version -> set of strict ancestors, via DFS over the (small,
    chain-shaped in valid histories) per-key DAG."""
    anc: Dict[Any, set] = {}
    # build predecessor map
    preds: Dict[Any, set] = {}
    for u, vs in succ_k.items():
        for v in vs:
            preds.setdefault(v, set()).add(u)

    def walk(v, seen):
        if v in anc:
            return anc[v]
        if v in seen:
            return set()  # cycle: cyclic-versions territory; stay sound
        seen.add(v)
        out = set()
        for u in preds.get(v, ()):
            out.add(u)
            out |= walk(u, seen)
        anc[v] = out
        return out

    for v in list(preds):
        walk(v, set())
    return anc


def check(history, guarantees: Sequence[str] = GUARANTEES,
          max_reported: int = 8) -> Dict[str, Any]:
    """Check session guarantees; result shape matches the elle checkers."""
    h = history if isinstance(history, History) else History(history)
    sessions = _sessions(h)
    dag = _version_dag(sessions)
    anc_of = {k: _ancestors(sk) for k, sk in dag.items()}

    found: Dict[str, List[dict]] = {}

    def report(name, item):
        lst = found.setdefault(name + "-violation", [])
        if len(lst) < max_reported:
            lst.append(item)

    def precedes(k, a, b) -> bool:
        """a is a strict ancestor of b in key k's version order."""
        return a in anc_of.get(k, {}).get(b, ())

    want = set(guarantees)
    for proc, seq in sessions.items():
        last_read: Dict[Any, Any] = {}   # key -> last externally read ver
        last_write: Dict[Any, Any] = {}  # key -> last written ver
        for inv, mops in seq:
            cur: Dict[Any, Any] = {}
            for f, k, v in mops:
                if f == "r":
                    if k in cur:
                        continue  # internal read: `internal`'s job
                    if v is None:
                        v = INIT  # observed the unwritten initial state
                    if "monotonic-reads" in want and k in last_read and \
                            precedes(k, v, last_read[k]):
                        report("monotonic-reads",
                               {"process": proc, "op": inv, "key": k,
                                "read": v, "after-reading": last_read[k]})
                    if "read-your-writes" in want and k in last_write and \
                            precedes(k, v, last_write[k]):
                        report("read-your-writes",
                               {"process": proc, "op": inv, "key": k,
                                "read": v, "after-writing": last_write[k]})
                    last_read[k] = v
                    cur[k] = v
                elif f == "w":
                    if "monotonic-writes" in want and k in last_write and \
                            precedes(k, v, last_write[k]):
                        report("monotonic-writes",
                               {"process": proc, "op": inv, "key": k,
                                "wrote": v, "after-writing": last_write[k]})
                    if "writes-follow-reads" in want and k in last_read \
                            and precedes(k, v, last_read[k]):
                        report("writes-follow-reads",
                               {"process": proc, "op": inv, "key": k,
                                "wrote": v, "after-reading": last_read[k]})
                    last_write[k] = v
                    cur[k] = v

    anomaly_types = sorted(found)
    boundary = consistency.friendly_boundary(anomaly_types)
    return {
        "valid?": not found,
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
    }
