"""Session-guarantee checker: monotonic reads / monotonic writes /
read-your-writes / writes-follow-reads over rw-register histories.

Completes the lattice's session family (reference:
`elle/consistency_model.clj` models the guarantees; the checking idea is
the classic Terry et al. formulation over a per-key version order).  The
version order is the same one the rw-register checker infers — per-key
edges u -> v when a committed txn externally reads u (or writes blind,
u = init) and then writes v, chained through the txn's write sequence.
Only *definite* violations are reported: two versions are compared only
when one is an ancestor of the other in the version DAG, so branching
(itself an anomaly, reported elsewhere as cyclic-versions/lost-update)
never manufactures a false session violation.

Guarantees (each emits the lattice's "<model>-violation" token):
- monotonic-reads: a session's successive reads of a key never go
  backwards in the version order.
- read-your-writes: after a session writes v, its later reads of that
  key return v or a successor.
- monotonic-writes: a session's writes to a key are installed in
  session order.
- writes-follow-reads: a session's write to a key is ordered after the
  versions the session previously read from that key — PLUS the
  cross-key propagation side (round 5, VERDICT r04 item 8): if session
  S1 read u(k1) and then wrote v(k2), any session that causally
  observes v (reads v or a DAG successor of it) and afterwards reads
  k1 must see u or a successor — an older read demonstrates v applied
  before the write it depends on.
- monotonic-writes likewise gets the cross-key side: S1 wrote w1(k1)
  then v(k2); an observer of v that afterwards reads k1 older than w1
  saw S1's writes applied out of session order.

Cross-key detection is two-pass: pass A registers, for every written
version, the writer session's prior reads/writes per other key (its
causal dependencies); pass B walks each session online, activating
obligations when a read causally includes a registered version and
reporting definite regressions on later reads.  All comparisons stay
ancestor-definite, so DAG branching never manufactures violations.

Scope notes: ok txns only (an indeterminate txn's effects are not
session-ordered), external reads only (txn-internal read-own-write is
`internal`'s job), sessions = processes (the reference's convention).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

from jepsen_tpu.checkers.elle import consistency
from jepsen_tpu.history.ops import INVOKE, OK, History

GUARANTEES = ("monotonic-reads", "monotonic-writes", "read-your-writes",
              "writes-follow-reads")

#: the unwritten initial version of every key (a read returning None
#: observes it; it precedes every written version of its key)
INIT = "__init__"


def _sessions(h: History):
    """Per-process list of (invoke_index, [mops]) for ok client txns."""
    out: Dict[Any, List] = {}
    for op in h.ops:
        if op.type != INVOKE or not op.is_client_op():
            continue
        ci = h.pair_index(op.index)
        if ci < 0 or h.ops[ci].type != OK:
            continue
        out.setdefault(op.process, []).append((op.index,
                                               h.ops[ci].value))
    for seq in out.values():
        seq.sort()
    return out


def _version_dag(sessions) -> Dict[Any, Dict[Any, set]]:
    """Per-key successor sets: succ[k][u] = direct successor versions."""
    succ: Dict[Any, Dict[Any, set]] = {}
    for seq in sessions.values():
        for _, mops in seq:
            cur: Dict[Any, Any] = {}  # txn-local last version per key
            for f, k, v in mops:
                if f == "r":
                    if k not in cur:
                        cur[k] = v if v is not None else INIT
                elif f == "w":
                    u = cur.get(k, INIT)
                    succ.setdefault(k, {}).setdefault(
                        u, set()).add(v)
                    cur[k] = v
    return succ


def _ancestors(succ_k: Dict[Any, set]) -> Dict[Any, set]:
    """version -> set of strict ancestors, via DFS over the (small,
    chain-shaped in valid histories) per-key DAG."""
    anc: Dict[Any, set] = {}
    # build predecessor map
    preds: Dict[Any, set] = {}
    for u, vs in succ_k.items():
        for v in vs:
            preds.setdefault(v, set()).add(u)

    def walk(v, seen):
        if v in anc:
            return anc[v]
        if v in seen:
            return set()  # cycle: cyclic-versions territory; stay sound
        seen.add(v)
        out = set()
        for u in preds.get(v, ()):
            out.add(u)
            out |= walk(u, seen)
        anc[v] = out
        return out

    for v in list(preds):
        walk(v, set())
    return anc


def check(history, guarantees: Sequence[str] = GUARANTEES,
          max_reported: int = 8) -> Dict[str, Any]:
    """Check session guarantees; result shape matches the elle checkers."""
    h = history if isinstance(history, History) else History(history)
    sessions = _sessions(h)
    dag = _version_dag(sessions)
    anc_of = {k: _ancestors(sk) for k, sk in dag.items()}

    found: Dict[str, List[dict]] = {}

    def report(name, item):
        lst = found.setdefault(name + "-violation", [])
        if len(lst) < max_reported:
            lst.append(item)

    def precedes(k, a, b) -> bool:
        """a is a strict ancestor of b in key k's version order."""
        return a in anc_of.get(k, {}).get(b, ())

    want = set(guarantees)

    # ---- pass A: per-written-version causal dependencies (cross-key) ----
    # wfr_dep[(k, v)] = {k1: u} — writer session had read u(k1) before
    # writing v(k2); mw_dep likewise for its prior writes.
    wfr_dep: Dict[tuple, Dict[Any, Any]] = {}
    mw_dep: Dict[tuple, Dict[Any, Any]] = {}
    if "writes-follow-reads" in want or "monotonic-writes" in want:
        for proc, seq in sessions.items():
            lr: Dict[Any, Any] = {}
            lw: Dict[Any, Any] = {}
            for inv, mops in seq:
                cur: Dict[Any, Any] = {}
                for f, k, v in mops:
                    if f == "r":
                        if k in cur:
                            continue
                        lr[k] = cur[k] = v if v is not None else INIT
                    elif f == "w":
                        d = {k1: u for k1, u in lr.items() if k1 != k}
                        if d:
                            wfr_dep[(k, v)] = d
                        dw = {k1: w for k1, w in lw.items() if k1 != k}
                        if dw:
                            mw_dep[(k, v)] = dw
                        lw[k] = cur[k] = v

    # ---- pass B: per-session walk (same-key rules + obligations) --------
    for proc, seq in sessions.items():
        last_read: Dict[Any, Any] = {}   # key -> last externally read ver
        last_write: Dict[Any, Any] = {}  # key -> last written ver
        # cross-key obligations activated by causally-observed versions:
        # reads of k1 must not precede any version in oblig_*[k1]
        oblig_wfr: Dict[Any, set] = {}
        oblig_mw: Dict[Any, set] = {}
        for inv, mops in seq:
            cur: Dict[Any, Any] = {}
            for f, k, v in mops:
                if f == "r":
                    if k in cur:
                        continue  # internal read: `internal`'s job
                    if v is None:
                        v = INIT  # observed the unwritten initial state
                    # cross-key checks against previously activated
                    # obligations (check BEFORE activating this read's)
                    if "writes-follow-reads" in want:
                        for u in oblig_wfr.get(k, ()):
                            if precedes(k, v, u):
                                report("writes-follow-reads",
                                       {"process": proc, "op": inv,
                                        "key": k, "read": v,
                                        "cross-key-dependency": u})
                                break
                    if "monotonic-writes" in want:
                        for w in oblig_mw.get(k, ()):
                            if precedes(k, v, w):
                                report("monotonic-writes",
                                       {"process": proc, "op": inv,
                                        "key": k, "read": v,
                                        "cross-key-prior-write": w})
                                break
                    if wfr_dep or mw_dep:
                        for ver in ({v} | anc_of.get(k, {}).get(v, set())):
                            for k1, u in wfr_dep.get((k, ver), {}).items():
                                oblig_wfr.setdefault(k1, set()).add(u)
                            for k1, w in mw_dep.get((k, ver), {}).items():
                                oblig_mw.setdefault(k1, set()).add(w)
                    if "monotonic-reads" in want and k in last_read and \
                            precedes(k, v, last_read[k]):
                        report("monotonic-reads",
                               {"process": proc, "op": inv, "key": k,
                                "read": v, "after-reading": last_read[k]})
                    if "read-your-writes" in want and k in last_write and \
                            precedes(k, v, last_write[k]):
                        report("read-your-writes",
                               {"process": proc, "op": inv, "key": k,
                                "read": v, "after-writing": last_write[k]})
                    last_read[k] = v
                    cur[k] = v
                elif f == "w":
                    if "monotonic-writes" in want and k in last_write and \
                            precedes(k, v, last_write[k]):
                        report("monotonic-writes",
                               {"process": proc, "op": inv, "key": k,
                                "wrote": v, "after-writing": last_write[k]})
                    if "writes-follow-reads" in want and k in last_read \
                            and precedes(k, v, last_read[k]):
                        report("writes-follow-reads",
                               {"process": proc, "op": inv, "key": k,
                                "wrote": v, "after-reading": last_read[k]})
                    last_write[k] = v
                    cur[k] = v

    anomaly_types = sorted(found)
    boundary = consistency.friendly_boundary(anomaly_types)
    return {
        "valid?": not found,
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
    }


def check_la(history, guarantees: Sequence[str] = GUARANTEES,
             max_reported: int = 8) -> Dict[str, Any]:
    """Session guarantees over LIST-APPEND histories (VERDICT r04 item 4:
    session models were checkable only on rw-register).

    The per-key version order is the longest ok read of the key — the
    same order the list-append checkers infer — and a read's observed
    version is its list length (reads are prefixes of the order;
    disagreement is `incompatible-order`, reported by the main checker
    and making the history invalid regardless).  Definite-violation
    rules under prefix semantics:

    - monotonic-reads: a session's later read of a key is shorter than
      an earlier one (the observed prefix went backwards).
    - read-your-writes: a session's earlier committed append v to k is
      absent from a later read of k (v's global position can only be
      past the read's end, so the read observed a pre-v state).
    - monotonic-writes: a session's appends v1 then v2 (separate txns)
      land in the key's order with v2 before v1.
    - writes-follow-reads: a session's append v lands inside a prefix
      the session had already read (pos(v) < earlier read length) —
      v was installed before versions the session had observed.

    Cross-key (VERDICT r04 item 8), prefix semantics: when S1 read n1
    elements of k1 and then appended v to k2, a session whose read of
    k2 contains v must afterwards see >= n1 elements of k1 (WFR), and
    must see S1's prior appends to other keys present (MW); shorter /
    missing reads demonstrate v applied before its dependencies.
    """
    h = history if isinstance(history, History) else History(history)
    sessions = _sessions(h)

    # per-key order: the longest ok read (list values), like the
    # list-append checkers' version inference
    order_pos: Dict[Any, Dict[Any, int]] = {}
    order_len: Dict[Any, int] = {}
    for seq in sessions.values():
        for _, mops in seq:
            for f, k, v in mops:
                if f == "r" and isinstance(v, (list, tuple)) and \
                        len(v) > order_len.get(k, -1):
                    order_len[k] = len(v)
                    order_pos[k] = {e: i for i, e in enumerate(v)}

    found: Dict[str, List[dict]] = {}

    def report(name, item):
        lst = found.setdefault(name + "-violation", [])
        if len(lst) < max_reported:
            lst.append(item)

    want = set(guarantees)

    # ---- pass A: per-appended-value causal dependencies (cross-key) ----
    wfr_dep: Dict[tuple, Dict[Any, int]] = {}   # (k, v) -> {k1: read len}
    mw_dep: Dict[tuple, Dict[Any, Any]] = {}    # (k, v) -> {k1: prior val}
    if "writes-follow-reads" in want or "monotonic-writes" in want:
        for proc, seq in sessions.items():
            lrl: Dict[Any, int] = {}
            lap: Dict[Any, Any] = {}
            for inv, mops in seq:
                seen: set = set()
                added: set = set()
                for f, k, v in mops:
                    if f == "r":
                        if k in seen or k in added or \
                                not isinstance(v, (list, tuple)):
                            continue
                        seen.add(k)
                        lrl[k] = max(len(v), lrl.get(k, 0))
                    elif f == "append":
                        d = {k1: n for k1, n in lrl.items()
                             if k1 != k and n > 0}
                        if d:
                            wfr_dep[(k, v)] = d
                        dw = {k1: w for k1, w in lap.items() if k1 != k}
                        if dw:
                            mw_dep[(k, v)] = dw
                        added.add(k)
                        lap[k] = v

    # ---- pass B: per-session walk --------------------------------------
    for proc, seq in sessions.items():
        last_read_len: Dict[Any, int] = {}
        last_appended: Dict[Any, List[Any]] = {}
        oblig_wfr: Dict[Any, int] = {}   # k1 -> min required read length
        oblig_mw: Dict[Any, set] = {}    # k1 -> values that must appear
        for inv, mops in seq:
            seen_in_txn: set = set()
            appended_in_txn: set = set()
            for f, k, v in mops:
                if f == "r":
                    if k in seen_in_txn or k in appended_in_txn or \
                            not isinstance(v, (list, tuple)):
                        # own-append contamination / repeat read:
                        # `internal`'s job; unknown reads carry nothing
                        continue
                    seen_in_txn.add(k)
                    n = len(v)
                    # cross-key checks (before activating this read's)
                    if "writes-follow-reads" in want and \
                            n < oblig_wfr.get(k, 0):
                        report("writes-follow-reads",
                               {"process": proc, "op": inv, "key": k,
                                "read-len": n,
                                "cross-key-required-len": oblig_wfr[k]})
                    if "monotonic-writes" in want and k in oblig_mw:
                        missing = [w for w in oblig_mw[k]
                                   if w not in set(v)]
                        if missing:
                            report("monotonic-writes",
                                   {"process": proc, "op": inv, "key": k,
                                    "cross-key-missing-writes":
                                        missing[:4]})
                    if wfr_dep or mw_dep:
                        for el in v:
                            for k1, n1 in wfr_dep.get((k, el), {}).items():
                                if n1 > oblig_wfr.get(k1, 0):
                                    oblig_wfr[k1] = n1
                            for k1, w in mw_dep.get((k, el), {}).items():
                                oblig_mw.setdefault(k1, set()).add(w)
                    if "monotonic-reads" in want and \
                            n < last_read_len.get(k, -1):
                        report("monotonic-reads",
                               {"process": proc, "op": inv, "key": k,
                                "read-len": n,
                                "after-read-len": last_read_len[k]})
                    if "read-your-writes" in want:
                        missing = [w for w in last_appended.get(k, ())
                                   if w not in set(v)]
                        if missing:
                            report("read-your-writes",
                                   {"process": proc, "op": inv, "key": k,
                                    "missing-own-appends": missing[:4]})
                    last_read_len[k] = max(n, last_read_len.get(k, -1))
                elif f == "append":
                    pos = order_pos.get(k, {}).get(v)
                    if "monotonic-writes" in want and pos is not None:
                        for w in last_appended.get(k, ()):
                            wp = order_pos.get(k, {}).get(w)
                            if wp is not None and pos < wp:
                                report("monotonic-writes",
                                       {"process": proc, "op": inv,
                                        "key": k, "appended": v,
                                        "after-appending": w})
                    if "writes-follow-reads" in want and pos is not None \
                            and pos < last_read_len.get(k, 0):
                        report("writes-follow-reads",
                               {"process": proc, "op": inv, "key": k,
                                "appended": v,
                                "inside-read-prefix-len":
                                    last_read_len[k]})
                    appended_in_txn.add(k)
                    last_appended.setdefault(k, []).append(v)

    anomaly_types = sorted(found)
    boundary = consistency.friendly_boundary(anomaly_types)
    return {
        "valid?": not found,
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
    }
