"""Device-side rw-register inference + fused core check.

The TPU half of the `elle/rw_register.clj` equivalent (SURVEY.md §2.3,
§7 stage 5): version-graph inference, non-cycle anomaly scans, txn
dependency edges and the 5-projection cycle sweep, all under one
`jax.jit` over the padded SoA arrays — the rw-register analogue of
`device_core.core_check` (round-2 VERDICT item 3: inference was
host-numpy only; BASELINE config 3 is a 1M-op rw-register history).

The inference is an exact jnp port of the host checker's vectorized
numpy (`rw_register.py` — which remains the semantic oracle, and whose
verdicts the fused check is differentially tested against):

- writers: committed-priority scatter-min (ok > info > fail) so an
  aborted duplicate cannot fabricate a G1a;
- per-(txn, key) runs via one lexsort; txn-local state (cur-before),
  final writes and last-write positions from segmented scans;
- version edges u -> v (or init(k) -> v for blind writes); cyclic
  versions detected by a rank sweep over the version graph (value-id
  ranks: inference contradictions are the backward edges);
- txn edges: wr (reader of v <- writer(v)), ww (writer(u) -> writer(v)),
  rw (external readers of u -> writer(v)) — the reader x version-edge
  join is shape-static: prefix-sum offsets + searchsorted expansion into
  a fixed `rw_cap` slot budget with exact overflow reporting (the device
  never silently truncates; callers regrow or fall back to the host).

Bit layout of the result: [duplicate-writes, internal, G1a, G1b,
lost-update, cyclic-versions, cycle-proj0..4, converged].
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.checkers.elle.device_core import (
    PROJECTIONS,
    chain_include_stack,
    proj_include_stack,
)
from jepsen_tpu.checkers.elle.device_infer import PaddedLA, pad_packed
from jepsen_tpu.history.soa import (
    MOP_APPEND,
    MOP_READ,
    TXN_FAIL,
    TXN_INFO,
    TXN_OK,
    PackedTxns,
)
from jepsen_tpu.ops.cycle_sweep import _sweep_arrays, projection_scan
from jepsen_tpu.ops.segments import segmented_cummax, segmented_cumsum

BIG = jnp.int32(2 ** 30)
NO_PREV = jnp.int32(-3)

COUNT_NAMES_RW = ("duplicate-writes", "internal", "G1a", "G1b",
                  "lost-update", "cyclic-versions")


@partial(jax.jit, static_argnames=("n_keys", "rw_cap"))
def infer_rw(h: PaddedLA, n_keys: int, rw_cap: int = 0):
    """Inference over a padded rw-register history.  Returns a dict of
    counts, edges, chains, ranks (same shape contract as
    `device_infer.infer`) plus version-graph arrays and the rw-join
    overflow (edges beyond rw_cap that could NOT be emitted)."""
    T = h.txn_type.shape[0]
    M = h.mop_txn.shape[0]
    V = h.rd_elems.shape[0]  # value-id capacity (same convention as la)
    nk = max(n_keys, 1)
    VN = V + nk              # version nodes: values + one init per key
    CAP = rw_cap or M

    ttype = h.txn_type
    ok = ttype == TXN_OK
    graph_txn = ok | (ttype == TXN_INFO)

    kind = jnp.where(h.mop_mask, h.mop_kind, -1)
    mtxn = jnp.clip(h.mop_txn, 0, T - 1)
    is_w = h.mop_mask & (kind == MOP_APPEND) & (h.mop_val >= 0)
    is_r = h.mop_mask & (kind == MOP_READ)
    known = jnp.where(is_r, h.mop_rd_len >= 0, h.mop_mask)
    mop_pos = jnp.arange(M, dtype=jnp.int32)

    # ---- writers: committed-priority (ok=0 < info=1 < fail=2, then pos)
    wt = ttype[mtxn]
    prio = jnp.where(ok[mtxn], 0, jnp.where(wt == TXN_INFO, 1, 2))
    enc = prio.astype(jnp.int32) * M + mop_pos
    val_slot = jnp.where(is_w, h.mop_val, V)
    enc_min = jnp.full(V + 1, 3 * M + M, jnp.int32).at[val_slot].min(
        jnp.where(is_w, enc, 3 * M + M))[:V]
    have_writer = enc_min < 3 * M + M
    writer = jnp.where(have_writer, mtxn[jnp.clip(enc_min % M, 0, M - 1)],
                       -1)
    writer_type = jnp.where(writer >= 0,
                            ttype[jnp.clip(writer, 0, T - 1)], 0)
    w_count = jnp.zeros(V + 1, jnp.int32).at[val_slot].add(
        is_w.astype(jnp.int32))[:V]
    duplicate_writes = jnp.sum((w_count > 1).astype(jnp.int32))

    # ---- (txn, key) runs --------------------------------------------------
    run_sort = jnp.lexsort((mop_pos,
                            jnp.where(h.mop_mask, h.mop_key, nk),
                            jnp.where(h.mop_mask, h.mop_txn, T)))
    rt = mtxn[run_sort]
    rk = jnp.where(h.mop_mask, h.mop_key, nk)[run_sort]
    rkind = kind[run_sort]
    rval = h.mop_val[run_sort]
    rknown = known[run_sort]
    rmask = h.mop_mask[run_sort]
    t2 = jnp.where(rmask, rt, T)
    run_start = jnp.concatenate([jnp.ones(1, bool),
                                 (t2[1:] != t2[:-1]) | (rk[1:] != rk[:-1])])
    run_end = jnp.concatenate([run_start[1:], jnp.ones(1, bool)])
    q = jnp.arange(M, dtype=jnp.int32)

    # last write position within the run (suffix max = reversed cummax)
    wpos = jnp.where(rmask & (rkind == MOP_APPEND), q, -1)
    last_w = segmented_cummax(wpos[::-1], run_end[::-1])[::-1]

    # final write per value: the run's last write mop
    r_final = rmask & (rkind == MOP_APPEND) & (q == last_w)
    is_final = jnp.zeros(V + 1, bool).at[
        jnp.where(r_final, rval, V)].max(r_final)[:V]

    # txn-local state before each mop (cur-before): previous defining mop
    defines = rmask & ((rkind == MOP_APPEND) |
                       ((rkind == MOP_READ) & rknown))
    def_val = jnp.where(rkind == MOP_APPEND, rval,
                        jnp.where(rval >= 0, rval, V + rk)).astype(jnp.int32)
    def_pos = jnp.where(defines, q, -1)
    prev_def = segmented_cummax(def_pos, run_start, exclusive=True,
                                neutral=-1)
    cur_before = jnp.where(prev_def >= 0,
                           def_val[jnp.clip(prev_def, 0, M - 1)], NO_PREV)

    r_is_read = rmask & (rkind == MOP_READ) & rknown & ok[rt]
    external_read = r_is_read & (cur_before == NO_PREV)

    # ---- internal ---------------------------------------------------------
    internal_bad = r_is_read & (cur_before != NO_PREV) & \
        (def_val != cur_before)
    internal = jnp.sum(internal_bad.astype(jnp.int32))

    # ---- G1a / G1b on external reads of real values -----------------------
    ev = jnp.clip(def_val, 0, V - 1)
    ext_real = external_read & (def_val < V)
    has_w = ext_real & (writer[ev] >= 0)
    g1a = has_w & (writer_type[ev] == TXN_FAIL)
    g1a_count = jnp.sum(g1a.astype(jnp.int32))
    g1b = has_w & (~is_final[ev]) & (writer[ev] != rt)
    g1b_count = jnp.sum(g1b.astype(jnp.int32))

    # ---- version edges ----------------------------------------------------
    ve_ok = rmask & (rkind == MOP_APPEND) & (rval >= 0) & graph_txn[rt]
    ve_u = jnp.where(cur_before >= 0, cur_before, V + rk).astype(jnp.int32)
    ve_v = jnp.clip(rval, 0, V - 1).astype(jnp.int32)
    # version-node ranks: init(k) -> k (first), value v -> nk + v; edges
    # against value-id order are the backward edges of the version sweep
    rank_v = jnp.concatenate([
        nk + jnp.arange(V, dtype=jnp.int32),
        jnp.arange(nk, dtype=jnp.int32)])  # node V+k = init(k)

    # ---- lost update ------------------------------------------------------
    # external reads of u whose txn later writes the key; >= 2 distinct
    # txns per u is a lost update
    upd = external_read & (last_w > q)
    u_key = jnp.where(upd, def_val, VN + 1)
    u_txn = jnp.where(upd, rt, T)
    lo_ord = jnp.lexsort((u_txn, u_key))
    su = u_key[lo_ord]
    st = u_txn[lo_ord]
    s_valid = su < VN + 1
    uniq_pair = s_valid & jnp.concatenate(
        [jnp.ones(1, bool), (su[1:] != su[:-1]) | (st[1:] != st[:-1])])
    grp_start = jnp.concatenate([jnp.ones(1, bool), su[1:] != su[:-1]])
    grp_end = jnp.concatenate([grp_start[1:], jnp.ones(1, bool)])
    grp_cnt = segmented_cumsum(uniq_pair.astype(jnp.int32), grp_start)
    lost_update = jnp.sum((grp_end & s_valid &
                           (grp_cnt >= 2)).astype(jnp.int32))

    # ---- txn dependency edges --------------------------------------------
    def edge_mask(src, dst, base):
        return base & (src >= 0) & (dst >= 0) & (src != dst) & \
            graph_txn[jnp.clip(src, 0, T - 1)] & \
            graph_txn[jnp.clip(dst, 0, T - 1)]

    # wr: writer(v) -> external reader of v
    wr_src = jnp.where(ext_real, writer[ev], -1)
    wr_dst = rt
    wr_ok = edge_mask(wr_src, wr_dst, ext_real)

    # ww: writer(u) -> writer(v) over version edges with real u
    ww_u_real = ve_ok & (ve_u < V)
    ww_src = jnp.where(ww_u_real, writer[jnp.clip(ve_u, 0, V - 1)], -1)
    ww_dst = jnp.where(ve_ok, writer[ve_v], -1)
    ww_ok = edge_mask(ww_src, ww_dst, ww_u_real)

    # rw: external readers of u -> writer(v) per version edge (u, v);
    # shape-static join: sort readers by value, prefix-sum slot offsets,
    # expand into CAP slots via searchsorted
    S_NOREAD = jnp.int32(VN + 2)
    S_NOEDGE = jnp.int32(VN + 3)
    rdv = jnp.where(external_read, def_val, S_NOREAD)
    r_ord = jnp.argsort(rdv, stable=True)
    rv_sorted = rdv[r_ord]
    rt_sorted = rt[r_ord]
    e_wdst = jnp.where(ve_ok, writer[ve_v], -1)
    e_usable = ve_ok & (e_wdst >= 0) & graph_txn[jnp.clip(e_wdst, 0, T - 1)]
    e_u = jnp.where(e_usable, ve_u, S_NOEDGE)
    lo = jnp.searchsorted(rv_sorted, e_u, side="left")
    hi = jnp.searchsorted(rv_sorted, e_u, side="right")
    cnt = jnp.where(e_usable, hi - lo, 0).astype(jnp.int32)
    offsets = jnp.cumsum(cnt)
    total = offsets[-1]
    j = jnp.arange(CAP, dtype=jnp.int32)
    e_j = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32)
    e_jc = jnp.clip(e_j, 0, M - 1)
    prev_off = jnp.where(e_j > 0, offsets[jnp.clip(e_j - 1, 0, M - 1)], 0)
    off = j - prev_off
    valid_j = (j < total) & (e_j < M)
    reader_j = rt_sorted[jnp.clip(lo[e_jc] + off, 0, M - 1)]
    rw_src = jnp.where(valid_j, reader_j, -1)
    rw_dst = jnp.where(valid_j, e_wdst[e_jc], -1)
    rw_ok = edge_mask(rw_src, rw_dst, valid_j)
    rw_overflow = jnp.maximum(total - CAP, 0)

    # ---- process chains + realtime barriers (same as la infer) ------------
    tidx = jnp.arange(T, dtype=jnp.int32)
    rank_txn = jnp.where(h.txn_mask, 2 * h.txn_complete_pos, BIG + tidx)
    pslot = jnp.where(h.txn_mask & graph_txn, h.txn_process, BIG)
    porder = jnp.lexsort((h.txn_invoke_pos, pslot))
    p_nodes = porder.astype(jnp.int32)
    p_sorted = pslot[porder]
    p_mask = p_sorted < BIG
    p_starts = jnp.concatenate([jnp.ones(1, bool),
                                p_sorted[1:] != p_sorted[:-1]])
    bslot = jnp.where(h.txn_mask & ok, h.txn_complete_pos, BIG)
    border = jnp.argsort(bslot)
    b_txn = border.astype(jnp.int32)
    b_mask = bslot[border] < BIG
    barrier_node = (T + tidx).astype(jnp.int32)
    rank_barrier = jnp.where(b_mask, 2 * bslot[border] + 1, BIG + T + tidx)
    b_starts = jnp.concatenate([jnp.ones(1, bool), jnp.zeros(T - 1, bool)])
    tb_src = b_txn
    tb_dst = barrier_node
    tb_ok = b_mask
    comp_sorted = jnp.where(b_mask, bslot[border], BIG)
    bi = jnp.searchsorted(comp_sorted, h.txn_invoke_pos, side="left") - 1
    bt_ok = h.txn_mask & graph_txn & (bi >= 0)
    bt_src = (T + jnp.clip(bi, 0, T - 1)).astype(jnp.int32)
    bt_dst = tidx

    return {
        "counts": {
            "duplicate-writes": duplicate_writes,
            "internal": internal,
            "G1a": g1a_count,
            "G1b": g1b_count,
            "lost-update": lost_update,
        },
        "edges": {
            "ww": (ww_src, ww_dst, ww_ok),
            "wr": (wr_src, wr_dst, wr_ok),
            "rw": (rw_src, rw_dst, rw_ok),
            "tb": (tb_src, tb_dst, tb_ok),
            "bt": (bt_src, bt_dst, bt_ok),
        },
        "chains": {
            "process": (p_nodes, p_starts, p_mask),
            "barrier": (barrier_node, b_starts, b_mask),
        },
        "ranks": {
            "txn": rank_txn.astype(jnp.int32),
            "barrier": rank_barrier.astype(jnp.int32),
        },
        "versions": {
            # node count is static (V + nk) — recomputed by callers, NOT
            # returned here (a jit output would turn it into a tracer)
            "src": jnp.where(ve_ok, ve_u, 0),
            "dst": jnp.where(ve_ok, ve_v, 0),
            "mask": ve_ok,
            "rank": rank_v,
        },
        "rw_overflow": rw_overflow,
    }


def _cc_call(h, n_keys, max_k, max_rounds, rw_cap):
    """The guarded dispatch body: rw_core_check through the AOT compile
    cache (memory table -> persisted executable -> compile+persist,
    plain jit on any failure — see jepsen_tpu.compilecache)."""
    from jepsen_tpu import compilecache

    return compilecache.call("elle.rw-core-check", rw_core_check, h,
                             n_keys=n_keys, max_k=max_k,
                             max_rounds=max_rounds, rw_cap=rw_cap)


@partial(jax.jit, static_argnames=("n_keys", "max_k", "max_rounds",
                                   "rw_cap"))
def rw_core_check(h: PaddedLA, n_keys: int, max_k: int = 128,
                  max_rounds: int = 64, rw_cap: int = 0
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused device verdict for an rw-register history.

    Returns (bits, overflowed, rw_overflow):
    bits: (12,) int32 — [6 counts per COUNT_NAMES_RW, 5 projection cycle
    flags, converged]; overflowed: backward edges beyond max_k across all
    sweeps (grow and retry); rw_overflow: rw-join edges beyond rw_cap
    (grow rw_cap or fall back to the host checker)."""
    out = infer_rw(h, n_keys, rw_cap=rw_cap)
    T = h.txn_type.shape[0]
    edges = out["edges"]
    chains = out["chains"]
    rank = jnp.concatenate([out["ranks"]["txn"], out["ranks"]["barrier"]])
    e_src = jnp.concatenate([edges[k][0] for k in ("ww", "wr", "rw", "tb",
                                                   "bt")])
    e_dst = jnp.concatenate([edges[k][1] for k in ("ww", "wr", "rw", "tb",
                                                   "bt")])
    masks = {k: edges[k][2] for k in ("ww", "wr", "rw", "tb", "bt")}

    pc_nodes, pc_starts, pc_mask = chains["process"]
    bc_nodes, bc_starts, bc_mask = chains["barrier"]
    chain_nodes = jnp.concatenate([pc_nodes, bc_nodes])
    chain_starts = jnp.concatenate([pc_starts, bc_starts])

    # one sweep instantiation scanned over the 5 projections via the
    # shared hoisted form (family-include flags + one shared backward
    # enumeration; see cycle_sweep.projection_scan / PROFILE.md §0b)
    conv_all, overflow, cyc_bits = projection_scan(
        2 * T, max_k, max_rounds, rank, e_src, e_dst,
        [masks[k] for k in ("ww", "wr", "rw", "tb", "bt")],
        proj_include_stack(PROJECTIONS),
        chain_nodes, chain_starts, [pc_mask, bc_mask],
        chain_include_stack(PROJECTIONS))

    # cyclic versions: rank sweep over the version graph (no chains)
    ver = out["versions"]
    vn_nodes = h.rd_elems.shape[0] + max(n_keys, 1)  # static: V + nk
    vempty_i = jnp.zeros(0, jnp.int32)
    vempty_b = jnp.zeros(0, bool)
    v_has, _, v_back, v_conv = _sweep_arrays(
        vn_nodes, max_k, max_rounds, ver["rank"],
        ver["src"], ver["dst"], ver["mask"], vempty_i, vempty_b, vempty_b)
    conv_all = conv_all & v_conv
    overflow = jnp.maximum(overflow, jnp.maximum(v_back - max_k, 0))

    counts = jnp.stack(
        [out["counts"][n].astype(jnp.int32) for n in COUNT_NAMES_RW[:-1]]
        + [v_has.astype(jnp.int32)])
    bits = jnp.concatenate(
        [counts, cyc_bits, conv_all.astype(jnp.int32)[None]])
    return bits, overflow, out["rw_overflow"]


RW_CAP_LIMIT = 1 << 24


def check(p: PackedTxns | PaddedLA, n_keys: int = None, max_k: int = 128,
          max_rounds: int = 64, deadline=None, policy=None,
          plan=None) -> dict:
    """Fused device check of an rw-register history; summary dict in the
    `check_sharded` row format.  Grows the backward-edge and rw-join
    budgets on overflow (exactness first); returns "unknown" only when
    every budget is exhausted — callers then use the host checker.

    Resilience: the fused jit seam runs under the device guard
    (transient retries per `policy`, synthetic faults per `plan`);
    `deadline` is polled before each grow-retry and raises
    `DeadlineExceeded` on expiry — `rw_register.check` and `check_safe`
    map that to an unknown/degraded verdict."""
    from jepsen_tpu.checkers.elle.device_core import (
        MAX_K_CAP,
        MAX_ROUNDS_CAP,
    )

    from jepsen_tpu import resilience, telemetry

    h = p if isinstance(p, PaddedLA) else pad_packed(p)
    n_keys = h.n_keys if n_keys is None else n_keys
    rw_cap = h.mop_txn.shape[0]

    # one phase span over the whole fused check incl. grow-retries
    # (infer/graph-build/cycle-sweep are fused in one jit program here,
    # so per-stage child spans would only time dispatch)
    ph = telemetry.phases()
    ph.start("elle.rw-core-check", device=True,
             t_pad=h.txn_type.shape[0])

    while True:
        if deadline is not None:
            deadline.check("elle.rw-core-check")
        bits, over, rw_over = resilience.device_call(
            "elle.rw-core-check",
            lambda: _cc_call(h, n_keys, max_k, max_rounds, rw_cap),
            policy=policy, deadline=deadline, plan=plan)
        over_i = int(np.asarray(over))
        rw_over_i = int(np.asarray(rw_over))
        conv = int(np.asarray(bits)[-1]) == 1
        if rw_over_i > 0 and rw_cap < RW_CAP_LIMIT:
            need = min(rw_cap + rw_over_i, RW_CAP_LIMIT)
            while rw_cap < need:
                rw_cap *= 2
            rw_cap = min(rw_cap, RW_CAP_LIMIT)
            continue
        if over_i > 0 and max_k < MAX_K_CAP:
            need = max_k + over_i
            while max_k < need:
                max_k *= 2
            max_k = min(max_k, MAX_K_CAP)
            continue
        if not conv and over_i == 0 and max_rounds < MAX_ROUNDS_CAP:
            max_rounds = min(max_rounds * 2, MAX_ROUNDS_CAP)
            continue
        break

    ph.end()
    row = np.asarray(bits)
    nc = len(COUNT_NAMES_RW)
    counts = {n: int(row[i]) for i, n in enumerate(COUNT_NAMES_RW)}
    cycles = [bool(x) for x in row[nc:-1]]
    exact = bool(row[-1]) and over_i == 0 and rw_over_i == 0
    invalid = any(v > 0 for v in counts.values()) or any(cycles)
    return {
        "valid?": (not invalid) if exact else "unknown",
        "counts": counts,
        "cycles": {
            "G0": cycles[0], "G1c": cycles[1], "G2-family": cycles[2],
            "G2-family-process": cycles[3],
            "G2-family-realtime": cycles[4],
        },
        "exact": exact,
    }
