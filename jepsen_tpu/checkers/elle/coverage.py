"""Anomaly-coverage contract: never silently validate an unsearched
anomaly (VERDICT r04 item 4 / weak #5).

`anomalies_for_models` hands checkers tokens across the WHOLE lattice
vocabulary; a checker that cannot produce some requested token must not
return `valid?: True` as if it had searched for it.  This module is the
single place that records, for the list-append pipeline, which tokens
are searched directly, which foreign-vocabulary tokens are *covered by
equivalence* under list-append semantics (each with its justification),
and which must degrade the verdict to `"unknown"` with an
`unchecked-anomalies` list.  The rw-register checker has its own inline
session handling (`rw_register.check`); its vocabulary is natively
rw-shaped so no equivalence map is needed there.

Reference: `elle/consistency_model.clj` defines the token lattice; the
reference checker itself silently ignores unknown tokens — this contract
is deliberately stricter (an oracle that cannot look must say so).
"""

from __future__ import annotations

from typing import Any, Dict, Set, Tuple

from jepsen_tpu.checkers.elle.specs import SPEC_ORDER

#: non-cycle anomaly counts device/host list-append inference produces
LA_COUNT_TOKENS = frozenset({
    "duplicate-appends", "duplicate-elements", "incompatible-order",
    "G1a", "G1b", "dirty-update", "internal",
})

#: foreign-vocabulary tokens covered by a searched family under
#: list-append semantics.  Every entry must carry its justification:
#:
#: - aborted-read / intermediate-read: the rw-register names for G1a /
#:   G1b; the la counts are exactly those checks over append values.
#: - duplicate-writes: rw name for duplicate-appends.
#: - cyclic-versions: a version-order contradiction; la version orders
#:   come from the longest read, so a contradiction surfaces as
#:   incompatible-order (reads disagreeing with the inferred order).
#: - lost-update: two txns updating one observed version.  Appends
#:   cannot lose updates (every committed append lands in the list);
#:   the conflict shape surfaces as ww/rw cycles (G-single family).
#: - G2: full Adya G2 adds predicate anti-dependencies; list-append
#:   has no predicate reads, so G2 == G2-item here (the reference's
#:   treatment on this workload).
#: - fractured-read: reading part of a txn's atomic writes — with
#:   append semantics the missing fragment is a reader<-writer rw edge
#:   against a wr edge, i.e. a G-single cycle; the length/content side
#:   is `internal`.
#: - monotonic-atomic-view-violation: MAV breaks are fractured reads
#:   observed after a first fragment — the identical G-single/internal
#:   shape as fractured-read above.
#: - G-SI / G-SIa / G-SIb / G-monotonic / G-MSR / G-update / G-cursor:
#:   specialized cycle taxa inside the ww∪wr∪rw(∪realtime) edge
#:   vocabulary.  Every one of them is a cycle in a projection this
#:   checker sweeps, so on a valid history (all projections acyclic)
#:   they are definitively absent; when a cycle exists the broader
#:   family (G-single / G1c / G2-item ± realtime) reports it and the
#:   verdict is already False.  This matches the reference checker's
#:   practical SI boundary (G-single + lost-update) on this workload.
LA_EQUIV_COVERED = frozenset({
    "aborted-read", "intermediate-read", "duplicate-writes",
    "cyclic-versions", "lost-update", "G2", "fractured-read",
    "monotonic-atomic-view-violation",
    "G-SI", "G-SIa", "G-SIb", "G-monotonic", "G-MSR", "G-update",
    "G-cursor",
})

_SUFFIX = "-violation"


def _session_tokens(want: Set[str]) -> Set[str]:
    from jepsen_tpu.checkers.elle import sessions

    return {w for w in want if w.endswith(_SUFFIX)
            and w[:-len(_SUFFIX)] in sessions.GUARANTEES}


def run_la_sessions(history, want: Set[str], packed_input: bool,
                    max_reported: int = 8) -> Tuple[Dict[str, Any], bool]:
    """Run the session-guarantee checker for requested session tokens on
    an op-level list-append history.  Returns (anomalies, checked).

    A PackedTxns-only caller cannot be session-checked (the packed form
    drops the op-level view the session walker needs) — `checked` stays
    False and `finalize_la` degrades the verdict unless process-edge
    cycle coverage applies (see there).
    """
    sess_want = _session_tokens(want)
    if not sess_want or packed_input:
        return {}, False
    from jepsen_tpu.checkers.elle import sessions

    res = sessions.check_la(
        history, guarantees=[w[:-len(_SUFFIX)] for w in sess_want],
        max_reported=max_reported)
    return res["anomalies"], True


def unchecked_for_la(want: Set[str], sess_checked: bool) -> list:
    """Requested tokens the list-append pipeline did not and cannot
    search this call."""
    searched = LA_COUNT_TOKENS | set(SPEC_ORDER) | LA_EQUIV_COVERED
    sess_want = _session_tokens(want)
    if sess_checked or "G-single-process" in want:
        # per-session ordering violations surface as process-edge cycles
        # in the transactional graph (the reference's own treatment), so
        # a strict/strong-session-class request keeps its verdict even
        # on packed input; a BARE session request does not.  Only the
        # G-single-process family qualifies: read-centric violations
        # (monotonic-reads, RYW) need anti-dependency (rw) edges, which
        # G0-process/G1c-process projections do not search
        searched |= sess_want
    return sorted(want - searched)


def apply_unchecked(result: Dict[str, Any], unchecked) -> Dict[str, Any]:
    """The degradation rule, shared by every checker: surface the
    unchecked list, and downgrade a would-be `valid?: True` to
    `"unknown"` (an oracle that cannot look must say so)."""
    if unchecked:
        result["unchecked-anomalies"] = sorted(unchecked)
        if result["valid?"] is True:
            result["valid?"] = "unknown"
    return result


def finalize_la(result: Dict[str, Any], want: Set[str],
                sess_checked: bool) -> Dict[str, Any]:
    """Apply the coverage contract to a finished list-append verdict."""
    return apply_unchecked(result, unchecked_for_la(want, sess_checked))
