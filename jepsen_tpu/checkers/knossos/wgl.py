"""Host WGL linearizability search (the exact anchor).

Equivalent of `knossos/wgl.clj` (SURVEY.md §2.4): Wing-Gong-Lowe DFS over
configurations (model state, linearized-set bitset) with a visited cache
of packed configs.  Uses the memoized int transition table; bitsets are
Python arbitrary-precision ints (the JVM BitSet analogue).  `info`
(crashed) ops never return: they may linearize anywhere after invocation
or not at all.

This is BASELINE.json config 1's correctness anchor; the TPU batched
frontier search (`device_wgl`) is differentially tested against it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu.checkers.knossos.memo import Memo, StateExplosion, memoize
from jepsen_tpu.checkers.knossos.prep import NEVER, LinOp, prepare
from jepsen_tpu.checkers.knossos.search import stamp_abort
from jepsen_tpu.history.ops import History
from jepsen_tpu.models import Inconsistent, Model


def _search_memo(ops: Sequence[LinOp], memo: Memo,
                 max_configs: int = 5_000_000, ctl=None):
    """DFS over (linearized bitset, state).  Returns (ok, final_info)."""
    n = len(ops)
    must = 0  # bitmask of ops that MUST linearize (have returns)
    for i, op in enumerate(ops):
        if op.return_pos < NEVER:
            must |= 1 << i
    table = memo.table
    op_sym = memo.op_sym
    invokes = [op.invoke_pos for op in ops]
    returns = [op.return_pos for op in ops]

    # candidates(S): ops not in S invoked before min return of not-in-S ops
    def candidates(S: int) -> List[int]:
        minret = NEVER + 1
        for i in range(n):
            if not (S >> i) & 1 and returns[i] < minret:
                minret = returns[i]
        return [i for i in range(n)
                if not (S >> i) & 1 and invokes[i] < minret]

    seen = set()
    # stack entries: (S, state, candidate list, next candidate index)
    S, state = 0, memo.init_state
    stack = [(S, state, candidates(S), 0)]
    seen.add((S, state))
    explored = 0
    while stack:
        S, state, cands, ci = stack[-1]
        if (S & must) == must:
            return True, None
        if ci >= len(cands):
            stack.pop()
            continue
        stack[-1] = (S, state, cands, ci + 1)
        i = cands[ci]
        s2 = int(table[state, op_sym[i]])
        if s2 < 0:
            continue
        S2 = S | (1 << i)
        key = (S2, s2)
        if key in seen:
            continue
        seen.add(key)
        explored += 1
        if explored > max_configs:
            return None, {"reason": "config budget exhausted"}
        if ctl is not None and explored % 4096 == 0 and ctl.aborted():
            return None, {"reason": "aborted"}
        stack.append((S2, s2, candidates(S2), 0))
    # exhausted without linearizing all required ops
    return False, _final_info(ops, seen, memo)


def _final_info(ops, seen, memo):
    """Minimal failure context: the largest linearized sets reached."""
    best = []
    best_count = -1
    for (S, st) in seen:
        c = bin(S).count("1")
        if c > best_count:
            best_count = c
            best = [(S, st)]
        elif c == best_count and len(best) < 4:
            best.append((S, st))
    return {
        "max-linearized": best_count,
        "op-count": len(ops),
        # history indices (orig_invoke), not internal prepared-op ids, so
        # reports and humans can find the ops
        "configs": [{"linearized": [ops[i].orig_invoke
                                    for i in range(len(ops))
                                    if (S >> i) & 1],
                     "state": int(st)} for (S, st) in best[:4]],
    }


def _search_direct(ops: Sequence[LinOp], model: Model,
                   max_configs: int = 1_000_000, ctl=None):
    """Unmemoized DFS for models whose state space explodes.  Polls
    `ctl` every 4096 configs so a competition/deadline can abort this
    leg too (it is a race contestant via `check`'s StateExplosion
    fallback, and non-daemon racer threads must stay cancellable)."""
    n = len(ops)
    must = 0
    for i, op in enumerate(ops):
        if op.return_pos < NEVER:
            must |= 1 << i
    returns = [op.return_pos for op in ops]
    invokes = [op.invoke_pos for op in ops]

    def candidates(S: int) -> List[int]:
        minret = NEVER + 1
        for i in range(n):
            if not (S >> i) & 1 and returns[i] < minret:
                minret = returns[i]
        return [i for i in range(n)
                if not (S >> i) & 1 and invokes[i] < minret]

    seen = set()
    stack = [(0, model, candidates(0), 0)]
    seen.add((0, model))
    explored = 0
    while stack:
        S, m, cands, ci = stack[-1]
        if (S & must) == must:
            return True, None
        if ci >= len(cands):
            stack.pop()
            continue
        stack[-1] = (S, m, cands, ci + 1)
        i = cands[ci]
        m2 = m.step(ops[i].f, ops[i].value)
        if isinstance(m2, Inconsistent):
            continue
        S2 = S | (1 << i)
        if (S2, m2) in seen:
            continue
        seen.add((S2, m2))
        explored += 1
        if explored > max_configs:
            return None, {"reason": "config budget exhausted"}
        if ctl is not None and explored % 4096 == 0 and ctl.aborted():
            return None, {"reason": "aborted"}
        stack.append((S2, m2, candidates(S2), 0))
    return False, {"op-count": n}


def _search_native(ops: Sequence[LinOp], memo: Memo, max_configs: int,
                   ctl=None):
    """C++ WGL (jepsen_tpu.native, SURVEY.md §2.5 #2) when available;
    returns (NotImplemented, None) to fall back to the Python anchor.
    `ctl.flag` is shared with the C++ search so a competition can abort
    it mid-run (the ctypes call releases the GIL)."""
    import os
    if os.environ.get("JT_NO_NATIVE"):
        return NotImplemented, None
    from jepsen_tpu import native
    res = native.wgl(memo.op_sym,
                     [op.invoke_pos for op in ops],
                     [op.return_pos for op in ops],
                     NEVER, memo.table, memo.init_state, max_configs,
                     abort_flag=ctl.flag if ctl is not None else None)
    if res is None:
        return NotImplemented, None
    ok, explored, aborted = res
    if aborted:
        return None, {"reason": "aborted", "explored": explored}
    if ok is None:
        return None, {"reason": "config budget exhausted",
                      "explored": explored}
    if ok is False:
        # Re-run the Python search for the final-info diagnostics
        # (max-linearized, witness configs) when cheap; keep the summary
        # shape when the config space is too big to redo.
        if explored <= 200_000:
            return _search_memo(ops, memo, max_configs, ctl)
        return False, {"op-count": len(ops), "explored": explored}
    return True, None


def check(history: History | Sequence[LinOp], model: Model,
          max_configs: int = 5_000_000, ctl=None) -> Dict[str, Any]:
    """Check linearizability of a single-object history against a model.
    `ctl` (a `search.Search`) lets a competition abort the search —
    both the Python DFS (polled every 4096 configs) and the C++ one
    (shared abort flag, polled every 1024 configs)."""
    ops = history if isinstance(history, list) else prepare(history)
    if not ops:
        return {"valid?": "unknown", "op-count": 0}
    try:
        memo = memoize(model, ops)
        ok, info = _search_native(ops, memo, max_configs, ctl)
        if ok is NotImplemented:
            ok, info = _search_memo(ops, memo, max_configs, ctl)
    except StateExplosion:
        ok, info = _search_direct(ops, model, max_configs, ctl)
    if ok is None:
        # an aborted search names its cause: deadline-driven aborts
        # surface as error=deadline-exceeded (resilience contract)
        return stamp_abort({"valid?": "unknown", "op-count": len(ops),
                            **(info or {})}, ctl)
    out: Dict[str, Any] = {"valid?": bool(ok), "op-count": len(ops)}
    if info:
        out["final-info"] = info
    return out
