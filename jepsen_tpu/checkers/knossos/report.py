"""SVG rendering of non-linearizable windows.

Equivalent of `knossos/linear/report.clj` (SURVEY.md §2.4): given a
failed linearizability analysis, draw the ops around the violation — one
lane per process, bars spanning invoke→return, the offending op
highlighted — as a standalone SVG written into the store dir.
"""

from __future__ import annotations

import html
from typing import Any, Dict, List, Optional

from ...history.ops import FAIL, INFO, INVOKE, OK, History

_LANE_H = 28
_PX_PER_POS = 14
_BAR_H = 20

_FILL = {OK: "#6DB6FE", INFO: "#FFAA26", FAIL: "#FEB5DA"}


def _window_ops(history: History, center_index: int, radius: int = 20):
    lo = max(0, center_index - radius)
    hi = min(len(history), center_index + radius)
    out = []
    for op in history:
        if op.type != INVOKE or not op.is_client_op():
            continue
        comp = history.completion(op)
        end = comp.index if comp is not None else hi
        if end < lo or op.index > hi:
            continue
        out.append((op, comp))
    return out, lo, hi


def render_analysis(history: History, analysis: Dict[str, Any],
                    path: str, radius: int = 20) -> Optional[str]:
    """Write an SVG for a failed analysis; returns the path (or None if
    the analysis has no localizable op)."""
    final = analysis.get("final-info") or {}
    op_info = final.get("op") or {}
    center = op_info.get("index")
    if center is None:
        # WGL-style failure: anchor on the last linearized op if present
        configs = final.get("configs") or []
        linz = [i for c in configs for i in c.get("linearized", [])]
        if not linz:
            return None
        center = max(linz)
    ops, lo, hi = _window_ops(history, int(center), radius)
    if not ops:
        return None
    procs = sorted({op.process for op, _ in ops}, key=repr)
    lane = {p: i for i, p in enumerate(procs)}

    def x(pos: int) -> float:
        return 60 + (pos - lo) * _PX_PER_POS

    parts: List[str] = []
    for p in procs:
        y = 20 + lane[p] * _LANE_H
        parts.append(f'<text x="6" y="{y + 14}" font-size="11">'
                     f'{html.escape(str(p))}</text>')
    for op, comp in ops:
        y = 20 + lane[op.process] * _LANE_H
        x0 = x(op.index)
        x1 = x(comp.index) if comp is not None else x(hi) + 10
        outcome = comp.type if comp is not None else INFO
        bad = op.index == center
        stroke = "#C60F0F" if bad else "#666"
        sw = 2.5 if bad else 0.75
        label = f"{op.f} {op.value!r}"
        if comp is not None and comp.value is not None \
                and comp.value != op.value:
            label += f" → {comp.value!r}"
        parts.append(
            f'<rect x="{x0:.0f}" y="{y}" width="{max(x1 - x0, 6):.0f}" '
            f'height="{_BAR_H}" rx="3" fill="{_FILL[outcome]}" '
            f'stroke="{stroke}" stroke-width="{sw}"/>'
            f'<text x="{x0 + 3:.0f}" y="{y + 14}" font-size="10">'
            f'{html.escape(label[:28])}</text>')
    w = x(hi) + 40
    h = 30 + len(procs) * _LANE_H
    svg = (f'<svg xmlns="http://www.w3.org/2000/svg" width="{w:.0f}" '
           f'height="{h}" font-family="sans-serif">'
           f'<text x="6" y="12" font-size="12" fill="#C60F0F">'
           f'non-linearizable: op {center}</text>'
           + "".join(parts) + "</svg>")
    with open(path, "w") as f:
        f.write(svg)
    return path
