"""Competition: race the linearizability algorithms.

Equivalent of `knossos/competition.clj` (SURVEY.md §2.4), which races
`linear` and `wgl` on two thread pools and takes the first definitive
answer.  Here three contestants exist: JIT-linear (`linear.py`), host WGL
(`wgl.py`, C++-accelerated via `jepsen_tpu.native`), and the TPU batched
frontier search (`device_wgl.py`).  Small histories race linear vs wgl on
threads (losers aborted via `search.Search`), falling back to the device
on "unknown"; large histories race all THREE legs concurrently — first
definitive verdict wins, losers are aborted.
"""

from __future__ import annotations

import inspect
import logging
import queue as _queue
import threading
import time
from typing import Any, Dict

logger = logging.getLogger("jepsen.knossos")

from jepsen_tpu import telemetry
from jepsen_tpu.checkers.knossos import device_wgl, linear, wgl
from jepsen_tpu.checkers.knossos.prep import prepare
from jepsen_tpu.checkers.knossos.search import ChildSearch, stamp_abort
from jepsen_tpu.history.ops import History
from jepsen_tpu.models import Model

HOST_FIRST_MAX_OPS = 256


def _race(contestants, ops, model, ctl, _also_accepts=(),
          **kw) -> Dict[str, Any]:
    """Race checkers on threads; first definitive answer wins and the
    losers are aborted via the shared `ctl` (reference competition
    semantics).  Threads are NON-daemon — a daemon straggler killed at
    interpreter exit inside native XLA code SIGABRTs ("FATAL: exception
    not rethrown") — so every leg must stay cancellable: with a ctl the
    device leg always takes the pollable blocked search, never the
    unabortable single-jit while_loop.  The wait loop polls `ctl` so an
    expired deadline ends the race even while every leg is mid-flight.
    """
    q: _queue.Queue = _queue.Queue()

    # a kwarg no contestant accepts (e.g. a misspelled budget like
    # max_config) would otherwise be dropped by EVERY per-leg filter —
    # auto mode silently unbounded where the direct paths TypeError
    if kw:
        accepted = set()
        for fn in [fn for _, fn in contestants] + list(_also_accepts):
            accepted |= set(inspect.signature(fn).parameters)
        dropped = sorted(set(kw) - accepted)
        if dropped:
            logger.warning(
                "race kwargs %s accepted by no contestant %s — ignored",
                dropped, [n for n, _ in contestants])

    def run(name, fn):
        try:
            # per-leg kwarg filter: the legs' signatures differ (e.g.
            # max_frontier is device-only) and a TypeError here would
            # silently kill a leg instead of racing it
            params = inspect.signature(fn).parameters
            leg_kw = {k: v for k, v in kw.items() if k in params}
            # each leg runs on its own thread, so this span is a root
            # on its own timeline row; device=True marks the TPU leg
            with telemetry.span(f"knossos.{name}", ops=len(ops),
                                device=(fn is device_wgl.check)) as sp:
                res = fn(list(ops), model, ctl=ctl, **leg_kw)
                sp.set_attr(valid=res.get("valid?"))
            q.put((name, res, None))
        except Exception as e:  # noqa: BLE001 — let the others finish
            logger.warning("%s contestant crashed", name, exc_info=True)
            q.put((name, None, e))

    fallback: Dict[str, Any] = {"valid?": "unknown"}
    pending = 0
    threads = []
    try:
        # starts inside the try: if the Nth start raises (thread
        # pressure), the finally still aborts the already-running legs
        for name, fn in contestants:
            t = threading.Thread(target=run, args=(name, fn),
                                 name=f"knossos-race-{name}")
            t.start()
            threads.append(t)
            pending += 1
        while pending:
            try:
                name, res, err = q.get(timeout=0.25)
            except _queue.Empty:
                if ctl.aborted():  # deadline fired / caller cancelled
                    # drain: a leg may have enqueued a definitive
                    # verdict in the poll window — don't discard it
                    try:
                        while True:
                            name, res, err = q.get_nowait()
                            if err is None and \
                                    res.get("valid?") != "unknown":
                                res.setdefault("algorithm", name)
                                return res
                    except _queue.Empty:
                        pass
                    return stamp_abort(dict(fallback, reason="aborted"),
                                       ctl)
                continue
            pending -= 1
            if err is not None:
                fallback = {"valid?": "unknown", "error": f"{name} crashed"}
                continue
            if res.get("valid?") != "unknown":
                res.setdefault("algorithm", name)
                return res
            fallback = res
        return fallback
    finally:
        ctl.abort()
        # losers are non-daemon (a daemon killed inside XLA SIGABRTs at
        # exit) and a leg stuck in one long compile/dispatch cannot see
        # ctl mid-call — don't block the winner's return on them, but DO
        # make slow unwinds diagnosable from the log (the reaper thread
        # itself touches no native code, so daemon is safe)
        if any(t.is_alive() for t in threads):
            def reap(ts=tuple(threads)):
                t_end = time.monotonic() + 30
                for t in ts:
                    t.join(timeout=max(0.0, t_end - time.monotonic()))
                stuck = [t.name for t in ts if t.is_alive()]
                if stuck:
                    logger.info(
                        "race losers still unwinding 30s after the "
                        "verdict: %s", stuck)

            threading.Thread(target=reap, daemon=True,
                             name="knossos-race-reaper").start()


HOST_LEGS = (("linear", linear.check), ("wgl", wgl.check))


def _polled(root, fn):
    """Run `fn` with a background poller driving `root.aborted()`.

    Deadline/parent-abort propagation is poll-driven (see
    `search.ChildSearch`), and the native C++ DFS only watches
    `root.flag` — on the direct-algorithm paths nothing else polls, so
    without this a `deadline_s` (or a caller ctl abort) would never
    reach a flag-only leg.  The poller is a daemon thread but touches
    no native code, so interpreter exit cannot SIGABRT inside it."""
    if root is None:
        return fn()
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            if root.aborted():
                return
            stop.wait(0.25)

    threading.Thread(target=poll, daemon=True,
                     name="knossos-deadline-poll").start()
    try:
        return fn()
    finally:
        stop.set()


def analysis(history: History, model: Model,
             algorithm: str = "auto", deadline_s=None, deadline=None,
             **kw) -> Dict[str, Any]:
    """Linearizability analysis.
    algorithm: auto | wgl | linear | device | competition.
    Telemetric runs get a ``knossos.analysis`` span over the whole call
    plus one root span per race leg (each on its own thread row).

    auto: small histories race linear vs wgl (cheap memoization, host
    DFS usually instant), then try the device on "unknown"; large ones
    race all THREE legs concurrently — the device frontier BFS is the
    expected winner at scale, but crash-heavy (`info`-dense) histories
    can blow up any single leg, and sequential device-first stalls the
    analysis for exactly the histories where the host DFS would answer
    (measured: a 1300-op 185-info history held the device leg >25 min
    while racing legs bound it).  `deadline_s` bounds the WHOLE
    analysis (race + fallback), anchored here; `deadline` (a
    cooperative `resilience.Deadline`, typically `check_safe`'s
    checker-time-limit budget) does the same but is shared with the
    caller, so one budget covers a whole composed check.  A
    deadline-driven abort returns ``{"valid?": "unknown",
    "error": "deadline-exceeded", ...partial stats}`` — never a hang.
    A caller-supplied `ctl` is never aborted by the race — losers are
    cancelled through linked child ctls (`search.ChildSearch`), so one
    ctl can bound a whole campaign of analyses.  Remaining `**kw`
    (e.g. max_configs) is forwarded to EVERY leg, device included: an
    explicit budget bounds the whole analysis, not just the host
    algorithms.
    """
    with telemetry.span("knossos.analysis", algorithm=algorithm) as sp:
        with telemetry.span("knossos.prep"):
            from jepsen_tpu.history.ir import HistoryIR

            ops = history.lin_ops() if isinstance(history, HistoryIR) \
                else prepare(history)
        sp.set_attr(ops=len(ops))
        res = _dispatch(ops, model, algorithm, deadline_s, deadline, kw)
        sp.set_attr(valid=res.get("valid?"),
                    algorithm_used=res.get("algorithm", algorithm),
                    error=res.get("error"))
        return res


def _dispatch(ops, model: Model, algorithm: str, deadline_s, deadline,
              kw: Dict[str, Any]) -> Dict[str, Any]:
    parent = kw.pop("ctl", None)
    # one root per analysis: carries this call's deadline (absolute from
    # here) and observes the caller's ctl; everything below aborts
    # through children of it, so neither root nor parent gets poisoned.
    # No parent and no deadline -> no root at all: a ctl-less device
    # check keeps its single-jit fast path, and there is nothing to
    # poll anyway.
    # `is not None`, not truthiness: deadline_s=0 means "already
    # expired, abort promptly", the opposite of unbounded
    root = (ChildSearch(parent, deadline_s=deadline_s, deadline=deadline)
            if parent is not None or deadline_s is not None
            or deadline is not None else None)
    if algorithm == "wgl":
        return _polled(root, lambda: wgl.check(ops, model, ctl=root, **kw))
    if algorithm == "linear":
        return _polled(root,
                       lambda: linear.check(ops, model, ctl=root, **kw))
    if algorithm == "device":
        return _polled(root,
                       lambda: device_wgl.check(ops, model, ctl=root, **kw))
    if len(ops) <= HOST_FIRST_MAX_OPS:
        # the device fallback three lines down also consumes kwargs:
        # a device-only kwarg here is NOT dropped, don't warn on it
        res = _race(HOST_LEGS, ops, model, ChildSearch(root),
                    _also_accepts=(device_wgl.check,), **kw)
        if res["valid?"] != "unknown":
            return res
        # same signature-based filter as _race: a host-only kwarg must
        # not TypeError the fallback leg
        dparams = inspect.signature(device_wgl.check).parameters
        dres = device_wgl.check(
            ops, model, ctl=ChildSearch(root) if root is not None else None,
            **{k: v for k, v in kw.items() if k in dparams})
        return dres if dres["valid?"] != "unknown" else res
    return _race(HOST_LEGS + (("device", device_wgl.check),),
                 ops, model, ChildSearch(root), **kw)
