"""Competition: race the linearizability algorithms.

Equivalent of `knossos/competition.clj` (SURVEY.md §2.4), which races
`linear` and `wgl` on two thread pools and takes the first definitive
answer.  Here three contestants exist: JIT-linear (`linear.py`), host WGL
(`wgl.py`, C++-accelerated via `jepsen_tpu.native`), and the TPU batched
frontier search (`device_wgl.py`).  Small histories race linear vs wgl on
threads (losers aborted via `search.Search`); large ones go to the
device first, with the host as fallback for "unknown".
"""

from __future__ import annotations

import concurrent.futures as _fut
import logging
from typing import Any, Dict

logger = logging.getLogger("jepsen.knossos")

from jepsen_tpu.checkers.knossos import device_wgl, linear, wgl
from jepsen_tpu.checkers.knossos.prep import prepare
from jepsen_tpu.checkers.knossos.search import Search
from jepsen_tpu.history.ops import History
from jepsen_tpu.models import Model

HOST_FIRST_MAX_OPS = 256


def _race_host(ops, model, **kw) -> Dict[str, Any]:
    """linear vs wgl on two threads; first definitive answer wins and the
    loser is aborted (reference competition semantics).  The executor is
    shut down without waiting — the loser notices `ctl` and exits."""
    ctl = Search()
    ex = _fut.ThreadPoolExecutor(max_workers=2)
    futs = {
        ex.submit(linear.check, list(ops), model, ctl=ctl, **kw): "linear",
        ex.submit(wgl.check, list(ops), model, ctl=ctl, **kw): "wgl",
    }
    fallback: Dict[str, Any] = {"valid?": "unknown"}
    try:
        for fut in _fut.as_completed(futs):
            try:
                res = fut.result()
            except Exception:  # noqa: BLE001 — let the other finish
                logger.warning("%s contestant crashed", futs[fut],
                               exc_info=True)
                fallback = {"valid?": "unknown",
                            "error": f"{futs[fut]} crashed"}
                continue
            if res.get("valid?") != "unknown":
                return res
            fallback = res
        return fallback
    finally:
        ctl.abort()
        ex.shutdown(wait=False)


def analysis(history: History, model: Model,
             algorithm: str = "auto", **kw) -> Dict[str, Any]:
    """Linearizability analysis.
    algorithm: auto | wgl | linear | device | competition."""
    ops = prepare(history)
    if algorithm == "wgl":
        return wgl.check(ops, model, **kw)
    if algorithm == "linear":
        return linear.check(ops, model, **kw)
    if algorithm == "device":
        return device_wgl.check(ops, model, **kw)
    if len(ops) <= HOST_FIRST_MAX_OPS:
        res = _race_host(ops, model, **kw)
        if res["valid?"] != "unknown":
            return res
        dres = device_wgl.check(ops, model)
        return dres if dres["valid?"] != "unknown" else res
    res = device_wgl.check(ops, model)
    if res["valid?"] != "unknown":
        return res
    return _race_host(ops, model, **kw)
