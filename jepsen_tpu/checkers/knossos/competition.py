"""Competition: host WGL vs device frontier search.

Equivalent of `knossos/competition.clj` (SURVEY.md §2.4), which races
`linear` and `wgl` and takes the first definitive answer.  Here the two
contestants are the exact host WGL (small-history anchor) and the TPU
batched frontier search (scales wider).  The host runs first below a size
threshold; the device verdict is used for larger histories, with the host
as fallback when the device returns "unknown" (overflow / state
explosion).
"""

from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu.checkers.knossos import device_wgl, wgl
from jepsen_tpu.checkers.knossos.prep import prepare
from jepsen_tpu.history.ops import History
from jepsen_tpu.models import Model

HOST_FIRST_MAX_OPS = 256


def analysis(history: History, model: Model,
             algorithm: str = "auto", **kw) -> Dict[str, Any]:
    """Linearizability analysis.  algorithm: auto | wgl | device."""
    ops = prepare(history)
    if algorithm == "wgl":
        return wgl.check(ops, model, **kw)
    if algorithm == "device":
        return device_wgl.check(ops, model, **kw)
    if len(ops) <= HOST_FIRST_MAX_OPS:
        res = wgl.check(ops, model)
        if res["valid?"] != "unknown":
            return res
        dres = device_wgl.check(ops, model)
        return dres if dres["valid?"] != "unknown" else res
    res = device_wgl.check(ops, model)
    if res["valid?"] != "unknown":
        return res
    return wgl.check(ops, model)
