"""Model memoization: reachable states -> dense ints + transition table.

Equivalent of `knossos/model/memo.clj` (SURVEY.md §2.4) — "the key trick
that makes WGL bit-packable, and the direct precursor of the TPU
transition-matrix design": enumerate the model states reachable under the
history's op alphabet, canonicalize each to an int, and precompute
`table[state, op] -> state' | -1` (inconsistent).  The host WGL walks the
int table; the device frontier search uploads it as an (S, A) int32 array.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from jepsen_tpu.checkers.knossos.prep import LinOp
from jepsen_tpu.models import Inconsistent, Model


class StateExplosion(Exception):
    pass


@dataclasses.dataclass
class Memo:
    table: np.ndarray          # (S, A) int32; -1 = inconsistent
    op_sym: np.ndarray         # (n_ops,) int32: op -> alphabet symbol
    n_states: int
    n_syms: int
    init_state: int = 0


def memoize(model: Model, ops: Sequence[LinOp],
            max_states: int = 200_000) -> Memo:
    """Enumerate reachable states under the ops' alphabet."""
    # alphabet: distinct (f, value) pairs (values normalized to hashables)
    def norm(v):
        if isinstance(v, list):
            return tuple(norm(x) for x in v)
        return v

    sym_ids: Dict[Tuple, int] = {}
    syms: List[Tuple[Any, Any]] = []
    op_sym = np.zeros(len(ops), np.int32)
    for i, op in enumerate(ops):
        k = (op.f, norm(op.value))
        s = sym_ids.get(k)
        if s is None:
            s = len(syms)
            sym_ids[k] = s
            syms.append((op.f, op.value))
        op_sym[i] = s

    state_ids: Dict[Model, int] = {model: 0}
    states: List[Model] = [model]
    rows: List[List[int]] = []
    frontier = [0]
    while frontier:
        nxt = []
        for si in frontier:
            m = states[si]
            row = []
            for (f, v) in syms:
                m2 = m.step(f, v)
                if isinstance(m2, Inconsistent):
                    row.append(-1)
                    continue
                j = state_ids.get(m2)
                if j is None:
                    j = len(states)
                    if j >= max_states:
                        raise StateExplosion(
                            f"more than {max_states} reachable states")
                    state_ids[m2] = j
                    states.append(m2)
                    nxt.append(j)
                row.append(j)
            while len(rows) <= si:
                rows.append(None)
            rows[si] = row
        frontier = nxt
    table = np.asarray(rows, dtype=np.int32)
    return Memo(table=table, op_sym=op_sym, n_states=len(states),
                n_syms=len(syms))
