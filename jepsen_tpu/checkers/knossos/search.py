"""Search control: abort flags, deadlines, budgets.

Equivalent of `knossos/search.clj` (SURVEY.md §2.4): a small handle the
long-running searches poll so a competition can abort the loser, a
deadline can bound wall time, and callers can read progress.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np


class Search:
    """Shared control block for one search run.

    `flag` is a (1,) int32 shared with native searches: ctypes calls
    release the GIL, so the C++ WGL polls this memory while another
    thread aborts — the loser of a competition stops within ~1k configs
    instead of running out its full budget.

    Aborts carry a *reason* ("aborted" for competition losers /
    caller cancels, "deadline-exceeded" for expired budgets) so the
    final result can attribute WHY the search stopped — the resilience
    contract that a bounded run returns `error: deadline-exceeded`
    rather than a bare unknown.  `deadline` may also be a cooperative
    `resilience.Deadline` object shared with the rest of a composed
    checker run (one budget over the whole analysis)."""

    def __init__(self, *, deadline_s: Optional[float] = None,
                 deadline=None):
        self._abort = threading.Event()
        self.flag = np.zeros(1, dtype=np.int32)
        # `is not None`: deadline_s=0 means already expired, not "no
        # deadline"
        self.deadline = (time.monotonic() + deadline_s
                         if deadline_s is not None else None)
        self.deadline_obj = deadline  # resilience.Deadline, cooperative
        self.abort_reason: Optional[str] = None
        self._explored_lock = threading.Lock()
        self.explored = 0
        self.result: Optional[dict] = None

    def add_explored(self, n: int) -> None:
        """Thread-safe progress increment: concurrently racing legs all
        funnel into one parent counter, and a bare `explored += n` is a
        non-atomic read-modify-write that loses updates under the race."""
        with self._explored_lock:
            self.explored += n

    def abort(self, reason: str = "aborted") -> None:
        if self.abort_reason is None:
            self.abort_reason = reason
        self._abort.set()
        self.flag[0] = 1

    def aborted(self) -> bool:
        if self._abort.is_set():
            return True
        if self.deadline is not None and time.monotonic() > self.deadline:
            self.abort(DEADLINE_REASON)
            return True
        if self.deadline_obj is not None and self.deadline_obj.expired():
            self.abort(DEADLINE_REASON)
            return True
        return False

    def report(self, result: dict) -> dict:
        self.result = result
        return result


DEADLINE_REASON = "deadline-exceeded"


def stamp_abort(res: dict, ctl) -> dict:
    """Attribute an aborted search's cause in its result: a
    deadline-driven abort becomes ``error: deadline-exceeded`` (the
    canonical resilience verdict shape); other aborts keep their
    ``reason``.  No-op for definitive results or ctl-less calls."""
    if (ctl is not None and isinstance(res, dict)
            and res.get("valid?") == "unknown"
            and getattr(ctl, "abort_reason", None) == DEADLINE_REASON):
        res = dict(res, error=DEADLINE_REASON)
        res["explored"] = res.get("explored", ctl.explored)
    return res


class ChildSearch(Search):
    """A Search linked to a parent: aborting the child never touches the
    parent (so a competition can abort its losers while the caller's ctl
    stays reusable), while a parent abort — or the parent's deadline —
    propagates to the child at the child's next `aborted()` poll.  The
    child inherits the parent's deadline implicitly through that poll;
    its own `deadline_s` (if any) is additional.  Note the propagation
    is poll-driven: a leg that only watches the shared `flag` memory
    (the native C++ DFS) sees a parent abort once any python-side
    participant polls this child."""

    def __init__(self, parent: Optional[Search] = None, *,
                 deadline_s: Optional[float] = None, deadline=None):
        super().__init__(deadline_s=deadline_s, deadline=deadline)
        self._parent = parent

    def aborted(self) -> bool:
        p = self._parent
        if p is not None and p.aborted():
            # inherit the parent's reason: a deadline that fired on the
            # root must surface as deadline-exceeded from every leg
            self.abort(p.abort_reason or "aborted")
        return super().aborted()

    # `explored` forwards up the chain so a campaign polling ITS handle
    # still sees progress when the work runs under a derived child (the
    # base-class ctor's `explored = 0` lands in the local slot — the
    # parent is not attached yet — so attaching never resets the
    # parent's count).
    @property
    def explored(self) -> int:
        p = getattr(self, "_parent", None)
        return p.explored if p is not None else \
            getattr(self, "_explored_local", 0)

    @explored.setter
    def explored(self, v: int) -> None:
        p = getattr(self, "_parent", None)
        if p is not None:
            p.explored = v
        else:
            self._explored_local = v

    def add_explored(self, n: int) -> None:
        # delegate to the root so its lock serializes sibling legs
        p = getattr(self, "_parent", None)
        if p is not None:
            p.add_explored(n)
        else:
            super().add_explored(n)
