"""Search control: abort flags, deadlines, budgets.

Equivalent of `knossos/search.clj` (SURVEY.md §2.4): a small handle the
long-running searches poll so a competition can abort the loser, a
deadline can bound wall time, and callers can read progress.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional


class Search:
    """Shared control block for one search run."""

    def __init__(self, *, deadline_s: Optional[float] = None):
        self._abort = threading.Event()
        self.deadline = (time.monotonic() + deadline_s
                         if deadline_s else None)
        self.explored = 0
        self.result: Optional[dict] = None

    def abort(self) -> None:
        self._abort.set()

    def aborted(self) -> bool:
        if self._abort.is_set():
            return True
        if self.deadline is not None and time.monotonic() > self.deadline:
            self._abort.set()
            return True
        return False

    def report(self, result: dict) -> dict:
        self.result = result
        return result
