"""Search control: abort flags, deadlines, budgets.

Equivalent of `knossos/search.clj` (SURVEY.md §2.4): a small handle the
long-running searches poll so a competition can abort the loser, a
deadline can bound wall time, and callers can read progress.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np


class Search:
    """Shared control block for one search run.

    `flag` is a (1,) int32 shared with native searches: ctypes calls
    release the GIL, so the C++ WGL polls this memory while another
    thread aborts — the loser of a competition stops within ~1k configs
    instead of running out its full budget."""

    def __init__(self, *, deadline_s: Optional[float] = None):
        self._abort = threading.Event()
        self.flag = np.zeros(1, dtype=np.int32)
        self.deadline = (time.monotonic() + deadline_s
                         if deadline_s else None)
        self.explored = 0
        self.result: Optional[dict] = None

    def abort(self) -> None:
        self._abort.set()
        self.flag[0] = 1

    def aborted(self) -> bool:
        if self._abort.is_set():
            return True
        if self.deadline is not None and time.monotonic() > self.deadline:
            self.abort()
            return True
        return False

    def report(self, result: dict) -> dict:
        self.result = result
        return result
