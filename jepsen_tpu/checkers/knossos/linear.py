"""Just-in-time linearization (Lowe's algorithm).

Equivalent of `knossos/linear.clj` + `knossos/linear/config.clj`
(SURVEY.md §2.4): configurations evolve per history *event* rather than
per linearization order.  A configuration is ``(model-state,
linearized-set)`` where the set holds ops linearized but not yet
returned.  On an op's return, every surviving configuration must have
linearized it — configurations are expanded "just in time" by linearizing
subsets of pending calls, then filtered; an empty configuration set is a
linearizability violation, localized to that return event.

Two config-set representations, the analogue of the reference's
array-packed config structures (`knossos/linear/config.clj`):

- **packed** (default): a config is ONE int64, ``state << P | mask``,
  where ``mask`` is a bitmask over concurrency *slots* (a slot is held
  by an op while it is pending, freed at its return; P = peak
  concurrency).  The whole config set is a sorted-unique numpy int64
  array, and the per-event JIT expansion is vectorized: one transition-
  table gather per (pending slot x frontier) round, `np.unique` dedup —
  no per-config Python.  This is what makes `linear` competitive with
  `wgl` on adversarial histories.
- **sets** (fallback for > 57 concurrent ops or huge state spaces):
  ``(state:int, frozenset[int])`` tuples, expanded per config.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from jepsen_tpu.checkers.knossos.memo import Memo, StateExplosion, memoize
from jepsen_tpu.checkers.knossos.prep import NEVER, LinOp, prepare
from jepsen_tpu.checkers.knossos.search import Search, stamp_abort
from jepsen_tpu.history.ops import History
from jepsen_tpu.models import Model

Config = Tuple[int, frozenset]


def _events(ops: Sequence[LinOp]) -> List[Tuple[int, str, int]]:
    evs = []
    for op in ops:
        evs.append((op.invoke_pos, "call", op.index))
        if op.return_pos < NEVER:
            evs.append((op.return_pos, "ret", op.index))
    evs.sort()
    return evs


def _jit_expand(configs: Set[Config], target: int, calls: Set[int],
                table, op_sym, max_configs: int) -> Optional[Set[Config]]:
    """All configs reachable by linearizing pending calls, keeping those
    with `target` linearized (then dropping target from the set).
    Returns None on budget blowout."""
    out: Set[Config] = set()
    seen: Set[Config] = set(configs)
    stack = list(configs)
    budget = max_configs
    while stack:
        state, lin = stack.pop()
        if target in lin:
            out.add((state, lin - {target}))
        pending = calls - lin
        for j in pending:
            s2 = int(table[state, op_sym[j]])
            if s2 < 0:
                continue
            c2 = (s2, lin | {j})
            if c2 in seen:
                continue
            seen.add(c2)
            budget -= 1
            if budget <= 0:
                return None
            stack.append(c2)
    return out


def _peak_concurrency(evs) -> int:
    """Peak number of simultaneously-pending ops = slots needed."""
    live = peak = 0
    for _, kind, _ in evs:
        live += 1 if kind == "call" else -1
        peak = max(peak, live)
    return peak


def _search_packed(ops: Sequence[LinOp], memo: Memo, evs, P: int,
                   max_configs: int, ctl: Optional[Search] = None):
    """Vectorized JIT search over int64-packed configs (see module doc)."""
    table = memo.table
    op_sym = memo.op_sym
    mask_all = (np.int64(1) << P) - 1

    free = list(range(P - 1, -1, -1))   # slot pool (smallest on top)
    slot_of: Dict[int, int] = {}        # pending op -> slot
    slot_sym: Dict[int, int] = {}       # slot -> transition symbol

    configs = np.asarray([np.int64(memo.init_state) << P])
    for pos, kind, i in evs:
        if ctl is not None and ctl.aborted():
            return None, {"reason": "aborted"}
        if kind == "call":
            s = free.pop()
            slot_of[i] = s
            slot_sym[s] = int(op_sym[i])
            continue

        # JIT expansion: closure of `configs` under linearizing pending
        # ops, as rounds of vectorized table gathers over the frontier
        t_slot = slot_of.pop(i)
        all_cfgs = configs                     # sorted unique
        frontier = configs
        while frontier.size:
            states = frontier >> P
            masks = frontier & mask_all
            new_parts = []
            for s, sym in slot_sym.items():
                bit = np.int64(1) << s
                sel = (masks & bit) == 0
                if not sel.any():
                    continue
                s2 = table[states[sel], sym]
                ok = s2 >= 0
                if not ok.any():
                    continue
                new_parts.append((s2[ok].astype(np.int64) << P)
                                 | (masks[sel][ok] | bit))
            if not new_parts:
                break
            cand = np.unique(np.concatenate(new_parts))
            fresh = cand[~np.isin(cand, all_cfgs, assume_unique=True)]
            if not fresh.size:
                break
            all_cfgs = np.union1d(all_cfgs, fresh)
            if all_cfgs.size > max_configs:
                return None, {"reason": "config budget exhausted"}
            frontier = fresh

        bit = np.int64(1) << t_slot
        survivors = all_cfgs[(all_cfgs & bit) != 0]
        if not survivors.size:
            # decode a few prior configs for the failure report
            op_of_slot = {s: j for j, s in slot_of.items()}
            op_of_slot[t_slot] = i
            prior = set()
            for c in configs[:4]:
                m = int(c) & int(mask_all)
                lin = frozenset(op_of_slot[s] for s in range(P)
                                if (m >> s) & 1 and s in op_of_slot)
                prior.add((int(c) >> P, lin))
            del slot_sym[t_slot]
            free.append(t_slot)
            return False, _failure_info(ops, i, pos, prior)
        configs = np.unique(survivors & ~bit)
        del slot_sym[t_slot]
        free.append(t_slot)
        if ctl is not None:
            ctl.add_explored(int(configs.size))
    return True, None


def _rowview(a: np.ndarray) -> np.ndarray:
    """View (C, W) rows as a structured 1-D array for row-wise
    membership (np.isin sorts lexicographically by fields)."""
    return np.ascontiguousarray(a).view(
        [("", a.dtype)] * a.shape[1]).ravel()


def _search_packed_wide(ops: Sequence[LinOp], memo: Memo, evs, P: int,
                        max_configs: int, ctl: Optional[Search] = None):
    """Wide-mask packed search: the >57-slot regime (crash-heavy
    histories, where every `info` op holds a slot forever — VERDICT r04
    item 3's missing contestant).

    A config is a row ``[state, lane_0 .. lane_{L-1}]`` (int64 cols;
    lanes hold uint32 slot bitmasks, L = ceil(P/32)) in a (C, 1+L)
    array kept row-sorted-unique by np.unique(axis=0).  The per-event
    expansion is the same vectorized frontier closure as the int64 path
    — one transition-table gather per (pending slot x frontier) round —
    just with 2-D rows instead of scalar packs.  ~P/57x more memory per
    config than the int64 path; identical asymptotics.
    """
    table = memo.table
    L = (P + 31) // 32

    free = list(range(P - 1, -1, -1))
    slot_of: Dict[int, int] = {}
    slot_sym: Dict[int, int] = {}

    configs = np.zeros((1, 1 + L), np.int64)
    configs[0, 0] = memo.init_state
    for pos, kind, i in evs:
        if ctl is not None and ctl.aborted():
            return None, {"reason": "aborted"}
        if kind == "call":
            s = free.pop()
            slot_of[i] = s
            slot_sym[s] = int(memo.op_sym[i])
            continue

        t_slot = slot_of.pop(i)
        all_cfgs = configs
        frontier = configs
        while frontier.shape[0]:
            # poll INSIDE the closure too: one event's expansion can run
            # minutes on info-dense histories, and the competition must
            # be able to abort this leg mid-event
            if ctl is not None and ctl.aborted():
                return None, {"reason": "aborted"}
            new_parts = []
            for s, sym in slot_sym.items():
                lane, bit = 1 + s // 32, np.int64(1) << (s % 32)
                sel = (frontier[:, lane] & bit) == 0
                if not sel.any():
                    continue
                sub = frontier[sel]
                s2 = table[sub[:, 0], sym]
                ok = s2 >= 0
                if not ok.any():
                    continue
                rows = sub[ok].copy()
                rows[:, 0] = s2[ok]
                rows[:, lane] |= bit
                new_parts.append(rows)
            if not new_parts:
                break
            cand = np.unique(np.concatenate(new_parts), axis=0)
            fresh = cand[~np.isin(_rowview(cand), _rowview(all_cfgs),
                                  assume_unique=True)]
            if not fresh.shape[0]:
                break
            all_cfgs = np.unique(np.concatenate([all_cfgs, fresh]),
                                 axis=0)
            if all_cfgs.shape[0] > max_configs:
                return None, {"reason": "config budget exhausted"}
            frontier = fresh

        lane, bit = 1 + t_slot // 32, np.int64(1) << (t_slot % 32)
        survivors = all_cfgs[(all_cfgs[:, lane] & bit) != 0]
        if not survivors.shape[0]:
            op_of_slot = {s: j for j, s in slot_of.items()}
            op_of_slot[t_slot] = i
            prior = set()
            for row in configs[:4]:
                lin = frozenset(
                    op_of_slot[s] for s in range(P)
                    if (int(row[1 + s // 32]) >> (s % 32)) & 1
                    and s in op_of_slot)
                prior.add((int(row[0]), lin))
            del slot_sym[t_slot]
            free.append(t_slot)
            return False, _failure_info(ops, i, pos, prior)
        survivors = survivors.copy()
        survivors[:, lane] &= ~bit
        configs = np.unique(survivors, axis=0)
        del slot_sym[t_slot]
        free.append(t_slot)
        if ctl is not None:
            ctl.add_explored(int(configs.shape[0]))
    return True, None


def _search_sets(ops: Sequence[LinOp], memo: Memo, evs, max_configs: int,
                 ctl: Optional[Search] = None):
    table = memo.table
    op_sym = memo.op_sym
    configs: Set[Config] = {(memo.init_state, frozenset())}
    calls: Set[int] = set()
    for pos, kind, i in evs:
        if ctl is not None and ctl.aborted():
            return None, {"reason": "aborted"}
        if kind == "call":
            calls.add(i)
            continue
        expanded = _jit_expand(configs, i, calls, table, op_sym,
                               max_configs)
        if expanded is None:
            return None, {"reason": "config budget exhausted"}
        calls.remove(i)
        if not expanded:
            return False, _failure_info(ops, i, pos, configs)
        configs = expanded
        if ctl is not None:
            ctl.add_explored(len(configs))
    return True, None


#: wide-mask slot ceiling: L = ceil(P/32) lanes per config row; past
#: this the per-config rows are so wide the sets path wins anyway
WIDE_MAX_SLOTS = 1024


def _search(ops: Sequence[LinOp], memo: Memo, max_configs: int,
            ctl: Optional[Search] = None, _force_sets: bool = False,
            _force_wide: bool = False):
    evs = _events(ops)
    P = _peak_concurrency(evs)
    # packed configs need state << P to fit an int64
    if not _force_sets:
        if not _force_wide and P and P <= 57 and \
                memo.n_states <= (1 << (62 - P)):
            return _search_packed(ops, memo, evs, P, max_configs, ctl)
        if P and P <= WIDE_MAX_SLOTS:
            return _search_packed_wide(ops, memo, evs, P, max_configs,
                                       ctl)
    return _search_sets(ops, memo, evs, max_configs, ctl)


def _failure_info(ops: Sequence[LinOp], bad_op: int, pos: int,
                  prior_configs: Set[Config]) -> dict:
    op = ops[bad_op]
    return {
        "op": {"index": op.orig_invoke, "f": op.f, "value": op.value},
        "return-pos": pos,
        "prior-config-count": len(prior_configs),
        "prior-configs": [
            {"state": int(s), "linearized-not-returned": sorted(lin)}
            for (s, lin) in list(prior_configs)[:4]],
    }


def check(history: "History | Sequence[LinOp]", model: Model,
          max_configs: int = 5_000_000,
          ctl: Optional[Search] = None) -> Dict[str, Any]:
    """JIT-linearization check; same result shape as `wgl.check`.  Unlike
    WGL, a violation is localized to the first un-linearizable return."""
    ops = history if isinstance(history, list) else prepare(history)
    if not ops:
        return {"valid?": "unknown", "op-count": 0}
    try:
        memo = memoize(model, ops)
    except StateExplosion:
        return {"valid?": "unknown", "reason": "state explosion",
                "op-count": len(ops)}
    ok, info = _search(ops, memo, max_configs, ctl)
    if ok is None:
        return stamp_abort({"valid?": "unknown", "op-count": len(ops),
                            **(info or {})}, ctl)
    out: Dict[str, Any] = {"valid?": bool(ok), "op-count": len(ops),
                           "algorithm": "linear"}
    if info:
        out["final-info"] = info
    return out
