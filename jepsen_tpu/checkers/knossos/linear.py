"""Just-in-time linearization (Lowe's algorithm).

Equivalent of `knossos/linear.clj` + `knossos/linear/config.clj`
(SURVEY.md §2.4): configurations evolve per history *event* rather than
per linearization order.  A configuration is ``(model-state,
linearized-set)`` where the set holds ops linearized but not yet
returned.  On an op's return, every surviving configuration must have
linearized it — configurations are expanded "just in time" by linearizing
subsets of pending calls, then filtered; an empty configuration set is a
linearizability violation, localized to that return event.

Uses the same memoized int model states as WGL (`memo.py`); compact
configs are ``(state:int, frozenset[int])`` — the Python analogue of the
reference's array-packed config structures.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.checkers.knossos.memo import Memo, StateExplosion, memoize
from jepsen_tpu.checkers.knossos.prep import NEVER, LinOp, prepare
from jepsen_tpu.checkers.knossos.search import Search
from jepsen_tpu.history.ops import History
from jepsen_tpu.models import Model

Config = Tuple[int, frozenset]


def _events(ops: Sequence[LinOp]) -> List[Tuple[int, str, int]]:
    evs = []
    for op in ops:
        evs.append((op.invoke_pos, "call", op.index))
        if op.return_pos < NEVER:
            evs.append((op.return_pos, "ret", op.index))
    evs.sort()
    return evs


def _jit_expand(configs: Set[Config], target: int, calls: Set[int],
                table, op_sym, max_configs: int) -> Optional[Set[Config]]:
    """All configs reachable by linearizing pending calls, keeping those
    with `target` linearized (then dropping target from the set).
    Returns None on budget blowout."""
    out: Set[Config] = set()
    seen: Set[Config] = set(configs)
    stack = list(configs)
    budget = max_configs
    while stack:
        state, lin = stack.pop()
        if target in lin:
            out.add((state, lin - {target}))
        pending = calls - lin
        for j in pending:
            s2 = int(table[state, op_sym[j]])
            if s2 < 0:
                continue
            c2 = (s2, lin | {j})
            if c2 in seen:
                continue
            seen.add(c2)
            budget -= 1
            if budget <= 0:
                return None
            stack.append(c2)
    return out


def _search(ops: Sequence[LinOp], memo: Memo, max_configs: int,
            ctl: Optional[Search] = None):
    table = memo.table
    op_sym = memo.op_sym
    configs: Set[Config] = {(memo.init_state, frozenset())}
    calls: Set[int] = set()
    for pos, kind, i in _events(ops):
        if ctl is not None and ctl.aborted():
            return None, {"reason": "aborted"}
        if kind == "call":
            calls.add(i)
            continue
        expanded = _jit_expand(configs, i, calls, table, op_sym,
                               max_configs)
        if expanded is None:
            return None, {"reason": "config budget exhausted"}
        calls.remove(i)
        if not expanded:
            return False, _failure_info(ops, i, pos, configs)
        configs = expanded
        if ctl is not None:
            ctl.explored += len(configs)
    return True, None


def _failure_info(ops: Sequence[LinOp], bad_op: int, pos: int,
                  prior_configs: Set[Config]) -> dict:
    op = ops[bad_op]
    return {
        "op": {"index": op.orig_invoke, "f": op.f, "value": op.value},
        "return-pos": pos,
        "prior-config-count": len(prior_configs),
        "prior-configs": [
            {"state": int(s), "linearized-not-returned": sorted(lin)}
            for (s, lin) in list(prior_configs)[:4]],
    }


def check(history: "History | Sequence[LinOp]", model: Model,
          max_configs: int = 5_000_000,
          ctl: Optional[Search] = None) -> Dict[str, Any]:
    """JIT-linearization check; same result shape as `wgl.check`.  Unlike
    WGL, a violation is localized to the first un-linearizable return."""
    ops = history if isinstance(history, list) else prepare(history)
    if not ops:
        return {"valid?": "unknown", "op-count": 0}
    try:
        memo = memoize(model, ops)
    except StateExplosion:
        return {"valid?": "unknown", "reason": "state explosion",
                "op-count": len(ops)}
    ok, info = _search(ops, memo, max_configs, ctl)
    if ok is None:
        return {"valid?": "unknown", **(info or {})}
    out: Dict[str, Any] = {"valid?": bool(ok), "op-count": len(ops),
                           "algorithm": "linear"}
    if info:
        out["final-info"] = info
    return out
