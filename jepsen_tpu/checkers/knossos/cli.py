"""Standalone linearizability-checker CLI.

Equivalent of the reference's `knossos/cli.clj` (SURVEY.md §2.4 "Op
ctors / standalone CLI"): check a STORED single-object history — a JSON
file of op dicts, or a `.jepsen` store run — against a named model,
without building a test map.

    python -m jepsen_tpu.checkers.knossos.cli history.json \
        --model cas-register [--algorithm competition]

History file format: a JSON array of op dicts
``{"type": "invoke|ok|fail|info", "process": 0, "f": "write",
"value": 1}`` in history order (the reference reads EDN; JSON is this
framework's serialization).  A path to a store run directory loads the
run's history instead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from jepsen_tpu.models import (
    FIFOQueue,
    GrowOnlySet,
    Mutex,
    UnorderedQueue,
    cas_register,
    register,
)

MODELS = {
    "register": register,
    "cas-register": cas_register,
    "mutex": Mutex,
    "fifo-queue": FIFOQueue,
    "unordered-queue": UnorderedQueue,
    "set": GrowOnlySet,
}

ALGORITHMS = ("auto", "wgl", "linear", "device", "competition")


def load_history(path: str):
    from jepsen_tpu.history.ops import history

    if os.path.isdir(path):
        from jepsen_tpu import store

        test = store.load(path)
        hist = test.get("history")
        if hist is None:
            raise SystemExit(f"no history stored in {path}")
        return hist.materialize() if hasattr(hist, "materialize") else hist
    with open(path) as f:
        ds = json.load(f)
    if not isinstance(ds, list):
        raise SystemExit("history file must be a JSON array of op dicts")
    # files without explicit indices use array order as history order
    return history(ds, reindex=any(d.get("index", -1) < 0 for d in ds))


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="knossos",
        description="Check a stored history for linearizability "
                    "(knossos/cli.clj equivalent)")
    p.add_argument("history", help="JSON history file or store run dir")
    p.add_argument("--model", default="cas-register",
                   choices=sorted(MODELS),
                   help="sequential model to check against")
    p.add_argument("--algorithm", default="auto", choices=ALGORITHMS)
    p.add_argument("--max-configs", type=int, default=5_000_000)
    opts = p.parse_args(argv)

    from jepsen_tpu.checkers.knossos import analysis

    h = load_history(opts.history)
    model = MODELS[opts.model]()
    res = analysis(h, model, algorithm=opts.algorithm,
                   max_configs=opts.max_configs)
    print(json.dumps(res, default=str, indent=2))
    if res["valid?"] is True:
        return 0
    return 1 if res["valid?"] is False else 2


if __name__ == "__main__":
    sys.exit(main())
