"""TPU linearizability search: batched frontier BFS over the config lattice.

The reference's WGL is a sequential DFS with a JVM-bitset visited cache
(`knossos/wgl.clj`).  Reframed for TPU (SURVEY.md §2.4, §2.7 "Knossos
competition" row, BASELINE.json north star): the DFS branch set becomes a
*wave* — all configurations with k linearized ops — processed as one wide
tensor step:

  config   = (model state int32, linearized bitset W x uint32)
  wave     = frontier (F, W+1) in HBM
  expand   = for every config x every op: candidate iff op not yet
             linearized, its invocation precedes every unlinearized
             return (real-time order), and the memoized transition table
             admits it — all as (F, n) masked gathers
  dedup    = Zobrist hashing (h(S ^ op) = h(S) ^ z[op]) so children hash
             incrementally without materializing (F*n, W) bitsets; unique
             by (h1, h2, state') via lexsort + adjacent-compare
  success  = some config linearizes every op that returned

`info` (crashed) ops never return and may stay unlinearized — exactly the
reference's forever-concurrent treatment.

Exactness: a 64-bit hash collision could merge two distinct configs
(collision odds < 1e-9 per wave at the default frontier cap).  The result
therefore carries `hash_dedup: True`; `competition.analysis` anchors
definitive verdicts on the exact host search when the history is small and
uses the device verdict beyond that, as the reference races wgl/linear.
Frontier overflow -> `"unknown"` (never a wrong verdict).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.checkers.knossos.memo import Memo, StateExplosion, memoize
from jepsen_tpu.checkers.knossos.prep import NEVER, LinOp
from jepsen_tpu.models import Model

INF = jnp.int32(2 ** 30)


@partial(jax.jit, static_argnames=("n", "W", "max_frontier", "n_waves"))
def _frontier_search(n: int, W: int, max_frontier: int, n_waves: int,
                     invokes, returns, op_sym, must, table, z1, z2,
                     init_state):
    """Returns (linearizable, exhausted, overflow).

    linearizable: some config covered every must-op.
    exhausted: frontier emptied without success (=> not linearizable).
    overflow: frontier cap exceeded at some wave (result unreliable).
    """
    F = max_frontier
    word_idx = (jnp.arange(n) // 32).astype(jnp.int32)
    bit = (jnp.arange(n) % 32).astype(jnp.int32)
    op_bit = (jnp.uint32(1) << bit.astype(jnp.uint32))

    # frontier: states (F,), bits (F, W) uint32, h1/h2 (F,), valid (F,)
    states0 = jnp.zeros(F, jnp.int32).at[0].set(init_state)
    bits0 = jnp.zeros((F, W), jnp.uint32)
    h10 = jnp.zeros(F, jnp.uint32)
    h20 = jnp.zeros(F, jnp.uint32)
    valid0 = jnp.zeros(F, bool).at[0].set(True)

    def success_of(states, bits, valid):
        covered = jnp.all((bits & must[None, :]) == must[None, :], axis=1)
        return jnp.any(valid & covered)

    def wave(state):
        states, bits, h1, h2, valid, done, overflow, w = state
        # (F, n): is op i linearized in config c?
        cfg_words = bits[:, word_idx]                      # (F, n)
        in_s = (cfg_words >> bit.astype(jnp.uint32)) & 1
        in_s = in_s.astype(bool)
        # min return among unlinearized ops
        ret_masked = jnp.where(in_s, INF, returns[None, :])
        minret = jnp.min(ret_masked, axis=1)               # (F,)
        cand = (~in_s) & (invokes[None, :] < minret[:, None]) & valid[:, None]
        nxt_state = table[states[:, None], op_sym[None, :]]  # (F, n)
        cand = cand & (nxt_state >= 0)

        # flatten children: ids (F*n,), dedup keys via Zobrist
        ch_h1 = (h1[:, None] ^ z1[None, :]).reshape(-1)
        ch_h2 = (h2[:, None] ^ z2[None, :]).reshape(-1)
        ch_state = nxt_state.reshape(-1)
        ch_mask = cand.reshape(-1)
        parent = jnp.repeat(jnp.arange(F, dtype=jnp.int32), n)
        opid = jnp.tile(jnp.arange(n, dtype=jnp.int32), F)

        # sort: invalid rows last, equal configs adjacent
        order = jnp.lexsort((
            ch_state, ch_h2, ch_h1, (~ch_mask).astype(jnp.int32)))
        s_h1 = ch_h1[order]
        s_h2 = ch_h2[order]
        s_state = ch_state[order]
        s_mask = ch_mask[order]
        first = jnp.concatenate([
            jnp.ones(1, bool),
            (s_h1[1:] != s_h1[:-1]) | (s_h2[1:] != s_h2[:-1]) |
            (s_state[1:] != s_state[:-1])])
        keep = s_mask & first
        n_new = jnp.sum(keep.astype(jnp.int32))
        overflow = overflow | (n_new > F)

        # compact the first F kept rows into the new frontier
        kidx = jnp.cumsum(keep.astype(jnp.int32)) - 1      # target row
        tgt = jnp.where(keep & (kidx < F), kidx, F)
        take = jnp.full(F + 1, -1, jnp.int32).at[tgt].max(
            jnp.arange(F * n, dtype=jnp.int32))[:F]        # source row in sorted
        new_valid = take >= 0
        tk = jnp.clip(take, 0, F * n - 1)
        src = order[tk]
        p = parent[src]
        o = opid[src]
        new_states = jnp.where(new_valid, ch_state[src], 0)
        new_bits = bits[p] | (
            jnp.zeros((F, W), jnp.uint32).at[
                jnp.arange(F), word_idx[o]].set(op_bit[o]))
        new_bits = jnp.where(new_valid[:, None], new_bits, 0)
        new_h1 = jnp.where(new_valid, ch_h1[src], 0)
        new_h2 = jnp.where(new_valid, ch_h2[src], 0)

        done = done | success_of(new_states, new_bits, new_valid)
        return (new_states, new_bits, new_h1, new_h2, new_valid, done,
                overflow, w + 1)

    def cond(state):
        _, _, _, _, valid, done, overflow, w = state
        return (~done) & (~overflow) & jnp.any(valid) & (w < n_waves)

    init_done = success_of(states0, bits0, valid0)
    init = (states0, bits0, h10, h20, valid0, init_done,
            jnp.array(False), jnp.int32(0))
    states, bits, h1, h2, valid, done, overflow, w = jax.lax.while_loop(
        cond, wave, init)
    exhausted = (~done) & (~overflow) & (~jnp.any(valid) | (w >= n_waves))
    return done, exhausted, overflow


def check(ops: Sequence[LinOp], model: Model,
          max_frontier: int = 16384) -> Dict[str, Any]:
    """Device linearizability check of prepared ops against a model."""
    n = len(ops)
    if n == 0:
        return {"valid?": "unknown", "op-count": 0}
    if n > 4096:
        return {"valid?": "unknown", "op-count": n,
                "reason": "too many ops for device WGL"}
    try:
        memo = memoize(model, ops)
    except StateExplosion:
        return {"valid?": "unknown", "op-count": n,
                "reason": "model state explosion"}
    n_pad = 8
    while n_pad < n:
        n_pad *= 2
    W = (n_pad + 31) // 32
    # padding ops: invoke at +inf so they are never candidates; returns just
    # above the info-op cap so they never constrain minret below real ops
    invokes = np.full(n_pad, 2 ** 30, np.int32)
    returns = np.full(n_pad, 2 ** 29 + 1, np.int32)
    op_sym = np.zeros(n_pad, np.int32)
    must = np.zeros(W, np.uint32)
    for i, op in enumerate(ops):
        invokes[i] = op.invoke_pos
        returns[i] = min(op.return_pos, 2 ** 29)
        op_sym[i] = memo.op_sym[i]
        if op.return_pos < NEVER:
            must[i // 32] |= np.uint32(1 << (i % 32))
    # padding ops: make them non-candidates (invoke = huge) and
    # transitions irrelevant; returns huge so they never constrain minret
    table = memo.table
    rng = np.random.default_rng(0xC0FFEE)
    z1 = rng.integers(0, 2 ** 32, n_pad, dtype=np.uint32)
    z2 = rng.integers(0, 2 ** 32, n_pad, dtype=np.uint32)

    lin, exhausted, overflow = _frontier_search(
        n_pad, W, max_frontier, n + 1,
        jnp.asarray(invokes), jnp.asarray(returns), jnp.asarray(op_sym),
        jnp.asarray(must), jnp.asarray(table), jnp.asarray(z1),
        jnp.asarray(z2), jnp.int32(memo.init_state))
    lin, exhausted, overflow = (bool(lin), bool(exhausted), bool(overflow))
    if overflow:
        return {"valid?": "unknown", "op-count": n,
                "reason": "frontier overflow", "hash_dedup": True}
    return {"valid?": True if lin else False, "op-count": n,
            "hash_dedup": True}
