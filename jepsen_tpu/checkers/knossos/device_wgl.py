"""TPU linearizability search: batched frontier BFS over the config lattice.

The reference's WGL is a sequential DFS with a JVM-bitset visited cache
(`knossos/wgl.clj`).  Reframed for TPU (SURVEY.md §2.4, §2.7 "Knossos
competition" row, BASELINE.json north star): the DFS branch set becomes a
*wave* — all configurations with k linearized ops — processed as one wide
tensor step:

  config   = (model state int32, linearized bitset W x uint32)
  wave     = frontier (F, W+1) in HBM
  expand   = for every config x every op: candidate iff op not yet
             linearized, its invocation precedes every unlinearized
             return (real-time order), and the memoized transition table
             admits it — all as (F, n) masked gathers
  dedup    = Zobrist hashing (h(S ^ op) = h(S) ^ z[op]) so children hash
             incrementally without materializing (F*n, W) bitsets; unique
             by (h1, h2, state') via lexsort + adjacent-compare
  success  = some config linearizes every op that returned

`info` (crashed) ops never return and may stay unlinearized — exactly the
reference's forever-concurrent treatment.

Exactness: a 64-bit hash collision could merge two distinct configs
(collision odds < 1e-9 per wave at the default frontier cap).  The result
therefore carries `hash_dedup: True`; `competition.analysis` anchors
definitive verdicts on the exact host search when the history is small and
uses the device verdict beyond that, as the reference races wgl/linear.

Scaling beyond the single-jit wave (SURVEY.md §7 "WGL state explosion:
wave-size caps + host spill"): histories past 4096 ops, and frontiers past
the device cap, go through the *blocked* search — the frontier lives on
the host as a list of <= F-row blocks (the JVM-heap analogue is host RAM,
the spill target), each wave expands block-by-block through one device
jit (`_expand_block`), and cross-block dedup happens on the host with
one vectorized sort-unique per wave — per-WAVE only, because configs in
different waves have different popcounts and so can never collide.  A
block whose unique children exceed the output capacity is split in half
and re-expanded — never truncated.  Only genuine resource exhaustion
(the cumulative explored-config counter passing `max_configs`) returns
"unknown"; frontier size alone no longer does.

Expansion is restricted per wave to the ACTIVE op window (ops not
linearized in every config, invokable below the wave's minret bound) —
without this, every wave pays F x n work and long serial histories are
hopeless; with it, per-wave cost tracks the real concurrency window.
Differentially tested per-wave against an exact Python set-BFS.

Crash-heavy histories (`info` ops) no longer blow the frontier up: each
crashed op stays forever-concurrent, so a naive BFS enumerates every
did/didn't-linearize-it subset per wave.  The blocked search prunes that
dimension with a sound cross-wave dominance rule: a config
(state, R, X₁) — R the linearized *returned* ops, X the linearized
*crashed* ops — simulates every future of (state, R, X₂) when X₁ ⊂ X₂.
Crashed ops never drive `minret` (their returns sit at the 2^29 cap,
above every real invoke), so the extra unlinearized crashed ops on the
X₁ side only add options, never constraints: any schedule from the X₂
config replays verbatim from the X₁ config.  The search keeps a host-
side store of minimal X-sets per (state, R) and drops dominated
children as they are generated — the config count then tracks the
DFS-competitive measure (states x returned-schedules x X-antichain)
instead of the crashed-subset lattice.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_DEBUG = bool(os.environ.get("JT_WGL_DEBUG"))

from jepsen_tpu.checkers.knossos.memo import Memo, StateExplosion, memoize
from jepsen_tpu.checkers.knossos.prep import NEVER, LinOp
from jepsen_tpu.checkers.knossos.search import stamp_abort
from jepsen_tpu.models import Model
from jepsen_tpu.resilience import NO_PLAN, device_call

INF = jnp.int32(2 ** 30)


@partial(jax.jit, static_argnames=("n", "W", "max_frontier", "n_waves"))
def _frontier_search(n: int, W: int, max_frontier: int, n_waves: int,
                     invokes, returns, op_sym, must, table, z1, z2,
                     init_state):
    """Returns (linearizable, exhausted, overflow).

    linearizable: some config covered every must-op.
    exhausted: frontier emptied without success (=> not linearizable).
    overflow: frontier cap exceeded at some wave (result unreliable).
    """
    F = max_frontier
    word_idx = (jnp.arange(n) // 32).astype(jnp.int32)
    bit = (jnp.arange(n) % 32).astype(jnp.int32)
    op_bit = (jnp.uint32(1) << bit.astype(jnp.uint32))

    # frontier: states (F,), bits (F, W) uint32, h1/h2 (F,), valid (F,)
    states0 = jnp.zeros(F, jnp.int32).at[0].set(init_state)
    bits0 = jnp.zeros((F, W), jnp.uint32)
    h10 = jnp.zeros(F, jnp.uint32)
    h20 = jnp.zeros(F, jnp.uint32)
    valid0 = jnp.zeros(F, bool).at[0].set(True)

    def success_of(states, bits, valid):
        covered = jnp.all((bits & must[None, :]) == must[None, :], axis=1)
        return jnp.any(valid & covered)

    def wave(state):
        states, bits, h1, h2, valid, done, overflow, w = state
        # (F, n): is op i linearized in config c?
        cfg_words = bits[:, word_idx]                      # (F, n)
        in_s = (cfg_words >> bit.astype(jnp.uint32)) & 1
        in_s = in_s.astype(bool)
        # min return among unlinearized ops
        ret_masked = jnp.where(in_s, INF, returns[None, :])
        minret = jnp.min(ret_masked, axis=1)               # (F,)
        cand = (~in_s) & (invokes[None, :] < minret[:, None]) & valid[:, None]
        nxt_state = table[states[:, None], op_sym[None, :]]  # (F, n)
        cand = cand & (nxt_state >= 0)

        # flatten children: ids (F*n,), dedup keys via Zobrist
        ch_h1 = (h1[:, None] ^ z1[None, :]).reshape(-1)
        ch_h2 = (h2[:, None] ^ z2[None, :]).reshape(-1)
        ch_state = nxt_state.reshape(-1)
        ch_mask = cand.reshape(-1)
        parent = jnp.repeat(jnp.arange(F, dtype=jnp.int32), n)
        opid = jnp.tile(jnp.arange(n, dtype=jnp.int32), F)

        # sort: invalid rows last, equal configs adjacent
        order = jnp.lexsort((
            ch_state, ch_h2, ch_h1, (~ch_mask).astype(jnp.int32)))
        s_h1 = ch_h1[order]
        s_h2 = ch_h2[order]
        s_state = ch_state[order]
        s_mask = ch_mask[order]
        first = jnp.concatenate([
            jnp.ones(1, bool),
            (s_h1[1:] != s_h1[:-1]) | (s_h2[1:] != s_h2[:-1]) |
            (s_state[1:] != s_state[:-1])])
        keep = s_mask & first
        n_new = jnp.sum(keep.astype(jnp.int32))
        overflow = overflow | (n_new > F)

        # compact the first F kept rows into the new frontier
        kidx = jnp.cumsum(keep.astype(jnp.int32)) - 1      # target row
        tgt = jnp.where(keep & (kidx < F), kidx, F)
        take = jnp.full(F + 1, -1, jnp.int32).at[tgt].max(
            jnp.arange(F * n, dtype=jnp.int32))[:F]        # source row in sorted
        new_valid = take >= 0
        tk = jnp.clip(take, 0, F * n - 1)
        src = order[tk]
        p = parent[src]
        o = opid[src]
        new_states = jnp.where(new_valid, ch_state[src], 0)
        new_bits = bits[p] | (
            jnp.zeros((F, W), jnp.uint32).at[
                jnp.arange(F), word_idx[o]].set(op_bit[o]))
        new_bits = jnp.where(new_valid[:, None], new_bits, 0)
        new_h1 = jnp.where(new_valid, ch_h1[src], 0)
        new_h2 = jnp.where(new_valid, ch_h2[src], 0)

        done = done | success_of(new_states, new_bits, new_valid)
        return (new_states, new_bits, new_h1, new_h2, new_valid, done,
                overflow, w + 1)

    def cond(state):
        _, _, _, _, valid, done, overflow, w = state
        return (~done) & (~overflow) & jnp.any(valid) & (w < n_waves)

    init_done = success_of(states0, bits0, valid0)
    init = (states0, bits0, h10, h20, valid0, init_done,
            jnp.array(False), jnp.int32(0))
    states, bits, h1, h2, valid, done, overflow, w = jax.lax.while_loop(
        cond, wave, init)
    exhausted = (~done) & (~overflow) & (~jnp.any(valid) | (w >= n_waves))
    return done, exhausted, overflow


MAX_DEVICE_OPS = 32768


def _setup(ops: Sequence[LinOp], memo: Memo):
    """Padded arrays shared by both search shapes."""
    n = len(ops)
    n_pad = 8
    while n_pad < n:
        n_pad *= 2
    W = (n_pad + 31) // 32
    # padding ops: invoke at +inf so they are never candidates; returns just
    # above the info-op cap so they never constrain minret below real ops
    invokes = np.full(n_pad, 2 ** 30, np.int32)
    returns = np.full(n_pad, 2 ** 29 + 1, np.int32)
    op_sym = np.zeros(n_pad, np.int32)
    must = np.zeros(W, np.uint32)
    for i, op in enumerate(ops):
        invokes[i] = op.invoke_pos
        returns[i] = min(op.return_pos, 2 ** 29)
        op_sym[i] = memo.op_sym[i]
        if op.return_pos < NEVER:
            must[i // 32] |= np.uint32(1 << (i % 32))
    rng = np.random.default_rng(0xC0FFEE)
    z1 = rng.integers(0, 2 ** 32, n_pad, dtype=np.uint32)
    z2 = rng.integers(0, 2 ** 32, n_pad, dtype=np.uint32)
    return n_pad, W, invokes, returns, op_sym, must, z1, z2


def check(ops: Sequence[LinOp], model: Model,
          max_frontier: int = 16384,
          max_configs: int = 20_000_000, ctl=None) -> Dict[str, Any]:
    """Device linearizability check of prepared ops against a model.

    `ctl` (a `search.Search`) aborts the blocked search between waves,
    between blocks, and inside the dominance-prune row loop — a
    competition can cancel this leg, and a deadline bounds it.  Passing
    a ctl also forces the blocked search for small histories: the
    single-jit path is one unabortable `lax.while_loop`, fine standalone
    but not as a cancellable race leg."""
    n = len(ops)
    if n == 0:
        return {"valid?": "unknown", "op-count": 0}
    if n > MAX_DEVICE_OPS:
        return {"valid?": "unknown", "op-count": n,
                "reason": "too many ops for device WGL"}
    if ctl is not None and ctl.aborted():
        # an expired/cancelled ctl skips the memoize/setup/transfer cost
        return stamp_abort({"valid?": "unknown", "op-count": n,
                            "reason": "aborted"}, ctl)
    try:
        memo = memoize(model, ops)
    except StateExplosion:
        return {"valid?": "unknown", "op-count": n,
                "reason": "model state explosion"}
    n_pad, W, invokes, returns, op_sym, must, z1, z2 = _setup(ops, memo)
    table = memo.table

    # The single-jit wave burns F x n_pad work EVERY wave regardless of
    # frontier occupancy — past ~1k ops a serial history pays thousands
    # of full-width waves and the blocked search (blocks sized to the
    # live frontier) is strictly faster as well as memory-spilled.
    # With a ctl we go blocked regardless of size: the single-jit path
    # is one unabortable `lax.while_loop`, and a competition loser must
    # stay cancellable (non-daemon racer threads join at process exit —
    # daemon threads SIGABRT inside native XLA teardown).
    if n <= 1024 and ctl is None:
        # guarded device seam: transient XLA failures (or injected
        # faults) retry per policy; persistent ones propagate to the
        # caller (the competition treats a crashed leg as a loser)
        lin, exhausted, overflow = device_call(
            "knossos.device-wgl", _frontier_search,
            n_pad, W, max_frontier, n + 1,
            jnp.asarray(invokes), jnp.asarray(returns),
            jnp.asarray(op_sym), jnp.asarray(must), jnp.asarray(table),
            jnp.asarray(z1), jnp.asarray(z2), jnp.int32(memo.init_state))
        lin, overflow = bool(lin), bool(overflow)
        if not overflow:
            return {"valid?": True if lin else False, "op-count": n,
                    "hash_dedup": True}
        # fall through: re-run with host-spilled frontier blocks

    return stamp_abort(
        _blocked_search(n, n_pad, W, invokes, returns, op_sym, must,
                        table, memo.init_state, z1, z2,
                        max_frontier, max_configs, ctl), ctl)


def _blocked_and_check(ops: Sequence[LinOp], model: Model,
                       max_frontier: int = 16384,
                       max_configs: int = 20_000_000,
                       ctl=None) -> Dict[str, Any]:
    """Route straight to the blocked (host-spill) search — used by tests
    and by callers that know the frontier will overflow."""
    n = len(ops)
    if ctl is not None and ctl.aborted():
        return stamp_abort({"valid?": "unknown", "op-count": n,
                            "reason": "aborted"}, ctl)
    try:
        memo = memoize(model, ops)
    except StateExplosion:
        return {"valid?": "unknown", "op-count": n,
                "reason": "model state explosion"}
    n_pad, W, invokes, returns, op_sym, must, z1, z2 = _setup(ops, memo)
    return stamp_abort(
        _blocked_search(n, n_pad, W, invokes, returns, op_sym, must,
                        memo.table, memo.init_state, z1, z2,
                        max_frontier, max_configs, ctl), ctl)


# ---------------------------------------------------------------------------
# Blocked search: host-resident frontier, device per-block expansion.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("A", "W", "F", "C"))
def _expand_block(A: int, W: int, F: int, C: int,
                  act_mask, act_invokes, act_returns, act_sym,
                  act_z1, act_z2, act_word, act_bit,
                  table, states, bits, h1, h2, valid):
    """Expand one frontier block of F configs into <= C unique children,
    over a WINDOW of A active ops (gathered on host).

    The window restriction is what makes long histories tractable
    (`knossos/wgl.clj`'s effective behavior): at wave k, ops linearized
    in every config and ops not yet invokable (invoke >= the (k+1)-th
    smallest return) can never be candidates, so the op axis shrinks
    from n to the concurrency window.  `minret` over active unlinearized
    ops is exact for the candidate test: excluded ops are either
    linearized (no contribution) or have returns strictly above the
    window bound every candidate's invoke is below.

    Returns (out_states, out_bits, out_h1, out_h2, out_valid, n_unique):
    children deduped within the block; n_unique may exceed C (the caller
    must then split the block and retry — nothing is silently dropped).
    """
    op_bit = (jnp.uint32(1) << act_bit.astype(jnp.uint32))

    cfg_words = bits[:, jnp.clip(act_word, 0, W - 1)]     # (F, A)
    in_s = ((cfg_words >> act_bit.astype(jnp.uint32)) & 1).astype(bool)
    in_s = in_s | ~act_mask[None, :]
    ret_masked = jnp.where(in_s, INF, act_returns[None, :])
    minret = jnp.min(ret_masked, axis=1)
    cand = (~in_s) & (act_invokes[None, :] < minret[:, None]) & \
        valid[:, None]
    nxt_state = table[states[:, None], jnp.clip(act_sym, 0, None)[None, :]]
    cand = cand & (nxt_state >= 0)

    ch_h1 = (h1[:, None] ^ act_z1[None, :]).reshape(-1)
    ch_h2 = (h2[:, None] ^ act_z2[None, :]).reshape(-1)
    ch_state = nxt_state.reshape(-1)
    ch_mask = cand.reshape(-1)
    parent = jnp.repeat(jnp.arange(F, dtype=jnp.int32), A)
    opid = jnp.tile(jnp.arange(A, dtype=jnp.int32), F)

    order = jnp.lexsort((
        ch_state, ch_h2, ch_h1, (~ch_mask).astype(jnp.int32)))
    s_h1 = ch_h1[order]
    s_h2 = ch_h2[order]
    s_state = ch_state[order]
    s_mask = ch_mask[order]
    first = jnp.concatenate([
        jnp.ones(1, bool),
        (s_h1[1:] != s_h1[:-1]) | (s_h2[1:] != s_h2[:-1]) |
        (s_state[1:] != s_state[:-1])])
    keep = s_mask & first
    n_unique = jnp.sum(keep.astype(jnp.int32))

    kidx = jnp.cumsum(keep.astype(jnp.int32)) - 1
    tgt = jnp.where(keep & (kidx < C), kidx, C)
    take = jnp.full(C + 1, -1, jnp.int32).at[tgt].max(
        jnp.arange(F * A, dtype=jnp.int32))[:C]
    out_valid = take >= 0
    tk = jnp.clip(take, 0, F * A - 1)
    src = order[tk]
    p = parent[src]
    o = opid[src]
    out_states = jnp.where(out_valid, ch_state[src], 0)
    out_bits = bits[p] | (
        jnp.zeros((C, W), jnp.uint32).at[
            jnp.arange(C), jnp.clip(act_word[o], 0, W - 1)].set(op_bit[o]))
    out_bits = jnp.where(out_valid[:, None], out_bits, 0)
    out_h1 = jnp.where(out_valid, ch_h1[src], 0)
    out_h2 = jnp.where(out_valid, ch_h2[src], 0)
    return out_states, out_bits, out_h1, out_h2, out_valid, n_unique


class _Aborted(Exception):
    """Raised inside long per-row host loops when `ctl` aborts mid-wave."""


def _blocked_search(n, n_pad, W, invokes, returns, op_sym, must, table,
                    init_state, z1, z2, max_frontier, max_configs,
                    ctl=None) -> Dict[str, Any]:
    """Breadth-first over waves; frontier spilled to host as block lists.

    Every wave holds configs with the same linearized-count, so the
    cross-wave dedup set only needs the current wave's keys.  Device
    memory is bounded by one (F, n_pad) expansion; host memory holds
    everything else — the SURVEY §7 "host spill" answer to WGL state
    explosion.
    """
    from jepsen_tpu.resilience import plan_for

    # resolve the fault plan ONCE per search: the expand loop below is
    # hot (thousands of block dispatches) and must not re-consult the
    # env per call; one plan also means one coherent call counter.  The
    # NO_PLAN sentinel tells device_call "already resolved, none" so
    # the no-faults case skips the per-call lookup too
    fault_plan = plan_for(None) or NO_PLAN
    F_max = max(64, min(max_frontier, 16384))

    table_dev = jnp.asarray(table)
    word_idx_h = (np.arange(n_pad) // 32).astype(np.int32)
    bit_h = (np.arange(n_pad) % 32).astype(np.int32)
    must_row = must[None, :]
    # (k+1)-th smallest real return bounds every wave-k config's minret
    real_rets = np.sort(returns[returns < 2 ** 29])

    # crashed-op dominance prune (see module doc): minimal linearized-
    # crashed bitsets per (state, returned-lin) key.  Engaged only when
    # crashed ops are numerous enough for subset blowup to matter — the
    # per-row host loop costs more than it saves on a near-clean history
    # (blowup is bounded by 2^n_info), and skipping both the prune AND
    # the store is sound: pruning only ever removes simulated configs.
    n_info = int(np.sum(returns[:n] == 2 ** 29))
    use_dominance = n_info >= 3
    info_mask = ~must  # words: bits of crashed (+ padding, always-0) ops
    dom: Dict[bytes, list] = {}

    def dominance_prune(s, b, h1u, h2u):
        """Drop configs whose crashed-lin set is a strict superset of a
        previously kept one at the same (state, returned-lin).  Keeps
        (and records) the survivors.  The store holds a python LIST of
        minimal-X rows per key (append is O(1); antichains stay small).

        Polls `ctl` every 1024 rows: this per-row python loop is the
        longest uninterruptible stretch in a crash-heavy wave (minutes
        at 100k-row frontiers), and an aborted competition loser must
        not keep burning the core until the wave ends."""
        R = b & must_row
        X = b & info_mask[None, :]
        keep_rows = np.ones(len(s), bool)
        for i in range(len(s)):
            if ctl is not None and i % 1024 == 1023 and ctl.aborted():
                raise _Aborted
            key = s[i].tobytes() + R[i].tobytes()
            stored = dom.get(key)
            xi = X[i]
            if stored is not None:
                # dominated iff some stored X' ⊆ X (strict or equal;
                # equal can't happen across waves, and within a wave the
                # exact dedup already removed duplicates)
                if any(bool(np.all((x & ~xi) == 0)) for x in stored):
                    keep_rows[i] = False
                    continue
                stored.append(xi.copy())
            else:
                dom[key] = [xi.copy()]
        return s[keep_rows], b[keep_rows], h1u[keep_rows], h2u[keep_rows]

    def active_window(blocks, k):
        """Op ids that can still be candidates at wave k: not linearized
        in EVERY config, and invokable below the wave's minret bound."""
        all_ones = np.full(W, 0xFFFFFFFF, np.uint64).astype(np.uint32)
        for st, bi, a1, a2, va in blocks:
            if va.any():
                all_ones &= np.bitwise_and.reduce(bi[va], axis=0)
        everywhere = ((all_ones[word_idx_h] >> bit_h) & 1).astype(bool)
        bound = (real_rets[k] if k < len(real_rets)
                 else np.int64(2 ** 62))
        act = ~everywhere & (invokes < bound)
        return np.nonzero(act)[0].astype(np.int32)

    def cap_of(F, A):
        # one config can have up to A children, so C >= A guarantees a
        # single-row block never needs splitting (split progress)
        return min(max(4 * F, A), F * A)

    # Small waves skip the device entirely: per-wave jit dispatch plus
    # host<->device round-trips dominate when the frontier is a few
    # hundred rows (the crash-heavy regime after dominance pruning), and
    # the expansion math is trivial in numpy at that size.
    HOST_EXPAND_MAX = 4096

    def expand_host(act, states, bits, h1, h2):
        """Exact children of a small frontier over the active window —
        the numpy mirror of `_expand_block` (no caps, no splitting)."""
        aw = word_idx_h[act]
        ab = bit_h[act]
        in_s = ((bits[:, aw] >> ab) & 1).astype(bool)          # (m, A)
        ret = np.where(in_s, np.int64(2 ** 30), returns[act][None, :])
        minret = ret.min(axis=1)
        cand = (~in_s) & (invokes[act][None, :] < minret[:, None])
        nxt = table[states[:, None], op_sym[act][None, :]]
        cand &= nxt >= 0
        rows, cols = np.nonzero(cand)
        ch_state = nxt[rows, cols].astype(np.int32)
        ch_h1 = h1[rows] ^ z1[act][cols]
        ch_h2 = h2[rows] ^ z2[act][cols]
        ch_bits = bits[rows].copy()
        ch_bits[np.arange(len(rows)), aw[cols]] |= (
            np.uint32(1) << ab[cols].astype(np.uint32))
        return ch_state, ch_bits, ch_h1, ch_h2

    def pad_block(states, bits, h1, h2, m):
        # right-size the block: a sparse wave (serial history) must not
        # pay full-F_max expansion work
        F = 64
        while F < m and F < F_max:
            F *= 2
        out = (np.zeros(F, np.int32), np.zeros((F, W), np.uint32),
               np.zeros(F, np.uint32), np.zeros(F, np.uint32),
               np.zeros(F, bool))
        out[0][:m] = states[:m]
        out[1][:m] = bits[:m]
        out[2][:m] = h1[:m]
        out[3][:m] = h2[:m]
        out[4][:m] = True
        return out

    # initial frontier: the empty config
    blocks = [pad_block(np.array([init_state], np.int32),
                        np.zeros((1, W), np.uint32),
                        np.zeros(1, np.uint32), np.zeros(1, np.uint32), 1)]
    if bool(np.all((blocks[0][1][:1] & must_row) == must_row)):
        return {"valid?": True, "op-count": n, "hash_dedup": True,
                "blocked": True}

    aborted = {"valid?": "unknown", "op-count": n, "reason": "aborted",
               "hash_dedup": True, "blocked": True}
    total_seen = 0
    for k in range(n + 1):
        if ctl is not None and ctl.aborted():
            return dict(aborted, explored=total_seen)
        # collect every block's (block-deduped) children, then do ONE
        # vectorized cross-block dedup + success check for the wave.
        # Configs in different waves have different popcounts, so no
        # cross-wave seen-set is needed.
        ch_s: List[np.ndarray] = []
        ch_b: List[np.ndarray] = []
        ch_h1: List[np.ndarray] = []
        ch_h2: List[np.ndarray] = []

        act = active_window(blocks, k)
        total_rows = int(sum(b[4].sum() for b in blocks))

        if _DEBUG and k % 50 == 0:
            import time as _t
            print(f"wave {k}: blocks={len(blocks)} rows={total_rows} "
                  f"A={len(act)} t={_t.perf_counter():.1f}", flush=True)

        if total_rows <= HOST_EXPAND_MAX and len(act):
            st = np.concatenate([b[0][b[4]] for b in blocks])
            bi = np.concatenate([b[1][b[4]] for b in blocks])
            a1 = np.concatenate([b[2][b[4]] for b in blocks])
            a2 = np.concatenate([b[3][b[4]] for b in blocks])
            o_st, o_bi, o_h1, o_h2 = expand_host(act, st, bi, a1, a2)
            if len(o_st):
                ch_s.append(o_st)
                ch_b.append(o_bi)
                ch_h1.append(o_h1)
                ch_h2.append(o_h2)
            work = []
        else:
            work = list(blocks)

        A = 8
        while A < len(act):
            A *= 2
        act_mask = np.zeros(A, bool)
        act_mask[:len(act)] = True
        act_pad = np.zeros(A, np.int32)
        act_pad[:len(act)] = act
        win = None
        if work:
            win = (jnp.asarray(act_mask), jnp.asarray(invokes[act_pad]),
                   jnp.asarray(returns[act_pad]),
                   jnp.asarray(op_sym[act_pad]),
                   jnp.asarray(z1[act_pad]), jnp.asarray(z2[act_pad]),
                   jnp.asarray(word_idx_h[act_pad]),
                   jnp.asarray(bit_h[act_pad]))
        while work:
            if ctl is not None and ctl.aborted():
                return dict(aborted, explored=total_seen)
            st, bi, a1, a2, va = work.pop()
            F = len(st)
            C = cap_of(F, A)
            outs = device_call(
                "knossos.device-wgl.expand", _expand_block,
                A, W, F, C, *win, table_dev,
                jnp.asarray(st), jnp.asarray(bi),
                jnp.asarray(a1), jnp.asarray(a2),
                jnp.asarray(va), plan=fault_plan)
            o_st, o_bi, o_h1, o_h2, o_va, n_uniq = (np.asarray(x)
                                                    for x in outs)
            if int(n_uniq) > C:
                # children overflow the output capacity: split the block
                # rows in half and re-expand — exact, never truncating
                half = max(1, int(va.sum()) // 2)
                idx = np.nonzero(va)[0]
                lo, hi = idx[:half], idx[half:]
                for part in (lo, hi):
                    if len(part):
                        work.append(pad_block(st[part], bi[part],
                                              a1[part], a2[part],
                                              len(part)))
                continue
            m = o_va
            ch_s.append(o_st[m])
            ch_b.append(o_bi[m])
            ch_h1.append(o_h1[m])
            ch_h2.append(o_h2[m])

        if not ch_s or not sum(len(x) for x in ch_s):
            return {"valid?": False, "op-count": n, "hash_dedup": True,
                    "blocked": True}
        if ctl is not None and ctl.aborted():
            return dict(aborted, explored=total_seen)
        s = np.concatenate(ch_s)
        b = np.concatenate(ch_b)
        h1_all = np.concatenate(ch_h1)
        h2_all = np.concatenate(ch_h2)
        key = (h1_all.astype(np.uint64) << np.uint64(32)) | h2_all
        order = np.lexsort((s, key))
        sk = key[order]
        ss = s[order]
        first = np.concatenate([[True],
                                (sk[1:] != sk[:-1]) | (ss[1:] != ss[:-1])])
        uniq = order[first]
        s, b = s[uniq], b[uniq]
        h1u = h1_all[uniq]
        h2u = h2_all[uniq]

        if bool(np.all((b & must[None, :]) == must[None, :],
                       axis=1).any()):
            return {"valid?": True, "op-count": n, "hash_dedup": True,
                    "blocked": True}
        if use_dominance:
            try:
                s, b, h1u, h2u = dominance_prune(s, b, h1u, h2u)
            except _Aborted:
                return dict(aborted, explored=total_seen)
            if not len(s):
                return {"valid?": False, "op-count": n,
                        "hash_dedup": True, "blocked": True}
        total_seen += len(s)
        if total_seen > max_configs:
            return {"valid?": "unknown", "op-count": n,
                    "reason": "config budget exhausted",
                    "explored": total_seen, "hash_dedup": True,
                    "blocked": True}
        blocks = [pad_block(s[i:], b[i:], h1u[i:], h2u[i:],
                            min(F_max, len(s) - i))
                  for i in range(0, len(s), F_max)]
    return {"valid?": False, "op-count": n, "hash_dedup": True,
            "blocked": True}
