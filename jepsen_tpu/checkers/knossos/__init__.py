"""Knossos-style linearizability checking (SURVEY.md §2.4)."""

from jepsen_tpu.checkers.knossos.wgl import check as check_wgl
from jepsen_tpu.checkers.knossos.competition import analysis

__all__ = ["check_wgl", "analysis"]
