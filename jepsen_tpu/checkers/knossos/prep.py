"""History preparation for linearizability checking.

Equivalent of `knossos/history.clj` (SURVEY.md §2.4): pair invocations
with completions, drop `fail` ops entirely (they never took effect), keep
`info` (crashed) ops as forever-open — they may linearize at any point
after their invocation, or not at all.

Produces a compact entry table: for each checked op i —
  f[i], value[i] (completion value for ok; invocation value for info,
  with reads' results unknown -> None), invoke_pos[i], return_pos[i]
  (2**30 for info = never returns).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List

from jepsen_tpu.history.ops import FAIL, INFO, INVOKE, OK, History

NEVER = 2 ** 30


@dataclasses.dataclass
class LinOp:
    index: int           # dense op id
    f: Any
    value: Any
    invoke_pos: int
    return_pos: int      # NEVER for info ops
    orig_invoke: int     # original history index (reporting)
    orig_complete: int   # -1 if none

    @property
    def is_info(self) -> bool:
        return self.return_pos >= NEVER


def prepare(h: History) -> List[LinOp]:
    ops: List[LinOp] = []
    for op in h.ops:
        if op.type != INVOKE or not op.is_client_op():
            continue
        comp_idx = h.pair_index(op.index)
        comp = h.ops[comp_idx] if comp_idx >= 0 else None
        if comp is not None and comp.type == FAIL:
            continue  # never happened
        if comp is not None and comp.type == OK:
            ops.append(LinOp(len(ops), op.f, comp.value, op.index,
                             comp.index, op.index, comp.index))
        else:
            # crashed / still open: result unknown
            v = op.value
            if op.f in ("read", "dequeue"):
                v = None
            ops.append(LinOp(len(ops), op.f, v, op.index, NEVER,
                             op.index, comp.index if comp else -1))
    return ops
