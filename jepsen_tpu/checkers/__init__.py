"""Checkers: the L3 layer (SURVEY.md §2.1 checker API, §2.3 Elle, §2.4 Knossos)."""

from jepsen_tpu.checkers.api import Checker, check_safe, compose

__all__ = ["Checker", "check_safe", "compose"]
