"""Clock-offset plot.

Equivalent of the reference's `jepsen/src/jepsen/checker/clock.clj`
(SURVEY.md §2.1): plots the per-node clock offsets sampled by the clock
nemesis (ops with ``f == "check-clock-offsets"`` whose value is
``{node: offset_ms}``, see `jepsen_tpu.nemesis.time`) so clock-skew faults
are visible alongside the perf graphs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..history.ops import INVOKE
from .api import Checker, output_path

_NS = 1e9


def offset_series(history) -> Dict[Any, List]:
    """node -> [(t_seconds, offset_ms), ...] from nemesis samples."""
    series: Dict[Any, List] = {}
    for op in history:
        if op.type == INVOKE or op.f != "check-clock-offsets":
            continue
        if not isinstance(op.value, dict):
            continue
        t = op.time / _NS
        for node, off in op.value.items():
            if off is None:
                continue
            series.setdefault(node, []).append((t, float(off)))
    return series


class ClockPlot(Checker):
    """Writes clock.png; always valid (reference `clock-plot`)."""

    def __init__(self, filename: str = "clock.png"):
        self.filename = filename

    def check(self, test, history, opts=None):
        series = offset_series(history)
        if not series:
            return {"valid?": True, "nodes": 0}
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(10, 4))
        for node, pts in sorted(series.items(), key=lambda kv: repr(kv[0])):
            t = [p[0] for p in pts]
            off = [p[1] for p in pts]
            ax.plot(t, off, marker="o", ms=3, lw=1, label=str(node))
        ax.axhline(0, color="#888", lw=0.8)
        ax.set_xlabel("time (s)")
        ax.set_ylabel("clock offset (ms)")
        ax.set_title(f'{test.get("name", "test")} clock offsets')
        ax.legend(fontsize=7)
        path = output_path(test, opts, self.filename)
        fig.savefig(path, dpi=110)
        plt.close(fig)
        return {"valid?": True, "nodes": len(series), "file": path}


def clock_plot(**kw) -> ClockPlot:
    return ClockPlot(**kw)
