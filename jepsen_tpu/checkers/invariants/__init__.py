"""Vectorized consistency-model checkers (the invariants family).

The breadth layer over the elle core (ROADMAP item 5): where
`checkers/elle` judges list-append and rw-register dependency graphs,
this package judges the rest of the Jepsen scenario surface the paper
names — bank transfers (total-balance + snapshot reads), predicate
workloads (long fork / write skew), and session guarantees — each as a
vectorized pass over one shared packed-history core (:mod:`.packed`),
with a host numpy oracle twin and a device path through the existing
device dispatch (`txn_cycles` rank sweep / jnp reductions) behind
`resilience.device_call` guards.

Registry: :data:`MODELS` maps model name -> metadata the flywheel
consumes (workload name, device classification, anomaly vocabulary) so
campaign specs, `DeviceSlots` classification, shrink probe twins, and
the web witness renderers agree on one table.
"""

from __future__ import annotations

from jepsen_tpu.checkers.invariants import bank, packed, predicate, session

__all__ = ["bank", "packed", "predicate", "session", "MODELS"]

#: model name -> flywheel metadata.  `device`: the checker dispatches to
#: jax (DeviceSlots serialization + shrink probe classification);
#: `anomalies`: the vocabulary its witnesses report (web renderers key
#: model-specific evidence off these).
MODELS = {
    "bank": {
        "workload": "bank",
        "device": True,
        "anomalies": ("bank-wrong-total", "bank-negative-balance"),
    },
    "long-fork": {
        "workload": "long-fork",
        "device": True,
        "anomalies": ("long-fork", "G2-item", "G-nonadjacent", "G-single"),
    },
    "write-skew": {
        "workload": "write-skew",
        "device": True,
        "anomalies": ("write-skew", "G2-item", "G-nonadjacent", "G-single"),
    },
    "session": {
        "workload": "session",
        "device": True,
        "anomalies": tuple(
            g + "-violation"
            for g in ("monotonic-reads", "monotonic-writes",
                      "read-your-writes", "writes-follow-reads")),
    },
}
