"""Bank invariants: total balance + snapshot reads, vectorized.

The reference's `jepsen/tests/bank.clj` checker as whole-history array
reductions over the SoA packing (:func:`packed.pack_bank`):

- **total balance**: every committed whole-state read must sum to the
  initial total (under snapshot isolation a read observes one atomic
  snapshot; transfers conserve money, so any other sum is read skew);
- **negative balances**: flagged unless the workload allows them
  (`negative-balances-ok`).

Both checks are one pass over the ``[n_reads, n_accounts]`` balance
matrix: row sums, sign tests, boolean reductions.  The **device path**
runs that pass as jnp reductions dispatched through
`resilience.device_call` (site ``invariants.bank``) with retry /
deadline / fault-plan semantics; a persistent device failure degrades
to the **host numpy oracle twin** (`host_verdict` — the exact same
arithmetic, the reference the device path is differentially pinned
against) with ``"degraded": "host-fallback"`` stamped, the same
contract the elle checkers follow.

Result shape matches the elle family (``valid?`` / ``anomaly-types`` /
``anomalies``) and keeps the legacy bank keys (``bad-reads`` /
``bad-read-count`` / ``read-count``) the workload tests and perf plots
already consume.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from jepsen_tpu import telemetry
from jepsen_tpu.checkers.invariants import packed as packed_mod
from jepsen_tpu.checkers.invariants.packed import PackedBank

WRONG_TOTAL = "bank-wrong-total"
NEGATIVE = "bank-negative-balance"

SITE = "invariants.bank"


def resolve_total(test: Optional[dict], pb: PackedBank,
                  total: Optional[int] = None) -> Optional[int]:
    """The expected conserved total: explicit arg > test map
    ``total-amount`` > sum of the test's initial ``accounts`` > the
    modal read sum (so a single anomalous read can't become the
    baseline)."""
    if total is not None:
        return int(total)
    t = (test or {}).get("total-amount")
    if t is not None:
        return int(t)
    accounts = (test or {}).get("accounts")
    if isinstance(accounts, dict) and accounts:
        return int(sum(accounts.values()))
    if pb.n_reads:
        sums = pb.balances.sum(axis=1)
        vals, counts = np.unique(sums, return_counts=True)
        return int(vals[np.argmax(counts)])
    return None


def _reduce_host(balances: np.ndarray, total: int, negative_ok: bool):
    """The one reduction both paths implement: (row sums, wrong-total
    mask, negative mask)."""
    sums = balances.sum(axis=1)
    wrong = sums != total
    neg = (balances < 0).any(axis=1) if not negative_ok \
        else np.zeros(len(balances), bool)
    return sums, wrong, neg


def _reduce_device(balances: np.ndarray, total: int, negative_ok: bool):
    import jax.numpy as jnp

    from jepsen_tpu.parallel.slots import place_sharded

    # sharded-by-default: rows (reads) split over the active mesh's
    # "batch" axis — the row sums/sign tests partition embarrassingly
    b = place_sharded(balances)
    sums = b.sum(axis=1)
    wrong = sums != total
    neg = (b < 0).any(axis=1) if not negative_ok \
        else jnp.zeros(b.shape[0], bool)
    return (np.asarray(sums), np.asarray(wrong), np.asarray(neg))


def host_verdict(pb: PackedBank, total: int, negative_ok: bool,
                 max_reported: int = 8) -> Dict[str, Any]:
    """The exact host oracle twin — numpy only, no jax import."""
    sums, wrong, neg = _reduce_host(pb.balances, total, negative_ok)
    return _render(pb, total, sums, wrong, neg, max_reported)


def _render(pb: PackedBank, total: int, sums, wrong, neg,
            max_reported: int) -> Dict[str, Any]:
    found: Dict[str, list] = {}
    bad = wrong | neg
    bad_reads = []
    for i in np.nonzero(bad)[0][:max_reported]:
        entry = {
            "op-index": int(pb.read_op_index[i]),
            "process": int(pb.read_process[i]),
            "total": int(sums[i]),
            "expected-total": int(total),
            "negative": [pb.accounts[j]
                         for j in np.nonzero(pb.balances[i] < 0)[0]],
        }
        bad_reads.append(entry)
        if wrong[i]:
            found.setdefault(WRONG_TOTAL, []).append(entry)
        if neg[i]:
            found.setdefault(NEGATIVE, []).append(entry)
    return {
        "valid?": not bool(bad.any()),
        "anomaly-types": sorted(found),
        "anomalies": found,
        "read-count": pb.n_reads,
        "bad-read-count": int(bad.sum()),
        "bad-reads": bad_reads,
        "expected-total": int(total),
    }


def check(history, test: Optional[dict] = None, *,
          negative_balances_ok: bool = False,
          total: Optional[int] = None,
          use_device: bool = True,
          max_reported: int = 8,
          deadline=None, plan=None, policy=None) -> Dict[str, Any]:
    """Check a bank history.  Accepts a History / op list / PackedBank.

    Device path first (guarded, retried, deadline-polled); persistent
    failure degrades to the host twin with the standard stamp.
    ``use_device=False`` IS the host twin — the two must agree
    verdict-for-verdict (pinned by tests/test_invariants.py)."""
    from jepsen_tpu import resilience

    ph = telemetry.phases()
    pb = history if isinstance(history, PackedBank) else None
    if pb is None:
        from jepsen_tpu.history.ir import HistoryIR

        accounts = ((test or {}).get("accounts") or {}).keys() or None
        ph.start("invariants.pack", device=False)
        pb = (history.bank(accounts)
              if isinstance(history, HistoryIR)
              else packed_mod.pack_bank(history, accounts=accounts))
    t = resolve_total(test, pb, total)
    if not pb.n_reads or t is None:
        ph.end()
        return {"valid?": "unknown", "read-count": pb.n_reads,
                "anomaly-types": [], "anomalies": {}, "bad-reads": []}
    if deadline is not None:
        deadline.check(SITE)
    if not use_device:
        ph.start("invariants.check", device=False, reads=pb.n_reads)
        res = host_verdict(pb, t, negative_balances_ok, max_reported)
        ph.end()
        return res
    ph.start("invariants.check", device=True, reads=pb.n_reads)
    try:
        (sums, wrong, neg), degraded = resilience.with_fallback(
            SITE,
            lambda: _reduce_device(pb.balances, t, negative_balances_ok),
            lambda: _reduce_host(pb.balances, t, negative_balances_ok),
            deadline=deadline, plan=plan, policy=policy, test=test)
    except resilience.DeadlineExceeded:
        ph.end()
        return resilience.deadline_result(checker="bank")
    res = _render(pb, t, sums, wrong, neg, max_reported)
    if degraded:
        res["degraded"] = degraded
    ph.end()
    return res
