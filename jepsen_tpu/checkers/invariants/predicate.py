"""Predicate-workload checkers: long fork and write skew.

Long-fork and write-skew histories are rw-register shaped (single
writes per key, whole-group predicate reads), so this checker rides the
shared packed core (:func:`packed.pack_rw` + :func:`packed.infer_rw`)
and judges two ways, cheapest first:

1. **Vectorized witness passes** over the packed arrays:
   - *long fork*: committed group reads bucketed by key set; for each
     key pair inside a bucket, boolean column reductions find a read
     observing ``(w1, ¬w2)`` against a read observing ``(¬w1, w2)`` —
     two reads ordering two writes oppositely.  The observed/absent
     flags come straight from the packed mop columns; the pass is
     O(group² · reads) array ops, no Python pair loop.
   - *write skew*: mutual anti-dependency pairs — txns A, B with rw
     edges both ways (each read a version the other overwrote /
     installed over) — found by intersecting the encoded rw edge set
     with its transpose.

2. **Cycle confirmation** through the elle graph machinery: the same
   edge list (ww / wr / rw including the predicate "absence"
   anti-dependencies) swept for ``G-single`` / ``G2-item`` /
   ``G-nonadjacent`` cycles by `txn_cycles.cycle_anomalies` — the
   device rank-sweep kernel with exact host Tarjan fallback — each
   witness edge explained by the rw Explainer (key, values, the "why"
   sentence).

The vectorized pass runs as a guarded device seam (site
``invariants.predicate`` via `resilience.with_fallback`): jnp
reductions on the device path, the identical numpy on the host oracle
twin (``use_device=False``), pinned equal verdict-for-verdict.
"""

from __future__ import annotations

from itertools import combinations
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import telemetry
from jepsen_tpu.checkers.elle import consistency
from jepsen_tpu.checkers.elle.graph import REL_RW
from jepsen_tpu.checkers.elle.txn_cycles import cycle_anomalies
from jepsen_tpu.checkers.invariants import packed as packed_mod
from jepsen_tpu.checkers.invariants.packed import RwInference
from jepsen_tpu.history.soa import MOP_READ, TXN_OK, PackedTxns

LONG_FORK = "long-fork"
WRITE_SKEW = "write-skew"

SITE = "invariants.predicate"

#: cycle families predicate anomalies surface as (write skew = a pure
#: anti-dependency cycle -> G2-item/G-nonadjacent; long fork = two
#: reads + two writers -> G-nonadjacent)
CYCLE_WANT = ("G-single", "G2-item", "G-nonadjacent")


# ---------------------------------------------------------------------------
# vectorized witness passes
# ---------------------------------------------------------------------------


def _group_reads(p: PackedTxns) -> Tuple[np.ndarray, np.ndarray,
                                         np.ndarray]:
    """Committed pure-read txns as (txn ids, [R, K] observed flags,
    [R, K] value ids).  K = n_keys; a txn row only covers its own key
    set (mask via per-txn key membership)."""
    T, M = p.n_txns, p.n_mops
    kind = p.mop_kind.astype(np.int64)
    mtxn = p.mop_txn.astype(np.int64)
    ok = p.txn_type == TXN_OK
    # pure-read committed txns with known results
    is_read = kind == MOP_READ
    known = p.mop_rd_len >= 0
    has_write = np.zeros(T, bool)
    has_unknown = np.zeros(T, bool)
    np.logical_or.at(has_write, mtxn, ~is_read)
    np.logical_or.at(has_unknown, mtxn, is_read & ~known)
    n_mops_txn = np.bincount(mtxn, minlength=T)
    pure = ok & ~has_write & ~has_unknown & (n_mops_txn > 0)
    sel = pure[mtxn] & is_read
    rt = np.unique(mtxn[sel])
    if not len(rt):
        z = np.zeros((0, p.n_keys), dtype=np.int64)
        return rt, z.astype(bool), z
    row = np.full(T, -1, np.int64)
    row[rt] = np.arange(len(rt))
    covered = np.zeros((len(rt), p.n_keys), bool)
    vals = np.full((len(rt), p.n_keys), -1, np.int64)
    mk = p.mop_key.astype(np.int64)[sel]
    mv = p.mop_val.astype(np.int64)[sel]
    rr = row[mtxn[sel]]
    covered[rr, mk] = True
    vals[rr, mk] = mv
    return rt, covered, vals


def _fork_scan(covered: np.ndarray, observed: np.ndarray):
    """The reducible half of the long-fork pass (runs on either
    backend xp = numpy | jax.numpy): for every key pair (i, j) over
    reads covering both, are there reads with (obs i, ¬obs j) AND
    reads with (¬obs i, obs j)?  Returns the [K, K] boolean fork
    matrix plus per-(pair-direction) first witness rows."""

    def run(xp):
        cov = xp.asarray(covered)
        obs = xp.asarray(observed)
        # reads covering key i with i observed / absent: [R, K]
        o = cov & obs
        a = cov & ~obs
        # pair (i, j): exists read covering both with i obs, j absent
        both = (cov.astype(xp.int32).T @ cov.astype(xp.int32))
        oa = (o.astype(xp.int32).T @ a.astype(xp.int32))
        # fork iff oa[i, j] > 0 and oa[j, i] > 0 over co-covered reads
        fork = (oa > 0) & (oa.T > 0) & (both > 0)
        return fork

    return run


def long_forks(p: PackedTxns, *, use_device: bool = True,
               max_reported: int = 8, deadline=None, plan=None,
               policy=None, test=None
               ) -> Tuple[List[dict], int, Optional[str]]:
    """Vectorized long-fork witnesses.  Returns (witness list,
    group-read count, degraded flag)."""
    from jepsen_tpu import resilience

    rt, covered, vals = _group_reads(p)
    if not len(rt):
        return [], 0, None
    observed = vals >= 0
    run = _fork_scan(covered, observed)
    degraded = None
    if use_device:
        def dev():
            import jax.numpy as jnp

            return np.asarray(run(jnp))

        fork, degraded = resilience.with_fallback(
            SITE, dev, lambda: run(np), deadline=deadline, plan=plan,
            policy=policy, test=test)
        fork = np.asarray(fork)
    else:
        fork = run(np)
    out: List[dict] = []
    ki, kj = np.nonzero(np.triu(fork, 1))
    orig = p.txn_orig_index
    o = covered & observed
    a = covered & ~observed
    for i, j in zip(ki.tolist(), kj.tolist()):
        if len(out) >= max_reported:
            break
        # first witness pair: a read with (i obs, j absent) and one
        # with (i absent, j obs)
        r1 = np.nonzero(o[:, i] & a[:, j])[0]
        r2 = np.nonzero(a[:, i] & o[:, j])[0]
        if not (len(r1) and len(r2)):
            continue
        out.append({
            "keys": [p.key_names[i], p.key_names[j]],
            "reads": [int(orig[rt[r1[0]]]), int(orig[rt[r2[0]]])],
            "why": (f"read T{int(orig[rt[r1[0]]])} observed key "
                    f"{p.key_names[i]!r} but not {p.key_names[j]!r}; "
                    f"read T{int(orig[rt[r2[0]]])} observed "
                    f"{p.key_names[j]!r} but not {p.key_names[i]!r} — "
                    "the two reads order the writes oppositely"),
        })
    return out, len(rt), degraded


def write_skews(inf: RwInference, max_reported: int = 8) -> List[dict]:
    """Mutual anti-dependency pairs: txns (a, b) with rw edges both
    ways.  Encoded-intersection over the rw projection — one sorted
    pass, no pair loop."""
    e = inf.edges
    m = e.rel == REL_RW
    src = e.src[m].astype(np.int64)
    dst = e.dst[m].astype(np.int64)
    if not len(src):
        return []
    n = int(inf.n_nodes)
    fwd = np.unique(src * n + dst)
    rev = np.unique(dst * n + src)
    both = np.intersect1d(fwd, rev, assume_unique=True)
    out: List[dict] = []
    orig = inf.p.txn_orig_index
    seen = set()
    for code in both.tolist():
        a, b = divmod(code, n)
        if a >= b or (a, b) in seen:
            continue  # report each unordered pair once
        seen.add((a, b))
        if len(out) >= max_reported:
            break
        out.append({
            "txns": [int(orig[a]), int(orig[b])],
            "why": (f"T{int(orig[a])} and T{int(orig[b])} each read a "
                    "version the other overwrote (mutual "
                    "anti-dependency): write skew"),
        })
    return out


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------


def check(history, consistency_models: Sequence[str] = (
              "snapshot-isolation",),
          anomalies: Sequence[str] = (),
          use_device: bool = True, max_reported: int = 8,
          deadline=None, plan=None, policy=None,
          test: Optional[dict] = None) -> Dict[str, Any]:
    """Check a predicate (long-fork / write-skew) history.

    Accepts a History / op list / PackedTxns (rw-register packing).
    ``use_device=False`` is the host oracle twin: the same passes on
    numpy and host Tarjan cycle search."""
    from jepsen_tpu import resilience
    from jepsen_tpu.checkers.elle.explain import rw_explainer

    from jepsen_tpu.history.ir import HistoryIR

    ph = telemetry.phases()
    ir = history if isinstance(history, HistoryIR) else None
    if isinstance(history, PackedTxns):
        p = history
    else:
        ph.start("invariants.pack", device=False)
        p = ir.packed("rw-register") if ir is not None \
            else packed_mod.pack_rw(history)
    if p.n_txns == 0 or not (p.txn_type == TXN_OK).any():
        ph.end()
        return {"valid?": "unknown", "anomaly-types": [], "anomalies": {},
                "not": [], "also-not": [], "read-count": 0}

    found: Dict[str, List[dict]] = {}
    degraded = None
    n_reads = 0
    try:
        ph.start("invariants.long-fork", device=use_device, txns=p.n_txns)
        forks, n_reads, degraded = long_forks(
            p, use_device=use_device, max_reported=max_reported,
            deadline=deadline, plan=plan, policy=policy, test=test)
        if forks:
            found[LONG_FORK] = forks

        ph.start("invariants.infer", device=False)
        # the IR shares ONE RwInference between the predicate and
        # session checkers of a composed check (docs/IR.md)
        inf = ir.rw_inference() if ir is not None \
            else packed_mod.infer_rw(p)
        skews = write_skews(inf, max_reported=max_reported)
        if skews:
            found[WRITE_SKEW] = skews

        # cycle confirmation over the same edges: device rank sweep
        # (txn_cycles) with host Tarjan fallback, per-edge evidence
        want = set(consistency.anomalies_for_models(
            [consistency.canonical(m) for m in consistency_models]))
        want |= set(anomalies) | set(CYCLE_WANT)
        if deadline is not None:
            deadline.check(SITE)
        ph.start("invariants.cycle-sweep", device=use_device)
        expl = rw_explainer(p, inf.writer, inf.v_src, inf.v_dst,
                            ext_read_txn=inf.ext_read_txn,
                            ext_read_val=inf.ext_read_val)
        found.update(cycle_anomalies(
            inf.edges, inf.n_nodes, inf.rank, want,
            use_device=use_device, max_reported=max_reported,
            explainer=expl, n_txns=p.n_txns,
            orig_index=p.txn_orig_index))
    except resilience.DeadlineExceeded:
        ph.end()
        return resilience.deadline_result(
            checker="predicate",
            **{"anomaly-types": sorted(found), "anomalies": found})
    ph.end()

    anomaly_types = sorted(found)
    boundary = consistency.friendly_boundary(anomaly_types)
    bad = set(boundary["not"]) | set(boundary["also-not"])
    requested_bad = bad & {consistency.canonical(m)
                           for m in consistency_models}
    # the predicate tokens themselves invalidate regardless of the
    # lattice: a long fork / write skew is what this workload exists
    # to find
    invalid = bool(requested_bad) or LONG_FORK in found \
        or WRITE_SKEW in found
    res: Dict[str, Any] = {
        "valid?": not invalid,
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
        "read-count": n_reads,
        "fork-count": len(found.get(LONG_FORK, ())),
        "skew-count": len(found.get(WRITE_SKEW, ())),
    }
    if degraded:
        res["degraded"] = degraded
    return res


# ---------------------------------------------------------------------------
# pairwise reference oracle (differential anchor for the vectorized pass)
# ---------------------------------------------------------------------------


def oracle_long_forks(history) -> List[dict]:
    """The quadratic pairwise long-fork scan (the original
    `long_fork.clj` formulation) — the semantic anchor the vectorized
    pass is differentially tested against.  Returns [{keys, reads}]."""
    from jepsen_tpu.history.ops import OK

    reads = []
    for op in history:
        if op.type != OK or op.f != "txn":
            continue
        mops = op.value or []
        if mops and all(m[0] == "r" for m in mops):
            reads.append(op)
    forks = []
    obs = [{m[1]: m[2] for m in op.value} for op in reads]
    buckets: Dict[frozenset, List[int]] = {}
    for i, o in enumerate(obs):
        buckets.setdefault(frozenset(o), []).append(i)
    for idxs in buckets.values():
        for ia, ib in combinations(idxs, 2):
            shared = [k for k in obs[ia] if k in obs[ib]]
            for k1, k2 in combinations(shared, 2):
                a1, a2 = obs[ia][k1], obs[ia][k2]
                b1, b2 = obs[ib][k1], obs[ib][k2]
                if a1 is not None and a2 is None and b1 is None \
                        and b2 is not None:
                    forks.append({"keys": [k1, k2],
                                  "reads": [reads[ia].index,
                                            reads[ib].index]})
                elif a1 is None and a2 is not None and b1 is not None \
                        and b2 is None:
                    forks.append({"keys": [k2, k1],
                                  "reads": [reads[ia].index,
                                            reads[ib].index]})
    return forks
