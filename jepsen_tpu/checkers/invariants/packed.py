"""The shared packed-history core for the invariants checker family.

Two packings, one idiom (SoA arrays the device can consume directly,
like `history/soa.py` does for the elle pipelines):

- :func:`pack_bank` flattens a bank history (transfer / whole-state
  read ops) into dense arrays: a ``[n_reads, n_accounts]`` balance
  matrix plus transfer columns.  The bank checker's invariants are then
  whole-history array reductions over these.

- :func:`pack_rw` + :func:`infer_rw` pack a transactional rw-register
  shaped history (the long-fork / write-skew / session workloads) via
  the elle `TxnPacker` and derive the per-key version orders and the
  txn dependency edges (ww / wr / rw — including the predicate
  "absence" anti-dependencies a read of the unwritten initial state
  creates) as one vectorized pass.  `RwInference` is what
  `predicate.py` sweeps for cycles and `session.py` ranks sessions
  against — one derivation, shared.

Rel codes and the :class:`~jepsen_tpu.checkers.elle.graph.EdgeList`
container are the elle core's own, so the device rank-sweep kernel and
the host Tarjan path apply unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from jepsen_tpu.checkers.elle.graph import (
    REL_RW,
    REL_WR,
    REL_WW,
    EdgeList,
    process_edges,
    realtime_edges_subset,
)
from jepsen_tpu.history.ops import FAIL, INFO, INVOKE, OK, History
from jepsen_tpu.history.soa import (
    MOP_APPEND,
    MOP_READ,
    TXN_FAIL,
    TXN_INFO,
    TXN_OK,
    PackedTxns,
    pack_txns,
)

__all__ = ["PackedBank", "pack_bank", "pack_rw", "RwInference", "infer_rw"]


# ---------------------------------------------------------------------------
# bank packing
# ---------------------------------------------------------------------------

_TXN_TYPE = {OK: TXN_OK, FAIL: TXN_FAIL, INFO: TXN_INFO}


@dataclasses.dataclass
class PackedBank:
    """A bank history flattened to structure-of-arrays."""

    accounts: List[Any]          # sorted account ids (column order)
    # committed whole-state reads
    balances: np.ndarray         # i64 [R, A]
    read_op_index: np.ndarray    # i64 [R] completion op index
    read_process: np.ndarray     # i64 [R]
    # transfers (all completions, type-tagged for attribution)
    tr_type: np.ndarray          # i8 [N] TXN_OK / TXN_FAIL / TXN_INFO
    tr_from: np.ndarray          # i64 [N] account column index
    tr_to: np.ndarray            # i64 [N]
    tr_amount: np.ndarray        # i64 [N]
    tr_op_index: np.ndarray      # i64 [N]

    @property
    def n_reads(self) -> int:
        return len(self.read_op_index)

    @property
    def n_accounts(self) -> int:
        return len(self.accounts)


def pack_bank(history, accounts: Optional[Any] = None) -> PackedBank:
    """Flatten a bank history's committed reads + transfers to SoA.

    `accounts` (optional iterable) pre-pins the column order so the
    initial-balance vector a test map carries lines up; accounts only
    seen in reads/transfers are appended after."""
    h = history if isinstance(history, History) else History(list(history))
    order: List[Any] = []
    for a in sorted(accounts, key=repr) if accounts else ():
        if a not in order:
            order.append(a)
    cols: Dict[Any, int] = {a: i for i, a in enumerate(order)}

    def col(a) -> int:
        i = cols.get(a)
        if i is None:
            i = cols[a] = len(order)
            order.append(a)
        return i

    reads: List[Tuple[dict, int, int]] = []
    trs: List[Tuple[int, int, int, int, int]] = []
    for op in h.ops:
        if op.type == INVOKE or not op.is_client_op():
            continue
        if op.f == "read" and op.type == OK and isinstance(op.value, dict):
            reads.append((op.value, op.index, int(op.process)))
            for a in op.value:
                col(a)
        elif op.f == "transfer" and op.type in _TXN_TYPE:
            v = op.value or {}
            if not isinstance(v, dict):
                continue
            trs.append((_TXN_TYPE[op.type], col(v.get("from")),
                        col(v.get("to")), int(v.get("amount") or 0),
                        op.index))
    A = len(order)
    bal = np.zeros((len(reads), A), dtype=np.int64)
    for i, (v, _, _) in enumerate(reads):
        for a, x in v.items():
            bal[i, cols[a]] = int(x)
    return PackedBank(
        accounts=order,
        balances=bal,
        read_op_index=np.asarray([i for _, i, _ in reads], np.int64),
        read_process=np.asarray([p for _, _, p in reads], np.int64),
        tr_type=np.asarray([t for t, *_ in trs], np.int8),
        tr_from=np.asarray([f for _, f, *_ in trs], np.int64),
        tr_to=np.asarray([t for _, _, t, *_ in trs], np.int64),
        tr_amount=np.asarray([a for *_, a, _ in trs], np.int64),
        tr_op_index=np.asarray([i for *_, i in trs], np.int64),
    )


# ---------------------------------------------------------------------------
# rw packing + shared inference
# ---------------------------------------------------------------------------


def pack_rw(history) -> PackedTxns:
    """Pack a transactional (``txn`` of ``[w k v] / [r k v]`` mops)
    history with the elle rw-register packer — the packed form every
    invariants checker over txn histories consumes."""
    if isinstance(history, PackedTxns):
        return history
    return pack_txns(history, "rw-register")


@dataclasses.dataclass
class RwInference:
    """Everything the predicate / session checkers derive once from a
    packed rw history.  Value-id space: ids < V are written versions;
    id ``V + k`` encodes key k's unwritten initial state (the version a
    predicate read of "absent" observes)."""

    p: PackedTxns
    writer: np.ndarray           # i64 [V] value id -> writing txn (-1)
    v_src: np.ndarray            # i64 version edges u -> v (init-encoded)
    v_dst: np.ndarray
    ext_read_txn: np.ndarray     # i64 external reads: reading txn
    ext_read_val: np.ndarray     # i64 observed value id (init-encoded)
    ext_read_mop: np.ndarray     # i64 mop row of the read
    edges: EdgeList              # ww/wr/rw + process + realtime(barriers)
    n_nodes: int                 # txns + barrier nodes
    rank: np.ndarray             # per-node completion rank (device sweep)
    # per-key chain ranks: rank_of[val_id] = position in its key's
    # version chain (init = 0), or -1 when the key's version graph is
    # not a simple chain (session checks then fall back to the walker)
    chain_rank: np.ndarray       # i64 [V + n_keys]
    chain_ok: np.ndarray         # bool [n_keys]


def infer_rw(p: PackedTxns) -> RwInference:
    """One vectorized pass: version orders, dependency edges, chains.

    Version-order sources are the rw-register defaults (initial state +
    txn-internal read-then-write / write-after-write), which are exact
    for the single-writer-per-key predicate workloads and for the
    session workloads' register traffic.  Mirrors the inference
    `elle/rw_register.check` runs inline; kept as a standalone pass so
    every invariants checker shares the arrays instead of re-deriving.
    """
    T, M, V = p.n_txns, p.n_mops, p.n_vals
    nk = max(p.n_keys, 1)

    ttype = p.txn_type.astype(np.int32)
    ok = ttype == TXN_OK
    graph_txn = ok | (ttype == TXN_INFO)

    kind = p.mop_kind.astype(np.int32)
    mtxn = p.mop_txn.astype(np.int64)
    mkey = p.mop_key.astype(np.int64)
    mval = p.mop_val.astype(np.int64)
    known = np.where(kind == MOP_READ, p.mop_rd_len >= 0, True)

    # writers (priority: ok > info > fail, like the rw checker)
    writer = np.full(V, -1, np.int64)
    wsel = np.nonzero(kind == MOP_APPEND)[0]
    if len(wsel):
        wvals = mval[wsel]
        prio = np.select([ok[mtxn[wsel]], ttype[mtxn[wsel]] == TXN_INFO],
                         [0, 1], 2)
        order = np.lexsort((wsel, prio, wvals))
        sv = wvals[order]
        first = np.concatenate([[True], sv[1:] != sv[:-1]])
        writer[sv[first]] = mtxn[wsel][order][first]

    # per-(txn, key) runs in mop order: the txn-local version state
    run_order = np.lexsort((np.arange(M), mkey, mtxn))
    rt, rk = mtxn[run_order], mkey[run_order]
    rkind = kind[run_order]
    rval = mval[run_order]
    rknown = known[run_order]
    run_start = np.concatenate([[True], (rt[1:] != rt[:-1]) |
                                (rk[1:] != rk[:-1])]) \
        if M else np.zeros(0, bool)
    seg_id = np.cumsum(run_start) - 1 if M else np.zeros(0, np.int64)

    from jepsen_tpu.checkers.elle.rw_register import _seg_exclusive_max

    defines = (rkind == MOP_APPEND) | ((rkind == MOP_READ) & rknown)
    def_val = np.where(rkind == MOP_APPEND, rval,
                       np.where(rval >= 0, rval, V + rk))
    def_pos = np.where(defines, np.arange(M), -1)
    prev_def = _seg_exclusive_max(def_pos, seg_id)
    NO_PREV = -3
    cur_before = np.where(prev_def >= 0, def_val[np.maximum(prev_def, 0)],
                          NO_PREV)

    # external reads: first defining mop of the run is this read
    r_is_read = (rkind == MOP_READ) & rknown & ok[rt]
    external_read = r_is_read & (cur_before == NO_PREV)
    ext_idx = np.nonzero(external_read)[0]
    ext_read_txn = rt[ext_idx]
    ext_read_val = def_val[ext_idx]
    ext_read_mop = run_order[ext_idx] if M else np.zeros(0, np.int64)

    # version edges: write with known predecessor u -> v (blind: init)
    w_idx = np.nonzero((rkind == MOP_APPEND) & graph_txn[rt])[0]
    u = np.where(cur_before[w_idx] >= 0, cur_before[w_idx], V + rk[w_idx])
    v_src = u.astype(np.int64)
    v_dst = rval[w_idx].astype(np.int64)

    # ---- txn dependency edges -------------------------------------------
    es: List[np.ndarray] = []
    ed: List[np.ndarray] = []
    er: List[np.ndarray] = []

    def add(src, dst, rel):
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        m = (src >= 0) & (dst >= 0) & (src != dst)
        m &= graph_txn[np.maximum(src, 0)] & graph_txn[np.maximum(dst, 0)]
        es.append(src[m].astype(np.int32))
        ed.append(dst[m].astype(np.int32))
        er.append(np.full(int(m.sum()), rel, np.int8))

    # wr: external reader of a real version <- its writer
    real = ext_read_val < V
    wr_src = (writer[ext_read_val[real]] if V
              else np.zeros(0, np.int64))
    add(wr_src, ext_read_txn[real], REL_WR)
    # ww: writer(u) -> writer(v) over real-u version edges
    real_u = v_src < V
    ww_src = np.where(real_u, writer[np.minimum(v_src, max(V - 1, 0))], -1) \
        if V else np.full(len(v_src), -1, np.int64)
    ww_dst = np.where(v_dst < V, writer[np.minimum(v_dst, max(V - 1, 0))],
                      -1) if V else np.full(len(v_dst), -1, np.int64)
    add(ww_src, ww_dst, REL_WW)
    # rw: external readers of u -> writer(v) per version edge u -> v —
    # the predicate anti-dependency: a read observing the INIT state of
    # key k (absence) has u == V + k, so the edge lands on the writer
    # of k's first installed version
    if len(ext_idx) and len(v_src):
        r_ord = np.argsort(ext_read_val, kind="stable")
        rv_sorted = ext_read_val[r_ord]
        rt_sorted = ext_read_txn[r_ord]
        lo = np.searchsorted(rv_sorted, v_src, side="left")
        hi = np.searchsorted(rv_sorted, v_src, side="right")
        cnt = hi - lo
        tot = int(cnt.sum())
        if tot:
            eidx = np.repeat(np.arange(len(v_src)), cnt)
            off = np.arange(tot) - np.repeat(np.cumsum(cnt) - cnt, cnt)
            readers = rt_sorted[lo[eidx] + off]
            wdst = np.where(v_dst[eidx] < V,
                            writer[np.minimum(v_dst[eidx], max(V - 1, 0))],
                            -1)
            add(readers, wdst, REL_RW)

    dep = EdgeList()
    dep.src = np.concatenate(es) if es else np.zeros(0, np.int32)
    dep.dst = np.concatenate(ed) if ed else np.zeros(0, np.int32)
    dep.rel = np.concatenate(er) if er else np.zeros(0, np.int8)

    proc = p.txn_process.astype(np.int64)
    inv = p.txn_invoke_pos.astype(np.int64)
    comp = p.txn_complete_pos.astype(np.int64)
    pe = process_edges(np.where(graph_txn, proc, -10 ** 9 - np.arange(T)),
                      inv)
    ok_ids = np.nonzero(ok)[0]
    rte, n_b, b_ranks = realtime_edges_subset(inv, comp, ok_ids,
                                              graph_txn, T)
    edges = EdgeList.concat([dep, pe, rte]).dedup()
    rank = np.concatenate([2 * comp, b_ranks]).astype(np.int32)

    chain_rank, chain_ok = _chain_ranks(V, nk, v_src, v_dst)
    return RwInference(
        p=p, writer=writer, v_src=v_src, v_dst=v_dst,
        ext_read_txn=ext_read_txn, ext_read_val=ext_read_val,
        ext_read_mop=ext_read_mop,
        edges=edges, n_nodes=T + n_b, rank=rank,
        chain_rank=chain_rank, chain_ok=chain_ok)


def _chain_ranks(V: int, nk: int,
                 v_src: np.ndarray, v_dst: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-key version-chain ranks from the direct version edges.

    A key whose version graph is a simple chain rooted at init (every
    node <= 1 successor and <= 1 predecessor, no cycle) gets exact
    ranks: init = 0, then 1, 2, ...  Branched / cyclic keys are marked
    not-ok (`chain_ok[k] = False`) and their versions rank -1 — the
    session checker falls back to the exact DAG walker there, so
    branching can never manufacture a false violation."""
    rank = np.full(V + nk, -1, np.int64)
    ok = np.ones(nk, bool)
    if not len(v_src):
        rank[V:] = 0
        return rank, ok
    succ: Dict[int, List[int]] = {}
    pred_count = np.zeros(V + nk, np.int64)
    for u, v in zip(v_src.tolist(), v_dst.tolist()):
        succ.setdefault(u, []).append(v)
        pred_count[v] += 1
    for k in range(nk):
        root = V + k
        rank[root] = 0
        seen = {root}
        node, r = root, 0
        good = True
        while True:
            nxt = sorted(set(succ.get(node, ())))
            if not nxt:
                break
            if len(nxt) > 1 or nxt[0] in seen or pred_count[nxt[0]] > 1:
                good = False
                break
            node = nxt[0]
            seen.add(node)
            r += 1
            rank[node] = r
        # versions of this key not reached by the chain (disconnected
        # writes) also break chain-exactness
        if good:
            ok[k] = True
        else:
            ok[k] = False
            for n in seen - {root}:
                rank[n] = -1
    # any version never reached from its key's init root stays -1; mark
    # its key not-ok so rank comparisons there are never trusted
    unreached = np.nonzero(rank[:V] < 0)[0]
    if len(unreached):
        # key of a version = key of its init ancestor; derive from edges
        # by walking v_src/v_dst once (init-encoded sources carry keys)
        vk = _version_keys(V, nk, v_src, v_dst)
        for v in unreached.tolist():
            k = int(vk[v])
            if 0 <= k < nk:
                ok[k] = False
    return rank, ok


def _version_keys(V: int, nk: int, v_src: np.ndarray,
                  v_dst: np.ndarray) -> np.ndarray:
    """value id -> key id, propagated from init-encoded edge sources."""
    vk = np.full(V, -1, np.int64)
    init_src = v_src >= V
    vk[v_dst[init_src & (v_dst < V)]] = v_src[init_src & (v_dst < V)] - V
    # propagate along real->real edges until fixpoint (chains are short)
    for _ in range(max(1, nk)):
        m = (v_src < V) & (v_dst < V)
        src_k = np.where(v_src < V, vk[np.minimum(v_src, max(V - 1, 0))],
                         -1)
        upd = m & (src_k >= 0)
        if not upd.any():
            break
        before = vk.copy()
        vk[v_dst[upd]] = src_k[upd]
        if np.array_equal(before, vk):
            break
    return vk
