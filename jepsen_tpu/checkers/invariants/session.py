"""Session guarantees as vectorized per-process passes.

Monotonic reads / monotonic writes / read-your-writes /
writes-follow-reads over rw-register-shaped histories, checked against
the per-key version orders the shared packed core derives
(:func:`packed.infer_rw`): every committed external read / write
becomes one event row ``(process, key, seq, is_write, rank)`` where
``rank`` is the version's position in its key's chain, and each
guarantee is a segmented comparison against the LAST prior event of
the relevant type in the same ``(process, key)`` segment —

    monotonic-reads      read rank  < last prior read rank
    read-your-writes     read rank  < last prior write rank
    monotonic-writes     write rank < last prior write rank
    writes-follow-reads  write rank < last prior read rank

"last prior X" is one encoded cumulative max (position-dominant
encoding, the `_seg_inclusive_max` trick), so the whole pass is a
handful of array ops: sort, cummax, compare.  The **device path** runs
the cummax + comparisons on jnp (``jax.lax.cummax``) behind
`resilience.device_call` (site ``invariants.session``); the **host
oracle twin** is the identical numpy, pinned equal verdict-for-verdict.

Exactness first: rank comparison is only definite on keys whose
version graph is a simple chain (`RwInference.chain_ok`).  Histories
with branched/cyclic keys — or cross-key read-then-write dependencies,
which need the obligation walker — fall back to the exact DAG walker
(`checkers.elle.sessions.check`), the same degradation rule the elle
family uses (an oracle that cannot look must say so, never silently
validate)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import telemetry
from jepsen_tpu.checkers.elle import consistency
from jepsen_tpu.checkers.elle.sessions import GUARANTEES
from jepsen_tpu.checkers.invariants import packed as packed_mod
from jepsen_tpu.checkers.invariants.packed import RwInference
from jepsen_tpu.history.soa import MOP_APPEND, TXN_OK, PackedTxns

SITE = "invariants.session"

_SUFFIX = "-violation"


def _session_events(p: PackedTxns, inf: RwInference):
    """Flatten committed reads/writes to (proc, key, seq, is_write,
    rank) rows sorted session-major.  Returns None when any event's
    rank is unknown or its key is not chain-shaped — the walker owns
    those histories."""
    ok = p.txn_type == TXN_OK
    V = p.n_vals
    # writes: committed append mops, in mop order
    kind = p.mop_kind.astype(np.int64)
    mtxn = p.mop_txn.astype(np.int64)
    w_sel = np.nonzero((kind == MOP_APPEND) & ok[mtxn])[0]
    # reads: the inference's external reads from committed txns
    r_txn = inf.ext_read_txn
    r_val = inf.ext_read_val
    r_mop = inf.ext_read_mop

    ev_txn = np.concatenate([mtxn[w_sel], r_txn]).astype(np.int64)
    ev_mop = np.concatenate([w_sel, r_mop]).astype(np.int64)
    ev_val = np.concatenate([p.mop_val.astype(np.int64)[w_sel],
                             r_val]).astype(np.int64)
    ev_write = np.concatenate([np.ones(len(w_sel), bool),
                               np.zeros(len(r_txn), bool)])
    if not len(ev_txn):
        return (np.zeros(0, np.int64),) * 5
    ev_key = p.mop_key.astype(np.int64)[ev_mop]
    if not inf.chain_ok[np.unique(ev_key)].all():
        return None
    rank = inf.chain_rank[ev_val]
    if (rank < 0).any():
        return None
    proc = p.txn_process.astype(np.int64)[ev_txn]
    inv = p.txn_invoke_pos.astype(np.int64)[ev_txn]
    # session order: invoke position, then mop order within the txn
    order = np.lexsort((ev_mop, inv, ev_key, proc))
    return (proc[order], ev_key[order], ev_write[order], rank[order],
            ev_txn[order])


def _cross_key_deps(p: PackedTxns) -> bool:
    """Does any SESSION write a key after touching another key?  That
    is exactly when the DAG walker registers cross-key obligations
    (writes-follow-reads / monotonic-writes propagation) the same-key
    vectorized pass cannot see — such histories fall back to the
    walker (exactness first).  Sessions that only read many keys, or
    write within one key, never register obligations and stay on the
    vectorized path."""
    ok = p.txn_type == TXN_OK
    kind = p.mop_kind.astype(np.int64)
    mtxn = p.mop_txn.astype(np.int64)
    mkey = p.mop_key.astype(np.int64)
    sel = ok[mtxn]
    if not sel.any():
        return False
    t, k, w = mtxn[sel], mkey[sel], (kind[sel] == MOP_APPEND)
    proc = p.txn_process.astype(np.int64)[t]
    inv = p.txn_invoke_pos.astype(np.int64)[t]
    pos = np.arange(len(t))
    order = np.lexsort((pos, inv, proc))
    touched: Dict[int, set] = {}
    for i in order.tolist():
        pr, key = int(proc[i]), int(k[i])
        seen = touched.setdefault(pr, set())
        if w[i] and (seen - {key}):
            return True
        seen.add(key)
    return False


def _viol_masks(seg_id: np.ndarray, is_write: np.ndarray,
                rank: np.ndarray):
    """Backend-generic violation masks.  Returns run(xp) computing the
    four masks via a 1-based-position cummax ("latest matching event so
    far") plus a segment-start comparison — the encoding stays within
    the event count, so jax's default int32 can't overflow even on
    million-event histories."""
    n = len(seg_id)
    # per-row first index of its own (process, key) segment
    new = np.concatenate([[True], seg_id[1:] != seg_id[:-1]]) \
        if n else np.zeros(0, bool)
    seg_start_np = np.maximum.accumulate(
        np.where(new, np.arange(n), 0)) if n else np.zeros(0, np.int64)

    def run(xp):
        w = xp.asarray(is_write)
        r = xp.asarray(rank)
        pos1 = xp.arange(1, n + 1)
        seg_start = xp.asarray(seg_start_np)

        def last_prior(of_write):
            # cummax of (1-based position where the event matches)
            # gives the latest matching event at-or-before each row;
            # the exclusive shift makes it strictly prior, and a match
            # from an earlier (process, key) segment is rejected by
            # the segment-start comparison
            match = w if of_write else ~w
            enc = xp.where(match, pos1, 0)
            cm = _cummax(xp, enc)
            prior = xp.concatenate([cm[:1] * 0, cm[:-1]])
            has = (prior > 0) & ((prior - 1) >= seg_start)
            prank = r[xp.clip(prior - 1, 0, max(n - 1, 0))]
            return has, prank

        has_r, last_r = last_prior(False)
        has_w, last_w = last_prior(True)
        # mask order == sessions.GUARANTEES order
        return (
            (~w) & has_r & (r < last_r),   # monotonic-reads
            w & has_w & (r < last_w),      # monotonic-writes
            (~w) & has_w & (r < last_w),   # read-your-writes
            w & has_r & (r < last_r),      # writes-follow-reads
        )

    return run


def _cummax(xp, a):
    if xp is np:
        return np.maximum.accumulate(a)
    from jax import lax

    return lax.cummax(a, axis=0)


def check(history, guarantees: Sequence[str] = GUARANTEES,
          use_device: bool = True, max_reported: int = 8,
          deadline=None, plan=None, policy=None,
          test: Optional[dict] = None) -> Dict[str, Any]:
    """Check session guarantees.  Accepts a History / op list /
    PackedTxns (rw-register packing).  Result shape matches the elle
    checkers; anomalies use the lattice's ``<guarantee>-violation``
    tokens."""
    from jepsen_tpu import resilience

    ph = telemetry.phases()
    op_level = None if isinstance(history, PackedTxns) else history
    if op_level is None:
        p = history
    else:
        ph.start("invariants.pack", device=False)
        p = packed_mod.pack_rw(history)
    if p.n_txns == 0 or not (p.txn_type == TXN_OK).any():
        ph.end()
        return {"valid?": "unknown", "anomaly-types": [], "anomalies": {},
                "not": [], "also-not": []}

    ph.start("invariants.infer", device=False, txns=p.n_txns)
    inf = packed_mod.infer_rw(p)
    ev = _session_events(p, inf)
    want = set(guarantees)

    if ev is None or _cross_key_deps(p):
        # branched versions / cross-key obligations: the exact DAG
        # walker owns the verdict (op-level input required)
        ph.end()
        return _walker_fallback(op_level, want)

    proc, key, is_write, rank, ev_txn = ev
    seg = np.zeros(len(proc), np.int64)
    if len(proc):
        new = np.concatenate([[True], (proc[1:] != proc[:-1]) |
                              (key[1:] != key[:-1])])
        seg = np.cumsum(new) - 1
    run = _viol_masks(seg, is_write, rank)
    ph.start("invariants.check", device=use_device, events=len(proc))
    degraded = None
    try:
        if use_device and len(proc):
            def dev():
                import jax.numpy as jnp

                return tuple(np.asarray(m) for m in run(jnp))

            masks, degraded = resilience.with_fallback(
                SITE, dev, lambda: run(np), deadline=deadline,
                plan=plan, policy=policy, test=test)
        else:
            masks = run(np) if len(proc) else (np.zeros(0, bool),) * 4
    except resilience.DeadlineExceeded:
        ph.end()
        return resilience.deadline_result(checker="session")
    ph.end()

    found: Dict[str, List[dict]] = {}
    orig = p.txn_orig_index
    for g, mask in zip(GUARANTEES, masks):
        if g not in want:
            continue
        hits = np.nonzero(np.asarray(mask))[0]
        if not len(hits):
            continue
        lst = found.setdefault(g + _SUFFIX, [])
        for i in hits[:max_reported]:
            lst.append({
                "process": int(proc[i]),
                "op": int(orig[ev_txn[i]]),
                "key": p.key_names[int(key[i])],
                "rank": int(rank[i]),
                "kind": "write" if is_write[i] else "read",
            })

    anomaly_types = sorted(found)
    boundary = consistency.friendly_boundary(anomaly_types)
    res: Dict[str, Any] = {
        "valid?": not found,
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
        "events": int(len(proc)),
    }
    if degraded:
        res["degraded"] = degraded
    return res


def _walker_fallback(op_level, want) -> Dict[str, Any]:
    from jepsen_tpu.checkers.elle import coverage, sessions

    if op_level is None:
        # packed-only input: the walker needs the op-level view —
        # degrade rather than silently validate
        return coverage.apply_unchecked(
            {"valid?": True, "anomaly-types": [], "anomalies": {},
             "not": [], "also-not": [],
             "fallback": "walker-needs-op-history"},
            sorted(g + _SUFFIX for g in want))
    res = sessions.check(op_level, guarantees=sorted(want))
    res["fallback"] = "dag-walker"
    return res
